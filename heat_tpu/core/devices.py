"""Device abstraction over JAX platforms.

TPU-native re-design of the reference device layer (reference:
heat/core/devices.py:17-167, `Device`, `cpu`, `gpu`, `get_device`,
`sanitize_device`, `use_device`). The reference binds each MPI rank to one
torch device (GPU picked round-robin by rank, devices.py:100). Here a
``Device`` names a JAX *platform* whose device set backs the arrays; the
actual placement of shards onto the platform's chips is owned by the
:class:`~heat_tpu.core.communication.Communication` mesh, not by the device —
on TPU the "one rank = one chip" pairing of the reference is replaced by
"one mesh = all chips".
"""

from __future__ import annotations

from typing import List, Optional, Union

import jax

__all__ = ["Device", "cpu", "get_device", "sanitize_device", "use_device"]


class Device:
    """A logical compute platform backing DNDarray storage.

    Parameters
    ----------
    device_type : str
        Platform name understood by ``jax.devices()`` — ``"cpu"``, ``"tpu"``,
        ``"gpu"`` — or the meta-name ``"accelerator"`` (first non-CPU platform;
        this is what the sandboxed ``axon`` TPU tunnel reports, for instance).
    device_id : int, optional
        Index of a specific device of that platform; ``None`` means the whole
        platform (all chips — the normal, mesh-backed mode).
    """

    def __init__(self, device_type: str, device_id: Optional[int] = None):
        self.__device_type = device_type
        self.__device_id = device_id

    @property
    def device_type(self) -> str:
        return self.__device_type

    @property
    def device_id(self) -> Optional[int]:
        return self.__device_id

    def jax_devices(self) -> List["jax.Device"]:
        """All JAX devices belonging to this platform (one-element list if a
        specific ``device_id`` was requested)."""
        devs = _platform_devices(self.__device_type)
        if self.__device_id is not None:
            return [devs[self.__device_id]]
        return devs

    @property
    def jax_device(self) -> "jax.Device":
        """The first (or the requested) JAX device of this platform."""
        return self.jax_devices()[0]

    def __eq__(self, other) -> bool:
        if isinstance(other, Device):
            return (
                self.device_type == other.device_type and self.device_id == other.device_id
            )
        return NotImplemented

    def __hash__(self):
        return hash((self.device_type, self.device_id))

    def __repr__(self) -> str:
        return f"device({self.__str__()!r})"

    def __str__(self) -> str:
        if self.__device_id is None:
            return self.__device_type
        return f"{self.__device_type}:{self.__device_id}"


def _platform_names() -> List[str]:
    """Names of available JAX platforms, CPU last."""
    names = []
    for d in jax.devices():
        if d.platform not in names:
            names.append(d.platform)
    if "cpu" not in names:
        try:
            jax.devices("cpu")
            names.append("cpu")
        except RuntimeError:  # pragma: no cover - cpu should always exist
            pass
    return names


def _platform_devices(device_type: str) -> List["jax.Device"]:
    """Resolve a device-type string to the JAX device list of that platform."""
    if device_type in ("accelerator", "tpu", "gpu"):
        # prefer a real accelerator platform; tolerate vendor names like "axon"
        candidates = [n for n in _platform_names() if n != "cpu"]
        if device_type in candidates:
            return jax.devices(device_type)
        if candidates:
            return jax.devices(candidates[0])
        if device_type == "accelerator":
            return jax.devices("cpu")
        raise RuntimeError(f"no {device_type} platform available")
    return jax.devices(device_type)


# platform singletons ---------------------------------------------------------

cpu = Device("cpu")
"""The CPU platform (always available)."""

# The default device prefers an accelerator when one exists; resolved lazily so
# that test harnesses can force ``jax_platforms=cpu`` before first array use.
__default_device: Optional[Device] = None


def _accelerator_available() -> bool:
    return any(n != "cpu" for n in _platform_names())


def get_device() -> Device:
    """The currently globally-set default device (reference devices.py:125)."""
    global __default_device
    if __default_device is None:
        __default_device = Device("accelerator") if _accelerator_available() else cpu
    return __default_device


def sanitize_device(device: Optional[Union[str, Device]]) -> Device:
    """Map a device specifier (None/str/Device) onto a Device object
    (reference devices.py:128-154)."""
    if device is None:
        return get_device()
    if isinstance(device, Device):
        return device
    if isinstance(device, str):
        spec = device.strip().lower()
        if ":" in spec:
            dtype, _, did = spec.partition(":")
            dev = Device(dtype, int(did))
        else:
            dev = Device(spec)
        # validate platform exists now rather than at first use
        dev.jax_devices()
        return dev
    raise ValueError(f"Unknown device, must be str or Device, got {device!r}")


def use_device(device: Optional[Union[str, Device]] = None) -> None:
    """Set the globally-used default device (reference devices.py:157)."""
    global __default_device
    __default_device = sanitize_device(device)
