"""2-level (node × local) mesh topology + tiered collective lowerings
(ISSUE 15 tentpole).

Heat's DASO is the paper's answer to hierarchical interconnects — reduce
inside the node, synchronize across nodes — but until this module only
DASO knew the topology: every other collective lowered *flat*, as if
every hop cost the same. Production TPU scale is DCN + ICI with an
order-of-magnitude bandwidth gap (ROADMAP item 3), so this module makes
the 2-level factorization a first-class capability:

* :class:`Topology` — a declared ``(node, local)`` factorization of the
  flat device mesh. ``HEAT_TPU_TOPOLOGY=node×local`` (``2x4`` grammar)
  pins it; unset, :func:`detect` derives it from the host-process
  structure on real multi-host hardware and falls back to the DASO-style
  *emulated* two-node split on a single even-sized host mesh — so the
  tiered lowerings and their tests are real even when the links are not.
* **Tiered lowerings** (:func:`hier_psum`, :func:`hier_all_gather`,
  :func:`hier_reduce_scatter`, :func:`hier_all_to_all`) — the
  ``shard_map``-level programs the :class:`MeshCommunication` wrappers
  dispatch under ``HEAT_TPU_HIERARCHICAL=1``. The canonical all-reduce
  form is: in-node **reduce-scatter** (ICI, exact) → cross-node
  **all-reduce over the 1/local-sized shard** (DCN, optionally
  compressed via the ISSUE 9 machinery) → in-node **all-gather**. Every
  stage carries explicit ``axis_index_groups``, so the emitted
  replica-group structure is the ground truth for which tier a hop
  rides — the per-tier accounting the HLO auditor and the analytic cost
  model (:mod:`heat_tpu.telemetry.collectives`,
  ``hierarchical_*_cost``) reconcile byte-for-byte.
* **Per-tier precision** — the in-node tier always moves exact; the
  cross-node (DCN) tier honors ``HEAT_TPU_HIERARCHICAL_PREC`` (falling
  back to the flat ``HEAT_TPU_COLLECTIVE_PREC`` knob), so "exact inside
  the node, bf16/int8 across" is one env var.
* **Named-axes tier primitives** (:func:`node_mean_cross_sum`) — the
  same arithmetic on an explicit 2-D ``(node, local)`` mesh, consumed by
  :class:`heat_tpu.optim.DASO`: its formerly hand-rolled node-group
  send collective is now a call into this module (bit-equivalent to the
  legacy path — pinned by ``tests/test_hierarchy.py``).

Degenerate topologies (``1×N`` / ``N×1``) lower flat: a 1-level
hierarchy IS the flat ring, and emitting singleton-group collectives
would only add audit noise. ``HEAT_TPU_HIERARCHICAL=0`` (the default)
preserves the flat path verbatim — bit-for-bit, program-for-program.

Program-cache discipline: the tiered lowering is part of the traced
program, so callers caching programs built over the
:class:`MeshCommunication` wrappers must key on
:func:`cache_token` (alongside ``collective_prec.effective`` — same
contract as ISSUE 9).
"""

from __future__ import annotations

import warnings
from dataclasses import dataclass
from typing import List, Optional, Tuple

import jax
import jax.numpy as jnp

from heat_tpu import _knobs as knobs

__all__ = [
    "Topology",
    "parse",
    "detect",
    "resolve",
    "active",
    "hierarchical_requested",
    "cross_mode",
    "fsdp_wire",
    "cache_token",
    "hier_psum",
    "hier_reduce_scatter",
    "hier_all_gather",
    "hier_all_to_all",
    "node_mean_cross_sum",
]

_ENV_TOPO = "HEAT_TPU_TOPOLOGY"
_ENV_HIER = "HEAT_TPU_HIERARCHICAL"
_ENV_PREC = "HEAT_TPU_HIERARCHICAL_PREC"


@dataclass(frozen=True)
class Topology:
    """A 2-level factorization of a flat ``p``-device mesh.

    ``node`` is the slow (DCN) tier size, ``local`` the fast (ICI) tier
    size; flat mesh position ``i`` sits at ``(i // local, i % local)`` —
    node-major, the layout DASO's 2-D mesh has always used. ``source``
    records where the factorization came from (``"knob"`` /
    ``"detected"`` / ``"trivial"``) for telemetry and debugging.
    """

    node: int
    local: int
    source: str = "detected"

    @property
    def size(self) -> int:
        return self.node * self.local

    @property
    def nontrivial(self) -> bool:
        """Whether tiered lowering differs from flat: both tiers > 1."""
        return self.node > 1 and self.local > 1

    def node_groups(self) -> List[List[int]]:
        """``axis_index_groups`` of the in-node (ICI) tier: one group per
        node, covering its ``local`` consecutive flat positions."""
        return [
            [n * self.local + l for l in range(self.local)]
            for n in range(self.node)
        ]

    def cross_groups(self) -> List[List[int]]:
        """``axis_index_groups`` of the cross-node (DCN) tier: one group
        per local position, striding across nodes."""
        return [
            [n * self.local + l for n in range(self.node)]
            for l in range(self.local)
        ]

    def describe(self) -> str:
        return f"{self.node}x{self.local}"


def parse(raw: str, p: int) -> Optional[Topology]:
    """Parse the ``HEAT_TPU_TOPOLOGY`` grammar (``NODExLOCAL``, ``x`` or
    ``×``) against a ``p``-device mesh. Malformed strings or
    factorizations that do not multiply to ``p`` return None (the caller
    falls back to detection) — with a warning for the mismatch case,
    which is a real configuration error, not an unset knob."""
    s = (raw or "").strip().lower().replace("×", "x")
    if not s:
        return None
    parts = s.split("x")
    if len(parts) != 2:
        return None
    try:
        node, local = int(parts[0]), int(parts[1])
    except ValueError:
        return None
    if node <= 0 or local <= 0:
        return None
    if node * local != p:
        warnings.warn(
            f"HEAT_TPU_TOPOLOGY={raw!r} declares {node}x{local}="
            f"{node * local} positions but the mesh has {p}; falling back "
            "to auto-detection"
        )
        return None
    return Topology(node, local, source="knob")


def detect(p: int) -> Topology:
    """Auto-detect a factorization of ``p`` devices.

    * Real multi-host runs: one node per host process (the DCN boundary
      XLA actually crosses), when the process count divides ``p``.
    * Single-host emulation: the DASO-style two-node split on even
      meshes — exactly how DASO's tests have always faked DCN on the
      virtual CPU mesh, so the tiered lowerings and their replica-group
      assertions exercise for real even when the links don't exist.
    * Everything else: trivial ``1×p`` (tiered lowering inactive).
    """
    nproc = jax.process_count()
    if nproc > 1 and p % nproc == 0:
        return Topology(nproc, p // nproc, source="detected")
    if p > 1 and p % 2 == 0:
        return Topology(2, p // 2, source="detected")
    return Topology(1, p, source="trivial")


def resolve(p: int) -> Topology:
    """The active topology for a ``p``-device mesh: the knob when set and
    valid, else detection."""
    topo = parse(knobs.raw(_ENV_TOPO, "") or "", p)
    return topo if topo is not None else detect(p)


def hierarchical_requested() -> bool:
    """The ``HEAT_TPU_HIERARCHICAL`` bit (default off)."""
    return bool(knobs.get(_ENV_HIER))


def active(p: int) -> Optional[Topology]:
    """The topology to lower tiered against, or None for the flat path:
    requires the ``HEAT_TPU_HIERARCHICAL`` opt-in AND a nontrivial
    factorization (degenerate ``1×N`` / ``N×1`` topologies lower flat)."""
    if not hierarchical_requested():
        return None
    topo = resolve(p)
    return topo if topo.nontrivial else None


def cross_mode(dtype, precision: Optional[str] = None) -> str:
    """The wire mode of the CROSS-NODE tier for one payload: an explicit
    per-call ``precision=`` wins; else ``HEAT_TPU_HIERARCHICAL_PREC``
    when set; else the flat ``HEAT_TPU_COLLECTIVE_PREC`` knob. Demoted to
    ``off`` for non-float payloads, like every ISSUE 9 surface."""
    from . import collective_prec

    if precision is None:
        raw = (knobs.raw(_ENV_PREC, "") or "").strip().lower()
        if raw in collective_prec.MODES:
            precision = raw
    return collective_prec.effective(dtype, precision)


def fsdp_wire(dtype, p: int, precision: Optional[str] = None) -> str:
    """The wire mode of one FSDP weight gather (and its transpose
    reduce-scatter) for one leaf (ISSUE 18, parallel/fsdp.py): an
    explicit per-rule ``precision`` wins; else ``HEAT_TPU_FSDP_PREC``
    when set; else — under an ACTIVE 2-level topology — the cross-node
    chain (:func:`cross_mode`: ``HEAT_TPU_HIERARCHICAL_PREC``, then
    ``HEAT_TPU_COLLECTIVE_PREC``), because there the in-node tier moves
    exact regardless and only the DCN hop compresses; else ``off``. The
    flat-mesh default is deliberately exact, NOT the global collective
    knob: a compressed weight gather changes the model every step, so
    lossy weight wires require the FSDP-specific opt-in. Demoted to
    ``off`` for non-float payloads like every ISSUE 9 surface."""
    from . import collective_prec

    if precision is None:
        raw = (knobs.raw("HEAT_TPU_FSDP_PREC", "") or "").strip().lower()
        if raw in collective_prec.MODES:
            precision = raw
    if precision is None:
        if active(p) is not None:
            return cross_mode(dtype, None)
        return "off"
    return collective_prec.effective(dtype, precision)


def cache_token(p: int) -> Tuple:
    """The program-cache key component that pins the tiered-lowering
    state of a traced program: ``(hierarchical?, node, local,
    cross-tier knob)``. Callers caching programs built over the
    MeshCommunication wrappers include this alongside
    ``collective_prec.effective(dtype)`` — flipping
    ``HEAT_TPU_HIERARCHICAL`` (or re-declaring the topology) must key a
    different compiled program, never silently reuse a stale one."""
    topo = active(p)
    if topo is None:
        return ("flat",)
    return (
        "hier", topo.node, topo.local,
        (knobs.raw(_ENV_PREC, "") or "").strip().lower(),
    )


# -- tiered lowerings over a FLAT mesh axis -----------------------------------
# These run inside shard_map kernels (or GSPMD bodies via shard_map) over
# the communicator's single flat axis; the tier structure enters purely
# through axis_index_groups, which is what the emitted replica groups —
# and hence the per-tier HLO audit — reflect.


def _pad_flat(x, multiple: int):
    """(flat payload zero-padded to a multiple, original element count)."""
    n = x.size
    chunk = -(-n // multiple)
    n_pad = chunk * multiple
    flat = x.reshape(-1)
    if n_pad != n:
        flat = jnp.pad(flat, (0, n_pad - n))
    return flat, n


def hier_psum(x, axis_name: str, topo: Topology,
              cross_wire: str = "off", block: Optional[int] = None):
    """Tiered all-reduce: in-node reduce-scatter (exact) → cross-node
    all-reduce of the ``1/local`` shard (``cross_wire``-compressed) →
    in-node all-gather. Bit-parity with the flat ``lax.psum`` holds
    whenever the payload's sums are exactly representable (integer
    payloads, integer-valued floats); general float payloads differ only
    by summation association."""
    from . import collective_prec

    flat, n = _pad_flat(x, topo.local)
    s = jax.lax.psum_scatter(
        flat, axis_name, scatter_dimension=0,
        axis_index_groups=topo.node_groups(), tiled=True,
    )
    if cross_wire == "off" or not collective_prec.compressible(x.dtype):
        s = jax.lax.psum(s, axis_name, axis_index_groups=topo.cross_groups())
    elif cross_wire == "bf16":
        w = s if s.dtype == jnp.bfloat16 else s.astype(jnp.bfloat16)
        s = jax.lax.psum(
            w, axis_name, axis_index_groups=topo.cross_groups()
        ).astype(x.dtype)
    else:
        s = collective_prec.psum(
            s, axis_name, topo.node, cross_wire, block,
            groups=topo.cross_groups(),
        )
    out = jax.lax.all_gather(
        s, axis_name, axis_index_groups=topo.node_groups(), tiled=True,
    )
    return out[:n].reshape(x.shape)


def hier_reduce_scatter(x, axis_name: str, topo: Topology,
                        cross_wire: str = "off",
                        block: Optional[int] = None):
    """Tiered reduce-scatter to the global ``1/p`` chunk: in-node
    reduce-scatter (exact) to the ``1/local`` shard, then cross-node
    reduce-scatter of that shard (``cross_wire``-compressed). Returns the
    1-D ``(ceil(numel/p),)`` chunk owned by this position — the same
    contract as the flat ``MeshCommunication.reduce_scatter``."""
    from . import collective_prec

    p = topo.size
    flat, _ = _pad_flat(x, p)
    c = flat.size // p
    # chunk transpose: stage 1 hands local-position l the l-th quarter,
    # stage 2 hands node-position n the n-th piece of it — so to land the
    # FLAT chunk n·local+l on device (n, l) (the contract the tiered
    # all-gather reassembles), chunks are pre-arranged (local, node)-major
    arranged = flat.reshape(topo.node, topo.local, c).swapaxes(0, 1)
    s = jax.lax.psum_scatter(
        arranged.reshape(-1), axis_name, scatter_dimension=0,
        axis_index_groups=topo.node_groups(), tiled=True,
    )
    return collective_prec.reduce_scatter(
        s, axis_name, topo.node, cross_wire, block,
        groups=topo.cross_groups(),
    )


def _two_stage_gather(axis_name: str, topo: Topology):
    """The exact two-stage gather mover: cross-node first (DCN), then
    in-node (ICI), reordered to the flat gather's node-major source
    order. Returns a function u -> (p,) + u.shape stacked blocks."""

    def mover(u):
        g1 = jax.lax.all_gather(
            u, axis_name, axis_index_groups=topo.cross_groups()
        )                                            # (node,) + u.shape
        g2 = jax.lax.all_gather(
            g1, axis_name, axis_index_groups=topo.node_groups()
        )                                            # (local, node) + u.shape
        g = jnp.swapaxes(g2, 0, 1)                   # (node, local) + u.shape
        return g.reshape((topo.size,) + u.shape)

    return mover


def hier_all_gather(x, axis_name: str, topo: Topology,
                    cross_wire: str = "off", block: Optional[int] = None,
                    tiled: bool = True):
    """Tiered all-gather: cross-node gather of the shard (DCN), then the
    in-node gather of the stacked node blocks (ICI). Exact mode is
    bit-identical to the flat tiled/stacked ``lax.all_gather`` — pure
    data movement, reordered to the same source-major layout. Compressed
    modes quantize ONCE at the source and move payload + scales through
    both stages (one quantization step of error, the flat compressed
    bound)."""
    from . import collective_prec as cp

    mover = _two_stage_gather(axis_name, topo)
    p = topo.size
    if cross_wire == "off" or not cp.compressible(x.dtype):
        g = mover(x)
    elif cross_wire == "bf16":
        w = x if x.dtype == jnp.bfloat16 else x.astype(jnp.bfloat16)
        u = jax.lax.bitcast_convert_type(w, jnp.uint16)
        g = jax.lax.bitcast_convert_type(mover(u), jnp.bfloat16).astype(
            x.dtype
        )
    elif cross_wire == "int8":
        q, s = cp._quant_tensor(x)
        qg = mover(q)                                  # (p,) + x.shape
        sg = jax.lax.bitcast_convert_type(
            mover(jax.lax.bitcast_convert_type(s, jnp.uint16)), jnp.bfloat16
        )                                              # (p,)
        g = cp._deq(qg, sg.reshape((p,) + (1,) * x.ndim)).astype(x.dtype)
    else:
        block = block or cp.block_size()
        q, s = cp._quant_flat_blocks(x, block)
        qg = mover(q)                                  # (p, nb, blk)
        sg = jax.lax.bitcast_convert_type(
            mover(jax.lax.bitcast_convert_type(s, jnp.uint16)), jnp.bfloat16
        )                                              # (p, nb)
        g = cp._deq(qg, sg[..., None]).reshape(p, -1)[:, : x.size]
        g = g.reshape((p,) + x.shape).astype(x.dtype)
    if tiled and x.ndim >= 1:
        return g.reshape((p * x.shape[0],) + x.shape[1:])
    return g


def _two_stage_a2a(axis_name: str, topo: Topology):
    """The exact two-stage slab exchange: stage A swaps
    destination-local slabs inside each node (ICI), stage B swaps
    destination-node bundles across nodes (DCN). Input: an array whose
    LEADING axis is the ``p`` destination slabs (node-major); output:
    the same shape with the leading axis holding the ``p`` SOURCE slabs
    (node-major) — exactly the flat ``all_to_all(split_axis=0,
    concat_axis=0)`` contract."""

    def mover(slabs):
        b = slabs.reshape((topo.node, topo.local) + slabs.shape[1:])
        a = jax.lax.all_to_all(
            b, axis_name, split_axis=1, concat_axis=0,
            axis_index_groups=topo.node_groups(),
        )                                   # (src_local, node, ...)
        c = jax.lax.all_to_all(
            a, axis_name, split_axis=1, concat_axis=0,
            axis_index_groups=topo.cross_groups(),
        )                                   # (src_node, src_local, ...)
        return c.reshape(slabs.shape)

    return mover


def hier_all_to_all(x, axis_name: str, topo: Topology,
                    split_axis: int, concat_axis: int,
                    cross_wire: str = "off", block: Optional[int] = None):
    """Tiered (tiled) all-to-all. Exact mode is bit-identical to the
    flat ``lax.all_to_all(tiled=True)`` — both stages are pure data
    movement and the staging restores the flat source-major layout.
    Compressed modes quantize per final-destination slab at the source
    (the :func:`heat_tpu.core.collective_prec.all_to_all` slab scheme)
    and move payload + scales through both stages."""
    from . import collective_prec as cp

    p = topo.size
    mover = _two_stage_a2a(axis_name, topo)
    if cross_wire == "off" or not cp.compressible(x.dtype):
        xm = jnp.moveaxis(x, split_axis, 0)
        s = xm.shape[0]
        slabs = xm.reshape((p, s // p) + xm.shape[1:])
        out = mover(slabs)
        out = out.reshape((p, s // p) + xm.shape[1:])
        out = jnp.moveaxis(out, 1, 1 + split_axis)
        out = jnp.moveaxis(out, 0, concat_axis)
        shp = list(out.shape)
        shp[concat_axis : concat_axis + 2] = [
            shp[concat_axis] * shp[concat_axis + 1]
        ]
        return out.reshape(shp)
    if cross_wire == "bf16":
        w = x if x.dtype == jnp.bfloat16 else x.astype(jnp.bfloat16)
        u = jax.lax.bitcast_convert_type(w, jnp.uint16)
        moved = hier_all_to_all(
            u, axis_name, topo, split_axis, concat_axis, "off", block
        )
        return jax.lax.bitcast_convert_type(moved, jnp.bfloat16).astype(
            x.dtype
        )
    # int8 / blockwise: per-destination-slab quantization, staged movement
    block = block or cp.block_size()
    w = x.shape[split_axis] // p
    xm = jnp.moveaxis(x, split_axis, 0)
    rest = xm.shape[1:]
    m = w
    for d in rest:
        m *= d
    slabs = xm.reshape(p, m)
    if cross_wire == "int8":
        nb, seg = 1, m
    else:
        seg = max(1, min(block, m))
        nb = max(1, -(-m // seg))
        if nb * seg != m:
            slabs = jnp.pad(slabs, ((0, 0), (0, nb * seg - m)))
    b3 = slabs.reshape(p, nb, seg).astype(jnp.float32)
    s = cp._scale_of(jnp.max(jnp.abs(b3), axis=2))           # (p, nb)
    q = jnp.clip(
        jnp.round(b3 / s.astype(jnp.float32)[..., None]), -127.0, 127.0
    ).astype(jnp.int8)
    qt = mover(q)                                            # (p, nb, seg)
    st = jax.lax.bitcast_convert_type(
        mover(jax.lax.bitcast_convert_type(s, jnp.uint16)), jnp.bfloat16
    )                                                        # (p, nb)
    deq = cp._deq(qt, st[..., None]).reshape(p, -1)[:, :m]
    deq = deq.reshape((p, w) + rest)
    deq = jnp.moveaxis(deq, 1, 1 + split_axis)
    deq = jnp.moveaxis(deq, 0, concat_axis)
    shp = list(deq.shape)
    shp[concat_axis : concat_axis + 2] = [
        shp[concat_axis] * shp[concat_axis + 1]
    ]
    return deq.reshape(shp).astype(x.dtype)


# -- named-axes tier primitives (the DASO form) --------------------------------


def node_mean_cross_sum(x, *, local_axis: str, node_axis: str, n_node: int,
                        wire: str, cast_dtype=jnp.bfloat16,
                        block: Optional[int] = None):
    """DASO's send primitive on an explicit 2-D ``(node, local)`` mesh:
    the node representative is the MEAN over the fast (ICI) tier, then a
    reduced-precision SUM across the slow (DCN) tier — the raw sum, not
    the average: DASO folds ``n_nodes`` into its staleness-weighted
    merge denominator (reference dp_optimizer.py:502-556).

    ``wire`` semantics match the DASO contract exactly (the
    bit-equivalence oracle in tests/test_hierarchy.py pins this against
    the legacy hand-rolled kernel): ``off`` moves ``cast_dtype`` on the
    wire (the historic bf16 downcast), ``bf16`` is that same program
    with the dtype pinned, ``int8``/``blockwise`` run the EQuARX
    two-phase quantized node psum and return an f32-accurate payload."""
    from . import collective_prec

    rep = jax.lax.pmean(x, local_axis)
    if wire in ("int8", "blockwise") and collective_prec.compressible(
        x.dtype
    ):
        return collective_prec.psum(rep, node_axis, n_node, wire, block)
    wire_cast = jnp.bfloat16 if wire == "bf16" else cast_dtype
    return jax.lax.psum(rep.astype(wire_cast), node_axis)
