"""heat_tpu — a TPU-native distributed n-dimensional array framework.

Ground-up re-design of the Heat (Helmholtz Analytics Toolkit) capability set
(reference: /root/reference, heat/__init__.py:5-19) for the JAX/XLA stack:
arrays are sharded `jax.Array`s over a `jax.sharding.Mesh`, collectives ride
ICI/DCN via XLA instead of MPI, local math runs on the MXU instead of torch.

Importing enables 64-bit dtypes (`jax_enable_x64`) so the numpy-compatible
dtype surface (int64/float64 defaults) matches the reference; TPU compute
paths default to float32/bfloat16 regardless.
"""

import jax as _jax

_jax.config.update("jax_enable_x64", True)

# older-runtime API shims (jax.shard_map / lax.pcast / pltpu.CompilerParams)
# — must install before any kernel module loads (see _jax_compat.py)
from . import _jax_compat as _compat

_compat.install()

# telemetry first: it is import-light (no core dependency) and the core
# modules' instrumentation hooks reference it; HEAT_TPU_TELEMETRY=1 in the
# environment turns recording on here (docs/OBSERVABILITY.md)
from . import telemetry

# resilience second: program_cache wraps every dispatch through it, so it
# must exist before core loads; HEAT_TPU_FAULTS / HEAT_TPU_RETRIES /
# HEAT_TPU_HBM_BUDGET arm it here (docs/RESILIENCE.md). Core-facing pieces
# (checkpoint) import core lazily to keep the load order acyclic.
from . import resilience

from .core import *
from . import core
from .core import linalg, program_cache, random, version
from .core.ragged import Ragged, ragged
from .core.version import version as __version__

# sparse container + audited SpMV/SpMM (ISSUE 13): mounts right after
# core (it consumes program_cache/telemetry/memory_guard) and before the
# ML subpackages (graph/cluster/serve route workloads through it)
from . import sparse

# ML subpackages (assembled as they are built; reference heat/__init__.py
# mounts cluster/classification/graph/naive_bayes/regression/spatial/nn/
# optim/utils the same way)
from . import cluster
from . import classification
from . import graph
from . import naive_bayes
from . import regression
from . import spatial
from . import utils
from . import parallel
from . import datasets
from . import nn
from . import optim
from . import serve

# streaming (ISSUE 16) mounts after the estimators and the serving tier
# it composes: online partial_fit estimators, out-of-core ChunkStream
# ingestion, and the versioned fit-while-serve rolling-update driver
from . import streaming

# the measured-feedback knob autotuner (ISSUE 11) mounts last: it
# consumes the substrate (knobs registry, telemetry, cost model, program
# cache) and is consulted from dispatch sites only behind the
# HEAT_TPU_AUTOTUNE flag check
from . import autotune
