"""Versioned fit-while-serve: publish a freshly fitted endpoint version
and roll it through a replica pool without a compile or a dropped
request (ISSUE 16).

Two publication planes:

* **in-process** — ``Server.publish(name, endpoint.with_params(...))``:
  endpoint parameters are program *arguments*, so a same-aval publish
  re-enters the warm executable (zero compiles, the ``version_swap``
  event records the CompileWatcher count) and the dispatch loop's
  single endpoint read per micro-batch makes the cutover bit-exact
  between batches;
* **cross-process** — :func:`rolling_update` here: a replica process is
  *born* from one checkpoint and serves exactly that version for its
  whole life, so rolling a pool is replace-one-at-a-time: spawn a
  replacement from the NEW checkpoint (it warms from the shared compile
  cache — zero steady compiles), hand it to the router, then
  drain-and-remove one old replica (the router retries its shedding
  503s to siblings — zero failed requests, provided the router opted
  into ``retry_in_flight=True``: serving queries are idempotent, and a
  draining replica may reset connections it had already accepted). No
  process ever serves a half-updated endpoint set, chaos included: SIGKILL mid-roll loses
  only the victim's in-flight work, and the roll resumes by spawning
  another replacement (every spawn after :meth:`ReplicaPool.
  set_checkpoint` is already the new version).
"""

from __future__ import annotations

import time
from typing import Optional

from .. import _knobs as knobs
from . import events

__all__ = ["rolling_update"]


def rolling_update(
    pool,
    router,
    checkpoint: str,
    *,
    drain_timeout: Optional[float] = None,
    ready_probe: bool = True,
) -> dict:
    """Roll every replica of ``pool`` onto ``checkpoint``,
    replica-by-replica, with the router draining each one.

    Per old replica: ``spawn(new) → router.add_target(new) →
    remove(old)`` (drain-then-kill; the pool asserts exit code 0).
    Capacity never drops below the starting replica count during the
    roll — the new replica is in rotation before its predecessor starts
    draining.

    Returns ``{"steps": [...], "replicas", "seconds", "versions"}``
    where ``versions`` maps replica index → the endpoint-version dict
    its ``/stats`` reports after the roll (the all-on-new-version
    oracle). ``drain_timeout`` defaults to the
    ``HEAT_TPU_STREAM_DRAIN_TIMEOUT`` knob — the version-swap drain
    policy: how long an old replica may take to finish its backlog
    before the roll fails loudly."""
    if drain_timeout is None:
        drain_timeout = float(knobs.get("HEAT_TPU_STREAM_DRAIN_TIMEOUT"))
    t_start = time.perf_counter()
    pool.set_checkpoint(checkpoint)
    old = [
        h.index for h in list(pool.replicas)
        if h.state == "up" and h.alive()
    ]
    if not old:
        raise RuntimeError("rolling_update: pool has no live replicas")
    steps = []
    for idx in old:
        t0 = time.perf_counter()
        repl = pool.spawn()  # born from the NEW checkpoint
        router.add_target(repl.url)
        rc = pool.remove(idx, timeout=drain_timeout)
        step = {
            "replaced": idx,
            "replacement": repl.index,
            "drain_rc": rc,
            "seconds": round(time.perf_counter() - t0, 3),
        }
        steps.append(step)
        events.emit("pool", "roll_step", **step)
        if rc != 0:
            raise RuntimeError(
                f"rolling_update: replica {idx} exited rc={rc} during "
                f"drain (log: {pool.handle(idx).log_path})"
            )
    versions = {}
    if ready_probe:
        for h in list(pool.replicas):
            if h.state == "up" and h.alive():
                try:
                    versions[h.index] = (
                        pool.stats(h.index).get("versions") or {}
                    )
                except Exception as e:  # noqa: BLE001 — a dead replica is data
                    versions[h.index] = {"error": repr(e)}
    return {
        "steps": steps,
        "replicas": len(old),
        "seconds": round(time.perf_counter() - t_start, 3),
        "versions": versions,
    }
