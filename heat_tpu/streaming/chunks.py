"""Out-of-core ingestion: walk HDF5/npy files in bounded row blocks
(ISSUE 16 — the reference's ``PartialH5Dataset`` access pattern done
natively).

:class:`ChunkStream` iterates one or more array files as placed
:class:`~heat_tpu.core.dndarray.DNDarray` chunks without ever
materializing a whole file: each block is an ``io.load_hdf5`` /
``io.load_npy`` row-range read (``chunks=(start, stop)`` — the h5py
range read touches only those rows; the npy memory map touches only
those pages), sized so the chunk's device bytes fit
:func:`heat_tpu.resilience.memory_guard.temp_budget` — with
``HEAT_TPU_HBM_BUDGET`` pinned, the stream's memory watermark stays
strictly below the load-all need (the CI streaming gate asserts it).
``HEAT_TPU_STREAM_CHUNK_ROWS`` overrides the automatic sizing.

Placement: a chunk loads directly at the target ``split`` (the loader
shards the block). A ``resplit=`` target instead loads row-sharded and
re-lays the chunk out through ``DNDarray.resplit`` — which, with a
budget armed, routes through the communication-aware relayout planner
(:mod:`heat_tpu.core.relayout_planner`), so even the per-chunk
relayout is bounded-memory.

Telemetry: one ``stream_chunk`` event per block (rows, bytes, read
seconds — the rows/s numerator of the ``streaming`` summarize block)
and a ``streaming.chunk_bytes`` high-water mark.
"""

from __future__ import annotations

import os
import time
from typing import Iterator, List, Optional, Sequence, Union

import numpy as np

from .. import _knobs as knobs
from ..core import io as core_io
from ..core import types
from ..core.dndarray import DNDarray
from ..resilience import memory_guard
from . import events

__all__ = ["ChunkStream"]


class ChunkStream:
    """Iterate array files as mesh-placed row-block chunks.

    Parameters
    ----------
    paths : str | sequence of str
        One or more ``.npy`` / HDF5 files, streamed in order. Every
        file must share the trailing (feature) shape.
    dataset : str, optional
        HDF5 dataset name (required for HDF5 files; ignored for npy).
    chunk_rows : int, optional
        Rows per chunk. Default: the ``HEAT_TPU_STREAM_CHUNK_ROWS``
        knob, or (at 0 = auto) the largest row count whose chunk bytes
        fit ``memory_guard.temp_budget()``.
    dtype, split, device, comm :
        Placement of each chunk (``io.load_*`` semantics). ``split=0``
        (default) shards chunk rows across the mesh.
    resplit : int | None, optional
        When set, each chunk loads row-sharded and is re-laid out to
        this split through the relayout planner (budget-aware).
    skip_rows : int
        Skip this many leading logical rows (checkpoint resume: restart
        the stream where the estimator carry left off). Must land on a
        chunk boundary of the same ``chunk_rows`` to reproduce the
        original chunk sequence bit-exactly.
    """

    def __init__(
        self,
        paths: Union[str, Sequence[str]],
        dataset: Optional[str] = None,
        *,
        chunk_rows: Optional[int] = None,
        dtype=types.float32,
        split: Optional[int] = 0,
        device=None,
        comm=None,
        resplit: Optional[int] = None,
        skip_rows: int = 0,
    ):
        self.paths: List[str] = (
            [paths] if isinstance(paths, str) else list(paths)
        )
        if not self.paths:
            raise ValueError("ChunkStream needs at least one file")
        self.dataset = dataset
        self.dtype = dtype
        self.split = split
        self.device = device
        self.comm = comm
        self.resplit = resplit
        self.skip_rows = int(skip_rows)
        self.rows_read = 0
        self.chunks_read = 0

        # shapes up front (header/metadata peeks — no data read)
        self._shapes = []
        tail = None
        for p in self.paths:
            shape = core_io.dataset_shape(
                p, dataset if self._is_hdf5(p) else None
            )
            if len(shape) < 1:
                raise ValueError(f"ChunkStream: {p!r} is 0-d")
            if tail is None:
                tail = shape[1:]
            elif shape[1:] != tail:
                raise ValueError(
                    f"ChunkStream: {p!r} has row shape {shape[1:]}, "
                    f"expected {tail} (all files must share it)"
                )
            self._shapes.append(shape)
        self._tail = tail
        if self.skip_rows < 0 or self.skip_rows > self.nrows():
            raise ValueError(
                f"skip_rows={skip_rows} outside [0, {self.nrows()}]"
            )
        self.chunk_rows = self._resolve_chunk_rows(chunk_rows)

    @staticmethod
    def _is_hdf5(path: str) -> bool:
        return path.endswith((".h5", ".hdf5"))

    def _row_bytes(self) -> int:
        width = int(np.prod(self._tail)) if self._tail else 1
        item = (
            self.dtype.byte_size() if hasattr(self.dtype, "byte_size")
            else np.dtype(self.dtype).itemsize
        )
        return max(1, width * item)

    def _resolve_chunk_rows(self, chunk_rows: Optional[int]) -> int:
        if chunk_rows is None:
            chunk_rows = int(knobs.get("HEAT_TPU_STREAM_CHUNK_ROWS") or 0)
        if chunk_rows < 0:
            raise ValueError(f"chunk_rows must be >= 0, got {chunk_rows}")
        if chunk_rows == 0:
            # auto: chunk bytes fit the temp budget (which is itself a
            # quarter of HEAT_TPU_HBM_BUDGET when armed)
            chunk_rows = max(1, memory_guard.temp_budget() // self._row_bytes())
        return min(int(chunk_rows), max(1, self.nrows()))

    # -- sizing/introspection ------------------------------------------------

    def nrows(self) -> int:
        """Total logical rows across all files."""
        return sum(s[0] for s in self._shapes)

    def load_all_bytes(self) -> int:
        """What materializing every file at once would cost (the
        baseline the out-of-core watermark must beat)."""
        return self.nrows() * self._row_bytes()

    def chunk_bytes(self) -> int:
        return self.chunk_rows * self._row_bytes()

    def __len__(self) -> int:
        # chunking restarts at every file boundary, so count per file
        total, to_skip = 0, self.skip_rows
        for shape in self._shapes:
            n = shape[0]
            if to_skip >= n:
                to_skip -= n
                continue
            rows = n - to_skip
            to_skip = 0
            total += -(-rows // self.chunk_rows)
        return total

    # -- iteration -----------------------------------------------------------

    def __iter__(self) -> Iterator[DNDarray]:
        from .. import telemetry

        to_skip = self.skip_rows
        for path, shape in zip(self.paths, self._shapes):
            n = shape[0]
            if to_skip >= n:
                to_skip -= n
                continue
            lo = to_skip
            to_skip = 0
            while lo < n:
                hi = min(lo + self.chunk_rows, n)
                t0 = time.perf_counter()
                if self._is_hdf5(path):
                    chunk = core_io.load_hdf5(
                        path, self.dataset, dtype=self.dtype,
                        split=0 if self.resplit is not None else self.split,
                        device=self.device, comm=self.comm, chunks=(lo, hi),
                    )
                else:
                    chunk = core_io.load_npy(
                        path, dtype=self.dtype,
                        split=0 if self.resplit is not None else self.split,
                        device=self.device, comm=self.comm, chunks=(lo, hi),
                    )
                if self.resplit is not None:
                    # budget-armed resplits route through the relayout
                    # planner (bounded-memory chunked relayout programs)
                    chunk = chunk.resplit(self.resplit)
                seconds = time.perf_counter() - t0
                nbytes = (hi - lo) * self._row_bytes()
                self.rows_read += hi - lo
                self.chunks_read += 1
                events.emit(
                    os.path.basename(path), "stream_chunk",
                    rows=hi - lo, bytes=nbytes,
                    seconds=round(seconds, 6), start=lo, stop=hi,
                )
                if telemetry.enabled():
                    telemetry.get_registry().high_water(
                        "streaming.chunk_bytes", float(nbytes)
                    )
                yield chunk
                lo = hi
