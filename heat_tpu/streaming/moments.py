"""Online column moments: the single-pass pallas Welford kernel behind a
Chan-style mergeable carry (ISSUE 16).

Each ``partial_fit(chunk)`` runs ONE cached program
(:func:`heat_tpu.core.statistics.chunk_moments`, site
``streaming.moments`` — the pallas single-HBM-read kernel on TPU, a
masked one-pass XLA form elsewhere) producing the chunk's
``(n, mean, M2)``, then folds it into the running carry with the exact
:func:`~heat_tpu.core.pallas_moments.chan_merge` formula the kernel
itself applies across row blocks. The carry lives on the HOST in
float64: the merge sequence is deterministic python arithmetic, so a
checkpointed stream resumes **bit-exactly** — and the carry is
mesh-independent (only the per-chunk device reduction sees the mesh).

Equivalence contract (pinned by tests/test_streaming.py):

* one-chunk ``partial_fit`` ≡ the direct kernel call — same program;
* K-chunk ``partial_fit`` vs one-shot moments over the concatenation —
  equal to documented float tolerance (the merge tree associates
  differently than the one-shot block sequence; Chan's formula keeps
  the error at the f32-rounding level, and the f64 host carry adds no
  error of its own);
* checkpoint → restore → continue ≡ uninterrupted stream, bit-exact
  (the carry round-trips through float64 blobs unchanged).
"""

from __future__ import annotations

from typing import Optional

import numpy as np

from ..core.dndarray import DNDarray
from ..core.pallas_moments import chan_merge
from ..core.statistics import chunk_moments
from . import events

__all__ = ["StreamingMoments"]


class StreamingMoments:
    """Single-pass streaming mean/var/std over the rows of a chunked
    2-D stream.

    Parameters
    ----------
    ddof : int
        Delta degrees of freedom of :meth:`var`/:meth:`std` (0 =
        population, 1 = sample).
    """

    def __init__(self, ddof: int = 0):
        self.ddof = int(ddof)
        self.n_seen = 0.0  # float64 exact for any realistic row count
        self._mean: Optional[np.ndarray] = None  # (d,) float64
        self._m2: Optional[np.ndarray] = None    # (d,) float64
        self.chunks_seen = 0

    # -- streaming -----------------------------------------------------------

    def partial_fit(self, x: DNDarray) -> "StreamingMoments":
        """Fold one chunk into the carry: one cached-program dispatch
        (zero-compile on a steady stream of equal-shaped chunks) + one
        host-side Chan merge."""
        n, mu, m2 = chunk_moments(x)
        mu = np.asarray(mu, dtype=np.float64)
        m2 = np.asarray(m2, dtype=np.float64)
        if self._mean is None:
            self._mean = np.zeros_like(mu)
            self._m2 = np.zeros_like(m2)
        elif self._mean.shape != mu.shape:
            raise ValueError(
                f"partial_fit chunk has {mu.shape[0]} features but the "
                f"carry holds {self._mean.shape[0]}"
            )
        self.n_seen, self._mean, self._m2 = chan_merge(
            self.n_seen, self._mean, self._m2, float(n), mu, m2
        )
        self.chunks_seen += 1
        return self

    def merge(self, other: "StreamingMoments") -> "StreamingMoments":
        """Combine another stream's carry into this one (exact — the
        carry algebra is associative up to float rounding, so shards of
        a stream processed independently merge into one estimate)."""
        if other._mean is None:
            return self
        if self._mean is None:
            self.n_seen = other.n_seen
            self._mean = other._mean.copy()
            self._m2 = other._m2.copy()
            self.chunks_seen += other.chunks_seen
            return self
        self.n_seen, self._mean, self._m2 = chan_merge(
            self.n_seen, self._mean, self._m2,
            other.n_seen, other._mean, other._m2,
        )
        self.chunks_seen += other.chunks_seen
        return self

    # -- results -------------------------------------------------------------

    @property
    def mean(self) -> np.ndarray:
        if self._mean is None:
            raise RuntimeError("partial_fit needs at least one chunk")
        return self._mean.copy()

    def var(self, ddof: Optional[int] = None) -> np.ndarray:
        if self._m2 is None:
            raise RuntimeError("partial_fit needs at least one chunk")
        k = self.ddof if ddof is None else int(ddof)
        denom = self.n_seen - k
        if denom <= 0:
            raise ValueError(
                f"var(ddof={k}) needs more than {k} rows, saw {self.n_seen}"
            )
        return self._m2 / denom

    def std(self, ddof: Optional[int] = None) -> np.ndarray:
        return np.sqrt(self.var(ddof))

    # -- checkpoint/resume ---------------------------------------------------

    def save(self, path: str) -> str:
        """Checkpoint the carry (CRC-verified blobs, atomic directory
        swap — :mod:`heat_tpu.resilience.checkpoint`). The float64 host
        carry round-trips bit-exactly, so resume-then-continue equals
        the uninterrupted stream on the same chunk sequence."""
        from .. import resilience

        if self._mean is None:
            raise RuntimeError("nothing to checkpoint: no chunk seen yet")
        out = resilience.save_checkpoint(
            [self._mean, self._m2], path,
            extra={
                "algo": "streaming_moments",
                "n_seen": float(self.n_seen),
                "chunks_seen": int(self.chunks_seen),
                "ddof": int(self.ddof),
            },
        )
        events.emit("moments", "checkpoint", path=path,
                    rows_seen=float(self.n_seen),
                    chunks=int(self.chunks_seen))
        return out

    @classmethod
    def restore(cls, path: str) -> "StreamingMoments":
        from .. import resilience

        leaves, extra = resilience.load_checkpoint(path, with_extra=True)
        if (extra or {}).get("algo") != "streaming_moments" or len(leaves) != 2:
            raise resilience.CheckpointError(
                f"{path!r} is a {(extra or {}).get('algo')!r} checkpoint, "
                f"not streaming_moments"
            )
        est = cls(ddof=int(extra.get("ddof", 0)))
        est._mean = np.asarray(leaves[0], dtype=np.float64)
        est._m2 = np.asarray(leaves[1], dtype=np.float64)
        est.n_seen = float(extra["n_seen"])
        est.chunks_seen = int(extra.get("chunks_seen", 0))
        events.emit("moments", "resume", path=path,
                    rows_seen=est.n_seen, chunks=est.chunks_seen)
        return est
