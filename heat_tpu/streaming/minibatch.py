"""Mini-batch K-Means: the Lloyd shift-carry window, applied per chunk
with decayed-count center blending (ISSUE 16).

Each ``partial_fit(chunk)`` is ONE cached program (site
``streaming.minibatch_kmeans``, one compile per chunk shape) that

1. runs a window of at most ``inner_iter`` Lloyd iterations on the
   chunk starting from the carried centers — the SAME
   :func:`~heat_tpu.cluster.kmeans._lloyd_window` body the checkpointed
   batch fit drives, with the SAME convergence carry (``shift``)
   threading across chunks;
2. hard-assigns the chunk against the window-refined centers (one more
   ``_lloyd_step`` distance pass) to get per-center batch counts and
   sums;
3. blends: ``counts' = decay·counts + counts_b`` and ``centers' =
   (decay·counts·centers + sums_b) / counts'`` for centers the batch
   touched — the decayed running mean of everything each center has
   absorbed (``decay=1`` is the pure running mean; ``decay<1`` forgets
   old data geometrically, the non-stationary-stream mode).

Mini-batch K-Means is order-dependent, so the K-chunk result matches a
one-shot :class:`~heat_tpu.cluster.KMeans` fit only to a documented
tolerance (well-separated data converges to the same centers; the
equivalence battery pins it). Checkpoint/resume of the carry
(centers, counts, shift) IS bit-exact on the same chunk sequence.
"""

from __future__ import annotations

from typing import Optional, Union

import jax.numpy as jnp
import numpy as np

from ..cluster._kcluster import _KCluster, _d2, _pad_weights
from ..cluster.kmeans import _lloyd_window
from ..core import program_cache, types
from ..core.dndarray import DNDarray
from . import events

__all__ = ["MiniBatchKMeans"]


class MiniBatchKMeans(_KCluster):
    """Online K-Means over a chunked stream.

    Parameters
    ----------
    n_clusters : int
    init : 'random' | 'probability_based' | DNDarray
        Initial centers, drawn from the FIRST chunk (reference init
        semantics applied to the head of the stream).
    inner_iter : int
        Lloyd window length per chunk (the ``max_iter`` of the carried
        :func:`_lloyd_window`); the window still exits early when the
        carried center shift drops below ``tol``.
    tol : float
        Convergence threshold on the squared center shift carry.
    decay : float
        Count decay per chunk in (0, 1]: 1.0 accumulates the exact
        running mean; smaller values geometrically forget old chunks.
    random_state : int, optional
    """

    def __init__(
        self,
        n_clusters: int = 8,
        init: Union[str, DNDarray] = "random",
        inner_iter: int = 3,
        tol: float = 0.0,
        decay: float = 1.0,
        random_state: Optional[int] = None,
    ):
        super().__init__(
            "euclidean", n_clusters, init, inner_iter, tol, random_state
        )
        if not 0.0 < decay <= 1.0:
            raise ValueError(f"decay must be in (0, 1], got {decay}")
        self.inner_iter = int(inner_iter)
        self.decay = float(decay)
        self._centers_np: Optional[np.ndarray] = None
        self._counts_np: Optional[np.ndarray] = None
        self._shift = float("inf")
        self.chunks_seen = 0
        self.rows_seen = 0

    # -- streaming -----------------------------------------------------------

    def partial_fit(self, x: DNDarray) -> "MiniBatchKMeans":
        """Fold one chunk into (centers, counts, shift): one
        cached-program dispatch per chunk shape (zero-compile steady
        stream), carry state on the host."""
        if not isinstance(x, DNDarray):
            raise TypeError(f"input needs to be a DNDarray, but was {type(x)}")
        if x.ndim != 2:
            raise ValueError("input needs to be 2D")
        dt = types.promote_types(x.dtype, types.float32)
        xb = x._masked(0).astype(dt.jnp_type())
        w = _pad_weights(xb, x.shape[0])
        k = self.n_clusters
        if self._centers_np is None:
            # draw init centers from the head of the stream, then route
            # them through the host carry like every later chunk — the
            # program's carry inputs always enter with the same (host)
            # placement, so call 2+ re-enters call 1's executable
            init = self._initialize_cluster_centers(x).astype(xb.dtype)
            self._centers_np = np.asarray(init)
            self._counts_np = np.zeros((k,), dtype=self._centers_np.dtype)
            self._shift = float("inf")
        elif self._centers_np.shape[1] != xb.shape[1]:
            raise ValueError(
                f"partial_fit chunk has {xb.shape[1]} feature columns "
                f"but the carried centers hold {self._centers_np.shape[1]}"
            )
        centers = jnp.asarray(self._centers_np, dtype=xb.dtype)
        counts = jnp.asarray(self._counts_np, dtype=xb.dtype)
        shift = jnp.asarray(self._shift, xb.dtype)
        comm = x.comm
        inner = self.inner_iter
        # NOTE: the logical row count is NOT in the key — validity
        # weights are a program *argument*, so a short final chunk that
        # pads to the steady physical shape re-enters the warm program
        key = (
            "minibatch", tuple(xb.shape), str(xb.dtype), x.split, k, inner,
        )

        def build():
            def prog(xv, wv, c0, cnt0, shift0, tol, decay):
                # (1) the carried Lloyd window on this chunk
                c_ref, _, shift_out = _lloyd_window(
                    xv, wv, c0, shift0, inner, tol
                )
                # (2) hard assignment against the refined centers
                d2 = _d2(xv, c_ref)
                labels = jnp.argmin(d2, axis=1)
                onehot = (
                    labels[:, None] == jnp.arange(k)[None, :]
                ).astype(xv.dtype) * wv[:, None]
                c_b = jnp.sum(onehot, axis=0)        # (k,)
                s_b = onehot.T @ xv                   # (k, d)
                # (3) decayed-count blend into the running centers
                cnt = decay * cnt0 + c_b
                blended = (
                    (decay * cnt0)[:, None] * c0 + s_b
                ) / jnp.maximum(cnt, 1e-12)[:, None]
                c_new = jnp.where(c_b[:, None] > 0, blended, c0)
                inertia = jnp.sum(jnp.min(d2, axis=1) * wv)
                return c_new, cnt, shift_out, inertia

            return prog

        fn = program_cache.cached_program(
            "streaming.minibatch_kmeans", key, build, comm=comm,
        )
        centers, counts, shift, inertia = fn(
            xb, w, centers, counts, shift,
            jnp.asarray(self.tol, xb.dtype),
            jnp.asarray(self.decay, xb.dtype),
        )
        self._centers_np = np.asarray(centers)
        self._counts_np = np.asarray(counts)
        self._shift = float(shift)
        self._inertia = float(inertia)
        self.chunks_seen += 1
        self.rows_seen += int(x.shape[0])
        self._cluster_centers = DNDarray.from_logical(
            centers, None, x.device, x.comm, dt
        )
        return self

    # -- checkpoint/resume ---------------------------------------------------

    def save(self, path: str) -> str:
        """Checkpoint the carry (centers, counts, shift) — same
        substrate and resume-equivalence contract as the batch
        ``KMeans`` checkpointed fit."""
        from .. import resilience

        if self._centers_np is None:
            raise RuntimeError("nothing to checkpoint: no chunk seen yet")
        out = resilience.save_checkpoint(
            [self._centers_np, self._counts_np], path,
            extra={
                "algo": "minibatch_kmeans",
                "shift": float(self._shift),
                "chunks_seen": int(self.chunks_seen),
                "rows_seen": int(self.rows_seen),
                "decay": float(self.decay),
                "inner_iter": int(self.inner_iter),
                "tol": float(self.tol),
            },
        )
        events.emit("minibatch_kmeans", "checkpoint", path=path,
                    rows_seen=self.rows_seen, chunks=self.chunks_seen)
        return out

    @classmethod
    def restore(cls, path: str) -> "MiniBatchKMeans":
        from .. import resilience

        leaves, extra = resilience.load_checkpoint(path, with_extra=True)
        if (extra or {}).get("algo") != "minibatch_kmeans" or len(leaves) != 2:
            raise resilience.CheckpointError(
                f"{path!r} is a {(extra or {}).get('algo')!r} checkpoint, "
                f"not minibatch_kmeans"
            )
        centers = np.asarray(leaves[0])
        est = cls(
            n_clusters=centers.shape[0],
            inner_iter=int(extra.get("inner_iter", 3)),
            tol=float(extra.get("tol", 0.0)),
            decay=float(extra.get("decay", 1.0)),
        )
        est._centers_np = centers
        est._counts_np = np.asarray(leaves[1])
        est._shift = float(extra["shift"])
        est.chunks_seen = int(extra.get("chunks_seen", 0))
        est.rows_seen = int(extra.get("rows_seen", 0))
        events.emit("minibatch_kmeans", "resume", path=path,
                    rows_seen=est.rows_seen, chunks=est.chunks_seen)
        return est
