"""heat_tpu.streaming — online estimators, out-of-core ingestion, and
versioned fit-while-serve (ISSUE 16; docs/STREAMING.md).

Three composing pieces:

* **online estimators** — :class:`StreamingMoments` (the single-pass
  pallas Welford kernel behind a Chan-mergeable host carry),
  :class:`MiniBatchKMeans` (the Lloyd shift-carry window with
  decayed-count blending), and the incremental
  :meth:`heat_tpu.regression.Lasso.partial_fit` (warm-started
  coordinate steps). Every ``partial_fit`` is ONE cached program per
  (chunk shape, split) — a steady stream runs zero-compile
  (``program_cache.site_stats("streaming.")`` is the oracle) — and the
  carries checkpoint/resume bit-exactly via
  :mod:`heat_tpu.resilience.checkpoint`;
* **out-of-core ingestion** — :class:`ChunkStream` walks HDF5/npy files
  in row blocks sized by ``memory_guard.temp_budget()``, never
  materializing a file (the reference's ``PartialH5Dataset`` pattern);
* **fit-while-serve** — ``Server.publish`` swaps a freshly fitted
  version in as a zero-compile program-argument update, and
  :func:`rolling_update` rolls a new checkpoint through a
  :class:`~heat_tpu.serve.net.ReplicaPool` replica-by-replica with the
  router draining each one.
"""

from __future__ import annotations

from .chunks import ChunkStream
from .events import EVENT_COUNTER, emit
from .minibatch import MiniBatchKMeans
from .moments import StreamingMoments
from .publish import rolling_update

__all__ = [
    "ChunkStream",
    "EVENT_COUNTER",
    "MiniBatchKMeans",
    "StreamingMoments",
    "emit",
    "rolling_update",
]
