"""Telemetry naming contract of the streaming subsystem (ISSUE 16).

Every ``streaming`` instant event increments exactly one aggregate
counter (``streaming.<name>``) alongside its emission, so a **live**
``report.summarize()`` (reading counters) and an **offline** one
(replaying a JSONL sink) reconstruct the *same* ``streaming`` block —
the reconciliation contract PR 5 established for resilience, PR 11 for
autotune, PR 12 for the router/pool tier, and PR 13 for sparse,
extended to the streaming tier. ``EVENT_COUNTER`` is that event-name →
counter-name map; :mod:`heat_tpu.telemetry.report` imports it for the
offline rename.

One deliberate extension: a ``stream_chunk`` event additionally folds
its ``rows`` field into the ``streaming.rows`` counter (the rows/s
numerator), and the offline reconstruction sums the same field — the
pair stays reconciled because both sides read the one ``rows`` value.
"""

from __future__ import annotations

from typing import Any

from .. import telemetry

__all__ = ["EVENT_COUNTER", "emit"]

# event (on the wire / in the sink)  ->  counter suffix (live registry)
EVENT_COUNTER = {
    "stream_chunk": "chunks",        # one out-of-core chunk ingested
    "version_swap": "version_swaps",  # in-process versioned publish
    "roll_step": "roll_steps",       # one replica replaced in a rolling update
    "checkpoint": "checkpoints",     # estimator carry checkpointed
    "resume": "resumes",             # estimator carry restored mid-stream
}


def emit(name: str, event: str, **fields: Any) -> None:
    """Emit one ``streaming`` instant event + its paired counter (no-op
    while telemetry is disabled — one flag check)."""
    if not telemetry.enabled():
        return
    reg = telemetry.get_registry()
    reg.add(f"streaming.{EVENT_COUNTER[event]}", 1)
    if event == "stream_chunk" and fields.get("rows"):
        reg.add("streaming.rows", int(fields["rows"]))
    reg.emit("streaming", name, event=event, **fields)
