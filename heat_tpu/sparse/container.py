"""The sharded sparse array container (ISSUE 13 tentpole).

A :class:`SparseDNDarray` is the CSR analog of the dense
:class:`~heat_tpu.core.dndarray.DNDarray`: **row-split along
``split=0``** with replicated host metadata, the same design language as
``ht.ragged`` (core/ragged.py — counts/displs plus a shard-aligned owner
map). Dense arrays carry a tail pad because XLA wants equal shards; a
sparse array additionally carries a per-shard **element capacity** pad,
because per-shard nnz is data-dependent while XLA shards must be
uniform:

* ``indptr``  — physical ``(p·(r+1),)`` int32, sharded: each mesh
  position holds its own local CSR row pointer (``r = ceil(m/p)`` rows
  per shard, tail rows of the last shard are *pad rows* with zero
  entries); ``indptr[r] = local_nnz``.
* ``indices`` — physical ``(p·cap,)`` int32, sharded: shard-local column
  ids; slots past ``local_nnz`` are pad (column 0), never reachable
  through ``indptr``.
* ``values``  — physical ``(p·cap,)``, sharded, same slot layout.

``cap = max(1, max_s nnz_s)`` is uniform across shards (the ragged
intent — "shard *s* owns ``counts[s]`` elements" — is metadata, exactly
like :class:`~heat_tpu.core.ragged.Ragged`). Replicated host metadata:
``counts``/``displs`` (per-shard element tallies) and the ceil-rule row
``owner`` map. Pad slots obey the dense pad invariant: their values are
zeros and **must never influence a result** — every kernel drops them by
segment id (an out-of-range segment, not a masked multiply, so even
inf/nan payloads in the dense operand cannot leak through a pad slot).

Index and pointer payloads live shard-local for the container's whole
life: :func:`~heat_tpu.sparse.ops.spmv`/``spmm`` move only float
operand/result payloads over the wire, and :func:`transpose` (the one
all-to-all-bearing op) pins its index-carrying slab exchange
``precision='off'`` — heatlint HL003's ``spmv``/``spmm`` kernel tokens
enforce that invariant for future edits (docs/STATIC_ANALYSIS.md).
"""

from __future__ import annotations

import builtins
from typing import Optional, Tuple, Type

import jax
import jax.numpy as jnp
import numpy as np

from ..core import types
from ..core.communication import MeshCommunication, sanitize_comm
from ..core.devices import Device, get_device
from ..core.dndarray import DNDarray

__all__ = ["SparseDNDarray"]


def _shard_array(comm: MeshCommunication, host: np.ndarray) -> jax.Array:
    """Lay a ``(p, per_shard)`` host matrix out as the flat sharded
    physical buffer ``(p·per_shard,)``."""
    flat = jnp.asarray(host.reshape(-1))
    if comm.size > 1:
        flat = jax.device_put(flat, comm.sharding(0, 1))
    return flat


class SparseDNDarray:
    """Distributed CSR matrix (see module docstring for the layout).

    Construct through :func:`heat_tpu.sparse.csr_from_dense` /
    :func:`~heat_tpu.sparse.csr_from_coo` (or
    :meth:`from_shard_arrays` when the sharded buffers already exist —
    the path the compiled transpose program uses).
    """

    def __init__(
        self,
        indptr: jax.Array,
        indices: jax.Array,
        values: jax.Array,
        gshape: Tuple[int, int],
        dtype: Type[types.datatype],
        counts: np.ndarray,
        device: Device,
        comm: MeshCommunication,
    ):
        m, n = (int(s) for s in gshape)
        if m <= 0 or n <= 0:
            raise ValueError(f"sparse shape must be positive, got {gshape}")
        p = comm.size
        r = comm.chunk_size(m)
        counts = np.asarray(counts, dtype=np.int64).reshape(-1)
        if counts.shape[0] != p:
            raise ValueError(
                f"counts must have one entry per mesh position ({p}), "
                f"got {counts.shape[0]}"
            )
        if (counts < 0).any():
            raise ValueError(f"counts must be non-negative: {counts.tolist()}")
        if indptr.shape != (p * (r + 1),):
            raise ValueError(
                f"indptr physical shape {tuple(indptr.shape)} != "
                f"({p * (r + 1)},) for gshape {gshape} on a {p}-mesh"
            )
        if indices.shape != values.shape or indices.ndim != 1:
            raise ValueError(
                f"indices/values must be matching 1-D buffers, got "
                f"{tuple(indices.shape)} vs {tuple(values.shape)}"
            )
        if indices.shape[0] % p:
            raise ValueError(
                f"element buffer length {indices.shape[0]} does not shard "
                f"over {p} positions"
            )
        cap = indices.shape[0] // p
        if int(counts.max(initial=0)) > cap:
            raise ValueError(
                f"counts {counts.tolist()} exceed the per-shard capacity {cap}"
            )
        self.__indptr = indptr
        self.__indices = indices
        self.__values = values
        self.__gshape = (m, n)
        self.__dtype = dtype
        self.__counts = counts
        self.__device = device
        self.__comm = comm
        self.__owner = None

    # -- metadata -------------------------------------------------------------

    @property
    def shape(self) -> Tuple[int, int]:
        return self.__gshape

    @property
    def ndim(self) -> int:
        return 2

    @property
    def split(self) -> int:
        """Always row-split: CSR's natural distribution axis."""
        return 0

    @property
    def dtype(self) -> Type[types.datatype]:
        return self.__dtype

    @property
    def device(self) -> Device:
        return self.__device

    @property
    def comm(self) -> MeshCommunication:
        return self.__comm

    @property
    def indptr(self) -> jax.Array:
        """The sharded physical ``(p·(r+1),)`` local row pointers."""
        return self.__indptr

    @property
    def indices(self) -> jax.Array:
        """The sharded physical ``(p·cap,)`` column ids."""
        return self.__indices

    @property
    def values(self) -> jax.Array:
        """The sharded physical ``(p·cap,)`` element values."""
        return self.__values

    @property
    def counts(self) -> np.ndarray:
        """Per-shard element tallies (a copy) — the ragged metadata."""
        return self.__counts.copy()

    @property
    def displs(self) -> np.ndarray:
        """Per-shard element start offsets into the global nnz order."""
        return np.concatenate([[0], np.cumsum(self.__counts)[:-1]])

    @property
    def nnz(self) -> int:
        return int(self.__counts.sum())

    @property
    def capacity(self) -> int:
        """Uniform per-shard element capacity (the sparse analog of the
        dense tail pad)."""
        return int(self.__indices.shape[0]) // self.__comm.size

    @property
    def row_chunk(self) -> int:
        """Rows per shard (ceil rule) — ``indptr`` stride minus one."""
        return self.__comm.chunk_size(self.__gshape[0])

    @property
    def density(self) -> float:
        m, n = self.__gshape
        return self.nnz / float(m * n)

    @property
    def owner(self) -> DNDarray:
        """``owner[i]`` = mesh position holding row ``i`` — the ceil-rule
        map as a row-aligned int64 DNDarray (split 0), mirroring
        :attr:`heat_tpu.core.ragged.Ragged.owner`. Built once, cached."""
        if self.__owner is None:
            from ..core import factories

            m = self.__gshape[0]
            r = self.row_chunk
            vec = np.minimum(
                np.arange(m, dtype=np.int64) // max(r, 1),
                self.__comm.size - 1,
            )
            self.__owner = factories.array(
                vec, split=0, device=self.__device, comm=self.__comm
            )
        return self.__owner

    def __repr__(self) -> str:
        m, n = self.__gshape
        return (
            f"SparseDNDarray(shape=({m}, {n}), nnz={self.nnz}, "
            f"density={self.density:.4g}, dtype={self.__dtype.__name__}, "
            f"split=0, mesh={self.__comm.size}, cap={self.capacity})"
        )

    # -- construction helpers -------------------------------------------------

    @classmethod
    def from_shard_arrays(
        cls,
        indptr: jax.Array,
        indices: jax.Array,
        values: jax.Array,
        gshape: Tuple[int, int],
        counts: np.ndarray,
        device: Optional[Device] = None,
        comm: Optional[MeshCommunication] = None,
        dtype: Optional[Type[types.datatype]] = None,
    ) -> "SparseDNDarray":
        """Wrap already-sharded physical buffers (the compiled-program
        construction path: transpose's build stage hands its outputs
        straight here, no host round-trip)."""
        comm = sanitize_comm(comm)
        device = device if device is not None else get_device()
        ht_dtype = (
            dtype if dtype is not None
            else types.canonical_heat_type(values.dtype)
        )
        return cls(
            indptr, indices, values, tuple(gshape), ht_dtype,
            counts, device, comm,
        )

    @classmethod
    def _from_host_csr_shards(
        cls,
        indptr: np.ndarray,    # (p, r+1)
        indices: np.ndarray,   # (p, cap)
        values: np.ndarray,    # (p, cap)
        gshape: Tuple[int, int],
        counts: np.ndarray,
        device: Optional[Device] = None,
        comm: Optional[MeshCommunication] = None,
        dtype: Optional[Type[types.datatype]] = None,
    ) -> "SparseDNDarray":
        """Lay host per-shard CSR blocks onto the mesh (the constructor
        finishing pass of ``csr_from_dense``/``csr_from_coo``)."""
        comm = sanitize_comm(comm)
        device = device if device is not None else get_device()
        vals = np.ascontiguousarray(values)
        ht_dtype = (
            dtype if dtype is not None
            else types.canonical_heat_type(vals.dtype)
        )
        return cls(
            _shard_array(comm, np.ascontiguousarray(indptr, dtype=np.int32)),
            _shard_array(comm, np.ascontiguousarray(indices, dtype=np.int32)),
            _shard_array(comm, vals),
            tuple(gshape), ht_dtype, counts, device, comm,
        )

    # -- conversions ----------------------------------------------------------

    def to_dense(self) -> DNDarray:
        """Materialize the dense row-split DNDarray (one cached scatter
        program; see :func:`heat_tpu.sparse.to_dense`)."""
        from . import ops

        return ops.to_dense(self)

    def coo(self) -> Tuple[np.ndarray, np.ndarray, np.ndarray]:
        """Host COO triplets ``(rows, cols, values)`` in global CSR
        order — the inspection/export path (small host sync)."""
        p = self.__comm.size
        r = self.row_chunk
        cap = self.capacity
        ip = np.asarray(self.__indptr).reshape(p, r + 1)
        ix = np.asarray(self.__indices).reshape(p, cap)
        v = np.asarray(self.__values).reshape(p, cap)
        rows, cols, vals = [], [], []
        for s in range(p):
            c = int(self.__counts[s])
            local = np.repeat(np.arange(r, dtype=np.int64), np.diff(ip[s]))
            rows.append(local[:c] + s * r)
            cols.append(ix[s, :c].astype(np.int64))
            vals.append(v[s, :c])
        return (
            np.concatenate(rows), np.concatenate(cols), np.concatenate(vals),
        )

    # -- structural ops -------------------------------------------------------

    def transpose(self) -> "SparseDNDarray":
        from . import ops

        return ops.transpose(self)

    @property
    def T(self) -> "SparseDNDarray":
        return self.transpose()

    # -- elementwise scalar ops on values -------------------------------------

    def _map_values(self, fn, dtype=None) -> "SparseDNDarray":
        """New container with ``values`` mapped elementwise — the
        structure (indptr/indices/counts) is shared, so scalar ops are
        one sharded elementwise dispatch over the element buffer."""
        new_vals = fn(self.__values)
        ht_dtype = (
            dtype if dtype is not None
            else types.canonical_heat_type(new_vals.dtype)
        )
        return SparseDNDarray(
            self.__indptr, self.__indices, new_vals, self.__gshape,
            ht_dtype, self.__counts, self.__device, self.__comm,
        )

    def astype(self, dtype) -> "SparseDNDarray":
        ht_dtype = types.canonical_heat_type(dtype)
        return self._map_values(
            lambda v: v.astype(ht_dtype.jnp_type()), ht_dtype
        )

    def __mul__(self, other) -> "SparseDNDarray":
        if not isinstance(other, (builtins.int, builtins.float)):
            return NotImplemented
        return self._map_values(lambda v: v * other)

    __rmul__ = __mul__

    def __truediv__(self, other) -> "SparseDNDarray":
        if not isinstance(other, (builtins.int, builtins.float)):
            return NotImplemented
        return self._map_values(lambda v: v / other)

    def __neg__(self) -> "SparseDNDarray":
        return self._map_values(lambda v: -v)

    def __abs__(self) -> "SparseDNDarray":
        return self._map_values(jnp.abs)

    # -- linear algebra -------------------------------------------------------

    def __matmul__(self, other):
        from . import ops

        if isinstance(other, DNDarray):
            if other.ndim == 1:
                return ops.spmv(self, other)
            if other.ndim == 2:
                return ops.spmm(self, other)
        return NotImplemented

    def matvec(self, x: DNDarray, **kwargs) -> DNDarray:
        from . import ops

        return ops.spmv(self, x, **kwargs)

    # -- solver operator protocol (core/linalg/solver.py) ---------------------

    def _matvec_spec(self, dt: Type[types.datatype]):
        """The iterative-solver operator hook: ``(leaves, matvec, key)``
        where ``leaves`` are the program arguments (sharded CSR buffers,
        values cast to the solve dtype), ``matvec(leaves, x, n)`` is a
        pure traceable replicated-in/replicated-out product, and ``key``
        joins the solver's program-cache signature. Lets
        ``linalg.lanczos``/``cg`` treat a sparse matrix as a drop-in
        operator (ISSUE 13: Spectral's Krylov matvecs become spmv)."""
        from . import ops

        wire = ops.spmv_wire(dt.jnp_type())
        leaves = (
            self.__indptr, self.__indices,
            self.__values.astype(dt.jnp_type()),
        )
        return leaves, ops.make_solver_matvec(self.__comm, wire), ("csr", wire)
