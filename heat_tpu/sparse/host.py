"""Host-side CSR row batches — the wire/serving-facing sparse type.

:class:`CsrRows` is a tiny numpy-only container for a batch of sparse
feature rows in CSR form: the shape a sparse-feature inference request
has *before* it reaches a device. It is deliberately a **leaf module**
(numpy imports only, no jax, no package siblings) so the serving layer
(:mod:`heat_tpu.serve`) and the network wire codec
(:mod:`heat_tpu.serve.net.wire`) can import it without pulling in the
array machinery — the same layering contract ``heat_tpu/_knobs.py``
keeps.

The micro-batcher's view of the world: requests are *ragged* (every row
carries its own ``nnz``), batches are built by :meth:`concat`, re-split
by row slicing, and padded to a ``(row bucket, nnz bucket)`` lattice by
the server so every dispatch re-enters a finitely-warmable cached
program family (docs/SERVING.md §sparse_query).
"""

from __future__ import annotations

from typing import List, Sequence, Tuple

import numpy as np

__all__ = ["CsrRows"]


class CsrRows:
    """A batch of sparse rows over ``cols`` features, CSR layout.

    ``indptr`` is ``(rows + 1,)`` int64 monotone with ``indptr[0] == 0``;
    ``indices`` (column ids, int32, each ``< cols``) and ``values``
    (float) are ``(nnz,)``. Rows may be empty; duplicate columns within a
    row are rejected only where a consumer requires it (the serving
    kernel sums duplicates, matching scipy's unconsolidated semantics).
    """

    __slots__ = ("indptr", "indices", "values", "cols")

    def __init__(self, indptr, indices, values, cols: int):
        indptr = np.asarray(indptr, dtype=np.int64).reshape(-1)
        indices = np.asarray(indices, dtype=np.int32).reshape(-1)
        values = np.asarray(values).reshape(-1)
        cols = int(cols)
        if cols <= 0:
            raise ValueError(f"cols must be positive, got {cols}")
        if indptr.size < 1 or indptr[0] != 0:
            raise ValueError("indptr must start at 0")
        if (np.diff(indptr) < 0).any():
            raise ValueError("indptr must be monotone non-decreasing")
        if int(indptr[-1]) > indices.size or indices.size != values.size:
            # indices/values may extend PAST indptr[-1]: those slots are
            # nnz-bucket pad (column 0, value 0) no row ever reaches —
            # the padded() lattice form the serving batcher dispatches
            raise ValueError(
                f"indptr accounts for {int(indptr[-1])} entries but "
                f"indices/values hold {indices.size}/{values.size}"
            )
        if indices.size and (
            (indices < 0).any() or (indices >= cols).any()
        ):
            raise ValueError(f"column indices must lie in [0, {cols})")
        self.indptr = indptr
        self.indices = indices
        self.values = values
        self.cols = cols

    # -- shape arithmetic -----------------------------------------------------

    @property
    def rows(self) -> int:
        return self.indptr.size - 1

    @property
    def nnz(self) -> int:
        return int(self.indptr[-1])

    @property
    def shape(self) -> Tuple[int, int]:
        return (self.rows, self.cols)

    def __len__(self) -> int:
        return self.rows

    def __repr__(self) -> str:
        return (
            f"CsrRows(rows={self.rows}, cols={self.cols}, nnz={self.nnz}, "
            f"dtype={self.values.dtype})"
        )

    # -- construction ---------------------------------------------------------

    @classmethod
    def from_dense(cls, arr) -> "CsrRows":
        """Compact the nonzeros of a dense ``(rows, cols)`` (or 1-D) array."""
        a = np.asarray(arr)
        if a.ndim == 1:
            a = a[None, :]
        if a.ndim != 2:
            raise ValueError(f"expected 1-D or 2-D input, got {a.ndim}-D")
        rows, cols = np.nonzero(a)
        indptr = np.zeros(a.shape[0] + 1, dtype=np.int64)
        np.add.at(indptr, rows + 1, 1)
        return cls(
            np.cumsum(indptr), cols.astype(np.int32), a[rows, cols],
            a.shape[1],
        )

    def to_dense(self) -> np.ndarray:
        """Densify (duplicate columns within a row sum, scipy-style).
        Pad element slots past ``indptr[-1]`` are ignored."""
        out = np.zeros((self.rows, self.cols), dtype=self.values.dtype)
        row_of = np.repeat(np.arange(self.rows), np.diff(self.indptr))
        nnz = self.nnz
        np.add.at(out, (row_of, self.indices[:nnz]), self.values[:nnz])
        return out

    # -- batching (the micro-batcher's operations) ----------------------------

    def __getitem__(self, key) -> "CsrRows":
        """Row slicing (contiguous slices only — what the batcher's
        oversize chunking needs)."""
        if not isinstance(key, slice) or key.step not in (None, 1):
            raise TypeError("CsrRows supports contiguous row slices only")
        start, stop, _ = key.indices(self.rows)
        stop = max(stop, start)
        lo, hi = int(self.indptr[start]), int(self.indptr[stop])
        return CsrRows(
            self.indptr[start:stop + 1] - lo,
            self.indices[lo:hi],
            self.values[lo:hi],
            self.cols,
        )

    @staticmethod
    def concat(parts: Sequence["CsrRows"]) -> "CsrRows":
        """Stack row batches (all over the same ``cols``) — the
        micro-batch coalescing step. Pad element slots past a part's
        ``indptr[-1]`` (the legal padded lattice form a client may send
        over the wire) are STRIPPED: concatenating them whole would
        shift every later part's row pointers into the pad region."""
        parts = list(parts)
        if not parts:
            raise ValueError("concat needs at least one CsrRows")
        cols = parts[0].cols
        if any(p.cols != cols for p in parts):
            raise ValueError("cannot concat CsrRows over different cols")
        if len(parts) == 1:
            return parts[0]
        ips: List[np.ndarray] = [parts[0].indptr]
        off = parts[0].nnz
        for p in parts[1:]:
            ips.append(p.indptr[1:] + off)
            off += p.nnz
        return CsrRows(
            np.concatenate(ips),
            np.concatenate([p.indices[:p.nnz] for p in parts]),
            np.concatenate([p.values[:p.nnz] for p in parts]),
            cols,
        )

    def padded(self, rows: int, nnz: int) -> "CsrRows":
        """Pad to exactly ``(rows, nnz)``: appended rows are empty,
        appended element slots carry ``(column 0, value 0)`` and belong
        to no row (``indptr`` never reaches them) — the masked-neutral
        pad discipline of the serving batcher (pad slots cannot perturb
        a real row's reduction)."""
        if rows < self.rows or nnz < self.nnz:
            raise ValueError(
                f"cannot pad {self.shape}/{self.nnz}nnz down to "
                f"({rows}, ...)/{nnz}nnz"
            )
        ip = np.concatenate([
            self.indptr,
            np.full(rows - self.rows, self.nnz, dtype=np.int64),
        ])
        ix = np.concatenate([
            self.indices, np.zeros(nnz - self.nnz, dtype=np.int32),
        ])
        v = np.concatenate([
            self.values,
            np.zeros(nnz - self.nnz, dtype=self.values.dtype),
        ])
        return CsrRows(ip, ix, v, self.cols)
