"""``heat_tpu.sparse`` — sharded CSR/COO arrays with audited SpMV/SpMM
(ISSUE 13).

The widest scenario gap the dense stack left open: ``heat/graph``-class
workloads (Laplacians, spectral clustering, KNN graphs) materialize
O(n²) dense similarity matrices. This package adds the row-split CSR
container (:class:`SparseDNDarray` — the ``ht.ragged`` design language:
replicated counts/displs metadata plus a shard-aligned owner map over
uniform-capacity shards), cached ``shard_map`` sparse × dense products
whose collective tails are cost-model-priced and HLO-audit-pinned, a
budget-planned all-to-all transpose, and the construction paths
(thresholded dense compaction, distributed-sort COO assembly). Consumers
wired through it: ``graph.Laplacian`` (eNeighbour), ``cluster.Spectral``
(Lanczos matvecs become spmv), ``graph.connected_components`` (iterated
structure-only min-propagation), and the ``sparse_query`` serving
endpoint (ragged CSR rows through the micro-batcher —
:class:`~heat_tpu.sparse.host.CsrRows` on the wire).

Observability: every op pairs one ``sparse.*`` counter with one
``sparse`` instant event (:data:`EVENT_COUNTER`), so
``report.summarize()``'s ``sparse`` block reconstructs identically live
and offline. docs/SPARSE.md is the operator guide.
"""

from .container import SparseDNDarray
from .host import CsrRows
from .ops import (
    csr_from_coo,
    csr_from_dense,
    spmm,
    spmv,
    spmv_wire,
    to_dense,
    transpose,
)

__all__ = [
    "SparseDNDarray",
    "CsrRows",
    "csr_from_coo",
    "csr_from_dense",
    "spmv",
    "spmm",
    "spmv_wire",
    "to_dense",
    "transpose",
    "EVENT_COUNTER",
]

# sparse event name -> registry counter suffix: every `sparse` event is
# paired 1:1 with a `sparse.<name>` counter increment, so the offline
# summarize reconstruction matches the live counters exactly (the PR 5 /
# PR 11 / PR 12 reconciliation contract).
EVENT_COUNTER = {
    name: f"sparse.{name}"
    for name in (
        "spmv", "spmm", "to_dense", "transpose", "from_dense", "from_coo",
        "laplacian", "dense_fallback", "components",
    )
}
