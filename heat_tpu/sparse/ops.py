"""Sparse kernels and constructors (ISSUE 13).

``spmv``/``spmm`` are cached ``shard_map`` programs (sites
``sparse.spmv``/``sparse.spmm`` in the process-global program registry):
every shard contracts its local CSR rows against the dense operand with
a segment reduction, and the only wire traffic is the **float tails** —
an in-kernel all-gather when the dense operand is row-split, and one
all-reduce when the caller asks for a replicated result. Both tails are
priced by :func:`heat_tpu.telemetry.collectives.spmv_cost` /
``spmm_cost`` and pinned zero-drift by the HLO auditor; index/indptr
payloads never leave their shard. The wire precision of the float tails
is ``HEAT_TPU_SPARSE_SPMV_PREC`` (default exact) — the hop call sites
live in :func:`_gather_operand` / :func:`_combine_replicated`, *outside*
any ``spmv``/``spmm``-named function, because heatlint HL003 treats
those kernel names as exact-semantics tokens: any future hop added
inside them (the place index data lives) must pin ``precision='off'``
or fail the lint gate.

``transpose`` is the one all-to-all-bearing op: elements route to the
shard owning their destination row through worst-case-sized static
slabs, planned against ``HEAT_TPU_HBM_BUDGET`` into bounded-memory
stages exactly like the dense relayout planner (arXiv:2112.01075 —
each stage is its own cached program whose slab fits the temp budget).
Both slab payloads (packed int64 sort keys carrying ``(row, col)``, and
the values) pin ``precision='off'``: the key payload IS index data.

Constructors (``csr_from_dense``, ``csr_from_coo``) are host-finishing
paths: the heavy compute (the distributed sort ``csr_from_coo`` reuses
from ``manipulations.sort``) runs on device, the final per-shard packing
runs on host — construction is not a steady-state hot path, and the
metadata (counts/displs) is replicated host state by design, exactly
like :class:`~heat_tpu.core.ragged.Ragged`.
"""

from __future__ import annotations

import math
from typing import Optional, Tuple

import jax
import jax.numpy as jnp
import numpy as np

from heat_tpu import _knobs as knobs

from .. import telemetry
from ..core import program_cache, types
from ..core.communication import MeshCommunication, sanitize_comm
from ..core.devices import get_device
from ..core.dndarray import DNDarray
from ..resilience import memory_guard
from .container import SparseDNDarray

__all__ = [
    "spmv",
    "spmm",
    "to_dense",
    "transpose",
    "csr_from_dense",
    "csr_from_coo",
    "spmv_wire",
    "make_solver_matvec",
]

# Packed transpose sort key sentinel: sorts past every real (col, row)
# key and survives // and % arithmetic without overflow.
_SENTINEL = np.int64(np.iinfo(np.int64).max)

_REDUCES = ("sum", "min", "max")


def _record(op: str, **fields) -> None:
    """One counter + one instant event per sparse operation, with
    matching names — the live==offline summarize-reconciliation contract
    (telemetry/report.py ``sparse`` block)."""
    if telemetry.enabled():
        reg = telemetry.get_registry()
        reg.add(f"sparse.{op}", 1)
        reg.emit("sparse", op, event=op, **fields)


def spmv_wire(dtype, precision: Optional[str] = None) -> str:
    """The effective wire mode of the sparse float tails: the per-call
    override, else ``HEAT_TPU_SPARSE_SPMV_PREC`` — demoted to ``off``
    for non-float payloads (index/integer data always moves exact)."""
    if precision is None:
        precision = knobs.get("HEAT_TPU_SPARSE_SPMV_PREC") or "off"
    p = str(precision).strip().lower()
    if p not in ("off", "bf16"):
        raise ValueError(
            f"sparse wire precision must be 'off' or 'bf16', got {precision!r}"
        )
    if p != "off" and not jnp.issubdtype(jnp.dtype(dtype), jnp.floating):
        return "off"
    return p


# -- in-kernel building blocks -------------------------------------------------
#
# NOTE these hop helpers are module-level on purpose (not nested inside
# the spmv/spmm kernel bodies): HL003 token-matches the enclosing
# function chain, and spmv/spmm are exact-semantics tokens — a comm hop
# inside a function of that name must pin precision='off'. The float
# value tails here are legitimately knob-gated (the ring-cdist
# contract), so they live outside the token scope; index payloads never
# ride a collective at all.


def _slot_rows(indptr: jax.Array, nslots: int) -> jax.Array:
    """Local row id per element slot, derived from the shard CSR
    pointer. Pad slots (``>= local_nnz``) land on row ``r`` — one past
    the last local row — so segment reductions with ``num_segments=r``
    drop them structurally (no masked multiply: even inf/nan operand
    values cannot leak through a pad slot)."""
    slots = jnp.arange(nslots, dtype=indptr.dtype)
    return jnp.searchsorted(indptr, slots, side="right") - 1


def _gather_operand(comm: MeshCommunication, xc: jax.Array, wire: str):
    """All-gather a row-split dense operand's physical chunks inside the
    kernel (float value payload; wire mode = the resolved sparse knob)."""
    return comm.all_gather(xc, tiled=True, precision=wire)


def _combine_replicated(
    comm: MeshCommunication, yg: jax.Array, wire: str, reduce: str
):
    """Combine per-shard global partials into the replicated result —
    the spmv all-reduce tail (float value payload; ``min``/``max``
    extremes ride the never-compressed pmin/pmax wrappers)."""
    if reduce == "min":
        return comm.pmin(yg)
    if reduce == "max":
        return comm.pmax(yg)
    return comm.psum(yg, precision=wire)


def _reduce_identity(dtype, reduce: str):
    if reduce == "sum":
        return jnp.zeros((), dtype=dtype)
    info = (
        jnp.finfo(dtype)
        if jnp.issubdtype(jnp.dtype(dtype), jnp.floating)
        else jnp.iinfo(dtype)
    )
    return jnp.asarray(info.max if reduce == "min" else info.min, dtype=dtype)


def _segment_reduce(contrib, rows, num_segments: int, reduce: str):
    if reduce == "min":
        return jax.ops.segment_min(contrib, rows, num_segments=num_segments)
    if reduce == "max":
        return jax.ops.segment_max(contrib, rows, num_segments=num_segments)
    return jax.ops.segment_sum(contrib, rows, num_segments=num_segments)


def _local_contract(ip, ix, vals, xg, reduce: str, pattern: bool):
    """One shard's CSR × dense contraction: ``(r,)`` for a vector
    operand, ``(r, k)`` for a matrix operand. ``pattern=True`` ignores
    the stored values (structure-only propagation — the
    connected-components label relay)."""
    rows = _slot_rows(ip, ix.shape[0])
    taken = xg[ix]
    if pattern:
        contrib = taken
    elif taken.ndim == 2:
        contrib = vals[:, None] * taken
    else:
        contrib = vals * taken
    return _segment_reduce(contrib, rows, ip.shape[0] - 1, reduce)


def _spmv_build(
    comm: MeshCommunication,
    x_split: Optional[int],
    out_split: Optional[int],
    wire: str,
    reduce: str,
    pattern: bool,
    x_ndim: int,
):
    """Program builder for one (layout, wire, reduce) spmv/spmm family —
    runs only on a registry miss; shapes dispatch inside the wrapper."""
    e_spec = comm.spec(0, 1)
    x_spec = comm.spec(0 if x_split == 0 else None, x_ndim)
    out_spec = (
        comm.spec(0, x_ndim) if out_split == 0 else comm.spec(None, x_ndim)
    )
    p = comm.size

    def body(ip, ix, vals, x):
        xg = (
            _gather_operand(comm, x, wire)
            if (x_split == 0 and p > 1) else x
        )
        y = _local_contract(ip, ix, vals, xg, reduce, pattern)
        if out_split == 0:
            return y
        r = ip.shape[0] - 1
        full_shape = (r * p,) + y.shape[1:]
        yg = jnp.full(full_shape, _reduce_identity(y.dtype, reduce))
        zero = jnp.zeros((), dtype=jnp.int32)
        start = (comm.axis_index() * r,) + (zero,) * (y.ndim - 1)
        yg = jax.lax.dynamic_update_slice(yg, y, start)
        # the combine runs on 1-position meshes too (a trivial hop):
        # the collective is what makes the output mesh-invariant, so
        # the replicated out_spec typechecks on every mesh size
        return _combine_replicated(comm, yg, wire, reduce)

    def call(ip, ix, vals, x):
        # NOTE the logical row count is NOT closed over here (it is not
        # part of the program key — one entry serves every shape family);
        # the dispatch slices the replicated result to logical rows
        # eagerly, a local op
        return jax.shard_map(
            body, mesh=comm.mesh,
            in_specs=(e_spec, e_spec, e_spec, x_spec),
            out_specs=out_spec,
        )(ip, ix, vals, x)

    return call


def _dispatch_sparse_dense(
    op: str,
    A: SparseDNDarray,
    x: DNDarray,
    out_split: Optional[int],
    precision: Optional[str],
    reduce: str,
    pattern: bool,
    audit: bool,
):
    """Shared spmv/spmm dispatch: resolve dtype + wire, price the
    collective tails, fetch the cached program, audit on request."""
    if not isinstance(A, SparseDNDarray):
        raise TypeError(f"expected a SparseDNDarray, got {type(A)}")
    if not isinstance(x, DNDarray):
        raise TypeError(f"dense operand must be a DNDarray, got {type(x)}")
    want_ndim = 1 if op == "spmv" else 2
    if x.ndim != want_ndim:
        raise ValueError(f"{op} expects a {want_ndim}-D dense operand")
    if x.shape[0] != A.shape[1]:
        raise ValueError(
            f"{op}: operand leading dim {x.shape[0]} != sparse cols "
            f"{A.shape[1]}"
        )
    if x.split not in (None, 0):
        raise NotImplementedError(f"{op} requires x.split in (None, 0)")
    if out_split not in (None, 0):
        raise NotImplementedError(f"{op} supports out_split in (None, 0)")
    if reduce not in _REDUCES:
        raise ValueError(f"reduce must be one of {_REDUCES}, got {reduce!r}")
    if x.comm != A.comm:
        raise ValueError(f"{op}: operands live on different communicators")

    comm = A.comm
    p = comm.size
    m, n = A.shape
    k = 1 if op == "spmv" else x.shape[1]
    dt = x.dtype if pattern else types.promote_types(A.dtype, x.dtype)
    # extremes and structure-only relays are exactness-critical; only
    # the summing VALUE tails are knob-compressible
    compressible = reduce == "sum" and not pattern
    wire = spmv_wire(dt.jnp_type(), precision) if compressible else "off"

    cost_fn = (
        telemetry.collectives.spmv_cost if op == "spmv"
        else telemetry.collectives.spmm_cost
    )
    cost_args = (m, n) if op == "spmv" else (m, n, k)
    cost, fields, do_audit = telemetry.op_cost(
        cost_fn, *cost_args, dt.byte_size(), p, x.split, out_split, wire,
        audit=audit,
    )

    key = (x.split, out_split, wire, reduce, pattern, dt.char())
    xb = x.larray.astype(dt.jnp_type())
    vals = A.values if pattern else A.values.astype(dt.jnp_type())
    args = (A.indptr, A.indices, vals, xb)
    with telemetry.span(
        f"sparse.{op}", gshape=[m, n], nnz=A.nnz, mesh=p, **fields
    ) as sp:
        prog = program_cache.cached_program(
            f"sparse.{op}", key,
            lambda: _spmv_build(
                comm, x.split, out_split, wire, reduce, pattern, want_ndim,
            ),
            comm=comm,
        )
        if do_audit:
            # the audit memo key carries the physical aval signature ON
            # TOP of the program key: one registry entry serves every
            # shape family (avals dispatch inside the wrapper), but each
            # shape lowers a distinct program whose collective bytes the
            # prediction must match shape-for-shape
            aval_sig = tuple(tuple(a.shape) for a in args)
            telemetry.hlo.audit_call(
                f"sparse.{op}",
                lambda: (prog, args),
                predicted=cost,
                key=program_cache.program_key(
                    f"sparse.{op}", key + (aval_sig,), comm=comm
                ),
                fields={"mesh": p, "nnz": A.nnz},
            )
        out = sp.output(prog(*args))
        if out_split is None:
            out = out[:m]  # replicated physical → logical rows (local slice)
    _record(
        op, nnz=A.nnz, rows=m, cols=n, out_split=out_split, wire=wire,
        **({"bytes": cost.bytes} if cost is not None else {}),
    )
    gshape = (m,) if op == "spmv" else (m, k)
    return DNDarray(out, gshape, dt, out_split, A.device, comm, True)


def spmv(
    A: SparseDNDarray,
    x: DNDarray,
    *,
    out_split: Optional[int] = 0,
    precision: Optional[str] = None,
    reduce: str = "sum",
    pattern: bool = False,
    audit: bool = False,
) -> DNDarray:
    """Sparse matrix–vector product ``A @ x`` as one cached ``shard_map``
    program (site ``sparse.spmv``).

    ``x`` may be replicated or row-split (``split=0`` pays the audited
    in-kernel all-gather). ``out_split=0`` (default) returns the
    row-split result with zero tail collectives; ``out_split=None``
    returns it replicated through the audited all-reduce tail — the form
    the iterative solvers consume. ``reduce`` selects the per-row
    combiner (``'sum'`` | ``'min'`` | ``'max'``; extremes always move
    exact) and ``pattern=True`` ignores the stored values (structure-only
    propagation, e.g. :func:`heat_tpu.graph.connected_components`).
    ``precision`` overrides ``HEAT_TPU_SPARSE_SPMV_PREC`` for the float
    value tails. Rows with no stored elements yield the reduction
    identity (0 for sum, ±dtype-max for min/max)."""
    return _dispatch_sparse_dense(
        "spmv", A, x, out_split, precision, reduce, pattern, audit
    )


def spmm(
    A: SparseDNDarray,
    X: DNDarray,
    *,
    out_split: Optional[int] = 0,
    precision: Optional[str] = None,
    audit: bool = False,
) -> DNDarray:
    """Sparse × dense matrix product ``A @ X`` (site ``sparse.spmm``) —
    :func:`spmv` semantics over a ``(n, k)`` dense operand (replicated or
    row-split), result ``(m, k)`` row-split (default) or replicated via
    the audited all-reduce tail."""
    return _dispatch_sparse_dense(
        "spmm", A, X, out_split, precision, "sum", False, audit
    )


# -- solver operator hook ------------------------------------------------------


def make_solver_matvec(comm: MeshCommunication, wire: str):
    """The traceable matvec the iterative solvers embed
    (``SparseDNDarray._matvec_spec``): replicated logical ``(n,)`` in,
    replicated logical ``(n,)`` out, CSR leaves as program arguments —
    so a Lanczos/CG program over a sparse operator carries ONE cache
    signature and its per-iteration matvec is the same shard-local
    contraction + all-reduce tail as the standalone ``sparse.spmv``
    program."""
    e_spec = comm.spec(0, 1)
    rep = comm.spec(None, 1)
    p = comm.size

    def matvec(leaves, x, n):
        ip, ix, vals = leaves

        def body(ipl, ixl, vl, xl):
            y = _local_contract(ipl, ixl, vl, xl, "sum", False)
            r = ipl.shape[0] - 1
            yg = jnp.zeros((r * p,), dtype=y.dtype)
            yg = jax.lax.dynamic_update_slice(yg, y, (comm.axis_index() * r,))
            return _combine_replicated(comm, yg, wire, "sum")

        y = jax.shard_map(
            body, mesh=comm.mesh, in_specs=(e_spec, e_spec, e_spec, rep),
            out_specs=rep,
        )(ip, ix, vals, x)
        return y[:n]

    return matvec


# -- densify -------------------------------------------------------------------


def to_dense(A: SparseDNDarray) -> DNDarray:
    """Materialize the dense row-split :class:`DNDarray` (one cached
    scatter program, site ``sparse.to_dense``; duplicate coordinates —
    which the constructors reject — would sum)."""
    if not isinstance(A, SparseDNDarray):
        raise TypeError(f"expected a SparseDNDarray, got {type(A)}")
    comm = A.comm
    m, n = A.shape
    e_spec = comm.spec(0, 1)

    def build():
        def body(ip, ix, vals):
            rows = _slot_rows(ip, ix.shape[0])
            r = ip.shape[0] - 1
            dense = jnp.zeros((r, n), dtype=vals.dtype)
            return dense.at[rows, ix].add(vals, mode="drop")

        def call(ip, ix, vals):
            return jax.shard_map(
                body, mesh=comm.mesh, in_specs=(e_spec, e_spec, e_spec),
                out_specs=comm.spec(0, 2),
            )(ip, ix, vals)

        return call

    prog = program_cache.cached_program(
        "sparse.to_dense", (n, A.dtype.char()), build, comm=comm
    )
    out = prog(A.indptr, A.indices, A.values)
    _record("to_dense", nnz=A.nnz, rows=m, cols=n)
    return DNDarray(out, (m, n), A.dtype, 0, A.device, comm, True)


# -- transpose (the all-to-all-bearing op) -------------------------------------


def _transpose_stage_build(comm: MeshCommunication, R: int, r_new: int):
    """One bounded-memory transpose stage: bucket this stage's element
    slice by destination shard (the owner of its column under the
    ceil rule), exchange worst-case-sized slabs with ONE all-to-all per
    payload (packed int64 keys = index data, values), and report the
    per-shard received tallies. ``R`` (the packed-key row base) and
    ``r_new`` (destination rows per shard) ride the program key."""
    e2_spec = comm.spec(0, 2)
    p = comm.size

    def body(ip, ixc, vc, k0):
        ixc, vc = ixc[0], vc[0]
        chunk = ixc.shape[0]
        slots = k0 + jnp.arange(chunk, dtype=ip.dtype)
        row_local = jnp.searchsorted(ip, slots, side="right") - 1
        valid = slots < ip[-1]
        r = ip.shape[0] - 1
        row_g = comm.axis_index() * r + row_local
        key = jnp.where(
            valid,
            ixc.astype(jnp.int64) * R + row_g.astype(jnp.int64),
            jnp.asarray(_SENTINEL),
        )
        dest = jnp.where(valid, ixc // r_new, p).astype(jnp.int32)
        order = jnp.argsort(dest)
        key_s, v_s, dest_s = key[order], vc[order], dest[order]
        start = jnp.searchsorted(
            dest_s, jnp.arange(p + 1, dtype=dest_s.dtype), side="left"
        )
        pos = jnp.arange(chunk, dtype=jnp.int32) - start[dest_s]
        flat = dest_s * chunk + pos  # dest p (pad) lands out of range
        send_k = (
            jnp.full((p * chunk,), _SENTINEL, dtype=jnp.int64)
            .at[flat].set(key_s, mode="drop").reshape(p, chunk)
        )
        send_v = (
            jnp.zeros((p * chunk,), dtype=vc.dtype)
            .at[flat].set(v_s, mode="drop").reshape(p, chunk)
        )
        if p > 1:
            # index-carrying payload: exactness pinned regardless of any
            # global wire knob (int64 would move exact anyway — the pin
            # makes the contract lint-visible)
            rk = comm.all_to_all(send_k, 0, 0, precision="off")
            rv = comm.all_to_all(send_v, 0, 0, precision="off")
        else:
            rk, rv = send_k, send_v
        rk, rv = rk.reshape(-1), rv.reshape(-1)
        cnt = jnp.sum(rk != _SENTINEL).astype(jnp.int32)
        return rk, rv, cnt[None]

    def call(ip, ixc, vc, k0):
        return jax.shard_map(
            body, mesh=comm.mesh,
            in_specs=(comm.spec(0, 1), e2_spec, e2_spec, comm.spec(None, 0)),
            out_specs=(comm.spec(0, 1), comm.spec(0, 1), comm.spec(0, 1)),
        )(ip, ixc, vc, k0)

    return call


def _transpose_build_build(
    comm: MeshCommunication, R: int, r_new: int, new_cap: int, n_stages: int
):
    """The compaction stage: merge every exchange stage's received slab,
    sort by packed key (destination CSR order — sentinels sink to the
    tail), and emit the transposed shard CSR directly as sharded
    buffers. Shard-local; no collectives."""
    e_spec = comm.spec(0, 1)

    def body(*arrs):
        ks = jnp.concatenate(arrs[:n_stages])
        vs = jnp.concatenate(arrs[n_stages:])
        order = jnp.argsort(ks)
        k_s = ks[order][:new_cap]
        v_s = vs[order][:new_cap]
        valid = k_s != _SENTINEL
        col = k_s // R          # destination (transposed) global row
        row = k_s % R           # destination column = source row
        local_row = col - (comm.axis_index() * r_new).astype(col.dtype)
        new_ip = jnp.searchsorted(
            local_row, jnp.arange(r_new + 1, dtype=local_row.dtype),
            side="left",
        ).astype(jnp.int32)
        new_ix = jnp.where(valid, row, 0).astype(jnp.int32)
        new_v = jnp.where(valid, v_s, jnp.zeros((), dtype=v_s.dtype))
        return new_ip, new_ix, new_v

    def call(*arrs):
        return jax.shard_map(
            body, mesh=comm.mesh, in_specs=(e_spec,) * (2 * n_stages),
            out_specs=(e_spec, e_spec, e_spec),
        )(*arrs)

    return call


def transpose(
    A: SparseDNDarray, *, audit: bool = False, slab: Optional[int] = None,
) -> SparseDNDarray:
    """``A.T`` as a planned slab exchange (sites ``sparse.transpose_a2a``
    + ``sparse.transpose_build``). With ``HEAT_TPU_HBM_BUDGET`` set the
    capacity axis decomposes into stages whose worst-case ``(p, slab)``
    send/receive slabs fit :func:`memory_guard.temp_budget` — the same
    bounded-memory discipline the dense relayout planner applies
    (arXiv:2112.01075); without a budget one monolithic stage runs. Each
    stage's all-to-alls are priced by
    :func:`~heat_tpu.telemetry.collectives.sparse_transpose_cost` and
    auditable per stage. ``slab`` overrides the planned stage width
    (testing/tuning hook — the budget arithmetic normally picks it)."""
    if not isinstance(A, SparseDNDarray):
        raise TypeError(f"expected a SparseDNDarray, got {type(A)}")
    comm = A.comm
    p = comm.size
    m, n = A.shape
    cap = A.capacity
    item = A.dtype.byte_size()
    R = comm.padded_size(m)
    r_new = comm.chunk_size(n)

    if slab is not None:
        slab = max(1, min(int(slab), cap))
    elif memory_guard.budget_bytes() is None:
        slab = cap
    else:
        # per-device working set of one stage: send + receive slabs of
        # (p, slab) for the 8-byte key and the value payload, plus the
        # sort scratch — bounded by the shared temp budget (budget/4,
        # the cdist row-blocking rule)
        per_elem = 3 * p * (8 + item)
        slab = max(1, min(cap, memory_guard.temp_budget() // per_elem))
    n_stages = max(1, math.ceil(cap / slab))

    cost, fields, do_audit = telemetry.op_cost(
        telemetry.collectives.sparse_transpose_cost, slab, item, p, n_stages,
        audit=audit,
    )

    ix2 = A.indices.reshape(p, cap)
    v2 = A.values.reshape(p, cap)
    stage_keys = []
    stage_vals = []
    stage_shapes = []
    counts_total = np.zeros(p, dtype=np.int64)
    with telemetry.span(
        "sparse.transpose", gshape=[m, n], nnz=A.nnz, mesh=p,
        stages=n_stages, slab=slab, **fields,
    ) as sp:
        for k0 in range(0, cap, slab):
            chunk = min(slab, cap - k0)
            prog = program_cache.cached_program(
                "sparse.transpose_a2a", (R, r_new, A.dtype.char()),
                lambda: _transpose_stage_build(comm, R, r_new),
                comm=comm,
            )
            args = (
                A.indptr, ix2[:, k0:k0 + chunk], v2[:, k0:k0 + chunk],
                jnp.asarray(k0, dtype=jnp.int32),
            )
            if do_audit:
                telemetry.hlo.audit_call(
                    "sparse.transpose_a2a",
                    lambda: (prog, args),
                    predicted=telemetry.collectives.sparse_transpose_cost(
                        chunk, item, p, 1
                    ),
                    key=program_cache.program_key(
                        "sparse.transpose_a2a",
                        (R, r_new, A.dtype.char(), chunk), comm=comm,
                    ),
                    fields={"mesh": p, "stage_of": n_stages},
                )
            rk, rv, cnt = prog(*args)
            stage_keys.append(rk)
            stage_vals.append(rv)
            stage_shapes.append(chunk)
            counts_total += np.asarray(cnt, dtype=np.int64)
        new_cap = max(1, int(counts_total.max()))
        build_prog = program_cache.cached_program(
            "sparse.transpose_build",
            (R, r_new, new_cap, tuple(stage_shapes), A.dtype.char()),
            lambda: _transpose_build_build(comm, R, r_new, new_cap,
                                           len(stage_keys)),
            comm=comm,
        )
        new_ip, new_ix, new_v = build_prog(*stage_keys, *stage_vals)
        sp.output(new_v)
    _record(
        "transpose", nnz=A.nnz, rows=m, cols=n, stages=n_stages, slab=slab,
        **({"bytes": cost.bytes * cost.steps} if cost is not None else {}),
    )
    return SparseDNDarray.from_shard_arrays(
        new_ip, new_ix, new_v, (n, m), counts_total,
        device=A.device, comm=comm, dtype=A.dtype,
    )


# -- constructors --------------------------------------------------------------


def _from_host_coo(
    rows: np.ndarray,
    cols: np.ndarray,
    vals: np.ndarray,
    shape: Tuple[int, int],
    comm: MeshCommunication,
    device,
    dtype=None,
) -> SparseDNDarray:
    """Pack sorted host COO triplets into the sharded CSR layout (the
    constructor finishing pass — see the module docstring for why this
    is a host path)."""
    m, n = (int(s) for s in shape)
    p = comm.size
    r = comm.chunk_size(m)
    rows = np.asarray(rows, dtype=np.int64)
    cols = np.asarray(cols, dtype=np.int64)
    vals = np.asarray(vals)
    if rows.size:
        if rows.min(initial=0) < 0 or rows.max(initial=0) >= m:
            raise ValueError(f"row indices must lie in [0, {m})")
        if cols.min(initial=0) < 0 or cols.max(initial=0) >= n:
            raise ValueError(f"column indices must lie in [0, {n})")
        packed = rows * n + cols
        if (np.diff(packed) <= 0).any():
            raise ValueError(
                "COO triplets must be sorted by (row, col) and free of "
                "duplicate coordinates"
            )
    bounds = np.searchsorted(rows, np.arange(p + 1) * r)
    counts = np.diff(bounds)
    cap = max(1, int(counts.max(initial=0)))
    ip = np.zeros((p, r + 1), dtype=np.int32)
    ix = np.zeros((p, cap), dtype=np.int32)
    v = np.zeros((p, cap), dtype=vals.dtype)
    for s in range(p):
        lo, hi = int(bounds[s]), int(bounds[s + 1])
        c = hi - lo
        ip[s] = np.searchsorted(
            rows[lo:hi], s * r + np.arange(r + 1)
        ).astype(np.int32)
        ix[s, :c] = cols[lo:hi]
        v[s, :c] = vals[lo:hi]
    return SparseDNDarray._from_host_csr_shards(
        ip, ix, v, (m, n), counts, device=device, comm=comm, dtype=dtype,
    )


def csr_from_dense(
    x,
    *,
    threshold: float = 0.0,
    keep: str = "nonzero",
    include_diagonal: bool = False,
    comm: Optional[MeshCommunication] = None,
    device=None,
) -> SparseDNDarray:
    """Compact a dense matrix into a :class:`SparseDNDarray`.

    ``keep`` selects the thresholding rule — ``'nonzero'`` (entries with
    ``|v| > threshold``, default 0), ``'above'`` (``v > threshold``) or
    ``'below'`` (``v < threshold``): the eNeighbour boundary semantics
    of :class:`heat_tpu.graph.Laplacian`. ``include_diagonal`` forces an
    explicit diagonal slot per row on square inputs (entries that fail
    the rule store 0) so structure-preserving transforms — the Laplacian
    ``I − D^{-1/2} A D^{-1/2}`` value rewrite — never need a structural
    insert. Reads the dense input to host once (a constructor, not a
    steady-state path; the memory-bounded construction route is the
    chunked Laplacian builder, which never materializes the full dense
    matrix)."""
    if keep not in ("nonzero", "above", "below"):
        raise ValueError(
            f"keep must be 'nonzero'/'above'/'below', got {keep!r}"
        )
    if isinstance(x, DNDarray):
        comm = x.comm if comm is None else comm
        device = x.device if device is None else device
        host = x.numpy()
        dtype = x.dtype
    else:
        host = np.asarray(x)
        dtype = None
    comm = sanitize_comm(comm)
    device = device if device is not None else get_device()
    if host.ndim != 2:
        raise ValueError(f"expected a 2-D matrix, got {host.ndim}-D")
    if keep == "above":
        rule = host > threshold
    elif keep == "below":
        rule = host < threshold
    else:
        rule = np.abs(host) > threshold
    mask = rule
    if include_diagonal:
        if host.shape[0] != host.shape[1]:
            raise ValueError("include_diagonal requires a square matrix")
        # forced diagonal slots are STRUCTURAL: entries failing the keep
        # rule store 0 (the documented contract) — values come from the
        # rule mask, not the structure mask
        mask = rule.copy()
        np.fill_diagonal(mask, True)
    rows, cols = np.nonzero(mask)
    vals = np.where(rule, host, 0)[rows, cols]
    out = _from_host_coo(
        rows, cols, vals, host.shape, comm, device, dtype=dtype
    )
    _record(
        "from_dense", nnz=out.nnz, rows=host.shape[0], cols=host.shape[1],
        keep=keep,
    )
    return out


def csr_from_coo(
    rows,
    cols,
    values,
    shape: Tuple[int, int],
    *,
    comm: Optional[MeshCommunication] = None,
    device=None,
) -> SparseDNDarray:
    """Build a :class:`SparseDNDarray` from COO triplets.

    DNDarray inputs (any split) route the ordering through the
    **distributed sort machinery** (``manipulations.sort``'s odd-even
    merge network) over packed ``row·n + col`` int64 keys — the
    device-side heavy lifting — with a host finishing pass that gathers
    the sorted permutation and packs the per-shard CSR blocks. Host
    array inputs lexsort locally. Duplicate coordinates are rejected."""
    m, n = (int(s) for s in shape)
    is_dnd = isinstance(rows, DNDarray)
    if is_dnd:
        if not (isinstance(cols, DNDarray) and isinstance(values, DNDarray)):
            raise TypeError(
                "csr_from_coo: rows/cols/values must all be DNDarrays "
                "(or all host arrays)"
            )
        comm = rows.comm if comm is None else comm
        device = rows.device if device is None else device
        if not (rows.shape == cols.shape == values.shape) or rows.ndim != 1:
            raise ValueError(
                f"csr_from_coo: triplets must be matching 1-D vectors, got "
                f"{rows.shape}/{cols.shape}/{values.shape}"
            )
        from ..core import manipulations

        packed = rows.astype(types.int64) * n + cols.astype(types.int64)
        sorted_keys, order = manipulations.sort(packed)
        ks = sorted_keys.numpy()
        vh = values.numpy()[order.numpy()]
        rh, ch = ks // n, ks % n
        sorted_via = "distributed-sort"
    else:
        rh = np.asarray(rows, dtype=np.int64)
        ch = np.asarray(cols, dtype=np.int64)
        vh = np.asarray(values)
        order = np.lexsort((ch, rh))
        rh, ch, vh = rh[order], ch[order], vh[order]
        sorted_via = "lexsort"
    comm = sanitize_comm(comm)
    device = device if device is not None else get_device()
    out = _from_host_coo(rh, ch, vh, (m, n), comm, device)
    _record("from_coo", nnz=out.nnz, rows=m, cols=n, sorted_via=sorted_via)
    return out
