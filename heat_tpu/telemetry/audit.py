"""``python -m heat_tpu.telemetry.audit <expr>`` — audit an expression.

Evaluates a Python expression with ``ht`` (heat_tpu), ``jnp``, ``np`` and
``jax`` in scope, with telemetry recording and the HLO collective auditor
globally enabled; prints one JSON report of every audit the expression's
instrumented ops recorded (emitted collectives, wire bytes, and the drift
verdict against the analytic cost model). Exit status 1 when any drift
was flagged — or when NO audit was recorded at all (a 1-device mesh or an
expression that never hits an instrumented op verifies nothing) —
greppable and CI-able.

Examples::

    python -m heat_tpu.telemetry.audit --mesh 8 \\
        "ht.resplit(ht.random.randn(256, 64, split=0), 1)"
    python -m heat_tpu.telemetry.audit --mesh 4 --trace /tmp/trace.json \\
        "ht.linalg.qr(ht.random.randn(512, 32, split=0))"

``--trace`` additionally exports the whole telemetry event stream as
Chrome-trace JSON (see docs/OBSERVABILITY.md, "Load the trace in
Perfetto").
"""

from __future__ import annotations

import argparse
import json
import sys


def main(argv=None) -> int:
    p = argparse.ArgumentParser(
        prog="python -m heat_tpu.telemetry.audit",
        description="Lower, compile and audit the XLA collectives of an "
                    "expression's instrumented ops (resplit, qr, cdist, ...), "
                    "diffing emitted vs analytically predicted communication.",
    )
    p.add_argument(
        "expr",
        help="Python expression evaluated with `ht` (heat_tpu), `jnp`, `np` "
             "and `jax` in scope, e.g. "
             "\"ht.resplit(ht.random.randn(256, 64, split=0), 1)\"",
    )
    p.add_argument("--mesh", type=int, default=0,
                   help="force an n-device virtual CPU mesh (0 = attached "
                        "platform as-is)")
    p.add_argument("--trace", type=str, default=None,
                   help="also export the telemetry event stream as "
                        "Chrome-trace JSON to this path")
    p.add_argument("--tolerance", type=float, default=None,
                   help="relative byte-drift tolerance (default: "
                        "HEAT_TPU_HLO_TOLERANCE or 0.1)")
    args = p.parse_args(argv)

    if args.mesh:
        # shared with benchmarks/_harness.bootstrap — must run before the
        # first backend use
        from ..utils.backend_probe import force_virtual_cpu_mesh

        force_virtual_cpu_mesh(args.mesh)

    import jax
    import jax.numpy as jnp
    import numpy as np

    import heat_tpu as ht
    from heat_tpu import telemetry
    from heat_tpu.telemetry import hlo

    if args.tolerance is not None:
        hlo.DEFAULT_TOLERANCE = args.tolerance
    if not telemetry.enabled():
        telemetry.enable()
    hlo.enable_audit()
    hlo.clear()

    result = eval(args.expr, {"ht": ht, "jnp": jnp, "np": np, "jax": jax})
    try:
        jax.block_until_ready(getattr(result, "larray", result))
    except Exception:
        pass  # host-side results (floats, tuples of DNDarrays, ...) are fine

    records = hlo.recent()
    drift = sum(len(r.report.drifts) for r in records if r.report is not None)
    # zero audits is a failure, not a pass: it means the expression never
    # reached an instrumented distributed op (1-device mesh, wrong expr) —
    # "verified" must mean something was actually verified
    out = {
        "expr": args.expr,
        "devices": jax.device_count(),
        "audits": [r.summary() for r in records],
        "n_audits": len(records),
        "drift": drift,
        "ok": drift == 0 and len(records) > 0,
    }
    if not records:
        out["error"] = (
            "no instrumented op was audited — distributed collectives need "
            "a >1-device mesh (pass --mesh N) and an expression that runs "
            "resplit/qr/cdist on split arrays"
        )
    if args.trace:
        telemetry.export_trace(args.trace)
        out["trace"] = args.trace
    print(json.dumps(out, indent=2, default=str))
    return 0 if out["ok"] else 1


if __name__ == "__main__":
    sys.exit(main())
