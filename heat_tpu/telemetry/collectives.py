"""Analytic collective cost model — bytes on the wire per relayout/kernel.

The reference framework moves every byte through an explicit MPI call, so
communication volume is readable off the source (reference
heat/core/communication.py:120-1864). Here XLA emits the collectives from
sharding annotations and the hand-scheduled `shard_map` kernels, so the
volume must be *derived* from the layout contract instead: given a logical
global shape, an element size, the old/new split axes and the mesh size,
the rules below name the collective XLA materializes and count its wire
bytes. The same arithmetic is what the redistribution literature optimizes
(arXiv:2112.01075 §2 counts all-to-all volume exactly this way).

Conventions
-----------
* Volumes are **total bytes crossing links, summed over all devices** —
  the quantity a bisection-bandwidth model divides by link count.
* Volumes are computed on the **logical** element count; the tail-pad
  rounds each shard up to ``ceil(n/p)`` in flight, so the physical number
  is within one shard-row of these figures (exact when the split dim is
  divisible by the mesh size — the configuration the tests pin).
* A replicated→split relayout is a local slice (each device already holds
  every element), hence zero wire bytes.

This module is import-light (numpy only) so instrumentation call sites can
use it without pulling in the array machinery.
"""

from __future__ import annotations

import math
from dataclasses import dataclass
from typing import Dict, Optional, Sequence

__all__ = [
    "CollectiveCost",
    "DEFAULT_WIRE_BLOCK",
    "DEFAULT_DCN_PREMIUM",
    "compression_factor",
    "weighted_wire",
    "relayout_cost",
    "relayout_chunk_cost",
    "a2a_kernel_cost",
    "ring_cdist_cost",
    "tsqr_cost",
    "gram_ring_cost",
    "fusion_reduce_cost",
    "allreduce_cost",
    "reduce_scatter_cost",
    "hierarchical_allreduce_cost",
    "hierarchical_reduce_scatter_cost",
    "hierarchical_allgather_cost",
    "hierarchical_a2a_cost",
    "fsdp_gather_cost",
    "fsdp_scatter_cost",
    "ring_attention_cost",
    "ulysses_attention_cost",
    "pipeline_cost",
    "pipeline_hop_cost",
    "spmv_cost",
    "spmm_cost",
    "sparse_transpose_cost",
]

# Blockwise collective-compression scale granularity (ISSUE 9): one f32
# scale per this many payload elements. Kept here (the import-light leaf
# module) so the cost model and heat_tpu.core.collective_prec share one
# default without a dependency cycle.
DEFAULT_WIRE_BLOCK = 128


# Default ICI-vs-DCN byte premium. The registered knob HEAT_TPU_DCN_PREMIUM
# carries the same value; kept here too so this module stays usable as the
# import-light leaf it is documented to be.
DEFAULT_DCN_PREMIUM = 8.0


@dataclass(frozen=True)
class CollectiveCost:
    """One collective's analytic cost.

    kind : the collective XLA/shard_map emits ("all-gather", "all-to-all",
        "ppermute-ring", "local-slice", "none", or a "+"-joined compound).
    bytes : total wire bytes summed over devices (see module conventions).
    steps : number of sequential communication rounds (1 for one-shot
        collectives, p for a p-hop ring).
    dcn_bytes : the portion of ``bytes`` that rides the slow cross-node
        (DCN) tier of a 2-level topology (ISSUE 15). The tier assignment
        follows the emitted replica-group structure: an op whose groups
        stay inside one node is ICI; an op whose groups span nodes is
        DCN. Flat lowerings on a non-trivial topology are therefore
        all-DCN (their single group spans every node); tiered lowerings
        charge only the cross-node stage here. 0 on 1-level meshes.
    """

    kind: str
    bytes: int
    steps: int = 1
    dcn_bytes: int = 0

    def as_fields(self) -> Dict[str, object]:
        """Span/event field dict (`collective=`, `bytes=`, `steps=`)."""
        out = {"collective": self.kind, "bytes": self.bytes, "steps": self.steps}
        if self.dcn_bytes:
            out["dcn_bytes"] = self.dcn_bytes
        return out


def weighted_wire(cost: "CollectiveCost", premium: Optional[float] = None) -> float:
    """Topology-priced wire figure: ICI bytes at 1x plus DCN bytes at the
    ``premium`` multiplier (default: the ``HEAT_TPU_DCN_PREMIUM`` knob).
    This is the scalar the relayout planner and the autotuner's analytic
    stage compare when picking tiered vs flat per program signature — on
    a 1-level mesh (``dcn_bytes == 0``) it degenerates to plain bytes."""
    if premium is None:
        try:
            from heat_tpu import _knobs as _k

            premium = _k.get("HEAT_TPU_DCN_PREMIUM")
        except Exception:  # registry unavailable: price flat
            premium = DEFAULT_DCN_PREMIUM
        if premium is None:
            premium = DEFAULT_DCN_PREMIUM
    local_bytes = int(cost.bytes) - int(cost.dcn_bytes)
    return float(local_bytes) + float(premium) * float(cost.dcn_bytes)


def _numel(gshape: Sequence[int]) -> int:
    n = 1
    for s in gshape:
        n *= int(s)
    return n


def compression_factor(
    itemsize: int, precision: str, block: int = DEFAULT_WIRE_BLOCK
) -> float:
    """Bytes-on-wire per logical byte for one compressed payload
    (``HEAT_TPU_COLLECTIVE_PREC``, ISSUE 9): ``off`` 1.0; ``bf16`` a
    2-byte wire element; ``int8`` a 1-byte wire element; ``blockwise``
    int8 plus one bf16 scale per ``block`` elements. Never above 1.0 —
    a payload narrower than the wire dtype moves as-is."""
    itemsize = int(itemsize)
    if precision == "bf16":
        return min(1.0, 2.0 / itemsize)
    if precision == "int8":
        return min(1.0, 1.0 / itemsize)
    if precision == "blockwise":
        return min(1.0, (1.0 + 2.0 / int(block)) / itemsize)
    return 1.0


# The scalar max all-reduce a per-tensor GSPMD quantization pays to learn
# the global max-abs: one f32 scalar, ring all-reduce model.
def _amax_allreduce_bytes(nproc: int) -> int:
    return 2 * 4 * (nproc - 1)


def _gspmd_blockwise(gshape: Sequence[int], old_split, block: int):
    """Mirror of collective_prec's GSPMD blockwise applicability + segment
    rule: blocks along the last axis (must exist and be unsharded), even
    ``block``-sized segments only when they divide the axis, else one
    whole-row segment. Returns (applicable, n_scale_elements)."""
    ndim = len(gshape)
    if ndim < 2 or old_split == ndim - 1 or int(gshape[-1]) <= 0:
        return False, 0
    last = int(gshape[-1])
    nb = last // block if (last >= block and last % block == 0) else 1
    return True, (_numel(gshape) // last) * nb


def relayout_cost(
    gshape: Sequence[int],
    itemsize: int,
    old_split: Optional[int],
    new_split: Optional[int],
    nproc: int,
    precision: str = "off",
    block: int = DEFAULT_WIRE_BLOCK,
) -> CollectiveCost:
    """Cost of the canonical relayout (`DNDarray._relayout` /
    `manipulations.resplit`) from ``old_split`` to ``new_split``.

    * split → same split, or any relayout on a 1-position mesh: no comm;
    * split s → replicated: **all-gather** — every device receives the
      (p-1)/p of the array it does not own: ``(p-1) · B`` total;
    * replicated → split s: **local slice** — zero wire bytes;
    * split s → split t (s ≠ t): **all-to-all** — each device keeps the
      1/p of its shard destined for itself and sends the rest:
      ``B · (p-1)/p`` total (the analytic all-to-all volume).

    ``precision`` (ISSUE 9, ``HEAT_TPU_COLLECTIVE_PREC``) prices the
    compressed-wire program instead: the payload moves at the compressed
    dtype, and the scale machinery's own (small) collectives are named in
    the compound ``kind`` — ``+all-reduce`` for the per-tensor max-abs
    scalar (``int8``, and ``blockwise`` degraded on shapes whose block
    axis is the sharded one), ``+all-gather`` for the replicated
    blockwise scales. Mirrors ``collective_prec.gspmd_reshard`` exactly
    so the HLO audit of a compressed relayout stays zero-drift.
    """
    b = _numel(gshape) * int(itemsize)
    if nproc <= 1 or old_split == new_split:
        return CollectiveCost("none", 0)
    if old_split is None:
        return CollectiveCost("local-slice", 0)
    kind = "all-gather" if new_split is None else "all-to-all"

    def payload(nbytes: int) -> int:
        if kind == "all-gather":
            return nbytes * (nproc - 1)
        return (nbytes * (nproc - 1)) // nproc

    if precision == "off" or int(itemsize) <= 1:
        return CollectiveCost(kind, payload(b))
    if precision == "bf16":
        wire = min(int(itemsize), 2)
        return CollectiveCost(kind, payload(_numel(gshape) * wire))
    if precision == "blockwise":
        ok, n_scales = _gspmd_blockwise(gshape, old_split, block)
        if ok:
            # blockwise scales are shard-local, replicated by one small
            # all-gather (same op as the payload when the payload gathers)
            scale_bytes = n_scales * 2 * (nproc - 1)
            pk = kind if kind == "all-gather" else kind + "+all-gather"
            return CollectiveCost(pk, payload(_numel(gshape)) + scale_bytes)
        precision = "int8"  # degraded: per-tensor scale
    # int8 per-tensor: scalar max all-reduce for the global scale
    return CollectiveCost(
        kind + "+all-reduce",
        payload(_numel(gshape)) + _amax_allreduce_bytes(nproc),
    )


def relayout_chunk_cost(
    gshape: Sequence[int],
    itemsize: int,
    src_split: int,
    dst_split: int,
    width: int,
    nproc: int,
    precision: str = "off",
    block: int = DEFAULT_WIRE_BLOCK,
) -> CollectiveCost:
    """Cost of ONE stage of the planner's chunked relayout
    (:mod:`heat_tpu.core.relayout_planner`): a destination-shard-aligned
    block of ``width`` columns along ``dst_split`` lands whole on one
    destination shard, so XLA emits one **all-gather** of the block —
    every device receives the whole chunk and the owner keeps its part:
    ``chunk_phys · (p-1)`` wire bytes, where ``chunk_phys`` counts the
    source buffer's tail pad along ``src_split`` (the bytes the program
    actually moves). Summed over a plan's stages this is ``~B·(p-1)`` —
    the wire premium the bounded-memory decomposition pays vs the
    monolithic all-to-all's ``B·(p-1)/p``.

    ``precision`` (ISSUE 9): chunk stages always use per-chunk
    (per-tensor) scales — a narrow chunk's last axis would make blockwise
    scale overhead comparable to the payload — so ``int8`` and
    ``blockwise`` price identically: int8 payload plus the scalar max
    all-reduce."""
    if nproc <= 1:
        return CollectiveCost("none", 0)
    other = 1
    for d, s in enumerate(gshape):
        if d == dst_split:
            continue
        s = int(s)
        if d == src_split:
            s = math.ceil(s / nproc) * nproc
        other *= s
    elems = other * int(width)
    if precision == "bf16" and int(itemsize) > 2:
        return CollectiveCost("all-gather", elems * 2 * (nproc - 1))
    if precision in ("int8", "blockwise") and int(itemsize) > 1:
        return CollectiveCost(
            "all-gather+all-reduce",
            elems * (nproc - 1) + _amax_allreduce_bytes(nproc),
        )
    return CollectiveCost("all-gather", elems * int(itemsize) * (nproc - 1))


def a2a_kernel_cost(
    phys_gshape: Sequence[int],
    itemsize: int,
    nproc: int,
    precision: str = "off",
    block: int = DEFAULT_WIRE_BLOCK,
) -> CollectiveCost:
    """Cost of the explicit shard_map all-to-all kernel
    (core/relayout_planner ``alltoall`` plans, via the
    ``MeshCommunication.all_to_all`` wrapper) on the PHYSICAL
    (pad-inclusive) shape. Uncompressed it is the plain all-to-all
    volume; compressed, each of the ``p`` outgoing slabs per device
    (``m = numel/p²`` elements) is quantized independently — per-slab
    scale for ``int8``, flat blocks of ``min(block, m)`` elements
    zero-padded to whole blocks for ``blockwise`` — and the bf16 scales
    ride their own (tiny) all-to-all. Mirrors
    ``collective_prec.all_to_all`` byte-for-byte."""
    numel = _numel(phys_gshape)
    if nproc <= 1:
        return CollectiveCost("none", 0)
    if precision == "off" or int(itemsize) <= 1:
        return CollectiveCost(
            "all-to-all", (numel * int(itemsize) * (nproc - 1)) // nproc
        )
    if precision == "bf16":
        wire = min(int(itemsize), 2)
        return CollectiveCost(
            "all-to-all", (numel * wire * (nproc - 1)) // nproc
        )
    m = numel // (nproc * nproc)
    if precision == "int8":
        nb, seg = 1, m
    else:
        seg = max(1, min(int(block), m))
        nb = max(1, -(-m // seg))
    per_dev = nproc * (nb * seg + nb * 2)  # padded int8 slabs + bf16 scales
    return CollectiveCost("all-to-all", per_dev * (nproc - 1))


def ring_cdist_cost(
    n: int, k: int, itemsize: int, nproc: int, hops: Optional[int] = None,
    precision: str = "off", block: int = DEFAULT_WIRE_BLOCK,
) -> CollectiveCost:
    """Cost of the ppermute ring distance kernel
    (:func:`heat_tpu.spatial.distance._ring_dist`): the row-split ``y``
    block circulates one hop per step, every device sending its
    ``ceil(n/p)·k`` block each hop. Only ``y`` moves — the stationary x
    rows never touch the wire, so the volume is independent of the x-row
    count. ``hops`` defaults to ``p`` (the serial kernel's `fori_loop`
    permutes on every iteration, including the final hop that returns
    each block home); the double-buffered overlap kernel skips that dead
    hop and passes ``hops = p - 1``.

    ``precision`` (ISSUE 9): the circulating y-block is re-quantized
    every hop, so each hop's permute moves the compressed payload plus
    its scales — per-tensor (one f32 scalar, ``int8``) or flat blocks of
    ``block`` elements zero-padded to a whole number of blocks
    (``blockwise``). Both permutes are collective-permute instructions,
    so the kind is unchanged."""
    if nproc <= 1:
        return CollectiveCost("none", 0)
    hops = nproc if hops is None else int(hops)
    elems = math.ceil(n / nproc) * int(k)
    per_hop = elems * int(itemsize)
    if precision == "bf16" and int(itemsize) > 2:
        per_hop = elems * 2
    elif precision == "int8" and int(itemsize) > 1:
        per_hop = elems + 2  # int8 payload + one bf16 scale per hop
    elif precision == "blockwise" and int(itemsize) > 1:
        seg = max(1, min(int(block), elems))  # implementation clamps too
        nb = max(1, -(-elems // seg))
        per_hop = nb * seg + nb * 2  # padded int8 blocks + bf16 scales
    return CollectiveCost("ppermute-ring", nproc * hops * per_hop, steps=hops)


def tsqr_cost(m: int, n: int, itemsize: int, nproc: int) -> CollectiveCost:
    """Cost of the TSQR kernel (:func:`heat_tpu.core.linalg.qr.qr`, row-split
    path): one in-kernel all-gather of the per-shard ``(min(chunk, n), n)``
    R factors — every device receives the ``p-1`` blocks it did not
    compute. The two GEMM stages are local."""
    if nproc <= 1:
        return CollectiveCost("none", 0)
    chunk = math.ceil(m / nproc)
    k1 = min(chunk, int(n))
    return CollectiveCost(
        "all-gather", nproc * (nproc - 1) * k1 * int(n) * int(itemsize)
    )


def gram_ring_cost(
    m: int, n: int, itemsize: int, nproc: int, hops: Optional[int] = None
) -> CollectiveCost:
    """Cost of the CholeskyQR2 ring Gram kernel
    (:func:`heat_tpu.core.linalg.qr._gram_ring`): ``hops`` ring hops of
    the stationary-transpose schedule (each device circulates its
    ``(ceil(n/p), m)`` block every hop — ``p`` hops for the serial
    kernel, ``p - 1`` for the double-buffered overlap kernel, which
    skips the final hop that only returns each block home) plus the
    final tiled all-gather of the ``(ceil(n/p), n_phys)`` row blocks of
    G."""
    if nproc <= 1:
        return CollectiveCost("none", 0)
    hops = nproc if hops is None else int(hops)
    c = math.ceil(n / nproc)
    n_phys = c * nproc
    ring = nproc * hops * c * int(m) * int(itemsize)
    gather = nproc * (nproc - 1) * c * n_phys * int(itemsize)
    return CollectiveCost("ppermute-ring+all-gather", ring + gather, steps=hops)


def fusion_reduce_cost(
    out_gshape: Sequence[int], itemsize: int, nproc: int
) -> CollectiveCost:
    """Cost of the collective tail of a fused chain+reduction program
    (core/fusion.py ``absorb_reduce``, site ``fusion_reduce``): a
    reduction crossing the split axis leaves each device holding a full
    partial result of the OUTPUT shape, combined by one all-reduce —
    ``2·B·(p-1)`` wire bytes for the reduce-scatter+broadcast lowering,
    where ``B`` is the replicated result's byte size. Reductions that keep
    the split (and 1-position meshes) move nothing."""
    if nproc <= 1:
        return CollectiveCost("none", 0)
    return CollectiveCost(
        "all-reduce", 2 * _numel(out_gshape) * int(itemsize) * (nproc - 1)
    )


def allreduce_cost(
    numel: int,
    itemsize: int,
    nproc: int,
    precision: str = "off",
    block: int = DEFAULT_WIRE_BLOCK,
) -> CollectiveCost:
    """Cost of one all-reduce of a ``numel``-element payload under
    ``HEAT_TPU_COLLECTIVE_PREC`` (ISSUE 9) — the DP gradient / DASO
    node-sync primitive:

    * ``off`` — XLA ring all-reduce, ``2·B·(p-1)``;
    * ``bf16`` — the same all-reduce on a bf16 payload;
    * ``int8``/``blockwise`` — the EQuARX two-phase form
      (``collective_prec.psum``): an all-to-all of each device's
      quantized partial (zero-padded to ``p`` chunks, blockwise also to
      whole blocks) plus an all-gather of the requantized reduced
      chunks, scales riding each phase. Mirrors the implementation
      byte-for-byte so the HLO audit stays zero-drift.
    """
    numel, itemsize = int(numel), int(itemsize)
    if nproc <= 1:
        return CollectiveCost("none", 0)
    if precision == "off" or itemsize <= 1 or (
        precision == "bf16" and itemsize <= 2
    ):
        return CollectiveCost(
            "all-reduce", 2 * numel * itemsize * (nproc - 1)
        )
    if precision == "bf16":
        return CollectiveCost("all-reduce", 2 * numel * 2 * (nproc - 1))
    chunk = -(-numel // nproc)
    if precision == "blockwise":
        blk = max(1, min(int(block), chunk))  # implementation clamps too
        chunk = -(-chunk // blk) * blk
        nb = chunk // blk
    else:
        nb = 1
    numel_p = chunk * nproc
    payload = 2 * numel_p * (nproc - 1)          # a2a phase + gather phase
    scales = 2 * 2 * nproc * nb * (nproc - 1)    # bf16 scales, both phases
    return CollectiveCost("all-to-all+all-gather", payload + scales)


def reduce_scatter_cost(
    numel: int,
    itemsize: int,
    nproc: int,
    precision: str = "off",
    block: int = DEFAULT_WIRE_BLOCK,
) -> CollectiveCost:
    """Cost of one flat ``MeshCommunication.reduce_scatter`` of a
    ``numel``-element payload (the payload is flattened and zero-padded to
    ``p`` equal chunks in flight — the physical figure counted here):

    * ``off``/narrow — ring reduce-scatter, ``B_pad · (p-1)``
      (per-participant operand ``B_pad``, the hlo.py wire model);
    * ``bf16`` — the same reduce-scatter on a bf16 payload;
    * ``int8``/``blockwise`` — the EQuARX first phase standing alone
      (``collective_prec.reduce_scatter``): an all-to-all of each
      device's quantized per-destination sub-chunks plus their scales,
      dequantize + accumulate on the receiver. Mirrors the
      implementation byte-for-byte.
    """
    numel, itemsize = int(numel), int(itemsize)
    if nproc <= 1:
        return CollectiveCost("none", 0)
    chunk = -(-numel // nproc)
    if precision == "off" or itemsize <= 1 or (
        precision == "bf16" and itemsize <= 2
    ):
        return CollectiveCost(
            "reduce-scatter", chunk * nproc * itemsize * (nproc - 1)
        )
    if precision == "bf16":
        return CollectiveCost(
            "reduce-scatter", chunk * nproc * 2 * (nproc - 1)
        )
    if precision == "blockwise":
        blk = max(1, min(int(block), chunk))
        chunk = -(-chunk // blk) * blk
        nb = chunk // blk
    else:
        nb = 1
    payload = chunk * nproc * (nproc - 1)            # int8 a2a phase
    scales = 2 * nproc * nb * (nproc - 1)            # bf16 scales alongside
    return CollectiveCost("all-to-all", payload + scales)


# -- hierarchy-aware tiered lowerings (ISSUE 15, core/topology.py) ------------
# Per-tier conventions: the in-node (ICI) tier always moves exact payloads;
# ``cross_precision`` is the wire mode of the cross-node (DCN) tier only.
# ``dcn_bytes`` carries the cross-node stage's volume so weighted_wire can
# price the DCN premium. Each function mirrors the topology.py lowering
# byte-for-byte so the HLO audit of a tiered program stays zero-drift.


def _hier_chunk(numel: int, local: int) -> int:
    """Per-device shard length of the in-node reduce-scatter: the flat
    payload zero-padded to ``local`` equal chunks."""
    return -(-int(numel) // int(local))


def hierarchical_allreduce_cost(
    numel: int,
    itemsize: int,
    node: int,
    local: int,
    cross_precision: str = "off",
    block: int = DEFAULT_WIRE_BLOCK,
) -> CollectiveCost:
    """Cost of one tiered all-reduce (``MeshCommunication.psum`` under
    ``HEAT_TPU_HIERARCHICAL=1`` on a ``node x local`` topology):

    1. **in-node reduce-scatter** (ICI, exact) of the padded flat payload
       — ``B_pad · (local-1) · node`` wire bytes, node groups;
    2. **cross-node all-reduce** (DCN) of the ``1/local``-sized shard —
       each device's cross payload is ``B_pad/local``, exactly the shard
       factor the acceptance oracle pins; ``local`` cross groups of
       ``node`` participants. ``cross_precision`` compresses THIS stage
       only (bf16 payload, or the EQuARX two-phase form per group);
    3. **in-node all-gather** (ICI, exact) of the reduced shard —
       ``B_pad · (local-1) · node``.

    Degenerate topologies (``node == 1`` or ``local == 1``) lower flat
    (:func:`allreduce_cost`) — a 1-level hierarchy IS the flat ring.
    """
    numel, itemsize = int(numel), int(itemsize)
    node, local = int(node), int(local)
    p = node * local
    if p <= 1:
        return CollectiveCost("none", 0)
    if node == 1 or local == 1:
        return allreduce_cost(numel, itemsize, p, cross_precision, block)
    chunk = _hier_chunk(numel, local)
    n_pad = chunk * local
    tier_ici = n_pad * itemsize * (local - 1) * node  # rs == ag volume
    if cross_precision in ("int8", "blockwise") and itemsize > 1:
        per_group = allreduce_cost(
            chunk, itemsize, node, cross_precision, block
        )
        cross = per_group.bytes * local
        kind = "reduce-scatter+all-to-all+all-gather"
    else:
        wire = itemsize
        if cross_precision == "bf16" and itemsize > 2:
            wire = 2
        cross = 2 * chunk * wire * (node - 1) * local
        kind = "reduce-scatter+all-reduce+all-gather"
    return CollectiveCost(
        kind, tier_ici * 2 + cross, dcn_bytes=cross
    )


def hierarchical_reduce_scatter_cost(
    numel: int,
    itemsize: int,
    node: int,
    local: int,
    cross_precision: str = "off",
    block: int = DEFAULT_WIRE_BLOCK,
) -> CollectiveCost:
    """Cost of one tiered reduce-scatter: in-node reduce-scatter (ICI,
    exact) to the ``1/local`` shard, then a cross-node reduce-scatter of
    that shard (DCN, ``cross_precision``-priced) down to the global
    ``1/p`` chunk. Degenerates to :func:`reduce_scatter_cost` on 1-level
    topologies."""
    numel, itemsize = int(numel), int(itemsize)
    node, local = int(node), int(local)
    p = node * local
    if p <= 1:
        return CollectiveCost("none", 0)
    if node == 1 or local == 1:
        return reduce_scatter_cost(numel, itemsize, p, cross_precision, block)
    # stage 1 pads to p (not just local) chunks so stage 2 scatters evenly
    chunk_p = -(-numel // p)
    n_pad = chunk_p * p
    chunk = n_pad // local
    tier_ici = n_pad * itemsize * (local - 1) * node
    per_group = reduce_scatter_cost(
        chunk, itemsize, node, cross_precision, block
    )
    cross = per_group.bytes * local
    kind = "reduce-scatter" if per_group.kind == "reduce-scatter" else (
        "reduce-scatter+" + per_group.kind
    )
    return CollectiveCost(kind, tier_ici + cross, dcn_bytes=cross)


def hierarchical_allgather_cost(
    shard_numel: int,
    itemsize: int,
    node: int,
    local: int,
    cross_precision: str = "off",
    block: int = DEFAULT_WIRE_BLOCK,
) -> CollectiveCost:
    """Cost of one tiered all-gather of a per-device ``shard_numel``
    payload: cross-node gather first (DCN — each device receives its
    ``node-1`` peer shards), then the in-node gather of the stacked
    blocks (ICI). Compressed modes quantize ONCE at the source and move
    the compressed payload through both stages (the scales ride both
    gathers), so the error bound is one quantization step — identical to
    the flat compressed gather. Exact total equals the flat
    ``p·s·(p-1)`` volume; only the tier split changes."""
    s, itemsize = int(shard_numel), int(itemsize)
    node, local = int(node), int(local)
    p = node * local
    if p <= 1:
        return CollectiveCost("none", 0)
    wire = itemsize
    scale_elems = 0
    if itemsize > 1 and cross_precision == "bf16":
        wire = min(itemsize, 2)
    elif itemsize > 1 and cross_precision == "int8":
        wire, scale_elems = 1, 1
    elif itemsize > 1 and cross_precision == "blockwise":
        seg = max(1, min(int(block), s))
        nb = max(1, -(-s // seg))
        s_padded = nb * seg
        wire, scale_elems, s = 1, nb, s_padded
    if node == 1 or local == 1:
        return CollectiveCost(
            "all-gather",
            p * (p - 1) * (s * wire + scale_elems * 2),
        )
    cross = (s * wire + scale_elems * 2) * (node - 1) * p
    ici = node * (s * wire + scale_elems * 2) * (local - 1) * p
    return CollectiveCost("all-gather", cross + ici, dcn_bytes=cross)


def hierarchical_a2a_cost(
    phys_numel: int,
    itemsize: int,
    node: int,
    local: int,
    cross_precision: str = "off",
    block: int = DEFAULT_WIRE_BLOCK,
) -> CollectiveCost:
    """Cost of one tiered all-to-all on the PHYSICAL (pad-inclusive)
    global element count: stage A exchanges destination-local slabs
    inside each node (ICI), stage B exchanges destination-node bundles
    across nodes (DCN). Total volume is ``B·((local-1)/local +
    (node-1)/node)`` — slightly above the flat ``B·(p-1)/p`` — but the
    DCN tier carries only the ``(node-1)/node`` share as ``local``-way
    aggregated transfers, which is what the premium pricing rewards.
    Compressed modes quantize per final-destination slab at the source
    (the :func:`a2a_kernel_cost` slab scheme) and move payload + scales
    through both stages."""
    numel, itemsize = int(phys_numel), int(itemsize)
    node, local = int(node), int(local)
    p = node * local
    if p <= 1:
        return CollectiveCost("none", 0)
    if node == 1 or local == 1:
        return a2a_kernel_cost((numel,), itemsize, p, cross_precision, block)
    if cross_precision == "off" or itemsize <= 1:
        total_payload = numel * itemsize
    elif cross_precision == "bf16":
        total_payload = numel * min(itemsize, 2)
    else:
        m = numel // (p * p)
        if cross_precision == "int8":
            nb, seg = 1, m
        else:
            seg = max(1, min(int(block), m))
            nb = max(1, -(-m // seg))
        total_payload = p * p * (nb * seg + nb * 2)
    ici = total_payload * (local - 1) // local
    cross = total_payload * (node - 1) // node
    return CollectiveCost("all-to-all", ici + cross, dcn_bytes=cross)


# -- FSDP weight-stream collectives (ISSUE 18, parallel/fsdp.py) --------------
# The FSDP forward all-gathers each leaf's flat 1/p chunk just-in-time and
# the backward re-scatters the weight cotangent through the gather's
# transpose. Both ride the MeshCommunication wrappers, so the tiered
# lowering (and its DCN split) and the ISSUE 9 compressed wire apply
# unchanged — these entries just price the FSDP payload convention (the
# pre-padded ``p x chunk`` flat layout of ``fsdp.flat_chunk``) so the
# per-layer HLO audit diffs against exactly the program dispatched.


def fsdp_gather_cost(
    chunk_numel: int,
    itemsize: int,
    node: int,
    local: int,
    precision: str = "off",
    block: int = DEFAULT_WIRE_BLOCK,
) -> CollectiveCost:
    """Cost of one just-in-time FSDP weight gather: every device
    contributes its ``chunk_numel``-element flat shard and receives the
    full ``p x chunk`` leaf. Flat meshes (``node == 1`` or ``local ==
    1``) emit one all-gather of ``p·s·(p-1)`` wire bytes (compressed
    modes move payload + scales, the ``collective_prec.all_gather``
    convention); 2-level topologies split the identical total across the
    DCN/ICI tiers (:func:`hierarchical_allgather_cost`), with
    ``precision`` compressing the wire payload quantized once at the
    source. ``dcn_bytes`` carries the cross-node stage for
    :func:`weighted_wire` premium pricing."""
    return hierarchical_allgather_cost(
        chunk_numel, itemsize, node, local, precision, block
    )


def fsdp_scatter_cost(
    padded_numel: int,
    itemsize: int,
    node: int,
    local: int,
    precision: str = "off",
    block: int = DEFAULT_WIRE_BLOCK,
) -> CollectiveCost:
    """Cost of the FSDP gather's transpose — the backward reduce-scatter
    of one leaf's weight cotangent: each device holds the full
    ``padded_numel``-element cotangent (the pre-padded ``p·chunk`` flat
    layout) and keeps the summed 1/p chunk it owns. Flat meshes price
    the ring reduce-scatter (quantized modes: the EQuARX first phase as
    an all-to-all, :func:`reduce_scatter_cost`); 2-level topologies the
    tiered in-node-exact / cross-node-``precision`` split
    (:func:`hierarchical_reduce_scatter_cost`)."""
    return hierarchical_reduce_scatter_cost(
        padded_numel, itemsize, node, local, precision, block
    )


# -- attention / pipeline kernels (the last unpriced collectives) -------------


def ring_attention_cost(
    b: int, t: int, h: int, d: int, itemsize: int, nproc: int
) -> CollectiveCost:
    """Cost of :func:`heat_tpu.parallel.ring_attention`: the K and V
    blocks — each ``(b, t/p, h, d)`` — circulate one ring hop per step
    for ``p`` steps (the serial fori_loop permutes on every iteration,
    including the final home hop), two collective-permutes per step.
    The stationary Q never touches the wire."""
    if nproc <= 1:
        return CollectiveCost("none", 0)
    per_hop = 2 * int(b) * (int(t) // nproc) * int(h) * int(d) * int(itemsize)
    return CollectiveCost(
        "ppermute-ring", nproc * nproc * per_hop, steps=nproc
    )


def ulysses_attention_cost(
    b: int, t: int, h: int, d: int, itemsize: int, nproc: int
) -> CollectiveCost:
    """Cost of :func:`heat_tpu.parallel.ulysses_attention`: three
    all-to-alls reshard Q/K/V sequence->heads and one reshards the
    output back — four exchanges of the full ``(b, t, h, d)`` tensor at
    the analytic all-to-all volume ``B·(p-1)/p`` each."""
    if nproc <= 1:
        return CollectiveCost("none", 0)
    full = int(b) * int(t) * int(h) * int(d) * int(itemsize)
    return CollectiveCost("all-to-all", 4 * (full * (nproc - 1)) // nproc)


def pipeline_cost(
    batch: int,
    feat_numel: int,
    itemsize: int,
    nproc: int,
    n_microbatches: int,
) -> CollectiveCost:
    """Cost of :func:`heat_tpu.parallel.pipeline_apply` (GPipe schedule):
    every one of the ``p + m - 1`` ticks permutes each stage's activation
    — a ``(batch/m, feat)`` microbatch on all ``p`` positions — one hop
    forward, then one final all-reduce both collects and replicates the
    ``(batch, feat)`` output buffer (only the last stage ever wrote it)."""
    if nproc <= 1:
        return CollectiveCost("none", 0)
    m = int(n_microbatches)
    mb_bytes = (int(batch) // m) * int(feat_numel) * int(itemsize)
    ticks = nproc + m - 1
    ring = ticks * nproc * mb_bytes
    out_bytes = int(batch) * int(feat_numel) * int(itemsize)
    # the out accumulator carries the microbatch-major (m, b/m, feat)
    # buffer on every position: a full-batch payload per participant
    allreduce = 2 * out_bytes * (nproc - 1)
    return CollectiveCost(
        "ppermute-ring+all-reduce", ring + allreduce, steps=ticks
    )


def pipeline_hop_cost(
    mb_batch: int,
    feat_numel: int,
    itemsize: int,
    nproc: int,
    stride: int = 1,
    local: Optional[int] = None,
) -> CollectiveCost:
    """Cost of ONE inter-stage pipeline hop (ISSUE 19,
    ``heat_tpu/parallel/pipeline.py`` site ``pipeline.step``): every mesh
    position ships its ``(mb_batch, feat)`` microbatch activation along
    one ``collective-permute`` pair ``i -> (i + stride) % p`` — ``p``
    pairs total, wraparound included, mirroring the emitted
    ``source_target_pairs`` byte-for-byte (the HLO auditor's
    collective-permute model is ``in_bytes x |pairs|``).

    ``stride`` is the stage-mapping hop (the in-stage group size —
    ``p/S``; the backward cotangent hop is the same permutation
    reversed, so one figure prices both directions). ``local`` is the
    MESH topology's in-node group size: pairs whose endpoints lie in
    different node groups ride the DCN tier and land in ``dcn_bytes``,
    priced at ``HEAT_TPU_DCN_PREMIUM`` by :func:`weighted_wire`. With
    the auto stage placement (stages == node groups, ``stride ==
    local``) every pair crosses — the full hop is DCN; ``local=None``
    (1-level mesh) prices zero DCN bytes. A schedule's total is
    ``n_hops x`` this figure (one fwd + one bwd permute per tick on a
    training table), which the zero-drift audit re-derives from the
    compiled program's pair lists."""
    if nproc <= 1:
        return CollectiveCost("none", 0)
    mb_bytes = int(mb_batch) * int(feat_numel) * int(itemsize)
    stride = int(stride) % int(nproc)
    cross = 0
    if local is not None and 0 < int(local) < int(nproc):
        local = int(local)
        cross = sum(
            1
            for i in range(int(nproc))
            if (i // local) != (((i + stride) % int(nproc)) // local)
        )
    return CollectiveCost(
        "ppermute-ring",
        int(nproc) * mb_bytes,
        steps=1,
        dcn_bytes=cross * mb_bytes,
    )


def spmm_cost(
    m: int,
    n: int,
    k: int,
    itemsize: int,
    nproc: int,
    x_split: Optional[int] = None,
    out_split: Optional[int] = 0,
    precision: str = "off",
) -> CollectiveCost:
    """Cost of one cached sparse × dense ``shard_map`` program
    (:func:`heat_tpu.sparse.spmm`, site ``sparse.spmm``; ``spmv`` is the
    ``k = 1`` special case). The CSR operand is row-split with
    shard-local ``indptr``/``indices``/``values`` — **index/ptr payloads
    never touch the wire** — so the only collectives are the float tails:

    * **operand gather** (``x_split == 0``): the dense ``(n, k)`` operand
      is row-split, so each shard all-gathers the other shards' physical
      chunks before the local contraction — ``p·(p−1)·ceil(n/p)·k``
      elements total (tail-pad inclusive, like :func:`tsqr_cost`).
      ``precision='bf16'`` moves the uint16 bit pattern (2-byte wire
      element, the ISSUE 9 bitcast pair).
    * **result all-reduce** (``out_split is None``): each shard scatters
      its local rows into a zero global ``(m_pad·k)`` partial and one
      ``psum`` combines them — :func:`allreduce_cost` of the *physical*
      (pad-inclusive) result under the same wire mode. A row-split
      result (``out_split == 0``) stays shard-local: zero wire bytes.

    Mirrors ``heat_tpu/sparse/ops.py`` byte-for-byte so the HLO audit of
    a sparse program stays zero-drift (the acceptance oracle of
    ISSUE 13)."""
    if nproc <= 1:
        return CollectiveCost("none", 0)
    itemsize = int(itemsize)
    wire_item = min(itemsize, 2) if precision == "bf16" else itemsize
    kinds = []
    total = 0
    if x_split == 0:
        chunk = math.ceil(n / nproc)
        kinds.append("all-gather")
        total += nproc * (nproc - 1) * chunk * int(k) * wire_item
    if out_split is None:
        m_pad = math.ceil(m / nproc) * nproc
        tail = allreduce_cost(m_pad * int(k), itemsize, nproc, precision)
        kinds.append(tail.kind)
        total += tail.bytes
    if not kinds:
        return CollectiveCost("none", 0)
    return CollectiveCost("+".join(kinds), total)


def spmv_cost(
    m: int,
    n: int,
    itemsize: int,
    nproc: int,
    x_split: Optional[int] = None,
    out_split: Optional[int] = 0,
    precision: str = "off",
) -> CollectiveCost:
    """Cost of one sparse matrix-vector product (site ``sparse.spmv``) —
    :func:`spmm_cost` with a single dense column. See there for the
    component rules (operand gather / result all-reduce)."""
    return spmm_cost(
        m, n, 1, itemsize, nproc,
        x_split=x_split, out_split=out_split, precision=precision,
    )


def sparse_transpose_cost(
    slab: int,
    itemsize: int,
    nproc: int,
    stages: int = 1,
) -> CollectiveCost:
    """Cost of ONE stage of the sparse CSR transpose
    (:func:`heat_tpu.sparse.transpose`, site ``sparse.transpose_a2a``):
    every shard routes its local elements to the shard owning their
    destination row through a static ``(p, slab)`` slab exchange — one
    **all-to-all** for the packed int64 ``(row, col)`` sort keys and one
    for the values, both pinned exact (the key payload IS index data).
    Slabs are worst-case sized (every element of a stage could target
    one destination), so each device ships ``(p−1)`` slabs of ``slab``
    elements per payload regardless of occupancy:
    ``p·(p−1)·slab·(8 + itemsize)`` wire bytes per stage. ``stages`` is
    the bounded-memory decomposition count the planner picked against
    ``HEAT_TPU_HBM_BUDGET`` (each stage is its own cached program, the
    arXiv:2112.01075 discipline dense relayout already uses); the figure
    here prices one stage — a plan's total is ``stages ×`` this, which
    the ``steps`` field records."""
    if nproc <= 1:
        return CollectiveCost("none", 0)
    per_stage = nproc * (nproc - 1) * int(slab) * (8 + int(itemsize))
    return CollectiveCost("all-to-all", per_stage, steps=int(stages))
