"""Analytic collective cost model — bytes on the wire per relayout/kernel.

The reference framework moves every byte through an explicit MPI call, so
communication volume is readable off the source (reference
heat/core/communication.py:120-1864). Here XLA emits the collectives from
sharding annotations and the hand-scheduled `shard_map` kernels, so the
volume must be *derived* from the layout contract instead: given a logical
global shape, an element size, the old/new split axes and the mesh size,
the rules below name the collective XLA materializes and count its wire
bytes. The same arithmetic is what the redistribution literature optimizes
(arXiv:2112.01075 §2 counts all-to-all volume exactly this way).

Conventions
-----------
* Volumes are **total bytes crossing links, summed over all devices** —
  the quantity a bisection-bandwidth model divides by link count.
* Volumes are computed on the **logical** element count; the tail-pad
  rounds each shard up to ``ceil(n/p)`` in flight, so the physical number
  is within one shard-row of these figures (exact when the split dim is
  divisible by the mesh size — the configuration the tests pin).
* A replicated→split relayout is a local slice (each device already holds
  every element), hence zero wire bytes.

This module is import-light (numpy only) so instrumentation call sites can
use it without pulling in the array machinery.
"""

from __future__ import annotations

import math
from dataclasses import dataclass
from typing import Dict, Optional, Sequence

__all__ = [
    "CollectiveCost",
    "relayout_cost",
    "relayout_chunk_cost",
    "ring_cdist_cost",
    "tsqr_cost",
    "gram_ring_cost",
    "fusion_reduce_cost",
]


@dataclass(frozen=True)
class CollectiveCost:
    """One collective's analytic cost.

    kind : the collective XLA/shard_map emits ("all-gather", "all-to-all",
        "ppermute-ring", "local-slice", "none", or a "+"-joined compound).
    bytes : total wire bytes summed over devices (see module conventions).
    steps : number of sequential communication rounds (1 for one-shot
        collectives, p for a p-hop ring).
    """

    kind: str
    bytes: int
    steps: int = 1

    def as_fields(self) -> Dict[str, object]:
        """Span/event field dict (`collective=`, `bytes=`, `steps=`)."""
        return {"collective": self.kind, "bytes": self.bytes, "steps": self.steps}


def _numel(gshape: Sequence[int]) -> int:
    n = 1
    for s in gshape:
        n *= int(s)
    return n


def relayout_cost(
    gshape: Sequence[int],
    itemsize: int,
    old_split: Optional[int],
    new_split: Optional[int],
    nproc: int,
) -> CollectiveCost:
    """Cost of the canonical relayout (`DNDarray._relayout` /
    `manipulations.resplit`) from ``old_split`` to ``new_split``.

    * split → same split, or any relayout on a 1-position mesh: no comm;
    * split s → replicated: **all-gather** — every device receives the
      (p-1)/p of the array it does not own: ``(p-1) · B`` total;
    * replicated → split s: **local slice** — zero wire bytes;
    * split s → split t (s ≠ t): **all-to-all** — each device keeps the
      1/p of its shard destined for itself and sends the rest:
      ``B · (p-1)/p`` total (the analytic all-to-all volume).
    """
    b = _numel(gshape) * int(itemsize)
    if nproc <= 1 or old_split == new_split:
        return CollectiveCost("none", 0)
    if old_split is None:
        return CollectiveCost("local-slice", 0)
    if new_split is None:
        return CollectiveCost("all-gather", b * (nproc - 1))
    return CollectiveCost("all-to-all", (b * (nproc - 1)) // nproc)


def relayout_chunk_cost(
    gshape: Sequence[int],
    itemsize: int,
    src_split: int,
    dst_split: int,
    width: int,
    nproc: int,
) -> CollectiveCost:
    """Cost of ONE stage of the planner's chunked relayout
    (:mod:`heat_tpu.core.relayout_planner`): a destination-shard-aligned
    block of ``width`` columns along ``dst_split`` lands whole on one
    destination shard, so XLA emits one **all-gather** of the block —
    every device receives the whole chunk and the owner keeps its part:
    ``chunk_phys · (p-1)`` wire bytes, where ``chunk_phys`` counts the
    source buffer's tail pad along ``src_split`` (the bytes the program
    actually moves). Summed over a plan's stages this is ``~B·(p-1)`` —
    the wire premium the bounded-memory decomposition pays vs the
    monolithic all-to-all's ``B·(p-1)/p``."""
    if nproc <= 1:
        return CollectiveCost("none", 0)
    other = 1
    for d, s in enumerate(gshape):
        if d == dst_split:
            continue
        s = int(s)
        if d == src_split:
            s = math.ceil(s / nproc) * nproc
        other *= s
    chunk = other * int(width) * int(itemsize)
    return CollectiveCost("all-gather", chunk * (nproc - 1))


def ring_cdist_cost(
    n: int, k: int, itemsize: int, nproc: int, hops: Optional[int] = None
) -> CollectiveCost:
    """Cost of the ppermute ring distance kernel
    (:func:`heat_tpu.spatial.distance._ring_dist`): the row-split ``y``
    block circulates one hop per step, every device sending its
    ``ceil(n/p)·k`` block each hop. Only ``y`` moves — the stationary x
    rows never touch the wire, so the volume is independent of the x-row
    count. ``hops`` defaults to ``p`` (the serial kernel's `fori_loop`
    permutes on every iteration, including the final hop that returns
    each block home); the double-buffered overlap kernel skips that dead
    hop and passes ``hops = p - 1``."""
    if nproc <= 1:
        return CollectiveCost("none", 0)
    hops = nproc if hops is None else int(hops)
    block = math.ceil(n / nproc) * int(k) * int(itemsize)
    return CollectiveCost("ppermute-ring", nproc * hops * block, steps=hops)


def tsqr_cost(m: int, n: int, itemsize: int, nproc: int) -> CollectiveCost:
    """Cost of the TSQR kernel (:func:`heat_tpu.core.linalg.qr.qr`, row-split
    path): one in-kernel all-gather of the per-shard ``(min(chunk, n), n)``
    R factors — every device receives the ``p-1`` blocks it did not
    compute. The two GEMM stages are local."""
    if nproc <= 1:
        return CollectiveCost("none", 0)
    chunk = math.ceil(m / nproc)
    k1 = min(chunk, int(n))
    return CollectiveCost(
        "all-gather", nproc * (nproc - 1) * k1 * int(n) * int(itemsize)
    )


def gram_ring_cost(
    m: int, n: int, itemsize: int, nproc: int, hops: Optional[int] = None
) -> CollectiveCost:
    """Cost of the CholeskyQR2 ring Gram kernel
    (:func:`heat_tpu.core.linalg.qr._gram_ring`): ``hops`` ring hops of
    the stationary-transpose schedule (each device circulates its
    ``(ceil(n/p), m)`` block every hop — ``p`` hops for the serial
    kernel, ``p - 1`` for the double-buffered overlap kernel, which
    skips the final hop that only returns each block home) plus the
    final tiled all-gather of the ``(ceil(n/p), n_phys)`` row blocks of
    G."""
    if nproc <= 1:
        return CollectiveCost("none", 0)
    hops = nproc if hops is None else int(hops)
    c = math.ceil(n / nproc)
    n_phys = c * nproc
    ring = nproc * hops * c * int(m) * int(itemsize)
    gather = nproc * (nproc - 1) * c * n_phys * int(itemsize)
    return CollectiveCost("ppermute-ring+all-gather", ring + gather, steps=hops)


def fusion_reduce_cost(
    out_gshape: Sequence[int], itemsize: int, nproc: int
) -> CollectiveCost:
    """Cost of the collective tail of a fused chain+reduction program
    (core/fusion.py ``absorb_reduce``, site ``fusion_reduce``): a
    reduction crossing the split axis leaves each device holding a full
    partial result of the OUTPUT shape, combined by one all-reduce —
    ``2·B·(p-1)`` wire bytes for the reduce-scatter+broadcast lowering,
    where ``B`` is the replicated result's byte size. Reductions that keep
    the split (and 1-position meshes) move nothing."""
    if nproc <= 1:
        return CollectiveCost("none", 0)
    return CollectiveCost(
        "all-reduce", 2 * _numel(out_gshape) * int(itemsize) * (nproc - 1)
    )
