"""Per-device memory watermarks.

Two sources, best available wins:

* ``device.memory_stats()`` — the runtime's own allocator statistics
  (``bytes_in_use`` / ``peak_bytes_in_use``), populated on TPU and GPU
  backends; returns ``None`` per device on CPU;
* ``jax.live_arrays()`` — framework-level accounting that works on every
  backend: the sum of shard bytes per device over all live ``jax.Array``\\ s.
  Replicated arrays count once per device (each replica occupies real
  memory). This sees only jax arrays, not scratch the compiler holds, so it
  is a lower bound — but it is the portion the framework controls.
"""

from __future__ import annotations

from collections import defaultdict
from typing import Dict, Optional

import jax

__all__ = ["live_bytes", "device_memory_stats", "watermark"]


def live_bytes() -> dict:
    """Framework-level live-array accounting: ``{"total": bytes,
    "per_device": {device: bytes}, "arrays": count}`` over
    ``jax.live_arrays()`` (addressable shards only).

    Buffers are de-duplicated by ``(device, buffer pointer)``: reading a
    sharded array's ``addressable_shards`` materializes per-shard view
    Arrays that jax caches on the parent AND reports in
    ``live_arrays()`` — without the dedup, the first ``live_bytes()``
    call would permanently double every sharded array in all later
    calls (ISSUE 6 found this via the relayout planner's
    before/after-decision comparisons). Aliased views of one buffer
    therefore count once — which is also the physically correct figure.
    """
    per_device: Dict[str, int] = defaultdict(int)
    count = 0
    seen = set()
    for arr in jax.live_arrays():
        count += 1
        try:
            for shard in arr.addressable_shards:
                data = shard.data
                dev = str(shard.device)
                try:
                    key = (dev, data.unsafe_buffer_pointer())
                    if key in seen:
                        continue
                    seen.add(key)
                except Exception:
                    pass  # no pointer API on this backend: count as-is
                per_device[dev] += data.nbytes
        except Exception:
            # deleted/donated buffers raise on access mid-iteration
            continue
    return {
        "total": sum(per_device.values()),
        "per_device": dict(per_device),
        "arrays": count,
    }


def device_memory_stats() -> Optional[Dict[str, dict]]:
    """Runtime allocator statistics per device (``bytes_in_use``,
    ``peak_bytes_in_use``, …), or None when no device reports any (CPU)."""
    out: Dict[str, dict] = {}
    for d in jax.devices():
        stats = None
        try:
            stats = d.memory_stats()
        except Exception:
            stats = None
        if stats:
            out[str(d)] = dict(stats)
    return out or None


def watermark(tag: str = "watermark") -> dict:
    """Snapshot memory now, update the registry's high-water marks, and —
    when telemetry is enabled — emit a ``memory`` event. Returns the
    snapshot either way (callable as a plain probe)."""
    from . import enabled, get_registry

    snap = live_bytes()
    stats = device_memory_stats()
    if stats is not None:
        snap["device_stats"] = stats
    if enabled():
        reg = get_registry()
        reg.high_water("live_bytes.total", snap["total"])
        for dev, b in snap["per_device"].items():
            reg.high_water(f"live_bytes.{dev}", b)
        if stats is not None:
            for dev, s in stats.items():
                if "peak_bytes_in_use" in s:
                    reg.high_water(
                        f"device_bytes.{dev}", s["peak_bytes_in_use"]
                    )
        reg.emit("memory", tag, **snap)
    return snap
