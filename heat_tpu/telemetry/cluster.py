"""Fleet-wide observability: metrics merging, SLO accounting, and the
merged cross-process trace (ISSUE 17 tentpole).

The PR 1–2 telemetry substrate is strictly per-process; since PRs 12/16
the system is a fleet (router + N replica processes + rolling version
updates). This module is the sensor plane over that fleet, built on
three exact contracts:

* **histogram merging is exact** — the per-endpoint latency histograms
  (:class:`heat_tpu.serve.metrics.LatencyHistogram`) are log-bucketed
  with fleet-wide fixed geometry, so bucket-wise addition of K replica
  scrapes yields byte-for-byte the histogram of the concatenated
  samples. Fleet p50/p95/p99 therefore carry the *same* one-bucket-width
  resolution as any single replica's — merging loses nothing.
* **scrapes are cumulative, rates are scraper-side** — ``GET /metrics``
  tallies are monotone since each replica's ``window_start`` and never
  reset, so windowed rates are per-replica deltas between two scrapes
  (``Δrequests / Δmono``) and can never race a reset. The same
  delta-histograms feed the SLO tail fractions.
* **clock alignment is explicit** — each process stamps wall clock on
  its own domain; the merged Perfetto export measures per-replica
  offsets via the ``/healthz`` round trip (offset = remote wall − RTT
  midpoint, uncertainty = RTT/2) and writes a ``clock_sync`` record per
  track instead of silently mixing domains.

:class:`SLO` + :func:`evaluate_slos` turn the merged view into the
error-budget **burn rate** ROADMAP item 4's autoscaler consumes: a
latency SLO ``p99_s`` allows 1% of requests over the target, an
availability SLO allows ``1 - availability`` failed/shed — burn rate is
(observed bad fraction) / (allowed bad fraction), so burn 1.0 spends the
budget exactly on schedule and burn ≫ 1 is the scale-up trigger. The
:class:`~heat_tpu.serve.net.router.Router` emits ``slo_burn`` events on
threshold crossings; everything here is pure computation.

All serve imports are lazy (function-local): telemetry must stay
importable without the serving tier.
"""

from __future__ import annotations

import json
import math
import os
from typing import Dict, List, Optional, Sequence

from heat_tpu import _knobs as knobs

__all__ = [
    "SLO",
    "merge_metrics",
    "summarize_cluster",
    "evaluate_slos",
    "prometheus_text",
    "export_merged_trace",
]

_COUNT_KEYS = (
    "requests", "rows", "batches", "dispatched_rows", "padded_rows",
    "shed", "errors",
)


class SLO:
    """One endpoint's service-level objective: ``p99_s`` (at most 1% of
    requests slower than this) and/or ``availability`` (at least this
    fraction answered, i.e. not errored or shed). Either may be None —
    only the declared objectives are accounted."""

    __slots__ = ("endpoint", "p99_s", "availability")

    def __init__(
        self,
        endpoint: str,
        p99_s: Optional[float] = None,
        availability: Optional[float] = None,
    ):
        if p99_s is None and availability is None:
            raise ValueError(
                f"SLO for {endpoint!r} declares no objective — give "
                f"p99_s and/or availability"
            )
        if p99_s is not None and p99_s <= 0:
            raise ValueError(f"p99_s must be positive, got {p99_s}")
        if availability is not None and not (0.0 < availability < 1.0):
            raise ValueError(
                f"availability must be in (0, 1), got {availability}"
            )
        self.endpoint = endpoint
        self.p99_s = None if p99_s is None else float(p99_s)
        self.availability = (
            None if availability is None else float(availability)
        )

    def describe(self) -> dict:
        return {"endpoint": self.endpoint, "p99_s": self.p99_s,
                "availability": self.availability}

    def __repr__(self) -> str:  # pragma: no cover — debugging aid
        return (f"SLO({self.endpoint!r}, p99_s={self.p99_s}, "
                f"availability={self.availability})")


# -- metrics merging ----------------------------------------------------------


def merge_metrics(scrapes: Dict[str, Optional[dict]]) -> dict:
    """Merge per-replica ``GET /metrics`` payloads (``{url: payload}``;
    ``None`` marks a failed scrape) into the fleet view: per-endpoint
    summed tallies + bucket-wise-merged latency histograms (exact — the
    module contract), per-replica identity/compile/version rows, and the
    list of replicas that failed to scrape (never silently dropped)."""
    from ..serve.metrics import LatencyHistogram

    endpoints: Dict[str, dict] = {}
    replicas: Dict[str, dict] = {}
    failures: List[str] = []
    for url in sorted(scrapes):
        payload = scrapes[url]
        if not payload:
            failures.append(url)
            continue
        net = payload.get("net", {})
        counters = payload.get("counters", {}) or {}
        replicas[url] = {
            "pid": net.get("pid"),
            "queue_depth": payload.get("queue_depth", 0),
            "shed": payload.get("shed", 0),
            "steady_backend_compiles": net.get(
                "steady_backend_compiles", 0
            ),
            "versions": dict(payload.get("versions", {}) or {}),
            "tracing": {
                "sampled": counters.get("tracing.sampled", 0),
                "spans": counters.get("tracing.spans", 0),
            },
        }
        for name, ep in (payload.get("endpoints", {}) or {}).items():
            agg = endpoints.get(name)
            if agg is None:
                agg = endpoints[name] = {k: 0 for k in _COUNT_KEYS}
                agg["hist"] = LatencyHistogram()
                agg["replicas"] = 0
            agg["replicas"] += 1
            for k in _COUNT_KEYS:
                agg[k] += int(ep.get(k, 0) or 0)
            lr = ep.get("latency_raw")
            if lr:
                agg["hist"].merge(LatencyHistogram.from_raw(lr))
    return {
        "endpoints": endpoints,
        "replicas": replicas,
        "scrape_failures": failures,
    }


def _scrape_state(scrapes: Dict[str, Optional[dict]]) -> dict:
    """The JSON-serializable per-(replica, endpoint) snapshot a later
    scrape diffs against for windowed rates: cumulative tallies, the
    replica's monotonic stamp, and the raw histogram counts."""
    state: Dict[str, dict] = {}
    for url, payload in scrapes.items():
        if not payload:
            continue
        eps = {}
        for name, ep in (payload.get("endpoints", {}) or {}).items():
            lr = ep.get("latency_raw") or {}
            eps[name] = {
                "requests": int(ep.get("requests", 0) or 0),
                "errors": int(ep.get("errors", 0) or 0),
                "shed": int(ep.get("shed", 0) or 0),
                "mono": float(ep.get("mono", 0.0) or 0.0),
                "window_start": float(ep.get("window_start", 0.0) or 0.0),
                "counts": list(lr.get("counts", ())),
                "count": int(lr.get("count", 0) or 0),
            }
        state[url] = eps
    return state


def _window_deltas(
    cur: dict, prev: Optional[dict]
) -> Dict[str, dict]:
    """Per-endpoint windowed deltas between two scrape states (fleet
    sums of per-replica deltas; a replica absent from ``prev`` — fresh
    spawn or first scrape — contributes its cumulative tallies over its
    own lifetime window). Returns ``{endpoint: {"requests", "errors",
    "shed", "seconds", "qps", "counts", "count"}}``."""
    out: Dict[str, dict] = {}
    prev = prev or {}
    for url, eps in cur.items():
        pep_all = prev.get(url, {})
        for name, c in eps.items():
            p = pep_all.get(name)
            row = out.setdefault(name, {
                "requests": 0, "errors": 0, "shed": 0,
                "seconds": 0.0, "qps": 0.0,
                "counts": None, "count": 0,
            })
            if p is not None and p.get("mono", 0.0) <= c["mono"]:
                d_req = max(0, c["requests"] - p["requests"])
                d_err = max(0, c["errors"] - p["errors"])
                d_shed = max(0, c["shed"] - p["shed"])
                dt = c["mono"] - p["mono"]
                d_counts = [
                    max(0, a - b)
                    for a, b in zip(c["counts"], p.get("counts", ()))
                ] if c["counts"] else []
            else:
                d_req, d_err, d_shed = (
                    c["requests"], c["errors"], c["shed"]
                )
                dt = max(0.0, c["mono"] - c["window_start"])
                d_counts = list(c["counts"])
            row["requests"] += d_req
            row["errors"] += d_err
            row["shed"] += d_shed
            row["seconds"] = max(row["seconds"], dt)
            if dt > 0:
                row["qps"] += d_req / dt
            if d_counts:
                if row["counts"] is None:
                    row["counts"] = [0] * len(d_counts)
                for i, v in enumerate(d_counts):
                    row["counts"][i] += v
                row["count"] += sum(d_counts)
    return out


def _tail_count(counts: Sequence[int], threshold_s: float) -> float:
    """Estimated number of samples above ``threshold_s`` in a raw
    bucket-count vector (exact for buckets fully above the threshold;
    the straddling bucket contributes its log-interpolated fraction)."""
    from ..serve import metrics as m

    if threshold_s <= 0:
        return float(sum(counts))
    total = 0.0
    for i, c in enumerate(counts):
        if not c:
            continue
        lo = 0.0 if i == 0 else m._BASE * m._GROWTH ** (i - 1)
        hi = m._BASE * m._GROWTH ** i
        if lo >= threshold_s:
            total += c
        elif hi > threshold_s:
            total += c * (hi - threshold_s) / (hi - lo)
    return total


# -- SLO accounting -----------------------------------------------------------


def burn_threshold() -> float:
    """``HEAT_TPU_SLO_BURN_THRESHOLD`` — the burn rate above which the
    router emits ``slo_burn`` events (1.0 = budget spent on schedule)."""
    try:
        return float(knobs.get("HEAT_TPU_SLO_BURN_THRESHOLD"))
    except (TypeError, ValueError):
        return 1.0


def evaluate_slos(
    slos: Sequence[SLO],
    window: Dict[str, dict],
) -> List[dict]:
    """Score each SLO against the windowed deltas (on the first scrape
    the "window" is each replica's lifetime — :func:`_window_deltas`
    falls back to the cumulative tallies). Returns one row per SLO: the
    objective, observed window, per-objective burn rates, the combined
    ``burn_rate`` (max of the declared objectives), and ``breach``
    (burn above :func:`burn_threshold`)."""
    thr = burn_threshold()
    rows = []
    for slo in slos:
        w = window.get(slo.endpoint) or {}
        n = int(w.get("requests", 0) or 0)
        row = {
            **slo.describe(),
            "window_requests": n,
            "window_seconds": round(float(w.get("seconds", 0.0)), 3),
            "burn_rate": 0.0,
            "breach": False,
            "threshold": thr,
        }
        burns = []
        if slo.p99_s is not None:
            counts = w.get("counts") or []
            total = int(w.get("count", 0) or 0)
            slow = _tail_count(counts, slo.p99_s) if total else 0.0
            frac = slow / total if total else 0.0
            # the p99 objective budgets 1% of requests over the target
            row["slow_fraction"] = round(frac, 6)
            row["latency_burn"] = round(frac / 0.01, 4)
            burns.append(row["latency_burn"])
        if slo.availability is not None:
            bad = int(w.get("errors", 0) or 0) + int(w.get("shed", 0) or 0)
            denom = n + int(w.get("shed", 0) or 0)
            frac = bad / denom if denom else 0.0
            budget = 1.0 - slo.availability
            row["bad_fraction"] = round(frac, 6)
            row["availability_burn"] = round(
                frac / budget if budget > 0 else math.inf, 4
            )
            burns.append(row["availability_burn"])
        if burns:
            row["burn_rate"] = max(burns)
            row["breach"] = bool(row["burn_rate"] > thr)
        rows.append(row)
    return rows


# -- fleet summary ------------------------------------------------------------


def summarize_cluster(
    scrapes: Dict[str, Optional[dict]],
    *,
    slos: Sequence[SLO] = (),
    prev_state: Optional[dict] = None,
    router_stats: Optional[dict] = None,
) -> dict:
    """The fleet-merged observability report (``report.summarize`` for a
    cluster): per-endpoint fleet tallies + QPS + merged p50/p95/p99 +
    occupancy, per-replica rows (pid, queue depth, compile counters,
    version lag, tracing counters), the optional router's own counters,
    and — when SLOs are declared — the ``slo`` burn-rate block ROADMAP
    item 4's autoscaler consumes.

    Pure function of its scrape inputs. ``prev_state`` is the ``state``
    field of an earlier summary; with it, QPS and SLO fractions are
    windowed per-replica deltas (scrape contract: cumulative counters,
    scraper-side rates); without it, they cover each replica's lifetime.
    The returned ``state`` feeds the next call."""
    merged = merge_metrics(scrapes)
    state = _scrape_state(scrapes)
    window = _window_deltas(state, prev_state)

    # endpoint versions across replicas: lag = replicas serving below
    # the fleet-max version (rolling update in flight / stuck)
    fleet_ver: Dict[str, int] = {}
    for rep in merged["replicas"].values():
        for name, v in rep["versions"].items():
            fleet_ver[name] = max(fleet_ver.get(name, 0), int(v))

    endpoints = {}
    for name, agg in merged["endpoints"].items():
        hist = agg["hist"]
        w = window.get(name, {})
        denom = agg["dispatched_rows"] + agg["padded_rows"]
        lagging = sum(
            1 for rep in merged["replicas"].values()
            if name in rep["versions"]
            and int(rep["versions"][name]) < fleet_ver.get(name, 0)
        )
        endpoints[name] = {
            "replicas": agg["replicas"],
            "requests": agg["requests"],
            "rows": agg["rows"],
            "batches": agg["batches"],
            "shed": agg["shed"],
            "errors": agg["errors"],
            "occupancy": (
                agg["dispatched_rows"] / denom if denom else None
            ),
            "qps": round(float(w.get("qps", 0.0)), 3),
            "window_requests": int(w.get("requests", 0)),
            "latency": hist.snapshot(),
            "version": fleet_ver.get(name),
            "version_lag": lagging,
        }

    out = {
        "replicas": merged["replicas"],
        "endpoints": endpoints,
        "scrape_failures": merged["scrape_failures"],
        "state": state,
    }
    if router_stats is not None:
        out["router"] = {
            "counters": router_stats.get("router", {}),
            "queue_depth": router_stats.get("queue_depth", 0),
            "replicas": router_stats.get("replicas", {}),
        }
    if slos:
        out["slo"] = evaluate_slos(list(slos), window)
    return out


# -- Prometheus exposition ----------------------------------------------------

def _prom_escape(v: str) -> str:
    return str(v).replace("\\", "\\\\").replace('"', '\\"')


def prometheus_text(summary: dict) -> str:
    """Render a :func:`summarize_cluster` report in Prometheus text
    exposition format (the merged fleet view — scrape the *router*, not
    N replicas). Counters are fleet-cumulative; quantiles come from the
    exactly-merged histograms."""
    lines: List[str] = []

    def head(name: str, typ: str, help_: str) -> None:
        lines.append(f"# HELP {name} {help_}")
        lines.append(f"# TYPE {name} {typ}")

    head("heat_tpu_requests_total", "counter",
         "Fleet-cumulative requests per endpoint.")
    for name, ep in sorted(summary.get("endpoints", {}).items()):
        lines.append(
            f'heat_tpu_requests_total{{endpoint="{_prom_escape(name)}"}} '
            f'{ep["requests"]}'
        )
    head("heat_tpu_errors_total", "counter",
         "Fleet-cumulative failed requests per endpoint.")
    for name, ep in sorted(summary.get("endpoints", {}).items()):
        lines.append(
            f'heat_tpu_errors_total{{endpoint="{_prom_escape(name)}"}} '
            f'{ep["errors"]}'
        )
    head("heat_tpu_shed_total", "counter",
         "Fleet-cumulative shed (503) requests per endpoint.")
    for name, ep in sorted(summary.get("endpoints", {}).items()):
        lines.append(
            f'heat_tpu_shed_total{{endpoint="{_prom_escape(name)}"}} '
            f'{ep["shed"]}'
        )
    head("heat_tpu_qps", "gauge",
         "Windowed fleet requests/second per endpoint (scraper-side "
         "delta).")
    for name, ep in sorted(summary.get("endpoints", {}).items()):
        lines.append(
            f'heat_tpu_qps{{endpoint="{_prom_escape(name)}"}} {ep["qps"]}'
        )
    head("heat_tpu_request_latency_seconds", "summary",
         "Merged-histogram latency quantiles per endpoint (exact "
         "bucket-wise merge; one-bucket-width resolution).")
    for name, ep in sorted(summary.get("endpoints", {}).items()):
        lat = ep.get("latency", {})
        for q, key in (("0.5", "p50_s"), ("0.95", "p95_s"),
                       ("0.99", "p99_s")):
            v = lat.get(key)
            if v is not None:
                lines.append(
                    f'heat_tpu_request_latency_seconds{{endpoint='
                    f'"{_prom_escape(name)}",quantile="{q}"}} {v:.9f}'
                )
    head("heat_tpu_replica_queue_depth", "gauge",
         "Per-replica admitted-but-unresolved backlog.")
    for url, rep in sorted(summary.get("replicas", {}).items()):
        lines.append(
            f'heat_tpu_replica_queue_depth{{replica='
            f'"{_prom_escape(url)}"}} {rep["queue_depth"]}'
        )
    head("heat_tpu_replica_steady_compiles", "counter",
         "Backend compiles after warm-up per replica (zero-recompile "
         "oracle).")
    for url, rep in sorted(summary.get("replicas", {}).items()):
        lines.append(
            f'heat_tpu_replica_steady_compiles{{replica='
            f'"{_prom_escape(url)}"}} {rep["steady_backend_compiles"]}'
        )
    if summary.get("slo"):
        head("heat_tpu_slo_burn_rate", "gauge",
             "Error-budget burn rate per SLO (1.0 = spending the budget "
             "exactly on schedule).")
        for row in summary["slo"]:
            lines.append(
                f'heat_tpu_slo_burn_rate{{endpoint='
                f'"{_prom_escape(row["endpoint"])}"}} {row["burn_rate"]}'
            )
    return "\n".join(lines) + "\n"


# -- merged Perfetto trace ----------------------------------------------------


def export_merged_trace(router, path: str) -> str:
    """Export ONE Perfetto/Chrome trace covering the router process plus
    every scrapable replica: each process becomes its own pid track
    (labelled with the replica URL), timestamps are clock-offset
    corrected from the router's ``/healthz`` round-trip calibration
    (explicit per-track ``clock_sync`` records carry offset +
    uncertainty), and all tracks share one fleet-wide t=0 — a sampled
    request's ``router.queue → router.post → serve.queue → … →
    serve.reply`` hops line up on one timeline, joined by trace_id."""
    from . import get_registry
    from . import trace as trace_mod

    sync = router.clock_sync()
    scraped = router.scrape_traces()

    # (events, pid, offset, uncertainty, label) per process; the router
    # itself is the reference clock domain (offset 0, no uncertainty —
    # but still labelled, so the merged file is self-describing)
    procs = [(
        list(get_registry().events), os.getpid(), 0.0, 0.0, "router",
    )]
    for url in sorted(scraped):
        payload = scraped[url]
        if not payload:
            continue
        s = sync.get(url) or {}
        procs.append((
            payload.get("events", []) or [],
            int(payload.get("pid") or 0),
            float(s.get("offset", 0.0)),
            float(s.get("uncertainty", 0.0)),
            url,
        ))

    # fleet anchor: earliest corrected start across every process
    anchor = None
    for events, _pid, offset, _unc, _label in procs:
        t = trace_mod.earliest_start(events)
        if t is not None:
            t -= offset
            if anchor is None or t < anchor:
                anchor = t

    all_events: List[dict] = []
    for events, pid, offset, unc, label in procs:
        all_events.extend(trace_mod.to_trace_events(
            events, pid,
            clock_offset=offset, clock_uncertainty=unc,
            anchor_ts=anchor, process_name=label,
        ))
    with open(path, "w") as f:
        json.dump(
            {"traceEvents": all_events, "displayTimeUnit": "ms"},
            f, default=str,
        )
    return path
