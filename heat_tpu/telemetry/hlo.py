"""Ground-truth XLA collective audit — predicted vs emitted.

The analytic cost model (:mod:`.collectives`) *predicts* what XLA should
emit from the layout contract; until now nothing in the repo verified the
prediction — exactly the gap that makes redistribution costs surprising in
practice (arXiv:2112.01075) and cross-mesh resharding invisible
(arXiv:2211.05322). This module closes the loop: lower-and-compile a
jitted computation (``fn.lower(...).compile()``), parse the optimized HLO
``as_text()`` plus ``cost_analysis()`` into a structured
:class:`CollectiveAudit` — one :class:`EmittedCollective` per emitted
``all-gather`` / ``all-reduce`` / ``reduce-scatter`` / ``all-to-all`` /
``collective-permute``, with element type, shape, replica groups and
modeled wire bytes — and :func:`compare` the audit against the analytic
:class:`~.collectives.CollectiveCost`, flagging **drift**: wrong
primitive, extra reshard, or byte mismatch beyond tolerance.

Wire-byte models per emitted op (``g`` = participants per replica group,
``n`` = total participants across groups, payload = per-participant
tensor bytes — the same "total bytes crossing links, summed over devices"
convention as the analytic model):

====================  =====================================================
op                    total wire bytes per execution
====================  =====================================================
all-gather            ``out · (g-1)/g · n`` (each device receives the
                      ``(g-1)/g`` of the result it does not hold)
all-to-all            ``in · (g-1)/g · n`` (each keeps its own ``1/g``)
reduce-scatter        ``in · (g-1)/g · n`` (ring reduce-scatter)
all-reduce            ``2 · in · (g-1)/g · n`` (ring: reduce-scatter +
                      all-gather phase)
collective-permute    ``in · |source_target_pairs|``
====================  =====================================================

A collective inside a loop body is counted ONCE per static instruction —
the HLO text does not expose trip counts — so :func:`compare` scales
``collective-permute`` volume by the predicted ring step count when the
prediction is a ``ppermute-ring``.

Auditing is opt-in: per call (``audit=True`` on `resplit`, `qr`, `cdist`)
or globally (:func:`enable_audit` / ``HEAT_TPU_HLO_AUDIT=1``, which the
benchmark harness's ``--audit`` flag sets). Each audit is memoized on the
(site, shapes, dtype, splits, mesh) key — the lower/compile cost is paid
once per distinct program, not per call — and recorded both in this
module (:func:`last_audit`, :func:`recent`) and, when telemetry is
recording, as an ``hlo_audit`` event that :func:`..report.summarize`
aggregates into the ``hlo_collectives`` benchmark section.
"""

from __future__ import annotations

import re
import warnings
from collections import deque
from dataclasses import dataclass, field
from typing import Any, Dict, List, Optional, Sequence, Tuple

import jax

from heat_tpu import _knobs as knobs

__all__ = [
    "EmittedCollective",
    "CollectiveAudit",
    "Drift",
    "DriftReport",
    "AuditRecord",
    "parse_hlo",
    "audit_compiled",
    "audit_computation",
    "compare",
    "audit_call",
    "enable_audit",
    "disable_audit",
    "audit_enabled",
    "last_audit",
    "recent",
    "clear",
    "DEFAULT_TOLERANCE",
]

# Byte-drift tolerance: |emitted - predicted| / predicted beyond which a
# drift is flagged. Audit sites predict on the shapes of the program being
# audited (the kernel costs use ceil-divided blocks; the relayout audit
# pads its shape the way the lowered program does), so this covers genuine
# compiler freedom — fusion-dependent layout choices, an XLA version
# changing the decomposition — not systematic padding arithmetic. 10%
# still catches a wrong primitive or a doubled transfer outright.
DEFAULT_TOLERANCE = float(knobs.raw("HEAT_TPU_HLO_TOLERANCE", "0.1"))

_COLLECTIVE_OPS = (
    "all-gather",
    "all-reduce",
    "reduce-scatter",
    "all-to-all",
    "collective-permute",
)

# One optimized-HLO instruction: `[ROOT] %name = <type> <opcode>(rest...`.
# The result type is either a tensor (`f32[64,32]{1,0}`) or a tuple of
# tensors (`(f32[8,1,4]{2,1,0}, ...)` — the tuple-form all-to-all). The
# opcode position (after " = <type> ") is what keeps consumer lines like
# `%gte = f32[...] get-tuple-element(... %all-to-all.1), index=0` from
# matching on their operand names.
_INSTR_RE = re.compile(
    r"^\s*(?:ROOT\s+)?%(?P<name>[^\s=]+)\s*=\s*(?P<rtype>\([^)]*\)|\S+)\s+"
    r"(?P<op>" + "|".join(_COLLECTIVE_OPS) + r")"
    r"(?P<variant>-start|-done)?"
    r"\((?P<rest>.*)$",
    re.MULTILINE,
)

_TENSOR_RE = re.compile(
    r"\b(pred|bf16|f16|f32|f64|f8e\w+|s4|s8|s16|s32|s64|u4|u8|u16|u32|u64"
    r"|c64|c128)\[([0-9,]*)\]"
)

_GROUPS_LITERAL_RE = re.compile(r"replica_groups=\{(\{[^}]*\}(?:,\{[^}]*\})*)\}")
_GROUPS_IOTA_RE = re.compile(r"replica_groups=\[(\d+),(\d+)\]<=\[")
_PAIRS_RE = re.compile(r"source_target_pairs=\{((?:\{\d+,\d+\},?)*)\}")
_OP_NAME_RE = re.compile(r'op_name="([^"]*)"')


def _itemsize(dt: str) -> int:
    if dt == "pred" or dt in ("s4", "u4", "s8", "u8") or dt.startswith("f8"):
        return 1
    if dt == "c128":
        return 16
    if dt == "c64":
        return 8
    return int(dt.lstrip("bfsu")) // 8


def _tensor_bytes(types: str) -> Tuple[int, Optional[str], Tuple[Tuple[int, ...], ...]]:
    """Sum the byte sizes of every tensor type in ``types``; also return
    the first element type and the shapes (for the audit record)."""
    total = 0
    dtype = None
    shapes: List[Tuple[int, ...]] = []
    for dt, dims in _TENSOR_RE.findall(types):
        shape = tuple(int(d) for d in dims.split(",") if d) if dims else ()
        numel = 1
        for d in shape:
            numel *= d
        total += numel * _itemsize(dt)
        shapes.append(shape)
        if dtype is None:
            dtype = dt
    return total, dtype, tuple(shapes)


def _split_operands_attrs(rest: str) -> Tuple[str, str]:
    """Split the text after the opening ``(`` into the operand list and the
    trailing attributes (``channel_id=…, replica_groups=…, metadata=…``)."""
    depth = 1
    for i, ch in enumerate(rest):
        if ch == "(":
            depth += 1
        elif ch == ")":
            depth -= 1
            if depth == 0:
                return rest[:i], rest[i + 1:]
    return rest, ""


def _parse_groups(attrs: str, default_participants: Optional[int]):
    """Replica groups → (group_size, n_participants, groups tuple)."""
    m = _GROUPS_LITERAL_RE.search(attrs)
    if m:
        groups = tuple(
            tuple(int(v) for v in grp.split(",") if v.strip())
            for grp in re.findall(r"\{([^}]*)\}", m.group(1))
        )
        groups = tuple(g for g in groups if g)
        if groups:
            return max(len(g) for g in groups), sum(len(g) for g in groups), groups
    m = _GROUPS_IOTA_RE.search(attrs)
    if m:  # iota form [num_groups, group_size]<=[n] (+ optional transpose)
        num, size = int(m.group(1)), int(m.group(2))
        return size, num * size, ((num, size),)
    n = default_participants or 1
    return n, n, ()


@dataclass(frozen=True)
class EmittedCollective:
    """One collective instruction in an optimized HLO module."""

    op: str                                  # canonical opcode
    name: str                                # HLO instruction name
    dtype: Optional[str]                     # element type, e.g. "f32"
    shapes: Tuple[Tuple[int, ...], ...]      # result tensor shape(s)
    in_bytes: int                            # per-participant operand bytes
    out_bytes: int                           # per-participant result bytes
    group_size: int                          # participants per replica group
    n_participants: int                      # total participants
    groups: Tuple                            # replica groups / st-pairs
    wire_bytes: int                          # modeled total wire bytes
    op_name: str = ""                        # XLA metadata provenance

    def summary(self) -> dict:
        return {
            "op": self.op,
            "name": self.name,
            "dtype": self.dtype,
            "shapes": [list(s) for s in self.shapes],
            "in_bytes": self.in_bytes,
            "out_bytes": self.out_bytes,
            "group_size": self.group_size,
            "wire_bytes": self.wire_bytes,
        }


def _wire_bytes(op: str, in_bytes: int, out_bytes: int, g: int, n: int,
                n_pairs: int) -> int:
    if op == "collective-permute":
        return in_bytes * n_pairs
    if g <= 1:
        return 0
    if op == "all-gather":
        return out_bytes * (g - 1) * n // g
    if op == "all-reduce":
        return 2 * in_bytes * (g - 1) * n // g
    # all-to-all and reduce-scatter: each participant ships the (g-1)/g of
    # its input destined elsewhere
    return in_bytes * (g - 1) * n // g


def parse_hlo(
    text: str, default_participants: Optional[int] = None
) -> List[EmittedCollective]:
    """Parse optimized HLO text into the emitted-collective records.

    Tolerant to XLA version noise: only the instruction grammar
    (``%name = type opcode(...)``) and the ``replica_groups`` /
    ``source_target_pairs`` attribute syntax are relied on. Async pairs
    count once (the ``-start`` carries the payload; ``-done`` is skipped).
    ``default_participants`` seeds the group size when an instruction
    carries no replica_groups attribute (flat single-group default).
    """
    out: List[EmittedCollective] = []
    for m in _INSTR_RE.finditer(text):
        if m.group("variant") == "-done":
            continue
        op = m.group("op")
        operands, attrs = _split_operands_attrs(m.group("rest"))
        in_bytes, in_dtype, _ = _tensor_bytes(operands)
        out_bytes, out_dtype, shapes = _tensor_bytes(m.group("rtype"))
        if m.group("variant") == "-start" and in_bytes <= out_bytes:
            # async form: the start's tuple result aliases the operand
            # buffer(s) alongside the actual result — counting both would
            # inflate the all-gather wire model past the drift tolerance
            out_bytes -= in_bytes
            shapes = shapes[1:] if len(shapes) > 1 else shapes
        pairs: Tuple = ()
        if op == "collective-permute":
            pm = _PAIRS_RE.search(attrs)
            if pm:
                pairs = tuple(
                    tuple(int(v) for v in pair.split(","))
                    for pair in re.findall(r"\{(\d+,\d+)\}", pm.group(1))
                )
            g = n = len({d for pr in pairs for d in pr}) or (
                default_participants or 1
            )
            groups: Tuple = pairs
        else:
            g, n, groups = _parse_groups(attrs, default_participants)
        om = _OP_NAME_RE.search(attrs)
        out.append(
            EmittedCollective(
                op=op,
                name=m.group("name"),
                dtype=out_dtype or in_dtype,
                shapes=shapes,
                in_bytes=in_bytes,
                out_bytes=out_bytes,
                group_size=g,
                n_participants=n,
                groups=groups,
                wire_bytes=_wire_bytes(op, in_bytes, out_bytes, g, n, len(pairs)),
                op_name=om.group(1) if om else "",
            )
        )
    return out


@dataclass
class CollectiveAudit:
    """The collectives one compiled XLA program will execute."""

    collectives: List[EmittedCollective]
    n_devices: int = 1
    flops: Optional[float] = None
    bytes_accessed: Optional[float] = None

    def counts(self) -> Dict[str, int]:
        """Static instruction count per opcode."""
        out: Dict[str, int] = {}
        for c in self.collectives:
            out[c.op] = out.get(c.op, 0) + 1
        return out

    def wire_by_op(self) -> Dict[str, int]:
        """Modeled wire bytes per opcode (per single execution of each
        instruction — loop trip counts are not included, see module doc)."""
        out: Dict[str, int] = {}
        for c in self.collectives:
            out[c.op] = out.get(c.op, 0) + c.wire_bytes
        return out

    def total_wire(self) -> int:
        return sum(c.wire_bytes for c in self.collectives)

    def summary(self) -> dict:
        s = {
            "ops": self.counts(),
            "wire_bytes": self.wire_by_op(),
            "instructions": [c.summary() for c in self.collectives],
            "n_devices": self.n_devices,
        }
        if self.flops is not None:
            s["flops"] = self.flops
        if self.bytes_accessed is not None:
            s["bytes_accessed"] = self.bytes_accessed
        return s


def audit_compiled(compiled, n_devices: Optional[int] = None) -> CollectiveAudit:
    """Audit an already-compiled executable (``jit(f).lower(...).compile()``)."""
    if n_devices is None:
        n_devices = jax.device_count()
    flops = bytes_accessed = None
    try:
        ca = compiled.cost_analysis()
        props = ca[0] if isinstance(ca, (list, tuple)) and ca else ca
        if isinstance(props, dict):
            flops = props.get("flops")
            bytes_accessed = props.get("bytes accessed")
    except Exception:  # pragma: no cover — cost analysis is best-effort
        pass
    return CollectiveAudit(
        collectives=parse_hlo(compiled.as_text(), default_participants=n_devices),
        n_devices=n_devices,
        flops=flops,
        bytes_accessed=bytes_accessed,
    )


def audit_computation(fn, *args, **kwargs) -> CollectiveAudit:
    """Lower-and-compile ``fn(*args, **kwargs)`` (a jitted or jittable
    callable — sharded example arguments determine the input layouts) and
    audit the compiled program. Compiles but never executes."""
    jitted = fn if hasattr(fn, "lower") else jax.jit(fn)
    return audit_compiled(jitted.lower(*args, **kwargs).compile())


# -- predicted-vs-emitted drift ----------------------------------------------

# analytic CollectiveCost.kind (possibly "+"-compound) → expected HLO opcode
_KIND_TO_OP = {
    "all-gather": "all-gather",
    "all-to-all": "all-to-all",
    "ppermute-ring": "collective-permute",
    "all-reduce": "all-reduce",
    "reduce-scatter": "reduce-scatter",
    "none": None,
    "local-slice": None,
}


@dataclass(frozen=True)
class Drift:
    """One predicted-vs-emitted discrepancy."""

    reason: str          # "missing-collective" | "unexpected-collective"
    #                    # | "byte-drift" | "unknown-kind"
    op: str
    predicted_bytes: int
    emitted_bytes: int
    detail: str

    def summary(self) -> dict:
        return {
            "reason": self.reason,
            "op": self.op,
            "predicted_bytes": self.predicted_bytes,
            "emitted_bytes": self.emitted_bytes,
            "detail": self.detail,
        }


@dataclass
class DriftReport:
    """Outcome of one :func:`compare`: ``ok`` iff no drift was flagged."""

    ok: bool
    drifts: List[Drift]
    expected_ops: Tuple[str, ...]
    predicted_bytes: int
    emitted_bytes: int       # steps-scaled total over the expected ops
    tolerance: float

    def summary(self) -> dict:
        return {
            "ok": self.ok,
            "expected_ops": list(self.expected_ops),
            "predicted_bytes": self.predicted_bytes,
            "emitted_bytes": self.emitted_bytes,
            "tolerance": self.tolerance,
            "drifts": [d.summary() for d in self.drifts],
        }


def compare(
    audit: CollectiveAudit,
    predicted,
    tolerance: Optional[float] = None,
    steps: Optional[int] = None,
) -> DriftReport:
    """Diff an audit against the analytic prediction for the same program.

    ``predicted`` is a :class:`~.collectives.CollectiveCost`. Flags:

    * **missing-collective** — the predicted primitive never appears;
    * **unexpected-collective** — an emitted collective the prediction
      does not name (e.g. an extra reshard XLA slipped in);
    * **byte-drift** — total emitted wire bytes over the expected ops
      differ from the predicted volume by more than ``tolerance``
      (relative; default :data:`DEFAULT_TOLERANCE`).

    Ring predictions (``ppermute-ring``) have their emitted
    ``collective-permute`` volume scaled by the predicted ``steps`` —
    the loop trip count the HLO text cannot express.
    """
    tolerance = DEFAULT_TOLERANCE if tolerance is None else tolerance
    steps = predicted.steps if steps is None else steps
    parts = predicted.kind.split("+")
    expected: List[str] = []
    drifts: List[Drift] = []
    for part in parts:
        if part not in _KIND_TO_OP:
            drifts.append(
                Drift("unknown-kind", part, predicted.bytes, 0,
                      f"analytic kind {part!r} has no HLO opcode mapping")
            )
            continue
        op = _KIND_TO_OP[part]
        if op is not None:
            expected.append(op)

    emitted_total = 0
    for op in dict.fromkeys(expected):  # unique, order-preserving
        instrs = [c for c in audit.collectives if c.op == op]
        if not instrs:
            drifts.append(
                Drift("missing-collective", op, predicted.bytes, 0,
                      f"predicted {predicted.kind!r} but the compiled "
                      f"program contains no {op}")
            )
            continue
        wire = sum(c.wire_bytes for c in instrs)
        if op == "collective-permute" and steps > 1:
            wire *= steps
        emitted_total += wire

    for c in audit.collectives:
        if c.op not in expected:
            drifts.append(
                Drift("unexpected-collective", c.op, 0, c.wire_bytes,
                      f"{c.name}: emitted {c.op} not named by the "
                      f"prediction {predicted.kind!r}")
            )

    if expected and not any(d.reason == "missing-collective" for d in drifts):
        pb = int(predicted.bytes)
        if pb > 0 and abs(emitted_total - pb) > tolerance * pb:
            drifts.append(
                Drift("byte-drift", "+".join(dict.fromkeys(expected)), pb,
                      emitted_total,
                      f"emitted {emitted_total} wire bytes vs predicted "
                      f"{pb} (beyond {tolerance:.0%} tolerance)")
            )

    return DriftReport(
        ok=not drifts,
        drifts=drifts,
        expected_ops=tuple(dict.fromkeys(expected)),
        predicted_bytes=int(predicted.bytes),
        emitted_bytes=emitted_total,
        tolerance=tolerance,
    )


# -- opt-in auditing at instrumented sites ------------------------------------

_AUDIT_ENABLED = False
_CACHE: Dict[Any, CollectiveAudit] = {}
_RECENT: "deque[AuditRecord]" = deque(maxlen=64)


@dataclass
class AuditRecord:
    """One recorded audit at an instrumented site."""

    site: str
    audit: CollectiveAudit
    report: Optional[DriftReport] = None
    fields: Dict[str, Any] = field(default_factory=dict)

    def summary(self) -> dict:
        s = {"site": self.site, **self.fields}
        s["audit"] = self.audit.summary()
        s["report"] = self.report.summary() if self.report else None
        return s


def audit_enabled() -> bool:
    """Whether the global opt-in (``HEAT_TPU_HLO_AUDIT=1`` /
    :func:`enable_audit`) is active; instrumented ops also audit when
    called with ``audit=True`` explicitly."""
    return _AUDIT_ENABLED


def enable_audit() -> None:
    global _AUDIT_ENABLED
    _AUDIT_ENABLED = True


def disable_audit() -> None:
    global _AUDIT_ENABLED
    _AUDIT_ENABLED = False


def clear() -> None:
    """Drop the memo cache and the recent-audit ring."""
    _CACHE.clear()
    _RECENT.clear()


def recent() -> List[AuditRecord]:
    """The most recent audits (bounded ring), oldest first."""
    return list(_RECENT)


def last_audit(site: Optional[str] = None) -> Optional[AuditRecord]:
    """The most recent audit, optionally filtered by site name."""
    for rec in reversed(_RECENT):
        if site is None or rec.site == site:
            return rec
    return None


def audit_call(
    site: str,
    build,
    predicted=None,
    key: Optional[Any] = None,
    fields: Optional[Dict[str, Any]] = None,
    tolerance: Optional[float] = None,
) -> Optional[AuditRecord]:
    """Audit one instrumented call site; never raises.

    ``build()`` returns ``(jittable_or_jitted, args_tuple)`` — the
    equivalent single-program computation to lower and compile (sharded
    example args pin the input layouts). Memoized on ``key`` so repeated
    calls with the same program shape pay the compile once. The record
    lands in :func:`recent`, and — when telemetry is recording — as an
    ``hlo_audit`` event with the emitted op counts/bytes and the drift
    verdict against ``predicted``.
    """
    audit = _CACHE.get(key) if key is not None else None
    if audit is None:
        try:
            fn, args = build()
            audit = audit_computation(fn, *args)
        except Exception as e:
            # the auditor observes; it must never take the workload down
            warnings.warn(f"heat_tpu.telemetry.hlo: audit of {site!r} "
                          f"failed ({e!r}); skipping")
            return None
        if key is not None:
            _CACHE[key] = audit
    report = (
        compare(audit, predicted, tolerance=tolerance)
        if predicted is not None
        else None
    )
    rec = AuditRecord(site=site, audit=audit, report=report,
                      fields=dict(fields or {}))
    _RECENT.append(rec)

    from . import enabled, get_registry

    if enabled():
        ev: Dict[str, Any] = {
            "ops": audit.counts(),
            "bytes_by_op": audit.wire_by_op(),
        }
        if report is not None:
            ev.update(
                predicted=predicted.kind,
                predicted_bytes=int(predicted.bytes),
                emitted_bytes=report.emitted_bytes,
                drift=len(report.drifts),
                ok=report.ok,
            )
            if report.drifts:
                ev["drifts"] = [d.summary() for d in report.drifts]
        else:
            ev["emitted_bytes"] = audit.total_wire()
        ev.update(fields or {})
        get_registry().emit("hlo_audit", site, **ev)
    return rec


# Environment activation (mirrors HEAT_TPU_TELEMETRY): the benchmark
# harness's --audit flag and the CI audit step set this before import.
if knobs.raw("HEAT_TPU_HLO_AUDIT", "").strip().lower() in (
    "1", "true", "yes", "on",
):
    _AUDIT_ENABLED = True
