"""Chrome-trace / Perfetto export of the telemetry event stream.

Turns the registry's events (or a JSONL sink read back via
:func:`..report.load_events`) into a `Trace Event Format
<https://docs.google.com/document/d/1CvAClvFfyA5R-PhYUmn5OOQtYMH4h6I0nSsKchNAySU>`_
JSON file loadable in ``chrome://tracing`` or https://ui.perfetto.dev:

* ``span`` / ``span_error`` events → complete (``"X"``) slices on the
  *spans* track, with their user fields (``bytes``, ``collective``,
  ``gshape``, anything via ``add_fields``) as ``args``;
* ``compile`` events → ``"X"`` slices on the *compile* track (the
  AOT/backend-compile durations, visually separated from execution);
* ``memory`` events → a ``live_bytes`` counter (``"C"``) track;
* ``trace_span`` events (ISSUE 17 request-trace hops) → ``"X"`` slices
  on the *requests* track, carrying their ``trace_id`` in ``args`` so
  Perfetto's query/filter UI groups one request's hops across tracks —
  and, in a merged export, across processes;
* everything else (``collective_trace``, ``hlo_audit``, …) → instant
  (``"i"``) markers on the *events* track.

Timestamps: the registry records wall-clock *end* times plus durations;
slices are re-anchored to their start (``ts - seconds``), shifted so the
earliest event is t=0, and emitted in microseconds, sorted — the
monotonic, pid/tid-complete stream the format requires.

Cross-process merging (ISSUE 17): each process records wall clock on its
own clock domain. A merged export passes per-process ``clock_offset``
(this process's wall minus the reference process's wall, measured by the
``/healthz`` round trip), ``clock_uncertainty`` (± RTT/2 of that probe),
and one fleet-wide ``anchor_ts`` so every track shares t=0. The offset
correction is explicit, never silent: a merged track carries a
``clock_sync`` instant record stating the applied offset and its
uncertainty. The single-process default (no offset, no anchor, no
uncertainty) is byte-identical to the pre-17 export.
"""

from __future__ import annotations

import json
import os
from typing import Iterable, List, Optional

__all__ = ["to_trace_events", "export_trace", "earliest_start"]

_TID_SPANS = 1
_TID_COMPILE = 2
_TID_EVENTS = 3
_TID_MEMORY = 4
_TID_AUTOTUNE = 5
_TID_REQUESTS = 6

_THREAD_NAMES = {
    _TID_SPANS: "spans",
    _TID_COMPILE: "compile",
    _TID_EVENTS: "events",
    _TID_MEMORY: "memory",
    _TID_AUTOTUNE: "autotune",
    _TID_REQUESTS: "requests",
}

_META_KEYS = ("ts", "kind", "name", "seconds", "depth", "parent", "start_ts")


def _args(ev: dict) -> dict:
    out = {k: v for k, v in ev.items() if k not in _META_KEYS}
    # depth/parent are span structure, useful to keep visible in the UI
    if "parent" in ev and ev.get("parent") is not None:
        out["parent"] = ev["parent"]
    return out


def _event_start(ev: dict) -> float:
    kind = ev.get("kind")
    ts_end = float(ev.get("ts", 0.0))
    dur = float(ev.get("seconds", 0.0) or 0.0)
    if kind in ("span", "span_error", "compile", "trace_span"):
        # spans carry their wall-clock start explicitly (deriving it as
        # `ts - seconds` mixes the wall and perf_counter clocks and
        # breaks slice containment at µs scale); compile events do not,
        # so they fall back to the derived start
        return float(ev.get("start_ts") or (ts_end - dur))
    return ts_end


def earliest_start(events: Iterable[dict]) -> Optional[float]:
    """Earliest wall-clock slice start in ``events`` (this process's
    clock domain) — the per-process input to a merged export's global
    ``anchor_ts``. ``None`` for an empty stream."""
    t0 = None
    for ev in events:
        start = _event_start(ev)
        if t0 is None or start < t0:
            t0 = start
    return t0


def to_trace_events(
    events: Optional[Iterable[dict]] = None, pid: Optional[int] = None,
    *,
    clock_offset: float = 0.0,
    clock_uncertainty: Optional[float] = None,
    anchor_ts: Optional[float] = None,
    process_name: Optional[str] = None,
) -> List[dict]:
    """Convert telemetry events (default: the live registry's) into a
    sorted Trace Event Format list (``ts``/``dur`` in microseconds,
    earliest event at t=0, ``pid``/``tid`` on every record).

    The keyword-only parameters serve cross-process merges (module
    docstring): ``clock_offset`` (seconds this process's wall clock runs
    ahead of the reference — subtracted from every timestamp) with its
    ``clock_uncertainty`` (emitted as an explicit ``clock_sync`` record
    whenever it is not ``None``), ``anchor_ts`` (the fleet-wide t=0 in
    reference wall seconds, replacing the local earliest-event anchor),
    and ``process_name`` (the track label — e.g. the replica URL). The
    defaults reproduce the single-process export byte-for-byte."""
    if events is None:
        from . import get_registry

        events = list(get_registry().events)
    else:
        events = list(events)
    if pid is None:
        pid = os.getpid()

    out: List[dict] = [
        {"name": "process_name", "ph": "M", "ts": 0, "pid": pid, "tid": 0,
         "args": {"name": process_name or "heat_tpu.telemetry"}},
    ]
    for tid, tname in _THREAD_NAMES.items():
        out.append({"name": "thread_name", "ph": "M", "ts": 0, "pid": pid,
                    "tid": tid, "args": {"name": tname}})

    rows: List[dict] = []
    t0 = None
    for ev in events:
        start = _event_start(ev) - clock_offset
        dur = float(ev.get("seconds", 0.0) or 0.0)
        if t0 is None or start < t0:
            t0 = start
        rows.append({"_start": start, "_dur": dur, **ev})
    if anchor_ts is not None:
        t0 = anchor_ts
    t0 = t0 or 0.0

    if clock_uncertainty is not None:
        # merged-export honesty: state the applied correction instead of
        # silently mixing clock domains (satellite of ISSUE 17)
        out.append({
            "name": "clock_sync", "cat": "clock_sync", "ph": "i", "ts": 0.0,
            "s": "p", "pid": pid, "tid": _TID_EVENTS,
            "args": {"offset_s": clock_offset,
                     "uncertainty_s": clock_uncertainty},
        })

    for ev in rows:
        kind = ev.get("kind")
        name = str(ev.get("name", "?"))
        ts_us = (ev["_start"] - t0) * 1e6
        dur_us = ev["_dur"] * 1e6
        clean = {k: v for k, v in ev.items() if k not in ("_start", "_dur")}
        if kind in ("span", "span_error"):
            out.append({
                "name": name, "cat": kind, "ph": "X", "ts": ts_us,
                "dur": dur_us, "pid": pid, "tid": _TID_SPANS,
                "args": _args(clean),
            })
        elif kind == "trace_span":
            # request-trace hops (ISSUE 17): trace_id stays in args so
            # Perfetto's filter box collects one request across tracks
            out.append({
                "name": name, "cat": "trace_span", "ph": "X", "ts": ts_us,
                "dur": dur_us, "pid": pid, "tid": _TID_REQUESTS,
                "args": _args(clean),
            })
        elif kind == "compile":
            out.append({
                "name": name, "cat": "compile", "ph": "X", "ts": ts_us,
                "dur": dur_us, "pid": pid, "tid": _TID_COMPILE,
                "args": _args(clean),
            })
        elif kind == "memory":
            out.append({
                "name": "live_bytes", "cat": "memory", "ph": "C",
                "ts": ts_us, "pid": pid, "tid": _TID_MEMORY,
                "args": {"total": ev.get("total", 0)},
            })
        elif kind == "autotune":
            # tuner activity gets its own track (ISSUE 11): trial /
            # db_hit / pick / adopt markers, named by their event so the
            # timeline reads as a tuning narrative
            out.append({
                "name": f"{ev.get('event', 'event')}:{name}",
                "cat": "autotune", "ph": "i", "ts": ts_us, "s": "p",
                "pid": pid, "tid": _TID_AUTOTUNE, "args": _args(clean),
            })
        else:  # collective_trace, hlo_audit, and future kinds
            out.append({
                "name": name, "cat": str(kind), "ph": "i", "ts": ts_us,
                "s": "p", "pid": pid, "tid": _TID_EVENTS,
                "args": _args(clean),
            })

    # metadata first, then everything else in monotonic ts order
    meta = [e for e in out if e["ph"] == "M"]
    rest = sorted((e for e in out if e["ph"] != "M"), key=lambda e: e["ts"])
    return meta + rest


def export_trace(
    path: str, events: Optional[Iterable[dict]] = None
) -> str:
    """Write the event stream as a Chrome-trace JSON object
    (``{"traceEvents": [...]}``) loadable in ``chrome://tracing`` /
    Perfetto; returns ``path``. ``events`` defaults to the live
    registry's stream — pass ``report.load_events(sink)`` to convert a
    JSONL sink from an earlier run."""
    trace = {
        "traceEvents": to_trace_events(events),
        "displayTimeUnit": "ms",
    }
    with open(path, "w") as f:
        json.dump(trace, f, default=str)
    return path
