"""Chrome-trace / Perfetto export of the telemetry event stream.

Turns the registry's events (or a JSONL sink read back via
:func:`..report.load_events`) into a `Trace Event Format
<https://docs.google.com/document/d/1CvAClvFfyA5R-PhYUmn5OOQtYMH4h6I0nSsKchNAySU>`_
JSON file loadable in ``chrome://tracing`` or https://ui.perfetto.dev:

* ``span`` / ``span_error`` events → complete (``"X"``) slices on the
  *spans* track, with their user fields (``bytes``, ``collective``,
  ``gshape``, anything via ``add_fields``) as ``args``;
* ``compile`` events → ``"X"`` slices on the *compile* track (the
  AOT/backend-compile durations, visually separated from execution);
* ``memory`` events → a ``live_bytes`` counter (``"C"``) track;
* everything else (``collective_trace``, ``hlo_audit``, …) → instant
  (``"i"``) markers on the *events* track.

Timestamps: the registry records wall-clock *end* times plus durations;
slices are re-anchored to their start (``ts - seconds``), shifted so the
earliest event is t=0, and emitted in microseconds, sorted — the
monotonic, pid/tid-complete stream the format requires.
"""

from __future__ import annotations

import json
import os
from typing import Iterable, List, Optional

__all__ = ["to_trace_events", "export_trace"]

_TID_SPANS = 1
_TID_COMPILE = 2
_TID_EVENTS = 3
_TID_MEMORY = 4
_TID_AUTOTUNE = 5

_THREAD_NAMES = {
    _TID_SPANS: "spans",
    _TID_COMPILE: "compile",
    _TID_EVENTS: "events",
    _TID_MEMORY: "memory",
    _TID_AUTOTUNE: "autotune",
}

_META_KEYS = ("ts", "kind", "name", "seconds", "depth", "parent", "start_ts")


def _args(ev: dict) -> dict:
    out = {k: v for k, v in ev.items() if k not in _META_KEYS}
    # depth/parent are span structure, useful to keep visible in the UI
    if "parent" in ev and ev.get("parent") is not None:
        out["parent"] = ev["parent"]
    return out


def to_trace_events(
    events: Optional[Iterable[dict]] = None, pid: Optional[int] = None
) -> List[dict]:
    """Convert telemetry events (default: the live registry's) into a
    sorted Trace Event Format list (``ts``/``dur`` in microseconds,
    earliest event at t=0, ``pid``/``tid`` on every record)."""
    if events is None:
        from . import get_registry

        events = list(get_registry().events)
    else:
        events = list(events)
    if pid is None:
        pid = os.getpid()

    out: List[dict] = [
        {"name": "process_name", "ph": "M", "ts": 0, "pid": pid, "tid": 0,
         "args": {"name": "heat_tpu.telemetry"}},
    ]
    for tid, tname in _THREAD_NAMES.items():
        out.append({"name": "thread_name", "ph": "M", "ts": 0, "pid": pid,
                    "tid": tid, "args": {"name": tname}})

    rows: List[dict] = []
    t0 = None
    for ev in events:
        kind = ev.get("kind")
        ts_end = float(ev.get("ts", 0.0))
        dur = float(ev.get("seconds", 0.0) or 0.0)
        if kind in ("span", "span_error", "compile"):
            # spans carry their wall-clock start explicitly (deriving it as
            # `ts - seconds` mixes the wall and perf_counter clocks and
            # breaks slice containment at µs scale); compile events do not,
            # so they fall back to the derived start
            start = float(ev.get("start_ts") or (ts_end - dur))
        else:
            start = ts_end
        if t0 is None or start < t0:
            t0 = start
        rows.append({"_start": start, "_dur": dur, **ev})
    t0 = t0 or 0.0

    for ev in rows:
        kind = ev.get("kind")
        name = str(ev.get("name", "?"))
        ts_us = (ev["_start"] - t0) * 1e6
        dur_us = ev["_dur"] * 1e6
        clean = {k: v for k, v in ev.items() if k not in ("_start", "_dur")}
        if kind in ("span", "span_error"):
            out.append({
                "name": name, "cat": kind, "ph": "X", "ts": ts_us,
                "dur": dur_us, "pid": pid, "tid": _TID_SPANS,
                "args": _args(clean),
            })
        elif kind == "compile":
            out.append({
                "name": name, "cat": "compile", "ph": "X", "ts": ts_us,
                "dur": dur_us, "pid": pid, "tid": _TID_COMPILE,
                "args": _args(clean),
            })
        elif kind == "memory":
            out.append({
                "name": "live_bytes", "cat": "memory", "ph": "C",
                "ts": ts_us, "pid": pid, "tid": _TID_MEMORY,
                "args": {"total": ev.get("total", 0)},
            })
        elif kind == "autotune":
            # tuner activity gets its own track (ISSUE 11): trial /
            # db_hit / pick / adopt markers, named by their event so the
            # timeline reads as a tuning narrative
            out.append({
                "name": f"{ev.get('event', 'event')}:{name}",
                "cat": "autotune", "ph": "i", "ts": ts_us, "s": "p",
                "pid": pid, "tid": _TID_AUTOTUNE, "args": _args(clean),
            })
        else:  # collective_trace, hlo_audit, and future kinds
            out.append({
                "name": name, "cat": str(kind), "ph": "i", "ts": ts_us,
                "s": "p", "pid": pid, "tid": _TID_EVENTS,
                "args": _args(clean),
            })

    # metadata first, then everything else in monotonic ts order
    meta = [e for e in out if e["ph"] == "M"]
    rest = sorted((e for e in out if e["ph"] != "M"), key=lambda e: e["ts"])
    return meta + rest


def export_trace(
    path: str, events: Optional[Iterable[dict]] = None
) -> str:
    """Write the event stream as a Chrome-trace JSON object
    (``{"traceEvents": [...]}``) loadable in ``chrome://tracing`` /
    Perfetto; returns ``path``. ``events`` defaults to the live
    registry's stream — pass ``report.load_events(sink)`` to convert a
    JSONL sink from an earlier run."""
    trace = {
        "traceEvents": to_trace_events(events),
        "displayTimeUnit": "ms",
    }
    with open(path, "w") as f:
        json.dump(trace, f, default=str)
    return path
