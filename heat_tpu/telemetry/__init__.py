"""heat_tpu.telemetry — runtime observability for distributed ops.

The reference framework's communication was explicit (every byte moved
through a hand-written MPI call, reference heat/core/communication.py), so
observability came for free by reading the source. On TPU the collectives
are emitted invisibly by XLA from sharding annotations; this package is the
measurement substrate that makes them visible again:

* a process-global :class:`Telemetry` registry — counters plus a JSON-lines
  event sink — enabled via :func:`enable` or ``HEAT_TPU_TELEMETRY=1``
  (sink path via ``HEAT_TPU_TELEMETRY_SINK``);
* an op/**span** API (``with span("resplit", bytes=...)``) with correct
  async-dispatch semantics: spans `jax.block_until_ready` their registered
  outputs before stopping the clock, so a span measures device work, not
  Python dispatch;
* **compile-time accounting** kept separate from execute time:
  :func:`measure_compile` times the AOT ``jit(f).lower(...).compile()``
  path for pure jitted functions, and :class:`CompileWatcher` accumulates
  the XLA trace/lower/backend-compile durations (via `jax.monitoring`)
  that occur inside arbitrary host-side code — the same quantities the AOT
  path measures, attributed to a first call;
* an analytic **collective cost model** (:mod:`.collectives`) giving
  bytes-on-the-wire for relayouts and the hand-scheduled kernels;
* an **HLO collective auditor** (:mod:`.hlo`) that closes the
  predicted-vs-emitted loop: lower-and-compile a jitted computation,
  parse the ground-truth collectives XLA emitted, and flag drift against
  the analytic prediction (``audit=`` on resplit/qr/cdist, or globally
  via ``HEAT_TPU_HLO_AUDIT=1``);
* per-device **memory watermarks** (:mod:`.memory`);
* a :mod:`.report` summarizer aggregating events into the JSON shape the
  benchmark harness emits;
* a :mod:`.trace` exporter turning the event stream into
  Chrome-trace/Perfetto JSON (:func:`export_trace`), plus a
  ``python -m heat_tpu.telemetry.audit`` CLI.

Disabled (the default), every hook compiles down to one module-flag check:
``span()`` returns a shared no-op context manager, call sites skip field
construction, and no listener work is done — the overhead budget is "not
measurable" (<2% on the tier-1 suite, pinned by the acceptance run).
"""

from __future__ import annotations

import json
import os
import threading
import time
from collections import defaultdict
from typing import Any, Dict, IO, Iterable, List, Optional, Union

import jax

from heat_tpu import _knobs as knobs

from . import collectives  # noqa: F401  (re-exported submodule)

__all__ = [
    "Telemetry",
    "CompileWatcher",
    "enable",
    "disable",
    "enabled",
    "flush",
    "get_registry",
    "span",
    "trace_event",
    "op_cost",
    "measure_compile",
    "collectives",
    "hlo",
    "memory",
    "report",
    "trace",
    "export_trace",
]

# Module-level fast path: every instrumentation site guards on this single
# boolean, so the disabled overhead is one attribute load + branch.
_ENABLED = False

_REGISTRY: Optional["Telemetry"] = None
_REGISTRY_LOCK = threading.Lock()

# Span nesting is tracked per thread (spans opened on worker threads must
# not see each other as parents).
_STATE = threading.local()


def _stack() -> list:
    s = getattr(_STATE, "stack", None)
    if s is None:
        s = _STATE.stack = []
    return s


class Telemetry:
    """Process-global registry: counters, high-water marks, and an event
    stream with an optional JSON-lines sink.

    Events are dicts with at least ``ts`` (unix seconds), ``kind`` and
    ``name``; spans add ``seconds``, ``depth``, ``parent`` and their user
    fields. The in-memory list and the sink receive identical records.
    """

    def __init__(self):
        self._lock = threading.Lock()
        self.counters: Dict[str, float] = defaultdict(float)
        self.watermarks: Dict[str, float] = {}
        self.events: List[dict] = []
        self._sink: Optional[IO[str]] = None
        self._sink_path: Optional[str] = None
        self._owns_sink = False

    # -- sink ----------------------------------------------------------------

    def attach_sink(self, sink: Union[str, IO[str]]) -> None:
        """Attach a JSONL sink: a path (opened in append mode, owned and
        closed by the registry) or any writable text file object."""
        self.close_sink()
        if isinstance(sink, (str, os.PathLike)):
            self._sink = open(sink, "a")
            self._sink_path = os.fspath(sink)
            self._owns_sink = True
        else:
            self._sink = sink
            self._sink_path = getattr(sink, "name", None)
            self._owns_sink = False

    def close_sink(self) -> None:
        if self._sink is not None and self._owns_sink:
            try:
                self._sink.close()
            except OSError:
                pass
        self._sink = None
        self._sink_path = None
        self._owns_sink = False

    @property
    def sink_path(self) -> Optional[str]:
        return self._sink_path

    # -- recording -----------------------------------------------------------

    def emit(self, kind: str, name: str, **fields: Any) -> dict:
        """Record one event (and write it to the sink, if attached)."""
        ev = {"ts": time.time(), "kind": kind, "name": name}
        ev.update(fields)
        with self._lock:
            self.events.append(ev)
            if self._sink is not None:
                try:
                    self._sink.write(json.dumps(ev, default=str) + "\n")
                    self._sink.flush()
                except (OSError, ValueError):
                    # a dead sink must never take the workload down —
                    # detach it fully (close an owned handle, clear the
                    # path) so no fd leaks and snapshot() stops naming a
                    # sink that no longer records
                    if self._owns_sink:
                        try:
                            self._sink.close()
                        except OSError:
                            pass
                    self._sink = None
                    self._sink_path = None
                    self._owns_sink = False
        return ev

    def add(self, counter: str, delta: float = 1.0) -> None:
        with self._lock:
            self.counters[counter] += delta

    def high_water(self, key: str, value: float) -> None:
        """Record ``value`` if it exceeds the stored mark for ``key``."""
        with self._lock:
            if value > self.watermarks.get(key, float("-inf")):
                self.watermarks[key] = value

    def snapshot(self) -> dict:
        with self._lock:
            return {
                "counters": dict(self.counters),
                "watermarks": dict(self.watermarks),
                "n_events": len(self.events),
                "sink": self._sink_path,
            }

    def clear(self, kinds: Optional[Iterable[str]] = None) -> None:
        """Drop counters, watermarks and in-memory events (the sink file, if
        any, is left as-is — it is an append-only log). With ``kinds``,
        drop only in-memory events of those kinds and keep everything else
        — e.g. ``clear(kinds=("span",))`` discards warmup spans while
        preserving the ``compile`` and ``collective_trace`` records that
        only fire while a program is first traced."""
        with self._lock:
            if kinds is not None:
                drop = set(kinds)
                self.events[:] = [
                    e for e in self.events if e.get("kind") not in drop
                ]
                return
            self.counters.clear()
            self.watermarks.clear()
            self.events.clear()


def get_registry() -> Telemetry:
    global _REGISTRY
    if _REGISTRY is None:
        with _REGISTRY_LOCK:
            if _REGISTRY is None:
                _REGISTRY = Telemetry()
    return _REGISTRY


# -- enable / disable ---------------------------------------------------------


def enabled() -> bool:
    """Whether telemetry is recording. Instrumentation sites branch on this
    before building field dicts, so the disabled cost is one check."""
    return _ENABLED


def enable(sink: Union[str, IO[str], None] = None) -> Telemetry:
    """Turn recording on. ``sink`` (or ``HEAT_TPU_TELEMETRY_SINK``) names a
    JSONL file to stream events to; with neither, events accumulate in
    memory only. Returns the registry."""
    global _ENABLED
    reg = get_registry()
    if sink is None:
        sink = knobs.raw("HEAT_TPU_TELEMETRY_SINK") or None
    if sink is not None:
        try:
            reg.attach_sink(sink)
        except OSError as e:
            # same contract as a sink dying mid-run: telemetry must never
            # take the workload down (enable() runs at `import heat_tpu`
            # when HEAT_TPU_TELEMETRY=1) — record in memory only
            import warnings

            warnings.warn(
                f"heat_tpu.telemetry: cannot open sink {sink!r} ({e}); "
                "recording in memory only"
            )
    _install_monitoring_listener()
    _install_atexit()
    _ENABLED = True
    return reg


def disable() -> None:
    """Turn recording off and close an owned sink. Counters and in-memory
    events are kept (call ``get_registry().clear()`` to drop them)."""
    global _ENABLED
    _ENABLED = False
    get_registry().close_sink()


# -- crash safety --------------------------------------------------------------
# Counters and watermarks live only in process memory: a hard abort used to
# lose them entirely (events stream to the sink per emit, but the aggregate
# state did not). flush() writes one "final" record carrying the full
# counter/watermark snapshot; it runs at interpreter exit (atexit, installed
# by enable()) and on every resilience escalation (guard.py), so the state
# of a dying run is on disk before the stack unwinds.

_atexit_installed = False


def flush(reason: str = "flush") -> Optional[dict]:
    """Write a ``final`` event carrying the current counter/watermark
    snapshot to the registry (and hence the JSONL sink, which is flushed
    per emit). Safe to call repeatedly; no-op when disabled."""
    if not _ENABLED:
        return None
    reg = get_registry()
    snap = reg.snapshot()
    return reg.emit(
        "final", reason,
        counters=snap["counters"], watermarks=snap["watermarks"],
    )


def _install_atexit() -> None:
    global _atexit_installed
    if _atexit_installed:
        return
    import atexit

    atexit.register(_atexit_flush)
    _atexit_installed = True


def _atexit_flush() -> None:  # pragma: no cover — exercised via subprocess
    try:
        if _ENABLED and get_registry()._sink is not None:
            flush("atexit")
        get_registry().close_sink()
    except Exception:
        pass


# -- span API -----------------------------------------------------------------


class _NoopSpan:
    """Shared do-nothing span returned while telemetry is disabled."""

    __slots__ = ()

    def __enter__(self):
        return self

    def __exit__(self, *exc):
        return False

    def add_fields(self, **fields):
        return self

    def output(self, value):
        return value


_NOOP_SPAN = _NoopSpan()


class Span:
    """A timed region with async-correct semantics.

    Register device outputs with :meth:`output`; on exit the span calls
    ``jax.block_until_ready`` on them **before** stopping the clock, so the
    recorded ``seconds`` covers the dispatched device work — without it,
    JAX's async dispatch would credit the work to whoever reads the result
    next. Compile time is deliberately NOT separated here (a span times what
    actually happened); use :func:`measure_compile`/:class:`CompileWatcher`
    for the compile/execute split.
    """

    __slots__ = ("name", "fields", "_outputs", "_t0", "_wall0")

    def __init__(self, name: str, fields: Dict[str, Any]):
        self.name = name
        self.fields = fields
        self._outputs: List[Any] = []
        self._t0 = 0.0
        self._wall0 = 0.0

    def add_fields(self, **fields: Any) -> "Span":
        self.fields.update(fields)
        return self

    def output(self, value):
        """Register a device value to block on at exit; returns it."""
        self._outputs.append(value)
        return value

    def __enter__(self) -> "Span":
        _stack().append(self)
        # wall-clock start recorded alongside the perf_counter duration
        # clock: deriving the start as `ts - seconds` would mix the two
        # clocks and break nesting containment in the trace export at
        # µs scale (trace.py anchors slices on start_ts)
        self._wall0 = time.time()
        self._t0 = time.perf_counter()
        return self

    def __exit__(self, exc_type, exc, tb):
        if exc_type is None and self._outputs:
            jax.block_until_ready(self._outputs)
        dt = time.perf_counter() - self._t0
        stack = _stack()
        if stack and stack[-1] is self:
            stack.pop()
        parent = stack[-1].name if stack else None
        reg = get_registry()
        if exc_type is not None:
            reg.emit(
                "span_error", self.name, seconds=dt, start_ts=self._wall0,
                error=repr(exc), **self.fields
            )
            return False
        reg.add(f"span.{self.name}.count", 1)
        reg.add(f"span.{self.name}.seconds", dt)
        b = self.fields.get("bytes")
        if b:
            reg.add(f"span.{self.name}.bytes", b)
        reg.emit(
            "span", self.name, seconds=dt, depth=len(stack), parent=parent,
            start_ts=self._wall0, **self.fields,
        )
        return False


def span(name: str, **fields: Any):
    """Open a telemetry span (context manager). Disabled: returns a shared
    no-op object — zero allocation, fields ignored."""
    if not _ENABLED:
        return _NOOP_SPAN
    return Span(name, fields)


def op_cost(cost_fn, *cost_args, audit: bool = False, use_global: bool = True):
    """Shared preamble for instrumented op sites; returns
    ``(cost, fields, do_audit)``:

    * ``cost`` — the analytic :class:`~.collectives.CollectiveCost`,
      computed only when recording or auditing will consume it (None on
      the cold path, preserving the one-flag-check disabled contract);
    * ``fields`` — the span field dict (``cost.as_fields()`` when
      recording, ``{}`` otherwise);
    * ``do_audit`` — whether this call should run the HLO audit:
      explicit ``audit=True``, plus the global ``HEAT_TPU_HLO_AUDIT``
      opt-in unless ``use_global=False`` (the ``_relayout`` primitive
      opts out so an op-level audit is never doubled).

    Every instrumented site goes through here so the flag semantics live
    in ONE place — a new op site cannot silently pick a diverged variant.
    """
    do_audit = audit or (use_global and hlo.audit_enabled())
    cost = cost_fn(*cost_args) if (_ENABLED or do_audit) else None
    fields = cost.as_fields() if (_ENABLED and cost is not None) else {}
    return cost, fields, do_audit


def trace_event(name: str, **fields: Any) -> None:
    """Record that a collective was *traced* (a `shard_map`/jit cache miss
    compiled a program containing it). Fired from the communication layer's
    collective wrappers — trace-time only, so a hot cached program emits
    nothing. No-op when disabled."""
    if not _ENABLED:
        return
    reg = get_registry()
    reg.add(f"traced.{name}", 1)
    reg.emit("collective_trace", name, **fields)


# -- compile-time accounting --------------------------------------------------

# jax.monitoring has no unregister API, so one process-lifetime listener is
# installed on first use and gated on the enabled flag / active watchers.
_MONITORING_PREFIX = "/jax/core/compile/"
_BACKEND_COMPILE_EVENT = "/jax/core/compile/backend_compile_duration"
_listener_installed = False
_ACTIVE_WATCHERS: List["CompileWatcher"] = []


def _install_monitoring_listener() -> None:
    global _listener_installed
    if _listener_installed:
        return
    try:
        from jax import monitoring as _monitoring

        _monitoring.register_event_duration_secs_listener(_on_duration_event)
        _listener_installed = True
    except Exception:  # pragma: no cover — very old jax without monitoring
        pass


def _on_duration_event(name: str, secs: float, **kw) -> None:
    if not name.startswith(_MONITORING_PREFIX):
        return
    stage = name[len(_MONITORING_PREFIX):]  # e.g. "backend_compile_duration"
    for w in _ACTIVE_WATCHERS:
        w._record(stage, secs)
    if not _ENABLED:
        return
    reg = get_registry()
    reg.add(f"compile.{stage}", secs)
    if name == _BACKEND_COMPILE_EVENT:
        reg.emit("compile", "backend_compile", seconds=secs)


class CompileWatcher:
    """Accumulate XLA compile-pipeline durations (jaxpr trace, MLIR
    lowering, backend compile — the same stages ``jit(f).lower(x).compile()``
    runs ahead of time) that occur while the context is open.

    For host-side thunks that cannot be AOT-lowered as a whole (e.g. a
    benchmark ``fit()`` mixing device ops with host logic), wrapping the
    first call in a watcher yields the compile seconds *separately* from
    the wall clock, instead of the reference harness's compile+execute
    blend. Works whether or not telemetry recording is enabled.
    """

    def __init__(self):
        self.stages: Dict[str, float] = defaultdict(float)
        self.counts: Dict[str, int] = defaultdict(int)
        self.events = 0

    @property
    def seconds(self) -> float:
        """Total compile-pipeline seconds observed (all stages)."""
        return sum(self.stages.values())

    @property
    def backend_seconds(self) -> float:
        return self.stages.get("backend_compile_duration", 0.0)

    @property
    def backend_compiles(self) -> int:
        """Number of backend-compile events in the window — i.e. how many
        distinct XLA programs were built (the fusion microbenchmark's
        dispatch-count oracle: an N-op chain fused into one program shows
        1 here where eager shows ~N)."""
        return self.counts.get("backend_compile_duration", 0)

    def _record(self, stage: str, secs: float) -> None:
        self.stages[stage] += secs
        self.counts[stage] += 1
        self.events += 1

    def __enter__(self) -> "CompileWatcher":
        _install_monitoring_listener()
        _ACTIVE_WATCHERS.append(self)
        return self

    def __exit__(self, *exc):
        try:
            _ACTIVE_WATCHERS.remove(self)
        except ValueError:
            pass
        return False


def measure_compile(fn, *args, **kwargs):
    """AOT-compile ``fn(*args, **kwargs)`` and time it: returns
    ``(seconds, compiled)`` where ``compiled`` is the executable from
    ``jit(fn).lower(...).compile()``. The clock covers trace + lower +
    backend compile and **no execution** — the honest ``compile_seconds``
    for a pure jittable function (first-full-call timing, by contrast,
    blends in one execution). Emits a ``compile`` event when enabled.

    ``fn`` may be a plain callable or an already-jitted function.
    """
    jitted = fn if hasattr(fn, "lower") else jax.jit(fn)
    t0 = time.perf_counter()
    compiled = jitted.lower(*args, **kwargs).compile()
    dt = time.perf_counter() - t0
    if _ENABLED:
        get_registry().emit(
            "compile", getattr(fn, "__name__", repr(fn)), seconds=dt, mode="aot"
        )
    return dt, compiled


# memory/report/hlo/trace/cluster import the registry machinery above,
# so they load last.
from . import memory  # noqa: E402,F401
from . import report  # noqa: E402,F401
from . import hlo  # noqa: E402,F401
from . import trace  # noqa: E402,F401
from . import cluster  # noqa: E402,F401

export_trace = trace.export_trace
SLO = cluster.SLO
summarize_cluster = cluster.summarize_cluster

# Environment activation: HEAT_TPU_TELEMETRY=1 turns recording on at import
# (heat_tpu/__init__ imports this package, so `import heat_tpu` suffices).
if knobs.raw("HEAT_TPU_TELEMETRY", "").strip().lower() in (
    "1", "true", "yes", "on",
):
    enable()
