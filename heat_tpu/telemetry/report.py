"""Aggregate telemetry events into the benchmark harness's JSON shape.

``benchmarks/_harness.py`` emits one JSON object per trial plus a summary
dict; :func:`summarize` produces the ``telemetry`` block that the summary
(and hence the committed ``BENCH_*`` files) gains when telemetry is on —
per-phase compile/execute/bytes-moved columns keyed by span name:

.. code-block:: json

    {"phases": {"resplit": {"calls": 2, "execute_seconds": 0.01,
                            "bytes_moved": 14336}},
     "compile_seconds": 0.4, "compile_events": 3,
     "traced_collectives": {"all_gather": 1},
     "peak_live_bytes": 1048576, "events": 17}

When the HLO collective auditor recorded any ``hlo_audit`` events
(``--audit`` / ``HEAT_TPU_HLO_AUDIT=1``), the summary also gains an
``hlo_collectives`` section of *ground-truth* emitted counts and wire
bytes next to the analytic ``phases`` — see docs/BENCHMARKS.md for the
field schema.
"""

from __future__ import annotations

import json
from typing import Iterable, List, Optional

__all__ = ["load_events", "summarize", "summarize_cluster", "bench_fields"]


def load_events(path: str) -> List[dict]:
    """Read a JSONL event sink back into a list of event dicts (skips
    blank/truncated lines — the sink is append-only across runs)."""
    out: List[dict] = []
    with open(path) as f:
        for line in f:
            line = line.strip()
            if not line:
                continue
            try:
                out.append(json.loads(line))
            except json.JSONDecodeError:
                continue
    return out


def summarize(
    events: Optional[Iterable[dict]] = None,
    watermarks: Optional[dict] = None,
) -> dict:
    """Aggregate events (default: the live registry's) into the per-phase
    summary block documented in the module docstring. Only top-level
    (``depth == 0``) spans become phase rows: a ``resplit`` and the
    ``relayout`` primitive it wraps carry the same analytic cost over the
    same wall-clock window, so counting both would double every byte and
    second a consumer sums across phases. Nesting stays visible in the raw
    stream (each span event carries ``depth``/``parent``); a ``relayout``
    invoked outside any op span is depth 0 and still gets its own row."""
    live = events is None
    if events is None:
        from . import get_registry

        reg = get_registry()
        events = list(reg.events)
        if watermarks is None:
            watermarks = dict(reg.watermarks)

    phases: dict = {}
    serve_rows: dict = {}
    serve_span: list = [None, None]  # [first ts, last ts] of serve traffic
    pc_retraces: dict = {}
    res_events: dict = {}
    at_events: dict = {}
    sn_events: dict = {}
    as_events: dict = {}
    sp_events: dict = {}
    st_events: dict = {}
    tr_spans = 0
    tr_ingress = 0
    st_rows = 0
    st_read_seconds = 0.0
    st_swap_seconds: list = []
    st_roll_seconds: list = []
    st_compiles = 0
    st_max_version = 0
    plan_counts: dict = {}
    hier_rows: dict = {}
    pipe_rows: dict = {}
    pipe_gather_bytes = 0
    pipe_gather_events = 0
    plan_last: Optional[dict] = None
    plan_wire = 0
    pc_evictions = 0
    compile_seconds = 0.0
    compile_events = 0
    traced: dict = {}
    hlo_sites: dict = {}
    hlo_audits = 0
    hlo_drift = 0
    n = 0
    for ev in events:
        n += 1
        kind = ev.get("kind")
        if kind == "span":
            if int(ev.get("depth", 0) or 0) != 0:
                continue
            row = phases.setdefault(
                ev.get("name"),
                {"calls": 0, "execute_seconds": 0.0, "bytes_moved": 0},
            )
            row["calls"] += 1
            row["execute_seconds"] += float(ev.get("seconds", 0.0))
            row["bytes_moved"] += int(ev.get("bytes", 0) or 0)
            if ev.get("collective"):
                row["collective"] = ev["collective"]
        elif kind == "compile":
            compile_seconds += float(ev.get("seconds", 0.0))
            compile_events += 1
        elif kind == "collective_trace":
            name = ev.get("name")
            traced[name] = traced.get(name, 0) + 1
            if ev.get("hier"):
                # tiered-lowering rows (ISSUE 15): per wrapper, how many
                # hierarchical programs were traced, on what topology,
                # and the analytic per-tier split (total vs DCN bytes —
                # the cross-node stage the DCN premium prices)
                hrow = hier_rows.setdefault(
                    name,
                    {"traced": 0, "topology": ev.get("hier"),
                     "bytes": 0, "dcn_bytes": 0, "wire": {}},
                )
                hrow["traced"] += 1
                hrow["topology"] = ev.get("hier")
                hrow["bytes"] += int(ev.get("bytes", 0) or 0)
                hrow["dcn_bytes"] += int(ev.get("dcn_bytes", 0) or 0)
                w = ev.get("wire") or "off"
                hrow["wire"][w] = hrow["wire"].get(w, 0) + 1
            if name == "pipeline_tick":
                # per-tick schedule spans (ISSUE 19): one event per tick
                # per traced pipeline.step program — the measured bubble
                # accounting the CI gate reconciles against the analytic
                # ScheduleTable, plus the hop wire/DCN volume per tick
                prow = pipe_rows.setdefault(
                    ev.get("schedule") or "?",
                    {"ticks": 0, "fwd": 0, "bwd": 0, "bubble_cells": 0,
                     "steady_bubble_cells": 0, "stages": 0,
                     "phases": {}, "hop_bytes": 0, "hop_dcn_bytes": 0},
                )
                prow["ticks"] += 1
                prow["stages"] = int(ev.get("stages", 0) or 0)
                prow["fwd"] += int(ev.get("n_fwd", 0) or 0)
                prow["bwd"] += int(ev.get("n_bwd", 0) or 0)
                bub = int(ev.get("bubble", 0) or 0)
                prow["bubble_cells"] += bub
                ph = ev.get("phase") or "?"
                prow["phases"][ph] = prow["phases"].get(ph, 0) + 1
                if ph == "steady":
                    prow["steady_bubble_cells"] += bub
                hops = ev.get("hops")
                hops = 1 if hops is None else int(hops)
                prow["hop_bytes"] += hops * int(ev.get("hop_bytes", 0) or 0)
                prow["hop_dcn_bytes"] += hops * int(
                    ev.get("hop_dcn_bytes", 0) or 0
                )
            elif name == "pipeline_gather":
                pipe_gather_bytes += int(ev.get("bytes", 0) or 0)
                pipe_gather_events += 1
        elif kind == "program_cache":
            if ev.get("event") == "retrace":
                name = ev.get("name")
                pc_retraces[name] = pc_retraces.get(name, 0) + 1
            elif ev.get("event") == "eviction":
                pc_evictions += int(ev.get("count", 1) or 1)
        elif kind == "resilience":
            what = ev.get("event") or "event"
            res_events[what] = res_events.get(what, 0) + 1
        elif kind == "autotune":
            what = ev.get("event") or "event"
            at_events[what] = at_events.get(what, 0) + 1
        elif kind == "serve_net":
            what = ev.get("event") or "event"
            sn_events[what] = sn_events.get(what, 0) + 1
        elif kind == "autoscale":
            what = ev.get("event") or "event"
            as_events[what] = as_events.get(what, 0) + 1
        elif kind == "trace_span":
            # request-trace hops (ISSUE 17): every hop pairs with the
            # `tracing.spans` counter, every ingress hop with
            # `tracing.sampled` — the live/offline reconciliation pair
            tr_spans += 1
            if ev.get("ingress"):
                tr_ingress += 1
        elif kind == "sparse":
            what = ev.get("event") or "event"
            sp_events[what] = sp_events.get(what, 0) + 1
        elif kind == "streaming":
            what = ev.get("event") or "event"
            st_events[what] = st_events.get(what, 0) + 1
            if what == "stream_chunk":
                st_rows += int(ev.get("rows", 0) or 0)
                st_read_seconds += float(ev.get("seconds", 0.0) or 0.0)
            elif what == "version_swap":
                st_swap_seconds.append(float(ev.get("seconds", 0.0) or 0.0))
                st_compiles += int(ev.get("backend_compiles", 0) or 0)
                st_max_version = max(
                    st_max_version, int(ev.get("version", 0) or 0)
                )
            elif what == "roll_step":
                st_roll_seconds.append(float(ev.get("seconds", 0.0) or 0.0))
        elif kind == "relayout_plan":
            p = ev.get("plan") or ev.get("name")
            plan_counts[p] = plan_counts.get(p, 0) + 1
            plan_wire += int(ev.get("predicted_bytes", 0) or 0)
            plan_last = {
                k: ev.get(k)
                for k in ("plan", "gshape", "src_split", "dst_split",
                          "chunks", "stages", "predicted_bytes",
                          "temp_bytes", "budget", "reason")
                if k in ev
            }
        elif kind in ("serve_request", "serve_batch", "serve"):
            what = ev.get("event")
            if kind == "serve" and what not in ("shed", "batch_failed"):
                continue  # warmup/degrade events are not per-endpoint rows
            row = serve_rows.setdefault(
                ev.get("name"),
                {"requests": 0, "errors": 0, "shed": 0, "batches": 0,
                 "rows": 0, "padded_rows": 0, "latencies": []},
            )
            ts = ev.get("ts")
            if ts is not None:
                if serve_span[0] is None or ts < serve_span[0]:
                    serve_span[0] = ts
                if serve_span[1] is None or ts > serve_span[1]:
                    serve_span[1] = ts
                t0, t1 = row.get("_ts0"), row.get("_ts1")
                if t0 is None or ts < t0:
                    row["_ts0"] = ts
                if t1 is None or ts > t1:
                    row["_ts1"] = ts
            if kind == "serve_request":
                row["requests"] += 1
                if not ev.get("ok", True):
                    row["errors"] += 1
                row["latencies"].append(float(ev.get("seconds", 0.0)))
            elif kind == "serve_batch":
                row["batches"] += 1
                row["rows"] += int(ev.get("rows", 0) or 0)
                row["padded_rows"] += int(ev.get("padded_rows", 0) or 0)
            elif what == "shed":
                row["shed"] += 1
            else:  # batch_failed
                row["errors"] += int(ev.get("requests", 1) or 1)
        elif kind == "hlo_audit":
            hlo_audits += 1
            drift = int(ev.get("drift", 0) or 0)
            hlo_drift += drift
            row = hlo_sites.setdefault(
                ev.get("name"),
                {"audits": 0, "instructions": {}, "wire_bytes": {},
                 "emitted_bytes": 0, "predicted_bytes": 0, "drift": 0},
            )
            row["audits"] += 1
            row["drift"] += drift
            for op, cnt in (ev.get("ops") or {}).items():
                row["instructions"][op] = row["instructions"].get(op, 0) + cnt
            for op, b in (ev.get("bytes_by_op") or {}).items():
                row["wire_bytes"][op] = row["wire_bytes"].get(op, 0) + int(b)
            row["emitted_bytes"] += int(ev.get("emitted_bytes", 0) or 0)
            row["predicted_bytes"] += int(ev.get("predicted_bytes", 0) or 0)
    for row in phases.values():
        row["execute_seconds"] = round(row["execute_seconds"], 6)

    out = {
        "phases": phases,
        "compile_seconds": round(compile_seconds, 6),
        "compile_events": compile_events,
        "traced_collectives": traced,
        "events": n,
    }
    if hier_rows:
        # hierarchy view (core/topology.py, ISSUE 15): per tiered
        # wrapper, traced-program counts, the (node x local) topology,
        # the analytic total-vs-DCN byte split, and the cross-tier wire
        # modes seen. Absent when no tiered program was traced, so flat
        # summaries keep their exact shape.
        out["hierarchy"] = {
            "collectives": hier_rows,
            "dcn_bytes": sum(r["dcn_bytes"] for r in hier_rows.values()),
            "bytes": sum(r["bytes"] for r in hier_rows.values()),
        }
    if pipe_rows or pipe_gather_events:
        # pipeline view (parallel/pipeline.py, ISSUE 19): per traced
        # schedule, tick/action/bubble tallies (steady_bubble_cells is
        # the schedule-shaped figure 1f1b cuts), per-tick hop wire and
        # DCN bytes, and the in-stage weight-gather stream. Absent when
        # no pipeline program was traced, so other summaries keep shape.
        out["pipeline"] = {
            "schedules": pipe_rows,
            "gather_bytes": pipe_gather_bytes,
            "gather_events": pipe_gather_events,
        }
    if plan_counts:
        # relayout-planner decisions (core/relayout_planner.py): how many
        # relayouts planned per plan kind, the summed predicted wire
        # bytes, and the last full decision payload. Absent when the
        # planner never armed, so unplanned summaries keep their shape.
        out["relayout_plan"] = {
            "plans": plan_counts,
            "predicted_bytes": plan_wire,
            "last": plan_last,
        }
    if serve_rows:
        # serving view (heat_tpu/serve, ISSUE 8): per-endpoint QPS and
        # latency percentiles over the event window, batch occupancy,
        # shed/error tallies. QPS spans the endpoint's own first→last
        # event; exact percentiles here (the offline aggregate holds the
        # full latency list — the server's live histogram quantizes).
        # Absent when no serve event was recorded, so non-serving
        # summaries keep their exact shape.
        window = (
            (serve_span[1] - serve_span[0])
            if serve_span[0] is not None else 0.0
        )
        eps = {}
        for name, row in serve_rows.items():
            lats = sorted(row.pop("latencies"))
            # per-endpoint QPS over the ENDPOINT'S own first→last event
            # span (two tenants active at different times must not dilute
            # each other's rate)
            ep_window = (
                (row.pop("_ts1") - row.pop("_ts0"))
                if "_ts0" in row else 0.0
            )

            def q(p, _l=lats):
                return _l[min(len(_l) - 1, int(p * len(_l)))] if _l else None

            out_row = dict(row)
            if lats:
                out_row["p50_s"] = round(q(0.50), 6)
                out_row["p95_s"] = round(q(0.95), 6)
                out_row["p99_s"] = round(q(0.99), 6)
                out_row["mean_s"] = round(sum(lats) / len(lats), 6)
            if row["requests"] and ep_window > 0:
                out_row["qps"] = round(row["requests"] / ep_window, 2)
            if row["batches"]:
                denom = row["rows"] + row["padded_rows"]
                out_row["mean_batch_rows"] = round(
                    row["rows"] / row["batches"], 3
                )
                out_row["occupancy"] = round(
                    row["rows"] / denom if denom else 1.0, 4
                )
            eps[name] = out_row
        out["serving"] = {
            "endpoints": eps,
            "requests": sum(r["requests"] for r in serve_rows.values()),
            "window_seconds": round(window, 4),
        }
        if watermarks and "serve.queue_depth" in watermarks:
            out["serving"]["peak_queue_depth"] = int(
                watermarks["serve.queue_depth"]
            )
    if hlo_audits:
        # ground-truth emitted collectives (telemetry/hlo.py) next to the
        # analytic phases — only present when the auditor actually ran, so
        # non-audited summaries keep their exact shape
        out["hlo_collectives"] = {
            "audits": hlo_audits,
            "drift": hlo_drift,
            "sites": hlo_sites,
        }
    # compiled-program registry counters (core/program_cache.py): live
    # summaries read the registry directly (hit/miss/eviction totals plus
    # per-site retrace counts); offline summaries reconstruct retraces
    # from the recorded instant events. Absent entirely when the registry
    # never ran, so pre-existing summary shapes are unchanged.
    if live:
        from ..core import program_cache as _pc

        pc = _pc.stats()
        if pc["hits"] or pc["misses"]:
            out["program_cache"] = pc
        # fusion-engine counters (core/fusion.py): deferred elementwise
        # ops, chain flushes, mean nodes per flushed program, eager
        # fallbacks, plus the Fusion 2.0 absorption counters —
        # reductions_absorbed (chains consumed by a reduce/moments
        # program) and epilogues_grafted (elementwise tails grafted onto
        # kernel nodes). Absent when no op ran deferred, so fusion-off
        # summaries keep their exact shape.
        from ..core import fusion as _fz

        fz = _fz.stats()
        if (
            fz["deferred"] or fz["flushes"] or fz["fallbacks"]
            or fz["reductions_absorbed"] or fz["epilogues_grafted"]
        ):
            out["fusion"] = fz
    elif pc_retraces or pc_evictions:
        out["program_cache"] = {
            "retraces": pc_retraces,
            "evictions": pc_evictions,
        }
    # resilience counters (heat_tpu/resilience, ISSUE 5): live summaries
    # read the registry's aggregate counters (retries/transient_faults/
    # gave_up/faults_injected/...); offline summaries reconstruct per-event
    # counts (retry/inject/gave_up/...) from the recorded instant events.
    # Absent entirely when the subsystem never fired, so fault-free
    # summaries keep their exact shape (the chaos CI step's zero-overhead
    # oracle relies on that).
    if live:
        from . import get_registry as _get_registry

        res = {
            k[len("resilience."):]: (int(v) if float(v).is_integer() else v)
            for k, v in _get_registry().counters.items()
            if k.startswith("resilience.")
        }
        if res:
            out["resilience"] = res
    elif res_events:
        # event name -> live counter name, so offline and live blocks
        # carry the SAME keys; transient_faults is derived (every caught
        # transient emitted either a retry or a gave_up event)
        rename = {
            "retry": "retries",
            "inject": "faults_injected",
            "checkpoint_save": "checkpoints_saved",
        }
        res = {rename.get(k, k): v for k, v in res_events.items()}
        transients = res.get("retries", 0) + res.get("gave_up", 0)
        if transients:
            res["transient_faults"] = transients
        out["resilience"] = res
    # autotune counters (heat_tpu/autotune, ISSUE 11): live summaries
    # read the registry's aggregate counters (trials/db_hits/stores/
    # adopted/...); offline summaries reconstruct the SAME block from the
    # recorded instant events — every counter increments exactly once
    # alongside its event, so live == offline (the resilience
    # reconciliation contract from PR 5, pinned in tests/test_autotune.py).
    # Absent entirely when the tuner never fired, so untuned summary
    # shapes are unchanged.
    if live:
        from . import get_registry as _get_registry

        at = {
            k[len("autotune."):]: (int(v) if float(v).is_integer() else v)
            for k, v in _get_registry().counters.items()
            if k.startswith("autotune.")
        }
        if at:
            out["autotune"] = at
    elif at_events:
        from heat_tpu.autotune import EVENT_COUNTER as _at_names

        out["autotune"] = {
            _at_names.get(k, k): v for k, v in at_events.items()
        }
    # network-serving-tier counters (heat_tpu/serve/net, ISSUE 12): the
    # router/pool/transport layer emits one `serve_net` event per counter
    # increment (serve/net/events.py), so live summaries (registry
    # counters) and offline sink replays reconstruct the SAME
    # `serving_net` block — the PR 5/PR 11 reconciliation contract.
    # Absent entirely when no router/pool ran, so single-process serving
    # summaries keep their exact shape.
    if live:
        from . import get_registry as _get_registry

        sn = {
            k[len("serve_net."):]: (int(v) if float(v).is_integer() else v)
            for k, v in _get_registry().counters.items()
            if k.startswith("serve_net.")
        }
        if sn:
            out["serving_net"] = sn
    elif sn_events:
        from heat_tpu.serve.net.events import EVENT_COUNTER as _sn_names

        out["serving_net"] = {
            _sn_names.get(k, k): v for k, v in sn_events.items()
        }
    # autoscaling-control-plane counters (serve/net/controller, ISSUE 20):
    # one `autoscale` event per `autoscale.<name>` counter increment, same
    # live/offline reconciliation contract as serving_net above. Absent
    # when no controller ran.
    if live:
        from . import get_registry as _get_registry

        asc = {
            k[len("autoscale."):]: int(v)
            for k, v in _get_registry().counters.items()
            if k.startswith("autoscale.")
        }
        if asc:
            out["autoscale"] = asc
    elif as_events:
        from heat_tpu.serve.net.controller import EVENT_COUNTER as _as_names

        out["autoscale"] = {
            _as_names.get(k, k): v for k, v in as_events.items()
        }
    # request-tracing counters (ISSUE 17): one `trace_span` event per
    # `tracing.spans` increment, one ingress span per `tracing.sampled`,
    # so live summaries and offline sink replays reconstruct the SAME
    # `tracing` block. Absent when no request was traced, so untraced
    # summary shapes are unchanged — and the CI off-run pins exactly
    # this absence.
    if live:
        from . import get_registry as _get_registry

        _c = _get_registry().counters
        tr = {
            "sampled": int(_c.get("tracing.sampled", 0)),
            "spans": int(_c.get("tracing.spans", 0)),
        }
        if tr["sampled"] or tr["spans"]:
            out["tracing"] = tr
    elif tr_spans:
        out["tracing"] = {"sampled": tr_ingress, "spans": tr_spans}
    # sparse-container counters (heat_tpu/sparse, ISSUE 13): every op
    # pairs one `sparse.<op>` counter with one `sparse` instant event
    # (sparse.EVENT_COUNTER), so live summaries (registry counters) and
    # offline sink replays reconstruct the SAME `sparse` block — the
    # PR 5/11/12 reconciliation contract. Absent entirely when no sparse
    # op ran, so dense-only summary shapes are unchanged.
    if live:
        from . import get_registry as _get_registry

        sm = {
            k[len("sparse."):]: (int(v) if float(v).is_integer() else v)
            for k, v in _get_registry().counters.items()
            if k.startswith("sparse.")
        }
        sm.pop("laplacian_live_bytes", None)  # a watermark key, not a counter
        if sm:
            out["sparse"] = sm
    elif sp_events:
        out["sparse"] = dict(sp_events)
    if watermarks and "sparse.laplacian_live_bytes" in watermarks:
        out.setdefault("sparse", {})["laplacian_live_bytes"] = int(
            watermarks["sparse.laplacian_live_bytes"]
        )
    # streaming counters (heat_tpu/streaming, ISSUE 16): one
    # `streaming.<counter>` per `streaming` instant event (plus the
    # rows-field fold into `streaming.rows` — streaming/events.py), so
    # live summaries (registry counters) and offline sink replays
    # reconstruct the SAME `streaming` block — the PR 5/11/12/13
    # reconciliation contract. Derived fields (rows/s ingested, publish
    # latency, compiles-per-swap, max published version, version lag =
    # the longest roll step, i.e. the widest mixed-version window) come
    # from the events in BOTH modes. Absent entirely when no stream ran,
    # so batch-only summary shapes are unchanged.
    if live:
        from . import get_registry as _get_registry

        st = {
            k[len("streaming."):]: (int(v) if float(v).is_integer() else v)
            for k, v in _get_registry().counters.items()
            if k.startswith("streaming.")
        }
        if st:
            out["streaming"] = st
    elif st_events:
        from heat_tpu.streaming import EVENT_COUNTER as _st_names

        st = {_st_names.get(k, k): v for k, v in st_events.items()}
        if st_rows:
            st["rows"] = st_rows
        out["streaming"] = st
    if st_events and "streaming" in out:
        st = out["streaming"]
        if st_read_seconds > 0:
            st["rows_per_s"] = round(st_rows / st_read_seconds, 3)
        if st_swap_seconds:
            st["update_latency"] = {
                "mean": round(sum(st_swap_seconds) / len(st_swap_seconds), 6),
                "max": round(max(st_swap_seconds), 6),
            }
            st["compiles_per_swap"] = st_compiles
            st["max_version"] = st_max_version
        if st_roll_seconds:
            st["version_lag"] = round(max(st_roll_seconds), 6)
    if watermarks and "streaming.chunk_bytes" in watermarks:
        out.setdefault("streaming", {})["chunk_bytes"] = int(
            watermarks["streaming.chunk_bytes"]
        )
    if watermarks:
        peak = watermarks.get("live_bytes.total")
        if peak is not None:
            out["peak_live_bytes"] = int(peak)
    return out


def summarize_cluster(scrapes, **kwargs) -> dict:
    """Fleet-merged summary over per-replica ``GET /metrics`` scrapes —
    thin alias for :func:`heat_tpu.telemetry.cluster.summarize_cluster`
    (ISSUE 17), living here so the per-process and fleet reports share
    one import surface."""
    from . import cluster

    return cluster.summarize_cluster(scrapes, **kwargs)


def bench_fields() -> dict:
    """The dict the benchmark harness merges into its summary line:
    ``{"telemetry": summarize()}`` when enabled, ``{}`` otherwise."""
    from . import enabled

    if not enabled():
        return {}
    return {"telemetry": summarize()}
