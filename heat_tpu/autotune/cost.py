"""Offline candidate pruning (ISSUE 11 stage 1): the analytic collective
cost model plus the planner's ``memory_analysis``-calibrated temp model
rank the lattice before anything is measured.

A *cost function* maps one config dict to a predicted scalar (lower is
better; ``inf`` = infeasible, pruned outright). The built-in
:func:`relayout_cost_fn` prices the relayout family the same way the
planner and the HLO auditor do — wire bytes from
:mod:`heat_tpu.telemetry.collectives` (``precision=`` included, so a
compressed candidate is priced byte-for-byte like the program it would
dispatch) and per-device temp bytes from
:mod:`heat_tpu.core.relayout_planner` (optionally replaced by a compiled
program's measured ``memory_analysis()`` figure, exactly like
``plan(measured_need=...)``). Sites without an analytic model skip
pruning and go straight to measured trials.
"""

from __future__ import annotations

import math
from typing import Callable, Dict, List, Optional, Sequence

__all__ = [
    "prune", "rank", "relayout_cost_fn", "fsdp_cost_fn", "pipeline_cost_fn",
]

ConfigCost = Callable[[Dict[str, str]], float]


def rank(
    configs: List[Dict[str, str]], cost_fn: ConfigCost
) -> List[tuple]:
    """``(predicted_cost, lattice_index, config)`` rows sorted by the
    analytic model (stable on ties via the lattice index). A cost
    function that raises for a config marks it infeasible rather than
    killing the tune."""
    rows = []
    for i, cfg in enumerate(configs):
        try:
            c = float(cost_fn(cfg))
        except Exception:
            c = math.inf
        rows.append((c, i, cfg))
    return sorted(rows, key=lambda r: (r[0], r[1]))


def prune(
    configs: List[Dict[str, str]],
    cost_fn: Optional[ConfigCost],
    *,
    keep: int = 8,
) -> List[Dict[str, str]]:
    """The configs that graduate to measured trials: the default config
    (``configs[0]``) unconditionally — the never-worse guarantee needs
    its measured wall — plus the ``keep - 1`` analytically cheapest
    feasible challengers, in predicted order. ``cost_fn=None`` skips
    pruning entirely (no analytic model for this site: every lattice
    candidate is measured, so callers without a model keep their search
    lists small)."""
    if cost_fn is None or len(configs) <= 1:
        return list(configs)
    default = configs[0]
    kept = [default]
    for c, i, cfg in rank(configs[1:], cost_fn):
        if len(kept) >= max(1, keep):
            break
        if math.isinf(c):
            continue
        kept.append(cfg)
    return kept


def fsdp_cost_fn(
    leaf_numels: Sequence[int],
    itemsize: int,
    nproc: int,
    *,
    dtype: str = "float32",
) -> ConfigCost:
    """Analytic cost of one FSDP training step (ISSUE 18) under a
    candidate config: per sharded leaf, one just-in-time weight gather
    in the forward, one re-gather in the rematerialized backward, and
    one gradient reduce-scatter — priced by
    :func:`heat_tpu.telemetry.collectives.fsdp_gather_cost` /
    ``fsdp_scatter_cost`` at the candidate's wire precision
    (``HEAT_TPU_FSDP_PREC``, falling back through the tiered cross-node
    chain exactly like :func:`heat_tpu.core.topology.fsdp_wire`).

    Prefetch depth (``HEAT_TPU_FSDP_PREFETCH``) moves no bytes — it is
    pure scheduling — so it is modelled as *exposure*: depth ``d``
    overlaps gathers with compute, leaving roughly ``1/(d+1)`` of the
    gather volume on the critical path, while the backward's scatter
    stream stays exposed. That is enough for the analytic stage to rank
    prefetch>0 above serial without pretending to know the GEMM wall;
    measured trials settle the rest. Topology-aware DCN pricing arms
    only when the lattice searches ``HEAT_TPU_HIERARCHICAL``, mirroring
    :func:`relayout_cost_fn`."""
    from ..telemetry import collectives as model

    numels = [int(n) for n in leaf_numels]

    def fn(config: Dict[str, str]) -> float:
        from ..core import collective_prec, topology

        prec = (config.get("HEAT_TPU_FSDP_PREC") or "").strip() or None
        if prec is None:
            prec = (
                config.get("HEAT_TPU_HIERARCHICAL_PREC") or ""
            ).strip() or None
        if prec is None:
            prec = (config.get("HEAT_TPU_COLLECTIVE_PREC") or "off").strip()
        prec = collective_prec.effective(dtype, prec)
        try:
            block = int(config.get("HEAT_TPU_COLLECTIVE_PREC_BLOCK") or 0)
        except ValueError:
            block = 0
        block = block if block > 0 else model.DEFAULT_WIRE_BLOCK
        try:
            depth = int(config.get("HEAT_TPU_FSDP_PREFETCH") or 0)
        except ValueError:
            return math.inf
        if depth < 0:
            return math.inf
        searching_hier = "HEAT_TPU_HIERARCHICAL" in config
        hier_on = (config.get("HEAT_TPU_HIERARCHICAL") or "0").strip() in (
            "1", "true", "yes", "on",
        )
        topo = topology.resolve(nproc)
        tiered = hier_on and topo.nontrivial
        node, local = (topo.node, topo.local) if tiered else (1, nproc)
        gathers: List = []
        scatters: List = []
        for numel in numels:
            chunk = -(-numel // nproc)
            if prec == "blockwise":
                chunk = -(-chunk // block) * block
            gathers.append(
                model.fsdp_gather_cost(
                    chunk, itemsize, node, local, prec, block=block
                )
            )
            scatters.append(
                model.fsdp_scatter_cost(
                    chunk * nproc, itemsize, node, local, prec, block=block
                )
            )
        premium = None
        if searching_hier:
            try:
                premium = float(config.get("HEAT_TPU_DCN_PREMIUM") or 0)
            except ValueError:
                premium = 0.0
            if premium <= 0:
                premium = None  # weighted_wire falls back to the live knob

        def price(c) -> float:
            if not searching_hier:
                return float(c.bytes)
            if topo.nontrivial and not c.dcn_bytes and c.bytes:
                # flat lowering on a 2-level topology: all bytes ride DCN
                c = model.CollectiveCost(
                    c.kind, c.bytes, steps=c.steps, dcn_bytes=c.bytes
                )
            return float(model.weighted_wire(c, premium))

        gather_wall = 2.0 * sum(price(c) for c in gathers)
        scatter_wall = sum(price(c) for c in scatters)
        return scatter_wall + gather_wall / float(depth + 1)

    return fn


def pipeline_cost_fn(
    layer_numels: Sequence[int],
    n_layers: int,
    batch: int,
    feat_numel: int,
    itemsize: int,
    nproc: int,
    *,
    n_stages: Optional[int] = None,
    budget: Optional[int] = None,
    dtype: str = "float32",
) -> ConfigCost:
    """Analytic cost of one pipeline training step (ISSUE 19) under a
    candidate config over the ``schedule × microbatch-count × prefetch ×
    wire`` lattice (``HEAT_TPU_PIPELINE_SCHEDULE``,
    ``HEAT_TPU_PIPELINE_MICROBATCHES``, ``HEAT_TPU_FSDP_PREFETCH``,
    ``HEAT_TPU_FSDP_PREC``). Three terms, all in (weighted) wire-byte
    units, straight from the schedule table the candidate would compile:

    * **hops** — every tick moves one collective-permute per direction,
      priced by :func:`heat_tpu.telemetry.collectives.pipeline_hop_cost`
      (DCN-weighted under a searched ``HEAT_TPU_HIERARCHICAL``, mirroring
      :func:`relayout_cost_fn`'s premium arming rule).
    * **gathers** — each (layer, microbatch, direction) is one in-stage
      grouped all-gather (ICI tier, never DCN); the forward share rides
      the prefetch window like :func:`fsdp_cost_fn` (``1/(d+1)``
      exposure), the backward re-gather stays exposed.
    * **bubble exposure** — ``steady_bubble_ticks`` (the schedule-shaped
      figure; total bubble cells are IDENTICAL across gpipe/1f1b at one
      ``(S, M)``) times the mean busy-cell compute proxy, which is what
      ranks 1f1b above gpipe and larger ``M`` above smaller before
      anything is measured.

    Feasibility: the candidate's activation stash
    (``stash_depth × microbatch bytes``, per stage) must fit ``budget``
    when one is given — gpipe at large ``M`` prunes to ``inf`` exactly
    where 1f1b's ``min(S, M)`` stash survives. Microbatch counts that do
    not divide the batch (or stage counts that do not divide the mesh or
    the layer count) are ``inf``. M changes the accumulation grouping, so
    its axis is neutral-kind in the knob registry: the tuner only adopts
    a different M through guarded measured trials; this model just ranks
    the candidates it measures first."""
    from ..telemetry import collectives as model

    numels = [int(n) for n in layer_numels]
    n_layers = int(n_layers)
    batch = int(batch)

    def fn(config: Dict[str, str]) -> float:
        from ..core import collective_prec, topology
        from ..parallel import schedule as sched_mod

        sched = (
            config.get("HEAT_TPU_PIPELINE_SCHEDULE") or "gpipe"
        ).strip().lower()
        if sched not in sched_mod.SCHEDULES:
            return math.inf
        searching_hier = "HEAT_TPU_HIERARCHICAL" in config
        hier_on = (config.get("HEAT_TPU_HIERARCHICAL") or "0").strip() in (
            "1", "true", "yes", "on",
        )
        topo = topology.resolve(nproc)
        tiered = hier_on and topo.nontrivial
        S = n_stages
        if S is None:
            try:
                S = int(config.get("HEAT_TPU_PIPELINE_STAGES") or 0)
            except ValueError:
                return math.inf
        if S == 0:
            S = topo.node if tiered else nproc
        if S < 1 or nproc % S or n_layers % S:
            return math.inf
        local = nproc // S
        try:
            M = int(config.get("HEAT_TPU_PIPELINE_MICROBATCHES") or 0)
        except ValueError:
            return math.inf
        M = M if M > 0 else S
        if batch % M:
            return math.inf
        try:
            depth = int(config.get("HEAT_TPU_FSDP_PREFETCH") or 0)
        except ValueError:
            return math.inf
        if depth < 0:
            return math.inf
        prec = (config.get("HEAT_TPU_FSDP_PREC") or "").strip() or None
        if prec is None:
            prec = (
                config.get("HEAT_TPU_HIERARCHICAL_PREC") or ""
            ).strip() or None
        if prec is None:
            prec = (config.get("HEAT_TPU_COLLECTIVE_PREC") or "off").strip()
        prec = collective_prec.effective(dtype, prec)
        if prec in ("int8", "blockwise"):
            prec = "bf16"  # the pipeline gather coercion (plan_pipeline)
        wire_item = 2 if prec == "bf16" else itemsize

        table = sched_mod.build_schedule(S, M, sched, train=True)
        mb = batch // M
        if budget is not None:
            stash_bytes = (
                table.stash_depth() * mb * int(feat_numel) * itemsize
            )
            if stash_bytes > budget:
                return math.inf

        hop = model.pipeline_hop_cost(
            mb, int(feat_numel), itemsize, nproc,
            stride=local, local=topo.local if tiered else None,
        )
        premium = None
        if searching_hier:
            try:
                premium = float(config.get("HEAT_TPU_DCN_PREMIUM") or 0)
            except ValueError:
                premium = 0.0
            if premium <= 0:
                premium = None  # weighted_wire falls back to the live knob
        hop_price = (
            model.weighted_wire(hop, premium)
            if searching_hier
            else float(hop.bytes)
        )
        # the kernel skips the final tick's hops (no consumer), so a
        # compiled step carries 2 x (n_ticks - 1) permutes
        hop_wall = (table.n_ticks - 1) * 2.0 * hop_price

        per_layer = sum(
            local * (local - 1) * -(-numel // local) for numel in numels
        ) * wire_item
        fwd_gathers = M * n_layers * per_layer
        bwd_gathers = M * n_layers * per_layer
        gather_wall = bwd_gathers + fwd_gathers / float(depth + 1)

        compute_proxy = 2.0 * M * n_layers * sum(numels) * itemsize
        per_cell = compute_proxy / float(max(1, table.busy_cells()))
        bubble_wall = table.steady_bubble_ticks() * per_cell
        return hop_wall + gather_wall + bubble_wall

    return fn


def relayout_cost_fn(
    gshape: Sequence[int],
    itemsize: int,
    src_split: Optional[int],
    dst_split: Optional[int],
    nproc: int,
    *,
    budget: Optional[int] = None,
    measured_need: Optional[int] = None,
) -> ConfigCost:
    """Analytic cost of one relayout signature under a candidate config:
    the plan the candidate's ``HEAT_TPU_RELAYOUT_PLAN`` would select
    (``budget``/``measured_need`` in the planner's own convention),
    priced in wire bytes at the candidate's collective precision.
    Candidates whose per-device temp exceeds the budget are infeasible
    (``inf``) — the temp model is the same one ``memory_analysis``
    calibrates in the planner tests."""
    # lazy imports: cost.py is reachable from the knobs/telemetry layer
    # and must not drag core in at module load
    from ..core import relayout_planner as planner
    from ..telemetry import collectives as model

    gshape = tuple(int(s) for s in gshape)

    def fn(config: Dict[str, str]) -> float:
        from ..core import topology

        plan_mode = (config.get("HEAT_TPU_RELAYOUT_PLAN") or "auto").strip()
        prec = (config.get("HEAT_TPU_COLLECTIVE_PREC") or "off").strip()
        try:
            block = int(config.get("HEAT_TPU_COLLECTIVE_PREC_BLOCK") or 0)
        except ValueError:
            block = 0
        block = block if block > 0 else model.DEFAULT_WIRE_BLOCK
        pl = planner.plan(
            gshape, itemsize, src_split, dst_split, nproc,
            budget=budget, measured_need=measured_need,
            plan_mode=plan_mode,
        )
        if budget is not None and pl.temp_bytes > budget:
            return math.inf
        # topology-aware pricing (ISSUE 15), armed ONLY when the lattice
        # searches HEAT_TPU_HIERARCHICAL (every config of such a lattice
        # carries the key): on a non-trivial (node x local)
        # factorization, a FLAT collective's single replica group spans
        # nodes, so its whole volume is DCN-priced; the tiered
        # all-to-all charges only its cross-node stage at the premium.
        # This is what lets the analytic stage pick tiered vs flat per
        # signature before anything is measured. Lattices that do not
        # search the knob keep the historic plain-byte pricing exactly.
        searching_hier = "HEAT_TPU_HIERARCHICAL" in config
        hier_on = (config.get("HEAT_TPU_HIERARCHICAL") or "0").strip() in (
            "1", "true", "yes", "on",
        )
        topo = topology.resolve(nproc)
        tiered = hier_on and topo.nontrivial
        if getattr(pl, "stages", None):
            costs = [
                model.relayout_chunk_cost(
                    gshape, itemsize, src_split, dst_split,
                    s.hi - s.lo, nproc, precision=prec, block=block,
                )
                for s in pl.stages
            ]
        elif pl.kind == "alltoall" and tiered:
            phys_numel = 1
            for d, s_ in enumerate(gshape):
                s_ = int(s_)
                if d in (src_split, dst_split):
                    s_ = -(-s_ // nproc) * nproc
                phys_numel *= s_
            # cross tier priced at the config's COLLECTIVE_PREC: the
            # relayout program resolves its wire mode explicitly per
            # call, so the HIERARCHICAL_PREC fallback never reaches it —
            # pricing it here would reward a compression the executed
            # program cannot deliver
            costs = [
                model.hierarchical_a2a_cost(
                    phys_numel, itemsize, topo.node, topo.local,
                    prec, block=block,
                )
            ]
        else:
            costs = [
                model.relayout_cost(
                    gshape, itemsize, src_split, dst_split, nproc,
                    precision=prec, block=block,
                )
            ]
        if not searching_hier:
            return float(sum(c.bytes for c in costs))
        try:
            premium = float(config.get("HEAT_TPU_DCN_PREMIUM") or 0)
        except ValueError:
            premium = 0.0
        if premium <= 0:
            premium = None  # weighted_wire falls back to the live knob
        total = 0.0
        for c in costs:
            if topo.nontrivial and not c.dcn_bytes and c.bytes:
                # flat lowering on a 2-level topology: all bytes ride DCN
                c = model.CollectiveCost(
                    c.kind, c.bytes, steps=c.steps, dcn_bytes=c.bytes
                )
            total += model.weighted_wire(c, premium)
        return float(total)

    return fn
