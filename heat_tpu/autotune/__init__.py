"""heat_tpu.autotune — measured-feedback knob autotuner with a
persistent tuning DB (ISSUE 11 tentpole).

PR 10 centralized every ``HEAT_TPU_*`` knob in a typed registry and the
telemetry stack already measures exactly what each knob trades (wall
time, wire bytes, retraces, HBM watermarks). This package closes the
loop — the observability stack becomes a control system:

1. **Search space from the registry.** Perf-relevant knobs declare
   ``tunable=`` metadata (candidate values + constraint class
   ``exact|lossy|neutral``) in :mod:`heat_tpu._knobs`; the lattice is
   built from those declarations (:mod:`.space`), never hardcoded here,
   so every future knob gets tuning for free.
2. **Analytic pruning first.** The collective cost model and the
   planner's ``memory_analysis``-calibrated temp model rank the lattice
   offline (:mod:`.cost`); only the cheapest feasible candidates
   graduate to hardware time.
3. **Measured trials second.** Guarded, telemetry-spanned median-of-k
   timings with MAD outlier rejection and per-candidate digest/allclose
   validation (:mod:`.trials`). The default config is always candidate 0
   and is measured under the identical protocol, so the winner is
   *never worse than default* by construction.
4. **Error budget as the constraint handler** (the PR 9
   accuracy-frontier contract): a lossy knob value (collective
   precision, cdist bf16x3, ``SERVE_EXACT=0``) is only ever searched
   under a caller-stated budget, a lossy winner must measure within it
   against the exact reference, and exact-semantics call sites keep
   their per-call ``precision="off"`` pins — a per-call pin beats any
   tuned overlay by construction (``collective_prec.resolve``).
5. **Winners persist** in an on-disk DB (:mod:`.db`,
   ``HEAT_TPU_TUNE_DB=<dir>``, atomic-swap JSON records keyed by
   signature + mesh topology + backend). A second process consults the
   DB at ``program_cache`` miss / ``serve.Server`` construction time —
   behind one ``HEAT_TPU_AUTOTUNE`` flag check — and starts *tuned*
   with zero measured trials, the same way ``HEAT_TPU_COMPILE_CACHE``
   makes it start *compiled*.

Adoption model: a winning config is installed into the knob **overlay**
(:func:`heat_tpu._knobs.set_override`), the layer every registered knob
read consults before the environment. The process-global overlay is the
union of adopted configs (newest tune wins a conflicting knob); for
exact per-signature scoping, run the workload under
``knobs.overlay(result.config)`` instead of adopting.

``HEAT_TPU_AUTOTUNE`` is default-off: dispatch stays bit-for-bit the
untuned path (one flag check on a program-cache *miss*, nothing at all
on the hit path; no DB reads, no new compiles).
"""

from __future__ import annotations

import threading
import time
from dataclasses import dataclass, field
from typing import Any, Callable, Dict, List, Optional

from heat_tpu import _knobs as knobs

from .. import telemetry
from . import cost, db, space, trials

__all__ = [
    "TuneResult",
    "tune",
    "enabled",
    "enable",
    "disable",
    "warm_start",
    "on_program_miss",
    "adopted",
    "reset",
    "bench_field",
    "cost",
    "db",
    "space",
    "trials",
]

_UNSET = object()

_LOCK = threading.RLock()
_ADOPTED: Dict[str, Dict[str, str]] = {}  # site -> adopted config
_WARM = {"done": False, "records": 0}
# serializes measured-trial sections: two concurrent tune() calls would
# overlay each other's candidate configs mid-measurement
_TUNE_LOCK = threading.Lock()

# event name -> live counter suffix. Every counter increments exactly
# once alongside its event, so report.summarize()'s offline event-replay
# reconstruction produces the SAME autotune block as the live counters
# (pinned by tests/test_autotune.py, the PR-5 resilience reconciliation
# contract).
EVENT_COUNTER = {
    "trial": "trials",
    "db_hit": "db_hits",
    "db_miss": "db_misses",
    "store": "stores",
    "adopt": "adopted",
    "pick": "picks",
    "reject_budget": "rejected_budget",
    "reject_digest": "rejected_digest",
    "reject_error": "rejected_error",
    "warm_start": "warm_starts",
}


def _emit(site: str, event: str, **fields: Any) -> None:
    if not telemetry.enabled():
        return
    reg = telemetry.get_registry()
    reg.add(f"autotune.{EVENT_COUNTER[event]}", 1)
    reg.emit("autotune", site, event=event, **fields)


# -- arming -------------------------------------------------------------------


def enabled() -> bool:
    """Whether the autotuner is armed (``HEAT_TPU_AUTOTUNE``,
    overlay-aware — :func:`enable` arms via the overlay)."""
    return bool(knobs.get("HEAT_TPU_AUTOTUNE"))


def enable(db_dir: Optional[str] = None) -> None:
    """Arm the autotuner in-process (equivalent to
    ``HEAT_TPU_AUTOTUNE=1``); ``db_dir`` additionally points the tuning
    DB (``HEAT_TPU_TUNE_DB``)."""
    knobs.set_override("HEAT_TPU_AUTOTUNE", "1")
    if db_dir is not None:
        knobs.set_override("HEAT_TPU_TUNE_DB", str(db_dir))


def disable() -> None:
    """Disarm (overlay ``HEAT_TPU_AUTOTUNE=0``; adopted configs stay
    installed — call :func:`reset` to drop them too)."""
    knobs.set_override("HEAT_TPU_AUTOTUNE", "0")


# -- adoption / warm start ----------------------------------------------------


def _adopt(site: str, config: Dict[str, str], emit: bool = True) -> None:
    with _LOCK:
        for n, v in config.items():
            knobs.set_override(n, v)
        _ADOPTED[site] = dict(config)
    if emit:
        _emit(site, "adopt", config=dict(config))


def adopted() -> Dict[str, Dict[str, str]]:
    """Per-site configs currently adopted into the knob overlay."""
    with _LOCK:
        return {s: dict(c) for s, c in _ADOPTED.items()}


def warm_start(force: bool = False) -> int:
    """Load every valid record for this mesh from the tuning DB and
    adopt its config (oldest first, so the newest tune wins overlapping
    knobs). Memoized — the dispatch-time consults cost one dict check
    after the first call. Returns the number of records adopted.

    Never raises: an unopenable ``HEAT_TPU_TUNE_DB`` (unwritable path,
    a plain file where the directory should be) degrades to *untuned* —
    the same contract as a corrupt record — and stays memoized, so a
    broken path is probed once, not on every program miss."""
    with _LOCK:
        if _WARM["done"] and not force:
            return _WARM["records"]
        _WARM["done"] = True
        n = skipped = 0
        try:
            d = db.open_db()
            if d is not None:
                ambient = knobs.get("HEAT_TPU_AUTOTUNE_BUDGET")
                for rec in d.records():
                    if not _budget_covers(rec, ambient):
                        # the dispatch-time form of the DB-hit budget
                        # gate: a persisted LOSSY winner is only
                        # auto-adopted when the ambient
                        # HEAT_TPU_AUTOTUNE_BUDGET covers its measured
                        # error — a process that stated no budget never
                        # inherits quantized collectives from the DB
                        skipped += 1
                        continue
                    _adopt(str(rec.get("site")), rec["config"], emit=False)
                    n += 1
        except OSError:
            d = None
        _WARM["records"] = n
    if d is not None:
        _emit("db", "warm_start", records=n, db=d.path, skipped=skipped)
    return n


def on_program_miss(site: str) -> None:
    """Program-registry miss hook (``core/program_cache.py``): a miss is
    the cold path, so consulting the DB here (memoized warm start) costs
    nothing in steady state. Called only when ``HEAT_TPU_AUTOTUNE`` is
    on — the off path never reaches this module."""
    warm_start()


def reset() -> None:
    """Drop adopted overlays and the warm-start memo (tests)."""
    with _LOCK:
        names: set = set()
        for cfg in _ADOPTED.values():
            names.update(cfg)
        knobs.clear_overrides(names)
        _ADOPTED.clear()
        _WARM["done"] = False
        _WARM["records"] = 0


# -- the tuner ----------------------------------------------------------------


@dataclass
class TuneResult:
    """One tune's outcome: the winning config (``{knob: raw value}``),
    the full DB record, and how it was reached (``from_db`` = zero-trial
    warm start)."""

    site: str
    key: str
    config: Dict[str, str] = field(default_factory=dict)
    record: Dict[str, Any] = field(default_factory=dict)
    trials_run: int = 0
    from_db: bool = False


def _budget_covers(rec: Dict[str, Any], budget: Any) -> bool:
    """Whether a persisted record's winner satisfies the CALLER's error
    budget: digest-validated (exact/neutral) picks always do; a lossy
    pick (``validation == "allclose"``) only when the caller states a
    budget covering the record's measured error. A DB hit must never
    adopt a lossy config past the stated contract — a record tuned
    under a looser budget re-tunes under the tighter one instead."""
    if rec.get("validation") != "allclose":
        return True
    if budget is None:
        return False
    try:
        return float(rec.get("max_rel_err", float("inf"))) <= float(budget)
    except (TypeError, ValueError):
        return False


def tune(
    site: str,
    workload: Callable[[], Any],
    *,
    signature: Any,
    search: List[str],
    error_budget: Any = _UNSET,
    trials_per_config: Optional[int] = None,
    warmup: int = 1,
    cost_fn: Optional[Callable[[Dict[str, str]], float]] = None,
    prune_to: int = 8,
    db_dir: Optional[str] = None,
    adopt: bool = True,
    persist: bool = True,
) -> TuneResult:
    """Tune ``workload`` over the ``search`` knobs for one program
    signature (module docstring has the protocol; docs/AUTOTUNE.md the
    operator guide).

    ``workload()`` must be re-runnable and return the result the
    validators judge (an array / pytree; it is blocked to completion
    before the clock stops). ``signature`` keys the DB record —
    ``program_key``-compatible static config (shapes, dtypes, splits).
    ``error_budget`` defaults to ``HEAT_TPU_AUTOTUNE_BUDGET`` (unset =
    exact-only; lossy knob values are then never searched).
    ``cost_fn`` (e.g. :func:`cost.relayout_cost_fn`) prunes the lattice
    analytically to ``prune_to`` configs before anything is measured.

    On a DB hit for this signature+mesh+backend the record's config is
    returned (and adopted) with **zero measured trials** — unless the
    record's winner is a lossy pick whose measured error exceeds THIS
    caller's budget (or the caller stated none), in which case the hit
    is discarded and the site re-tunes under the stated budget.

    Trials install each candidate into the process-global knob overlay
    for the duration of its measurement, so OTHER threads dispatching
    concurrently see trial values (including lossy ones) and pollute
    the trial's timing — run tune() quiesced (docs/AUTOTUNE.md
    §Limits). Concurrent ``tune()`` calls are serialized on a module
    lock so two tunes can never interleave their candidate overlays.
    """
    with _TUNE_LOCK:
        return _tune_locked(
            site, workload, signature=signature, search=search,
            error_budget=error_budget, trials_per_config=trials_per_config,
            warmup=warmup, cost_fn=cost_fn, prune_to=prune_to,
            db_dir=db_dir, adopt=adopt, persist=persist,
        )


def _tune_locked(
    site: str,
    workload: Callable[[], Any],
    *,
    signature: Any,
    search: List[str],
    error_budget: Any = _UNSET,
    trials_per_config: Optional[int] = None,
    warmup: int = 1,
    cost_fn: Optional[Callable[[Dict[str, str]], float]] = None,
    prune_to: int = 8,
    db_dir: Optional[str] = None,
    adopt: bool = True,
    persist: bool = True,
) -> TuneResult:
    budget = (
        knobs.get("HEAT_TPU_AUTOTUNE_BUDGET")
        if error_budget is _UNSET else error_budget
    )
    # coerce up front: a numpy scalar budget must neither skew the
    # comparisons nor reach json.dump in the persisted record
    budget = None if budget is None else float(budget)
    k = int(
        trials_per_config
        if trials_per_config is not None
        else (knobs.get("HEAT_TPU_AUTOTUNE_TRIALS") or 5)
    )
    mesh = db.mesh_fingerprint()
    key = db.tune_key(site, signature, mesh)
    d = db.open_db(db_dir)
    if d is not None:
        rec = d.lookup(key, mesh)
        if rec is not None and _budget_covers(rec, budget):
            _emit(site, "db_hit", key=key)
            if adopt:
                _adopt(site, rec["config"])
            return TuneResult(
                site=site, key=key, config=dict(rec["config"]),
                record=rec, trials_run=0, from_db=True,
            )
        if rec is not None:
            # a valid record whose lossy winner exceeds this caller's
            # budget: discard the hit and re-tune under the stated
            # budget (last-write-wins the persisted record)
            _emit(site, "db_miss", key=key, reason="budget")
        else:
            _emit(site, "db_miss", key=key)

    lattice = space.candidates(search, error_budget=budget)
    configs = cost.prune(lattice, cost_fn, keep=prune_to)
    base = configs[0]
    trials_run = 0

    def _measure(cfg: Dict[str, str], idx: int):
        nonlocal trials_run

        def on_sample(i: int, dt: float) -> None:
            _emit(site, "trial", config_index=idx, sample=i, seconds=dt)

        with knobs.overlay(cfg):
            with telemetry.span(
                "autotune.measure", site=site, config_index=idx
            ):
                samples, out = trials.measure(
                    workload, k=k, warmup=warmup, on_sample=on_sample
                )
        trials_run += len(samples)
        return trials.robust_median(samples), out

    # default config: the wall every challenger must beat or tie, and
    # the bit-identity anchor for exact/neutral shifts
    base_wall, base_out = _measure(base, 0)
    base_digest = trials.digest(base_out)

    # exact reference for lossy shifts: the default config with every
    # searched lossy knob at its exact-semantics value (one unmeasured
    # run; coincides with the default run when nothing lossy is searched)
    ref_out = base_out
    anchor = space.exact_variant(base)
    if anchor != base and any(
        space.is_lossy_shift(cfg, base) for cfg in configs[1:]
    ):
        import jax

        with knobs.overlay(anchor):
            ref_out = jax.block_until_ready(workload())

    rows = [(base_wall, 0, base, 0.0, "digest")]
    for idx, cfg in enumerate(configs[1:], start=1):
        try:
            wall, out = _measure(cfg, idx)
        except Exception as e:  # noqa: BLE001 — a broken candidate is
            # disqualified, never fatal (guarded-trial contract)
            _emit(site, "reject_error", config_index=idx, error=repr(e))
            continue
        if space.is_lossy_shift(cfg, base):
            err = trials.max_rel_err(out, ref_out)
            if budget is None or not (err <= float(budget)):
                _emit(
                    site, "reject_budget", config_index=idx,
                    max_rel_err=err, budget=budget,
                )
                continue
            rows.append((wall, idx, cfg, err, "allclose"))
        else:
            if trials.digest(out) != base_digest:
                _emit(site, "reject_digest", config_index=idx)
                continue
            rows.append((wall, idx, cfg, 0.0, "digest"))

    # min wall; ties break toward the default (lattice index 0) — the
    # winner can never be worse than the measured default
    wall, idx, config, err, validation = min(rows, key=lambda r: (r[0], r[1]))
    _emit(
        site, "pick", config=dict(config), wall=wall,
        baseline_wall=base_wall, config_index=idx,
        configs_measured=len(rows), trials=trials_run,
    )
    record = {
        "schema": db.SCHEMA,
        "key": key,
        "site": site,
        "signature": repr(signature),
        "mesh": mesh,
        "config": dict(config),
        "default_config": dict(base),
        "baseline_wall": base_wall,
        "tuned_wall": wall,
        "speedup": (base_wall / wall) if wall > 0 else 1.0,
        "trials": trials_run,
        "configs_measured": len(rows),
        "lattice": len(lattice),
        "error_budget": budget,
        "max_rel_err": err,
        "validation": validation,
        "created": time.time(),
    }
    if adopt:
        # adopt BEFORE persisting: a store failure must never lose the
        # measured winner
        _adopt(site, config)
    if d is not None and persist:
        try:
            d.store(record)
            _emit(site, "store", key=key)
        except (OSError, TypeError, ValueError):
            # an unwritable/unopenable DB path, a full disk, or an
            # unserializable record loses persistence, never the
            # measured winner: it is already adopted and is returned
            pass
    return TuneResult(
        site=site, key=key, config=dict(config), record=record,
        trials_run=trials_run, from_db=False,
    )


# -- bench probe ---------------------------------------------------------------


def bench_field() -> dict:
    """The ``autotune`` detail row for bench summaries (bench.py /
    docs/BENCHMARKS.md): armed bit, DB location + valid-record count,
    live counters (trials run, DB hits, ...), and the chosen config per
    adopted site. Cheap — no tuning runs here."""
    out: dict = {"enabled": enabled()}
    try:
        d = db.open_db()
        out["db"] = d.path if d is not None else None
        if d is not None:
            out["db_records"] = d.count()
    except Exception as e:  # noqa: BLE001 — probe must never kill bench
        out["db_error"] = repr(e)
    snap = adopted()
    if snap:
        out["adopted"] = snap
    if telemetry.enabled():
        counters = {
            name[len("autotune."):]: int(v)
            for name, v in telemetry.get_registry().counters.items()
            if name.startswith("autotune.")
        }
        if counters:
            out["counters"] = counters
    return out
