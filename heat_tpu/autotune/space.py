"""Candidate-lattice construction (ISSUE 11): the search space comes
from the knob registry's ``tunable=`` metadata, not from the tuner.

A *config* is a ``{knob name: raw env string}`` dict — exactly what the
knob overlay installs — covering only the knobs a tune searches. The
default config (every searched knob at its CURRENT effective value:
overlay/env if set, declared default otherwise) is always candidate 0,
which is what lets the winner-selection rule guarantee "never worse than
default": the default is measured under the same protocol as every
challenger.

Lossy knobs (constraint class ``lossy``) are only enumerated when the
caller states a positive error budget; without one they stay pinned at
their current value, so an exact-only tune can never even *construct* a
config that moves a lossy knob.
"""

from __future__ import annotations

import itertools
from typing import Dict, Iterable, List, Optional

from heat_tpu import _knobs as knobs

__all__ = [
    "default_config",
    "candidates",
    "lossy_knobs",
    "exact_variant",
    "is_lossy_shift",
]

# Lattice bound before analytic pruning: the cartesian product over
# tunable values is capped here so a wide knob list cannot explode the
# offline stage (the measured stage is bounded separately by prune_to).
MAX_CONFIGS = 64


def _tunable(name: str) -> knobs.Knob:
    k = knobs.REGISTRY.get(name)
    if k is None:
        raise KeyError(f"{name!r} is not a registered HEAT_TPU knob")
    if k.tunable is None:
        raise ValueError(
            f"{name!r} carries no tunable= metadata — declare its search "
            "space in heat_tpu/_knobs.py before tuning it"
        )
    return k


def default_config(names: Iterable[str]) -> Dict[str, str]:
    """The searched knobs at their current effective raw values."""
    return {n: knobs.default_raw(n) for n in names}


def lossy_knobs(names: Iterable[str]) -> List[str]:
    return [n for n in names if _tunable(n).tunable.kind == "lossy"]


def exact_variant(config: Dict[str, str]) -> Dict[str, str]:
    """``config`` with every lossy knob moved to its declared
    exact-semantics value — the reference the error budget is measured
    against (docs/AUTOTUNE.md §error-budget contract)."""
    out = dict(config)
    for n in config:
        t = _tunable(n).tunable
        if t.kind == "lossy":
            out[n] = t.exact_value
    return out


def is_lossy_shift(config: Dict[str, str], base: Dict[str, str]) -> bool:
    """Whether ``config`` differs from ``base`` on any lossy knob — the
    validator's digest-vs-allclose fork: exact/neutral shifts must stay
    bit-identical to the default run, lossy shifts are judged against
    the exact reference under the budget."""
    return any(
        config.get(n) != base.get(n) for n in lossy_knobs(config)
    )


def candidates(
    names: Iterable[str],
    *,
    error_budget: Optional[float] = None,
    max_configs: int = MAX_CONFIGS,
) -> List[Dict[str, str]]:
    """The candidate lattice over ``names``: default config first, then
    the cartesian product of each knob's declared values (plus the
    current value, if the environment holds one the registry does not
    enumerate), deterministic order, capped at ``max_configs``."""
    names = list(names)
    if not names:
        raise ValueError("tune over an empty knob list")
    base = default_config(names)
    search_lossy = error_budget is not None and error_budget > 0
    axes: List[List[str]] = []
    for n in names:
        t = _tunable(n).tunable
        if t.kind == "lossy" and not search_lossy:
            axes.append([base[n]])
            continue
        vals = list(t.values)
        if base[n] not in vals:
            vals.insert(0, base[n])
        axes.append(vals)
    out: List[Dict[str, str]] = [base]
    seen = {tuple(sorted(base.items()))}
    for combo in itertools.product(*axes):
        cfg = dict(zip(names, combo))
        sig = tuple(sorted(cfg.items()))
        if sig in seen:
            continue
        seen.add(sig)
        out.append(cfg)
        if len(out) >= max_configs:
            break
    return out
