"""Persistent tuning DB (ISSUE 11): winners on disk, keyed like the
compile cache.

One JSON record per tuned signature, written with the same
atomic-swap discipline as :mod:`heat_tpu.resilience.checkpoint` (write a
tmp file, ``os.replace`` into place), so a reader never sees a torn
record and concurrent tuners last-write-win a whole record at a time.

The key is a content hash over ``(schema, site, signature, mesh
topology, backend platform, device kind)`` — ``program_key()``-compatible
in the sense that the ``(site, static-config)`` pair the program registry
keys on is the same pair that keys the tuning record, with the
process-local communicator identity replaced by its stable cross-process
description (device count + platform + kind). Two processes on the same
mesh therefore compute the same key, which is what makes the
second-process zero-trial warm start work; a record written on a
different mesh or backend is *foreign* and is cleanly rejected at lookup
(same contract as a checkpoint CRC mismatch: skip, never crash, never
apply).
"""

from __future__ import annotations

import hashlib
import json
import os
import tempfile
from typing import Any, Dict, Iterator, List, Optional

from heat_tpu import _knobs as knobs

__all__ = [
    "SCHEMA",
    "TuneDB",
    "mesh_fingerprint",
    "tune_key",
    "open_db",
]

# Bump on any record-shape change: old records become foreign (rejected
# at lookup), never misread.
SCHEMA = 1


def mesh_fingerprint() -> Dict[str, Any]:
    """Stable cross-process description of the mesh the tuning ran on:
    a record only applies to the topology+backend it was measured on."""
    import jax

    devs = jax.devices()
    return {
        "devices": len(devs),
        "platform": devs[0].platform,
        "device_kind": devs[0].device_kind,
    }


def tune_key(
    site: str, signature: Any, mesh: Optional[Dict[str, Any]] = None
) -> str:
    """The DB key for one tuned program signature (module docstring has
    the contract). ``signature`` is the caller's static config — same
    role as the ``key`` argument of ``program_cache.program_key`` — and
    participates by ``repr``, so it must be a stable value (tuples of
    ints/strs, not object identities)."""
    mesh = mesh or mesh_fingerprint()
    payload = repr((
        SCHEMA, str(site), signature,
        int(mesh["devices"]), str(mesh["platform"]),
        str(mesh["device_kind"]),
    ))
    return hashlib.sha256(payload.encode()).hexdigest()[:32]


def _valid(rec: Any, key: Optional[str], mesh: Dict[str, Any]) -> bool:
    """Schema/key/mesh validation — the foreign-record gate."""
    if not isinstance(rec, dict):
        return False
    if rec.get("schema") != SCHEMA:
        return False
    if key is not None and rec.get("key") != key:
        return False
    m = rec.get("mesh")
    if not isinstance(m, dict) or (
        m.get("devices") != mesh["devices"]
        or m.get("platform") != mesh["platform"]
        or m.get("device_kind") != mesh["device_kind"]
    ):
        return False
    cfg = rec.get("config")
    if not isinstance(cfg, dict) or not all(
        isinstance(k, str) and k in knobs.REGISTRY and isinstance(v, str)
        for k, v in cfg.items()
    ):
        # a config naming unregistered knobs (or non-string values) can
        # never be installed into the overlay — reject the whole record
        return False
    return True


class TuneDB:
    """Directory of atomic-swap JSON tuning records.

    The directory is created lazily on first :meth:`store` — read-only
    consults (``lookup``/``records``/``count``, e.g. the bench probe or
    a disabled tuner with ``HEAT_TPU_TUNE_DB`` merely exported) never
    touch the filesystem beyond reads."""

    def __init__(self, path: str):
        self.path = os.fspath(path)

    def _file(self, key: str) -> str:
        return os.path.join(self.path, f"{key}.json")

    def store(self, record: Dict[str, Any]) -> str:
        """Atomically write one record (validated against the current
        mesh first — a tuner must never persist a record it would itself
        reject). Returns the record path."""
        key = record.get("key")
        if not key or not _valid(record, key, mesh_fingerprint()):
            raise ValueError(
                "refusing to store an invalid tuning record "
                f"(schema/key/mesh/config): {record.get('key')!r}"
            )
        os.makedirs(self.path, exist_ok=True)
        final = self._file(key)
        fd, tmp = tempfile.mkstemp(
            prefix=f".{key}.", suffix=".tmp", dir=self.path
        )
        try:
            with os.fdopen(fd, "w") as f:
                json.dump(record, f, sort_keys=True)
                f.write("\n")
            os.replace(tmp, final)  # atomic swap: readers see old or new
        except BaseException:
            try:
                os.unlink(tmp)
            except OSError:
                pass
            raise
        return final

    def lookup(
        self, key: str, mesh: Optional[Dict[str, Any]] = None
    ) -> Optional[Dict[str, Any]]:
        """The record for ``key``, or None. Corrupt files (torn JSON),
        schema drift, key mismatches, and foreign mesh/backend records
        all return None — a bad DB entry degrades to "untuned", never to
        a crash or a wrong config."""
        mesh = mesh or mesh_fingerprint()
        try:
            with open(self._file(key)) as f:
                rec = json.load(f)
        except (OSError, json.JSONDecodeError, UnicodeDecodeError):
            return None
        return rec if _valid(rec, key, mesh) else None

    def records(
        self, mesh: Optional[Dict[str, Any]] = None
    ) -> Iterator[Dict[str, Any]]:
        """Every valid record for this mesh, oldest store first (so a
        warm start that merges overlapping configs lets the newest tune
        win)."""
        mesh = mesh or mesh_fingerprint()
        rows: List[tuple] = []
        try:
            entries = os.listdir(self.path)
        except OSError:
            return
        for fn in entries:
            if not fn.endswith(".json") or fn.startswith("."):
                continue
            key = fn[: -len(".json")]
            path = os.path.join(self.path, fn)
            try:
                mtime = os.path.getmtime(path)
            except OSError:
                continue
            rec = self.lookup(key, mesh)
            if rec is not None:
                rows.append((mtime, key, rec))
        for _, _, rec in sorted(rows, key=lambda r: (r[0], r[1])):
            yield rec

    def count(self, mesh: Optional[Dict[str, Any]] = None) -> int:
        return sum(1 for _ in self.records(mesh))


def open_db(path: Optional[str] = None) -> Optional[TuneDB]:
    """The active tuning DB: explicit ``path``, else ``HEAT_TPU_TUNE_DB``
    (overlay-aware), else None (tuning runs in memory only — winners are
    adopted for this process but not persisted)."""
    path = path or (knobs.raw("HEAT_TPU_TUNE_DB", "") or "").strip()
    return TuneDB(path) if path else None
