"""Measured-trial machinery (ISSUE 11 stage 2): guarded, telemetry-
spanned workload timings plus the digest/allclose validators.

Timing protocol: per candidate config, ``warmup`` untimed calls (the
first call owns the compile wait — same discipline as the bench
harness), then ``k`` timed calls, each blocked to completion via
``jax.block_until_ready`` before the clock stops (async-dispatch
honesty, same contract as ``telemetry.Span``). The per-config statistic
is the **median of k after MAD outlier rejection** — a GC pause or a
noisy-neighbor blip disqualifies a sample, not a config.

Validation: outputs are flattened to leaves; :func:`digest` is the
bit-identity oracle (sha256 over each leaf's bytes + dtype/shape),
:func:`max_rel_err` the amax-normalized error the budget bounds (the
same metric the collective-precision CI gate pins).
"""

from __future__ import annotations

import hashlib
import statistics
import time
from typing import Any, Callable, List, Tuple

import jax
import numpy as np

__all__ = [
    "measure",
    "robust_median",
    "digest",
    "max_rel_err",
]

# MAD z-score beyond which a sample is an outlier (the conventional
# 1.4826 factor makes MAD a consistent sigma estimator for normal noise).
_MAD_SIGMA = 1.4826
_OUTLIER_Z = 3.5


def robust_median(samples: List[float]) -> float:
    """Median after MAD outlier rejection; degenerate spreads (MAD 0)
    fall back to the plain median."""
    if not samples:
        raise ValueError("no samples")
    med = statistics.median(samples)
    mad = statistics.median([abs(s - med) for s in samples])
    if mad <= 0.0:
        return med
    kept = [
        s for s in samples
        if abs(s - med) / (_MAD_SIGMA * mad) <= _OUTLIER_Z
    ]
    return statistics.median(kept or samples)


def measure(
    workload: Callable[[], Any],
    *,
    k: int,
    warmup: int = 1,
    on_sample: Callable[[int, float], None] = None,
) -> Tuple[List[float], Any]:
    """Run ``workload`` ``warmup + k`` times; returns ``(samples, out)``
    where ``out`` is the last call's (blocked) output — the value the
    validators judge. ``on_sample(trial_index, seconds)`` fires per timed
    trial (the tuner's telemetry hook)."""
    out = None
    for _ in range(max(0, warmup)):
        out = jax.block_until_ready(workload())
    samples: List[float] = []
    for i in range(max(1, k)):
        t0 = time.perf_counter()
        out = jax.block_until_ready(workload())
        dt = time.perf_counter() - t0
        samples.append(dt)
        if on_sample is not None:
            on_sample(i, dt)
    return samples, out


def _leaves(out: Any) -> List[np.ndarray]:
    leaves = jax.tree_util.tree_leaves(out)
    return [np.asarray(leaf) for leaf in leaves]


def digest(out: Any) -> str:
    """Bit-identity digest of a pytree of arrays (dtype/shape included:
    a float64 zero and a float32 zero must not collide)."""
    h = hashlib.sha256()
    for a in _leaves(out):
        h.update(str((a.dtype.str, a.shape)).encode())
        h.update(np.ascontiguousarray(a).tobytes())
    return h.hexdigest()


def max_rel_err(out: Any, ref: Any) -> float:
    """Max over leaves of ``max|out - ref| / max|ref|`` (amax-normalized;
    an all-zero reference leaf normalizes by 1). Structure or shape
    mismatches are infinite error — a candidate that changes the output
    SHAPE can never pass a numeric budget."""
    a_leaves, b_leaves = _leaves(out), _leaves(ref)
    if len(a_leaves) != len(b_leaves):
        return float("inf")
    worst = 0.0
    for a, b in zip(a_leaves, b_leaves):
        if a.shape != b.shape:
            return float("inf")
        if a.size == 0:
            continue
        bf = b.astype(np.float64, copy=False)
        af = a.astype(np.float64, copy=False)
        denom = float(np.max(np.abs(bf))) or 1.0
        err = float(np.max(np.abs(af - bf))) / denom
        if not np.isfinite(err):
            return float("inf")
        worst = max(worst, err)
    return worst
