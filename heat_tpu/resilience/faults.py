"""Deterministic, seeded fault injector (ISSUE 5 tentpole piece 1).

Heat's MPI lineage was fail-stop: any rank error killed the job, so the
reference never needed to *test* recovery paths. This port retries
transient runtime faults (see :mod:`.guard`) — which means CI must be able
to *produce* those faults on demand, reproducibly, without TPU hardware or
real HBM pressure. This module is that producer: a rule table consulted at
the two framework chokepoints every dispatch already routes through —
:func:`heat_tpu.core.program_cache.cached_program` executions and the
:class:`~heat_tpu.core.communication.MeshCommunication` collective
wrappers — raising synthetic transient errors, adding latency, or
corrupting outputs with NaNs.

Spec grammar (``HEAT_TPU_FAULTS`` env var or :func:`inject`)
------------------------------------------------------------
``rule(;rule)*`` where each rule is ``site_pattern(:key=value)*``:

* ``site_pattern`` — :mod:`fnmatch` glob matched against the dispatch site
  name (``relayout``, ``fusion``, ``collective.psum``, ``cg_chunk`` …).
* ``kind=resource|reset|latency|nan`` — what to inject (default
  ``resource``): a RESOURCE_EXHAUSTED-class error, a connection-reset-class
  error, a ``delay``-second sleep, or NaN corruption of the call's output.
  ``nan`` applies at program-execution sites only — the ``collective.*``
  wrappers run at *trace* time, where poisoning the output would bake the
  corruption into the cached executable forever, so the guard leaves
  tracer outputs clean (raising kinds work everywhere). Note also that at
  trace-time sites ``calls=``/``p=`` count *traces*, not executions — a
  hot cached program re-enters no wrappers.
* ``calls=1,3`` — inject at these 1-based call indices (counted per
  (rule, site) pair, so a glob rule fires independently at each site it
  matches).
* ``p=0.25`` — inject with this probability per call. The draw is a pure
  function of ``(seed, site, call index)`` (CRC32-based — *not* python's
  salted ``hash``), so a fixed seed reproduces the exact same injection
  schedule in every process: chaos CI failures replay locally.
* ``seed=7`` — seed for the ``p`` draw (default 0).
* ``delay=0.05`` — seconds for ``kind=latency`` (default 0.01).
* ``times=2`` — stop firing after this many injections (per rule, across
  all sites). Unset = unlimited.

Example::

    HEAT_TPU_FAULTS='relayout:kind=resource:calls=1;collective.*:kind=reset:calls=1'

injects one synthetic HBM OOM at the first relayout dispatch and one
connection reset at the first call of every collective wrapper site.

Disabled (no rules), the cost at every chokepoint is one module-flag
check — the same contract as telemetry.
"""

from __future__ import annotations

import fnmatch
import threading
import time
import zlib
from typing import Dict, List, Optional

from heat_tpu import _knobs as knobs

__all__ = [
    "FaultRule",
    "InjectedFault",
    "InjectedResourceExhausted",
    "InjectedConnectionReset",
    "inject",
    "clear",
    "active",
    "check",
    "parse_spec",
    "stats",
]


class InjectedFault(RuntimeError):
    """Base class of every synthetic error this module raises. Carries the
    site and call index for the guard's attempt history; classified as
    *transient* by :func:`heat_tpu.resilience.guard.classify`."""

    transient = True

    def __init__(self, message: str, site: str = "?", index: int = 0):
        super().__init__(message)
        self.site = site
        self.index = index


class InjectedResourceExhausted(InjectedFault):
    """Synthetic RESOURCE_EXHAUSTED-class fault (the shape of an XLA HBM
    OOM / allocator failure)."""


class InjectedConnectionReset(InjectedFault):
    """Synthetic connection-reset-class fault (the shape of a DCN/ICI
    transport hiccup or a coordinator socket drop)."""


_KINDS = ("resource", "reset", "latency", "nan")


class FaultRule:
    """One parsed injection rule. Mutable state: per-site call counters and
    the fired-injection count (both behind the module lock)."""

    __slots__ = ("pattern", "kind", "calls", "p", "seed", "delay", "times",
                 "counts", "fired")

    def __init__(
        self,
        pattern: str,
        kind: str = "resource",
        calls: Optional[tuple] = None,
        p: Optional[float] = None,
        seed: int = 0,
        delay: float = 0.01,
        times: Optional[int] = None,
    ):
        if kind not in _KINDS:
            raise ValueError(
                f"fault kind must be one of {_KINDS}, got {kind!r}"
            )
        if calls is None and p is None:
            # a rule with neither trigger fires on every call
            p = 1.0
        self.pattern = pattern
        self.kind = kind
        self.calls = tuple(int(c) for c in calls) if calls else None
        self.p = float(p) if p is not None else None
        self.seed = int(seed)
        self.delay = float(delay)
        self.times = int(times) if times is not None else None
        self.counts: Dict[str, int] = {}
        self.fired = 0

    def matches(self, site: str) -> bool:
        return fnmatch.fnmatchcase(site, self.pattern)

    def should_fire(self, site: str) -> Optional[int]:
        """Advance this rule's per-site call counter and decide whether to
        inject. Returns the 1-based call index when firing, else None.
        Caller holds the module lock."""
        index = self.counts.get(site, 0) + 1
        self.counts[site] = index
        if self.times is not None and self.fired >= self.times:
            return None
        if self.calls is not None and index in self.calls:
            return index
        if self.p is not None and _draw(self.seed, site, index) < self.p:
            return index
        return None

    def describe(self) -> dict:
        return {
            "pattern": self.pattern,
            "kind": self.kind,
            "calls": self.calls,
            "p": self.p,
            "seed": self.seed,
            "delay": self.delay,
            "times": self.times,
            "fired": self.fired,
        }


def _draw(seed: int, site: str, index: int) -> float:
    """Deterministic uniform in [0, 1) — a pure function of its inputs.
    CRC32 instead of ``hash()``: python salts string hashes per process
    (PYTHONHASHSEED), which would make a "seeded" schedule unreproducible
    across processes."""
    h = zlib.crc32(f"{seed}:{site}:{index}".encode())
    return h / 2**32


# One flag + one lock. `_ACTIVE` mirrors bool(_RULES) so the chokepoint
# fast path is a single module attribute load.
_LOCK = threading.Lock()
_RULES: List[FaultRule] = []
_ACTIVE = False
_INJECTED: Dict[str, int] = {}


def active() -> bool:
    """Whether any injection rule is installed (the chokepoint fast-path
    flag)."""
    return _ACTIVE


def parse_spec(spec: str) -> List[FaultRule]:
    """Parse a ``HEAT_TPU_FAULTS`` spec string into rules (see module
    docstring for the grammar). Raises ValueError on malformed specs —
    a chaos configuration that silently parses to nothing would make CI
    "pass" without testing anything."""
    rules = []
    for chunk in spec.split(";"):
        chunk = chunk.strip()
        if not chunk:
            continue
        parts = chunk.split(":")
        pattern = parts[0].strip()
        if not pattern or "=" in pattern:
            raise ValueError(
                f"fault rule {chunk!r} must start with a site pattern "
                "(e.g. 'relayout:kind=resource:calls=1')"
            )
        kw: dict = {}
        for part in parts[1:]:
            if "=" not in part:
                raise ValueError(f"malformed fault option {part!r} in {chunk!r}")
            k, v = part.split("=", 1)
            k = k.strip()
            v = v.strip()
            if k == "kind":
                kw["kind"] = v
            elif k == "calls":
                kw["calls"] = tuple(int(c) for c in v.split(",") if c)
            elif k == "p":
                kw["p"] = float(v)
            elif k == "seed":
                kw["seed"] = int(v)
            elif k == "delay":
                kw["delay"] = float(v)
            elif k == "times":
                kw["times"] = int(v)
            else:
                raise ValueError(f"unknown fault option {k!r} in {chunk!r}")
        rules.append(FaultRule(pattern, **kw))
    return rules


def inject(
    site: str = "*",
    kind: str = "resource",
    calls: Optional[tuple] = None,
    p: Optional[float] = None,
    seed: int = 0,
    delay: float = 0.01,
    times: Optional[int] = None,
) -> FaultRule:
    """Install one injection rule programmatically (the API twin of the
    ``HEAT_TPU_FAULTS`` env spec). Returns the rule (its ``fired`` counter
    is live). Arms the resilience dispatch wrapper."""
    rule = FaultRule(site, kind=kind, calls=calls, p=p, seed=seed,
                     delay=delay, times=times)
    global _ACTIVE
    with _LOCK:
        _RULES.append(rule)
        _ACTIVE = True
    from . import refresh

    refresh()
    return rule


def install_spec(spec: str) -> List[FaultRule]:
    """Parse and install every rule of ``spec`` (used by env activation)."""
    rules = parse_spec(spec)
    global _ACTIVE
    with _LOCK:
        _RULES.extend(rules)
        _ACTIVE = bool(_RULES)
    return rules


def clear() -> None:
    """Remove every rule and zero the injection counters."""
    global _ACTIVE
    with _LOCK:
        _RULES.clear()
        _INJECTED.clear()
        _ACTIVE = False
    from . import refresh

    refresh()


def check(site: str) -> Optional[str]:
    """Consult the rule table for one dispatch at ``site``.

    Raises the synthetic error for ``resource``/``reset`` rules, sleeps for
    ``latency`` rules, and returns ``"nan"`` when the caller (the guard)
    should corrupt the call's output. Returns None when nothing fires.
    Called only when :func:`active` — the disabled path never enters."""
    directive = None
    sleep_s = 0.0
    fire: Optional[tuple] = None  # (rule, index) of the first raising rule
    with _LOCK:
        for rule in _RULES:
            if not rule.matches(site):
                continue
            index = rule.should_fire(site)
            if index is None:
                continue
            rule.fired += 1
            _INJECTED[site] = _INJECTED.get(site, 0) + 1
            if rule.kind == "latency":
                sleep_s += rule.delay
            elif rule.kind == "nan":
                directive = "nan"
            elif fire is None:
                fire = (rule, index)
    _record(site, fire, sleep_s, directive)
    if sleep_s:
        time.sleep(sleep_s)
    if fire is not None:
        rule, index = fire
        if rule.kind == "resource":
            raise InjectedResourceExhausted(
                f"RESOURCE_EXHAUSTED: synthetic HBM allocator failure "
                f"injected at site {site!r} (call {index}, rule "
                f"{rule.pattern!r})",
                site=site, index=index,
            )
        raise InjectedConnectionReset(
            f"connection reset by peer: synthetic transport fault injected "
            f"at site {site!r} (call {index}, rule {rule.pattern!r})",
            site=site, index=index,
        )
    return directive


def _record(site: str, fire, sleep_s: float, directive) -> None:
    if fire is None and not sleep_s and directive is None:
        return
    from .. import telemetry

    if not telemetry.enabled():
        return
    reg = telemetry.get_registry()
    reg.add("resilience.faults_injected", 1)
    kind = (
        fire[0].kind if fire is not None
        else ("nan" if directive == "nan" else "latency")
    )
    reg.emit("resilience", site, event="inject", fault_kind=kind)


def stats() -> dict:
    """Snapshot: installed rules and per-site injection counts."""
    with _LOCK:
        return {
            "rules": [r.describe() for r in _RULES],
            "injected": dict(_INJECTED),
        }


# Environment activation happens in heat_tpu/resilience/__init__.py (the
# package reads HEAT_TPU_FAULTS once at import, mirroring telemetry's
# HEAT_TPU_TELEMETRY pattern) — this module stays import-order agnostic.
_ENV_VAR = "HEAT_TPU_FAULTS"


def env_spec() -> str:
    return knobs.raw(_ENV_VAR, "").strip()
