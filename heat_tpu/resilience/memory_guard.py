"""Pre-flight HBM budgeting + graceful degradation (ISSUE 5 piece 3).

Memory-aware redistribution planning (PAPERS.md, arXiv:2112.01075) frames
the question this module answers operationally: *before* a compiled
program dispatches, will the device fit its temporaries and outputs on top
of what is already live? With ``HEAT_TPU_HBM_BUDGET`` set (bytes, with
optional K/M/G suffix), every guarded program dispatch runs
:func:`preflight`:

``predicted = live-bytes watermark + program temp/output bytes``

where live bytes come from :func:`heat_tpu.telemetry.memory.live_bytes`
(framework-level accounting, every backend) and program bytes from the
compiled executable's ``memory_analysis()`` (memoized per (program, aval
signature) — the compile is the same one the first call pays anyway).

On predicted overflow the guard degrades before it fails:

1. **fusion window-flush** — :func:`heat_tpu.core.fusion.set_pressure_cap`
   drops the deferral depth cap to 1, so pending elementwise DAGs flush in
   minimal windows instead of accumulating wide programs;
2. **garbage collection** — drops dead python references pinning device
   buffers;
3. re-measure; if the predicted total now fits, dispatch proceeds (the
   pressure cap stays until a later preflight sees comfortable headroom);
4. otherwise raise :class:`HeatTpuMemoryError` naming the site, the
   predicted/live/budget byte counts, and the remediation ladder.

For **relayouts** the ladder no longer ends in step 4: with a budget
armed, ``DNDarray._relayout`` consults the communication-aware planner
(:mod:`heat_tpu.core.relayout_planner`) *before* dispatch, using the
same ``live + temp + output <= budget`` arithmetic as :func:`preflight`
— a monolithic program that would overflow is replaced by a
bounded-memory chunked program chain whose stages fit, so the resplit
succeeds instead of erroring at the ceiling (ISSUE 6).

The cdist/manhattan row-blocked kernels additionally consult
:func:`temp_budget` so their broadcast temporaries are chunked along the
batch axis to fit the budget (spatial/distance.py).

Unset (the default), the cost is one flag check — the package is not even
armed, so :func:`preflight` is never called.
"""

from __future__ import annotations

import gc
import re
from collections import OrderedDict
from typing import Optional, Tuple

from heat_tpu import _knobs as knobs

from .guard import HeatTpuRuntimeError
from .. import telemetry

__all__ = [
    "HeatTpuMemoryError",
    "budget_bytes",
    "headroom",
    "preflight",
    "program_bytes",
    "temp_budget",
]


class HeatTpuMemoryError(HeatTpuRuntimeError):
    """Pre-flight HBM budget check predicted an overflow that degradation
    could not absorb."""


_SUFFIX = {"k": 1 << 10, "m": 1 << 20, "g": 1 << 30, "t": 1 << 40}
_BUDGET_RE = re.compile(r"^(\d+(?:\.\d+)?)\s*([kmgt]?)i?b?$")

# cache the parsed env var: (raw string, parsed bytes)
_BUDGET_CACHE: Tuple[Optional[str], Optional[int]] = (None, None)


def _parse_budget(raw: str) -> Optional[int]:
    m = _BUDGET_RE.match(raw.strip().lower().replace("_", ""))
    if not m:
        return None
    val = float(m.group(1)) * _SUFFIX.get(m.group(2), 1)
    return int(val) if val > 0 else None


def budget_bytes() -> Optional[int]:
    """The active HBM budget in bytes (``HEAT_TPU_HBM_BUDGET``), or None.
    Accepts plain byte counts or K/M/G/T suffixes (``"512M"``, ``"8G"``,
    ``"8GiB"``). Malformed values disable the guard (None)."""
    global _BUDGET_CACHE
    raw = knobs.raw("HEAT_TPU_HBM_BUDGET", "").strip()
    if not raw:
        return None
    cached_raw, cached_val = _BUDGET_CACHE
    if raw == cached_raw:
        return cached_val
    val = _parse_budget(raw)
    _BUDGET_CACHE = (raw, val)
    return val


# program-bytes memo: (id(fn), aval signature) -> bytes. Bounded LRU — an
# id() key can only go stale after the program-cache registry evicts the
# wrapper AND the allocator reuses the address, at which point the worst
# case is one wrong (but plausible) byte estimate.
_COST_CACHE: "OrderedDict[tuple, int]" = OrderedDict()
_COST_CACHE_MAX = 256


def _aval_sig(args: tuple) -> tuple:
    sig = []
    for a in args:
        shape = getattr(a, "shape", None)
        dtype = getattr(a, "dtype", None)
        if shape is not None and dtype is not None:
            sig.append((tuple(shape), str(dtype)))
        else:
            sig.append(repr(a)[:32])
    return tuple(sig)


def program_bytes(fn, args: tuple) -> int:
    """Temp + output bytes of the compiled executable for ``fn(*args)``
    (memoized). 0 when the program cannot be lowered/analyzed — the guard
    then budgets on live bytes alone rather than blocking dispatch."""
    key = (id(fn), _aval_sig(args))
    cached = _COST_CACHE.get(key)
    if cached is not None:
        _COST_CACHE.move_to_end(key)
        return cached
    b = 0
    try:
        compiled = fn.lower(*args).compile()
        ma = compiled.memory_analysis()
        b = int(
            getattr(ma, "temp_size_in_bytes", 0)
            + getattr(ma, "output_size_in_bytes", 0)
        )
    except Exception:
        b = 0
    _COST_CACHE[key] = b
    while len(_COST_CACHE) > _COST_CACHE_MAX:
        _COST_CACHE.popitem(last=False)
    return b


def _live_total() -> int:
    try:
        return int(telemetry.memory.live_bytes()["total"])
    except Exception:
        return 0


def headroom() -> Tuple[Optional[int], int]:
    """``(budget_bytes, live_bytes)`` — the two sides of the budget
    arithmetic in one call, shared by :func:`preflight`, the relayout
    planner's plan selection, and the serving admission controller
    (ISSUE 8), so every consumer compares the SAME quantities. Budget is
    None when the guard is unarmed (live bytes are then not measured:
    the disabled path stays one env read)."""
    budget = budget_bytes()
    if budget is None:
        return None, 0
    return budget, _live_total()


def _set_pressure(on: bool) -> None:
    from ..core import fusion

    fusion.set_pressure_cap(1 if on else None)


def preflight(site: str, fn, args: tuple) -> None:
    """Budget check before one guarded program dispatch (see module
    docstring). No-op without a budget; raises
    :class:`HeatTpuMemoryError` when degradation cannot make the
    prediction fit."""
    budget = budget_bytes()
    if budget is None:
        return
    need = program_bytes(fn, args)
    live = _live_total()
    if live + need <= budget:
        # comfortable headroom (< 50% of budget) releases the degraded
        # fusion window so throughput recovers once pressure subsides
        if live + need < budget // 2:
            from ..core import fusion

            if fusion.pressure_cap() is not None:
                _set_pressure(False)
        return
    # --- degradation ladder -------------------------------------------------
    if telemetry.enabled():
        reg = telemetry.get_registry()
        reg.add("resilience.memory_pressure", 1)
        reg.emit(
            "resilience", site, event="memory_pressure",
            live_bytes=live, program_bytes=need, budget=budget,
        )
    _set_pressure(True)   # 1. shrink future fusion windows
    gc.collect()          # 2. drop dead refs pinning device buffers
    live = _live_total()  # 3. re-measure
    if live + need <= budget:
        return
    if telemetry.enabled():
        telemetry.flush("memory_escalation")
    raise HeatTpuMemoryError(
        f"pre-flight HBM budget exceeded at site {site!r}: live {live:,} B "
        f"+ program {need:,} B > HEAT_TPU_HBM_BUDGET {budget:,} B "
        f"(after fusion window-flush and gc)",
        site=site,
        hints=[
            "raise HEAT_TPU_HBM_BUDGET or unset it to disable pre-flight "
            "budgeting",
            "shard the operand over more devices (resplit) so per-chip "
            "live bytes drop",
            "chunk the workload along the batch axis (cdist/manhattan do "
            "this automatically under the budget)",
            "relayouts decompose automatically under the budget "
            "(HEAT_TPU_RELAYOUT_PLAN, core/relayout_planner.py) — other "
            "sites may free buffers and retry",
        ],
    )


def temp_budget(default: int = 1 << 28) -> int:
    """Byte budget for one kernel's broadcast temporaries — ``default``
    without an HBM budget, else a quarter of it (floored at 1 MiB). The
    row-blocked distance kernels size their batch chunks with this."""
    b = budget_bytes()
    if b is None:
        return default
    return max(1 << 20, min(default, b // 4))
