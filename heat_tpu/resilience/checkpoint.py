"""Sharded checkpoint/restore with integrity checking (ISSUE 5 piece 4).

The orbax-backed :func:`heat_tpu.save_checkpoint` (core/io.py) depends on
an optional heavyweight stack (orbax → tensorstore). This module is the
dependency-free resilience twin used by the iterative-algorithm resume
hooks (``cluster.kmeans``, ``linalg.solver`` cg/lanczos, the DASO loop):
a checkpoint is a **directory** of per-shard ``.npy`` blobs plus one JSON
manifest, verifiable and restorable on any host with numpy.

Layout::

    <path>/
      manifest.json            # written LAST — its presence commits the dir
      leaf00000_shard000.npy   # one blob per mesh-position chunk
      leaf00001.npy            # plain arrays: one blob

Manifest schema (``format: "heat_tpu.checkpoint", version: 1``)::

    {"format": ..., "version": 1,
     "leaves": [
       {"kind": "dndarray", "gshape": [...], "split": 0, "dtype": "float32",
        "shards": [{"file": ..., "crc32": ..., "shape": [...]}, ...]},
       {"kind": "array", "file": ..., "crc32": ..., "dtype": ..., "shape": [...]},
       {"kind": "jax_sharded", "shape": [...], "dtype": ...,
        "shards": [{"file": ..., "crc32": ..., "index": [[lo, hi], ...]}, ...]},
       {"kind": "scalar", "value": 3.5, "type": "float"},
       {"kind": "none"}],
     "extra": {...}}           # caller state (iteration counters, schedules)

Integrity and atomicity:

* every blob carries a CRC32 of its **file bytes** (header included), so a
  flipped byte anywhere in a shard is detected at load
  (:class:`CheckpointCorruptError` names the file);
* a truncated or unparseable manifest is rejected cleanly
  (:class:`CheckpointError`), never a raw json/numpy traceback;
* writes go to ``<path>.tmp.<pid>`` and the directory is swapped into
  place only after the manifest lands — a run killed mid-save leaves the
  previous checkpoint intact (a stale ``.tmp.*`` sibling at worst).

DNDarray leaves are stored as their **per-mesh-position logical chunks**
(the ceil-rule slabs of :meth:`MeshCommunication.chunk` — tail pads never
touch disk) and restored via ``factories.array(split=...)``, so a
checkpoint written on one mesh restores on another mesh size: the manifest
records the logical layout, not the physical one.

Sharded **jax** arrays (FSDP/ZeRO parameter and state shards, ISSUE 18)
are written as one blob *per addressable shard*, streamed straight from
each device buffer — the full value is never gathered host-side, which
matters exactly when a leaf was sharded because it does not fit one
device. The manifest records each shard's index into the logical shape;
restore reassembles the logical array, so the next mesh (any
factorization) re-places it freely.
"""

from __future__ import annotations

import json
import os
import shutil
import zlib
from typing import Any, List, Optional, Tuple

import numpy as np

__all__ = [
    "CheckpointError",
    "CheckpointCorruptError",
    "save_checkpoint",
    "load_checkpoint",
    "exists",
]

FORMAT = "heat_tpu.checkpoint"
VERSION = 1


class CheckpointError(RuntimeError):
    """A checkpoint could not be written or read (bad manifest, missing
    blobs, structural mismatch)."""


class CheckpointCorruptError(CheckpointError):
    """A shard blob failed its CRC32 integrity check."""


def _crc32_file(path: str) -> int:
    crc = 0
    with open(path, "rb") as f:
        while True:
            block = f.read(1 << 20)
            if not block:
                break
            crc = zlib.crc32(block, crc)
    return crc


def _write_blob(dirpath: str, name: str, arr: np.ndarray) -> dict:
    """Write one ``.npy`` blob and return its manifest record."""
    fpath = os.path.join(dirpath, name)
    with open(fpath, "wb") as f:
        np.save(f, arr)
    return {
        "file": name,
        "crc32": _crc32_file(fpath),
        "shape": list(arr.shape),
        "dtype": str(arr.dtype),
    }


def _read_blob(dirpath: str, rec: dict) -> np.ndarray:
    name = rec.get("file")
    fpath = os.path.join(dirpath, name or "")
    if not name or not os.path.exists(fpath):
        raise CheckpointError(
            f"checkpoint blob {name!r} is missing from {dirpath!r}"
        )
    crc = _crc32_file(fpath)
    if crc != int(rec.get("crc32", -1)):
        raise CheckpointCorruptError(
            f"checkpoint shard {name!r} failed its CRC32 check "
            f"(stored {rec.get('crc32')}, computed {crc}) — the blob is "
            "corrupt; restore from an older checkpoint"
        )
    try:
        return np.load(fpath, allow_pickle=False)
    except (ValueError, OSError) as e:
        raise CheckpointCorruptError(
            f"checkpoint shard {name!r} is unreadable ({e})"
        ) from None


def _pack_leaf(x, dirpath: str, idx: int) -> dict:
    """One manifest record (+ blob files) per pytree leaf."""
    from ..core.dndarray import DNDarray

    if isinstance(x, DNDarray):
        host = x.numpy()  # logical global array (pads already sliced off)
        split = x.split
        shards = []
        if split is None:
            shards.append(_write_blob(dirpath, f"leaf{idx:05d}_shard000.npy", host))
        else:
            for r in range(x.comm.size):
                _, _, slices = x.comm.chunk(x.shape, split, r)
                shards.append(
                    _write_blob(
                        dirpath, f"leaf{idx:05d}_shard{r:03d}.npy",
                        np.ascontiguousarray(host[slices]),
                    )
                )
        return {
            "kind": "dndarray",
            "gshape": list(x.shape),
            "split": split,
            "dtype": x.dtype.__name__,
            "shards": shards,
        }
    if _is_sharded_jax_array(x):
        # sharded-param save (ISSUE 18): one blob PER ADDRESSABLE SHARD,
        # written straight from each device buffer — the full logical
        # array is never materialized host-side, which matters exactly
        # when FSDP sharded the leaf because it does not fit one device.
        # The manifest records each shard's index into the logical
        # shape, so restore reassembles (and the next mesh re-shards)
        # independent of this mesh's factorization.
        shards = []
        for s, sh in enumerate(x.addressable_shards):
            rec = _write_blob(
                dirpath, f"leaf{idx:05d}_shard{s:03d}.npy",
                np.ascontiguousarray(sh.data),
            )
            rec["index"] = [
                [sl.start, sl.stop] for sl in _norm_index(sh.index, x.shape)
            ]
            shards.append(rec)
        return {
            "kind": "jax_sharded",
            "shape": list(x.shape),
            "dtype": str(x.dtype),
            "shards": shards,
        }
    if isinstance(x, (np.ndarray, np.generic)) or hasattr(x, "__array__"):
        rec = _write_blob(dirpath, f"leaf{idx:05d}.npy", np.asarray(x))
        rec["kind"] = "array"
        return rec
    if x is None:
        return {"kind": "none"}
    if isinstance(x, (bool, int, float, str)):
        return {"kind": "scalar", "value": x, "type": type(x).__name__}
    if isinstance(x, complex):
        return {"kind": "scalar", "value": [x.real, x.imag], "type": "complex"}
    raise CheckpointError(
        f"cannot checkpoint leaf of type {type(x).__name__} — supported "
        "leaves are DNDarray, array-likes, scalars, and None"
    )


def _is_sharded_jax_array(x) -> bool:
    """A placed jax array whose shards do NOT all hold the full value —
    the leaves :func:`_pack_leaf` streams per-shard instead of gathering."""
    if not (hasattr(x, "addressable_shards") and hasattr(x, "sharding")):
        return False
    try:
        return not x.sharding.is_fully_replicated
    except Exception:
        return False


def _norm_index(index, shape) -> Tuple:
    """Normalize a shard's index (tuple of slices, possibly open-ended)
    to concrete ``slice(start, stop)`` per dimension."""
    out = []
    for sl, dim in zip(index, shape):
        start, stop, step = sl.indices(int(dim))
        if step != 1:
            raise CheckpointError(
                "cannot checkpoint a shard with a strided index"
            )
        out.append(slice(start, stop))
    return tuple(out)


def _unpack_leaf(rec: dict, dirpath: str, comm, device):
    kind = rec.get("kind")
    if kind == "jax_sharded":
        import jax.numpy as jnp

        shape = tuple(int(s) for s in rec.get("shape", []))
        host = np.empty(shape, dtype=np.dtype(rec.get("dtype", "float64")))
        seen = np.zeros(shape, dtype=bool) if shape else None
        for s in rec.get("shards", []):
            blob = _read_blob(dirpath, s)
            idx = tuple(slice(int(a), int(b)) for a, b in s.get("index", []))
            host[idx] = blob
            if seen is not None:
                seen[idx] = True
        if seen is not None and not seen.all():
            raise CheckpointError(
                "jax_sharded record does not cover the full logical shape "
                f"{shape} — shard set is incomplete"
            )
        return jnp.asarray(host)
    if kind == "dndarray":
        from ..core import types
        from ..core.factories import array as _array

        split = rec.get("split")
        parts = [_read_blob(dirpath, s) for s in rec.get("shards", [])]
        if not parts:
            raise CheckpointError("dndarray record carries no shards")
        if split is None:
            host = parts[0]
        else:
            host = (
                parts[0] if len(parts) == 1
                else np.concatenate(parts, axis=split)
            )
        gshape = tuple(rec.get("gshape", host.shape))
        if tuple(host.shape) != gshape:
            raise CheckpointError(
                f"reassembled shards give shape {tuple(host.shape)}, "
                f"manifest says {gshape} — shard set is incomplete"
            )
        dtype = getattr(types, rec.get("dtype", ""), None)
        return _array(host, dtype=dtype, split=split, comm=comm, device=device)
    if kind == "array":
        import jax.numpy as jnp

        return jnp.asarray(_read_blob(dirpath, rec))
    if kind == "scalar":
        v = rec.get("value")
        if rec.get("type") == "complex":
            return complex(v[0], v[1])
        return v
    if kind == "none":
        return None
    raise CheckpointError(f"unknown checkpoint leaf kind {kind!r}")


def save_checkpoint(state, path: str, *, extra: Optional[dict] = None) -> str:
    """Checkpoint a pytree of DNDarrays / arrays / scalars to the directory
    ``path`` (created or atomically replaced). ``extra`` is a free-form
    JSON-serializable dict stored in the manifest — iteration counters,
    schedule state. Returns ``path``.

    Write protocol: blobs + manifest land in ``<path>.tmp.<pid>`` first;
    only after the manifest is on disk is the directory swapped in, so a
    kill mid-save never destroys the previous checkpoint."""
    import jax

    path = os.fspath(path)
    tmp = f"{path}.tmp.{os.getpid()}"
    if os.path.exists(tmp):
        shutil.rmtree(tmp)
    os.makedirs(tmp)
    try:
        leaves = jax.tree.leaves(state, is_leaf=_is_leaf)
        records = [_pack_leaf(x, tmp, i) for i, x in enumerate(leaves)]
        manifest = {
            "format": FORMAT,
            "version": VERSION,
            "leaves": records,
            "extra": extra or {},
        }
        mpath = os.path.join(tmp, "manifest.json")
        with open(mpath, "w") as f:
            json.dump(manifest, f, indent=1)
            f.flush()
            os.fsync(f.fileno())
        # commit: swap the completed tmp dir into place. POSIX has no
        # atomic directory exchange, so there is a crash window between
        # the two renames where ``path`` is absent — load_checkpoint
        # recovers from it by falling back to the newest committed
        # .old./.tmp. sibling (both hold a complete manifest by this
        # point, and the manifest is always written last).
        if os.path.exists(path):
            old = f"{path}.old.{os.getpid()}"
            if os.path.exists(old):
                shutil.rmtree(old)
            os.rename(path, old)
            os.rename(tmp, path)
            shutil.rmtree(old, ignore_errors=True)
        else:
            os.rename(tmp, path)
        _reap_stale_siblings(path)
    except CheckpointError:
        shutil.rmtree(tmp, ignore_errors=True)
        raise
    except Exception as e:
        shutil.rmtree(tmp, ignore_errors=True)
        raise CheckpointError(f"checkpoint write to {path!r} failed: {e!r}") from e
    from .. import telemetry

    if telemetry.enabled():
        reg = telemetry.get_registry()
        reg.add("resilience.checkpoints_saved", 1)
        reg.emit("resilience", path, event="checkpoint_save",
                 leaves=len(records))
    return path


def _is_leaf(x) -> bool:
    from ..core.dndarray import DNDarray

    return isinstance(x, DNDarray)


def _sibling_dirs(path: str) -> List[str]:
    """Existing ``<path>.old.<pid>`` / ``<path>.tmp.<pid>`` siblings,
    newest first."""
    parent = os.path.dirname(os.path.abspath(path)) or "."
    base = os.path.basename(path)
    out = []
    try:
        names = os.listdir(parent)
    except OSError:
        return []
    for name in names:
        if name.startswith(base + ".old.") or name.startswith(base + ".tmp."):
            full = os.path.join(parent, name)
            if os.path.isdir(full):
                out.append(full)
    out.sort(key=lambda p: os.path.getmtime(p), reverse=True)
    return out


def _reap_stale_siblings(path: str) -> None:
    """Drop leftover .old./.tmp. siblings (any pid) after a successful
    commit — a crashed earlier process (different pid) can no longer
    clean up its own debris, and ``path`` now supersedes them all."""
    for d in _sibling_dirs(path):
        shutil.rmtree(d, ignore_errors=True)


def _resolve_checkpoint_dir(path: str) -> str:
    """``path`` itself when it holds a manifest; otherwise the newest
    .old./.tmp. sibling that does — recovery for a save killed inside the
    commit window (the manifest is written last, so any sibling carrying
    one is a complete checkpoint)."""
    if os.path.exists(os.path.join(path, "manifest.json")):
        return path
    for cand in _sibling_dirs(path):
        if os.path.exists(os.path.join(cand, "manifest.json")):
            import warnings

            warnings.warn(
                f"heat_tpu.resilience: checkpoint {path!r} is missing "
                f"(save interrupted mid-commit?); recovering from "
                f"{cand!r}"
            )
            return cand
    return path  # let load_manifest raise its clean error


def exists(path: str) -> bool:
    """Whether ``path`` holds a loadable checkpoint — including one
    stranded in a commit-window sibling that :func:`load_checkpoint`
    would recover. The resume hooks use this instead of a bare isdir so
    a crash mid-commit does not silently restart from scratch."""
    path = os.fspath(path)
    return os.path.exists(
        os.path.join(_resolve_checkpoint_dir(path), "manifest.json")
    )


def load_manifest(path: str) -> dict:
    """Read and validate the manifest of checkpoint directory ``path``.
    Raises :class:`CheckpointError` on a missing, truncated, or
    wrong-format manifest — never a raw json traceback."""
    path = os.fspath(path)
    mpath = os.path.join(path, "manifest.json")
    if not os.path.isdir(path) or not os.path.exists(mpath):
        raise CheckpointError(
            f"{path!r} is not a heat_tpu checkpoint (no manifest.json — "
            "an interrupted save leaves only a .tmp.* sibling)"
        )
    try:
        with open(mpath) as f:
            manifest = json.load(f)
    except (json.JSONDecodeError, OSError, UnicodeDecodeError) as e:
        raise CheckpointError(
            f"checkpoint manifest {mpath!r} is truncated or corrupt ({e})"
        ) from None
    if not isinstance(manifest, dict) or manifest.get("format") != FORMAT:
        raise CheckpointError(
            f"checkpoint manifest {mpath!r} has format "
            f"{manifest.get('format') if isinstance(manifest, dict) else '?'!r}, "
            f"expected {FORMAT!r}"
        )
    if int(manifest.get("version", -1)) > VERSION:
        raise CheckpointError(
            f"checkpoint {path!r} was written by a newer format version "
            f"({manifest.get('version')} > {VERSION})"
        )
    return manifest


def load_checkpoint(
    path: str,
    like=None,
    comm=None,
    device=None,
    *,
    with_extra: bool = False,
):
    """Restore a checkpoint written by :func:`save_checkpoint`.

    ``like`` (optional) supplies the pytree structure to rebuild; without
    it a flat leaf list is returned. DNDarray leaves reshard over ``comm``
    (default communicator when None) — the manifest stores the *logical*
    layout, so a different mesh size restores fine. Every shard's CRC32 is
    verified before use. ``with_extra=True`` returns ``(tree, extra)``.

    A save killed inside its commit window can leave ``path`` absent with
    the complete checkpoint stranded in a ``.old.``/``.tmp.`` sibling —
    that sibling is recovered automatically (with a warning)."""
    import jax

    path = _resolve_checkpoint_dir(os.fspath(path))
    manifest = load_manifest(path)
    records = manifest.get("leaves", [])
    leaves: List[Any] = [
        _unpack_leaf(rec, path, comm, device) for rec in records
    ]
    if like is not None:
        structure = jax.tree.structure(like, is_leaf=_is_leaf)
        if structure.num_leaves != len(leaves):
            raise CheckpointError(
                f"checkpoint {path!r} holds {len(leaves)} leaves but the "
                f"'like' structure expects {structure.num_leaves}"
            )
        tree = jax.tree.unflatten(structure, leaves)
    else:
        tree = leaves
    if with_extra:
        return tree, manifest.get("extra", {})
    return tree
