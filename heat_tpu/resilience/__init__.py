"""heat_tpu.resilience — fault injection, guarded retry dispatch,
memory-pressure degradation, and sharded checkpoint/restore (ISSUE 5).

Heat's MPI lineage is fail-stop: any rank error kills the job. A
production jax_graft deployment must instead survive transient runtime
faults, memory pressure, and mid-run interruption of long iterative
algorithms. PRs 1–4 concentrated every program dispatch into ONE
chokepoint (:func:`heat_tpu.core.program_cache.cached_program`) — this
package hangs the resilience machinery exactly there, the way JaxPP-style
multi-controller systems centralize failure handling at dispatch
(PAPERS.md, arXiv:2412.14374):

* :mod:`.faults` — deterministic, seeded fault injector
  (``HEAT_TPU_FAULTS=<spec>`` or :func:`inject`): synthetic
  RESOURCE_EXHAUSTED / connection-reset errors, latency, NaN corruption,
  per-site and per-call-index, fully reproducible for chaos CI;
* :mod:`.guard` — :func:`guarded_call` around every cached-program
  execution and explicit collective: transient-vs-permanent
  classification, capped exponential backoff + jitter
  (``HEAT_TPU_RETRIES``, default 0 = off), escalation to
  :class:`HeatTpuRuntimeError` with site + attempt history + hints;
* :mod:`.memory_guard` — pre-flight HBM budgeting
  (``HEAT_TPU_HBM_BUDGET``): live-bytes watermark + compiled-program
  temp/output bytes vs the budget, with a degradation ladder (fusion
  window-flush → gc → actionable :class:`HeatTpuMemoryError`);
* :mod:`.checkpoint` — per-shard ``.npy`` + JSON-manifest
  checkpoint/restore with CRC32 integrity and atomic directory swap;
  consumed by the ``checkpoint_every=``/``resume=`` hooks in
  ``cluster.KMeans``, ``linalg.solver.cg``/``lanczos`` and the DASO
  optimizer.

Zero-overhead contract: none of this runs until the package is **armed**
(retries > 0, faults installed, or a budget set). Disarmed, every program
dispatch pays exactly one module-flag check — the same design as
telemetry's disabled path. Arming state is computed once per
:func:`refresh` (import time, plus every programmatic change), never per
dispatch.
"""

from __future__ import annotations

import warnings

from . import checkpoint, faults, guard, memory_guard
from .checkpoint import (
    CheckpointCorruptError,
    CheckpointError,
    load_checkpoint,
    save_checkpoint,
)
from .faults import clear as clear_faults
from .faults import inject
from .guard import HeatTpuRuntimeError, guarded_call
from .memory_guard import HeatTpuMemoryError

__all__ = [
    "faults",
    "guard",
    "memory_guard",
    "checkpoint",
    "inject",
    "clear_faults",
    "guarded_call",
    "wrap_program",
    "armed",
    "refresh",
    "stats",
    "HeatTpuRuntimeError",
    "HeatTpuMemoryError",
    "CheckpointError",
    "CheckpointCorruptError",
    "save_checkpoint",
    "load_checkpoint",
]

# THE dispatch fast-path flag: wrap_program closures branch on this one
# module global. False ⇒ a guarded site is a plain call through one
# comparison; True ⇒ dispatch routes through guard/memory_guard.
_ARMED = False


def armed() -> bool:
    """Whether any resilience feature is active (retries requested, fault
    rules installed, or an HBM budget set)."""
    return _ARMED


def refresh() -> bool:
    """Recompute the armed flag from the injector's rule table and the
    environment (``HEAT_TPU_RETRIES`` / ``HEAT_TPU_HBM_BUDGET``). Called
    at import and by :func:`inject`/:func:`clear_faults`; call it manually
    after changing those env vars mid-process (tests)."""
    global _ARMED
    _ARMED = (
        faults.active()
        or guard.max_retries() > 0
        or memory_guard.budget_bytes() is not None
    )
    return _ARMED


def wrap_program(site: str, fn, *, donated: bool = False):
    """Wrap one compiled-program callable with the resilience dispatch
    path. Disarmed (the default), the wrapper is one flag check and a
    tail call; armed, execution runs the memory-guard preflight and the
    transient-retry guard. ``lower`` is forwarded so the HLO auditor and
    the memory guard can still AOT-compile the wrapped program.

    This is called ONCE per program-cache registry miss
    (core/program_cache.py) — the registry stores the wrapped callable, so
    the hot path pays no per-dispatch wrapping."""

    def call(*args, **kwargs):
        if not _ARMED:
            return fn(*args, **kwargs)
        if not kwargs and memory_guard.budget_bytes() is not None:
            memory_guard.preflight(site, fn, args)
        return guard.guarded_call(site, fn, args, kwargs, donated=donated)

    if hasattr(fn, "lower"):
        call.lower = fn.lower
    call.__wrapped__ = fn
    return call


def stats() -> dict:
    """Snapshot of the subsystem state: armed flag, retry config, fault
    rules/injections, and the HBM budget."""
    return {
        "armed": _ARMED,
        "retries": guard.max_retries(),
        "faults": faults.stats(),
        "hbm_budget": memory_guard.budget_bytes(),
    }


# -- environment activation (mirrors HEAT_TPU_TELEMETRY) ----------------------
# HEAT_TPU_FAULTS=<spec> installs injection rules at `import heat_tpu`;
# HEAT_TPU_RETRIES / HEAT_TPU_HBM_BUDGET arm their features the same way.
_spec = faults.env_spec()
if _spec:
    try:
        faults.install_spec(_spec)
    except ValueError as _e:  # pragma: no cover — bad spec must not kill import
        warnings.warn(
            f"heat_tpu.resilience: ignoring malformed HEAT_TPU_FAULTS spec "
            f"({_e})"
        )
del _spec
refresh()
