"""Guarded dispatch: classify → retry → escalate (ISSUE 5 tentpole piece 2).

Every cached-program execution and explicit collective wrapper routes
through :func:`guarded_call` when the resilience subsystem is armed
(retries requested, faults injected, or an HBM budget set — see the
package ``__init__``). The guard:

* asks the fault injector first (so synthetic faults land *before* the
  program dispatches — a retry re-executes the already-compiled program,
  never recompiles it; ``tests/test_resilience.py`` pins that with a
  CompileWatcher oracle);
* classifies exceptions into **transient** (injector synthetics, XLA
  ``RESOURCE_EXHAUSTED``, connection-reset-class transport errors,
  jaxlib runtime aborts) vs **permanent** (everything else — shape errors,
  user bugs — which propagate unchanged so existing error contracts hold);
* retries transients up to ``HEAT_TPU_RETRIES`` times with capped
  exponential backoff plus deterministic jitter
  (``HEAT_TPU_RETRY_BASE``/``HEAT_TPU_RETRY_CAP`` seconds);
* escalates an exhausted transient to :class:`HeatTpuRuntimeError`
  carrying the site, the full attempt history, and remediation hints —
  and flushes the telemetry sink first, so the counters/events of the
  dying run are on disk before the exception unwinds.

Telemetry: ``resilience.transient_faults`` / ``resilience.retries`` /
``resilience.gave_up`` counters plus one instant ``resilience`` event per
retry/escalation feed :func:`heat_tpu.telemetry.report.summarize` and the
Chrome trace.

Donation caveat: a program that donated its input buffer can only be
retried when the failure happened *before* XLA consumed the donation (the
injector's faults, allocator failures at launch). A mid-execution fault
after donation surfaces "Array has been deleted" on the retry — classified
permanent and escalated with a hint naming the donating site.
"""

from __future__ import annotations

import time
import zlib
from typing import Any, Callable, Dict, List, Optional

from heat_tpu import _knobs as knobs

from . import faults
from .. import telemetry

__all__ = [
    "HeatTpuRuntimeError",
    "classify",
    "guarded_call",
    "max_retries",
]

DEFAULT_BASE = 0.05  # seconds; first backoff
DEFAULT_CAP = 2.0    # seconds; backoff ceiling


class HeatTpuRuntimeError(RuntimeError):
    """A framework dispatch failed permanently (transient retries
    exhausted, or a memory budget could not be satisfied). Carries:

    * ``site`` — the program-cache/collective site that failed;
    * ``attempts`` — list of ``{"attempt", "error", "classification"}``
      dicts, one per try;
    * ``hints`` — actionable remediation strings (also in the message).
    """

    def __init__(
        self,
        message: str,
        *,
        site: Optional[str] = None,
        attempts: Optional[List[dict]] = None,
        hints: Optional[List[str]] = None,
    ):
        self.site = site
        self.attempts = list(attempts or [])
        self.hints = list(hints or [])
        if self.hints:
            message = message + "\n  remediation: " + "; ".join(self.hints)
        super().__init__(message)


def max_retries() -> int:
    """``HEAT_TPU_RETRIES`` (default 0 = retries off). Read live — only
    consulted once the package is armed, so the disabled hot path never
    touches the environment."""
    raw = knobs.raw("HEAT_TPU_RETRIES", "").strip()
    if raw:
        try:
            return max(0, int(raw))
        except ValueError:
            pass
    return 0


def _backoff_base() -> float:
    raw = knobs.raw("HEAT_TPU_RETRY_BASE", "").strip()
    try:
        return float(raw) if raw else DEFAULT_BASE
    except ValueError:
        return DEFAULT_BASE


def _backoff_cap() -> float:
    raw = knobs.raw("HEAT_TPU_RETRY_CAP", "").strip()
    try:
        return float(raw) if raw else DEFAULT_CAP
    except ValueError:
        return DEFAULT_CAP


# Substrings marking an exception message as transient-infrastructure.
# Lowercase; matched against str(exc).lower(). RESOURCE_EXHAUSTED is the
# XLA allocator's status code; DEADLINE_EXCEEDED/UNAVAILABLE are the
# runtime's RPC-layer codes; "aborted" covers jaxlib runtime aborts.
_TRANSIENT_MARKERS = (
    "resource_exhausted",
    "resource exhausted",
    "out of memory",
    "connection reset",
    "connection aborted",
    "socket closed",
    "deadline_exceeded",
    "deadline exceeded",
    "unavailable",
    "aborted",
)

# Messages that look transient but must NOT be retried: a donated (deleted)
# buffer can never come back, and retrying a shape error is pointless.
_PERMANENT_MARKERS = (
    "deleted",
    "donated",
)


def classify(exc: BaseException) -> str:
    """``"transient"`` or ``"permanent"`` for one exception instance."""
    if isinstance(exc, faults.InjectedFault):
        return "transient"
    if isinstance(exc, (ConnectionResetError, ConnectionAbortedError)):
        return "transient"
    msg = str(exc).lower()
    if any(m in msg for m in _PERMANENT_MARKERS):
        return "permanent"
    # XlaRuntimeError is not importable on every jaxlib; match by name up
    # the MRO so wrapped/renamed variants still classify
    names = {c.__name__ for c in type(exc).__mro__}
    runtime_like = bool(
        names & {"XlaRuntimeError", "JaxRuntimeError", "RuntimeError", "OSError"}
    )
    if runtime_like and any(m in msg for m in _TRANSIENT_MARKERS):
        return "transient"
    return "permanent"


def _sleep_backoff(site: str, attempt: int) -> None:
    base = _backoff_base()
    if base <= 0:
        return
    delay = min(_backoff_cap(), base * (2.0 ** attempt))
    # deterministic jitter in [0.75, 1.25) of the nominal delay — spreads
    # concurrent retriers without making test runs irreproducible
    u = zlib.crc32(f"{site}:{attempt}".encode()) / 2**32
    time.sleep(delay * (0.75 + 0.5 * u))


def _hints_for(site: str, last: BaseException, donated: bool) -> List[str]:
    hints = []
    msg = str(last).lower()
    if "resource" in msg or "memory" in msg:
        hints.append(
            "reduce operand size or set HEAT_TPU_HBM_BUDGET to pre-flight "
            "allocations (docs/RESILIENCE.md §budget)"
        )
    if donated:
        hints.append(
            f"site {site!r} donates its input buffer; a mid-execution fault "
            "cannot be replayed — re-create the source array and re-dispatch"
        )
    hints.append(
        "raise HEAT_TPU_RETRIES / HEAT_TPU_RETRY_CAP for flakier substrates"
    )
    return hints


def guarded_call(
    site: str,
    fn: Callable,
    args: tuple = (),
    kwargs: Optional[Dict[str, Any]] = None,
    *,
    donated: bool = False,
):
    """Execute ``fn(*args, **kwargs)`` under the fault injector and the
    transient-retry policy (see module docstring). Returns the call's
    result; permanent exceptions propagate unchanged; exhausted transients
    raise :class:`HeatTpuRuntimeError`."""
    kwargs = kwargs or {}
    retries = max_retries()
    attempts: List[dict] = []
    attempt = 0
    injector_on = faults.active()
    while True:
        try:
            directive = faults.check(site) if injector_on else None
            out = fn(*args, **kwargs)
        except Exception as e:  # noqa: BLE001 — classification decides
            cls = classify(e)
            attempts.append(
                {"attempt": attempt, "error": repr(e), "classification": cls}
            )
            if cls != "transient":
                if attempt == 0:
                    # first-attempt permanent errors propagate unchanged —
                    # existing error contracts (shape/type raises) hold
                    raise
                # a permanent error *mid-retry* (e.g. a donated buffer
                # deleted by the failed first execution) escalates with
                # the full history instead of a context-free raise
                if telemetry.enabled():
                    reg = telemetry.get_registry()
                    reg.add("resilience.gave_up", 1)
                    reg.emit(
                        "resilience", site, event="gave_up",
                        attempts=len(attempts), error=repr(e),
                    )
                    telemetry.flush("escalation")
                raise HeatTpuRuntimeError(
                    f"retry of site {site!r} hit a permanent error after "
                    f"{len(attempts) - 1} transient failure(s): {e!r}",
                    site=site,
                    attempts=attempts,
                    hints=_hints_for(site, e, donated),
                ) from e
            if telemetry.enabled():
                reg = telemetry.get_registry()
                reg.add("resilience.transient_faults", 1)
            if attempt >= retries:
                if telemetry.enabled():
                    reg = telemetry.get_registry()
                    reg.add("resilience.gave_up", 1)
                    reg.emit(
                        "resilience", site, event="gave_up",
                        attempts=len(attempts), error=repr(e),
                    )
                    telemetry.flush("escalation")
                raise HeatTpuRuntimeError(
                    f"transient fault at site {site!r} persisted through "
                    f"{len(attempts)} attempt(s) "
                    f"(HEAT_TPU_RETRIES={retries}); last error: {e!r}",
                    site=site,
                    attempts=attempts,
                    hints=_hints_for(site, e, donated),
                ) from e
            if telemetry.enabled():
                reg = telemetry.get_registry()
                reg.add("resilience.retries", 1)
                reg.emit(
                    "resilience", site, event="retry", attempt=attempt,
                    error=repr(e),
                )
            _sleep_backoff(site, attempt)
            attempt += 1
            continue
        if directive == "nan":
            out = _corrupt_nan(out)
        return out


def _corrupt_nan(out):
    """Poison every inexact array leaf of ``out`` with NaNs — the injected
    silent-corruption fault used to exercise downstream detection
    (checkpoint CRC validation, user-level finiteness checks).

    Tracer outputs are left untouched: a collective wrapper runs while a
    program is being *traced*, and poisoning a tracer would bake the
    corruption into the cached executable permanently — every later
    execution (long after ``clear_faults()``) would return NaNs. ``nan``
    faults therefore apply only at program-execution sites; trace-time
    sites count the injection but stay clean."""
    import jax
    import jax.numpy as jnp
    import numpy as np

    if any(
        isinstance(leaf, jax.core.Tracer) for leaf in jax.tree.leaves(out)
    ):
        return out

    def poison(x):
        if hasattr(x, "dtype") and jnp.issubdtype(
            np.dtype(x.dtype), np.inexact
        ):
            return x * jnp.asarray(float("nan"), dtype=x.dtype)
        return x

    return jax.tree.map(poison, out)
