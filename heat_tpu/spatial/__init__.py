"""Distance computations (reference: heat/spatial/)."""

from .distance import *
