"""Pairwise distance computations.

Re-design of reference heat/spatial/distance.py:136-494, whose engine
`_dist` (:209) is the reference's ring-communication showpiece: each rank
keeps a stationary row block and circulates moving blocks rank→rank+1 with
Send/Recv (:280-326), exploiting symmetry by shipping computed tiles back.
On TPU two paths replace it:

* **MXU path (default)**: the quadratic expansion ``‖a−b‖² = ‖a‖² + ‖b‖²
  − 2 a·bᵀ`` turns the whole distance matrix into one GEMM — this is where
  the FLOPs belong on TPU and it is the benchmark path.
* **Ring path** (`ring=True` or metric without a GEMM form): a `shard_map`
  kernel with the reference's schedule — stationary local rows, K-side
  blocks circulated with `jax.lax.ppermute` over ICI, `lax.fori_loop` over
  mesh steps. Same schedule as ring attention (SURVEY §5); peak memory per
  chip drops from O(n·m) to O(n·m/p).
"""

from __future__ import annotations

import warnings
from functools import partial
from typing import Callable, Optional

import jax
import jax.numpy as jnp

from ..core import program_cache, types
from ..core.dndarray import DNDarray
from .. import telemetry

__all__ = ["cdist", "manhattan", "rbf"]


def _quadratic_euclidean(x: jax.Array, y: jax.Array) -> jax.Array:
    """‖x_i − y_j‖ via the GEMM form, clamped for numerical safety.

    The GEMM runs at HIGH precision (bf16x3): on TPU the default bf16 passes
    lose ~1e-3 relative, which catastrophic cancellation at small distances
    (e.g. the cdist(X, X) diagonal) turns into absolute errors of ~0.3."""
    x2 = jnp.sum(x * x, axis=1, keepdims=True)
    y2 = jnp.sum(y * y, axis=1, keepdims=True).T
    d2 = x2 + y2 - 2.0 * jnp.matmul(x, y.T, precision=jax.lax.Precision.HIGH)
    return jnp.sqrt(jnp.maximum(d2, 0.0))


def _pairwise_euclidean(x: jax.Array, y: jax.Array) -> jax.Array:
    diff = x[:, None, :] - y[None, :, :]
    return jnp.sqrt(jnp.sum(diff * diff, axis=-1))


def _pairwise_manhattan(x: jax.Array, y: jax.Array) -> jax.Array:
    return jnp.sum(jnp.abs(x[:, None, :] - y[None, :, :]), axis=-1)


def _blocked_rows(fn, x: jax.Array, y: jax.Array, budget_bytes: Optional[int] = None) -> jax.Array:
    """Apply a pairwise *broadcast-form* block fn over row blocks of ``x`` so
    the (block, n, k) broadcast temporary stays under ``budget_bytes`` (the
    reference streams blocks rank-to-rank for the same reason,
    distance.py:280-326; single-chip the stream becomes a `lax.map` over row
    tiles). GEMM-form fns need no blocking — call them directly.

    The default budget is 256 MiB, shrunk by the resilience memory guard
    when ``HEAT_TPU_HBM_BUDGET`` is set (ISSUE 5 degradation ladder: the
    batch axis chunks to fit the declared budget instead of overflowing).
    Resolved at trace time — a program traced under one budget keeps its
    block size until the avals change."""
    if budget_bytes is None:
        from ..resilience import memory_guard

        budget_bytes = memory_guard.temp_budget(1 << 28)
    m, k = x.shape
    n = y.shape[0]
    per_row = max(1, n * k * x.dtype.itemsize)
    bs = max(1, min(m, budget_bytes // per_row))
    if bs >= m:
        return fn(x, y)
    nb = -(-m // bs)
    xp = jnp.pad(x, ((0, nb * bs - m), (0, 0)))
    out = jax.lax.map(lambda xb: fn(xb, y), xp.reshape(nb, bs, k))
    return out.reshape(nb * bs, n)[:m]


# Stable module-level block fns (identity-stable so the jit cache below hits).
_blocked_euclidean = partial(_blocked_rows, _pairwise_euclidean)
_blocked_manhattan = partial(_blocked_rows, _pairwise_manhattan)


@partial(jax.jit, static_argnums=(0, 3))
def _local_dist(block_fn, xm: jax.Array, ym: jax.Array, dt) -> jax.Array:
    """Single-dispatch local distance computation: cast + block fn compiled
    as one XLA program (eager per-op dispatch costs a host round-trip each)."""
    return block_fn(xm.astype(dt), ym.astype(dt))


@jax.jit
def _rbf_from_dist(d: jax.Array, gamma) -> jax.Array:
    return jnp.exp(-gamma * d * d)


def _ring_dist(
    x: DNDarray, y: DNDarray, block_fn: Callable, audit_cost=None
) -> jax.Array:
    """Ring-pipelined block distance matrix (reference distance.py:280-326).

    Both operands row-split. Each mesh position keeps its stationary x-block
    and circulates the y-block one hop per step; after p steps every position
    has filled its (local rows × all columns) slab. ``audit_cost`` (an
    analytic CollectiveCost) turns on the HLO collective audit of the
    kernel program (telemetry/hlo.py).

    Schedule (ISSUE 6): by default the loop body is **double-buffered** —
    the next hop's ppermute is issued *before* the current block's tile
    GEMM, so the permute carries no data dependence on the compute and
    XLA's latency-hiding scheduler can overlap the two — and the final
    dead hop (which only returns each block home) is peeled off, so the
    ring runs ``p-1`` hops instead of ``p``. Tile values and update
    order are untouched: the result is bit-identical to the serial
    schedule, which ``HEAT_TPU_RING_OVERLAP=0`` restores verbatim
    (core/relayout_planner.py `ring_overlap`)."""
    from ..core import relayout_planner

    comm = x.comm
    p = comm.size
    axis = comm.axis_name
    xm = x.larray
    ym = y.larray
    cy = ym.shape[0] // p
    n_cols = ym.shape[0]
    overlap = relayout_planner.ring_overlap() and p > 1

    def kernel(xb, yb):
        rank = jax.lax.axis_index(axis)
        out = jnp.zeros((xb.shape[0], n_cols), dtype=xb.dtype)
        # mark the accumulator as device-varying for the scan carry typing
        out = jax.lax.pcast(out, (axis,), to="varying")

        def tile_into(t, yblk, out):
            # the ring sends i→i+1, so after t hops shard i holds origin
            # (i−t) mod p
            col = ((rank - t) % p) * cy
            tile = block_fn(xb, yblk)
            zero = jnp.zeros((), dtype=col.dtype)
            return jax.lax.dynamic_update_slice(out, tile, (zero, col))

        if overlap:
            def step(t, carry):
                yblk, out = carry
                # hop FIRST (no dependence on the tile GEMM below — the
                # permute rides under the local compute), consume second
                ynext = comm.ring_permute(yblk)
                out = tile_into(t, yblk, out)
                return (ynext, out)

            yb, out = jax.lax.fori_loop(0, p - 1, step, (yb, out))
            # last block: compute only — the p-th hop of the serial
            # schedule moved data nobody consumed
            return tile_into(p - 1, yb, out)

        def step(t, carry):
            yblk, out = carry
            out = tile_into(t, yblk, out)
            # the comm wrapper (not raw lax.ppermute) so the hop is named
            # in telemetry's trace-time collective record
            yblk = comm.ring_permute(yblk)
            return (yblk, out)

        _, out = jax.lax.fori_loop(0, p, step, (yb, out))
        return out

    spec = comm.spec(0, 2)
    out_spec = spec
    # block_fn is a module-level function (stable identity), so the ring
    # program is shared across calls of the same kernel + layout family;
    # the schedule is part of the signature — serial and double-buffered
    # kernels never share a program. The collective-compression wire mode
    # (ISSUE 9 — the circulating y-block is re-quantized per hop under
    # HEAT_TPU_COLLECTIVE_PREC) is part of it too: modes key separate
    # programs and repeat dispatch per mode stays zero-recompile.
    from ..core import collective_prec

    wire = collective_prec.effective(ym.dtype)
    key = (block_fn, cy, n_cols, "overlap" if overlap else "serial", wire)
    smapped = program_cache.cached_program(
        "ring_cdist", key,
        lambda: jax.shard_map(
            kernel, mesh=comm.mesh, in_specs=(spec, spec),
            out_specs=out_spec,
        ),
        comm=comm,
    )
    if audit_cost is not None:
        # the audit lowers the SAME cached program the call executes
        telemetry.hlo.audit_call(
            "ring_cdist",
            lambda: (smapped, (xm, ym)),
            predicted=audit_cost,
            key=program_cache.program_key("ring_cdist", key, comm=comm),
            fields={"mesh": p},
        )
    return smapped(xm, ym)


def _pallas_local(
    comm, xbuf: jax.Array, yb: jax.Array, epilogue: str, gamma: float,
    interpret: bool = False,
) -> jax.Array:
    """Fused Pallas euclidean kernel over the local path's layout: x rows
    (possibly sharded split=0), y replicated. Single mesh: one call;
    multi-device: shard_map over the row shards (each computes its
    (local_rows, n) slab — the same decomposition as `_local_dist`, with
    the whole epilogue fused into the GEMM output tile). ``interpret``
    exists so the sharded wiring is testable on the CPU mesh."""
    from .pallas_cdist import euclid_pallas

    if comm.size == 1:
        return euclid_pallas(xbuf, yb, gamma, epilogue=epilogue, interpret=interpret)
    spec = comm.spec(0, 2)
    return jax.shard_map(
        lambda xb, yy: euclid_pallas(
            xb, yy, gamma, epilogue=epilogue, interpret=interpret
        ),
        mesh=comm.mesh,
        in_specs=(spec, comm.spec(None, 2)),
        out_specs=spec,
        # pallas_call's ShapeDtypeStruct outputs carry no vma annotation;
        # the varying-across-mesh check cannot see through the kernel
        check_vma=False,
    )(xbuf, yb)


def _dist(
    x: DNDarray,
    y: Optional[DNDarray],
    block_fn: Callable,
    ring_ok: bool,
    ring: bool,
    rbf_gamma: Optional[float] = None,
    audit: bool = False,
) -> DNDarray:
    """Distance engine (reference distance.py:209): result is
    (n_x, n_y) distributed along the rows of x. ``rbf_gamma`` composes the
    Gaussian-kernel epilogue — fused into the Pallas tile when that path
    runs, one extra compiled exp pass otherwise."""
    if not isinstance(x, DNDarray):
        raise TypeError(f"x must be a DNDarray, but was {type(x)}")
    if x.ndim != 2:
        raise NotImplementedError(f"x has {x.ndim} dimensions, expecting 2")
    if y is None:
        y = x
    if not isinstance(y, DNDarray):
        raise TypeError(f"y must be a DNDarray, but was {type(y)}")
    if y.ndim != 2:
        raise NotImplementedError(f"y has {y.ndim} dimensions, expecting 2")
    if x.shape[1] != y.shape[1]:
        raise ValueError(
            f"inputs must have the same number of features, got {x.shape[1]} and {y.shape[1]}"
        )
    if x.split is not None and x.split != 0:
        raise NotImplementedError("cdist requires x.split in (None, 0)")

    promoted = types.promote_types(types.promote_types(x.dtype, y.dtype), types.float32)
    out_split = 0 if x.split == 0 else None
    m, n = x.shape[0], y.shape[0]

    use_ring = (
        ring
        and ring_ok
        and x.split == 0
        and y.split == 0
        and x.comm.size > 1
    )
    def _finish(out):
        if rbf_gamma is not None:
            out = _rbf_from_dist(out, jnp.asarray(rbf_gamma, out.dtype))
        return DNDarray(out, (m, n), promoted, out_split, x.device, x.comm, True)

    if use_ring:
        # ring kernel works on the padded buffers; x pad rows land in output
        # pad rows, y pad columns are sliced off below. The hop count is
        # schedule-dependent: the double-buffered kernel skips the final
        # dead hop (p-1 hops), the serial kernel permutes p times.
        from ..core import relayout_planner

        p_ring = x.comm.size
        hops = p_ring - 1 if relayout_planner.ring_overlap() else p_ring
        from ..core import collective_prec

        ring_wire = collective_prec.effective(promoted.jnp_type())
        cost, fields, do_audit = telemetry.op_cost(
            telemetry.collectives.ring_cdist_cost, n, x.shape[1],
            promoted.byte_size(), x.comm.size, hops, ring_wire,
            collective_prec.block_size(), audit=audit,
        )
        with telemetry.span(
            "ring_cdist", gshape=[m, n], mesh=x.comm.size,
            overlap=hops < p_ring, **fields
        ) as sp:
            xm = x._masked(0).astype(promoted.jnp_type())
            ym = y._masked(0).astype(promoted.jnp_type())
            xw = DNDarray(xm, x.shape, promoted, 0, x.device, x.comm, True)
            yw = DNDarray(ym, y.shape, promoted, 0, y.device, y.comm, True)
            out = sp.output(
                _ring_dist(
                    xw, yw, block_fn,
                    audit_cost=cost if do_audit else None,
                )
            )
        out = out[:, :n]
        return _finish(out)

    # y's logical rows become output COLUMNS, whole on every row-shard (the
    # replicated-centers pattern): replicate via the compiled relayout when
    # y is split — multi-host safe, unlike the host-logical view
    yb = y._relayout(None) if y.split is not None else y.larray

    if block_fn is _quadratic_euclidean:
        from .pallas_cdist import pallas_cdist_applicable

        # multi-device needs x row-SHARDED (the shard_map decomposition);
        # a replicated x on a >1-device mesh keeps the XLA path
        layout_ok = x.comm.size == 1 or x.split == 0
        if layout_ok and pallas_cdist_applicable(x.shape[1], promoted.jnp_type()):
            epi = "rbf" if rbf_gamma is not None else "dist"
            try:
                out = _pallas_local(
                    x.comm,
                    x.larray.astype(promoted.jnp_type()),
                    yb.astype(promoted.jnp_type()),
                    epi,
                    0.0 if rbf_gamma is None else float(rbf_gamma),
                )
                # force materialization INSIDE the try: Mosaic/TPU runtime
                # faults surface lazily and must trigger the fallback here,
                # not at the caller's first read
                jax.block_until_ready(out)
            except Exception as e:  # pragma: no cover — TPU-runtime only
                # Mosaic lowering/runtime failure must degrade to the XLA
                # form, not kill the workload
                warnings.warn(f"pallas cdist fell back to XLA: {e!r}")
            else:
                return DNDarray(
                    out, (m, n), promoted, out_split, x.device, x.comm, True
                )

    out = _local_dist(block_fn, x.larray, yb, promoted.jnp_type())
    return _finish(out)


def cdist(X: DNDarray, Y: Optional[DNDarray] = None, quadratic_expansion: bool = False, ring: bool = False, audit: bool = False) -> DNDarray:
    """Euclidean distance matrix (reference distance.py:136).

    ``quadratic_expansion`` selects the GEMM form (reference offers the same
    switch); ``ring=True`` (extension) forces the ppermute ring kernel for
    O(n·m/p) per-chip memory when both operands are row-split.
    ``audit=True`` (or ``HEAT_TPU_HLO_AUDIT=1``) lower-compiles the ring
    kernel and diffs the collectives XLA actually emitted against the
    analytic cost model (telemetry/hlo.py)."""
    fn = _quadratic_euclidean if quadratic_expansion else _blocked_euclidean
    return _dist(X, Y, fn, ring_ok=True, ring=ring, audit=audit)


def manhattan(X: DNDarray, Y: Optional[DNDarray] = None, expand: bool = False, ring: bool = False, audit: bool = False) -> DNDarray:
    """City-block distance matrix (reference distance.py:186)."""
    return _dist(X, Y, _blocked_manhattan, ring_ok=True, ring=ring, audit=audit)


def rbf(
    X: DNDarray,
    Y: Optional[DNDarray] = None,
    sigma: float = 1.0,
    quadratic_expansion: bool = False,
    ring: bool = False,
    audit: bool = False,
) -> DNDarray:
    """Gaussian kernel matrix exp(−‖x−y‖²/2σ²) (reference distance.py:159).

    On TPU with the GEMM form, the exp epilogue fuses into the Pallas
    distance tile (no separate m×n exp pass); elsewhere it is one extra
    compiled pass over the distance matrix."""
    gamma = 1.0 / (2.0 * sigma * sigma)
    fn = _quadratic_euclidean if quadratic_expansion else _blocked_euclidean
    return _dist(X, Y, fn, ring_ok=True, ring=ring, rbf_gamma=gamma,
                 audit=audit)
