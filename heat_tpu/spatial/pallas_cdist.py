"""Pallas TPU kernel for GEMM-form pairwise euclidean distances.

The XLA path (:func:`heat_tpu.spatial.distance._quadratic_euclidean`)
computes ``sqrt(max(x2 + y2 - 2 x@yT, 0))`` as a dot plus broadcast
elementwise consumers; at bench shapes (m=n=16384, k=128) the m×n f32
intermediates dominate — several extra HBM round trips over the one
obligatory output write. This kernel fuses the whole epilogue into the
GEMM's output tile while it is still in VMEM: one HBM write total (the
r4 bench measured 7.2 TF/s counted on the XLA path; the output-bandwidth
roofline at these shapes permits ~30-50 TF/s).

Epilogues: ``dist`` (euclidean distance, the cdist result) and ``rbf``
(``exp(-gamma * d2)`` — the Gaussian kernel matrix directly, saving the
separate exp pass that :func:`heat_tpu.spatial.rbf` otherwise runs).

The in-kernel dot defaults to the manual ``"bf16x3"`` split product
(pallas_util.dot_f32) — HIGH-class accuracy, the documented guard against
catastrophic cancellation on the cdist(X, X) diagonal (distance.py:36-39),
from three DEFAULT-tier dots that provably land on the MXU.

Scope gate: f32 tiles with k ≤ 512 (the small-k regime where the epilogue
dominates; larger k is GEMM-bound and XLA's path is already fine — and
blocks must fit VMEM).

No reference analog (the reference's distance engine is ring-MPI torch,
distance.py:209); this is TPU-native plumbing under the same API.
"""

from __future__ import annotations

import functools
import warnings
from typing import Optional

import jax
import jax.numpy as jnp
import numpy as np
from jax.experimental import pallas as pl
from jax.experimental.pallas import tpu as pltpu

from ..core.pallas_util import DotPrecision, dot_f32
from heat_tpu import _knobs as knobs
from .. import telemetry

__all__ = ["euclid_pallas", "pallas_cdist_applicable", "cdist_precision"]

# jax_enable_x64 is on framework-wide: pin index-map literals to i32 (a
# Python-int 0 would trace as i64, which Mosaic cannot legalize — same
# guard as pallas_attention._I0)
_I0 = np.int32(0)

_MAX_K = 512  # f32 (bm, kp)+(bn, kp) tiles must fit VMEM; beyond this the
# workload is GEMM-bound and the XLA path is the right tool

# In-kernel dot strategy override. The "bf16x3" default is analysis-backed
# but UNMEASURED on hardware (advisor r5); until the scripts/tpu_tune.py
# sweep lands on-chip numbers, this env var is the one-line revert knob —
# no source edit, no redeploy (docs/TUNING_RUNBOOK.md).
_PREC_ENV = "HEAT_TPU_CDIST_PREC"
_PREC_VALUES = ("bf16x3", "default", "high", "highest")


def cdist_precision() -> DotPrecision:
    """The in-kernel dot strategy for the fused cdist kernel: ``"bf16x3"``
    unless ``HEAT_TPU_CDIST_PREC`` names one of ``bf16x3`` / ``default`` /
    ``high`` / ``highest`` (the ``jax.lax.Precision`` tiers). Read at call
    time, so a sweep can flip it between runs of one process."""
    v = (knobs.raw(_PREC_ENV, "") or "").strip().lower()
    if not v or v == "bf16x3":
        return "bf16x3"
    if v in _PREC_VALUES:
        return v.upper()  # dot_f32 resolves tier names via lax.Precision
    warnings.warn(
        f"{_PREC_ENV}={v!r} is not one of {_PREC_VALUES}; "
        "keeping the bf16x3 default"
    )
    return "bf16x3"


def _kernel(gamma_ref, x_ref, y_ref, o_ref, *, epilogue, precision):
    xb = x_ref[:]  # (bm, kp) f32
    yb = y_ref[:]  # (bn, kp) f32
    # contraction over k with f32 accumulation. ``precision`` is a
    # lax.Precision tier or "bf16x3" (manual MXU-guaranteed three-pass
    # split product, pallas_util.dot_f32) — HIGH-class accuracy is the
    # XLA path's documented cancellation guard (distance.py:36-39);
    # which strategy is fastest is measured by scripts/tpu_tune.py
    dot = dot_f32(xb, yb, (((1,), (1,)), ((), ())), precision)
    x2 = jnp.sum(xb * xb, axis=1, keepdims=True)  # (bm, 1)
    y2 = jnp.sum(yb * yb, axis=1)[None, :]  # (1, bn)
    d2 = jnp.maximum(x2 + y2 - jnp.float32(2.0) * dot, jnp.float32(0.0))
    if epilogue == "rbf":
        o_ref[:] = jnp.exp(-gamma_ref[0, 0] * d2)
    else:
        o_ref[:] = jnp.sqrt(d2)


def _round_up(v: int, m: int) -> int:
    return -(-v // m) * m


def euclid_pallas(
    x: jax.Array,
    y: jax.Array,
    gamma=0.0,
    *,
    epilogue: str = "dist",
    block_m: int = 512,
    block_n: int = 1024,
    interpret: bool = False,
    precision: Optional[DotPrecision] = None,
) -> jax.Array:
    """Fused pairwise euclidean kernel on one device's tiles.

    ``x`` (m, k) and ``y`` (n, k) f32; returns (m, n) f32 — the distance
    matrix (``epilogue='dist'``) or Gaussian kernel matrix
    (``epilogue='rbf'`` with ``gamma``). Inputs are zero-padded to block
    multiples (zero feature columns contribute nothing to dot or norms;
    pad rows are sliced off the result).

    With telemetry enabled, host-level calls become a ``pallas_cdist``
    span whose ``bytes`` is the kernel's one obligatory HBM output write
    (the quantity the fusion exists to minimize — see module docstring);
    calls from inside a trace (the sharded `shard_map` wrapping in
    distance.py hands tracers in) bypass instrumentation, since the span
    would measure trace time, not the kernel.

    ``precision=None`` (the default) resolves :func:`cdist_precision` —
    ``"bf16x3"`` unless the ``HEAT_TPU_CDIST_PREC`` env override names a
    ``jax.lax.Precision`` tier.
    """
    if precision is None:
        precision = cdist_precision()
    if telemetry.enabled() and not isinstance(x, jax.core.Tracer):
        m, n = int(x.shape[0]), int(y.shape[0])
        with telemetry.span(
            "pallas_cdist", bytes=m * n * 4, gshape=[m, n],
            epilogue=epilogue, hbm_write=True,
        ) as sp:
            return sp.output(
                _euclid_pallas_jit(
                    x, y, gamma, epilogue=epilogue, block_m=block_m,
                    block_n=block_n, interpret=interpret, precision=precision,
                )
            )
    return _euclid_pallas_jit(
        x, y, gamma, epilogue=epilogue, block_m=block_m, block_n=block_n,
        interpret=interpret, precision=precision,
    )


@functools.partial(
    jax.jit,
    static_argnames=("epilogue", "block_m", "block_n", "interpret", "precision"),
)
def _euclid_pallas_jit(
    x: jax.Array,
    y: jax.Array,
    gamma=0.0,
    *,
    epilogue: str = "dist",
    block_m: int = 512,
    block_n: int = 1024,
    interpret: bool = False,
    precision: DotPrecision = "bf16x3",
) -> jax.Array:
    m, k = x.shape
    n = y.shape[0]
    bm, bn = min(block_m, _round_up(m, 8)), min(block_n, _round_up(n, 128))
    # feature lanes pad at 64-granularity (k=64/128 stay unpadded)
    mp, np_, kp = _round_up(m, bm), _round_up(n, bn), _round_up(k, 64)
    if (mp, kp) != (m, k):
        x = jnp.pad(x, ((0, mp - m), (0, kp - k)))
    if (np_, kp) != (n, k):
        y = jnp.pad(y, ((0, np_ - n), (0, kp - k)))
    gamma_arr = jnp.asarray(gamma, jnp.float32).reshape(1, 1)

    out = pl.pallas_call(
        functools.partial(_kernel, epilogue=epilogue, precision=precision),
        grid=(mp // bm, np_ // bn),
        in_specs=[
            pl.BlockSpec((1, 1), lambda i, j: (_I0, _I0), memory_space=pltpu.SMEM),
            pl.BlockSpec((bm, kp), lambda i, j: (i, _I0), memory_space=pltpu.VMEM),
            pl.BlockSpec((bn, kp), lambda i, j: (j, _I0), memory_space=pltpu.VMEM),
        ],
        out_specs=pl.BlockSpec(
            (bm, bn), lambda i, j: (i, j), memory_space=pltpu.VMEM
        ),
        out_shape=jax.ShapeDtypeStruct((mp, np_), jnp.float32),
        compiler_params=pltpu.CompilerParams(
            dimension_semantics=("parallel", "parallel"),
        ),
        interpret=interpret,
    )(gamma_arr, x.astype(jnp.float32), y.astype(jnp.float32))
    return out[:m, :n]


def pallas_cdist_applicable(k: int, jnp_dtype) -> bool:
    """Whether the fused kernel covers this (k, dtype) on the current
    default backend (TPU only — interpret mode off-TPU would be a de-opt)."""
    return (
        jax.default_backend() == "tpu"
        and k <= _MAX_K
        and jnp_dtype == jnp.float32
    )
