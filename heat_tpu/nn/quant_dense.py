"""Int8 inference modules — W8A8 Dense over the Pallas quantized GEMM.

The reference has no quantization support; on TPU the int8 MXU path runs
~2× the bf16 rate (v5e: 394 vs 197 TOPS peak), so this is a pure
capability extension on the framework's inference hot path. Weights
quantize per-output-channel at call time (cheap, cacheable by jit);
activations quantize per-row. The matmul itself is
:func:`heat_tpu.core.linalg.int8_matmul` — int8 tiles, int32 VMEM
accumulation, fused f32 rescale.
"""

from __future__ import annotations

from typing import Any

import flax.linen as nn
import jax.numpy as jnp


class QuantDense(nn.Module):
    """Drop-in W8A8 variant of ``nn.Dense`` (no bias by default, matching
    the transformer blocks). Params stay float (training runs full
    precision elsewhere); quantization happens in the forward, so a
    trained float checkpoint loads directly.

    Note the cost of that convenience: the kernel re-quantizes on every
    call (under jit the kernel is a traced argument, so the absmax/round
    pass is part of the compiled step — it is NOT folded away). For a
    serving path where the weights are frozen, pre-quantize once and call
    the GEMM directly::

        qw, sw = quantize_int8(params[...]["kernel"], axis=0)
        y = int8_matmul(qx, sx, qw, sw)
    """

    features: int
    use_bias: bool = False
    dtype: Any = jnp.float32

    @nn.compact
    def __call__(self, x):
        from ..core.linalg import int8_matmul, quantize_int8

        d_in = x.shape[-1]
        kernel = self.param(
            "kernel", nn.initializers.lecun_normal(), (d_in, self.features),
            jnp.float32,
        )
        lead = x.shape[:-1]
        xf = x.reshape(-1, d_in).astype(jnp.float32)
        qx, sx = quantize_int8(xf, axis=1)
        qw, sw = quantize_int8(kernel, axis=0)
        y = int8_matmul(qx, sx, qw, sw, out_dtype=jnp.float32)
        if self.use_bias:
            bias = self.param(
                "bias", nn.initializers.zeros, (self.features,), jnp.float32
            )
            y = y + bias
        return y.reshape(*lead, self.features).astype(self.dtype)
