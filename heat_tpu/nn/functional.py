"""Functional nn ops: jax.nn passthrough + distributed attention entry point.

Reference parity: ``heat.nn.functional`` forwards to ``torch.nn.functional``
(reference heat/nn/functional.py). Here unknown names resolve to ``jax.nn``
(relu, gelu, softmax, one_hot, …); the module's own surface is the
long-context attention front-end over :mod:`heat_tpu.parallel`.
"""

from __future__ import annotations

from typing import Optional, Union

import jax
import jax.numpy as jnp

from ..core.dndarray import DNDarray
from ..parallel import local_attention, ring_attention, ulysses_attention

__all__ = ["dense", "scaled_dot_product_attention"]


def dense(x, w, bias=None, activation=None):
    """Affine layer ``activation(x @ w + bias)`` on DNDarrays — the DP
    forward building block, expressed entirely in framework ops so the
    Fusion 2.0 engine compiles it as ONE cached program: the matmul is a
    lazy kernel node (core/fusion.py ``defer_matmul``) and the bias add +
    activation graft onto it as the kernel's epilogue. With
    ``HEAT_TPU_FUSION_REDUCE=0`` the same expression dispatches op by op,
    bit for bit.

    ``activation`` is ``None``, one of ``"relu"`` / ``"tanh"`` /
    ``"sigmoid"`` (compositions of fusable framework ops), or any callable
    taking and returning a DNDarray (a callable built from non-framework
    ops will flush the kernel first — still correct, just not one
    program)."""
    from ..core import arithmetics, exponential, statistics, trigonometrics
    from ..core.linalg import matmul

    y = matmul(x, w)
    if bias is not None:
        y = arithmetics.add(y, bias)
    if activation is None:
        return y
    if callable(activation):
        return activation(y)
    if activation == "relu":
        return statistics.maximum(y, 0.0)
    if activation == "tanh":
        return trigonometrics.tanh(y)
    if activation == "sigmoid":
        # 1 / (1 + exp(-y)) as fusable framework ops
        return arithmetics.div(
            1.0, arithmetics.add(exponential.exp(arithmetics.mul(y, -1.0)), 1.0)
        )
    raise ValueError(
        f"activation must be None, 'relu', 'tanh', 'sigmoid' or a callable, "
        f"got {activation!r}"
    )


def scaled_dot_product_attention(
    q: Union[jax.Array, DNDarray],
    k: Union[jax.Array, DNDarray],
    v: Union[jax.Array, DNDarray],
    *,
    causal: bool = False,
    scale: Optional[float] = None,
    strategy: str = "auto",
    comm=None,
) -> Union[jax.Array, DNDarray]:
    """softmax(QKᵀ/√d)V with ``(batch, seq, heads, head_dim)`` layout.

    Dispatch: DNDarrays split along the sequence axis (axis 1) run the
    distributed kernels — ``strategy`` picks ``"ring"`` (K/V circulated over
    ICI, any head count) or ``"ulysses"`` (all_to_all head↔seq swap, needs
    heads % mesh size == 0); ``"auto"`` prefers ulysses when it applies since
    it does fewer hops. Everything else (replicated DNDarrays, raw arrays)
    runs the single-device blockwise kernel.
    """
    if strategy not in ("auto", "ring", "ulysses"):
        raise ValueError(
            f"strategy must be 'auto', 'ring' or 'ulysses', got {strategy!r}"
        )
    is_dnd = isinstance(q, DNDarray)
    if is_dnd:
        if not (isinstance(k, DNDarray) and isinstance(v, DNDarray)):
            raise TypeError("q, k, v must all be DNDarray or all jax.Array")
        if not (q.split == k.split == v.split):
            raise ValueError(
                f"q/k/v splits must match, got {q.split}/{k.split}/{v.split}"
            )
        comm = q.comm
        if q.ndim != 4:
            raise ValueError(f"expected (B, T, H, D) inputs, got ndim={q.ndim}")
        if q.split == 1 and comm.size > 1:
            seq_len = q.shape[1]
            h = q.shape[2]
            if strategy == "auto":
                strategy = "ulysses" if h % comm.size == 0 else "ring"
            fn = {"ring": ring_attention, "ulysses": ulysses_attention}[strategy]
            out = fn(
                q._masked(0), k._masked(0), v._masked(0),
                comm=comm, causal=causal, scale=scale, seq_len=seq_len,
            )
            return DNDarray(
                out, q.shape, q.dtype, q.split, q.device, comm, True
            )
        if q.split not in (None, 1):
            raise NotImplementedError(
                f"attention over split={q.split} not supported; resplit to 1"
            )
        out = local_attention(
            q._replicated(), k._replicated(), v._replicated(), causal=causal, scale=scale
        )
        return DNDarray.from_logical(out, q.split, q.device, q.comm)

    return local_attention(q, k, v, causal=causal, scale=scale)


def __getattr__(name):
    """jax.nn passthrough (reference functional.py func_getattr analog)."""
    try:
        return getattr(jax.nn, name)
    except AttributeError:
        raise AttributeError(
            f"function {name} not implemented in jax.nn or heat_tpu.nn.functional"
        ) from None
