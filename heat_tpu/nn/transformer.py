"""Transformer blocks over the device mesh — the flagship model family.

The reference framework has no transformer/attention code at all (SURVEY
§2.5: "no transformer code"); its parallelism mechanisms (ring schedule,
axis-aware Alltoall) are exactly what long-context attention is made of.
This module is the capability those mechanisms exist for, built TPU-first
as flax modules:

* :class:`TransformerBlock` — pre-LN block: attention (XLA online-softmax,
  the Pallas flash kernel, or a sequence-parallel schedule) + SwiGLU MLP.
* :class:`TransformerLM` — embedding → N blocks → final LN → logit
  projection; a complete causal LM forward.

Parallelism is selected by ``attn_impl``:

- ``"local"`` — single-shard XLA blockwise attention.
- ``"flash"`` — the hand-tiled Pallas kernel
  (:func:`heat_tpu.parallel.flash_attention`, 2.7× the XLA path on v5e).
- ``"ring"`` / ``"ulysses"`` — sequence-parallel over a mesh axis, for
  sequences sharded with :class:`heat_tpu.MeshCommunication` (pass
  ``comm=``). Ring keeps K/V moving over ICI; ulysses swaps sequence↔heads
  with two all_to_alls.

Weights are plain flax params — shard them with `jax.sharding` NamedSharding
(tp: column/row-split the Dense kernels; dp: replicate) exactly as any flax
model; the dryrun (`__graft_entry__.py`) exercises a dp×sp layout.
"""

from __future__ import annotations

from typing import Any, Optional

import flax.linen as nn
import jax
import jax.numpy as jnp


def _attend(q, k, v, *, impl, causal, comm, block_size, flash_bwd_impl):
    from ..parallel import (
        flash_attention,
        local_attention,
        ring_attention,
        ulysses_attention,
    )

    if impl == "flash":
        if block_size is None:
            return flash_attention(  # tuned tiles
                q, k, v, causal=causal, bwd_impl=flash_bwd_impl
            )
        return flash_attention(
            q, k, v, causal=causal, block_q=block_size, block_k=block_size,
            bwd_impl=flash_bwd_impl,
        )
    if impl == "ring":
        # the ring processes one mesh chunk per hop; there is no block knob
        return ring_attention(q, k, v, comm=comm, causal=causal)
    if impl == "ulysses":
        return ulysses_attention(
            q, k, v, comm=comm, causal=causal,
            block_size=512 if block_size is None else block_size,
        )
    return local_attention(
        q, k, v, causal=causal,
        block_size=512 if block_size is None else block_size,
    )


class MultiHeadAttention(nn.Module):
    """QKV projection → blockwise attention → output projection.

    ``(B, T, D_model)`` in and out; the attention core runs in
    ``(B, T, H, D_head)`` layout shared by every impl, so switching
    single-chip ↔ sequence-parallel changes no weights.
    """

    num_heads: int
    attn_impl: str = "local"
    causal: bool = True
    comm: Optional[Any] = None
    block_size: Optional[int] = None  # None = each impl's tuned default
    dtype: Any = jnp.float32
    # flash backward strategy (pallas_attention.flash_attention bwd_impl)
    flash_bwd_impl: str = "two_pass"

    @nn.compact
    def __call__(self, x):
        d_model = x.shape[-1]
        if d_model % self.num_heads:
            raise ValueError(f"d_model {d_model} not divisible by {self.num_heads} heads")
        d_head = d_model // self.num_heads
        dense = lambda name: nn.DenseGeneral(  # noqa: E731
            (self.num_heads, d_head), axis=-1, use_bias=False,
            dtype=self.dtype, name=name,
        )
        q, k, v = dense("query")(x), dense("key")(x), dense("value")(x)
        o = _attend(
            q, k, v, impl=self.attn_impl, causal=self.causal, comm=self.comm,
            flash_bwd_impl=self.flash_bwd_impl,
            block_size=self.block_size,
        )
        return nn.DenseGeneral(
            d_model, axis=(-2, -1), use_bias=False, dtype=self.dtype, name="out"
        )(o)


class TransformerBlock(nn.Module):
    """Pre-LN residual block: x + attn(LN(x)); x + swiglu(LN(x))."""

    num_heads: int
    mlp_ratio: float = 4.0
    attn_impl: str = "local"
    causal: bool = True
    comm: Optional[Any] = None
    block_size: Optional[int] = None  # None = each impl's tuned default
    dtype: Any = jnp.float32
    flash_bwd_impl: str = "two_pass"

    @nn.compact
    def __call__(self, x):
        d_model = x.shape[-1]
        h = nn.LayerNorm(dtype=self.dtype, name="ln1")(x)
        x = x + MultiHeadAttention(
            self.num_heads, self.attn_impl, self.causal, self.comm,
            self.block_size, self.dtype, self.flash_bwd_impl, name="attn",
        )(h)
        h = nn.LayerNorm(dtype=self.dtype, name="ln2")(x)
        d_ff = int(d_model * self.mlp_ratio)
        gate = nn.Dense(d_ff, use_bias=False, dtype=self.dtype, name="gate")(h)
        up = nn.Dense(d_ff, use_bias=False, dtype=self.dtype, name="up")(h)
        h = nn.silu(gate) * up  # SwiGLU: two MXU GEMMs + one VPU fuse
        return x + nn.Dense(d_model, use_bias=False, dtype=self.dtype, name="down")(h)


class TransformerLM(nn.Module):
    """Causal LM: token embedding → blocks → final LN → tied-untied logits."""

    vocab_size: int
    d_model: int
    num_heads: int
    num_layers: int
    max_len: int = 2048
    mlp_ratio: float = 4.0
    attn_impl: str = "local"
    comm: Optional[Any] = None
    block_size: Optional[int] = None  # None = each impl's tuned default
    remat: bool = False  # checkpoint each block: O(L) -> O(1) activations
    # None = full recompute; "dots" = save MXU dot outputs and recompute
    # only the cheap elementwise ops (jax.checkpoint_policies.
    # dots_with_no_batch_dims_saveable) — usually faster when HBM allows
    remat_policy: Optional[str] = None
    dtype: Any = jnp.float32
    flash_bwd_impl: str = "two_pass"

    @nn.compact
    def __call__(self, tokens):
        if tokens.shape[-1] > self.max_len:
            # nn.Embed's gather would silently clamp positions past the
            # table instead of erroring
            raise ValueError(
                f"sequence length {tokens.shape[-1]} exceeds max_len {self.max_len}"
            )
        x = nn.Embed(self.vocab_size, self.d_model, dtype=self.dtype, name="embed")(tokens)
        pos = nn.Embed(self.max_len, self.d_model, dtype=self.dtype, name="pos")(
            jnp.arange(tokens.shape[-1])
        )
        x = x + pos[None]
        # rematerialization trades backward-pass FLOPs for activation
        # memory — the standard long-context recipe (HBM is the bottleneck)
        if self.remat:
            if self.remat_policy == "dots":
                import jax

                block_cls = nn.remat(
                    TransformerBlock,
                    policy=jax.checkpoint_policies.dots_with_no_batch_dims_saveable,
                )
            else:
                block_cls = nn.remat(TransformerBlock)
        else:
            block_cls = TransformerBlock
        for i in range(self.num_layers):
            x = block_cls(
                self.num_heads, self.mlp_ratio, self.attn_impl, True,
                self.comm, self.block_size, self.dtype,
                self.flash_bwd_impl, name=f"block{i}",
            )(x)
        x = nn.LayerNorm(dtype=self.dtype, name="ln_f")(x)
        return nn.Dense(self.vocab_size, use_bias=False, dtype=self.dtype, name="lm_head")(x)
