"""Mixture-of-experts layer — expert parallelism (ep) over the mesh.

The reference has no MoE (its parallelism is DP-only, SURVEY §2.5); this
is the TPU-native strategy expressed the XLA way: routing builds static
``(tokens, experts, capacity)`` dispatch/combine tensors (Switch top-1,
capacity-factor bounded — over-capacity tokens drop to the residual,
standard behavior), the dispatch/expert/combine contractions are three
einsums, and a single ``with_sharding_constraint`` on the expert axis
makes XLA insert the token all_to_alls — no hand-written collective
choreography, exactly the "let the compiler place the collectives"
design stance of the framework (SURVEY §7).
"""

from __future__ import annotations

import math
from typing import Any, Optional

import flax.linen as nn
import jax
import jax.numpy as jnp


class MoEMLP(nn.Module):
    """Switch-style top-1 MoE feed-forward: gate → dispatch → per-expert
    SwiGLU-free MLP (silu) → combine. ``(B, T, D)`` in and out.

    Pass ``comm=`` to shard the expert axis over the mesh (``n_experts``
    divisible by ``comm.size``); without it the layer is a single-shard
    reference implementation with identical numerics.
    """

    n_experts: int
    d_ff: int
    capacity_factor: float = 1.25
    comm: Optional[Any] = None
    dtype: Any = jnp.float32

    @nn.compact
    def __call__(self, x):
        b, t, d = x.shape
        n_tok = b * t
        xt = x.reshape(n_tok, d)

        logits = nn.Dense(
            self.n_experts, use_bias=False, dtype=self.dtype, name="gate"
        )(xt)
        probs = jax.nn.softmax(logits.astype(jnp.float32), axis=-1)
        expert = jnp.argmax(probs, axis=-1)  # (N,) top-1
        gate_w = jnp.take_along_axis(probs, expert[:, None], axis=-1)[:, 0]

        cap = int(math.ceil(n_tok / self.n_experts * self.capacity_factor))
        e_onehot_i = jax.nn.one_hot(expert, self.n_experts, dtype=jnp.int32)
        # 1-indexed arrival position of each token within its expert queue —
        # integer cumsum: an f32 one loses exact positions past 2^24 tokens
        pos = jnp.cumsum(e_onehot_i, axis=0) * e_onehot_i
        keep = (pos > 0) & (pos <= cap)
        pos0 = jnp.clip(pos - 1, 0, cap - 1)
        slot = jax.nn.one_hot(pos0, cap, dtype=jnp.float32)  # (N, E, C)
        dispatch = slot * keep[..., None].astype(jnp.float32)
        combine = dispatch * gate_w[:, None, None]

        w_in = self.param(
            "w_in",
            nn.initializers.lecun_normal(),
            (self.n_experts, d, self.d_ff),
            self.dtype,
        )
        w_out = self.param(
            "w_out",
            nn.initializers.lecun_normal(),
            (self.n_experts, self.d_ff, d),
            self.dtype,
        )

        expert_in = jnp.einsum("nd,nec->ecd", xt.astype(self.dtype), dispatch.astype(self.dtype))
        expert_in = self._shard_experts(expert_in)
        h = nn.silu(jnp.einsum("ecd,edf->ecf", expert_in, self._shard_experts(w_in)))
        expert_out = jnp.einsum("ecf,efd->ecd", h, self._shard_experts(w_out))
        out = jnp.einsum("ecd,nec->nd", expert_out, combine.astype(self.dtype))
        return out.reshape(b, t, d)

    def _shard_experts(self, arr):
        if self.comm is None:
            return arr
        if self.n_experts % self.comm.size:
            raise ValueError(
                f"n_experts {self.n_experts} not divisible by mesh size "
                f"{self.comm.size}"
            )
        return jax.lax.with_sharding_constraint(
            arr, self.comm.sharding(0, arr.ndim)
        )
