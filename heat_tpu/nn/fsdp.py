"""Fully-sharded data parallelism (ISSUE 18) — the :class:`DataParallel`
twin whose parameters live as flat 1/p shards on the mesh.

ZeRO (PR 15) sharded the optimizer *state* but kept every parameter
replicated; :class:`FSDP` closes the gap for the big-model scenario
(ROADMAP item 3): parameters persist in the
:func:`heat_tpu.parallel.fsdp.fsdp_shard` layout across steps, and each
layer's weights are all-gathered just-in-time
(:func:`heat_tpu.parallel.fsdp.fsdp_gather` — tiered under
``HEAT_TPU_HIERARCHICAL=1``, wire-compressed per partition rule),
consumed, and dropped. Layouts come from a regex
:class:`~heat_tpu.parallel.fsdp.PartitionRules` table, so arbitrary
pytrees — not just the nn/ demos — get placements declaratively.

Two memory disciplines bound the transient footprint:

* **Per-stage rematerialization** — each stage's gather sits INSIDE its
  ``jax.checkpoint`` region, so the backward re-gathers weights instead
  of holding every layer's full parameters as residuals (the
  arXiv:2112.01075 bounded-decomposition discipline, applied to the
  weight stream the way PR 6 applied it to relayout).
* **Prefetch windowing** — ``HEAT_TPU_FSDP_PREFETCH`` depth ``d`` issues
  stage ``k``'s gather alongside stage ``k−d``'s compute (the
  communication-scheduling recipe of arXiv:2211.05322): an
  ``optimization_barrier`` ties each gather's chunk inputs to the
  activation produced ``d`` stages earlier, so XLA may hide the gather
  under the GEMMs but can NOT hoist every gather to the top of the
  program — at most ``d+1`` stages' full weights are live at once.
  Depth 0 is fully serial. Pure scheduling: outputs are bit-identical
  at every depth.

``HEAT_TPU_FSDP=0`` (the default) keeps the replicated
:class:`DataParallel` dispatch bit-for-bit — same program family, same
cache site — so the knob is a pure opt-in. ZeRO composes: the optimizer
state follows the sharded parameter layout (sharded state over sharded
params), and checkpoints are written in the topology-independent
*logical* form, so a run restarted on a different mesh factorization
restores bit-exactly (the same property
:class:`~heat_tpu.optim.ZeroOptimizer` pins).
"""

from __future__ import annotations

from typing import Any, Callable, List, Optional, Tuple

import jax
import jax.numpy as jnp
import optax
from jax.sharding import PartitionSpec as P

from heat_tpu import _knobs as knobs

from ..core import program_cache
from ..core.communication import MeshCommunication, sanitize_comm
from ..parallel import fsdp as _fsdp
from .data_parallel import DataParallel, _module_apply

__all__ = ["FSDP"]


def _tie(tree: Any, token):
    """Schedule barrier: the returned tree is value-identical to
    ``tree``, but XLA cannot start any op consuming it before ``token``
    (an activation) exists — the prefetch-window bound. Differentiable
    as the identity (``optimization_barrier`` has no built-in rule):
    leaf cotangents pass straight through, and ``token``'s gradient path
    is cut — its real cotangent flows through the stage that actually
    consumes the activation, not through the barrier."""
    leaves, treedef = jax.tree_util.tree_flatten(tree)
    if not leaves:
        return tree
    tok = jax.lax.stop_gradient(token)

    def impl(args):
        out = jax.lax.optimization_barrier(tuple(args))
        return tuple(out[:-1])

    @jax.custom_vjp
    def barrier(*args):
        return impl(args)

    def fwd(*args):
        return impl(args), None

    def bwd(_, ct):
        return tuple(ct) + (jnp.zeros(tok.shape, tok.dtype),)

    barrier.defvjp(fwd, bwd)
    out = barrier(*(tuple(leaves) + (tok,)))
    return jax.tree_util.tree_unflatten(treedef, list(out))


class FSDP(DataParallel):
    """Fully-sharded data parallelism over the communicator's mesh.

    Parameters
    ----------
    module : flax.linen.Module, callable, or a sequence of them
        A single network, or a SEQUENCE of stages applied left-to-right
        (``x = stage_k(params_k, x)``). The sequential form is the one
        that bounds transient memory and overlaps gathers with compute:
        weights gather per stage, not all at once. A single module
        gathers everything up front — still a persistent-memory win
        (params live 1/p between steps), but no per-layer streaming.
    comm : MeshCommunication, optional
        Mesh whose single axis is the data-parallel axis.
    optimizer : optax.GradientTransformation, optional
        Bound optimizer used by :meth:`make_train_step` /
        :meth:`init_opt_state`.
    rules : heat_tpu.parallel.PartitionRules, optional
        The layout table (default: shard every non-scalar leaf).
    precision : str, optional
        Instance-wide wire override for gathers whose rule pins none
        (``off | bf16 | int8 | blockwise``); default inherits the
        :func:`heat_tpu.core.topology.fsdp_wire` chain.
    prefetch : int, optional
        Gather-prefetch depth; default ``HEAT_TPU_FSDP_PREFETCH``.

    The ``HEAT_TPU_FSDP`` knob and prefetch depth are resolved at
    construction (like ZeroOptimizer's wire mode): the layout is part of
    the training state, not something to flip mid-run.
    """

    def __init__(
        self,
        module,
        comm: Optional[MeshCommunication] = None,
        optimizer=None,
        rules=None,
        precision: Optional[str] = None,
        prefetch: Optional[int] = None,
    ):
        self._multi = isinstance(module, (list, tuple))
        stages = list(module) if self._multi else [module]
        self.stage_apply: List[Callable] = [_module_apply(m) for m in stages]
        self.stages = stages
        multi = self._multi
        stage_apply = self.stage_apply

        def full_apply(params, *inputs):
            x = inputs[0]
            for f, sp in zip(stage_apply, params if multi else [params]):
                x = f(sp, x)
            return x

        super().__init__(
            full_apply, comm, optimizer, blocking_parameter_updates=True
        )
        self.module = module
        self.rules = (
            rules if rules is not None else _fsdp.PartitionRules.fsdp_default()
        )
        self.precision = precision
        self.enabled = bool(knobs.get("HEAT_TPU_FSDP"))
        self.prefetch = int(
            prefetch
            if prefetch is not None
            else knobs.get("HEAT_TPU_FSDP_PREFETCH")
        )
        if self.prefetch < 0:
            raise ValueError(f"prefetch depth must be >= 0, got {self.prefetch}")
        self._plan: Optional[_fsdp.FsdpPlan] = None
        self._loss_wrappers: dict = {}

    # -- initialization / layout ----------------------------------------------

    def init(self, rngs, *sample_inputs):
        """Initialize parameters in the LOGICAL (replicated) form —
        :meth:`shard_params` places them. Sequential mode initializes
        stage by stage, flowing the sample activation forward (stages
        must be flax modules; bare callables cannot self-initialize)."""
        if not self._multi:
            return super().init(rngs, *sample_inputs)
        x = sample_inputs[0]
        params = []
        for i, m in enumerate(self.stages):
            if not hasattr(m, "init"):
                raise TypeError(
                    f"stage {i} is a bare callable — sequential FSDP.init "
                    "needs flax modules; build per-stage params yourself "
                    "and call shard_params instead"
                )
            key = jax.random.fold_in(rngs, i)
            p_i = m.init(key, x)
            x = m.apply(p_i, x)
            params.append(p_i)
        return jax.device_put(tuple(params), self.comm.replicated())

    def plan(self, params) -> _fsdp.FsdpPlan:
        """Resolve (and pin) the partition plan from a logical parameter
        tree. Re-planning with different shapes replaces the pin."""
        self._plan = _fsdp.plan_partition(
            params, self.rules, self.comm, precision=self.precision
        )
        return self._plan

    def _ensure_plan(self, params) -> _fsdp.FsdpPlan:
        if self._plan is None:
            return self.plan(params)
        return self._plan

    def shard_params(self, params):
        """Logical → persistent layout: the plan's flat ``(p, chunk)``
        rows for sharded leaves (knob off: replicated, the DataParallel
        layout — bit-for-bit the baseline)."""
        if not self.enabled:
            return jax.device_put(params, self.comm.replicated())
        return _fsdp.fsdp_shard(params, self._ensure_plan(params), self.comm)

    def unshard_params(self, params):
        """Persistent layout → logical numpy (checkpoint interchange)."""
        import numpy as np

        if not self.enabled:
            return jax.tree_util.tree_map(np.asarray, params)
        if self._plan is None:
            raise ValueError("no plan pinned — call shard_params/plan first")
        return _fsdp.fsdp_unshard(params, self._plan)

    def param_bytes_per_device(self, params) -> int:
        """Worst-case per-device live parameter bytes (the watermark
        figure the CI gate compares against the replicated baseline)."""
        return _fsdp.bytes_per_device(params)

    # -- state layout helpers --------------------------------------------------

    def _param_flags(self, plan):
        return plan.unflatten([l.sharded for l in plan.leaves])

    def _state_template_flags(self, optimizer, params_sharded, plan):
        """Per-state-leaf sharded flags: a state leaf is sharded iff its
        shape is one of the plan's ``(p, chunk)`` row shapes (collisions
        with replicated leaves are rejected at plan time, so the shape
        test is sound)."""
        template = jax.eval_shape(optimizer.init, params_sharded)
        rows = {(plan.p, l.chunk) for l in plan.leaves if l.sharded}
        flags = jax.tree_util.tree_map(
            lambda t: tuple(getattr(t, "shape", ())) in rows, template
        )
        return template, flags

    def init_opt_state(self, params):
        """Optimizer state OVER the persistent layout — ZeRO composed on
        FSDP: state leaves for sharded parameters are themselves
        ``(p, chunk)`` rows pinned sharded (each position updates only
        its chunk); replicated parameters keep replicated state."""
        opt = self.optimizer
        if opt is None:
            raise ValueError("no optimizer bound; pass one at construction")
        if not self.enabled:
            return jax.device_put(opt.init(params), self.comm.replicated())
        comm = self.comm
        plan = self._ensure_plan(params)
        _, sflags = self._state_template_flags(opt, params, plan)

        def build():
            def init_fn(ps):
                state = opt.init(ps)
                return jax.tree_util.tree_map(
                    lambda l, f: jax.lax.with_sharding_constraint(
                        l, comm.sharding(0, 2)
                    )
                    if f
                    else l,
                    state,
                    sflags,
                )

            return init_fn

        return program_cache.cached_program(
            "fsdp_opt_init", (opt, plan.signature()), build, comm=comm
        )(params)

    # -- forward ---------------------------------------------------------------

    def _stage_trees(self, params):
        return list(params) if self._multi else [params]

    def _gather_stage(self, stage_params, stage_idx: int, plan):
        """Gather one stage's sharded leaves back to logical form inside
        the kernel (replicated leaves pass through)."""
        comm = self.comm
        prefix = f"{stage_idx}/" if self._multi else ""
        paths = _fsdp.leaf_paths(stage_params)
        treedef = jax.tree_util.tree_structure(stage_params)
        gathered = [
            _fsdp.fsdp_gather(leaf, plan.by_path[prefix + path], comm)
            for path, leaf in paths
        ]
        return jax.tree_util.tree_unflatten(treedef, gathered)

    def _forward_local(self, params, x, plan, depth: int, remat: bool):
        """The staged forward INSIDE a shard_map kernel: per-stage
        gather (optionally rematerialized) with the prefetch-window
        barrier. Returns the final activation."""
        acts = [x]
        out = x
        for k, st in enumerate(self._stage_trees(params)):
            apply_k = self.stage_apply[k]

            def f(sp, tie, xin, _k=k, _apply=apply_k):
                sp = _tie(sp, tie)
                full = self._gather_stage(sp, _k, plan)
                return _apply(full, xin)

            if remat:
                f = jax.checkpoint(f)
            out = f(st, acts[max(0, k - depth)], out)
            acts.append(out)
        return out

    def __call__(self, params, *inputs):
        """Forward pass. Knob off: the replicated ``dp_forward``
        program. Enabled: the gather-streamed shard_map forward (batch
        axis 0 sharded, output sharded along 0)."""
        if not self.enabled:
            return super().__call__(params, *inputs)
        comm = self.comm
        axis = comm.axis_name
        plan = self._ensure_plan(params)
        depth = self.prefetch
        me = self

        def build():
            p_specs = plan.unflatten(
                [P(axis) if l.sharded else P() for l in plan.leaves]
            )

            def kernel(params, x):
                return me._forward_local(params, x, plan, depth, remat=False)

            def fwd(params, x):
                return jax.shard_map(
                    kernel, mesh=comm.mesh,
                    in_specs=(p_specs, P(axis)), out_specs=P(axis),
                )(params, x)

            return fwd

        compiled = program_cache.cached_program(
            "fsdp_forward", (plan.signature(), depth), build, comm=comm
        )
        return compiled(params, *self.shard_batch(*inputs))

    # -- training --------------------------------------------------------------

    def _full_loss(self, loss_fn):
        """``loss_fn(out, *tail)`` lifted to the DataParallel
        ``loss(params, *batch)`` contract (memoized per loss_fn so the
        replicated fallback's program-cache key stays stable)."""
        cached = self._loss_wrappers.get(loss_fn)
        if cached is None:
            apply_fn = self.apply_fn

            def full_loss(params, *batch):
                return loss_fn(apply_fn(params, batch[0]), *batch[1:])

            self._loss_wrappers[loss_fn] = cached = full_loss
        return cached

    def make_train_step(
        self, loss_fn: Callable, optimizer=None,
        precision: Optional[str] = None,
    ) -> Callable:
        """Build the compiled train step:
        ``step(params, opt_state, *batch) -> (params, opt_state, loss)``.

        ``loss_fn(out, *batch_tail) -> scalar`` is the MEAN loss over
        the local batch rows (note the contract differs from
        :class:`DataParallel`, whose loss closes over the forward — FSDP
        must own the forward to schedule the per-stage gathers).

        Knob off (``HEAT_TPU_FSDP=0``): delegates to the replicated
        :class:`DataParallel` blocking step, bit-for-bit. Enabled: one
        shard_map program — staged forward (remat per stage, prefetch
        window ``d``), backward re-gathers and reduce-scatters each
        leaf's gradient chunk via the
        :func:`~heat_tpu.parallel.fsdp.fsdp_gather` custom vjp,
        per-chunk optimizer update (ZeRO-composed state), parameters
        stay sharded. Zero steady-state compiles: the program is
        memoized on (loss, optimizer, plan signature, depth)."""
        optimizer = optimizer if optimizer is not None else self.optimizer
        if optimizer is None:
            raise ValueError("no optimizer bound; pass one here or at init")
        if not self.enabled:
            return super().make_train_step(
                self._full_loss(loss_fn), optimizer, precision=precision
            )
        if self._plan is None:
            raise ValueError(
                "no plan pinned — call shard_params(params) before "
                "make_train_step so the step is traced against the layout"
            )
        from ..core import collective_prec

        comm = self.comm
        axis = comm.axis_name
        p = comm.size
        plan = self._plan
        depth = self.prefetch
        block = collective_prec.block_size()
        me = self

        def build():
            pflags = me._param_flags(plan)
            p_specs = plan.unflatten(
                [P(axis) if l.sharded else P() for l in plan.leaves]
            )

            def local_view(tree, flags):
                return jax.tree_util.tree_map(
                    lambda x, f: x[0] if f else x, tree, flags
                )

            def restack(tree, flags):
                return jax.tree_util.tree_map(
                    lambda x, f: x[None] if f else x, tree, flags
                )

            def kernel(sflags, params, opt_state, *batch):
                x, rest = batch[0], tuple(batch[1:])

                def fwd_loss(ps):
                    out = me._forward_local(ps, x, plan, depth, remat=True)
                    return loss_fn(out, *rest)

                loss, grads = jax.value_and_grad(fwd_loss)(params)
                loss = comm.psum(loss, precision="off") / p

                # sharded leaves: the custom-vjp reduce-scatter already
                # holds this position's chunk of the global SUM; the
                # mean over p local-mean losses divides by p. Replicated
                # leaves sum exactly (their gradients never ride the
                # compressed weight wire).
                def grad_mean(g, f):
                    if f:
                        return g / p
                    return comm.psum(g, precision="off") / p

                grads = jax.tree_util.tree_map(grad_mean, grads, pflags)
                my_p = local_view(params, pflags)
                my_g = local_view(grads, pflags)
                my_s = local_view(opt_state, sflags)
                updates, s_new = optimizer.update(my_g, my_s, my_p)
                p_new = optax.apply_updates(my_p, updates)
                return (
                    restack(p_new, pflags),
                    restack(s_new, sflags),
                    loss,
                )

            def step(params, opt_state, *batch):
                _, sflags = me._state_template_flags(
                    optimizer, params, plan
                )
                s_specs = jax.tree_util.tree_map(
                    lambda f: P(axis) if f else P(), sflags
                )
                in_specs = (p_specs, s_specs) + (P(axis),) * len(batch)
                return jax.shard_map(
                    lambda *a: kernel(sflags, *a),
                    mesh=comm.mesh,
                    in_specs=in_specs,
                    out_specs=(p_specs, s_specs, P()),
                )(params, opt_state, *batch)

            return step

        compiled = program_cache.cached_program(
            "fsdp_train_step",
            (loss_fn, optimizer, plan.signature(), depth, block),
            build,
            comm=comm,
        )
        self._train_step = compiled
        return compiled

    # -- checkpoint / restore --------------------------------------------------

    def _zero(self, optimizer=None):
        """The composed ZeRO view of this instance's optimizer — its
        logical-state machinery is layout-compatible (sharded state
        leaves are ``(p, chunk)`` rows here too)."""
        from ..optim import ZeroOptimizer

        opt = optimizer if optimizer is not None else self.optimizer
        if opt is None:
            raise ValueError("no optimizer bound; pass one at construction")
        return ZeroOptimizer(opt, self.comm, precision="off")

    def save_checkpoint(self, path: str, params, opt_state) -> str:
        """Checkpoint in the topology-independent LOGICAL form (per-leaf
        blobs, CRC-checked, atomic swap): sharded params unshard, sharded
        state rows unpad — the blobs carry no trace of this mesh's size
        or factorization, so restore works across factorizations."""
        from .. import resilience

        logical_p = self.unshard_params(params)
        logical_s = self._zero()._logical_state(logical_p, opt_state)
        return resilience.save_checkpoint(
            {"params": logical_p, "opt_state": logical_s}, path,
            extra={
                "algo": "fsdp",
                "enabled": bool(self.enabled),
                "prefetch": int(self.prefetch),
                "rules": repr(self.rules),
            },
        )

    def load_checkpoint(self, path: str, params_template):
        """Restore onto THIS instance's mesh/plan: logical blobs re-pad
        and re-shard for the current factorization, bit-exactly.
        ``params_template`` supplies structure and logical shapes (e.g.
        a fresh ``init``). Returns ``(params, opt_state)`` in the
        persistent layout."""
        from .. import resilience

        opt = self.optimizer
        if opt is None:
            raise ValueError("no optimizer bound; pass one at construction")
        template_state = jax.eval_shape(opt.init, params_template)
        tree, extra = resilience.load_checkpoint(
            path,
            like={"params": params_template, "opt_state": template_state},
            with_extra=True,
        )
        if extra.get("algo") != "fsdp":
            raise resilience.CheckpointError(
                f"{path!r} is a {extra.get('algo')!r} checkpoint, not fsdp"
            )
        params = self.shard_params(
            jax.tree_util.tree_map(jnp.asarray, tree["params"])
        )
        if not self.enabled:
            return params, jax.device_put(
                jax.tree_util.tree_map(jnp.asarray, tree["opt_state"]),
                self.comm.replicated(),
            )
        plan = self._plan
        template, sflags = self._state_template_flags(opt, params, plan)
        comm = self.comm

        def reshard(l, t, f):
            l = jnp.asarray(l)
            if not f:
                return jax.device_put(l, comm.replicated())
            # the sharded-layout state template carries the exact
            # (p, chunk) row shape this logical leaf re-pads into
            pp, c = t.shape
            flat = l.reshape(-1)
            if pp * c != l.size:
                flat = jnp.pad(flat, (0, pp * c - l.size))
            return jax.device_put(flat.reshape(pp, c), comm.sharding(0, 2))

        opt_state = jax.tree_util.tree_map(
            reshard, tree["opt_state"], template, sflags
        )
        return params, opt_state
