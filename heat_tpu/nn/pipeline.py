"""ht.nn.Pipeline — the MPMD pipeline-training front end (ISSUE 19).

Wraps `heat_tpu/parallel/pipeline.py`'s schedule-table kernel into the
module-level workflow the other ``nn`` wrappers follow: plan → shard →
train-step → checkpoint. A :class:`Pipeline` holds one homogeneous layer
function applied ``n_layers`` times; the layers split into ``S`` stages
mapped per node group (:func:`heat_tpu.parallel.plan_stages`), each
stage's weights live flat-sharded ``1/local`` across its group (the PR 18
FSDP tier), microbatch activations hop stage→stage over the DCN tier,
and the whole step — warmup/steady/cooldown, forwards, hand-rolled
backwards, optimizer update — is ONE cached program at site
``pipeline.step``.

Elastic contract: checkpoints store the LOGICAL form — per-layer
unpadded params, per-layer optimizer-state rows matched to their param
leaf by tree-path correspondence, and the step cursor — so a run killed
on one ``node × local`` factorization resumes bit-exactly on another
(any stage count dividing the layer count), because within-stage compute
is replicated (the ``1/local`` sharding changes WHERE chunks live, never
what any microbatch computes) and the schedule replays from the step
boundary.
"""

from __future__ import annotations

from typing import Any, Callable, List, Optional, Sequence

import jax
import jax.numpy as jnp

from .. import _knobs as knobs
from ..core import topology as _topo
from ..core.communication import MeshCommunication, get_comm
from ..parallel import pipeline as _pl
from ..parallel import schedule as _sched

__all__ = ["Pipeline"]


def _layer_apply(layer) -> Callable:
    if hasattr(layer, "apply"):
        return lambda p, x: layer.apply(p, x)
    if callable(layer):
        return layer
    raise TypeError(f"layer must be a flax module or callable, got {layer!r}")


class Pipeline:
    """Pipeline-parallel training of ``n_layers`` homogeneous layers.

    Parameters
    ----------
    layer : flax.linen.Module or callable
        One layer, ``h = layer(params, h)`` (shape-preserving — the
        homogeneous-pipeline contract). Every layer shares this function
        and the parameter *signature*; each has its own parameter values.
    n_layers : int
        Total layer count; must divide by the stage count.
    comm, optimizer, loss_fn
        Mesh, bound optax optimizer, and ``loss_fn(out, y) -> scalar``
        (both required for :meth:`make_train_step`).
    n_stages : int, optional
        Default ``HEAT_TPU_PIPELINE_STAGES`` (0 = auto: node groups of an
        active 2-level topology, else one stage per position).
    n_microbatches : int, optional
        Default ``HEAT_TPU_PIPELINE_MICROBATCHES`` (0 = auto: the stage
        count — the classic balanced point).
    schedule : str, optional
        ``gpipe`` or ``1f1b``; default ``HEAT_TPU_PIPELINE_SCHEDULE``.
        Results are bit-identical either way; 1f1b cuts the activation
        stash to ``min(S, M)`` and the steady-window bubble.
    prefetch : int, optional
        In-stage weight-gather prefetch depth (default
        ``HEAT_TPU_FSDP_PREFETCH`` — the same window contract).
    precision : str, optional
        In-stage gather wire (default the ``fsdp_wire`` chain; lossy
        modes beyond bf16 coerce to bf16 — see ``plan_pipeline``).
    remat : bool
        Rematerialize layer forwards inside backward ticks
        (`jax.checkpoint`), bounding the stash to INPUT activations of
        in-flight microbatches. Default True.

    Knobs resolve at construction, like every other nn wrapper: the
    schedule is part of the training state, not something to flip
    mid-run (resume re-resolves on the new instance).
    """

    def __init__(
        self,
        layer,
        n_layers: int,
        comm: Optional[MeshCommunication] = None,
        optimizer=None,
        loss_fn: Optional[Callable] = None,
        *,
        n_stages: Optional[int] = None,
        n_microbatches: Optional[int] = None,
        schedule: Optional[str] = None,
        prefetch: Optional[int] = None,
        precision: Optional[str] = None,
        remat: bool = True,
    ):
        self.layer = layer
        self.layer_apply = _layer_apply(layer)
        self.n_layers = int(n_layers)
        self.comm = comm if comm is not None else get_comm()
        self.optimizer = optimizer
        self.loss_fn = loss_fn
        self.mapping = _sched.plan_stages(self.comm.size, n_stages)
        if self.n_layers % self.mapping.n_stages:
            raise ValueError(
                f"{self.n_layers} layers do not divide into "
                f"{self.mapping.n_stages} stages"
            )
        m = (
            n_microbatches
            if n_microbatches is not None
            else int(knobs.get("HEAT_TPU_PIPELINE_MICROBATCHES"))
        )
        self.n_microbatches = int(m) if int(m) > 0 else self.mapping.n_stages
        self.schedule = _sched.resolve_schedule_name(schedule)
        self.prefetch = int(
            prefetch
            if prefetch is not None
            else knobs.get("HEAT_TPU_FSDP_PREFETCH")
        )
        if self.prefetch < 0:
            raise ValueError(
                f"prefetch depth must be >= 0, got {self.prefetch}"
            )
        self.precision = _topo.fsdp_wire(
            jnp.float32, self.comm.size, precision
        )
        self.remat = bool(remat)
        self._layout: Optional[_pl.PipelineLayout] = None

    # -- initialization / layout ----------------------------------------------

    def init(self, rng, sample_x) -> List[Any]:
        """Per-layer logical params (a list of ``n_layers`` pytrees) —
        flax layers initialize with split keys, the sample activation
        flowing forward; bare callables cannot self-initialize."""
        if not hasattr(self.layer, "init"):
            raise TypeError(
                "layer is a bare callable — build the per-layer params "
                "list yourself and call shard_params"
            )
        params = []
        x = sample_x
        for j in range(self.n_layers):
            key = jax.random.fold_in(rng, j)
            p_j = self.layer.init(key, x)
            x = self.layer.apply(p_j, x)
            params.append(p_j)
        return params

    def plan(self, layer_params: Sequence[Any]) -> _pl.PipelineLayout:
        """Resolve (and pin) the chunked stage-layer layout."""
        self._layout = _pl.plan_pipeline(
            layer_params, self.mapping, wire=self.precision
        )
        return self._layout

    def _ensure_layout(self, layer_params) -> _pl.PipelineLayout:
        if self._layout is None:
            return self.plan(layer_params)
        return self._layout

    @property
    def layout(self) -> _pl.PipelineLayout:
        if self._layout is None:
            raise ValueError("no layout pinned — call plan/shard_params first")
        return self._layout

    def shard_params(self, layer_params: Sequence[Any]):
        """Logical per-layer list → persistent ``(p, lps, chunk)`` rows."""
        return _pl.shard_pipeline_params(
            layer_params, self._ensure_layout(layer_params), self.comm
        )

    def unshard_params(self, params) -> List[Any]:
        """Persistent rows → logical per-layer numpy list."""
        return _pl.unshard_pipeline_params(params, self.layout)

    def param_bytes_per_device(self) -> int:
        """Per-device persistent parameter bytes of the pinned layout —
        ``1/p`` of the model (each position holds its stage's ``1/local``
        chunks of ``n_layers/S`` layers)."""
        return self.layout.bytes_per_device()

    def init_opt_state(self, params):
        """Optimizer state OVER the persistent layout (ZeRO-composed):
        state leaves shaped like a param row are pinned to the same
        sharding; scalars stay replicated."""
        opt = self.optimizer
        if opt is None:
            raise ValueError("no optimizer bound; pass one at construction")
        state = opt.init(params)
        rows = self.layout.row_shapes()
        comm = self.comm
        return jax.tree_util.tree_map(
            lambda l: jax.device_put(l, comm.sharding(0, 3))
            if tuple(getattr(l, "shape", ())) in rows
            else jax.device_put(l, comm.replicated()),
            state,
        )

    # -- the step programs -----------------------------------------------------

    def _table(self, train: bool) -> _sched.ScheduleTable:
        return _sched.build_schedule(
            self.mapping.n_stages,
            self.n_microbatches,
            self.schedule,
            train=train,
        )

    def _micro(self, arr):
        m = self.n_microbatches
        b = arr.shape[0]
        if b % m:
            raise ValueError(
                f"batch {b} not divisible into {m} microbatches"
            )
        return arr.reshape(m, b // m, *arr.shape[1:])

    def make_train_step(self) -> Callable:
        """``step(params, opt_state, x, y) -> (params, opt_state, loss)``
        — one cached schedule-table program (site ``pipeline.step``);
        repeat steps at fixed shapes are pure cache hits."""
        if self.optimizer is None or self.loss_fn is None:
            raise ValueError(
                "make_train_step needs optimizer and loss_fn bound at "
                "construction"
            )
        prog = _pl.pipeline_step_program(
            self.layer_apply,
            self.layout,
            self.mapping,
            self._table(train=True),
            comm=self.comm,
            loss_fn=self.loss_fn,
            optimizer=self.optimizer,
            prefetch=self.prefetch,
            remat=self.remat,
        )

        def step(params, opt_state, x, y):
            return prog(params, opt_state, self._micro(x), self._micro(y))

        return step

    def __call__(self, params, x):
        """Forward-only pipelined apply of all ``n_layers`` layers."""
        prog = _pl.pipeline_step_program(
            self.layer_apply,
            self.layout,
            self.mapping,
            self._table(train=False),
            comm=self.comm,
            prefetch=self.prefetch,
            remat=self.remat,
        )
        out = prog(params, self._micro(x))
        return out.reshape(x.shape[0], *out.shape[2:])

    # -- optimizer-state correspondence (the elastic machinery) ----------------

    def _state_correspondence(self, layout: _pl.PipelineLayout):
        """Map each optimizer-state leaf to its param leaf (or None for
        replicated scalars): a state leaf corresponds to param leaf ``k``
        iff it has the ``(p, lps, chunk_k)`` row shape AND the param
        leaf's tree path is a suffix of the state leaf's path — the
        structure optax transforms produce (``mu``/``nu`` mirror the
        params tree). Row-shaped leaves with no unique correspondence are
        rejected loudly: without a param identity their padding cannot be
        unpadded topology-independently."""
        opt = self.optimizer
        if opt is None:
            raise ValueError("no optimizer bound; pass one at construction")
        stacked_t = jax.tree_util.tree_unflatten(
            layout.treedef,
            [
                jax.ShapeDtypeStruct(
                    (layout.p, layout.layers_per_stage, layout.chunk(k)),
                    layout.dtypes[k],
                )
                for k in range(len(layout.shapes))
            ],
        )
        state_t = jax.eval_shape(opt.init, stacked_t)
        p_paths = [
            tuple(path)
            for path, _ in jax.tree_util.tree_flatten_with_path(stacked_t)[0]
        ]
        rows = layout.row_shapes()
        corr: List[Optional[int]] = []
        for path, leaf in jax.tree_util.tree_flatten_with_path(state_t)[0]:
            shape = tuple(leaf.shape)
            if shape not in rows:
                corr.append(None)
                continue
            pt = tuple(path)
            hits = [
                k
                for k, pp in enumerate(p_paths)
                if len(pt) >= len(pp)
                and pt[len(pt) - len(pp):] == pp
                and (layout.p, layout.layers_per_stage, layout.chunk(k))
                == shape
            ]
            if len(hits) != 1:
                raise ValueError(
                    f"optimizer-state leaf at {jax.tree_util.keystr(path)} "
                    "has a sharded row shape but no unique param-leaf "
                    "correspondence; Pipeline checkpoints support optax-"
                    "style states whose sharded leaves mirror the params "
                    "tree"
                )
            corr.append(hits[0])
        return state_t, corr

    def _logical_state(self, opt_state):
        """Persistent state → topology-independent logical form: matched
        leaves become stacked ``(n_layers, *shape)`` numpy, scalars pass
        through."""
        import numpy as np

        layout = self.layout
        _, corr = self._state_correspondence(layout)
        leaves, treedef = jax.tree_util.tree_flatten(opt_state)
        out = []
        for leaf, k in zip(leaves, corr):
            if k is None:
                out.append(np.asarray(leaf))
            else:
                out.append(
                    _pl.unshard_state_rows(
                        leaf, layout, layout.numel(k), layout.shapes[k]
                    )
                )
        return jax.tree_util.tree_unflatten(treedef, out)

    def _reshard_state(self, logical_state):
        layout = self.layout
        _, corr = self._state_correspondence(layout)
        leaves, treedef = jax.tree_util.tree_flatten(logical_state)
        comm = self.comm
        out = []
        for leaf, k in zip(leaves, corr):
            if k is None:
                out.append(
                    jax.device_put(jnp.asarray(leaf), comm.replicated())
                )
            else:
                out.append(_pl.shard_state_rows(leaf, layout, comm))
        return jax.tree_util.tree_unflatten(treedef, out)

    def _logical_state_template(self, layout: _pl.PipelineLayout):
        state_t, corr = self._state_correspondence(layout)
        leaves, treedef = jax.tree_util.tree_flatten(state_t)
        out = []
        for leaf, k in zip(leaves, corr):
            if k is None:
                out.append(leaf)
            else:
                out.append(
                    jax.ShapeDtypeStruct(
                        (layout.n_layers,) + layout.shapes[k], leaf.dtype
                    )
                )
        return jax.tree_util.tree_unflatten(treedef, out)

    # -- elastic checkpoint / resume -------------------------------------------

    def save_checkpoint(self, path: str, params, opt_state, step: int = 0):
        """Checkpoint the LOGICAL form + the schedule cursor ``step``
        (per-leaf blobs, CRC-checked, atomic swap — no trace of this
        mesh's factorization, stage count, or schedule)."""
        from .. import resilience

        return resilience.save_checkpoint(
            {
                "params": self.unshard_params(params),
                "opt_state": self._logical_state(opt_state),
            },
            path,
            extra={
                "algo": "pipeline",
                "step": int(step),
                "schedule": self.schedule,
                "n_microbatches": int(self.n_microbatches),
                "n_layers": int(self.n_layers),
            },
        )

    def resume(self, path: str, params_template: Sequence[Any]):
        """Restore onto THIS instance's mesh/mapping (possibly a
        different ``node × local`` factorization or stage count than the
        writer's): logical blobs re-pad and re-shard for the current
        layout, bit-exactly. ``params_template`` supplies structure and
        logical shapes (e.g. a fresh :meth:`init`). Returns
        ``(params, opt_state, step)``."""
        from .. import resilience

        # validate provenance BEFORE the structural load so a wrong-model
        # checkpoint fails with the informative error, not a leaf-count one
        extra = resilience.checkpoint.load_manifest(path).get("extra", {})
        if extra.get("algo") != "pipeline":
            raise resilience.CheckpointError(
                f"{path!r} is a {extra.get('algo')!r} checkpoint, "
                "not pipeline"
            )
        if int(extra.get("n_layers", self.n_layers)) != self.n_layers:
            raise resilience.CheckpointError(
                f"checkpoint has {extra.get('n_layers')} layers, this "
                f"Pipeline has {self.n_layers}"
            )

        layout = self._ensure_layout(params_template)
        like = {
            "params": [
                jax.tree_util.tree_map(
                    lambda l: jax.ShapeDtypeStruct(
                        jnp.shape(l), jnp.asarray(l).dtype
                    ),
                    layer,
                )
                for layer in params_template
            ],
            "opt_state": self._logical_state_template(layout),
        }
        tree, extra = resilience.load_checkpoint(
            path, like=like, with_extra=True
        )
        params = _pl.shard_pipeline_params(
            [
                jax.tree_util.tree_map(jnp.asarray, layer)
                for layer in tree["params"]
            ],
            layout,
            self.comm,
        )
        opt_state = self._reshard_state(tree["opt_state"])
        return params, opt_state, int(extra.get("step", 0))
