"""Data-parallel model wrappers (reference: heat/nn/data_parallel.py).

The reference's :class:`DataParallel` registers per-parameter backward hooks
that Allreduce each gradient — blocking (reference data_parallel.py:223-241)
or overlapped via Iallreduce + next-iteration forward pre-hooks (:243-297).
On TPU the whole train step is one compiled XLA program: sharding the batch
over the mesh makes the gradient mean a `psum` the compiler schedules, and
XLA's latency-hiding scheduler overlaps it with remaining backward compute —
the nonblocking hook machinery exists *inside the compiler*. What this class
provides is the same contract (wrap a model, get synchronous DP semantics)
plus the compiled train-step factory.

:class:`DataParallelMultiGPU` is the hierarchical flavor that pairs with
:class:`heat_tpu.optim.DASO` (reference data_parallel.py:314-376 wraps
node-local torch DDP; here it binds the model to DASO's 2-level mesh).
"""

from __future__ import annotations

from typing import Any, Callable, Optional, Tuple

import jax
import jax.numpy as jnp
import optax
from jax.sharding import PartitionSpec as P

from ..core import program_cache
from ..core.communication import MeshCommunication, sanitize_comm
from ..core.dndarray import DNDarray

__all__ = ["DataParallel", "DataParallelMultiGPU"]


def _module_apply(module) -> Callable:
    """Accept a flax.linen Module (has .apply) or a bare callable
    ``fn(params, *args)``."""
    if hasattr(module, "apply"):
        return lambda params, *a, **kw: module.apply(params, *a, **kw)
    if callable(module):
        return module
    raise TypeError(
        f"module must be a flax Module or callable(params, *inputs), got {type(module)}"
    )


class DataParallel:
    """Synchronous data parallelism over the communicator's device mesh.

    Parameters
    ----------
    module : flax.linen.Module or callable
        The network; a callable must have signature ``fn(params, *inputs)``.
    comm : MeshCommunication, optional
        Mesh whose single axis is the data-parallel axis.
    optimizer : optax.GradientTransformation, optional
        Bound optimizer used by :meth:`make_train_step`.
    blocking_parameter_updates : bool
        ``True`` (the reference's blocking mode, data_parallel.py:223-241):
        each step applies its own globally-averaged gradients — the psum is
        on the step's critical path.
        ``False`` (the reference's non-blocking mode, :243-297): **explicit
        double buffering** — step ``k`` outputs its averaged gradients and
        applies step ``k−1``'s. Inside the compiled step the psum result is
        only a program *output*, so XLA's latency-hiding scheduler overlaps
        it with the optimizer compute; across steps the average is ready
        before its first consumer. The first step applies zeros, exactly
        like the reference's hooks returning zeros on iteration 0 (:276).
    """

    def __init__(
        self,
        module,
        comm: Optional[MeshCommunication] = None,
        optimizer=None,
        blocking_parameter_updates: bool = False,
    ):
        self.module = module
        self.apply_fn = _module_apply(module)
        self.comm = sanitize_comm(comm)
        self.optimizer = optimizer
        self.blocking_parameter_updates = blocking_parameter_updates
        self._compiled_call = None
        self._train_step = None

    # -- forward -------------------------------------------------------------

    def init(self, rngs, *sample_inputs):
        """Initialize parameters (replicated across the mesh)."""
        params = self.module.init(rngs, *sample_inputs)
        return jax.device_put(params, self.comm.replicated())

    def shard_batch(self, *arrays):
        """Place host arrays batch-sharded (axis 0) over the dp mesh.

        DNDarrays pass through as their device buffer only when already
        split along 0 and evenly sharded — a tail-padded batch would feed
        garbage pad rows into the loss mean (the reference's Dataset slices
        uneven tails off up front, reference datatools.py:147-155; use the
        DataLoader or a divisible batch size)."""
        out = []
        for a in arrays:
            if isinstance(a, DNDarray):
                if a.split not in (None, 0):
                    raise ValueError(
                        f"DataParallel batches must be split along 0, got {a.split}"
                    )
                if a.split == 0 and a.pad_count:
                    raise ValueError(
                        f"batch axis ({a.shape[0]}) must divide evenly over "
                        f"the {self.comm.size}-device mesh; pad rows would "
                        "bias the loss. Use heat_tpu.utils.data.DataLoader "
                        "or a divisible batch size."
                    )
                out.append(a._logical() if a.split is None else a._masked(0))
            else:
                a = jnp.asarray(a)
                out.append(jax.device_put(a, self.comm.sharding(0, a.ndim)))
        return tuple(out)

    def __call__(self, params, *inputs):
        """Forward pass; inputs are batch-sharded, output comes back sharded
        along axis 0 (one compiled program, memoized in the process-global
        program registry — two wrappers over the same module share it)."""
        if self._compiled_call is None:
            self._compiled_call = program_cache.cached_program(
                "dp_forward", self.apply_fn, lambda: self.apply_fn,
                comm=self.comm,
            )
        return self._compiled_call(params, *self.shard_batch(*inputs))

    # -- training ------------------------------------------------------------

    def make_train_step(
        self, loss_fn: Callable, optimizer=None,
        precision: Optional[str] = None,
    ) -> Callable:
        """Build the compiled DP train step.

        ``loss_fn(params, *batch) -> scalar`` closes over :attr:`apply_fn`.
        With the batch axis sharded and params replicated, XLA emits exactly
        one gradient psum per step (the reference's per-parameter Allreduce
        hooks, fused). Call with batch arrays sharded via
        :meth:`shard_batch`.

        Blocking mode returns ``step(params, opt_state, *batch) ->
        (params, opt_state, loss)``.

        Non-blocking (double-buffered) mode returns ``step(params,
        opt_state, pending_grads, *batch) -> (params, opt_state,
        next_pending_grads, loss)`` — thread ``pending_grads`` through the
        loop, seeded by :meth:`init_pending`. Step ``k`` applies step
        ``k−1``'s global average while its own psum overlaps the optimizer
        compute (reference data_parallel.py:243-297 semantics: global grads
        applied just-in-time one iteration later).

        ``precision`` (ISSUE 9, default: the global
        ``HEAT_TPU_COLLECTIVE_PREC`` knob): compress the gradient
        all-reduce's wire payload. ``off`` keeps the exact GSPMD step
        bit-for-bit. Compressed modes restructure the step as a
        ``shard_map`` over the dp mesh — each device takes
        ``value_and_grad`` of the loss on its local batch shard and the
        per-leaf gradient *mean* rides a compressed collective
        (cast-psum-upcast for ``bf16``; the EQuARX two-phase quantized
        all-reduce for ``int8``/``blockwise`` — collective_prec.psum).
        This assumes the standard DP contract the reference's DDP hooks
        assume too: ``loss_fn`` is a MEAN over batch rows, so the global
        gradient is the mean of per-shard gradients. The wire mode is
        part of the program signature (modes key separate cache
        entries)."""
        from ..core import collective_prec

        optimizer = optimizer if optimizer is not None else self.optimizer
        if optimizer is None:
            raise ValueError("no optimizer bound; pass one here or at init")
        wire = collective_prec.resolve(precision)

        if wire != "off":
            step = self._make_compressed_step(loss_fn, optimizer, wire)
        elif self.blocking_parameter_updates:

            def step(params, opt_state, *batch):
                loss, grads = jax.value_and_grad(loss_fn)(params, *batch)
                updates, opt_state = optimizer.update(grads, opt_state, params)
                params = optax.apply_updates(params, updates)
                return params, opt_state, loss

        else:

            def step(params, opt_state, pending_grads, *batch):
                # trace-time guard: the 3rd argument must be a gradient
                # pytree, catching callers using the blocking-mode arity
                if jax.tree_util.tree_structure(
                    pending_grads
                ) != jax.tree_util.tree_structure(params):
                    raise TypeError(
                        "non-blocking (double-buffered) DataParallel step "
                        "signature is step(params, opt_state, pending_grads, "
                        "*batch) -> (params, opt_state, next_pending, loss); "
                        "seed pending_grads with DataParallel.init_pending("
                        "params), or construct with "
                        "blocking_parameter_updates=True for the 3-tuple step"
                    )
                loss, grads = jax.value_and_grad(loss_fn)(params, *batch)
                # apply the PREVIOUS step's averaged grads; this step's psum
                # only feeds the program output — off the critical path
                updates, opt_state = optimizer.update(
                    pending_grads, opt_state, params
                )
                params = optax.apply_updates(params, updates)
                return params, opt_state, grads, loss

        # (loss_fn, optimizer, mode, wire) is the static config: two
        # wrappers building the same train step share one compiled program
        raw_step = step
        compiled = program_cache.cached_program(
            "dp_train_step",
            (loss_fn, optimizer, self.blocking_parameter_updates, wire),
            lambda: raw_step,
            comm=self.comm,
        )
        self._train_step = compiled
        return compiled

    def _make_compressed_step(self, loss_fn, optimizer, wire: str):
        """The shard_map form of the train step whose gradient collective
        moves a compressed payload (``wire`` in bf16/int8/blockwise).
        Non-float gradient leaves (rare, e.g. integer counters) pass
        through an exact pmean."""
        from ..core import collective_prec

        comm = self.comm
        axis = comm.axis_name
        p = comm.size
        blocking = self.blocking_parameter_updates
        block = collective_prec.block_size()

        def grad_mean(g):
            if not collective_prec.compressible(g.dtype):
                return jax.lax.pmean(g, axis)
            return collective_prec.pmean(g, axis, p, wire, block)

        def kernel_body(params, opt_state, batch):
            # local grads of the local-batch mean loss; the global mean
            # over equal shards is the pmean of the local means (the
            # shard_batch contract forbids uneven/padded batches)
            loss, grads = jax.value_and_grad(loss_fn)(params, *batch)
            loss = jax.lax.pmean(loss, axis)
            grads = jax.tree.map(grad_mean, grads)
            return loss, grads

        if blocking:

            def kernel(params, opt_state, *batch):
                loss, grads = kernel_body(params, opt_state, batch)
                updates, opt_state = optimizer.update(
                    grads, opt_state, params
                )
                params = optax.apply_updates(params, updates)
                return params, opt_state, loss

            def step(params, opt_state, *batch):
                in_specs = (P(), P()) + (P(axis),) * len(batch)
                return jax.shard_map(
                    kernel, mesh=comm.mesh, in_specs=in_specs,
                    out_specs=(P(), P(), P()),
                )(params, opt_state, *batch)

        else:

            def kernel(params, opt_state, pending_grads, *batch):
                loss, grads = kernel_body(params, opt_state, batch)
                updates, opt_state = optimizer.update(
                    pending_grads, opt_state, params
                )
                params = optax.apply_updates(params, updates)
                return params, opt_state, grads, loss

            def step(params, opt_state, pending_grads, *batch):
                if jax.tree_util.tree_structure(
                    pending_grads
                ) != jax.tree_util.tree_structure(params):
                    raise TypeError(
                        "non-blocking (double-buffered) DataParallel step "
                        "signature is step(params, opt_state, pending_grads,"
                        " *batch) -> (params, opt_state, next_pending, "
                        "loss); seed pending_grads with "
                        "DataParallel.init_pending(params)"
                    )
                in_specs = (P(), P(), P()) + (P(axis),) * len(batch)
                return jax.shard_map(
                    kernel, mesh=comm.mesh, in_specs=in_specs,
                    out_specs=(P(), P(), P(), P()),
                )(params, opt_state, pending_grads, *batch)

        return step

    @staticmethod
    def init_pending(params):
        """Zero gradient buffer seeding the double-buffered loop (the
        reference's iteration-0 zero-return, data_parallel.py:276)."""
        return jax.tree_util.tree_map(jnp.zeros_like, params)


class DataParallelMultiGPU:
    """Hierarchical data parallelism paired with DASO (reference
    data_parallel.py:314-376).

    The reference wraps the model in node-local torch DDP (NCCL fast domain)
    and leaves the slow inter-node domain to DASO over MPI. The TPU analog:
    DASO owns a 2-level mesh (``local`` axis ≈ ICI/NCCL, ``node`` axis ≈
    DCN/MPI); this wrapper binds the module's loss to that schedule via
    ``daso.set_model``.
    """

    def __init__(self, module, daso):
        self.module = module
        self.apply_fn = _module_apply(module)
        self.daso = daso
        daso.set_model(module)

    def __call__(self, params, *inputs):
        return self.apply_fn(params, *inputs)
