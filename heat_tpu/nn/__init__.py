"""heat_tpu.nn — data-parallel module wrappers + flax passthrough.

The reference mounts ``torch.nn`` behind a module-level ``__getattr__`` so
``ht.nn.Conv2d`` *is* ``torch.nn.Conv2d`` (reference heat/nn/__init__.py:19-31),
and adds its own :class:`DataParallel` wrappers on top. The TPU-native analog
passes through to **flax.linen** (``ht.nn.Dense``, ``ht.nn.Conv`` …) — the
module system of the JAX stack — with the distributed wrappers defined here.
"""

from . import functional
from .data_parallel import DataParallel, DataParallelMultiGPU
from .fsdp import FSDP
from .pipeline import Pipeline
from .transformer import MultiHeadAttention, TransformerBlock, TransformerLM
from .moe import MoEMLP
from .quant_dense import QuantDense

__all__ = [
    "DataParallel",
    "DataParallelMultiGPU",
    "FSDP",
    "functional",
    "MoEMLP",
    "MultiHeadAttention",
    "Pipeline",
    "QuantDense",
    "TransformerBlock",
    "TransformerLM",
]


def __getattr__(name):
    """Fall through to ``flax.linen`` for anything not defined here
    (reference heat/nn/__init__.py:19-31 does the same against torch.nn)."""
    import flax.linen as _linen

    try:
        return getattr(_linen, name)
    except AttributeError:
        raise AttributeError(
            f"module {name} not implemented in flax.linen or heat_tpu.nn"
        ) from None
