"""k-nearest-neighbors classifier.

Re-design of reference heat/classification/kneighborsclassifier.py:9-136:
fit stores the training data; predict is `cdist(x, train)` + topk + one-hot
vote (:45, :117). Identical pipeline here; the distance matrix is the MXU
GEMM form and the vote a one-hot GEMM.
"""

from __future__ import annotations

import jax.numpy as jnp
import numpy as np

from ..core import types
from ..core.base import BaseEstimator, ClassificationMixin
from ..core.dndarray import DNDarray

__all__ = ["KNeighborsClassifier"]


class KNeighborsClassifier(BaseEstimator, ClassificationMixin):
    """KNN classifier (reference kneighborsclassifier.py:9).

    Parameters
    ----------
    n_neighbors : int
        Number of neighbors considered in the vote.
    """

    def __init__(self, n_neighbors: int = 5):
        self.n_neighbors = n_neighbors
        self.x = None
        self.y = None
        self._classes = None

    def fit(self, x: DNDarray, y: DNDarray) -> "KNeighborsClassifier":
        """Store the training set (reference kneighborsclassifier.py `fit`)."""
        if not isinstance(x, DNDarray) or not isinstance(y, DNDarray):
            raise TypeError("x and y need to be DNDarrays")
        self.x = x
        self.y = y
        self._classes = np.unique(np.asarray(y._replicated()))
        return self

    def predict(self, x: DNDarray) -> DNDarray:
        """Vote among the k nearest training samples (reference
        kneighborsclassifier.py:117)."""
        if self.x is None:
            raise RuntimeError("fit needs to be called before predict")
        from ..cluster._kcluster import _d2

        xq = x._masked(0).astype(jnp.float32)  # zeroed tail-pad rows
        xt = self.x._replicated().astype(jnp.float32)  # (n, d)
        yt = self.y._replicated().ravel()

        d2 = _d2(xq, xt)  # (m, n), HIGHEST-precision GEMM form
        k = min(self.n_neighbors, xt.shape[0])
        _, idx = _smallest_k(d2, k)
        neigh = jnp.take(yt, idx)  # (m, k) labels
        classes = jnp.asarray(self._classes)
        votes = jnp.sum(
            (neigh[:, :, None] == classes[None, None, :]).astype(jnp.int32), axis=1
        )  # (m, c)
        pred = jnp.take(classes, jnp.argmax(votes, axis=1))
        return DNDarray(
            pred, (x.shape[0],), types.canonical_heat_type(pred.dtype), x.split, x.device, x.comm, True
        )


def _smallest_k(d2: jnp.ndarray, k: int):
    import jax

    vals, idx = jax.lax.top_k(-d2, k)
    return -vals, idx
