"""Classification algorithms (reference: heat/classification/)."""

from .kneighborsclassifier import *
