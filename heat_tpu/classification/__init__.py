"""Populated by the ML build stage."""
