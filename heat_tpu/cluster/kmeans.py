"""K-Means clustering.

Re-design of reference heat/cluster/kmeans.py:13-139 (Lloyd iterations:
assign via cdist+argmin, masked-sum centroid update with an implicit
Allreduce, inertia convergence check). Here one Lloyd iteration is a single
jit-compiled function over the padded sharded sample buffer — the distance
matrix and the one-hot centroid update are both GEMMs on the MXU, and XLA
inserts the single cross-shard psum per iteration.
"""

from __future__ import annotations

from functools import partial
from typing import Optional, Union

import jax
import jax.numpy as jnp

from ..core import types
from ..core.dndarray import DNDarray
from ._kcluster import _KCluster, _d2

__all__ = ["KMeans"]


@partial(jax.jit, donate_argnums=())
def _lloyd_step(xb: jax.Array, w: jax.Array, centers: jax.Array):
    """One Lloyd iteration: assign + masked centroid update + inertia.

    All math is batched GEMM; `w` zeroes tail-pad rows out of the sums and
    counts (the reference's empty-shard neutral elements, _operations.py
    :401-410, become this weight vector)."""
    d2 = _d2(xb, centers)  # (m, k)
    labels = jnp.argmin(d2, axis=1)
    k = centers.shape[0]
    onehot = (labels[:, None] == jnp.arange(k)[None, :]).astype(xb.dtype) * w[:, None]
    counts = jnp.sum(onehot, axis=0)  # (k,)
    sums = onehot.T @ xb  # (k, d)
    new_centers = jnp.where(
        counts[:, None] > 0, sums / jnp.maximum(counts, 1.0)[:, None], centers
    )
    inertia = jnp.sum(jnp.min(d2, axis=1) * w)
    shift = jnp.sum((new_centers - centers) ** 2)
    return new_centers, labels, inertia, shift


@partial(jax.jit, static_argnames=("max_iter",))
def _lloyd_fit(xb: jax.Array, w: jax.Array, centers: jax.Array, max_iter: int, tol):
    """The whole Lloyd loop as one on-device `lax.while_loop` — the reference
    drives iterations from Python with a per-iteration convergence fetch
    (kmeans.py:122-135); on TPU that host sync per iteration would dominate,
    so the loop, the convergence test, and the final assignment all compile
    into a single XLA program (SURVEY §3.3)."""

    def cond(carry):
        _, it, shift = carry
        return jnp.logical_and(it < max_iter, shift > tol)

    def body(carry):
        c, it, _ = carry
        new_c, _, _, shift = _lloyd_step.__wrapped__(xb, w, c)
        return new_c, it + 1, shift

    centers, n_iter, _ = jax.lax.while_loop(
        cond, body, (centers, jnp.int32(0), jnp.asarray(jnp.inf, xb.dtype))
    )
    d2 = _d2(xb, centers)
    labels = jnp.argmin(d2, axis=1)
    inertia = jnp.sum(jnp.min(d2, axis=1) * w)
    return centers, labels, inertia, n_iter


class KMeans(_KCluster):
    """K-Means clusterer (reference kmeans.py:13).

    Parameters
    ----------
    n_clusters : int
    init : 'random' | 'probability_based' | DNDarray
    max_iter : int
    tol : float
        Convergence threshold on the squared centroid shift.
    random_state : int, optional
    """

    def __init__(
        self,
        n_clusters: int = 8,
        init: Union[str, DNDarray] = "random",
        max_iter: int = 300,
        tol: float = 1e-4,
        random_state: Optional[int] = None,
    ):
        super().__init__("euclidean", n_clusters, init, max_iter, tol, random_state)

    def fit(self, x: DNDarray) -> "KMeans":
        """Run Lloyd iterations to convergence (reference kmeans.py:102)."""
        if not isinstance(x, DNDarray):
            raise TypeError(f"input needs to be a DNDarray, but was {type(x)}")
        if x.ndim != 2:
            raise ValueError("input needs to be 2D")

        dt, xb, w, centers = self._fit_buffers(x)

        from .pallas_lloyd import (
            lloyd_fit_pallas,
            lloyd_fit_pallas_sharded,
            pallas_lloyd_applicable,
        )

        done = False
        if pallas_lloyd_applicable(
            x.comm.size, x.split, x.shape[1], self.n_clusters, xb.dtype
        ):
            # fused single-pass-over-X Lloyd update (see pallas_lloyd);
            # Mosaic failure degrades to the XLA fit rather than erroring
            try:
                if x.comm.size > 1:
                    p_out = lloyd_fit_pallas_sharded(
                        x.comm, xb, centers, x.shape[0], self.max_iter,
                        jnp.asarray(self.tol, xb.dtype),
                    )
                else:
                    p_out = lloyd_fit_pallas(
                        xb, centers, x.shape[0], self.max_iter,
                        jnp.asarray(self.tol, xb.dtype),
                    )
                # materialize INSIDE the try — async TPU runtime faults
                # surface lazily and must trigger the fallback here
                jax.block_until_ready(p_out)
                centers, labels, inertia, n_iter = p_out
                done = True
            except Exception as e:  # pragma: no cover — TPU-runtime only
                import warnings

                warnings.warn(f"pallas kmeans fell back to XLA: {e!r}")
        if not done:
            centers, labels, inertia, n_iter = _lloyd_fit(
                xb, w, centers, self.max_iter, jnp.asarray(self.tol, xb.dtype)
            )
        n_iter = int(n_iter)

        self._cluster_centers = DNDarray.from_logical(centers, None, x.device, x.comm, dt)
        self._labels = DNDarray(
            labels.astype(jnp.int64), (x.shape[0],), types.int64, x.split, x.device, x.comm, True
        )
        self._inertia = float(inertia)
        self._n_iter = n_iter
        return self
