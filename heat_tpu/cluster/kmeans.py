"""K-Means clustering.

Re-design of reference heat/cluster/kmeans.py:13-139 (Lloyd iterations:
assign via cdist+argmin, masked-sum centroid update with an implicit
Allreduce, inertia convergence check). Here one Lloyd iteration is a single
jit-compiled function over the padded sharded sample buffer — the distance
matrix and the one-hot centroid update are both GEMMs on the MXU, and XLA
inserts the single cross-shard psum per iteration.
"""

from __future__ import annotations

from functools import partial
from typing import Optional, Union

import jax
import jax.numpy as jnp

from ..core import types
from ..core.dndarray import DNDarray
from ._kcluster import _KCluster, _d2

__all__ = ["KMeans"]


@partial(jax.jit, donate_argnums=())
def _lloyd_step(xb: jax.Array, w: jax.Array, centers: jax.Array):
    """One Lloyd iteration: assign + masked centroid update + inertia.

    All math is batched GEMM; `w` zeroes tail-pad rows out of the sums and
    counts (the reference's empty-shard neutral elements, _operations.py
    :401-410, become this weight vector)."""
    d2 = _d2(xb, centers)  # (m, k)
    labels = jnp.argmin(d2, axis=1)
    k = centers.shape[0]
    onehot = (labels[:, None] == jnp.arange(k)[None, :]).astype(xb.dtype) * w[:, None]
    counts = jnp.sum(onehot, axis=0)  # (k,)
    sums = onehot.T @ xb  # (k, d)
    new_centers = jnp.where(
        counts[:, None] > 0, sums / jnp.maximum(counts, 1.0)[:, None], centers
    )
    inertia = jnp.sum(jnp.min(d2, axis=1) * w)
    shift = jnp.sum((new_centers - centers) ** 2)
    return new_centers, labels, inertia, shift


class KMeans(_KCluster):
    """K-Means clusterer (reference kmeans.py:13).

    Parameters
    ----------
    n_clusters : int
    init : 'random' | 'probability_based' | DNDarray
    max_iter : int
    tol : float
        Convergence threshold on the squared centroid shift.
    random_state : int, optional
    """

    def __init__(
        self,
        n_clusters: int = 8,
        init: Union[str, DNDarray] = "random",
        max_iter: int = 300,
        tol: float = 1e-4,
        random_state: Optional[int] = None,
    ):
        super().__init__("euclidean", n_clusters, init, max_iter, tol, random_state)

    def fit(self, x: DNDarray) -> "KMeans":
        """Run Lloyd iterations to convergence (reference kmeans.py:102)."""
        if not isinstance(x, DNDarray):
            raise TypeError(f"input needs to be a DNDarray, but was {type(x)}")
        if x.ndim != 2:
            raise ValueError("input needs to be 2D")

        dt, xb, w, centers = self._fit_buffers(x)

        labels = None
        inertia = None
        n_iter = 0
        for it in range(self.max_iter):
            centers, labels, inertia, shift = _lloyd_step(xb, w, centers)
            n_iter = it + 1
            if float(shift) <= self.tol:
                break

        self._cluster_centers = DNDarray.from_logical(centers, None, x.device, x.comm, dt)
        self._labels = DNDarray(
            labels.astype(jnp.int64), (x.shape[0],), types.int64, x.split, x.device, x.comm, True
        )
        self._inertia = float(inertia)
        self._n_iter = n_iter
        return self
