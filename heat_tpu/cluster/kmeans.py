"""K-Means clustering.

Re-design of reference heat/cluster/kmeans.py:13-139 (Lloyd iterations:
assign via cdist+argmin, masked-sum centroid update with an implicit
Allreduce, inertia convergence check). Here one Lloyd iteration is a single
jit-compiled function over the padded sharded sample buffer — the distance
matrix and the one-hot centroid update are both GEMMs on the MXU, and XLA
inserts the single cross-shard psum per iteration.
"""

from __future__ import annotations

from functools import partial
from typing import Optional, Union

import jax
import jax.numpy as jnp

from ..core import types
from ..core.dndarray import DNDarray
from ._kcluster import _KCluster, _d2

__all__ = ["KMeans"]


@partial(jax.jit, donate_argnums=())
def _lloyd_step(xb: jax.Array, w: jax.Array, centers: jax.Array):
    """One Lloyd iteration: assign + masked centroid update + inertia.

    All math is batched GEMM; `w` zeroes tail-pad rows out of the sums and
    counts (the reference's empty-shard neutral elements, _operations.py
    :401-410, become this weight vector)."""
    d2 = _d2(xb, centers)  # (m, k)
    labels = jnp.argmin(d2, axis=1)
    k = centers.shape[0]
    onehot = (labels[:, None] == jnp.arange(k)[None, :]).astype(xb.dtype) * w[:, None]
    counts = jnp.sum(onehot, axis=0)  # (k,)
    sums = onehot.T @ xb  # (k, d)
    new_centers = jnp.where(
        counts[:, None] > 0, sums / jnp.maximum(counts, 1.0)[:, None], centers
    )
    inertia = jnp.sum(jnp.min(d2, axis=1) * w)
    shift = jnp.sum((new_centers - centers) ** 2)
    return new_centers, labels, inertia, shift


def _lloyd_window(
    xb: jax.Array, w: jax.Array, centers: jax.Array, shift0, max_iter: int, tol
):
    """The traceable body of :func:`_lloyd_fit_carry` — a resumable
    window of Lloyd iterations with the convergence carry entering and
    leaving. Split out so the streaming mini-batch updater
    (:class:`heat_tpu.streaming.MiniBatchKMeans`) can compose the SAME
    window math inside its own cached program (one program per chunk
    shape) instead of re-deriving the iteration."""

    def cond(carry):
        _, it, shift = carry
        return jnp.logical_and(it < max_iter, shift > tol)

    def body(carry):
        c, it, _ = carry
        new_c, _, _, shift = _lloyd_step.__wrapped__(xb, w, c)
        return new_c, it + 1, shift

    return jax.lax.while_loop(cond, body, (centers, jnp.int32(0), shift0))


@partial(jax.jit, static_argnames=("max_iter",))
def _lloyd_fit_carry(
    xb: jax.Array, w: jax.Array, centers: jax.Array, shift0, max_iter: int, tol
):
    """A resumable window of Lloyd iterations: same body as
    :func:`_lloyd_fit`, but the convergence carry (``shift``) enters and
    leaves the program, so the checkpoint driver can run the fit as exact
    chunks — ``k`` windows of ``checkpoint_every`` iterations apply the
    identical per-iteration math as one uninterrupted ``while_loop``
    (the resume-equivalence oracle in tests/test_resilience.py)."""
    return _lloyd_window(xb, w, centers, shift0, max_iter, tol)


@jax.jit
def _lloyd_final(xb: jax.Array, w: jax.Array, centers: jax.Array):
    """Final assignment + inertia for converged centers — the tail of
    :func:`_lloyd_fit`, shared by the checkpointed driver."""
    d2 = _d2(xb, centers)
    labels = jnp.argmin(d2, axis=1)
    inertia = jnp.sum(jnp.min(d2, axis=1) * w)
    return labels, inertia


@partial(jax.jit, static_argnames=("max_iter",))
def _lloyd_fit(xb: jax.Array, w: jax.Array, centers: jax.Array, max_iter: int, tol):
    """The whole Lloyd loop as one on-device `lax.while_loop` — the reference
    drives iterations from Python with a per-iteration convergence fetch
    (kmeans.py:122-135); on TPU that host sync per iteration would dominate,
    so the loop, the convergence test, and the final assignment all compile
    into a single XLA program (SURVEY §3.3)."""

    def cond(carry):
        _, it, shift = carry
        return jnp.logical_and(it < max_iter, shift > tol)

    def body(carry):
        c, it, _ = carry
        new_c, _, _, shift = _lloyd_step.__wrapped__(xb, w, c)
        return new_c, it + 1, shift

    centers, n_iter, _ = jax.lax.while_loop(
        cond, body, (centers, jnp.int32(0), jnp.asarray(jnp.inf, xb.dtype))
    )
    d2 = _d2(xb, centers)
    labels = jnp.argmin(d2, axis=1)
    inertia = jnp.sum(jnp.min(d2, axis=1) * w)
    return centers, labels, inertia, n_iter


class KMeans(_KCluster):
    """K-Means clusterer (reference kmeans.py:13).

    Parameters
    ----------
    n_clusters : int
    init : 'random' | 'probability_based' | DNDarray
    max_iter : int
    tol : float
        Convergence threshold on the squared centroid shift.
    random_state : int, optional
    checkpoint_every : int, optional
        Opt-in resilience hook (ISSUE 5): checkpoint the fit state every
        this many Lloyd iterations via
        :func:`heat_tpu.resilience.save_checkpoint` — the fit then runs as
        exact iteration windows, so a killed run resumes at the last
        completed window with bit-identical results to an uninterrupted
        fit. Requires ``checkpoint_path``.
    checkpoint_path : str, optional
        Checkpoint directory (atomically swapped on every save).
    resume : bool
        Load ``checkpoint_path`` (when it exists and is a kmeans
        checkpoint) and continue from its iteration count instead of the
        initial centers.
    """

    def __init__(
        self,
        n_clusters: int = 8,
        init: Union[str, DNDarray] = "random",
        max_iter: int = 300,
        tol: float = 1e-4,
        random_state: Optional[int] = None,
        checkpoint_every: Optional[int] = None,
        checkpoint_path: Optional[str] = None,
        resume: bool = False,
    ):
        super().__init__("euclidean", n_clusters, init, max_iter, tol, random_state)
        if checkpoint_every is not None:
            if checkpoint_every <= 0:
                raise ValueError(
                    f"checkpoint_every must be positive, got {checkpoint_every}"
                )
            if not checkpoint_path:
                raise ValueError("checkpoint_every requires checkpoint_path")
        elif resume:
            # resume only works through the windowed driver — ignoring the
            # flag would silently redo every completed iteration
            raise ValueError("resume=True requires checkpoint_every")
        self.checkpoint_every = checkpoint_every
        self.checkpoint_path = checkpoint_path
        self.resume = resume

    def fit(self, x: DNDarray) -> "KMeans":
        """Run Lloyd iterations to convergence (reference kmeans.py:102)."""
        if not isinstance(x, DNDarray):
            raise TypeError(f"input needs to be a DNDarray, but was {type(x)}")
        if x.ndim != 2:
            raise ValueError("input needs to be 2D")

        dt, xb, w, centers = self._fit_buffers(x)

        if self.checkpoint_every is not None:
            # checkpointed fit: exact iteration windows (the pallas path is
            # a whole-fit program with no resumable carry, so the windowed
            # XLA driver serves this mode on every backend)
            centers, labels, inertia, n_iter = self._fit_checkpointed(
                xb, w, centers
            )
            self._cluster_centers = DNDarray.from_logical(
                centers, None, x.device, x.comm, dt
            )
            self._labels = DNDarray(
                labels.astype(jnp.int64), (x.shape[0],), types.int64,
                x.split, x.device, x.comm, True,
            )
            self._inertia = float(inertia)
            self._n_iter = n_iter
            return self

        from .pallas_lloyd import (
            lloyd_fit_pallas,
            lloyd_fit_pallas_sharded,
            pallas_lloyd_applicable,
        )

        done = False
        if pallas_lloyd_applicable(
            x.comm.size, x.split, x.shape[1], self.n_clusters, xb.dtype
        ):
            # fused single-pass-over-X Lloyd update (see pallas_lloyd);
            # Mosaic failure degrades to the XLA fit rather than erroring
            try:
                if x.comm.size > 1:
                    p_out = lloyd_fit_pallas_sharded(
                        x.comm, xb, centers, x.shape[0], self.max_iter,
                        jnp.asarray(self.tol, xb.dtype),
                    )
                else:
                    p_out = lloyd_fit_pallas(
                        xb, centers, x.shape[0], self.max_iter,
                        jnp.asarray(self.tol, xb.dtype),
                    )
                # materialize INSIDE the try — async TPU runtime faults
                # surface lazily and must trigger the fallback here
                jax.block_until_ready(p_out)
                centers, labels, inertia, n_iter = p_out
                done = True
            except Exception as e:  # pragma: no cover — TPU-runtime only
                import warnings

                warnings.warn(f"pallas kmeans fell back to XLA: {e!r}")
        if not done:
            centers, labels, inertia, n_iter = _lloyd_fit(
                xb, w, centers, self.max_iter, jnp.asarray(self.tol, xb.dtype)
            )
        n_iter = int(n_iter)

        self._cluster_centers = DNDarray.from_logical(centers, None, x.device, x.comm, dt)
        self._labels = DNDarray(
            labels.astype(jnp.int64), (x.shape[0],), types.int64, x.split, x.device, x.comm, True
        )
        self._inertia = float(inertia)
        self._n_iter = n_iter
        return self

    def _fit_checkpointed(self, xb, w, centers):
        """Drive Lloyd iterations in windows of ``checkpoint_every``,
        checkpointing (centers, iteration count, convergence carry) after
        each window. The carried ``shift`` makes the chunking exact: the
        sequence of per-iteration updates is identical to one uninterrupted
        :func:`_lloyd_fit` run, and a resumed fit continues it bit-for-bit
        (``shift`` round-trips through the manifest as a python float —
        exact for f32/f64 values)."""
        import os

        import numpy as np

        from .. import resilience

        path = self.checkpoint_path
        every = int(self.checkpoint_every)
        tol = jnp.asarray(self.tol, xb.dtype)
        it_done = 0
        shift = jnp.asarray(jnp.inf, xb.dtype)
        if self.resume and resilience.checkpoint.exists(path):
            leaves, extra = resilience.load_checkpoint(path, with_extra=True)
            if extra.get("algo") != "kmeans" or len(leaves) != 1:
                raise resilience.CheckpointError(
                    f"{path!r} is a {extra.get('algo')!r} checkpoint, not kmeans"
                )
            centers = jnp.asarray(leaves[0], dtype=xb.dtype)
            it_done = int(extra["n_iter"])
            shift = jnp.asarray(extra["shift"], xb.dtype)
        while it_done < self.max_iter and bool(shift > tol):
            window = min(every, self.max_iter - it_done)
            centers, n_it, shift = _lloyd_fit_carry(
                xb, w, centers, shift, window, tol
            )
            it_done += int(n_it)
            resilience.save_checkpoint(
                [np.asarray(centers)], path,
                extra={"algo": "kmeans", "n_iter": it_done,
                       "shift": float(shift)},
            )
        labels, inertia = _lloyd_final(xb, w, centers)
        return centers, labels, inertia, it_done
