"""Shared machinery for the K-family clusterers.

Re-design of reference heat/cluster/_kcluster.py:10-254. The reference picks
initial centroids with rank-owned Bcasts (:100-130) and assigns points via a
`cdist` against replicated centers (:196). Here initialization samples from
the logical global view and the whole Lloyd-style iteration runs as one
jit-compiled step over the padded sharded buffer, with a validity-weight
vector neutralizing tail pads (one psum per iteration, inserted by XLA —
same collective count as the reference's Allreduce).
"""

from __future__ import annotations

from typing import Optional

import jax
import jax.numpy as jnp
import numpy as np

from ..core import types
from ..core.base import BaseEstimator, ClusteringMixin
from ..core.dndarray import DNDarray

__all__ = ["_KCluster"]


def _d2(xb: "jax.Array", centers: "jax.Array") -> "jax.Array":
    """(m, k) squared euclidean distances in GEMM form — THE shared kernel
    for all K-family assignment steps and KNN.

    HIGH matmul precision (bf16x3 on TPU): the x²+c²−2xc form cancels
    catastrophically at small distances, and TPU default single-pass bf16
    turns that into absolute errors ~0.3 that flip assignments near Voronoi
    boundaries. bf16x3 recovers ~f32-quality products at half the cost of
    HIGHEST's 6-pass true-f32 (see spatial/distance.py)."""
    x2 = jnp.sum(xb * xb, axis=1, keepdims=True)
    c2 = jnp.sum(centers * centers, axis=1)[None, :]
    prod = jnp.matmul(xb, centers.T, precision=jax.lax.Precision.HIGH)
    return jnp.maximum(x2 + c2 - 2.0 * prod, 0.0)


def _d1(xb: "jax.Array", centers: "jax.Array") -> "jax.Array":
    """(m, k) Manhattan distances — the assignment metric of KMedians and
    KMedoids (reference kmedians.py:49, kmedoids.py:48: both fix
    ``metric=manhattan``). Delegates to the spatial row-blocked kernel so the
    memory-budget logic lives in one place."""
    from ..spatial.distance import _blocked_manhattan

    return _blocked_manhattan(xb, centers)


def _pad_weights(xb: "jax.Array", n_logical: int) -> "jax.Array":
    """Validity weights: 1 for logical rows, 0 for tail pads."""
    return (jnp.arange(xb.shape[0]) < n_logical).astype(xb.dtype)


class _KCluster(BaseEstimator, ClusteringMixin):
    """Base for KMeans/KMedians/KMedoids (reference _kcluster.py:10).

    Parameters mirror the reference: metric-specific update lives in the
    subclass's `_update_step`; init is ``'random'`` (k data rows) or
    ``'probability_based'`` (k-means++ seeding, reference :100-130) or a
    DNDarray of initial centers.
    """

    def __init__(self, metric: str, n_clusters: int, init, max_iter: int, tol: float, random_state: Optional[int]):
        if metric not in ("euclidean", "manhattan"):
            raise ValueError(f"metric must be 'euclidean' or 'manhattan', got {metric!r}")
        self._metric_name = metric
        self.n_clusters = n_clusters
        self.init = init
        self.max_iter = max_iter
        self.tol = tol
        self.random_state = random_state

        self._cluster_centers = None
        self._labels = None
        self._inertia = None
        self._n_iter = None

    @property
    def cluster_centers_(self) -> DNDarray:
        return self._cluster_centers

    @property
    def labels_(self) -> DNDarray:
        return self._labels

    @property
    def inertia_(self) -> float:
        return self._inertia

    @property
    def n_iter_(self) -> int:
        return self._n_iter

    # -- initialization ------------------------------------------------------

    def _initialize_cluster_centers(self, x: DNDarray) -> jax.Array:
        """Initial (k, d) centers as a replicated jax array (reference
        _kcluster.py:87)."""
        k = self.n_clusters
        seed = self.random_state if self.random_state is not None else 0
        key = jax.random.PRNGKey(seed)
        n = x.shape[0]
        buf = x._masked(0)  # padded physical buffer, pad rows zeroed

        if isinstance(self.init, DNDarray):
            if self.init.shape != (k, x.shape[1]):
                raise ValueError(
                    f"passed centroids need to be of shape ({k}, {x.shape[1]}), but are {self.init.shape}"
                )
            return self.init._replicated()
        if self.init == "random":
            # sampled indices are < n, so the sharded gather never reads the
            # pad — the owning-rank-Bcast of the reference (:100-130) becomes
            # one compiled cross-shard take
            idx = jax.random.choice(key, n, shape=(k,), replace=False)
            return jnp.take(buf, idx, axis=0)
        if self.init in ("probability_based", "kmeans++", "k-means++"):
            # k-means++ seeding (reference 'probability_based' :100-130);
            # pad rows get probability 0 so they are never selected
            row_ok = jnp.arange(buf.shape[0]) < n
            centers = [jnp.take(buf, jax.random.randint(key, (), 0, n), axis=0)]
            for i in range(1, k):
                key, sub = jax.random.split(key)
                c = jnp.stack(centers)
                d2 = jnp.min(_d2(buf.astype(jnp.float32), c.astype(jnp.float32)), axis=1)
                d2 = jnp.where(row_ok, d2, 0.0)
                probs = d2 / jnp.maximum(jnp.sum(d2), 1e-30)
                # compiled slice to the logical length (stays on device)
                nxt = jax.random.choice(sub, n, p=probs[:n])
                centers.append(jnp.take(buf, nxt, axis=0))
            return jnp.stack(centers)
        raise ValueError(
            f"initialization needs to be 'random', 'probability_based' or a DNDarray, but was {self.init}"
        )

    # -- assignment ----------------------------------------------------------

    def _assign_to_cluster(self, x: DNDarray) -> DNDarray:
        """Hard assignment of each sample under the estimator's metric
        (reference _kcluster.py:196,206: ``self._metric(x, centers).argmin``)."""
        centers = self._cluster_centers._replicated()
        dist_fn = _d1 if self._metric_name == "manhattan" else _d2
        d = dist_fn(x._masked(0).astype(centers.dtype), centers)
        labels = jnp.argmin(d, axis=1).astype(jnp.int64)
        return DNDarray(labels, (x.shape[0],), types.int64, x.split, x.device, x.comm, True)

    def predict(self, x: DNDarray) -> DNDarray:
        """Nearest learned centroid for each sample (reference
        _kcluster.py `predict`)."""
        if self._cluster_centers is None:
            raise RuntimeError("fit needs to be called before predict")
        return self._assign_to_cluster(x)

    # -- fit driver ----------------------------------------------------------

    def _fit_buffers(self, x: DNDarray):
        """(masked padded samples, validity weights, initial centers) for the
        jitted fit loops — pads are zeroed (tail-pad invariant: pad values
        are otherwise unspecified) and weighted out of all sums."""
        dt = types.promote_types(x.dtype, types.float32)
        xb = x._masked(0).astype(dt.jnp_type())
        w = _pad_weights(xb, x.shape[0])
        centers = self._initialize_cluster_centers(x).astype(xb.dtype)
        return dt, xb, w, centers

    def fit(self, x: DNDarray):
        raise NotImplementedError()
