"""Fused Pallas TPU kernel for the Lloyd (K-Means) iteration.

The XLA Lloyd step (:func:`heat_tpu.cluster.kmeans._lloyd_step`) is already
one compiled program, but it materializes two (n, k) f32 intermediates per
iteration (the distance matrix and the one-hot matrix) — at bench shapes
(n=2M, d=k=64) that is ~5 HBM round trips over X's own footprint, and the
r4 bench measured 4.5 TF/s counted against a ~50 TF/s bandwidth roofline.

This kernel runs the whole accumulation in one pass over X: for each row
block the assignment scores, argmin, and the (k, d)/(k,) sums+counts
updates all happen on the tile while it is in VMEM — X is read exactly
ONCE per Lloyd iteration and nothing (n, k)-sized ever touches HBM.

MXU dots per block (scores: (bm,d)x(d,k); update: (k,bm)x(bm,d)), both
with f32 accumulation. The argmin drops the ||x||^2 term (constant per
row — it cannot change the winner), so scores are just c2 - 2 x.c with
the manual ``"bf16x3"`` split product by default (HIGH-class accuracy —
the guard from ``_kcluster._d2`` — via MXU-guaranteed DEFAULT-tier dots,
see pallas_util.dot_f32).

Scope: TPU f32 fits — single-device directly, multi-device via
`lloyd_fit_pallas_sharded` (shard_map over row shards + one psum of the
sums/counts per iteration, the same single-collective shape as the XLA
fit). The final labels/inertia pass stays on the XLA `_d2` form — one
extra pass at the end of the fit is noise across max_iter iterations.
"""

from __future__ import annotations

import functools

import jax
import jax.numpy as jnp
import numpy as np
from jax.experimental import pallas as pl
from jax.experimental.pallas import tpu as pltpu

from ..core.pallas_util import DotPrecision, dot_f32

__all__ = [
    "lloyd_fit_pallas",
    "lloyd_fit_pallas_sharded",
    "pallas_lloyd_applicable",
]

_I0 = np.int32(0)  # i32 index-map literal (jax_enable_x64 guard)
_MAX_D = 512
_MAX_K = 1024


def _round_up(v: int, m: int) -> int:
    return -(-v // m) * m


def _lloyd_kernel(
    lim_ref, x_ref, c_ref, sums_ref, counts_ref, sums_s, counts_s, *, bm, k,
    precision,
):
    """Grid = (num_row_blocks,), sequential. Scratch (sums, counts)
    accumulates across blocks; written out at the last block. ``lim_ref``
    holds this buffer's LOCAL valid-row count — rows at or past it (the
    global tail pad on the last shards, plus any local block-size
    round-up pad) drop out of sums and counts."""
    i = pl.program_id(0)
    nb = pl.num_programs(0)

    @pl.when(i == 0)
    def _init():
        sums_s[:] = jnp.zeros_like(sums_s)
        counts_s[:] = jnp.zeros_like(counts_s)

    xb = x_ref[:]  # (bm, dp) f32
    c = c_ref[:]  # (kp, dp) f32
    # ``precision`` (a tier or "bf16x3") for the scores dot is swept
    # on-chip by scripts/tpu_tune.py (Mosaic lowering cost per strategy
    # is not uniform; see pallas_util.dot_f32)
    dot = dot_f32(xb, c, (((1,), (1,)), ((), ())), precision)  # (bm, kp)
    c2 = jnp.sum(c * c, axis=1)[None, :]  # (1, kp)
    score = c2 - jnp.float32(2.0) * dot  # argmin-equivalent to d2
    jidx = jax.lax.broadcasted_iota(jnp.int32, score.shape, 1)
    score = jnp.where(jidx < k, score, jnp.float32(3.4e38))  # mask center pads
    labels = jnp.argmin(score, axis=1)[:, None]  # (bm, 1)
    row = i * bm + jax.lax.broadcasted_iota(jnp.int32, (bm, 1), 0)
    valid = row < lim_ref[0]
    onehot = jnp.where(
        (labels == jidx) & valid, jnp.float32(1.0), jnp.float32(0.0)
    )  # (bm, kp)
    # the update dot carries the same guard: onehot is exact in bf16, so
    # the split product recovers f32-class center sums — a bare DEFAULT
    # dot would bake ~2^-9 operand rounding into every center coordinate
    sums_s[:] += dot_f32(
        onehot, xb, (((0,), (0,)), ((), ())), precision
    )  # (kp, dp)
    counts_s[:] += jnp.broadcast_to(
        jnp.sum(onehot, axis=0, keepdims=True), counts_s.shape
    )

    @pl.when(i == nb - 1)
    def _flush():
        sums_ref[:] = sums_s[:]
        counts_ref[:] = counts_s[:]


def _lloyd_update(x, centers_pad, n, k, bm, interpret, lim=None,
                  precision: DotPrecision = "bf16x3"):
    """One fused accumulation pass: (sums (kp, dp), counts (8, kp)).
    ``x`` must already be padded to (mp, dp) with mp % bm == 0;
    ``centers_pad`` to (kp, dp); ``lim`` is the LOCAL valid-row count
    (defaults to the global n — correct outside shard_map)."""
    mp, dp = x.shape
    kp = centers_pad.shape[0]
    if lim is None:
        lim = jnp.full((1,), n, jnp.int32)
    return pl.pallas_call(
        functools.partial(_lloyd_kernel, bm=bm, k=k, precision=precision),
        grid=(mp // bm,),
        in_specs=[
            # explicit i32 index map: a bare SMEM BlockSpec synthesizes a
            # default map whose literals trace as i64 under jax_enable_x64,
            # which Mosaic cannot legalize ("func.return(i64)")
            pl.BlockSpec((1,), lambda i: (_I0,), memory_space=pltpu.SMEM),
            pl.BlockSpec((bm, dp), lambda i: (i, _I0), memory_space=pltpu.VMEM),
            pl.BlockSpec((kp, dp), lambda i: (_I0, _I0), memory_space=pltpu.VMEM),
        ],
        out_specs=[
            pl.BlockSpec((kp, dp), lambda i: (_I0, _I0), memory_space=pltpu.VMEM),
            pl.BlockSpec((8, kp), lambda i: (_I0, _I0), memory_space=pltpu.VMEM),
        ],
        out_shape=[
            jax.ShapeDtypeStruct((kp, dp), jnp.float32),
            jax.ShapeDtypeStruct((8, kp), jnp.float32),
        ],
        scratch_shapes=[
            pltpu.VMEM((kp, dp), jnp.float32),
            pltpu.VMEM((8, kp), jnp.float32),
        ],
        compiler_params=pltpu.CompilerParams(
            dimension_semantics=("arbitrary",),
        ),
        interpret=interpret,
    )(lim.astype(jnp.int32), x, centers_pad)


@functools.partial(
    jax.jit,
    static_argnames=("n", "max_iter", "block_m", "interpret", "precision"),
)
def lloyd_fit_pallas(
    xb: jax.Array,
    centers0: jax.Array,
    n: int,
    max_iter: int,
    tol,
    block_m: int = 512,
    interpret: bool = False,
    precision: DotPrecision = "bf16x3",
):
    """The whole K-Means fit with the fused update kernel inside a
    `lax.while_loop`; returns (centers (k, d), labels (m,), inertia,
    n_iter) with the same semantics as `kmeans._lloyd_fit` (labels/inertia
    from one final XLA `_d2` pass over the converged centers)."""
    from ._kcluster import _d2

    m, d = xb.shape
    k = centers0.shape[0]
    # feature lanes pad at 64-granularity (like 64-wide attention
    # heads): d=64 stays unpadded — a 128 pad would double X's HBM
    # footprint and read traffic at the bench shapes
    dp, kp = _round_up(d, 64), _round_up(k, 128)
    bm = min(block_m, _round_up(m, 8))
    mp = _round_up(m, bm)
    xp = jnp.pad(xb.astype(jnp.float32), ((0, mp - m), (0, dp - d)))
    c0 = jnp.pad(centers0.astype(jnp.float32), ((0, kp - k), (0, dp - d)))

    def cond(carry):
        _, it, shift = carry
        return jnp.logical_and(it < max_iter, shift > tol)

    def body(carry):
        c, it, _ = carry
        sums, counts = _lloyd_update(xp, c, n, k, bm, interpret,
                                     precision=precision)
        cnt = counts[0:1, :].T  # (kp, 1); center pads stay 0
        new_c = jnp.where(cnt > 0, sums / jnp.maximum(cnt, 1.0), c)
        shift = jnp.sum((new_c - c) ** 2)
        return new_c, it + 1, shift

    cpad, n_iter, _ = jax.lax.while_loop(
        cond, body, (c0, jnp.int32(0), jnp.asarray(jnp.inf, jnp.float32))
    )
    centers = cpad[:k, :d].astype(xb.dtype)
    # final assignment on the XLA form (one pass; exact d2 for inertia)
    w = (jnp.arange(m) < n).astype(xb.dtype)
    d2 = _d2(xb, centers)
    labels = jnp.argmin(d2, axis=1)
    inertia = jnp.sum(jnp.min(d2, axis=1) * w)
    return centers, labels, inertia, n_iter


@functools.partial(
    jax.jit,
    static_argnames=(
        "comm", "n", "max_iter", "block_m", "interpret", "precision"
    ),
)
def lloyd_fit_pallas_sharded(
    comm,
    xb: jax.Array,
    centers0: jax.Array,
    n: int,
    max_iter: int,
    tol,
    block_m: int = 512,
    interpret: bool = False,
    precision: DotPrecision = "bf16x3",
):
    """Multi-device variant: the fused update runs per row-shard inside
    `shard_map` and one psum per iteration merges the (k, d)+(k,)
    sums/counts — the same single-collective-per-Lloyd-iteration shape as
    the XLA fit (and the reference's Allreduce, kmeans.py:73). Centers
    carry replicated through the while_loop; labels/inertia come from one
    final XLA `_d2` pass on the sharded buffer outside the shard_map."""
    from ._kcluster import _d2

    p = comm.size
    m, d = xb.shape
    k = centers0.shape[0]
    # feature lanes pad at 64-granularity (like 64-wide attention
    # heads): d=64 stays unpadded — a 128 pad would double X's HBM
    # footprint and read traffic at the bench shapes
    dp, kp = _round_up(d, 64), _round_up(k, 128)
    c_rows = m // p  # physical buffer rows divide the mesh by invariant
    bm = min(block_m, _round_up(c_rows, 8))
    c0 = jnp.pad(centers0.astype(jnp.float32), ((0, kp - k), (0, dp - d)))

    def shard_fn(xs, c0_):
        rank = comm.axis_index()
        # local valid rows: global logical rows falling inside this shard
        lim = jnp.clip(n - rank * c_rows, 0, c_rows).astype(jnp.int32).reshape((1,))
        mp_l = _round_up(c_rows, bm)
        xp = jnp.pad(xs.astype(jnp.float32), ((0, mp_l - c_rows), (0, dp - d)))

        def cond(carry):
            _, it, shift = carry
            return jnp.logical_and(it < max_iter, shift > tol)

        def body(carry):
            c, it, _ = carry
            sums, counts = _lloyd_update(xp, c, n, k, bm, interpret, lim,
                                         precision=precision)
            # comm wrapper (not raw lax.psum) so the hop is visible to
            # the HLO auditor/cost model; pinned exact — centroid
            # accumulation predates the collective-precision knob and a
            # compressed wire would move the fixed point (heatlint HL002)
            sums = comm.psum(sums, precision="off")
            counts = comm.psum(counts, precision="off")
            cnt = counts[0:1, :].T
            new_c = jnp.where(cnt > 0, sums / jnp.maximum(cnt, 1.0), c)
            shift = jnp.sum((new_c - c) ** 2)
            return new_c, it + 1, shift

        cpad, n_iter, _ = jax.lax.while_loop(
            cond, body, (c0_, jnp.int32(0), jnp.asarray(jnp.inf, jnp.float32))
        )
        return cpad, n_iter

    cpad, n_iter = jax.shard_map(
        shard_fn,
        mesh=comm.mesh,
        in_specs=(comm.spec(0, 2), comm.spec(None, 2)),
        out_specs=(comm.spec(None, 2), comm.spec(None, 0)),
        check_vma=False,
    )(xb, c0)
    centers = cpad[:k, :d].astype(xb.dtype)
    w = (jnp.arange(m) < n).astype(xb.dtype)
    d2 = _d2(xb, centers)
    labels = jnp.argmin(d2, axis=1)
    inertia = jnp.sum(jnp.min(d2, axis=1) * w)
    return centers, labels, inertia, n_iter


def pallas_lloyd_applicable(comm_size: int, split, d: int, k: int, jnp_dtype) -> bool:
    """TPU f32 fits with blocks that fit VMEM; multi-device needs the
    sample buffer row-sharded (split=0)."""
    return (
        jax.default_backend() == "tpu"
        and (comm_size == 1 or split == 0)
        and d <= _MAX_D
        and k <= _MAX_K
        and jnp_dtype == jnp.float32
    )
