"""Spectral clustering.

Re-design of reference heat/cluster/spectral.py:12-201: rbf/cdist similarity
→ `Laplacian.construct` → Lanczos tridiagonalization → eigendecomposition of
the small T on host → k lowest eigenvectors → KMeans in the embedding space.
The pipeline is identical; each stage is the TPU-native version (GEMM
similarity, shard-aware Lanczos, MXU KMeans).
"""

from __future__ import annotations

from typing import Optional, Union

import jax.numpy as jnp
import numpy as np

from ..core import types
from ..core.base import BaseEstimator, ClusteringMixin
from ..core.dndarray import DNDarray
from ..core.linalg import lanczos
from ..graph import Laplacian
from .. import spatial
from .kmeans import KMeans

__all__ = ["Spectral"]


class Spectral(BaseEstimator, ClusteringMixin):
    """Spectral clustering on the graph Laplacian's spectral embedding
    (reference spectral.py:12).

    Parameters (mirror the reference): `gamma` is the RBF kernel coefficient
    (σ = sqrt(1/2γ)), `metric` selects the similarity, `laplacian` the graph
    construction, `n_lanczos` the Krylov subspace size.
    """

    def __init__(
        self,
        n_clusters: Optional[int] = None,
        gamma: float = 1.0,
        metric: str = "rbf",
        laplacian: str = "fully_connected",
        threshold: float = 1.0,
        boundary: str = "upper",
        n_lanczos: int = 300,
        assign_labels: str = "kmeans",
        sparse: Optional[bool] = None,
        **params,
    ):
        self.n_clusters = n_clusters
        self.gamma = gamma
        self.metric = metric
        self.laplacian = laplacian
        self.threshold = threshold
        self.boundary = boundary
        self.n_lanczos = n_lanczos
        self.assign_labels = assign_labels
        self.sparse = sparse

        sigma = float(np.sqrt(1.0 / (2.0 * gamma)))
        pair = None
        if callable(metric):
            # extension over the reference (spectral.py:84 raises for
            # anything beyond rbf/euclidean): any DNDarray -> DNDarray
            # similarity callable plugs into the Laplacian (no block
            # form, so an eNeighbour graph loses the O(n²)-free
            # construction guarantee — Laplacian degrades gracefully)
            sim = metric
        elif metric == "rbf":
            sim = lambda x: spatial.rbf(x, sigma=sigma, quadratic_expansion=True)
            pair = lambda a, b: spatial.rbf(
                a, b, sigma=sigma, quadratic_expansion=True
            )
        elif metric == "euclidean":
            sim = lambda x: spatial.cdist(x, quadratic_expansion=True)
            pair = lambda a, b: spatial.cdist(a, b, quadratic_expansion=True)
        elif metric == "manhattan":
            # extension: L1 affinity via the same ring/GEMM machinery
            sim = lambda x: spatial.manhattan(x)
            pair = lambda a, b: spatial.manhattan(a, b)
        else:
            raise NotImplementedError(f"Metric {metric} is currently not implemented")
        self._laplacian = Laplacian(
            sim,
            definition="norm_sym",
            mode="eNeighbour" if laplacian == "eNeighbour" else "fully_connected",
            threshold_key=boundary,
            threshold_value=threshold,
            sparse=sparse,
            # the two-operand block form: what lets the eNeighbour graph
            # build as a SparseDNDarray in temp_budget-sized row blocks
            # instead of materializing the O(n²) similarity (ISSUE 13)
            pair_similarity=pair,
        )
        if assign_labels == "kmeans":
            self._cluster = KMeans(
                n_clusters=n_clusters if n_clusters else 8, init="probability_based"
            )
        else:
            raise NotImplementedError(
                f"Linkage via {assign_labels} is currently not implemented"
            )
        self._labels = None
        self._embedding = None

    @property
    def labels_(self) -> DNDarray:
        return self._labels

    def _spectral_embedding(self, x: DNDarray):
        """Lowest eigenpairs of L via Lanczos (reference spectral.py:103).
        An eNeighbour graph arrives as a
        :class:`~heat_tpu.sparse.SparseDNDarray` and the Krylov matvecs
        run as spmv inside the very same cached Lanczos program — the
        solver's operator protocol makes sparse a drop-in (ISSUE 13)."""
        L = self._laplacian.construct(x)
        m = min(self.n_lanczos, x.shape[0])
        V, T = lanczos(L, m)
        t_host = np.asarray(T.numpy(), dtype=np.float64)
        eigval, eigvec = np.linalg.eigh(t_host)  # ascending
        v_log = V._replicated().astype(jnp.float64)
        full_vec = v_log @ jnp.asarray(eigvec)  # Ritz vectors
        return (
            DNDarray.from_logical(jnp.asarray(eigval), None, x.device, x.comm),
            DNDarray.from_logical(full_vec, x.split, x.device, x.comm),
        )

    def _embed(self, x: DNDarray, eigvec: DNDarray) -> DNDarray:
        """Slice the k lowest eigenvectors and rewrap as the float32
        clustering space — shared by fit and predict so both always classify
        in the same embedding."""
        components = eigvec[:, : self.n_clusters]
        return DNDarray.from_logical(
            components._replicated().astype(jnp.float32), x.split, x.device, x.comm
        )

    @staticmethod
    def _as_rows(x: DNDarray) -> DNDarray:
        """Canonicalize to row-split (or replicated) samples. The reference
        raises NotImplementedError for split != 0 (spectral.py:154,:198);
        here any split is accepted — a feature-split input pays one relayout
        up front and the pipeline runs on rows as usual."""
        if x.split is not None and x.split != 0:
            from ..core import manipulations

            return manipulations.resplit(x, 0)
        return x

    def fit(self, x: DNDarray) -> "Spectral":
        """Embed and cluster (reference spectral.py:134)."""
        if not isinstance(x, DNDarray):
            raise TypeError(f"input needs to be a DNDarray, but was {type(x)}")
        x = self._as_rows(x)
        eigval, eigvec = self._spectral_embedding(x)
        if self.n_clusters is None:
            # largest eigen-gap heuristic (reference spectral.py:150)
            ev = eigval.numpy()
            diff = np.diff(ev)
            self.n_clusters = int(np.argmax(diff) + 1)
            self._cluster.n_clusters = self.n_clusters
        comp = self._embed(x, eigvec)
        self._embedding = comp
        self._cluster.fit(comp)
        self._labels = self._cluster.labels_
        return self

    def predict(self, x: DNDarray) -> DNDarray:
        """Labels for ``x``: x is re-embedded by a fresh eigenspectrum
        computation and classified against the fitted KMeans centroids
        (reference spectral.py:174-201 — note the embedding is recomputed
        from x's own similarity graph, so this is only meaningful for data
        drawn from the fitted distribution; the reference carries the same
        caveat in its docstring Warning)."""
        if self._embedding is None:
            raise RuntimeError("fit needs to be called before predict")
        if not isinstance(x, DNDarray):
            raise TypeError(f"input needs to be a DNDarray, but was {type(x)}")
        x = self._as_rows(x)
        _, eigvec = self._spectral_embedding(x)
        return self._cluster.predict(self._embed(x, eigvec))
