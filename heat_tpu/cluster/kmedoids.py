"""K-Medoids clustering (reference: heat/cluster/kmedoids.py:10-150 — Lloyd
skeleton with Manhattan assignment (``metric=manhattan``, reference
kmedoids.py:48) and the updated centroid snapped to the actual data point
closest to the member median (reference `_update_centroids` :55-110)."""

from __future__ import annotations

from functools import partial
from typing import Optional, Union

import jax
import jax.numpy as jnp

from ..core import types
from ..core.dndarray import DNDarray
from ._kcluster import _KCluster, _d1
from .kmedians import _median_update

__all__ = ["KMedoids"]


@partial(jax.jit, static_argnames=("max_iter",))
def _medoid_fit(xb: jax.Array, w: jax.Array, centers: jax.Array, max_iter: int, tol):
    """Whole fit loop on-device (see kmeans._lloyd_fit for the rationale).

    Update rule per the reference: per-cluster per-dimension median, then
    snap to the L1-closest valid data point (searched over the full data set,
    reference kmedoids.py:99-110); empty clusters keep their center (the
    reference draws a random sample instead, :86-98 — deterministic
    keep-old is the jit-stable choice, documented deviation)."""
    valid = w > 0

    def snap(med, c_old, any_member):
        d = jnp.sum(jnp.abs(xb - med[None, :]), axis=1)
        d = jnp.where(valid, d, jnp.inf)
        return jnp.where(any_member, xb[jnp.argmin(d)], c_old)

    def cond(carry):
        _, it, shift = carry
        return jnp.logical_and(it < max_iter, shift > tol)

    def body(carry):
        c, it, _ = carry
        d1 = _d1(xb, c)
        labels = jnp.argmin(d1, axis=1)
        medians, member_any = _median_update(xb, labels, valid, c)
        new_c = jax.vmap(snap)(medians, c, member_any)
        shift = jnp.sum((new_c - c) ** 2)
        return new_c, it + 1, shift

    centers, n_iter, _ = jax.lax.while_loop(
        cond, body, (centers, jnp.int32(0), jnp.asarray(jnp.inf, xb.dtype))
    )
    d1 = _d1(xb, centers)
    labels = jnp.argmin(d1, axis=1)
    inertia = jnp.sum(jnp.min(d1, axis=1) * w)
    return centers, labels, inertia, n_iter


class KMedoids(_KCluster):
    """K-Medoids clusterer (reference kmedoids.py:10)."""

    def __init__(
        self,
        n_clusters: int = 8,
        init: Union[str, DNDarray] = "random",
        max_iter: int = 300,
        random_state: Optional[int] = None,
    ):
        if init == "kmedoids++":
            init = "probability_based"
        # reference fixes tol=0.0 (kmedoids.py:52): iterate until the medoids
        # stop moving or max_iter
        super().__init__("manhattan", n_clusters, init, max_iter, 0.0, random_state)

    def fit(self, x: DNDarray) -> "KMedoids":
        """Medoid-update Lloyd iterations (reference kmedoids.py `fit`)."""
        if not isinstance(x, DNDarray):
            raise TypeError(f"input needs to be a DNDarray, but was {type(x)}")
        if x.ndim != 2:
            raise ValueError("input needs to be 2D")
        dt, xb, w, centers = self._fit_buffers(x)

        centers, labels, inertia, n_iter = _medoid_fit(
            xb, w, centers, self.max_iter, jnp.asarray(self.tol, xb.dtype)
        )

        self._cluster_centers = DNDarray.from_logical(centers, None, x.device, x.comm, dt)
        self._labels = DNDarray(
            labels.astype(jnp.int64), (x.shape[0],), types.int64, x.split, x.device, x.comm, True
        )
        self._inertia = float(inertia)
        self._n_iter = int(n_iter)
        return self
