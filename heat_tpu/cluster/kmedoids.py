"""K-Medoids clustering (reference: heat/cluster/kmedoids.py:10-150 — Lloyd
skeleton with the updated centroid snapped to the nearest actual data
point)."""

from __future__ import annotations

from functools import partial
from typing import Optional, Union

import jax
import jax.numpy as jnp

from ..core import types
from ..core.dndarray import DNDarray
from ._kcluster import _KCluster, _d2

__all__ = ["KMedoids"]


@partial(jax.jit, static_argnums=(3,))
def _medoid_step(xb: jax.Array, w: jax.Array, centers: jax.Array, k: int):
    d2 = _d2(xb, centers)
    labels = jnp.argmin(d2, axis=1)
    valid = w > 0
    onehot = (labels[:, None] == jnp.arange(k)[None, :]).astype(xb.dtype) * w[:, None]
    counts = jnp.sum(onehot, axis=0)
    means = jnp.where(
        counts[:, None] > 0, (onehot.T @ xb) / jnp.maximum(counts, 1.0)[:, None], centers
    )

    # snap each mean to the closest member point (the medoid snap)
    def snap(c):
        member = (labels == c) & valid
        dist = jnp.sum((xb - means[c][None, :]) ** 2, axis=1)
        dist = jnp.where(member, dist, jnp.inf)
        idx = jnp.argmin(dist)
        return jnp.where(jnp.any(member), xb[idx], centers[c])

    new_centers = jax.vmap(snap)(jnp.arange(k))
    inertia = jnp.sum(jnp.sqrt(jnp.min(d2, axis=1)) * w)
    shift = jnp.sum((new_centers - centers) ** 2)
    return new_centers, labels, inertia, shift


class KMedoids(_KCluster):
    """K-Medoids clusterer (reference kmedoids.py:10)."""

    def __init__(
        self,
        n_clusters: int = 8,
        init: Union[str, DNDarray] = "random",
        max_iter: int = 300,
        tol: float = 1e-4,
        random_state: Optional[int] = None,
    ):
        super().__init__("euclidean", n_clusters, init, max_iter, tol, random_state)

    def fit(self, x: DNDarray) -> "KMedoids":
        """Medoid-update Lloyd iterations (reference kmedoids.py `fit`)."""
        if not isinstance(x, DNDarray):
            raise TypeError(f"input needs to be a DNDarray, but was {type(x)}")
        if x.ndim != 2:
            raise ValueError("input needs to be 2D")
        dt, xb, w, centers = self._fit_buffers(x)

        labels, inertia, n_iter = None, None, 0
        for it in range(self.max_iter):
            centers, labels, inertia, shift = _medoid_step(xb, w, centers, self.n_clusters)
            n_iter = it + 1
            if float(shift) <= self.tol:
                break

        self._cluster_centers = DNDarray.from_logical(centers, None, x.device, x.comm, dt)
        self._labels = DNDarray(
            labels.astype(jnp.int64), (x.shape[0],), types.int64, x.split, x.device, x.comm, True
        )
        self._inertia = float(inertia)
        self._n_iter = n_iter
        return self
