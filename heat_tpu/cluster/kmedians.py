"""K-Medians clustering (reference: heat/cluster/kmedians.py:10-137 — Lloyd
skeleton with Manhattan assignment (``metric=manhattan``, reference
kmedians.py:49) and a per-dimension median update)."""

from __future__ import annotations

from functools import partial
from typing import Optional, Union

import jax
import jax.numpy as jnp

from ..core import types
from ..core.dndarray import DNDarray
from ._kcluster import _KCluster, _d1

__all__ = ["KMedians"]


def _median_update(xb: jax.Array, labels: jax.Array, valid: jax.Array, centers: jax.Array):
    """Per-cluster per-dimension median over members; empty clusters keep
    their center (reference kmedians.py `_update_centroids`). Returns
    ``(medians, any_member)`` so callers that need the empty-cluster mask
    (KMedoids' snap step) don't recompute membership."""

    def upd(c):
        member = (labels == c) & valid
        masked = jnp.where(member[:, None], xb, jnp.nan)
        med = jnp.nanmedian(masked, axis=0)
        has = jnp.any(member)
        return jnp.where(has, med, centers[c]), has

    return jax.vmap(upd)(jnp.arange(centers.shape[0]))


@partial(jax.jit, static_argnames=("max_iter",))
def _median_fit(xb: jax.Array, w: jax.Array, centers: jax.Array, max_iter: int, tol):
    """Whole fit loop on-device (see kmeans._lloyd_fit for the rationale)."""
    valid = w > 0

    def cond(carry):
        _, it, shift = carry
        return jnp.logical_and(it < max_iter, shift > tol)

    def body(carry):
        c, it, _ = carry
        d1 = _d1(xb, c)
        labels = jnp.argmin(d1, axis=1)
        new_c, _ = _median_update(xb, labels, valid, c)
        shift = jnp.sum((new_c - c) ** 2)
        return new_c, it + 1, shift

    centers, n_iter, _ = jax.lax.while_loop(
        cond, body, (centers, jnp.int32(0), jnp.asarray(jnp.inf, xb.dtype))
    )
    d1 = _d1(xb, centers)
    labels = jnp.argmin(d1, axis=1)
    inertia = jnp.sum(jnp.min(d1, axis=1) * w)
    return centers, labels, inertia, n_iter


class KMedians(_KCluster):
    """K-Medians clusterer (reference kmedians.py:10)."""

    def __init__(
        self,
        n_clusters: int = 8,
        init: Union[str, DNDarray] = "random",
        max_iter: int = 300,
        tol: float = 1e-4,
        random_state: Optional[int] = None,
    ):
        super().__init__("manhattan", n_clusters, init, max_iter, tol, random_state)

    def fit(self, x: DNDarray) -> "KMedians":
        """Median-update Lloyd iterations (reference kmedians.py `fit`)."""
        if not isinstance(x, DNDarray):
            raise TypeError(f"input needs to be a DNDarray, but was {type(x)}")
        if x.ndim != 2:
            raise ValueError("input needs to be 2D")
        dt, xb, w, centers = self._fit_buffers(x)

        centers, labels, inertia, n_iter = _median_fit(
            xb, w, centers, self.max_iter, jnp.asarray(self.tol, xb.dtype)
        )

        self._cluster_centers = DNDarray.from_logical(centers, None, x.device, x.comm, dt)
        self._labels = DNDarray(
            labels.astype(jnp.int64), (x.shape[0],), types.int64, x.split, x.device, x.comm, True
        )
        self._inertia = float(inertia)
        self._n_iter = int(n_iter)
        return self
