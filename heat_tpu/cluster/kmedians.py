"""K-Medians clustering (reference: heat/cluster/kmedians.py:10-137 — same
Lloyd skeleton as KMeans with a per-dimension median update)."""

from __future__ import annotations

from functools import partial
from typing import Optional, Union

import jax
import jax.numpy as jnp

from ..core import types
from ..core.dndarray import DNDarray
from ._kcluster import _KCluster, _d2

__all__ = ["KMedians"]


@partial(jax.jit, static_argnums=(3,))
def _median_step(xb: jax.Array, w: jax.Array, centers: jax.Array, k: int):
    d2 = _d2(xb, centers)
    labels = jnp.argmin(d2, axis=1)
    valid = w > 0

    def upd(c):
        member = (labels == c) & valid
        masked = jnp.where(member[:, None], xb, jnp.nan)
        med = jnp.nanmedian(masked, axis=0)
        return jnp.where(jnp.any(member), med, centers[c])

    new_centers = jax.vmap(upd)(jnp.arange(k))
    inertia = jnp.sum(jnp.sqrt(jnp.min(d2, axis=1)) * w)
    shift = jnp.sum((new_centers - centers) ** 2)
    return new_centers, labels, inertia, shift


class KMedians(_KCluster):
    """K-Medians clusterer (reference kmedians.py:10)."""

    def __init__(
        self,
        n_clusters: int = 8,
        init: Union[str, DNDarray] = "random",
        max_iter: int = 300,
        tol: float = 1e-4,
        random_state: Optional[int] = None,
    ):
        super().__init__("manhattan", n_clusters, init, max_iter, tol, random_state)

    def fit(self, x: DNDarray) -> "KMedians":
        """Median-update Lloyd iterations (reference kmedians.py `fit`)."""
        if not isinstance(x, DNDarray):
            raise TypeError(f"input needs to be a DNDarray, but was {type(x)}")
        if x.ndim != 2:
            raise ValueError("input needs to be 2D")
        dt, xb, w, centers = self._fit_buffers(x)

        labels, inertia, n_iter = None, None, 0
        for it in range(self.max_iter):
            centers, labels, inertia, shift = _median_step(xb, w, centers, self.n_clusters)
            n_iter = it + 1
            if float(shift) <= self.tol:
                break

        self._cluster_centers = DNDarray.from_logical(centers, None, x.device, x.comm, dt)
        self._labels = DNDarray(
            labels.astype(jnp.int64), (x.shape[0],), types.int64, x.split, x.device, x.comm, True
        )
        self._inertia = float(inertia)
        self._n_iter = n_iter
        return self
