"""Distributed request tracing (ISSUE 17 tentpole).

One request through the fleet is many timelines: it queues in the
router, rides the JSON wire, queues again in a replica, coalesces into a
micro-batch, gets padded, executes as one ``cached_program`` dispatch,
and is sliced back out. This module mints the **trace context** that
stitches those hops into one story:

* :func:`mint` creates a ``TraceContext`` at the ingress (``Router.submit``
  for networked serving, ``Server.submit`` for in-process serving) — a
  process-unique ``trace_id`` plus the name of the minting hop as
  ``parent_span``. The sampling decision (``HEAT_TPU_TRACE_SAMPLE``) is
  made **once, at ingress**, deterministically from the trace id, and
  travels with the context — a request is traced at every hop or at
  none, never half.
* The context rides the request envelope as a version-tolerant ``trace``
  field (:func:`heat_tpu.serve.net.wire.encode_request`): old replicas
  ignore the unknown key, old routers simply never send it, and either
  way the payload bytes — and therefore the answers — are bit-identical.
* :func:`hop` stamps each hop as a ``trace_span`` telemetry event
  (wall-clock ``start_ts`` + ``seconds``, ``trace_id``/``parent``
  fields) that :mod:`heat_tpu.telemetry.trace` renders on a dedicated
  *requests* track and :func:`heat_tpu.telemetry.cluster.export_merged_trace`
  joins across processes into ONE Perfetto timeline.

Cost contract: tracing only records while telemetry records, so with
telemetry off every call site is the usual single flag check; with
telemetry on but ``HEAT_TPU_TRACE_REQUESTS=0`` the ingress check is one
knob read and no per-hop work happens. Tracing never touches payloads —
answers are bit-identical on and off (pinned by the CI cluster gate).

Counter pairing (the PR 5/11/12 reconciliation discipline): every
``trace_span`` event increments ``tracing.spans``, and every sampled
ingress mint increments ``tracing.sampled`` alongside a span carrying
``ingress=True`` — a live ``report.summarize()`` (counters) and an
offline sink replay reconstruct the same tallies.
"""

from __future__ import annotations

import itertools
import os
import zlib
from typing import Any, List, Optional, Sequence

from heat_tpu import _knobs as knobs

from .. import telemetry

__all__ = ["TraceContext", "active", "sample_rate", "mint", "from_wire",
           "hop", "HOPS"]

# the canonical hop-span names, in request order (docs/OBSERVABILITY.md;
# the CI gate asserts a sampled routed request produced every one)
HOPS = (
    "router.queue",    # router ingress -> worker picked the job up
    "router.post",     # HTTP round trip to the chosen replica
    "serve.queue",     # replica ingress -> batcher started its batch
    "serve.coalesce",  # micro-batch assembly (concat across requests)
    "serve.pad",       # pad-to-ladder-bucket host work
    "serve.execute",   # cached_program dispatch + result materialization
    "serve.reply",     # slicing results back + resolving futures
)

_COUNTER = itertools.count()


class TraceContext:
    """One request's trace identity: the fleet-unique ``trace_id``, the
    minting hop's name as ``parent_span``, and the ingress sampling
    verdict (an unsampled request never constructs one)."""

    __slots__ = ("trace_id", "parent_span")

    def __init__(self, trace_id: str, parent_span: str):
        self.trace_id = trace_id
        self.parent_span = parent_span

    def to_wire(self) -> dict:
        """The version-tolerant ``trace`` field of the request JSON."""
        return {"id": self.trace_id, "parent": self.parent_span,
                "sampled": True}

    def __repr__(self) -> str:  # pragma: no cover — debugging aid
        return f"TraceContext({self.trace_id!r}, parent={self.parent_span!r})"


def active() -> bool:
    """Whether request tracing records: telemetry must be on (the single
    hot-path flag) AND ``HEAT_TPU_TRACE_REQUESTS`` not opted out."""
    return telemetry.enabled() and bool(knobs.get("HEAT_TPU_TRACE_REQUESTS"))


def sample_rate() -> float:
    """``HEAT_TPU_TRACE_SAMPLE`` clamped to [0, 1]."""
    try:
        rate = float(knobs.get("HEAT_TPU_TRACE_SAMPLE"))
    except (TypeError, ValueError):
        return 1.0
    return min(1.0, max(0.0, rate))


def _sampled(trace_id: str, rate: float) -> bool:
    # deterministic per trace id (the faults-style stable draw): the
    # verdict is reproducible and independent of which thread minted it
    if rate >= 1.0:
        return True
    if rate <= 0.0:
        return False
    return (zlib.crc32(trace_id.encode("ascii")) % 1_000_000) < rate * 1e6


def mint(origin: str) -> Optional[TraceContext]:
    """Mint a context at an ingress hop, or ``None`` when tracing is off
    or the ingress sampling draw said no. Increments ``tracing.sampled``
    for every minted (= sampled) context."""
    if not active():
        return None
    trace_id = f"{os.getpid():08x}{next(_COUNTER) & 0xFFFFFFFF:08x}"
    if not _sampled(trace_id, sample_rate()):
        return None
    telemetry.get_registry().add("tracing.sampled", 1)
    return TraceContext(trace_id, origin)


def from_wire(obj: Any) -> Optional[TraceContext]:
    """Adopt a wire ``trace`` field minted by an upstream ingress, or
    ``None`` (absent field / malformed / local tracing opted out — the
    local ``HEAT_TPU_TRACE_REQUESTS=0`` flag wins even when the router
    sampled the request)."""
    if not isinstance(obj, dict) or not obj.get("sampled"):
        return None
    if not active():
        return None
    trace_id = obj.get("id")
    if not isinstance(trace_id, str) or not trace_id:
        return None
    parent = obj.get("parent")
    return TraceContext(
        trace_id, parent if isinstance(parent, str) else "remote",
    )


def hop(
    name: str,
    ctxs: Sequence[TraceContext],
    start_ts: float,
    seconds: float,
    *,
    ingress: bool = False,
    **fields: Any,
) -> None:
    """Stamp one hop span onto the telemetry stream. ``ctxs`` is every
    sampled context the hop served — per-request hops pass one, batch
    hops pass all of the batch's sampled contexts (the span then carries
    ``trace_id`` of the first plus the full ``trace_ids`` list, so a
    per-trace reader finds its batch hops by membership)."""
    ctxs = [c for c in ctxs if c is not None]
    if not ctxs:
        return
    reg = telemetry.get_registry()
    reg.add("tracing.spans", 1)
    primary = ctxs[0]
    if len(ctxs) > 1:
        fields["trace_ids"] = [c.trace_id for c in ctxs]
    if ingress:
        fields["ingress"] = True
    reg.emit(
        "trace_span", name,
        seconds=float(seconds), start_ts=float(start_ts),
        trace_id=primary.trace_id, parent=primary.parent_span,
        **fields,
    )


def span_trace_ids(ev: dict) -> List[str]:
    """Every trace id a ``trace_span`` event carries (the single
    ``trace_id`` plus the batch ``trace_ids`` list) — the membership
    helper trace checkers use."""
    out = []
    tid = ev.get("trace_id")
    if tid:
        out.append(tid)
    for t in ev.get("trace_ids") or ():
        if t not in out:
            out.append(t)
    return out
