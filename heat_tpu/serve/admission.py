"""Admission control: shed before OOM, degrade before shed (ISSUE 8).

The serving analog of the resilience memory guard's degradation ladder
(:mod:`heat_tpu.resilience.memory_guard`): every ``Server.submit`` runs
:meth:`AdmissionController.admit` *before* the request enters the queue,
so overload surfaces as an immediate 503-style
:class:`ServerOverloadedError` at the front door — never as an OOM (or an
unbounded queue) behind it.

Two gates:

* **queue depth** — ``HEAT_TPU_SERVE_QUEUE_MAX`` (default 1024) pending
  requests; past it every submit sheds with ``reason="queue_full"``.
  Open-loop arrival cannot be back-pressured, so a bounded queue is the
  only thing standing between a rate spike and unbounded memory.
* **memory budget** — with ``HEAT_TPU_HBM_BUDGET`` armed, the projected
  cost of dispatching this request at the current ladder bucket
  (*measured* ``memory_analysis`` bytes for warmed buckets via
  :func:`memory_guard.program_bytes`, the endpoint's analytic estimate
  otherwise) is checked against the live-bytes headroom. On projected
  overflow the controller first **degrades**: the batch-size ladder cap
  halves until a bucket fits (smaller programs, smaller temporaries —
  same arithmetic as the relayout planner's bounded-memory
  decomposition), and only when even a 1-row bucket cannot fit does the
  request shed with ``reason="memory"``. Comfortable headroom (<50% of
  budget) releases the cap, mirroring ``memory_guard.preflight``.

Costs derive from per-request byte arithmetic, not wall-clock guesses —
the same budget model the memory-efficient-redistribution planner uses
(PAPERS.md, arXiv:2112.01075).
"""

from __future__ import annotations

import threading
import time
from typing import Callable, List, Optional

from heat_tpu import _knobs as knobs

from .. import telemetry
from ..resilience import memory_guard

__all__ = [
    "ServeError",
    "ServerOverloadedError",
    "ServerClosedError",
    "AdmissionController",
]

DEFAULT_QUEUE_MAX = 1024


class ServeError(RuntimeError):
    """Base class for serving-front-end errors."""


class ServerOverloadedError(ServeError):
    """Request shed by admission control (HTTP-503 analog). Carries
    ``status`` (always 503), ``reason`` (``"queue_full"`` | ``"memory"``
    | ``"draining"``) and ``endpoint``."""

    status = 503

    def __init__(self, message: str, *, reason: str, endpoint: str):
        self.reason = reason
        self.endpoint = endpoint
        super().__init__(message)


class ServerClosedError(ServeError):
    """Submit after close, or the server shut down with the request
    pending."""


def _env_int(name: str, default: int) -> int:
    raw = (knobs.raw(name, "") or "").strip()
    if raw:
        try:
            v = int(raw)
            if v > 0:
                return v
        except ValueError:
            pass
    return default


class AdmissionController:
    """Front-door gate + batch-ladder degradation state for one server.

    ``measured_cost`` maps ``(endpoint_name, bucket) -> bytes`` from the
    server's warm-up measurements; buckets never warmed fall back to the
    endpoint's analytic :meth:`~.endpoints.Endpoint.cost_bytes`.
    """

    def __init__(
        self,
        queue_max: Optional[int] = None,
        *,
        measured_cost: Optional[Callable[[str, int], Optional[int]]] = None,
        live_ttl: float = 0.010,
    ):
        self.queue_max = (
            queue_max if queue_max is not None
            else _env_int("HEAT_TPU_SERVE_QUEUE_MAX", DEFAULT_QUEUE_MAX)
        )
        self._measured_cost = measured_cost
        self._lock = threading.Lock()
        self._cap: Optional[int] = None  # degraded ladder cap (None = full)
        # the live-bytes walk (jax.live_arrays + per-buffer dedup) is the
        # expensive half of headroom(); at serving rates many submits land
        # inside one batch window, so the (budget, live) reading is
        # memoized for ``live_ttl`` seconds — admission is a projection,
        # not an exact allocator, and the projected-cost term dominates
        # whatever drift a 10 ms-stale live figure introduces. 0 disables.
        self.live_ttl = live_ttl
        self._headroom_cached = (None, 0)
        self._headroom_ts = float("-inf")
        self.sheds = 0
        self.degrades = 0

    # -- ladder state --------------------------------------------------------

    def bucket_cap(self, ladder: List[int]) -> int:
        """The largest ladder bucket currently allowed (degradation
        clamps it)."""
        cap = self._cap
        top = ladder[-1]
        return top if cap is None else min(cap, top)

    def _degrade_to(self, cap: int, endpoint: str) -> None:
        with self._lock:
            if self._cap is not None and self._cap <= cap:
                return
            self._cap = cap
            self.degrades += 1
        if telemetry.enabled():
            reg = telemetry.get_registry()
            reg.add("serve.degraded", 1)
            reg.emit("serve", endpoint, event="degrade", bucket_cap=cap)

    def _release(self) -> None:
        with self._lock:
            if self._cap is None:
                return
            self._cap = None
        if telemetry.enabled():
            reg = telemetry.get_registry()
            reg.emit("serve", "ladder", event="degrade_release")

    def shed(self, endpoint: str, reason: str, message: str) -> None:
        """Count + emit one shed and raise the 503-style error. Public:
        the server routes its own shed reasons (``"draining"``, ISSUE 12)
        through here so every shed carries identical telemetry."""
        with self._lock:
            self.sheds += 1
        if telemetry.enabled():
            reg = telemetry.get_registry()
            reg.add("serve.shed", 1)
            reg.emit("serve", endpoint, event="shed", reason=reason)
        raise ServerOverloadedError(message, reason=reason, endpoint=endpoint)

    # -- the gate ------------------------------------------------------------

    def _headroom(self):
        """``memory_guard.headroom()`` memoized for ``live_ttl`` seconds
        (see __init__ — the live walk is the per-submit hot cost)."""
        if self.live_ttl <= 0:
            return memory_guard.headroom()
        now = time.monotonic()
        with self._lock:
            if now - self._headroom_ts <= self.live_ttl:
                return self._headroom_cached
        reading = memory_guard.headroom()
        with self._lock:
            self._headroom_cached = reading
            self._headroom_ts = now
        return reading

    def _cost(self, name: str, ep, bucket: int) -> int:
        if self._measured_cost is not None:
            m = self._measured_cost(name, bucket)
            if m:
                return m
        return ep.cost_bytes(bucket)

    def admit(
        self, name: str, ep, rows: int, queue_depth: int, ladder: List[int]
    ) -> None:
        """Raise :class:`ServerOverloadedError` or return (admitted).
        Degradation is a side effect: the ladder cap the batcher reads
        may shrink (or recover) here."""
        if queue_depth >= self.queue_max:
            self.shed(
                name, "queue_full",
                f"serve queue is full ({queue_depth} >= "
                f"{self.queue_max} pending requests); retry later or raise "
                f"HEAT_TPU_SERVE_QUEUE_MAX",
            )
        budget, live = self._headroom()
        if budget is None:
            return
        cap = self.bucket_cap(ladder)
        bucket = next((b for b in ladder if b >= min(rows, cap)), cap)
        need = self._cost(name, ep, bucket)
        if live + need <= budget:
            if self._cap is not None and live + need < budget // 2:
                self._release()
            return
        # degrade: walk the ladder down until a bucket's projected cost
        # fits — smaller batches, smaller temporaries, same answers
        for b in reversed([b for b in ladder if b < bucket]):
            if live + self._cost(name, ep, b) <= budget:
                self._degrade_to(b, name)
                return
        self.shed(
            name, "memory",
            f"projected dispatch cost {need:,} B on top of {live:,} B live "
            f"exceeds HEAT_TPU_HBM_BUDGET {budget:,} B even at the smallest "
            f"batch bucket; shedding before OOM",
        )
