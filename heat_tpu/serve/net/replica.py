"""Replica process: restore → warm → listen → drain on SIGTERM.

``python -m heat_tpu.serve.net.replica --checkpoint CKPT [--mesh N]
[--host H] [--port P]`` is the unit :class:`~.pool.ReplicaPool` spawns
N times. Lifecycle:

1. (optional) force an ``N``-device virtual CPU mesh *before* the
   backend initializes — the same dance as the bench harness ``--mesh``;
2. :meth:`heat_tpu.serve.Server.restore` the endpoint checkpoint (the
   crash-recovery path: a replica is rebuilt from the CRC-verified
   resilience checkpoint, never refit — restored answers are
   bit-identical);
3. ``warmup()`` the whole batch ladder. With the parent exporting a
   shared ``HEAT_TPU_COMPILE_CACHE`` dir this deserializes instead of
   compiling, and a shared ``HEAT_TPU_TUNE_DB`` warm-starts the knob
   overlay with zero measured trials (PR 3 / PR 11 — "a second process
   starts compiled *and* tuned", now load-bearing for horizontal
   scale);
4. start the :class:`~.transport.HttpFront` (which arms the
   steady-state CompileWatcher ``/stats`` exposes) and print ONE
   machine-readable **ready line** on stdout::

       {"ready": true, "port": <bound>, "pid": ..., "warmup": {...}}

5. block until **SIGTERM/SIGINT**, then shut down gracefully: shed new
   requests 503/``draining`` (the router retries siblings), finish
   every queued + in-flight batch, ``telemetry.flush()`` (the final
   counter/watermark snapshot reaches the sink — a killed in-process
   server used to drop it), and ``exit 0``. The pool's drain-then-kill
   removal is exactly one SIGTERM + wait.
"""

from __future__ import annotations

import argparse
import json
import os
import signal
import sys
import threading

__all__ = ["main"]


def _build_parser() -> argparse.ArgumentParser:
    p = argparse.ArgumentParser(
        prog="python -m heat_tpu.serve.net.replica",
        description="One serving replica: restore a serve checkpoint, warm "
                    "the ladder, serve HTTP until SIGTERM (docs/SERVING.md).",
    )
    p.add_argument("--checkpoint", required=True,
                   help="serve checkpoint directory (Server.save) holding "
                        "the endpoint set this replica serves")
    p.add_argument("--host", default="127.0.0.1")
    p.add_argument("--port", type=int, default=None,
                   help="listen port (default: HEAT_TPU_SERVE_NET_PORT; "
                        "0 binds an ephemeral port, printed in the ready "
                        "line)")
    p.add_argument("--mesh", type=int, default=0,
                   help="force an n-device virtual CPU mesh before backend "
                        "init (0 = use the attached platform as-is)")
    p.add_argument("--request-timeout", type=float, default=30.0,
                   help="per-request future wait before HTTP 504")
    p.add_argument("--drain-timeout", type=float, default=30.0,
                   help="max seconds the SIGTERM drain waits for queued + "
                        "in-flight work before closing anyway")
    return p


def main(argv=None) -> int:
    args = _build_parser().parse_args(argv)
    if args.mesh:
        from heat_tpu.utils.backend_probe import force_virtual_cpu_mesh

        force_virtual_cpu_mesh(args.mesh)
    # imported here, after the mesh decision — backend init is lazy, and
    # restore() below is the first device touch
    from heat_tpu import telemetry
    from heat_tpu.serve import Server

    from .transport import HttpFront

    server = Server.restore(args.checkpoint)
    warm = server.warmup()
    front = HttpFront(
        server, host=args.host, port=args.port,
        request_timeout=args.request_timeout,
    )
    front.warmup_report = warm
    front.start()

    stop = threading.Event()

    def _on_signal(signum, frame):  # noqa: ARG001 — signal contract
        stop.set()

    signal.signal(signal.SIGTERM, _on_signal)
    signal.signal(signal.SIGINT, _on_signal)
    from heat_tpu.serve import tracing

    print(json.dumps({
        "ready": True,
        "url": front.url,
        "port": front.port,
        "pid": os.getpid(),
        "endpoints": sorted(server.endpoints()),
        "warmup": warm,
        # observability posture (ISSUE 17): whether this replica records
        # adopted trace contexts — the pool/CI can verify a fleet's
        # tracing configuration from the ready lines alone
        "tracing": tracing.active(),
    }), flush=True)

    stop.wait()
    # graceful shutdown (ISSUE 12 satellite): drain the queue, flush the
    # final telemetry snapshot, exit 0 — nothing in flight is dropped,
    # and the sink carries the replica's last counters/watermarks
    drained = front.drain(args.drain_timeout)
    telemetry.flush("sigterm_drain")
    print(json.dumps({"exit": True, "drained": drained,
                      "pid": os.getpid()}), flush=True)
    return 0


if __name__ == "__main__":
    sys.exit(main())
