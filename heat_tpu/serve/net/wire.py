"""Wire schema of the network serving tier (ISSUE 12).

One request/response format shared by the HTTP transport
(:mod:`.transport`), the router client (:mod:`.router`), and the
multi-process load generator: a JSON envelope whose array payload rides
as the **base64 of the array's ``.npy`` serialization**. The ``.npy``
container is self-describing (dtype + shape + C-order bytes) and
round-trips bitwise, so the exact-mode serving contract survives the
network hop: a request answered through the router is *bit-identical* to
the same request dispatched against an in-process
:class:`~heat_tpu.serve.Server` — the property the CI serving-net gate's
router-vs-direct digest comparison pins.

Request body (``POST /v1/<endpoint>``)::

    {"payload": "<base64(npy bytes)>"}

With request tracing on (ISSUE 17), either envelope additionally carries
a version-tolerant ``trace`` field minted at the router ingress::

    {"payload": "...", "trace": {"id": "<hex>", "parent": "<span name>",
                                 "sampled": true}}

``trace`` follows the same compatibility discipline as the response
``version`` field: :func:`decode_request` only requires the payload key
and ignores everything else, so pre-17 replicas serve traced requests
unchanged and pre-17 routers simply never send the field. The payload
bytes are untouched either way — answers stay bit-identical with tracing
on or off.

Sparse request body (ISSUE 13 — ragged CSR rows for ``sparse_query``
endpoints, :class:`heat_tpu.sparse.host.CsrRows`)::

    {"payload_csr": {"indptr": "<b64 npy>", "indices": "<b64 npy>",
                     "values": "<b64 npy>", "cols": <int>}}

Success response (HTTP 200)::

    {"ok": true, "result": "<base64(npy bytes)>"}

Error response (HTTP 4xx/5xx)::

    {"ok": false, "error": "<message>", "reason": "<machine tag>"}

``reason`` carries the admission-control taxonomy across the wire
(``queue_full`` | ``memory`` | ``draining`` | ``closed`` | ...), so the
router's sticky-degradation logic can distinguish a shed worth retrying
on a sibling from a caller bug worth surfacing.

Object-dtype arrays never serialize (``allow_pickle=False`` on both
directions — a replica must not unpickle attacker-controlled bytes), and
malformed envelopes raise :class:`WireError` rather than leaking numpy
internals to the transport layer.
"""

from __future__ import annotations

import base64
import io
import json
from typing import Tuple

import numpy as np

__all__ = [
    "WireError",
    "encode_array",
    "decode_array",
    "encode_request",
    "decode_request",
    "decode_request_ex",
    "encode_response",
    "encode_error",
    "decode_response",
    "decode_response_version",
]


class WireError(ValueError):
    """Malformed wire envelope or payload (maps to HTTP 400)."""


def encode_array(arr: np.ndarray) -> str:
    """``base64(npy bytes)`` of ``arr`` — dtype/shape self-describing,
    bitwise round-trip (:func:`decode_array` is the inverse)."""
    arr = np.ascontiguousarray(arr)
    if arr.dtype.hasobject:
        raise WireError("object-dtype arrays cannot travel on the wire")
    buf = io.BytesIO()
    np.save(buf, arr, allow_pickle=False)
    return base64.b64encode(buf.getvalue()).decode("ascii")


def decode_array(data: str) -> np.ndarray:
    """Inverse of :func:`encode_array`; raises :class:`WireError` on
    garbage instead of leaking codec internals."""
    if not isinstance(data, str):
        raise WireError(f"payload must be a base64 string, got {type(data)}")
    try:
        raw = base64.b64decode(data.encode("ascii"), validate=True)
    except Exception as e:
        raise WireError(f"payload is not valid base64: {e}") from None
    try:
        return np.load(io.BytesIO(raw), allow_pickle=False)
    except Exception as e:
        raise WireError(f"payload is not a valid .npy blob: {e}") from None


def encode_request(payload, trace=None) -> bytes:
    """The JSON body of ``POST /v1/<endpoint>``. Dense payloads ride the
    ``payload`` envelope; :class:`~heat_tpu.sparse.host.CsrRows` batches
    ride ``payload_csr`` — three self-describing ``.npy`` blobs plus the
    feature width, bitwise round-trip like the dense form. ``trace`` is
    the optional ISSUE-17 trace-context dict (version-tolerant: absent
    when tracing is off or the request is unsampled)."""
    from ...sparse.host import CsrRows

    if isinstance(payload, CsrRows):
        obj = {
            "payload_csr": {
                "indptr": encode_array(payload.indptr),
                "indices": encode_array(payload.indices),
                "values": encode_array(payload.values),
                "cols": int(payload.cols),
            }
        }
    else:
        obj = {"payload": encode_array(payload)}
    if trace is not None:
        obj["trace"] = trace
    return json.dumps(obj).encode("utf-8")


def decode_request(body: bytes):
    """Parse a request body into the payload — a dense array, or a
    :class:`~heat_tpu.sparse.host.CsrRows` batch for the sparse
    envelope (server side; ``Server.submit`` accepts both). Any
    ``trace`` field is ignored here — transports that propagate tracing
    use :func:`decode_request_ex`."""
    return decode_request_ex(body)[0]


def decode_request_ex(body: bytes):
    """Parse a request body → ``(payload, trace_or_None)`` where
    ``trace`` is the raw wire dict of the ISSUE-17 trace field (``None``
    when absent or malformed — a bad trace field must never fail a
    request, it only loses the trace)."""
    try:
        obj = json.loads(body.decode("utf-8"))
    except Exception as e:
        raise WireError(f"request body is not JSON: {e}") from None
    trace = obj.get("trace") if isinstance(obj, dict) else None
    if not isinstance(trace, dict):
        trace = None
    if isinstance(obj, dict) and "payload_csr" in obj:
        csr = obj["payload_csr"]
        if not isinstance(csr, dict) or not all(
            k in csr for k in ("indptr", "indices", "values", "cols")
        ):
            raise WireError(
                'payload_csr must carry {"indptr", "indices", "values", '
                '"cols"}'
            )
        from ...sparse.host import CsrRows

        try:
            return CsrRows(
                decode_array(csr["indptr"]),
                decode_array(csr["indices"]),
                decode_array(csr["values"]),
                int(csr["cols"]),
            ), trace
        except WireError:
            raise
        except Exception as e:
            raise WireError(f"malformed CSR payload: {e}") from None
    if not isinstance(obj, dict) or "payload" not in obj:
        raise WireError('request JSON must be {"payload": "<base64 npy>"}')
    return decode_array(obj["payload"]), trace


def encode_response(result: np.ndarray, version=None) -> bytes:
    """The JSON body of a 200 response. ``version`` (ISSUE 16) stamps
    the endpoint version that served the request into the envelope, so
    a client driving a rolling update can observe which replicas have
    cut over; absent for pre-16 peers (decoders default it to None)."""
    obj = {"ok": True, "result": encode_array(result)}
    if version is not None:
        obj["version"] = int(version)
    return json.dumps(obj).encode("utf-8")


def encode_error(message: str, reason: str) -> bytes:
    """The JSON body of an error response (``reason`` is the machine
    tag the router keys its retry policy on)."""
    return json.dumps(
        {"ok": False, "error": str(message), "reason": reason}
    ).encode("utf-8")


def decode_response(body: bytes) -> Tuple[bool, object, str]:
    """Parse a response body → ``(ok, result_or_message, reason)``:
    ``(True, ndarray, "")`` on success, ``(False, message, reason)`` on a
    structured error."""
    try:
        obj = json.loads(body.decode("utf-8"))
    except Exception as e:
        raise WireError(f"response body is not JSON: {e}") from None
    if not isinstance(obj, dict) or "ok" not in obj:
        raise WireError('response JSON must carry an "ok" field')
    if obj["ok"]:
        if "result" not in obj:
            raise WireError('ok response is missing "result"')
        return True, decode_array(obj["result"]), ""
    return False, str(obj.get("error", "")), str(obj.get("reason", ""))


def decode_response_version(body: bytes):
    """The endpoint version stamped into a 200 envelope, or ``None``
    (error responses, pre-16 peers). Used by the rolling-update driver
    to verify every in-rotation replica answers from one version."""
    try:
        obj = json.loads(body.decode("utf-8"))
    except Exception as e:
        raise WireError(f"response body is not JSON: {e}") from None
    v = obj.get("version") if isinstance(obj, dict) else None
    return int(v) if v is not None else None
