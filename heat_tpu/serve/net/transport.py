"""HTTP transport: one replica's network front (ISSUE 12 tentpole).

A deliberately thin adapter — stdlib ``http.server.ThreadingHTTPServer``
(zero new dependencies) translating wire requests (:mod:`.wire`) into
the existing :meth:`heat_tpu.serve.Server.submit` futures API. All the
hard serving problems stay where PR 8 solved them: the micro-batcher
coalesces *across* concurrent handler threads exactly as it does across
in-process submitters, admission control sheds before OOM, and the
cached-program registry keeps steady state at zero compiles. The
transport adds only sockets, the wire codec, and the three operational
endpoints a router needs:

* ``POST /v1/<endpoint>`` — decode payload, ``submit()``, wait the
  future, encode the result. Admission sheds map to **HTTP 503** with
  the machine ``reason`` (``queue_full`` | ``memory`` | ``draining``)
  in the body, which is what the router's sticky-degradation ladder
  keys on; malformed payloads are 400, a missing endpoint 404, a future
  timeout 504.
* ``GET /healthz`` — 200 while accepting, 503 while draining/closed
  (the router's eviction/re-add probe).
* ``GET /stats`` — :meth:`Server.stats` plus a ``net`` block: pid,
  bound port, draining flag, HTTP tallies, the warm-up report, and
  ``steady_backend_compiles`` — a :class:`telemetry.CompileWatcher`
  armed when the front starts (i.e. *after* warm-up), so the router and
  the CI gate can pin the zero-compile steady state of a warm-started
  replica remotely.
* ``GET /metrics`` — :meth:`Server.metrics` (ISSUE 17): cumulative
  tallies + RAW latency-histogram buckets, the mergeable scrape form the
  router's fleet aggregation consumes (docs/OBSERVABILITY.md schema).
* ``GET /trace`` — this process's in-memory telemetry events plus pid
  and a wall stamp, so a router can pull every replica's timeline
  in-band and merge them into one Perfetto trace without sharing a sink
  file across processes.

``/healthz`` additionally reports ``wall``/``mono`` clock stamps — the
round trip is the router's clock-sync probe (offset = remote wall − RTT
midpoint, uncertainty = RTT/2) that aligns per-process timelines in the
merged trace.

Graceful shutdown: :meth:`HttpFront.drain` sheds new work 503-style
(router retries siblings), lets queued + in-flight batches finish
(:meth:`Server.drain`), then stops the listener — the replica's SIGTERM
handler drives exactly this, then ``telemetry.flush()`` and ``exit 0``.
"""

from __future__ import annotations

import json
import os
import threading
import time
from concurrent.futures import TimeoutError as FutureTimeoutError
from http.server import BaseHTTPRequestHandler, ThreadingHTTPServer
from typing import Optional

import numpy as np

from heat_tpu import _knobs as knobs

from ... import telemetry
from .. import tracing
from ..admission import ServerClosedError, ServerOverloadedError
from . import wire
from .events import emit as _emit

__all__ = ["HttpFront"]


class _NetHTTPServer(ThreadingHTTPServer):
    daemon_threads = True
    allow_reuse_address = True
    # BaseHTTPRequestHandler writes status line / headers / body as
    # separate small sends; with Nagle on, the write-write-read pattern
    # stalls tens of ms per response on some kernels — measured 33 ms
    # round trips on loopback before this flag
    disable_nagle_algorithm = True
    front: "HttpFront"  # set by HttpFront.start


class _Handler(BaseHTTPRequestHandler):
    protocol_version = "HTTP/1.1"  # keep-alive: every response sets length

    # -- plumbing ------------------------------------------------------------

    def log_message(self, fmt, *args):  # noqa: D102 — silence per-request
        pass                            # stderr chatter (telemetry has it)

    def _send(self, code: int, body: bytes) -> None:
        self.send_response(code)
        self.send_header("Content-Type", "application/json")
        self.send_header("Content-Length", str(len(body)))
        self.end_headers()
        try:
            self.wfile.write(body)
        except (BrokenPipeError, ConnectionResetError):
            pass  # client went away mid-response; nothing to salvage

    def _send_error(self, code: int, message: str, reason: str) -> None:
        self.server.front._count(code)
        self._send(code, wire.encode_error(message, reason))

    # -- routes --------------------------------------------------------------

    def do_GET(self):  # noqa: N802 — BaseHTTPRequestHandler contract
        front = self.server.front
        if self.path == "/healthz":
            accepting = front.accepting()
            # wall/mono ride the health probe so the router's clock-sync
            # round trip needs no extra route (pre-17 clients ignore them)
            body = json.dumps(
                {"ok": accepting, "draining": front.draining,
                 "pid": front.pid, "wall": time.time(),
                 "mono": time.monotonic()}
            ).encode()
            self._send(200 if accepting else 503, body)
        elif self.path == "/stats":
            self._send(200, json.dumps(front.stats_payload()).encode())
        elif self.path == "/metrics":
            self._send(200, json.dumps(front.metrics_payload()).encode())
        elif self.path == "/trace":
            self._send(200, json.dumps(front.trace_payload()).encode())
        else:
            self._send_error(404, f"unknown path {self.path!r}", "not_found")

    def do_POST(self):  # noqa: N802
        front = self.server.front
        if not self.path.startswith("/v1/"):
            self._send_error(404, f"unknown path {self.path!r}", "not_found")
            return
        name = self.path[len("/v1/"):]
        endpoints = getattr(front.server, "endpoints", None)
        if endpoints is not None and name not in endpoints():
            # documented contract: a missing endpoint is 404 ("not
            # deployed on this replica"), distinct from 400 (bad payload)
            self._send_error(
                404, f"no endpoint {name!r} on this replica", "not_found"
            )
            return
        try:
            length = int(self.headers.get("Content-Length", 0))
            payload, trace = wire.decode_request_ex(self.rfile.read(length))
        except wire.WireError as e:
            self._send_error(400, str(e), "bad_request")
            return
        # adopt the ingress's trace verdict (None when the field is
        # absent: a pre-17 router sent this, and the replica must not
        # re-mint — sampling is decided once, at the ingress)
        ctx = tracing.from_wire(trace) if trace is not None else None
        try:
            # capture the version at submit: the server swaps endpoints
            # only between micro-batches, and a replica process mounts
            # exactly one checkpoint version for its whole life, so this
            # is the version that serves the request in a rolling deploy
            getv = getattr(front.server, "endpoint_version", None)
            version = getv(name) if getv is not None else None
            fut = front.server.submit(name, payload, trace=ctx)
            result = fut.result(front.request_timeout)
        except ServerOverloadedError as e:
            self._send_error(503, str(e), e.reason)
            return
        except ServerClosedError as e:
            self._send_error(503, str(e), "closed")
            return
        except FutureTimeoutError:
            self._send_error(
                504,
                f"endpoint {name!r} did not answer within "
                f"{front.request_timeout}s", "timeout",
            )
            return
        except ValueError as e:
            # unknown endpoint / wrong feature count — caller bug, 400
            self._send_error(400, str(e), "bad_request")
            return
        except Exception as e:  # noqa: BLE001 — a failed batch is data
            self._send_error(500, repr(e), "internal")
            return
        front._count(200)
        self._send(200, wire.encode_response(np.asarray(result), version=version))


class HttpFront:
    """One replica's HTTP listener over an existing
    :class:`heat_tpu.serve.Server` (module docstring has the routes).
    ``port`` 0 (default, knob ``HEAT_TPU_SERVE_NET_PORT``) binds an
    ephemeral port; read :attr:`port` / :attr:`url` after
    :meth:`start`."""

    def __init__(
        self,
        server,
        *,
        host: str = "127.0.0.1",
        port: Optional[int] = None,
        request_timeout: float = 30.0,
    ):
        self.server = server
        self.host = host
        self.port = int(
            port if port is not None else knobs.get("HEAT_TPU_SERVE_NET_PORT")
        )
        self.request_timeout = float(request_timeout)
        self.pid = os.getpid()
        self.warmup_report: Optional[dict] = None  # replica main fills this
        self._httpd: Optional[_NetHTTPServer] = None
        self._thread: Optional[threading.Thread] = None
        self._steady_cw: Optional[telemetry.CompileWatcher] = None
        self._lock = threading.Lock()
        self._http_by_code: dict = {}

    # -- lifecycle -----------------------------------------------------------

    def start(self) -> int:
        """Bind + serve in a daemon thread; returns the bound port. Arms
        the steady-state CompileWatcher — call *after* ``warmup()`` so
        every later backend compile is a steady-state violation."""
        if self._httpd is not None:
            return self.port
        self._httpd = _NetHTTPServer((self.host, self.port), _Handler)
        self._httpd.front = self
        self.port = self._httpd.server_address[1]
        # held open for the front's lifetime: backend_compiles read by
        # /stats is the remote zero-compile oracle
        self._steady_cw = telemetry.CompileWatcher()
        self._steady_cw.__enter__()
        self._thread = threading.Thread(
            target=self._httpd.serve_forever,
            kwargs={"poll_interval": 0.05},
            name="heat_tpu.serve.net.http", daemon=True,
        )
        self._thread.start()
        _emit("http", "listen", port=self.port, pid=self.pid)
        return self.port

    def stop(self, timeout: float = 5.0) -> None:
        """Stop the listener (does not touch the serve.Server)."""
        httpd, self._httpd = self._httpd, None
        if httpd is not None:
            httpd.shutdown()
            httpd.server_close()
        if self._thread is not None:
            self._thread.join(timeout)
            self._thread = None
        if self._steady_cw is not None:
            self._steady_cw.__exit__(None, None, None)

    def drain(self, timeout: float = 30.0) -> bool:
        """Graceful shutdown: shed new submits 503/``draining`` (the
        router retries siblings), finish queued + in-flight batches,
        then stop the listener. Returns ``Server.drain``'s verdict."""
        _emit("http", "drain", port=self.port, pid=self.pid)
        drained = self.server.drain(timeout)
        self.stop()
        return drained

    def __enter__(self) -> "HttpFront":
        self.start()
        return self

    def __exit__(self, *exc) -> bool:
        self.stop()
        return False

    # -- introspection -------------------------------------------------------

    @property
    def url(self) -> str:
        return f"http://{self.host}:{self.port}"

    @property
    def draining(self) -> bool:
        return bool(getattr(self.server, "draining", False))

    def accepting(self) -> bool:
        return (
            self._httpd is not None
            and not self.draining
            and not getattr(self.server, "_closed", False)
        )

    def _count(self, code: int) -> None:
        with self._lock:
            self._http_by_code[code] = self._http_by_code.get(code, 0) + 1

    def steady_backend_compiles(self) -> int:
        cw = self._steady_cw
        return cw.backend_compiles if cw is not None else 0

    def stats_payload(self) -> dict:
        """``GET /stats`` body: ``Server.stats()`` + the ``net`` block
        (docs/SERVING.md schema)."""
        with self._lock:
            by_code = dict(self._http_by_code)
        stats = self.server.stats()
        stats["net"] = {
            "pid": self.pid,
            "port": self.port,
            "draining": self.draining,
            "http_requests": sum(by_code.values()),
            "http_by_code": {str(k): v for k, v in by_code.items()},
            "steady_backend_compiles": self.steady_backend_compiles(),
            "warmup": self.warmup_report,
            "autotune_trials": _autotune_trials(),
        }
        return stats

    def metrics_payload(self) -> dict:
        """``GET /metrics`` body: :meth:`Server.metrics` (raw mergeable
        tallies) + the replica identity/clock block scrapers key on."""
        getm = getattr(self.server, "metrics", None)
        out = getm() if getm is not None else {"endpoints": {}}
        out["net"] = {
            "pid": self.pid,
            "port": self.port,
            "draining": self.draining,
            "steady_backend_compiles": self.steady_backend_compiles(),
            "wall": time.time(),
            "mono": time.monotonic(),
        }
        return out

    def trace_payload(self) -> dict:
        """``GET /trace`` body: this process's in-memory telemetry
        events (empty when telemetry is off), stamped with pid + wall so
        the merged-trace exporter can label and clock-align the track."""
        reg = telemetry.get_registry()
        with reg._lock:
            events = [dict(ev) for ev in reg.events]
        return {"pid": self.pid, "wall": time.time(), "events": events}


def _autotune_trials() -> Optional[int]:
    """Measured autotune trials this process ran — 0 when every site
    warm-started from the shared DB (the remote half of the PR 11 replay
    oracle, pinned by the cross-process warm-start test). The tuner
    counts trials through the telemetry registry, so this reads ``None``
    (unknown) while telemetry is disabled."""
    if not telemetry.enabled():
        return None
    # single dict lookup, not an items() scan: /stats runs on handler
    # threads while batcher threads mutate the counters dict
    return int(telemetry.get_registry().counters.get("autotune.trials", 0))
