"""heat_tpu.serve.net — horizontally scaled serving tier (ISSUE 12).

PR 8 built the serving engine as an in-process library; this package
puts a network in front of it and scales it out, using only the stdlib:

* :mod:`.wire` — the JSON + base64-``.npy`` wire schema (bitwise
  round-trip, so exact-mode answers survive the network hop);
* :mod:`.transport` — :class:`HttpFront`, a thin
  ``ThreadingHTTPServer`` adapter translating ``POST /v1/<endpoint>``
  into the existing ``Server.submit()`` futures API, plus ``/healthz``
  and ``/stats`` (stats carries the remote zero-compile oracle:
  ``steady_backend_compiles`` from a CompileWatcher armed post-warmup);
* :mod:`.replica` — the replica process
  (``python -m heat_tpu.serve.net.replica``): restore an endpoint
  checkpoint, warm from the SHARED persistent compile cache + tuning
  DB (replica 2..N reach zero-compile, pre-tuned steady state without
  retracing), serve until SIGTERM, then drain → ``telemetry.flush()``
  → exit 0;
* :mod:`.pool` — :class:`ReplicaPool`, spawning/scaling/draining/
  killing N replica processes over one checkpoint;
* :mod:`.router` — :class:`Router`, least-loaded dispatch from polled
  ``/stats``, sticky degradation (a 503 shed retries siblings before
  the client sees it), health-check eviction + re-add, and the same
  ``submit``/``predict``/``stats`` client surface as the in-process
  server (so one load generator drives both). ISSUE 20 adds
  priority-class weighted-fair admission and hedged tail-latency
  retries;
* :mod:`.controller` — :class:`AutoscaleController` (ISSUE 20), the
  SLO-driven control loop closing the sensors (PR 17) → actuators
  (PR 12 spawn/remove) gap: scale-up on ``slo_burn``/sustained
  backlog, drain-idle scale-down, chaos replacement of dead replicas,
  all bounded by min/max + cooldown hysteresis and deterministically
  testable via an injectable clock + scripted metrics.

docs/SERVING.md §"Network serving" has the architecture, wire schema,
routing policy, degradation ladder, and failure semantics;
``benchmarks/serving/net.py`` is the multi-process load generator
behind the committed replica-scaling artifact.
"""

from __future__ import annotations

from .controller import AutoscaleController
from .events import EVENT_COUNTER
from .pool import ReplicaHandle, ReplicaPool
from .router import ReplicaDownError, Router
from .transport import HttpFront
from .wire import WireError
from . import controller, events, pool, replica, router, transport, wire  # noqa: F401

__all__ = [
    "AutoscaleController",
    "HttpFront",
    "ReplicaPool",
    "ReplicaHandle",
    "Router",
    "ReplicaDownError",
    "WireError",
    "EVENT_COUNTER",
]
