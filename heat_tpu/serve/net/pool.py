"""Replica pool: spawn, warm, scale, drain, and kill replica processes.

:class:`ReplicaPool` turns one endpoint checkpoint into N serving
processes (ISSUE 12): each replica runs
``python -m heat_tpu.serve.net.replica`` against the SAME checkpoint,
and — when the parent exports them — the SAME persistent
``HEAT_TPU_COMPILE_CACHE`` and ``HEAT_TPU_TUNE_DB`` directories, so
replica 2..N reach the zero-compile, pre-tuned steady state without
retracing (the PR 3 / PR 11 "second process starts warm" property, now
the thing that makes horizontal scale-out cheap). The pool:

* **spawns** replicas as detached subprocesses, parses each one's ready
  line (bound ephemeral port, warm-up report), and tails stderr into a
  per-replica log file for post-mortems;
* **scales up** (:meth:`spawn`) — a new replica warms from the shared
  caches and can be handed to ``Router.add_target``;
* **removes gracefully** (:meth:`remove`) — drain-then-kill: one
  SIGTERM, the replica sheds new work 503-style (the router retries
  siblings), finishes its backlog, flushes telemetry, exits 0 — the
  pool asserts the exit code;
* **kills** (:meth:`kill`) — SIGKILL for chaos testing: only that
  replica's in-flight requests are lost, the router evicts it on the
  next connection failure;
* **restores** — because a replica is *born* from a checkpoint, crash
  recovery is just :meth:`spawn` again: the resilience checkpoint
  machinery guarantees the restored endpoint set answers
  bit-identically.

Per-replica admission budgets (queue bound, ladder top, HBM budget)
travel via the ``env`` mapping — each replica enforces its own bounded
queue/memory envelope, the per-process analog of the bounded-memory
decomposition discipline (arXiv:2112.01075) the relayout planner uses
in-process.
"""

from __future__ import annotations

import json
import os
import signal
import subprocess
import sys
import tempfile
import threading
import time
from typing import Dict, List, Optional

from heat_tpu import _knobs as knobs

from .events import emit as _emit

__all__ = ["ReplicaPool", "ReplicaHandle"]


class ReplicaHandle:
    """One spawned replica process: subprocess handle, bound address,
    ready-line payload, and the stderr log path."""

    def __init__(self, index: int, proc: subprocess.Popen, log_path: str):
        self.index = index
        self.proc = proc
        self.log_path = log_path
        self.port: Optional[int] = None
        self.url: Optional[str] = None
        self.ready: Optional[dict] = None
        self.state = "spawning"  # spawning | up | removed | killed | dead
        self._lines: List[str] = []
        self._reader = threading.Thread(
            target=self._read_stdout, daemon=True,
            name=f"heat_tpu.serve.net.pool-reader-{index}",
        )
        self._reader.start()

    def _read_stdout(self) -> None:
        for line in self.proc.stdout:
            self._lines.append(line)
        try:
            self.proc.stdout.close()
        except Exception:
            pass

    def wait_ready(self, timeout: float) -> dict:
        """Block until the replica's ready line (or death/timeout)."""
        deadline = time.monotonic() + timeout
        seen = 0
        while time.monotonic() < deadline:
            while seen < len(self._lines):
                line = self._lines[seen].strip()
                seen += 1
                if not line:
                    continue
                try:
                    obj = json.loads(line)
                except json.JSONDecodeError:
                    continue
                if obj.get("ready"):
                    self.ready = obj
                    self.port = int(obj["port"])
                    self.url = str(obj["url"])
                    self.state = "up"
                    return obj
            if self.proc.poll() is not None:
                raise RuntimeError(
                    f"replica {self.index} exited rc={self.proc.returncode} "
                    f"before its ready line; stderr tail:\n"
                    f"{self.log_tail()}"
                )
            time.sleep(0.02)
        raise TimeoutError(
            f"replica {self.index} produced no ready line within {timeout}s; "
            f"stderr tail:\n{self.log_tail()}"
        )

    def exit_lines(self) -> List[dict]:
        """Every JSON line the replica printed after ready (the graceful
        exit record lands here)."""
        out = []
        for line in list(self._lines):
            try:
                obj = json.loads(line.strip())
            except (json.JSONDecodeError, AttributeError):
                continue
            if not obj.get("ready"):
                out.append(obj)
        return out

    def log_tail(self, max_bytes: int = 4000) -> str:
        try:
            with open(self.log_path, "rb") as f:
                f.seek(0, os.SEEK_END)
                size = f.tell()
                f.seek(max(0, size - max_bytes))
                return f.read().decode("utf-8", "replace")
        except OSError:
            return "<no log>"

    def alive(self) -> bool:
        return self.proc.poll() is None


class ReplicaPool:
    """Spawn + manage ``replicas`` serving processes over one endpoint
    checkpoint (module docstring has the lifecycle)."""

    def __init__(
        self,
        checkpoint: str,
        replicas: Optional[int] = None,
        *,
        mesh: int = 0,
        host: str = "127.0.0.1",
        env: Optional[Dict[str, str]] = None,
        python: Optional[str] = None,
        ready_timeout: float = 240.0,
        log_dir: Optional[str] = None,
        replica_args: Optional[List[str]] = None,
    ):
        self.checkpoint = str(checkpoint)
        self.n = int(
            replicas if replicas is not None
            else knobs.get("HEAT_TPU_SERVE_NET_REPLICAS")
        )
        if self.n < 1:
            raise ValueError(f"need at least one replica, got {self.n}")
        self.mesh = int(mesh)
        self.host = host
        self.env_overrides = dict(env or {})
        self.python = python or sys.executable
        self.ready_timeout = float(ready_timeout)
        self.log_dir = log_dir or tempfile.mkdtemp(prefix="heat_tpu_pool_")
        os.makedirs(self.log_dir, exist_ok=True)
        self.replica_args = list(replica_args or [])
        self.replicas: List[ReplicaHandle] = []
        self.failed: List[ReplicaHandle] = []   # warmup-dead, reaped (ISSUE 20)
        self._next_index = 0
        self._sleep = time.sleep                # injectable (spawn-retry backoff)

    # -- lifecycle -----------------------------------------------------------

    def start(self) -> "ReplicaPool":
        """Spawn all replicas CONCURRENTLY, then wait for every ready
        line (imports + warm-up overlap across processes; the shared
        compile cache is multi-process safe). A replica that dies
        before ready is reaped (never left a zombie target) before the
        error propagates."""
        handles = [self._spawn_one() for _ in range(self.n)]
        first_error = None
        for h in handles:
            try:
                h.wait_ready(self.ready_timeout)
            except Exception as e:  # noqa: BLE001 — reap, then re-raise
                self._reap(h, why=repr(e))
                if first_error is None:
                    first_error = e
        if first_error is not None:
            raise first_error
        return self

    def spawn(
        self,
        checkpoint: Optional[str] = None,
        *,
        retries: Optional[int] = None,
        backoff_s: float = 0.5,
    ) -> ReplicaHandle:
        """Add ONE replica (scale-up / re-add after a kill); blocks
        until its ready line. ``checkpoint`` (ISSUE 16) births the
        replica from a *different* checkpoint than the pool default —
        the rolling-update primitive: a replica process serves exactly
        one checkpoint version for its whole life, so replacing
        replicas one by one rolls a new version through the pool with
        no process ever serving a half-updated endpoint set.

        Failure path (ISSUE 20): a replica that dies (or hangs) during
        warmup is **reaped** — killed, marked dead, dropped from the
        live set, ``spawn_fail`` evented — and the spawn retried with
        exponential backoff up to ``retries`` extra attempts (default
        ``HEAT_TPU_AUTOSCALE_SPAWN_RETRIES``). It is never left as a
        zombie target a router keeps scoring."""
        attempts = 1 + int(
            retries if retries is not None
            else knobs.get("HEAT_TPU_AUTOSCALE_SPAWN_RETRIES")
        )
        delay = float(backoff_s)
        last: Optional[Exception] = None
        for i in range(max(1, attempts)):
            h = self._spawn_one(checkpoint=checkpoint)
            try:
                h.wait_ready(self.ready_timeout)
                return h
            except Exception as e:  # noqa: BLE001 — reap + retry
                last = e
                self._reap(h, why=repr(e))
                if i + 1 < attempts:
                    self._sleep(delay)
                    delay *= 2
        raise RuntimeError(
            f"replica spawn failed {attempts} time(s) "
            f"(reaped each attempt; last log at "
            f"{self.failed[-1].log_path if self.failed else '<none>'})"
        ) from last

    def _reap(self, h: ReplicaHandle, why: str = "") -> None:
        """Remove a warmup-dead replica from the live set: kill the
        process if anything is left of it, mark the handle dead, move
        it to ``self.failed`` (log kept for post-mortems), and emit
        ``spawn_fail``. After this the handle can never appear in
        :meth:`urls` — no zombie targets."""
        try:
            if h.alive():
                h.proc.kill()
                h.proc.wait(10.0)
        except Exception:
            pass
        h.state = "dead"
        try:
            self.replicas.remove(h)
        except ValueError:
            pass
        self.failed.append(h)
        _emit("pool", "spawn_fail", replica=h.index,
              rc=h.proc.returncode, why=why[:200])

    def set_checkpoint(self, checkpoint: str) -> None:
        """Re-point the pool default checkpoint (future spawns,
        including crash-recovery respawns, pick up the new version)."""
        self.checkpoint = str(checkpoint)

    def _spawn_one(self, checkpoint: Optional[str] = None) -> ReplicaHandle:
        index = self._next_index
        self._next_index += 1
        cmd = [
            self.python, "-m", "heat_tpu.serve.net.replica",
            "--checkpoint", str(checkpoint or self.checkpoint),
            "--host", self.host, "--port", "0",
        ]
        if self.mesh:
            cmd += ["--mesh", str(self.mesh)]
        cmd += self.replica_args
        env = dict(os.environ)
        env.update(self.env_overrides)
        log_path = os.path.join(self.log_dir, f"replica_{index}.log")
        logf = open(log_path, "wb")
        try:
            proc = subprocess.Popen(
                cmd, stdout=subprocess.PIPE, stderr=logf, env=env,
                text=True,
            )
        finally:
            logf.close()  # the child holds its own descriptor
        h = ReplicaHandle(index, proc, log_path)
        self.replicas.append(h)
        _emit("pool", "spawn", replica=index, pid=proc.pid)
        return h

    def urls(self) -> List[str]:
        """Base URLs of every live replica (Router's target list)."""
        return [
            h.url for h in self.replicas
            if h.state == "up" and h.url and h.alive()
        ]

    def handle(self, index: int) -> ReplicaHandle:
        for h in self.replicas:
            if h.index == index:
                return h
        raise KeyError(f"no replica with index {index}")

    # -- management ----------------------------------------------------------

    def stats(self, index: int, timeout: float = 5.0) -> dict:
        """``GET /stats`` from one replica."""
        return self._get_json(index, "/stats", timeout)

    def metrics(self, index: int, timeout: float = 5.0) -> dict:
        """``GET /metrics`` from one replica (ISSUE 17): the cumulative
        mergeable scrape — raw latency-histogram buckets, version map,
        tracing counters — the same payload the router's fleet
        aggregation consumes."""
        return self._get_json(index, "/metrics", timeout)

    def scrape_metrics(self, timeout: float = 5.0) -> Dict[str, dict]:
        """``{url: metrics payload}`` across every live replica — a
        routerless pool feeds this straight into
        :func:`heat_tpu.telemetry.cluster.summarize_cluster`."""
        out: Dict[str, dict] = {}
        for h in self.replicas:
            if h.state == "up" and h.url and h.alive():
                try:
                    out[h.url] = self.metrics(h.index, timeout)
                except Exception:
                    out[h.url] = None
        return out

    def _get_json(self, index: int, path: str, timeout: float) -> dict:
        import http.client

        h = self.handle(index)
        conn = http.client.HTTPConnection(self.host, h.port, timeout=timeout)
        try:
            conn.request("GET", path)
            return json.loads(conn.getresponse().read().decode())
        finally:
            conn.close()

    def kill(self, index: int) -> None:
        """SIGKILL — the chaos primitive. No drain, no flush: only this
        replica's in-flight requests are lost (router semantics)."""
        h = self.handle(index)
        if h.alive():
            h.proc.kill()
            h.proc.wait(10.0)
        h.state = "killed"
        _emit("pool", "kill", replica=index)

    def remove(self, index: int, timeout: float = 60.0) -> int:
        """Drain-then-kill removal: SIGTERM → the replica sheds new work
        (router retries siblings), finishes its backlog, flushes
        telemetry, exits. Returns the exit code (0 = clean drain;
        asserted by the CI gate) — a replica that ignores the deadline
        is hard-killed and reports its real rc."""
        h = self.handle(index)
        if h.alive():
            h.proc.send_signal(signal.SIGTERM)
            try:
                h.proc.wait(timeout)
            except subprocess.TimeoutExpired:
                h.proc.kill()
                h.proc.wait(10.0)
        h.state = "removed"
        rc = int(h.proc.returncode)
        _emit("pool", "remove", replica=index, rc=rc)
        return rc

    def close(self, timeout: float = 30.0) -> None:
        """Tear the pool down: graceful SIGTERM sweep, hard kill for
        stragglers. Idempotent."""
        for h in self.replicas:
            if h.alive():
                try:
                    h.proc.send_signal(signal.SIGTERM)
                except OSError:
                    pass
        deadline = time.monotonic() + timeout
        for h in self.replicas:
            if h.proc.poll() is None:
                try:
                    h.proc.wait(max(0.1, deadline - time.monotonic()))
                except subprocess.TimeoutExpired:
                    h.proc.kill()
                    h.proc.wait(10.0)
            if h.state in ("spawning", "up"):
                h.state = "dead"

    def __enter__(self) -> "ReplicaPool":
        return self

    def __exit__(self, *exc) -> bool:
        self.close()
        return False
