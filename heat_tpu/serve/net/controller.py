"""SLO-driven autoscaling control plane (ISSUE 20 tentpole).

:class:`AutoscaleController` closes the loop ROADMAP item 4 left open:
PR 12 built the actuators (``ReplicaPool.spawn`` / ``remove`` / ``kill``)
and PR 17 built the sensors (``Router.cluster_summary()`` fleet metrics,
declared :class:`~heat_tpu.telemetry.cluster.SLO` objectives, windowed
burn rates, the ``slo_burn`` breach event); this module is the policy
that watches the sensors and drives the actuators so the fleet holds its
SLO at minimum footprint:

* **scale-up** — an SLO burn breach (``Router.check_slos()``) triggers
  immediately; sustained backlog (per-replica score above
  ``HEAT_TPU_AUTOSCALE_BACKLOG_HIGH`` for ``HEAT_TPU_AUTOSCALE_BACKLOG_TICKS``
  consecutive ticks) or fresh sheds trigger after the streak. One
  replica per action, bounded by ``HEAT_TPU_AUTOSCALE_MAX`` and the
  ``HEAT_TPU_AUTOSCALE_UP_COOLDOWN_S`` cooldown.
* **scale-down** — after ``HEAT_TPU_AUTOSCALE_IDLE_TICKS`` consecutive
  drain-idle ticks (per-replica backlog at/below
  ``HEAT_TPU_AUTOSCALE_IDLE_LOW``, zero new sheds, no burn), the newest
  replica drains out (``Router.remove_target`` first — no new dispatch —
  then ``ReplicaPool.remove``'s SIGTERM drain), bounded by
  ``HEAT_TPU_AUTOSCALE_MIN`` and ``HEAT_TPU_AUTOSCALE_DOWN_COOLDOWN_S``.
* **hysteresis** — any action resets both streaks; the down cooldown is
  measured from the LAST action in either direction, so a scale-up is
  never immediately undone by a stale idle streak.
* **chaos replacement** — a replica that died without being removed
  (SIGKILL, OOM, crash) is respawned on the next tick, outside the
  cooldown discipline (repair is not scaling): the dead target is
  detached from the router, ``pool.spawn()`` warm-starts a replacement
  from the shared compile cache + tuning DB (zero steady-state
  compiles — the PR 3/PR 12 composition), and ``Router.add_target``
  rejoins it.

**Determinism.** Every decision path runs without sleeps: ``clock`` is
injectable (tests pass a counter), ``metrics_fn`` swaps the live
router/pool observation for a scripted trace, and the three actuators
(``scale_up_fn`` / ``scale_down_fn`` / ``replace_fn``) are injectable
stubs — ``tick()`` is then a pure decision-table step whose verdicts
land in ``self.history``. The live wiring (pool + router) is only the
default binding of those hooks.

Telemetry: every action emits one ``autoscale`` instant event paired
with one ``autoscale.<counter>`` registry increment (the PR 5/11/12
live==offline reconciliation contract; ``EVENT_COUNTER`` below is the
map ``telemetry.report`` replays), and rides the Chrome trace like any
other instant event. ``replica_seconds`` integrates the live footprint
over time — the bench honesty figure the autoscale artifact prices
against static max provisioning.
"""

from __future__ import annotations

import threading
import time
from typing import Any, Callable, Dict, List, Optional

from heat_tpu import _knobs as knobs

from ... import telemetry

__all__ = ["AutoscaleController", "EVENT_COUNTER"]

# autoscale event (sink)  ->  counter suffix (live registry) — the same
# reconciliation contract serve/net/events.py holds for serve_net
EVENT_COUNTER = {
    "scale_up": "scale_ups",       # one replica spawned + joined
    "scale_down": "scale_downs",   # one replica drained + removed
    "replace": "replacements",     # dead replica respawned (chaos repair)
}


def _emit(event: str, **fields: Any) -> None:
    """One ``autoscale`` instant event + its paired counter (no-op while
    telemetry is disabled — one flag check)."""
    if not telemetry.enabled():
        return
    reg = telemetry.get_registry()
    reg.add(f"autoscale.{EVENT_COUNTER[event]}", 1)
    reg.emit("autoscale", "controller", event=event, **fields)


def _knob(value, name, cast):
    return cast(knobs.get(name) if value is None else value)


class AutoscaleController:
    """SLO-holding replica-count controller over a
    :class:`~.pool.ReplicaPool` + :class:`~.router.Router` pair (module
    docstring has the policy). Construct with ``pool``/``router`` for
    live control, or with ``metrics_fn`` + actuator stubs for
    deterministic decision-table tests."""

    def __init__(
        self,
        pool=None,
        router=None,
        *,
        min_replicas: Optional[int] = None,
        max_replicas: Optional[int] = None,
        backlog_high: Optional[float] = None,
        backlog_ticks: Optional[int] = None,
        idle_low: Optional[float] = None,
        idle_ticks: Optional[int] = None,
        up_cooldown_s: Optional[float] = None,
        down_cooldown_s: Optional[float] = None,
        tick_interval_s: Optional[float] = None,
        slo_check_every: int = 1,
        clock: Callable[[], float] = time.monotonic,
        metrics_fn: Optional[Callable[[], dict]] = None,
        scale_up_fn: Optional[Callable[[], Any]] = None,
        scale_down_fn: Optional[Callable[[], Any]] = None,
        replace_fn: Optional[Callable[[Any], Any]] = None,
    ):
        self.pool = pool
        self.router = router
        self.min_replicas = _knob(min_replicas, "HEAT_TPU_AUTOSCALE_MIN", int)
        self.max_replicas = _knob(max_replicas, "HEAT_TPU_AUTOSCALE_MAX", int)
        if self.min_replicas < 1 or self.max_replicas < self.min_replicas:
            raise ValueError(
                f"need 1 <= min <= max, got min={self.min_replicas} "
                f"max={self.max_replicas}"
            )
        self.backlog_high = _knob(
            backlog_high, "HEAT_TPU_AUTOSCALE_BACKLOG_HIGH", float
        )
        self.backlog_ticks = max(1, _knob(
            backlog_ticks, "HEAT_TPU_AUTOSCALE_BACKLOG_TICKS", int
        ))
        self.idle_low = _knob(idle_low, "HEAT_TPU_AUTOSCALE_IDLE_LOW", float)
        self.idle_ticks_needed = max(1, _knob(
            idle_ticks, "HEAT_TPU_AUTOSCALE_IDLE_TICKS", int
        ))
        self.up_cooldown_s = _knob(
            up_cooldown_s, "HEAT_TPU_AUTOSCALE_UP_COOLDOWN_S", float
        )
        self.down_cooldown_s = _knob(
            down_cooldown_s, "HEAT_TPU_AUTOSCALE_DOWN_COOLDOWN_S", float
        )
        self.tick_interval_s = _knob(
            tick_interval_s, "HEAT_TPU_AUTOSCALE_TICK_S", float
        )
        # SLO-burn probing scrapes every replica's /metrics — at small
        # tick intervals that wall-clock cost would crowd out the tick
        # cadence itself, so the check may run every Nth tick (the burn
        # verdict holds between probes; backlog/shed stay per-tick)
        self.slo_check_every = max(1, int(slo_check_every))
        self._last_burn = False
        self.clock = clock
        self.metrics_fn = metrics_fn
        self._scale_up_fn = scale_up_fn or self._default_scale_up
        self._scale_down_fn = scale_down_fn or self._default_scale_down
        self._replace_fn = replace_fn or self._default_replace
        # decision state
        self.ticks = 0
        self._hot_ticks = 0
        self._idle_ticks = 0
        self._last_shed: Optional[int] = None
        self._last_up = float("-inf")      # up allowed on the first tick
        self._last_action = float("-inf")
        self.history: List[dict] = []
        self.counts = {"scale_ups": 0, "scale_downs": 0, "replacements": 0,
                       "clamped_max": 0, "clamped_min": 0}
        # replica-seconds integral (the footprint the bench prices)
        self.replica_seconds = 0.0
        self._last_tick_t: Optional[float] = None
        # background loop
        self._stop = threading.Event()
        self._thread: Optional[threading.Thread] = None

    # -- observation ---------------------------------------------------------

    def _observe(self) -> dict:
        """One sensor reading. Scripted mode (``metrics_fn``) returns it
        verbatim; live mode derives it from the router's routing state +
        SLO accounting and the pool's process liveness:

        * ``replicas`` — live serving processes;
        * ``backlog`` — admitted-but-unresolved work (per-replica polled
          pending + router in-flight + router queue depth);
        * ``slo_burn`` — any declared SLO burning above threshold
          (``Router.check_slos()`` emits the breach events as a side
          effect — the controller IS that signal's consumer);
        * ``shed`` — cumulative router sheds (the tick diffs it);
        * ``dead`` — pool indices that died without being removed.
        """
        if self.metrics_fn is not None:
            return dict(self.metrics_fn())
        rs = self.router.stats()
        backlog = sum(
            r["score"] for r in rs["replicas"].values() if r["up"]
        ) + rs["queue_depth"]
        burn = self._last_burn
        if self.router.slos and self.ticks % self.slo_check_every == 0:
            try:
                burn = any(
                    row.get("breach") for row in self.router.check_slos()
                )
            except Exception:  # noqa: BLE001 — scrape trouble is not a
                burn = False   # scale signal; the ops plane flags suspects
            self._last_burn = burn
        dead: List[int] = []
        replicas = 0
        if self.pool is not None:
            for h in self.pool.replicas:
                if h.state == "up":
                    if h.alive():
                        replicas += 1
                    else:
                        dead.append(h.index)
        else:
            replicas = sum(1 for r in rs["replicas"].values() if r["up"])
        return {
            "replicas": replicas,
            "backlog": backlog,
            "slo_burn": burn,
            "shed": rs["router"]["shed"],
            "dead": dead,
        }

    # -- default actuators (live pool + router binding) ----------------------

    def _default_scale_up(self):
        h = self.pool.spawn()
        if self.router is not None:
            self.router.add_target(h.url)
        return h.index

    def _default_scale_down(self):
        # newest live replica drains first (LIFO keeps the long-lived
        # base footprint — and its warm caches — stable)
        live = [h for h in self.pool.replicas
                if h.state == "up" and h.alive()]
        if not live:
            return None
        h = live[-1]
        if self.router is not None and h.url:
            self.router.remove_target(h.url)
        self.pool.remove(h.index)
        return h.index

    def _default_replace(self, index):
        old = self.pool.handle(index)
        old.state = "dead"
        if self.router is not None and old.url:
            self.router.remove_target(old.url)
        h = self.pool.spawn()
        if self.router is not None:
            self.router.add_target(h.url)
        return h.index

    # -- the decision step ---------------------------------------------------

    def tick(self) -> dict:
        """One observe → decide → act step; returns (and records in
        ``self.history``) the decision row. Deterministic given the
        injected clock + metrics: no sleeps, no wall-clock reads."""
        now = self.clock()
        obs = self._observe()
        self.ticks += 1
        if self._last_tick_t is not None:
            self.replica_seconds += (
                max(0.0, now - self._last_tick_t) * obs["replicas"]
            )
        self._last_tick_t = now
        row: Dict[str, Any] = {
            "tick": self.ticks, "t": now, "obs": obs, "action": "hold",
        }

        # 1. repair before policy: a dead replica is replaced 1:1,
        # outside the cooldown discipline
        for index in list(obs.get("dead") or ()):
            try:
                new = self._replace_fn(index)
            except Exception as e:  # noqa: BLE001 — a failed respawn is
                row["replace_error"] = repr(e)  # data, not a crashed loop
                continue
            self.counts["replacements"] += 1
            row.setdefault("replaced", []).append(
                {"old": index, "new": new}
            )
            _emit("replace", old=index, new=new, tick=self.ticks)
        if "replaced" in row:
            row["action"] = "replace"
            self._last_action = now
            self._hot_ticks = 0
            self._idle_ticks = 0

        # 2. streaks (hysteresis state)
        n = max(1, int(obs["replicas"]))
        per_replica = obs["backlog"] / n
        shed = int(obs.get("shed") or 0)
        shed_delta = 0 if self._last_shed is None else shed - self._last_shed
        self._last_shed = shed
        row["per_replica_backlog"] = round(per_replica, 3)
        row["shed_delta"] = shed_delta
        pressure = (
            bool(obs.get("slo_burn"))
            or per_replica >= self.backlog_high
            or shed_delta > 0
        )
        if pressure:
            self._hot_ticks += 1
            self._idle_ticks = 0
        elif per_replica <= self.idle_low and shed_delta == 0:
            self._idle_ticks += 1
            self._hot_ticks = 0
        else:
            self._hot_ticks = 0
            self._idle_ticks = 0
        row["hot_ticks"] = self._hot_ticks
        row["idle_ticks"] = self._idle_ticks

        # 3. decide + clamp + cooldown
        want = 0
        if bool(obs.get("slo_burn")) or self._hot_ticks >= self.backlog_ticks:
            want = 1
        elif self._idle_ticks >= self.idle_ticks_needed:
            want = -1
        if want > 0:
            if int(obs["replicas"]) >= self.max_replicas:
                row["action"] = "clamp_max"
                self.counts["clamped_max"] += 1
            elif now - self._last_up < self.up_cooldown_s:
                row["action"] = "cooldown_up"
            else:
                try:
                    new = self._scale_up_fn()
                except Exception as e:  # noqa: BLE001
                    row["action"] = "scale_up_error"
                    row["error"] = repr(e)
                else:
                    row["action"] = "scale_up"
                    row["replica"] = new
                    self.counts["scale_ups"] += 1
                    self._last_up = now
                    self._last_action = now
                    self._hot_ticks = 0
                    self._idle_ticks = 0
                    _emit(
                        "scale_up", replica=new, tick=self.ticks,
                        reason="slo_burn" if obs.get("slo_burn")
                        else ("shed" if shed_delta > 0 else "backlog"),
                        per_replica_backlog=round(per_replica, 3),
                    )
        elif want < 0:
            if int(obs["replicas"]) <= self.min_replicas:
                row["action"] = "clamp_min"
                self.counts["clamped_min"] += 1
                self._idle_ticks = 0
            elif now - self._last_action < self.down_cooldown_s:
                row["action"] = "cooldown_down"
            else:
                try:
                    gone = self._scale_down_fn()
                except Exception as e:  # noqa: BLE001
                    row["action"] = "scale_down_error"
                    row["error"] = repr(e)
                else:
                    row["action"] = "scale_down"
                    row["replica"] = gone
                    self.counts["scale_downs"] += 1
                    self._last_action = now
                    self._idle_ticks = 0
                    self._hot_ticks = 0
                    _emit("scale_down", replica=gone, tick=self.ticks)
        self.history.append(row)
        return row

    # -- background loop -----------------------------------------------------

    def start(self) -> "AutoscaleController":
        """Run ``tick()`` every ``tick_interval_s`` seconds on a daemon
        thread until :meth:`stop`."""
        if self._thread is not None:
            return self
        self._stop.clear()

        def _loop():
            while not self._stop.is_set():
                try:
                    self.tick()
                except Exception:  # noqa: BLE001 — the loop must survive
                    pass           # one bad scrape; the row records errors
                self._stop.wait(self.tick_interval_s)

        self._thread = threading.Thread(
            target=_loop, name="heat_tpu.serve.net.autoscale", daemon=True,
        )
        self._thread.start()
        return self

    def stop(self) -> None:
        """Stop the background loop (idempotent; the pool/router stay
        up — the controller only ever owns the POLICY)."""
        self._stop.set()
        t, self._thread = self._thread, None
        if t is not None:
            t.join(5.0)

    def stats(self) -> dict:
        """Decision-plane counters + footprint integral (the bench /
        CI-gate surface)."""
        return {
            "ticks": self.ticks,
            "replica_seconds": round(self.replica_seconds, 3),
            "hot_ticks": self._hot_ticks,
            "idle_ticks": self._idle_ticks,
            **self.counts,
        }

    def __enter__(self) -> "AutoscaleController":
        return self

    def __exit__(self, *exc) -> bool:
        self.stop()
        return False
