"""Least-loaded request router over a set of replica HTTP fronts.

The horizontal half of the ISSUE 12 tentpole: N replica processes (each
a :class:`~heat_tpu.serve.Server` behind :class:`~.transport.HttpFront`)
scale QPS past the single-process ceiling, and the router is the piece
that makes them look like ONE server to a client:

* **least-loaded dispatch** — a poll thread refreshes every healthy
  replica's ``/stats`` each ``HEAT_TPU_SERVE_NET_POLL_MS``; the dispatch
  score is the polled backlog (admitted-but-unresolved ``pending``)
  plus this router's own in-flight count to that replica (fresher than
  any poll). Requests go to the minimum-score replica. An optional
  ``max_inflight`` caps concurrent requests per replica (the client
  half of the per-replica admission-budget discipline — the analog of a
  proxy's per-backend circuit-breaker concurrency cap): workers block
  for a free slot instead of piling onto a busy replica, and a request
  whose deadline passes while every slot stays taken sheds 503-style
  (``router_timeout``).
* **sticky degradation** — a 503 shed from one replica (queue_full /
  memory / draining) retries up to ``HEAT_TPU_SERVE_NET_RETRIES``
  *siblings* before the client sees :class:`ServerOverloadedError`:
  one overloaded (or draining) replica degrades to "the others absorb
  it", not to client-visible failure. The shedding replica is NOT
  evicted — it is alive and telling us so.
* **health eviction + re-add** — a connection-level failure evicts the
  replica from rotation (its queued work re-routes); the poll thread
  keeps probing ``/healthz`` and re-adds it the moment it answers —
  a drained-and-restarted (or crash-restored) replica rejoins without
  router restart.
* **failure semantics** — a connect-refused replica never saw the
  request: safe to retry a sibling. A connection that drops *after* the
  request was sent is ambiguous (it may have executed), so by default
  those fail with :class:`ReplicaDownError` — the bench chaos phase's
  "killing a replica loses only its in-flight requests" contract.
  ``retry_in_flight=True`` opts into at-least-once re-dispatch for
  callers that know their endpoints are pure.

The client surface mirrors the in-process server — ``submit()`` returns
a future, ``predict()`` blocks, ``stats()["endpoints"]`` carries the
same per-endpoint latency aggregates (:class:`~..metrics.EndpointStats`)
— so the PR 8 open-loop load generator drives a router and a local
server through the identical code path (the scaling artifact's
apples-to-apples requirement).
"""

from __future__ import annotations

import http.client
import json
import threading
import time
from concurrent.futures import Future
from queue import Empty, Queue
from typing import Dict, List, Optional, Sequence, Tuple, Union
from urllib.parse import urlparse

import numpy as np

from heat_tpu import _knobs as knobs

from .. import tracing
from ..admission import ServeError, ServerClosedError, ServerOverloadedError
from ..metrics import EndpointStats
from . import wire
from .events import emit as _emit

__all__ = ["Router", "ReplicaDownError"]

_POLL_TIMEOUT = 2.0  # seconds per /stats / /healthz probe


class _NoDelayConnection(http.client.HTTPConnection):
    """HTTPConnection with Nagle disabled — request/response pairs are
    single small write-read exchanges, exactly the pattern Nagle +
    delayed ACK stalls (measured: 33 ms loopback round trips without
    this, ~3 ms with)."""

    def connect(self):
        super().connect()
        import socket as _socket

        self.sock.setsockopt(
            _socket.IPPROTO_TCP, _socket.TCP_NODELAY, 1
        )


class ReplicaDownError(ServeError):
    """No healthy replica could (safely) serve the request: every
    candidate was down, or the chosen replica's connection dropped with
    the request in flight (``retry_in_flight=False``)."""


class _Target:
    """One replica as the router sees it."""

    __slots__ = ("url", "host", "port", "up", "inflight", "polled_pending",
                 "poll_fails", "evictions")

    def __init__(self, url: str):
        parsed = urlparse(url if "//" in url else f"http://{url}")
        if not parsed.hostname or not parsed.port:
            raise ValueError(f"replica url needs host:port, got {url!r}")
        self.url = f"http://{parsed.hostname}:{parsed.port}"
        self.host = parsed.hostname
        self.port = parsed.port
        self.up = True
        self.inflight = 0
        self.polled_pending = 0
        self.poll_fails = 0
        self.evictions = 0

    def score(self) -> int:
        # routing state is guarded by the router's one Condition; reads
        # of two ints race only with themselves (shed tolerance: the
        # score is a heuristic, not an allocator)
        return self.polled_pending + self.inflight


class _Job:
    __slots__ = ("endpoint", "body", "future", "t0", "t_wall", "ctx")

    def __init__(self, endpoint: str, body: bytes, ctx=None):
        self.endpoint = endpoint
        self.body = body
        self.future: Future = Future()
        self.t0 = time.perf_counter()
        # wall twin of t0, trace-only (spans anchor on wall clock)
        self.t_wall = time.time() if ctx is not None else 0.0
        self.ctx = ctx  # Optional[tracing.TraceContext]


class _InFlightDrop(Exception):
    """Connection died after the request was on the wire (internal)."""


class _ResponseTimeout(Exception):
    """The replica accepted the request but did not answer within the
    socket timeout (internal). NOT an outage: the replica is healthy,
    just slow — it must not be evicted, and the request must not be
    blindly retried (it may still execute)."""


class Router:
    """Least-loaded HTTP router over replica fronts (module docstring
    has the policy). ``targets`` is a sequence of replica base URLs
    (``http://host:port`` or ``host:port``) or an object with a
    ``urls()`` method (:class:`~.pool.ReplicaPool`)."""

    def __init__(
        self,
        targets: Union[Sequence[str], object],
        *,
        retries: Optional[int] = None,
        poll_ms: Optional[float] = None,
        workers: Optional[int] = None,
        request_timeout: float = 30.0,
        retry_in_flight: bool = False,
        max_inflight: Optional[int] = None,
        slos: Optional[Sequence] = None,
    ):
        if hasattr(targets, "urls"):
            targets = targets.urls()
        self._targets: List[_Target] = [_Target(u) for u in targets]
        if not self._targets:
            raise ValueError("router needs at least one replica url")
        # per-replica in-flight budget (the client half of the bounded
        # per-replica admission discipline): a worker holding a request
        # BLOCKS for a slot rather than piling more concurrency onto a
        # busy replica. None = unlimited.
        self.max_inflight = (
            None if max_inflight is None else max(1, int(max_inflight))
        )
        self._state = threading.Condition()
        self.retries = int(
            retries if retries is not None
            else knobs.get("HEAT_TPU_SERVE_NET_RETRIES")
        )
        poll_ms = (
            poll_ms if poll_ms is not None
            else knobs.get("HEAT_TPU_SERVE_NET_POLL_MS")
        )
        self.poll_interval = max(0.001, float(poll_ms) / 1e3)
        self.request_timeout = float(request_timeout)
        self.retry_in_flight = bool(retry_in_flight)
        n_workers = (
            workers if workers is not None
            else max(8, 4 * len(self._targets))
        )
        self._stats: Dict[str, EndpointStats] = {}
        self._stats_lock = threading.Lock()
        self._queue: "Queue" = Queue()
        self._closed = False
        # ISSUE 17: declared SLOs (telemetry.cluster.SLO) + the rolling
        # scrape-snapshot ring cluster_summary() windows burn rates over
        self.slos = list(slos) if slos else []
        self.window_start = time.monotonic()
        self._slo_snaps: List[tuple] = []  # (mono, scrape state)
        self._slo_lock = threading.Lock()
        self._counts = {"requests": 0, "retries": 0, "evictions": 0,
                        "readds": 0, "failed": 0, "shed": 0}
        self._counts_lock = threading.Lock()
        self._local = threading.local()  # per-worker connection cache
        self._poll_conns: Dict[str, http.client.HTTPConnection] = {}
        self._workers = [
            threading.Thread(
                target=self._work, name=f"heat_tpu.serve.net.router-{i}",
                daemon=True,
            )
            for i in range(int(n_workers))
        ]
        for t in self._workers:
            t.start()
        self._poll_thread = threading.Thread(
            target=self._poll_loop, name="heat_tpu.serve.net.router-poll",
            daemon=True,
        )
        self._poll_thread.start()

    # -- client surface ------------------------------------------------------

    def submit(self, name: str, payload) -> Future:
        """Enqueue one request; the future resolves to the result rows,
        or to :class:`ServerOverloadedError` (every candidate shed),
        :class:`ReplicaDownError` (no healthy replica / in-flight drop),
        or the upstream error."""
        if self._closed:
            raise ServerClosedError("router is closed")
        # trace ingress (ISSUE 17): the sampling verdict is made HERE,
        # once, and rides the wire — replicas adopt, never re-mint
        ctx = tracing.mint("router.submit")
        job = _Job(
            name,
            wire.encode_request(
                np.asarray(payload),
                trace=ctx.to_wire() if ctx is not None else None,
            ),
            ctx,
        )
        self._ep_stats(name).record_request(
            int(np.asarray(payload).shape[0])
            if np.asarray(payload).ndim else 1
        )
        self._queue.put(job)
        return job.future

    def predict(self, name: str, payload, timeout: Optional[float] = 30.0):
        """Synchronous convenience: ``submit(...).result(timeout)``."""
        return self.submit(name, payload).result(timeout)

    def add_target(self, url: str) -> None:
        """Join a new replica into the rotation (scale-up / re-add of a
        freshly spawned process)."""
        t = _Target(url)
        with self._state:
            if any(x.url == t.url for x in self._targets):
                return
            self._targets.append(t)
            self._state.notify_all()

    def stats(self) -> dict:
        """Loadgen-compatible aggregates: per-endpoint latency stats
        (client-observed submit→resolve), per-replica routing state, and
        the router counters."""
        with self._counts_lock:
            counts = dict(self._counts)
        with self._stats_lock:  # first-seen endpoints insert concurrently
            stats_items = list(self._stats.items())
        return {
            "endpoints": {n: s.snapshot() for n, s in stats_items},
            "queue_depth": self._queue.qsize(),
            # scrape contract (ISSUE 17): cumulative-since-window_start
            # counters + a monotonic stamp, so two scrapes derive rates
            # on their own side without racing any reset
            "window_start": self.window_start,
            "mono": time.monotonic(),
            "slos": [s.describe() for s in self.slos],
            "replicas": {
                t.url: {
                    "up": t.up,
                    "score": t.score(),
                    "inflight": t.inflight,
                    "polled_pending": t.polled_pending,
                    "evictions": t.evictions,
                }
                for t in list(self._targets)
            },
            "router": counts,
            "closed": self._closed,
        }

    # -- fleet observability (ISSUE 17) --------------------------------------

    def _ops_get(self, target: _Target, path: str):
        """GET over a dedicated short-lived connection → ``(status,
        body)``. The keep-alive poll connections are poll-thread-only;
        observability scrapes run on caller threads and must not share
        them."""
        conn = _NoDelayConnection(
            target.host, target.port, timeout=_POLL_TIMEOUT
        )
        try:
            conn.request("GET", path)
            resp = conn.getresponse()
            return resp.status, resp.read()
        finally:
            conn.close()

    def scrape_metrics(self) -> Dict[str, Optional[dict]]:
        """Pull ``GET /metrics`` from every replica → ``{url: payload}``
        (``None`` for replicas that failed to answer — merged summaries
        report them as ``scrape_failures``, never silently drop them)."""
        out: Dict[str, Optional[dict]] = {}
        for t in list(self._targets):
            try:
                status, body = self._ops_get(t, "/metrics")
                out[t.url] = (
                    json.loads(body.decode()) if status == 200 else None
                )
            except Exception:
                out[t.url] = None
        return out

    def scrape_traces(self) -> Dict[str, Optional[dict]]:
        """Pull ``GET /trace`` (each replica's in-memory telemetry
        events) → ``{url: {"pid", "wall", "events"} | None}``."""
        out: Dict[str, Optional[dict]] = {}
        for t in list(self._targets):
            try:
                status, body = self._ops_get(t, "/trace")
                out[t.url] = (
                    json.loads(body.decode()) if status == 200 else None
                )
            except Exception:
                out[t.url] = None
        return out

    def clock_sync(self, probes: int = 3) -> Dict[str, dict]:
        """Calibrate each replica's wall-clock offset against this process
        via the ``/healthz`` round trip: of ``probes`` exchanges on one
        keep-alive connection, take the minimum-RTT sample and estimate
        ``offset = remote_wall - rtt_midpoint`` with ``uncertainty =
        rtt / 2`` (the remote stamp happened somewhere inside the round
        trip). Returns ``{url: {"offset", "uncertainty", "rtt", "pid"}}``
        — pre-17 replicas (no ``wall`` in /healthz) are omitted."""
        out: Dict[str, dict] = {}
        for t in list(self._targets):
            best = None
            pid = None
            try:
                conn = _NoDelayConnection(
                    t.host, t.port, timeout=_POLL_TIMEOUT
                )
                try:
                    for _ in range(max(1, int(probes))):
                        a = time.time()
                        conn.request("GET", "/healthz")
                        resp = conn.getresponse()
                        body = resp.read()
                        b = time.time()
                        payload = json.loads(body.decode())
                        wall = payload.get("wall")
                        if wall is None:
                            break
                        pid = payload.get("pid")
                        rtt = b - a
                        if best is None or rtt < best[0]:
                            best = (rtt, float(wall) - (a + b) / 2.0)
                finally:
                    conn.close()
            except Exception:
                continue
            if best is not None:
                out[t.url] = {
                    "offset": best[1],
                    "uncertainty": best[0] / 2.0,
                    "rtt": best[0],
                    "pid": pid,
                }
        return out

    def cluster_summary(self) -> dict:
        """Scrape every replica and return the fleet-merged report
        (:func:`heat_tpu.telemetry.cluster.summarize_cluster`): fleet
        QPS + exactly-merged p50/p95/p99 per endpoint, per-replica
        occupancy/compile/version-lag rows, and — when this router
        declares SLOs — the ``slo`` burn-rate block. Burn windows roll
        over ``HEAT_TPU_SLO_WINDOW_S``: each call diffs against the
        scrape snapshot taken about one window ago (the first call
        covers each replica's lifetime)."""
        from ...telemetry import cluster as _cluster

        scrapes = self.scrape_metrics()
        now = time.monotonic()
        try:
            window_s = float(knobs.get("HEAT_TPU_SLO_WINDOW_S"))
        except (TypeError, ValueError):
            window_s = 60.0
        with self._slo_lock:
            cutoff = now - max(0.001, window_s)
            # keep the newest snapshot at/older than the cutoff as the
            # window's far edge; everything older is garbage
            while len(self._slo_snaps) >= 2 and self._slo_snaps[1][0] <= cutoff:
                self._slo_snaps.pop(0)
            prev = self._slo_snaps[0][1] if self._slo_snaps else None
        summary = _cluster.summarize_cluster(
            scrapes, slos=self.slos, prev_state=prev,
            router_stats=self.stats(),
        )
        with self._slo_lock:
            self._slo_snaps.append((now, summary["state"]))
        return summary

    def check_slos(self) -> List[dict]:
        """One SLO accounting pass: :meth:`cluster_summary`'s ``slo``
        block, with an ``slo_burn`` telemetry event emitted for every
        breach (burn rate above ``HEAT_TPU_SLO_BURN_THRESHOLD``) — the
        scale-up trigger signal ROADMAP item 4 consumes."""
        rows = self.cluster_summary().get("slo", [])
        for row in rows:
            if row.get("breach"):
                _emit(
                    "slo", "slo_burn",
                    endpoint=row["endpoint"],
                    burn_rate=row["burn_rate"],
                    threshold=row["threshold"],
                    window_requests=row["window_requests"],
                    window_seconds=row["window_seconds"],
                )
        return rows

    def prometheus_text(self) -> str:
        """The merged fleet view in Prometheus text exposition format
        (scrape the router once instead of N replicas)."""
        from ...telemetry import cluster as _cluster

        return _cluster.prometheus_text(self.cluster_summary())

    def export_cluster_trace(self, path: str) -> str:
        """Export ONE merged Perfetto trace: this router's events plus
        every replica's (``GET /trace``), clock-offset corrected via the
        ``/healthz`` calibration, pid = replica, one fleet-wide t=0
        (:func:`heat_tpu.telemetry.cluster.export_merged_trace`)."""
        from ...telemetry import cluster as _cluster

        return _cluster.export_merged_trace(self, path)

    def close(self) -> None:
        """Stop workers + poll thread; fail queued requests with
        :class:`ServerClosedError`. Idempotent."""
        if self._closed:
            return
        self._closed = True
        with self._state:
            self._state.notify_all()  # wake workers blocked on a slot
        for _ in self._workers:
            self._queue.put(None)
        for t in self._workers:
            t.join(5.0)
        self._poll_thread.join(5.0)
        for conn in self._poll_conns.values():
            try:
                conn.close()
            except Exception:
                pass
        self._poll_conns.clear()
        while True:
            try:
                job = self._queue.get_nowait()
            except Empty:
                break
            if job is not None:
                try:
                    job.future.set_exception(
                        ServerClosedError("router closed with request "
                                          "pending")
                    )
                except Exception:
                    pass

    def __enter__(self) -> "Router":
        return self

    def __exit__(self, *exc) -> bool:
        self.close()
        return False

    # -- internals -----------------------------------------------------------

    def _ep_stats(self, name: str) -> EndpointStats:
        st = self._stats.get(name)
        if st is None:
            with self._stats_lock:
                st = self._stats.setdefault(name, EndpointStats(name))
        return st

    def _count(self, key: str, n: int = 1) -> None:
        with self._counts_lock:
            self._counts[key] += n

    def _pick_locked(self, exclude: set):
        """(best-free-target, any-up-but-at-budget) under ``_state``."""
        best, best_score, busy = None, None, False
        for t in self._targets:
            if not t.up or t.url in exclude:
                continue
            if (
                self.max_inflight is not None
                and t.inflight >= self.max_inflight
            ):
                busy = True
                continue
            s = t.score()
            if best_score is None or s < best_score:
                best, best_score = t, s
        return best, busy

    def _acquire(self, exclude: set, deadline: float):
        """Claim an in-flight slot on the least-loaded eligible replica;
        blocks while every eligible replica is at its in-flight budget.
        Returns ``(target, None)``, or ``(None, "down")`` when no healthy
        replica exists (fail fast), or ``(None, "timeout")`` when the
        request's deadline passed while waiting for a slot."""
        with self._state:
            while True:
                best, busy = self._pick_locked(exclude)
                if best is not None:
                    best.inflight += 1
                    return best, None
                if not busy or self._closed:
                    return None, "down"
                if time.perf_counter() >= deadline:
                    return None, "timeout"
                self._state.wait(
                    max(0.001, min(0.1, deadline - time.perf_counter()))
                )

    def _release(self, target: _Target) -> None:
        with self._state:
            target.inflight -= 1
            self._state.notify()

    def _evict(self, target: _Target, why: str) -> None:
        with self._state:
            if not target.up:
                return
            target.up = False
            target.evictions += 1
            target.poll_fails = 0
            self._state.notify_all()
        self._count("evictions")
        _emit("router", "evict", replica=target.url, reason=why)

    def _readd(self, target: _Target) -> None:
        with self._state:
            if target.up:
                return
            target.up = True
            target.polled_pending = 0
            self._state.notify_all()
        self._count("readds")
        _emit("router", "readd", replica=target.url)

    # one keep-alive connection per (worker thread, replica)
    def _conn(self, target: _Target, fresh: bool = False):
        cache = getattr(self._local, "conns", None)
        if cache is None:
            cache = self._local.conns = {}
        conn = cache.get(target.url)
        if fresh and conn is not None:
            try:
                conn.close()
            except Exception:
                pass
            conn = None
        if conn is None:
            conn = _NoDelayConnection(
                target.host, target.port, timeout=self.request_timeout
            )
            cache[target.url] = conn
        return conn

    def _post(self, target: _Target, path: str, body: bytes):
        """POST once; returns ``(status, body_bytes)``. Raises
        ``ConnectionError``-family when the request never made it onto
        an accepted connection (safe to retry a sibling),
        :class:`_InFlightDrop` when the connection died after the send
        (ambiguous — the request may have executed)."""
        conn = self._conn(target)
        reused = conn.sock is not None
        try:
            conn.request(
                "POST", path, body=body,
                headers={"Content-Type": "application/json"},
            )
        except Exception:
            conn.close()
            if not reused:
                raise  # fresh connect failed: replica is unreachable
            # keep-alive race: the server closed the idle conn under us
            # and the send never happened — one fresh-connection resend
            conn = self._conn(target, fresh=True)
            conn.request(
                "POST", path, body=body,
                headers={"Content-Type": "application/json"},
            )
        try:
            resp = conn.getresponse()
            data = resp.read()
            return resp.status, data
        except TimeoutError as e:  # socket.timeout: slow, not dead
            conn.close()
            raise _ResponseTimeout(repr(e)) from e
        except Exception as e:
            conn.close()
            raise _InFlightDrop(repr(e)) from e

    def _work(self) -> None:
        while True:
            job = self._queue.get()
            if job is None:
                return
            try:
                self._dispatch(job)
            except Exception as e:  # noqa: BLE001 — never kill a worker
                try:
                    job.future.set_exception(e)
                except Exception:
                    pass

    def _dispatch(self, job: _Job) -> None:
        st = self._ep_stats(job.endpoint)
        if job.ctx is not None:
            # router.queue: ingress -> a worker picked the job up. The
            # ingress=True flag pairs this span 1:1 with the sampled
            # mint, the live/offline reconciliation hook.
            now_wall = time.time()
            tracing.hop(
                "router.queue", (job.ctx,), job.t_wall,
                max(0.0, now_wall - job.t_wall), ingress=True,
                endpoint=job.endpoint,
            )
        path = f"/v1/{job.endpoint}"
        tried: set = set()
        attempts = 1 + max(0, self.retries)
        shed_reasons: List[str] = []
        down: List[str] = []
        deadline = job.t0 + self.request_timeout
        while len(tried) < attempts:
            target, why = self._acquire(tried, deadline)
            if target is None:
                if why == "timeout":
                    # every eligible replica stayed at its in-flight
                    # budget for the whole deadline — overload, not
                    # outage: shed 503-style
                    shed_reasons.append("router_timeout")
                break
            tried.add(target.url)
            t_post_wall = time.time() if job.ctx is not None else 0.0
            try:
                status, data = self._post(target, path, job.body)
            except _ResponseTimeout as e:
                # the replica is healthy but did not answer in time —
                # 504-analog: no eviction (one slow request must not
                # bounce a live replica), no retry (ambiguous: the
                # request may still execute)
                st.record_error()
                self._count("failed")
                _emit("router", "failed", replica=target.url,
                      endpoint=job.endpoint, reason="timeout")
                job.future.set_exception(ServeError(
                    f"replica {target.url} did not answer "
                    f"{job.endpoint!r} within {self.request_timeout}s: {e}"
                ))
                return
            except _InFlightDrop as e:
                self._evict(target, "in_flight_drop")
                if self.retry_in_flight:
                    self._count("retries")
                    _emit("router", "retry", replica=target.url,
                          endpoint=job.endpoint, reason="in_flight_drop")
                    continue
                st.record_error()
                self._count("failed")
                _emit("router", "failed", replica=target.url,
                      endpoint=job.endpoint, reason="in_flight_drop")
                job.future.set_exception(ReplicaDownError(
                    f"replica {target.url} dropped the connection with "
                    f"the request in flight: {e}"
                ))
                return
            except Exception:
                # connect-level failure: the replica never saw the
                # request — evict it and retry a sibling
                self._evict(target, "connect")
                down.append(target.url)
                self._count("retries")
                _emit("router", "retry", replica=target.url,
                      endpoint=job.endpoint, reason="connect")
                continue
            finally:
                self._release(target)
            if status == 200:
                try:
                    ok, result, _reason = wire.decode_response(data)
                    if not ok:
                        raise wire.WireError(
                            f"200 response carried ok=false: {result}"
                        )
                except wire.WireError as e:
                    st.record_error()
                    self._count("failed")
                    _emit("router", "failed", replica=target.url,
                          endpoint=job.endpoint, reason="wire")
                    job.future.set_exception(e)
                    return
                dt = time.perf_counter() - job.t0
                st.record_done(dt)
                self._count("requests")
                _emit("router", "route", replica=target.url,
                      endpoint=job.endpoint, seconds=dt)
                if job.ctx is not None:
                    # router.post: the winning HTTP round trip (retries
                    # that shed/failed are visible as serve_net events)
                    tracing.hop(
                        "router.post", (job.ctx,), t_post_wall,
                        max(0.0, time.time() - t_post_wall),
                        endpoint=job.endpoint, replica=target.url,
                    )
                job.future.set_result(result)
                return
            ok, message, reason = _safe_decode(data)
            if status == 503:
                # sticky degradation: a shed (queue_full/memory/
                # draining/closed) retries siblings before failing
                shed_reasons.append(reason or "shed")
                _emit("router", "retry", replica=target.url,
                      endpoint=job.endpoint, reason=reason or "shed")
                self._count("retries")
                continue
            # 4xx/5xx: deterministic upstream verdict — do not retry
            st.record_error()
            self._count("failed")
            _emit("router", "failed", replica=target.url,
                  endpoint=job.endpoint, reason=reason or str(status))
            exc: Exception
            if status == 400 or status == 404:
                exc = ValueError(message or f"HTTP {status}")
            else:
                exc = ServeError(
                    f"replica {target.url} answered HTTP {status}: "
                    f"{message}"
                )
            job.future.set_exception(exc)
            return
        # retry ladder exhausted
        if shed_reasons:
            st.record_shed()
            self._count("shed")
            _emit("router", "shed", endpoint=job.endpoint,
                  reasons=shed_reasons[:4])
            job.future.set_exception(ServerOverloadedError(
                f"every tried replica shed the request "
                f"(reasons: {shed_reasons})",
                reason=shed_reasons[-1], endpoint=job.endpoint,
            ))
        else:
            st.record_error()
            self._count("failed")
            _emit("router", "failed", endpoint=job.endpoint,
                  reason="no_replicas")
            job.future.set_exception(ReplicaDownError(
                f"no healthy replica for {job.endpoint!r} "
                f"(down: {down or [t.url for t in self._targets]})"
            ))

    # -- background poll -----------------------------------------------------

    # one keep-alive poll connection per replica (poll-thread-only +
    # close(); default 25 ms ticks would otherwise open ~40 TCP
    # connections per replica per second)
    def _poll_conn(self, target: _Target, fresh: bool = False):
        conn = self._poll_conns.get(target.url)
        if fresh and conn is not None:
            try:
                conn.close()
            except Exception:
                pass
            conn = None
        if conn is None:
            conn = _NoDelayConnection(
                target.host, target.port, timeout=_POLL_TIMEOUT
            )
            self._poll_conns[target.url] = conn
        return conn

    def _poll_get(self, target: _Target, path: str):
        """GET over the cached poll connection → ``(status, body)``;
        one fresh-connection resend when a reused conn died idle."""
        conn = self._poll_conn(target)
        reused = conn.sock is not None
        try:
            conn.request("GET", path)
            resp = conn.getresponse()
            return resp.status, resp.read()
        except Exception:
            try:
                conn.close()
            except Exception:
                pass
            if not reused:
                self._poll_conns.pop(target.url, None)
                raise
            conn = self._poll_conn(target, fresh=True)
            try:
                conn.request("GET", path)
                resp = conn.getresponse()
                return resp.status, resp.read()
            except Exception:
                try:
                    conn.close()
                except Exception:
                    pass
                self._poll_conns.pop(target.url, None)
                raise

    def _poll_one(self, target: _Target) -> None:
        try:
            if target.up:
                _status, body = self._poll_get(target, "/stats")
                payload = json.loads(body.decode())
                with self._state:
                    target.polled_pending = int(
                        payload.get("pending", payload.get("queue_depth", 0))
                        or 0
                    )
                    target.poll_fails = 0
            else:
                status, _body = self._poll_get(target, "/healthz")
                if status == 200:
                    self._readd(target)
        except Exception:
            if target.up:
                with self._state:
                    target.poll_fails += 1
                    fails = target.poll_fails
                # two consecutive poll misses = gone (a single slow
                # poll under load must not bounce a healthy replica)
                if fails >= 2:
                    self._evict(target, "health_poll")

    def _poll_loop(self) -> None:
        while not self._closed:
            for target in list(self._targets):
                if self._closed:
                    return
                self._poll_one(target)
            time.sleep(self.poll_interval)


def _safe_decode(data: bytes) -> Tuple[bool, str, str]:
    try:
        ok, message, reason = wire.decode_response(data)
        return ok, str(message), reason
    except Exception:
        return False, data[:200].decode("utf-8", "replace"), ""
