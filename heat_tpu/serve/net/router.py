"""Least-loaded request router over a set of replica HTTP fronts.

The horizontal half of the ISSUE 12 tentpole: N replica processes (each
a :class:`~heat_tpu.serve.Server` behind :class:`~.transport.HttpFront`)
scale QPS past the single-process ceiling, and the router is the piece
that makes them look like ONE server to a client:

* **least-loaded dispatch** — a poll thread refreshes every healthy
  replica's ``/stats`` each ``HEAT_TPU_SERVE_NET_POLL_MS``; the dispatch
  score is the polled backlog (admitted-but-unresolved ``pending``)
  plus this router's own in-flight count to that replica (fresher than
  any poll). Requests go to the minimum-score replica. An optional
  ``max_inflight`` caps concurrent requests per replica (the client
  half of the per-replica admission-budget discipline — the analog of a
  proxy's per-backend circuit-breaker concurrency cap): workers block
  for a free slot instead of piling onto a busy replica, and a request
  whose deadline passes while every slot stays taken sheds 503-style
  (``router_timeout``).
* **sticky degradation** — a 503 shed from one replica (queue_full /
  memory / draining) retries up to ``HEAT_TPU_SERVE_NET_RETRIES``
  *siblings* before the client sees :class:`ServerOverloadedError`:
  one overloaded (or draining) replica degrades to "the others absorb
  it", not to client-visible failure. The shedding replica is NOT
  evicted — it is alive and telling us so.
* **health eviction + re-add** — a connection-level failure evicts the
  replica from rotation (its queued work re-routes); the poll thread
  keeps probing ``/healthz`` and re-adds it the moment it answers —
  a drained-and-restarted (or crash-restored) replica rejoins without
  router restart.
* **failure semantics** — a connect-refused replica never saw the
  request: safe to retry a sibling. A connection that drops *after* the
  request was sent is ambiguous (it may have executed), so by default
  those fail with :class:`ReplicaDownError` — the bench chaos phase's
  "killing a replica loses only its in-flight requests" contract.
  ``retry_in_flight=True`` opts into at-least-once re-dispatch for
  callers that know their endpoints are pure.

The client surface mirrors the in-process server — ``submit()`` returns
a future, ``predict()`` blocks, ``stats()["endpoints"]`` carries the
same per-endpoint latency aggregates (:class:`~..metrics.EndpointStats`)
— so the PR 8 open-loop load generator drives a router and a local
server through the identical code path (the scaling artifact's
apples-to-apples requirement).

ISSUE 20 adds the multi-tenant robustness dimensions:

* **priority classes + weighted-fair admission** — requests carry a
  priority class (per-endpoint via ``endpoint_priorities`` /
  ``set_priority``, or per-request via ``submit(priority=...)``);
  workers drain the queue by smooth weighted round-robin over the
  configured class weights (``HEAT_TPU_SERVE_PRIORITY_WEIGHTS``), so a
  bulk tenant at any offered rate cannot starve a latency tenant — and
  neither can be starved below its weight share. With
  ``HEAT_TPU_SERVE_PRIORITY_QUEUE_MAX`` bounding the router queue, the
  shed order is priority-aware: the newest job of the lowest-weight
  queued class sheds first (``priority_shed``), and the degradation
  ladder follows — a 503-shed bottom-priority request yields its
  sibling retries whenever higher-priority work is waiting.
* **hedged retries** — with ``HEAT_TPU_HEDGE_ENABLE``, a first-attempt
  request that has not answered within the hedge delay (explicit
  ``HEAT_TPU_HEDGE_DELAY_MS``, else the endpoint's observed p95 once
  ``HEAT_TPU_HEDGE_MIN_SAMPLES`` samples exist) is duplicated to a
  sibling replica; the first HTTP response wins and the loser is
  canceled by closing its connection. ``HEAT_TPU_HEDGE_MAX_FRACTION``
  hard-caps hedges relative to completed requests. Endpoints are pure
  (restored estimators), so the duplicate execution is harmless — the
  same property ``retry_in_flight`` relies on.
* **hardened ops plane** — ``scrape_metrics`` / ``scrape_traces`` /
  ``clock_sync`` retry once on transient connection resets (the
  resilience classifier's verdict) and mark the target ``suspect``
  (flag in ``stats()``, ``suspect`` event) instead of silently
  returning a ``None`` entry; any successful scrape or poll clears the
  flag.
"""

from __future__ import annotations

import http.client
import json
import queue as _queue_mod
import threading
import time
from collections import deque
from concurrent.futures import Future
from queue import Empty
from typing import Dict, List, Optional, Sequence, Tuple, Union
from urllib.parse import urlparse

import numpy as np

from heat_tpu import _knobs as knobs

from .. import tracing
from ..admission import ServeError, ServerClosedError, ServerOverloadedError
from ..metrics import EndpointStats
from . import wire
from .events import emit as _emit

__all__ = ["Router", "ReplicaDownError"]

_POLL_TIMEOUT = 2.0  # seconds per /stats / /healthz probe


class _NoDelayConnection(http.client.HTTPConnection):
    """HTTPConnection with Nagle disabled — request/response pairs are
    single small write-read exchanges, exactly the pattern Nagle +
    delayed ACK stalls (measured: 33 ms loopback round trips without
    this, ~3 ms with)."""

    def connect(self):
        super().connect()
        import socket as _socket

        self.sock.setsockopt(
            _socket.IPPROTO_TCP, _socket.TCP_NODELAY, 1
        )


class ReplicaDownError(ServeError):
    """No healthy replica could (safely) serve the request: every
    candidate was down, or the chosen replica's connection dropped with
    the request in flight (``retry_in_flight=False``)."""


class _Target:
    """One replica as the router sees it."""

    __slots__ = ("url", "host", "port", "up", "inflight", "polled_pending",
                 "poll_fails", "evictions", "suspect")

    def __init__(self, url: str):
        parsed = urlparse(url if "//" in url else f"http://{url}")
        if not parsed.hostname or not parsed.port:
            raise ValueError(f"replica url needs host:port, got {url!r}")
        self.url = f"http://{parsed.hostname}:{parsed.port}"
        self.host = parsed.hostname
        self.port = parsed.port
        self.up = True
        self.inflight = 0
        self.polled_pending = 0
        self.poll_fails = 0
        self.evictions = 0
        self.suspect = False  # ops scrape failed after retry (ISSUE 20)

    def score(self) -> int:
        # routing state is guarded by the router's one Condition; reads
        # of two ints race only with themselves (shed tolerance: the
        # score is a heuristic, not an allocator)
        return self.polled_pending + self.inflight


class _Job:
    __slots__ = ("endpoint", "body", "future", "t0", "t_wall", "ctx",
                 "cls", "weight")

    def __init__(self, endpoint: str, body: bytes, ctx=None,
                 cls: str = "default", weight: float = 1.0):
        self.endpoint = endpoint
        self.body = body
        self.future: Future = Future()
        self.t0 = time.perf_counter()
        # wall twin of t0, trace-only (spans anchor on wall clock)
        self.t_wall = time.time() if ctx is not None else 0.0
        self.ctx = ctx  # Optional[tracing.TraceContext]
        self.cls = cls          # priority class (ISSUE 20)
        self.weight = weight    # the class's configured weight


class _FairQueue:
    """Weighted-fair multi-class FIFO (ISSUE 20): jobs queue per
    priority class; :meth:`get` drains classes by smooth weighted
    round-robin over the configured weights, so over any window each
    backlogged class is served in proportion to its weight — a
    high-rate bulk class cannot starve a latency class, and the bulk
    class still receives its weight share. With a single class (no
    priorities configured) this degenerates to exactly the old FIFO.
    Worker-shutdown sentinels (``None``) ride a control lane served
    before any job."""

    def __init__(self, weights: Dict[str, float]):
        self._cv = threading.Condition()
        self._weights = {k: float(v) for k, v in weights.items()}
        self._classes: Dict[str, deque] = {}
        self._credit: Dict[str, float] = {}
        self._control: deque = deque()
        self._size = 0

    def weight(self, cls: str) -> float:
        return self._weights.get(cls, 1.0)

    def put(self, job) -> None:
        with self._cv:
            if job is None:
                self._control.append(None)
            else:
                self._classes.setdefault(job.cls, deque()).append(job)
                self._size += 1
            self._cv.notify()

    def qsize(self) -> int:
        return self._size  # racy read, same tolerance as Queue.qsize

    def _pick_locked(self):
        live = [c for c, q in self._classes.items() if q]
        if not live:
            return None
        if len(live) == 1:
            chosen = live[0]
        else:
            # smooth weighted round-robin: every nonempty class earns
            # its weight in credit, the richest class is served and
            # pays the round's total — proportions converge to the
            # weights with bounded per-class latency
            total = 0.0
            chosen = None
            best = None
            for c in sorted(live):  # sorted: deterministic tie-break
                w = self.weight(c)
                self._credit[c] = self._credit.get(c, 0.0) + w
                total += w
                if best is None or self._credit[c] > best:
                    best = self._credit[c]
                    chosen = c
            self._credit[chosen] -= total
        job = self._classes[chosen].popleft()
        self._size -= 1
        return job

    def get(self):
        with self._cv:
            while True:
                if self._control:
                    return self._control.popleft()
                job = self._pick_locked()
                if job is not None:
                    return job
                self._cv.wait()

    def get_nowait(self):
        with self._cv:
            if self._control:
                return self._control.popleft()
            job = self._pick_locked()
            if job is None:
                raise Empty
            return job

    def shed_lowest(self, below_weight: float):
        """Pop (to shed) the NEWEST job of the lowest-weight nonempty
        class with weight strictly below ``below_weight`` — the
        priority-aware shed order. ``None`` when every queued job is at
        or above that priority."""
        with self._cv:
            best_c = None
            best_w = None
            for c, q in self._classes.items():
                if not q:
                    continue
                w = self.weight(c)
                if w >= below_weight:
                    continue
                if best_w is None or w < best_w:
                    best_w, best_c = w, c
            if best_c is None:
                return None
            job = self._classes[best_c].pop()  # newest arrival sheds first
            self._size -= 1
            return job

    def max_queued_weight(self) -> Optional[float]:
        """Highest weight among classes with queued work (the
        priority-yield probe)."""
        with self._cv:
            ws = [self.weight(c) for c, q in self._classes.items() if q]
        return max(ws) if ws else None


class _InFlightDrop(Exception):
    """Connection died after the request was on the wire (internal)."""


class _ResponseTimeout(Exception):
    """The replica accepted the request but did not answer within the
    socket timeout (internal). NOT an outage: the replica is healthy,
    just slow — it must not be evicted, and the request must not be
    blindly retried (it may still execute)."""


class Router:
    """Least-loaded HTTP router over replica fronts (module docstring
    has the policy). ``targets`` is a sequence of replica base URLs
    (``http://host:port`` or ``host:port``) or an object with a
    ``urls()`` method (:class:`~.pool.ReplicaPool`)."""

    def __init__(
        self,
        targets: Union[Sequence[str], object],
        *,
        retries: Optional[int] = None,
        poll_ms: Optional[float] = None,
        workers: Optional[int] = None,
        request_timeout: float = 30.0,
        retry_in_flight: bool = False,
        max_inflight: Optional[int] = None,
        slos: Optional[Sequence] = None,
        priorities: Optional[Dict[str, float]] = None,
        endpoint_priorities: Optional[Dict[str, str]] = None,
        priority_queue_max: Optional[int] = None,
        hedge: Optional[bool] = None,
        hedge_delay_ms: Optional[float] = None,
        hedge_max_fraction: Optional[float] = None,
        hedge_min_samples: Optional[int] = None,
    ):
        if hasattr(targets, "urls"):
            targets = targets.urls()
        self._targets: List[_Target] = [_Target(u) for u in targets]
        if not self._targets:
            raise ValueError("router needs at least one replica url")
        # per-replica in-flight budget (the client half of the bounded
        # per-replica admission discipline): a worker holding a request
        # BLOCKS for a slot rather than piling more concurrency onto a
        # busy replica. None = unlimited.
        self.max_inflight = (
            None if max_inflight is None else max(1, int(max_inflight))
        )
        self._state = threading.Condition()
        self.retries = int(
            retries if retries is not None
            else knobs.get("HEAT_TPU_SERVE_NET_RETRIES")
        )
        poll_ms = (
            poll_ms if poll_ms is not None
            else knobs.get("HEAT_TPU_SERVE_NET_POLL_MS")
        )
        self.poll_interval = max(0.001, float(poll_ms) / 1e3)
        self.request_timeout = float(request_timeout)
        self.retry_in_flight = bool(retry_in_flight)
        n_workers = (
            workers if workers is not None
            else max(8, 4 * len(self._targets))
        )
        self._stats: Dict[str, EndpointStats] = {}
        self._stats_lock = threading.Lock()
        # priority classes + weighted-fair admission (ISSUE 20)
        self._weights = (
            dict(priorities) if priorities is not None
            else _parse_weights(knobs.get("HEAT_TPU_SERVE_PRIORITY_WEIGHTS"))
        )
        self.endpoint_priorities = dict(endpoint_priorities or {})
        self.priority_queue_max = int(
            priority_queue_max if priority_queue_max is not None
            else knobs.get("HEAT_TPU_SERVE_PRIORITY_QUEUE_MAX")
        )
        self._queue = _FairQueue(self._weights)
        self._class_counts: Dict[str, Dict[str, int]] = {}
        # hedged retries (ISSUE 20)
        self.hedge = bool(
            hedge if hedge is not None
            else knobs.get("HEAT_TPU_HEDGE_ENABLE")
        )
        self.hedge_delay_ms = float(
            hedge_delay_ms if hedge_delay_ms is not None
            else knobs.get("HEAT_TPU_HEDGE_DELAY_MS")
        )
        self.hedge_max_fraction = float(
            hedge_max_fraction if hedge_max_fraction is not None
            else knobs.get("HEAT_TPU_HEDGE_MAX_FRACTION")
        )
        self.hedge_min_samples = int(
            hedge_min_samples if hedge_min_samples is not None
            else knobs.get("HEAT_TPU_HEDGE_MIN_SAMPLES")
        )
        self._closed = False
        # ISSUE 17: declared SLOs (telemetry.cluster.SLO) + the rolling
        # scrape-snapshot ring cluster_summary() windows burn rates over
        self.slos = list(slos) if slos else []
        self.window_start = time.monotonic()
        self._slo_snaps: List[tuple] = []  # (mono, scrape state)
        self._slo_lock = threading.Lock()
        self._counts = {"requests": 0, "retries": 0, "evictions": 0,
                        "readds": 0, "failed": 0, "shed": 0,
                        "hedges": 0, "hedge_wins": 0, "priority_sheds": 0}
        self._counts_lock = threading.Lock()
        self._local = threading.local()  # per-worker connection cache
        self._poll_conns: Dict[str, http.client.HTTPConnection] = {}
        self._workers = [
            threading.Thread(
                target=self._work, name=f"heat_tpu.serve.net.router-{i}",
                daemon=True,
            )
            for i in range(int(n_workers))
        ]
        for t in self._workers:
            t.start()
        self._poll_thread = threading.Thread(
            target=self._poll_loop, name="heat_tpu.serve.net.router-poll",
            daemon=True,
        )
        self._poll_thread.start()

    # -- client surface ------------------------------------------------------

    def submit(
        self, name: str, payload, *, priority: Optional[str] = None,
    ) -> Future:
        """Enqueue one request; the future resolves to the result rows,
        or to :class:`ServerOverloadedError` (every candidate shed, or
        the bounded router queue shed it by priority),
        :class:`ReplicaDownError` (no healthy replica / in-flight drop),
        or the upstream error. ``priority`` overrides the endpoint's
        configured class for this one request."""
        if self._closed:
            raise ServerClosedError("router is closed")
        # trace ingress (ISSUE 17): the sampling verdict is made HERE,
        # once, and rides the wire — replicas adopt, never re-mint
        ctx = tracing.mint("router.submit")
        cls = (
            priority or self.endpoint_priorities.get(name) or "default"
        )
        job = _Job(
            name,
            wire.encode_request(
                np.asarray(payload),
                trace=ctx.to_wire() if ctx is not None else None,
            ),
            ctx,
            cls=cls,
            weight=self._queue.weight(cls),
        )
        self._ep_stats(name).record_request(
            int(np.asarray(payload).shape[0])
            if np.asarray(payload).ndim else 1
        )
        self._class_count(cls, "submitted")
        # bounded weighted-fair admission: past the queue bound, the
        # NEWEST job of the lowest-weight queued class sheds first; an
        # incoming job at (or below) the bottom queued priority sheds
        # itself — shed order is priority-aware, never FIFO-blind
        if (
            self.priority_queue_max > 0
            and self._queue.qsize() >= self.priority_queue_max
        ):
            victim = self._queue.shed_lowest(job.weight)
            if victim is None:
                self._shed_priority(job)
                return job.future
            self._shed_priority(victim)
        self._queue.put(job)
        return job.future

    def set_priority(self, endpoint: str, cls: str) -> None:
        """Bind ``endpoint`` to priority class ``cls`` (per-request
        ``submit(priority=...)`` still overrides)."""
        self.endpoint_priorities[str(endpoint)] = str(cls)

    def _class_count(self, cls: str, key: str, n: int = 1) -> None:
        with self._counts_lock:
            row = self._class_counts.setdefault(
                cls, {"submitted": 0, "routed": 0, "shed": 0}
            )
            row[key] += n

    def _shed_priority(self, job: _Job) -> None:
        """Resolve one job as priority-shed (the bounded-queue path)."""
        st = self._ep_stats(job.endpoint)
        st.record_shed()
        self._count("shed")
        self._count("priority_sheds")
        self._class_count(job.cls, "shed")
        _emit("router", "priority_shed", endpoint=job.endpoint,
              cls=job.cls)
        try:
            job.future.set_exception(ServerOverloadedError(
                f"router queue is full ({self.priority_queue_max} "
                f"pending); class {job.cls!r} (weight "
                f"{job.weight:g}) shed by priority order",
                reason="priority_shed", endpoint=job.endpoint,
            ))
        except Exception:
            pass

    def predict(self, name: str, payload, timeout: Optional[float] = 30.0):
        """Synchronous convenience: ``submit(...).result(timeout)``."""
        return self.submit(name, payload).result(timeout)

    def add_target(self, url: str) -> None:
        """Join a new replica into the rotation (scale-up / re-add of a
        freshly spawned process)."""
        t = _Target(url)
        with self._state:
            if any(x.url == t.url for x in self._targets):
                return
            self._targets.append(t)
            self._state.notify_all()

    def remove_target(self, url: str) -> bool:
        """Administratively take a replica out of rotation (ISSUE 20:
        scale-down / dead-replica replacement). Unlike eviction the
        poll thread stops probing it — it will not be re-added. Returns
        whether the url was present. In-flight requests to it finish on
        their own (the drain half of scale-down is the pool's SIGTERM)."""
        canonical = _Target(url).url
        removed = None
        with self._state:
            for i, t in enumerate(self._targets):
                if t.url == canonical:
                    removed = self._targets.pop(i)
                    break
            self._state.notify_all()
        if removed is None:
            return False
        conn = self._poll_conns.pop(canonical, None)
        if conn is not None:
            try:
                conn.close()
            except Exception:
                pass
        _emit("router", "detach", replica=canonical)
        return True

    def stats(self) -> dict:
        """Loadgen-compatible aggregates: per-endpoint latency stats
        (client-observed submit→resolve), per-replica routing state, and
        the router counters."""
        with self._counts_lock:
            counts = dict(self._counts)
            class_counts = {
                c: dict(row) for c, row in self._class_counts.items()
            }
        with self._stats_lock:  # first-seen endpoints insert concurrently
            stats_items = list(self._stats.items())
        return {
            "endpoints": {n: s.snapshot() for n, s in stats_items},
            "queue_depth": self._queue.qsize(),
            # scrape contract (ISSUE 17): cumulative-since-window_start
            # counters + a monotonic stamp, so two scrapes derive rates
            # on their own side without racing any reset
            "window_start": self.window_start,
            "mono": time.monotonic(),
            "slos": [s.describe() for s in self.slos],
            "replicas": {
                t.url: {
                    "up": t.up,
                    "score": t.score(),
                    "inflight": t.inflight,
                    "polled_pending": t.polled_pending,
                    "evictions": t.evictions,
                    "suspect": t.suspect,
                }
                for t in list(self._targets)
            },
            "router": counts,
            "priority": {
                "weights": dict(self._weights),
                "queue_max": self.priority_queue_max,
                "classes": {
                    c: dict(row) for c, row in class_counts.items()
                },
            },
            "closed": self._closed,
        }

    # -- fleet observability (ISSUE 17) --------------------------------------

    def _ops_get_once(self, target: _Target, path: str):
        """GET over a dedicated short-lived connection → ``(status,
        body)``. The keep-alive poll connections are poll-thread-only;
        observability scrapes run on caller threads and must not share
        them."""
        conn = _NoDelayConnection(
            target.host, target.port, timeout=_POLL_TIMEOUT
        )
        try:
            conn.request("GET", path)
            resp = conn.getresponse()
            return resp.status, resp.read()
        finally:
            conn.close()

    def _ops_get(self, target: _Target, path: str):
        """Hardened ops-plane GET (ISSUE 20): one retry when the
        resilience classifier calls the failure transient (connection
        resets/aborts — a mid-scrape restart, not an outage); a target
        that still fails is marked ``suspect`` (flag + event) so the
        failure is never a silent ``None`` entry. Success clears the
        flag."""
        from ...resilience.guard import classify

        try:
            out = self._ops_get_once(target, path)
        except Exception as e:
            if classify(e) != "transient":
                self._mark_suspect(target, path, e)
                raise
            try:
                out = self._ops_get_once(target, path)
            except Exception as e2:
                self._mark_suspect(target, path, e2)
                raise
        self._clear_suspect(target)
        return out

    def _mark_suspect(self, target: _Target, path: str, exc) -> None:
        with self._state:
            already = target.suspect
            target.suspect = True
        if not already:
            _emit("router", "suspect", replica=target.url, path=path,
                  error=repr(exc)[:200])

    def _clear_suspect(self, target: _Target) -> None:
        if target.suspect:
            with self._state:
                target.suspect = False

    def scrape_metrics(self) -> Dict[str, Optional[dict]]:
        """Pull ``GET /metrics`` from every replica → ``{url: payload}``
        (``None`` for replicas that failed to answer — merged summaries
        report them as ``scrape_failures``, never silently drop them)."""
        out: Dict[str, Optional[dict]] = {}
        for t in list(self._targets):
            try:
                status, body = self._ops_get(t, "/metrics")
                out[t.url] = (
                    json.loads(body.decode()) if status == 200 else None
                )
            except Exception:
                out[t.url] = None
        return out

    def scrape_traces(self) -> Dict[str, Optional[dict]]:
        """Pull ``GET /trace`` (each replica's in-memory telemetry
        events) → ``{url: {"pid", "wall", "events"} | None}``."""
        out: Dict[str, Optional[dict]] = {}
        for t in list(self._targets):
            try:
                status, body = self._ops_get(t, "/trace")
                out[t.url] = (
                    json.loads(body.decode()) if status == 200 else None
                )
            except Exception:
                out[t.url] = None
        return out

    def clock_sync(self, probes: int = 3) -> Dict[str, dict]:
        """Calibrate each replica's wall-clock offset against this process
        via the ``/healthz`` round trip: of ``probes`` exchanges on one
        keep-alive connection, take the minimum-RTT sample and estimate
        ``offset = remote_wall - rtt_midpoint`` with ``uncertainty =
        rtt / 2`` (the remote stamp happened somewhere inside the round
        trip). Returns ``{url: {"offset", "uncertainty", "rtt", "pid"}}``
        — pre-17 replicas (no ``wall`` in /healthz) are omitted."""
        from ...resilience.guard import classify

        def _probe(t: _Target):
            best = None
            pid = None
            conn = _NoDelayConnection(
                t.host, t.port, timeout=_POLL_TIMEOUT
            )
            try:
                for _ in range(max(1, int(probes))):
                    a = time.time()
                    conn.request("GET", "/healthz")
                    resp = conn.getresponse()
                    body = resp.read()
                    b = time.time()
                    payload = json.loads(body.decode())
                    wall = payload.get("wall")
                    if wall is None:
                        break
                    pid = payload.get("pid")
                    rtt = b - a
                    if best is None or rtt < best[0]:
                        best = (rtt, float(wall) - (a + b) / 2.0)
            finally:
                conn.close()
            return best, pid

        out: Dict[str, dict] = {}
        for t in list(self._targets):
            # same hardening as _ops_get: one retry on a transient
            # reset, suspect flag on persistent failure — never a
            # silently missing calibration entry
            try:
                best, pid = _probe(t)
            except Exception as e:
                if classify(e) != "transient":
                    self._mark_suspect(t, "/healthz", e)
                    continue
                try:
                    best, pid = _probe(t)
                except Exception as e2:
                    self._mark_suspect(t, "/healthz", e2)
                    continue
            self._clear_suspect(t)
            if best is not None:
                out[t.url] = {
                    "offset": best[1],
                    "uncertainty": best[0] / 2.0,
                    "rtt": best[0],
                    "pid": pid,
                }
        return out

    def cluster_summary(self) -> dict:
        """Scrape every replica and return the fleet-merged report
        (:func:`heat_tpu.telemetry.cluster.summarize_cluster`): fleet
        QPS + exactly-merged p50/p95/p99 per endpoint, per-replica
        occupancy/compile/version-lag rows, and — when this router
        declares SLOs — the ``slo`` burn-rate block. Burn windows roll
        over ``HEAT_TPU_SLO_WINDOW_S``: each call diffs against the
        scrape snapshot taken about one window ago (the first call
        covers each replica's lifetime)."""
        from ...telemetry import cluster as _cluster

        scrapes = self.scrape_metrics()
        now = time.monotonic()
        try:
            window_s = float(knobs.get("HEAT_TPU_SLO_WINDOW_S"))
        except (TypeError, ValueError):
            window_s = 60.0
        with self._slo_lock:
            cutoff = now - max(0.001, window_s)
            # keep the newest snapshot at/older than the cutoff as the
            # window's far edge; everything older is garbage
            while len(self._slo_snaps) >= 2 and self._slo_snaps[1][0] <= cutoff:
                self._slo_snaps.pop(0)
            prev = self._slo_snaps[0][1] if self._slo_snaps else None
        summary = _cluster.summarize_cluster(
            scrapes, slos=self.slos, prev_state=prev,
            router_stats=self.stats(),
        )
        with self._slo_lock:
            self._slo_snaps.append((now, summary["state"]))
        return summary

    def check_slos(self) -> List[dict]:
        """One SLO accounting pass: :meth:`cluster_summary`'s ``slo``
        block, with an ``slo_burn`` telemetry event emitted for every
        breach (burn rate above ``HEAT_TPU_SLO_BURN_THRESHOLD``) — the
        scale-up trigger signal ROADMAP item 4 consumes."""
        rows = self.cluster_summary().get("slo", [])
        for row in rows:
            if row.get("breach"):
                _emit(
                    "slo", "slo_burn",
                    endpoint=row["endpoint"],
                    burn_rate=row["burn_rate"],
                    threshold=row["threshold"],
                    window_requests=row["window_requests"],
                    window_seconds=row["window_seconds"],
                )
        return rows

    def prometheus_text(self) -> str:
        """The merged fleet view in Prometheus text exposition format
        (scrape the router once instead of N replicas)."""
        from ...telemetry import cluster as _cluster

        return _cluster.prometheus_text(self.cluster_summary())

    def export_cluster_trace(self, path: str) -> str:
        """Export ONE merged Perfetto trace: this router's events plus
        every replica's (``GET /trace``), clock-offset corrected via the
        ``/healthz`` calibration, pid = replica, one fleet-wide t=0
        (:func:`heat_tpu.telemetry.cluster.export_merged_trace`)."""
        from ...telemetry import cluster as _cluster

        return _cluster.export_merged_trace(self, path)

    def close(self) -> None:
        """Stop workers + poll thread; fail queued requests with
        :class:`ServerClosedError`. Idempotent."""
        if self._closed:
            return
        self._closed = True
        with self._state:
            self._state.notify_all()  # wake workers blocked on a slot
        for _ in self._workers:
            self._queue.put(None)
        for t in self._workers:
            t.join(5.0)
        self._poll_thread.join(5.0)
        for conn in self._poll_conns.values():
            try:
                conn.close()
            except Exception:
                pass
        self._poll_conns.clear()
        while True:
            try:
                job = self._queue.get_nowait()
            except Empty:
                break
            if job is not None:
                try:
                    job.future.set_exception(
                        ServerClosedError("router closed with request "
                                          "pending")
                    )
                except Exception:
                    pass

    def __enter__(self) -> "Router":
        return self

    def __exit__(self, *exc) -> bool:
        self.close()
        return False

    # -- internals -----------------------------------------------------------

    def _ep_stats(self, name: str) -> EndpointStats:
        st = self._stats.get(name)
        if st is None:
            with self._stats_lock:
                st = self._stats.setdefault(name, EndpointStats(name))
        return st

    def _count(self, key: str, n: int = 1) -> None:
        with self._counts_lock:
            self._counts[key] += n

    def _pick_locked(self, exclude: set):
        """(best-free-target, any-up-but-at-budget) under ``_state``."""
        best, best_score, busy = None, None, False
        for t in self._targets:
            if not t.up or t.url in exclude:
                continue
            if (
                self.max_inflight is not None
                and t.inflight >= self.max_inflight
            ):
                busy = True
                continue
            s = t.score()
            if best_score is None or s < best_score:
                best, best_score = t, s
        return best, busy

    def _acquire(self, exclude: set, deadline: float):
        """Claim an in-flight slot on the least-loaded eligible replica;
        blocks while every eligible replica is at its in-flight budget.
        Returns ``(target, None)``, or ``(None, "down")`` when no healthy
        replica exists (fail fast), or ``(None, "timeout")`` when the
        request's deadline passed while waiting for a slot."""
        with self._state:
            while True:
                best, busy = self._pick_locked(exclude)
                if best is not None:
                    best.inflight += 1
                    return best, None
                if not busy or self._closed:
                    return None, "down"
                if time.perf_counter() >= deadline:
                    return None, "timeout"
                self._state.wait(
                    max(0.001, min(0.1, deadline - time.perf_counter()))
                )

    def _release(self, target: _Target) -> None:
        with self._state:
            target.inflight -= 1
            self._state.notify()

    def _try_acquire(self, exclude: set) -> Optional[_Target]:
        """Non-blocking slot claim (the hedge arm): the least-loaded
        eligible replica, or ``None`` — a hedge must never queue behind
        the very congestion it is trying to route around."""
        with self._state:
            best, _busy = self._pick_locked(exclude)
            if best is not None:
                best.inflight += 1
            return best

    # -- hedged retries (ISSUE 20) -------------------------------------------

    def _hedge_delay_s(self, endpoint: str) -> Optional[float]:
        """Seconds to wait before duplicating a straggler: the explicit
        knob when set, else the endpoint's observed p95 once enough
        samples exist (``None`` = don't hedge yet)."""
        if self.hedge_delay_ms > 0:
            return self.hedge_delay_ms / 1e3
        snap = self._ep_stats(endpoint).snapshot().get("latency", {})
        if snap.get("count", 0) < self.hedge_min_samples:
            return None
        return snap.get("p95_s")

    def _hedge_budget_ok(self) -> bool:
        """Hard cap: hedges stay at/below ``hedge_max_fraction`` of
        completed requests (budget is earned by traffic — a cold router
        never hedges its first 1/fraction requests)."""
        with self._counts_lock:
            return (
                self._counts["hedges"] + 1
                <= self.hedge_max_fraction
                * max(1.0, float(self._counts["requests"]))
            )

    def _hedged_post(
        self, primary: _Target, path: str, job: _Job, delay_s: float,
        deadline: float,
    ):
        """POST to ``primary``; if no response lands within ``delay_s``,
        duplicate to the least-loaded sibling and take the FIRST HTTP
        response (any status — a fast 503 still wins and rides the
        normal retry ladder). The loser is canceled by closing its
        connection. Each arm runs on its own fresh connection (a shared
        keep-alive conn cannot be closed from another thread safely).

        Returns ``(status, body, winner_target)``. When every launched
        arm fails, re-raises the PRIMARY arm's failure under the
        dispatch taxonomy (ConnectionError-family / _InFlightDrop /
        _ResponseTimeout) so eviction/retry semantics are unchanged."""
        results: "_queue_mod.Queue" = _queue_mod.Queue()
        conns: Dict[str, _NoDelayConnection] = {}

        def _attempt(tag: str, tgt: _Target) -> None:
            conn = _NoDelayConnection(
                tgt.host, tgt.port, timeout=self.request_timeout
            )
            conns[tag] = conn
            sent = False
            try:
                conn.request(
                    "POST", path, body=job.body,
                    headers={"Content-Type": "application/json"},
                )
                sent = True
                resp = conn.getresponse()
                results.put((tag, tgt, "ok", (resp.status, resp.read())))
            except Exception as e:  # noqa: BLE001 — classified below
                if not sent:
                    kind = "conn"
                elif isinstance(e, TimeoutError):
                    kind = "timeout"
                else:
                    kind = "drop"
                results.put((tag, tgt, kind, e))
            finally:
                try:
                    conn.close()
                except Exception:
                    pass

        threading.Thread(
            target=_attempt, args=("primary", primary), daemon=True,
            name="heat_tpu.serve.net.router-hedge-primary",
        ).start()
        launched = {"primary"}
        hedge_target: Optional[_Target] = None
        first = None
        try:
            wait = max(0.0, min(delay_s, deadline - time.perf_counter()))
            try:
                first = results.get(timeout=wait)
            except Empty:
                pass
            if first is None and time.perf_counter() < deadline:
                # primary is straggling: duplicate to a sibling if one
                # has a free slot right now
                hedge_target = self._try_acquire({primary.url})
                if hedge_target is not None:
                    launched.add("hedge")
                    self._count("hedges")
                    _emit("router", "hedge", endpoint=job.endpoint,
                          primary=primary.url, sibling=hedge_target.url)
                    threading.Thread(
                        target=_attempt, args=("hedge", hedge_target),
                        daemon=True,
                        name="heat_tpu.serve.net.router-hedge-secondary",
                    ).start()
            failures: Dict[str, tuple] = {}
            received = 1 if first is not None else 0
            winner = None
            while winner is None and (
                first is not None or received < len(launched)
            ):
                if first is not None:
                    tag, tgt, kind, payload = first
                    first = None
                else:
                    try:
                        item = results.get(
                            timeout=max(
                                0.0, deadline - time.perf_counter()
                            )
                        )
                    except Empty:
                        break
                    received += 1
                    tag, tgt, kind, payload = item
                if kind == "ok":
                    winner = (tag, tgt, payload)
                else:
                    failures[tag] = (kind, payload)
            if winner is not None:
                tag, tgt, (status, data) = winner
                # first-wins: cancel the loser by closing its socket
                # (its thread errors out; the result is discarded)
                for other in launched - {tag} - set(failures):
                    oc = conns.get(other)
                    if oc is not None:
                        try:
                            oc.close()
                        except Exception:
                            pass
                if tag == "hedge":
                    self._count("hedge_wins")
                    _emit("router", "hedge_win", endpoint=job.endpoint,
                          replica=tgt.url)
                return status, data, tgt
            # no arm produced a response: surface the primary's failure
            # under the normal taxonomy (deadline with a silent primary
            # is the slow-not-dead case)
            kind, exc = failures.get("primary", (None, None))
            if kind == "conn":
                raise exc
            if kind == "drop":
                raise _InFlightDrop(repr(exc)) from exc
            raise _ResponseTimeout(
                f"no hedge arm answered within the deadline "
                f"({self.request_timeout}s)"
                if exc is None else repr(exc)
            ) from exc
        finally:
            if hedge_target is not None:
                self._release(hedge_target)

    def _evict(self, target: _Target, why: str) -> None:
        with self._state:
            if not target.up:
                return
            target.up = False
            target.evictions += 1
            target.poll_fails = 0
            self._state.notify_all()
        self._count("evictions")
        _emit("router", "evict", replica=target.url, reason=why)

    def _readd(self, target: _Target) -> None:
        with self._state:
            if target.up:
                return
            target.up = True
            target.polled_pending = 0
            self._state.notify_all()
        self._count("readds")
        _emit("router", "readd", replica=target.url)

    # one keep-alive connection per (worker thread, replica)
    def _conn(self, target: _Target, fresh: bool = False):
        cache = getattr(self._local, "conns", None)
        if cache is None:
            cache = self._local.conns = {}
        conn = cache.get(target.url)
        if fresh and conn is not None:
            try:
                conn.close()
            except Exception:
                pass
            conn = None
        if conn is None:
            conn = _NoDelayConnection(
                target.host, target.port, timeout=self.request_timeout
            )
            cache[target.url] = conn
        return conn

    def _post(self, target: _Target, path: str, body: bytes):
        """POST once; returns ``(status, body_bytes)``. Raises
        ``ConnectionError``-family when the request never made it onto
        an accepted connection (safe to retry a sibling),
        :class:`_InFlightDrop` when the connection died after the send
        (ambiguous — the request may have executed)."""
        conn = self._conn(target)
        reused = conn.sock is not None
        try:
            conn.request(
                "POST", path, body=body,
                headers={"Content-Type": "application/json"},
            )
        except Exception:
            conn.close()
            if not reused:
                raise  # fresh connect failed: replica is unreachable
            # keep-alive race: the server closed the idle conn under us
            # and the send never happened — one fresh-connection resend
            conn = self._conn(target, fresh=True)
            conn.request(
                "POST", path, body=body,
                headers={"Content-Type": "application/json"},
            )
        try:
            resp = conn.getresponse()
            data = resp.read()
            return resp.status, data
        except TimeoutError as e:  # socket.timeout: slow, not dead
            conn.close()
            raise _ResponseTimeout(repr(e)) from e
        except Exception as e:
            conn.close()
            raise _InFlightDrop(repr(e)) from e

    def _work(self) -> None:
        while True:
            job = self._queue.get()
            if job is None:
                return
            try:
                self._dispatch(job)
            except Exception as e:  # noqa: BLE001 — never kill a worker
                try:
                    job.future.set_exception(e)
                except Exception:
                    pass

    def _dispatch(self, job: _Job) -> None:
        st = self._ep_stats(job.endpoint)
        if job.ctx is not None:
            # router.queue: ingress -> a worker picked the job up. The
            # ingress=True flag pairs this span 1:1 with the sampled
            # mint, the live/offline reconciliation hook.
            now_wall = time.time()
            tracing.hop(
                "router.queue", (job.ctx,), job.t_wall,
                max(0.0, now_wall - job.t_wall), ingress=True,
                endpoint=job.endpoint,
            )
        path = f"/v1/{job.endpoint}"
        tried: set = set()
        attempts = 1 + max(0, self.retries)
        shed_reasons: List[str] = []
        down: List[str] = []
        deadline = job.t0 + self.request_timeout
        while len(tried) < attempts:
            target, why = self._acquire(tried, deadline)
            if target is None:
                if why == "timeout":
                    # every eligible replica stayed at its in-flight
                    # budget for the whole deadline — overload, not
                    # outage: shed 503-style
                    shed_reasons.append("router_timeout")
                break
            tried.add(target.url)
            t_post_wall = time.time() if job.ctx is not None else 0.0
            via = target
            try:
                hedge_delay = None
                if (
                    self.hedge
                    and len(tried) == 1
                    and self._hedge_budget_ok()
                ):
                    hedge_delay = self._hedge_delay_s(job.endpoint)
                if hedge_delay is not None:
                    status, data, via = self._hedged_post(
                        target, path, job, hedge_delay, deadline
                    )
                else:
                    status, data = self._post(target, path, job.body)
            except _ResponseTimeout as e:
                # the replica is healthy but did not answer in time —
                # 504-analog: no eviction (one slow request must not
                # bounce a live replica), no retry (ambiguous: the
                # request may still execute)
                st.record_error()
                self._count("failed")
                _emit("router", "failed", replica=target.url,
                      endpoint=job.endpoint, reason="timeout")
                job.future.set_exception(ServeError(
                    f"replica {target.url} did not answer "
                    f"{job.endpoint!r} within {self.request_timeout}s: {e}"
                ))
                return
            except _InFlightDrop as e:
                self._evict(target, "in_flight_drop")
                if self.retry_in_flight:
                    self._count("retries")
                    _emit("router", "retry", replica=target.url,
                          endpoint=job.endpoint, reason="in_flight_drop")
                    continue
                st.record_error()
                self._count("failed")
                _emit("router", "failed", replica=target.url,
                      endpoint=job.endpoint, reason="in_flight_drop")
                job.future.set_exception(ReplicaDownError(
                    f"replica {target.url} dropped the connection with "
                    f"the request in flight: {e}"
                ))
                return
            except Exception:
                # connect-level failure: the replica never saw the
                # request — evict it and retry a sibling
                self._evict(target, "connect")
                down.append(target.url)
                self._count("retries")
                _emit("router", "retry", replica=target.url,
                      endpoint=job.endpoint, reason="connect")
                continue
            finally:
                self._release(target)
            if status == 200:
                try:
                    ok, result, _reason = wire.decode_response(data)
                    if not ok:
                        raise wire.WireError(
                            f"200 response carried ok=false: {result}"
                        )
                except wire.WireError as e:
                    st.record_error()
                    self._count("failed")
                    _emit("router", "failed", replica=target.url,
                          endpoint=job.endpoint, reason="wire")
                    job.future.set_exception(e)
                    return
                dt = time.perf_counter() - job.t0
                st.record_done(dt)
                self._count("requests")
                self._class_count(job.cls, "routed")
                _emit("router", "route", replica=via.url,
                      endpoint=job.endpoint, seconds=dt)
                if job.ctx is not None:
                    # router.post: the winning HTTP round trip (retries
                    # that shed/failed are visible as serve_net events)
                    tracing.hop(
                        "router.post", (job.ctx,), t_post_wall,
                        max(0.0, time.time() - t_post_wall),
                        endpoint=job.endpoint, replica=via.url,
                    )
                job.future.set_result(result)
                return
            ok, message, reason = _safe_decode(data)
            if status == 503:
                # sticky degradation: a shed (queue_full/memory/
                # draining/closed) retries siblings before failing.
                # Priority-aware ladder (ISSUE 20): a shed request whose
                # class sits below queued higher-priority work yields
                # its sibling retries — bulk degrades first, the
                # latency tenant keeps the retry capacity.
                shed_reasons.append(reason or "shed")
                top = self._queue.max_queued_weight()
                if top is not None and top > job.weight:
                    shed_reasons.append("priority_yield")
                    break
                _emit("router", "retry", replica=via.url,
                      endpoint=job.endpoint, reason=reason or "shed")
                self._count("retries")
                continue
            # 4xx/5xx: deterministic upstream verdict — do not retry
            st.record_error()
            self._count("failed")
            _emit("router", "failed", replica=target.url,
                  endpoint=job.endpoint, reason=reason or str(status))
            exc: Exception
            if status == 400 or status == 404:
                exc = ValueError(message or f"HTTP {status}")
            else:
                exc = ServeError(
                    f"replica {target.url} answered HTTP {status}: "
                    f"{message}"
                )
            job.future.set_exception(exc)
            return
        # retry ladder exhausted (or yielded by priority)
        if shed_reasons:
            st.record_shed()
            self._count("shed")
            self._class_count(job.cls, "shed")
            _emit("router", "shed", endpoint=job.endpoint,
                  reasons=shed_reasons[:4])
            job.future.set_exception(ServerOverloadedError(
                f"every tried replica shed the request "
                f"(reasons: {shed_reasons})",
                reason=shed_reasons[-1], endpoint=job.endpoint,
            ))
        else:
            st.record_error()
            self._count("failed")
            _emit("router", "failed", endpoint=job.endpoint,
                  reason="no_replicas")
            job.future.set_exception(ReplicaDownError(
                f"no healthy replica for {job.endpoint!r} "
                f"(down: {down or [t.url for t in self._targets]})"
            ))

    # -- background poll -----------------------------------------------------

    # one keep-alive poll connection per replica (poll-thread-only +
    # close(); default 25 ms ticks would otherwise open ~40 TCP
    # connections per replica per second)
    def _poll_conn(self, target: _Target, fresh: bool = False):
        conn = self._poll_conns.get(target.url)
        if fresh and conn is not None:
            try:
                conn.close()
            except Exception:
                pass
            conn = None
        if conn is None:
            conn = _NoDelayConnection(
                target.host, target.port, timeout=_POLL_TIMEOUT
            )
            self._poll_conns[target.url] = conn
        return conn

    def _poll_get(self, target: _Target, path: str):
        """GET over the cached poll connection → ``(status, body)``;
        one fresh-connection resend when a reused conn died idle."""
        conn = self._poll_conn(target)
        reused = conn.sock is not None
        try:
            conn.request("GET", path)
            resp = conn.getresponse()
            return resp.status, resp.read()
        except Exception:
            try:
                conn.close()
            except Exception:
                pass
            if not reused:
                self._poll_conns.pop(target.url, None)
                raise
            conn = self._poll_conn(target, fresh=True)
            try:
                conn.request("GET", path)
                resp = conn.getresponse()
                return resp.status, resp.read()
            except Exception:
                try:
                    conn.close()
                except Exception:
                    pass
                self._poll_conns.pop(target.url, None)
                raise

    def _poll_one(self, target: _Target) -> None:
        try:
            if target.up:
                _status, body = self._poll_get(target, "/stats")
                payload = json.loads(body.decode())
                with self._state:
                    target.polled_pending = int(
                        payload.get("pending", payload.get("queue_depth", 0))
                        or 0
                    )
                    target.poll_fails = 0
                    target.suspect = False  # it answered: not suspect
            else:
                status, _body = self._poll_get(target, "/healthz")
                if status == 200:
                    self._readd(target)
        except Exception:
            if target.up:
                with self._state:
                    target.poll_fails += 1
                    fails = target.poll_fails
                # two consecutive poll misses = gone (a single slow
                # poll under load must not bounce a healthy replica)
                if fails >= 2:
                    self._evict(target, "health_poll")

    def _poll_loop(self) -> None:
        while not self._closed:
            for target in list(self._targets):
                if self._closed:
                    return
                self._poll_one(target)
            time.sleep(self.poll_interval)


def _parse_weights(spec: Optional[str]) -> Dict[str, float]:
    """Parse ``HEAT_TPU_SERVE_PRIORITY_WEIGHTS`` — ``"latency=8,bulk=1"``
    → ``{"latency": 8.0, "bulk": 1.0}``. Empty/unset = single implicit
    class (pure FIFO, the pre-20 behavior)."""
    out: Dict[str, float] = {}
    for part in (spec or "").replace(";", ",").split(","):
        part = part.strip()
        if not part:
            continue
        if "=" not in part:
            raise ValueError(
                f"priority weight {part!r} must be 'class=weight' "
                "(HEAT_TPU_SERVE_PRIORITY_WEIGHTS)"
            )
        k, v = part.split("=", 1)
        w = float(v)
        if w <= 0:
            raise ValueError(
                f"priority class {k.strip()!r} needs a positive weight, "
                f"got {w}"
            )
        out[k.strip()] = w
    return out


def _safe_decode(data: bytes) -> Tuple[bool, str, str]:
    try:
        ok, message, reason = wire.decode_response(data)
        return ok, str(message), reason
    except Exception:
        return False, data[:200].decode("utf-8", "replace"), ""
