"""Telemetry naming contract of the network serving tier (ISSUE 12).

Every ``serve_net`` instant event increments exactly one aggregate
counter (``serve_net.<name>``) alongside its emission, so a **live**
``report.summarize()`` (reading counters) and an **offline** one
(replaying a JSONL sink) reconstruct the *same* ``serving_net`` block —
the reconciliation contract PR 5 established for resilience and PR 11
for autotune, extended to the router/pool tier. ``EVENT_COUNTER`` is
that event-name → counter-name map; :mod:`heat_tpu.telemetry.report`
imports it for the offline rename.
"""

from __future__ import annotations

from typing import Any

from ... import telemetry

__all__ = ["EVENT_COUNTER", "emit"]

# event (on the wire / in the sink)  ->  counter suffix (live registry)
EVENT_COUNTER = {
    "route": "requests",         # one successfully routed request
    "retry": "retries",          # sibling retry after a 503/connect-refused
    "evict": "evictions",        # replica marked down, out of rotation
    "readd": "readds",           # health probe brought a replica back
    "failed": "failed",          # request failed after the retry ladder
    "shed": "shed",              # every replica shed (503 to the client)
    "spawn": "replicas_spawned",  # pool started a replica process
    "remove": "replicas_removed",  # drain-then-kill removal completed
    "kill": "replicas_killed",   # hard kill (chaos)
    "listen": "listens",         # HTTP front bound its port
    "drain": "drains",           # graceful drain began
    "slo_burn": "slo_burns",     # SLO burn rate crossed threshold (ISSUE 17)
    # -- ISSUE 20: autoscaling control plane / priority / hedging ------------
    "spawn_fail": "spawn_fails",  # replica died during warmup, reaped
    "suspect": "suspects",       # ops scrape failed after retry: target
    #                              flagged suspect (never a silent None)
    "detach": "detaches",        # target administratively removed from
    #                              rotation (scale-down / replacement)
    "priority_shed": "priority_sheds",  # weighted-fair admission shed the
    #                              lowest-priority queued (or incoming) job
    "hedge": "hedges",           # duplicate dispatch launched for a
    #                              straggling in-flight request
    "hedge_win": "hedge_wins",   # the hedge arm answered first (loser
    #                              canceled by closing its connection)
}


def emit(name: str, event: str, **fields: Any) -> None:
    """Emit one ``serve_net`` instant event + its paired counter (no-op
    while telemetry is disabled — one flag check)."""
    if not telemetry.enabled():
        return
    reg = telemetry.get_registry()
    reg.add(f"serve_net.{EVENT_COUNTER[event]}", 1)
    reg.emit("serve_net", name, event=event, **fields)
