"""heat_tpu.serve — multi-tenant micro-batched inference front end (ISSUE 8).

The first subsystem that *uses* the PR 1-7 substrate under concurrent
load, and the ROADMAP's "millions of users" story:

* fitted estimators mount as named **endpoints** (:mod:`.endpoints`):
  KMeans ``predict``, KNN classify, cdist/rbf queries, Lasso and
  GaussianNB inference, ``nn.functional.dense`` forward;
* a thread-safe :class:`~.server.Server` accepts concurrent request
  streams and a **micro-batcher** coalesces compatible requests into
  single dispatches through :func:`heat_tpu.core.program_cache
  .cached_program` — after :meth:`~.server.Server.warmup` pre-traces the
  batch-size ladder, the steady state compiles **nothing** (pad-to-bucket
  keeps the program registry finite, and the zero pad rows are
  masked-neutral: in exact mode batched answers are bit-identical to
  solo dispatch);
* **admission control** (:mod:`.admission`) sheds with 503-style
  :class:`~.admission.ServerOverloadedError` before OOM — queue-depth
  bound plus the :mod:`~heat_tpu.resilience.memory_guard` budget
  arithmetic, degrading the batch ladder under pressure before shedding;
* every dispatch already runs under :func:`heat_tpu.resilience
  .wrap_program` retry semantics (transient faults cost one batch retry,
  never the process), and :meth:`~.server.Server.save` /
  :meth:`~.server.Server.restore` checkpoint the fitted endpoints
  through the CRC-verified resilience checkpoint format;
* the telemetry **serving view**: per-endpoint QPS, queue depth, batch
  occupancy, and p50/p95/p99 latency through
  :func:`heat_tpu.telemetry.report.summarize` (``serving`` block) and
  :meth:`~.server.Server.stats`.

See docs/SERVING.md for architecture, knobs (``HEAT_TPU_SERVE_*``) and
the SLO metrics schema; ``benchmarks/serving/`` for the open-loop
Poisson load generator.
"""

from __future__ import annotations

from .admission import (
    AdmissionController,
    ServeError,
    ServerClosedError,
    ServerOverloadedError,
)
from .endpoints import (
    Endpoint,
    cdist_query,
    dense_forward,
    gaussian_nb_predict,
    kmeans_predict,
    knn_classify,
    lasso_predict,
    rbf_query,
    sparse_query,
)
from .server import Server
from . import admission, endpoints, metrics, net, server  # noqa: F401

__all__ = [
    "Server",
    "Endpoint",
    "net",
    "AdmissionController",
    "ServeError",
    "ServerOverloadedError",
    "ServerClosedError",
    "kmeans_predict",
    "knn_classify",
    "gaussian_nb_predict",
    "lasso_predict",
    "cdist_query",
    "rbf_query",
    "dense_forward",
    "sparse_query",
]
