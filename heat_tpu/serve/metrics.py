"""Serving-side latency/occupancy accounting (ISSUE 8).

The telemetry registry records *events*; a serving front end additionally
needs cheap online aggregates it can report while the event stream is
disabled — per-endpoint request counts, shed/error tallies, batch
occupancy, and latency percentiles. :class:`LatencyHistogram` is a
fixed-size log-bucketed histogram (10 µs … ~300 s, 1.25× growth): O(1)
record, O(buckets) quantile, no per-request allocation, thread-safe under
the owning :class:`EndpointStats` lock. Percentile estimates interpolate
inside the winning bucket, so the p50/p95/p99 the server reports are
within one bucket width (≤25%) of exact — the honest resolution for an
SLO dashboard, at zero memory growth under sustained load.
"""

from __future__ import annotations

import math
import threading
import time
from typing import List, Optional

__all__ = ["LatencyHistogram", "EndpointStats"]

# bucket i covers (BASE*GROWTH^(i-1), BASE*GROWTH^i]; bucket 0 covers
# [0, BASE]. 80 buckets reach BASE*1.25^79 ≈ 459 s — beyond any sane SLO.
_BASE = 1e-5
_GROWTH = 1.25
_NBUCKETS = 80
_LOG_GROWTH = math.log(_GROWTH)


class LatencyHistogram:
    """Log-bucketed latency histogram with quantile estimation."""

    __slots__ = ("counts", "count", "total", "min", "max")

    def __init__(self):
        self.counts: List[int] = [0] * _NBUCKETS
        self.count = 0
        self.total = 0.0
        self.min = float("inf")
        self.max = 0.0

    def record(self, seconds: float) -> None:
        if seconds < 0:
            seconds = 0.0
        if seconds <= _BASE:
            i = 0
        else:
            i = min(
                _NBUCKETS - 1,
                1 + int(math.log(seconds / _BASE) / _LOG_GROWTH),
            )
        self.counts[i] += 1
        self.count += 1
        self.total += seconds
        if seconds < self.min:
            self.min = seconds
        if seconds > self.max:
            self.max = seconds

    def quantile(self, q: float) -> Optional[float]:
        """Estimated ``q``-quantile in seconds (linear interpolation inside
        the winning bucket, clamped to the observed min/max). None when
        empty."""
        if self.count == 0:
            return None
        target = q * self.count
        seen = 0
        for i, c in enumerate(self.counts):
            if c == 0:
                continue
            if seen + c >= target:
                lo = 0.0 if i == 0 else _BASE * _GROWTH ** (i - 1)
                hi = _BASE * _GROWTH ** i
                frac = (target - seen) / c
                est = lo + (hi - lo) * frac
                return max(self.min, min(self.max, est))
            seen += c
        return self.max

    def snapshot(self) -> dict:
        if self.count == 0:
            return {"count": 0}
        return {
            "count": self.count,
            "mean_s": self.total / self.count,
            "min_s": self.min,
            "max_s": self.max,
            "p50_s": self.quantile(0.50),
            "p95_s": self.quantile(0.95),
            "p99_s": self.quantile(0.99),
        }

    # -- cluster merge contract (ISSUE 17) -----------------------------------
    # Two histograms with identical (base, growth, nbuckets) merge EXACTLY
    # by bucket-wise addition: bucket membership depends only on the sample
    # value, never on which process recorded it, so the merged counts (and
    # hence every quantile estimate) equal those of a single histogram fed
    # the concatenated samples. raw() / from_raw() are the wire form of
    # that contract — GET /metrics ships raw bucket counts, the router
    # merges them, and fleet-wide percentiles come out of the merged
    # histogram at the same (one-bucket-width) resolution as local ones.

    def raw(self) -> dict:
        """Wire-form snapshot: the raw bucket counts plus the scalar
        moments, tagged with the bucket geometry so a merger can refuse
        a mismatched histogram instead of silently mis-binning."""
        return {
            "base": _BASE,
            "growth": _GROWTH,
            "counts": list(self.counts),
            "count": self.count,
            "total": self.total,
            "min": self.min if self.count else None,
            "max": self.max if self.count else None,
        }

    @classmethod
    def from_raw(cls, raw: dict) -> "LatencyHistogram":
        """Inverse of :meth:`raw` (ValueError on bucket-geometry drift)."""
        if (
            float(raw.get("base", _BASE)) != _BASE
            or float(raw.get("growth", _GROWTH)) != _GROWTH
            or len(raw.get("counts", ())) != _NBUCKETS
        ):
            raise ValueError(
                "histogram bucket geometry mismatch: expected "
                f"base={_BASE} growth={_GROWTH} nbuckets={_NBUCKETS}, got "
                f"base={raw.get('base')} growth={raw.get('growth')} "
                f"nbuckets={len(raw.get('counts', ()))}"
            )
        h = cls()
        h.counts = [int(c) for c in raw["counts"]]
        h.count = int(raw.get("count", sum(h.counts)))
        h.total = float(raw.get("total", 0.0))
        if raw.get("min") is not None:
            h.min = float(raw["min"])
        if raw.get("max") is not None:
            h.max = float(raw["max"])
        return h

    def merge(self, other: "LatencyHistogram") -> "LatencyHistogram":
        """Bucket-wise in-place merge (the exact aggregation contract);
        returns ``self``."""
        for i, c in enumerate(other.counts):
            self.counts[i] += c
        self.count += other.count
        self.total += other.total
        if other.count:
            if other.min < self.min:
                self.min = other.min
            if other.max > self.max:
                self.max = other.max
        return self


class EndpointStats:
    """Per-endpoint serving aggregates: request/row/batch tallies, shed
    and error counts, pad overhead, and the latency histogram. All
    mutation goes through the instance lock — the submit path and the
    batcher thread both write here.

    Scrape contract (ISSUE 17): every tally is **cumulative since
    ``window_start``** (a monotonic-clock stamp taken at construction)
    and is never reset. A scraper derives windowed rates entirely on its
    own side — ``(cur.requests - prev.requests) / (cur.mono -
    prev.mono)`` — so two consecutive scrapes can never race a reset
    (there is none), and K scrapers each keep their own window without
    perturbing each other or the autoscaler.
    """

    def __init__(self, name: str):
        self.name = name
        self._lock = threading.Lock()
        self.window_start = time.monotonic()
        self.requests = 0
        self.rows = 0
        self.batches = 0
        self.dispatched_rows = 0
        self.padded_rows = 0
        self.shed = 0
        self.errors = 0
        self.latency = LatencyHistogram()

    def record_request(self, rows: int) -> None:
        with self._lock:
            self.requests += 1
            self.rows += rows

    def record_shed(self) -> None:
        with self._lock:
            self.shed += 1

    def record_batch(self, rows: int, padded: int) -> None:
        with self._lock:
            self.batches += 1
            self.dispatched_rows += rows
            self.padded_rows += padded

    def record_done(self, seconds: float) -> None:
        with self._lock:
            self.latency.record(seconds)

    def record_error(self, n: int = 1) -> None:
        with self._lock:
            self.errors += n

    def snapshot(self) -> dict:
        with self._lock:
            out = {
                "requests": self.requests,
                "rows": self.rows,
                "batches": self.batches,
                "shed": self.shed,
                "errors": self.errors,
                "padded_rows": self.padded_rows,
                "latency": self.latency.snapshot(),
                "window_start": self.window_start,
                "mono": time.monotonic(),
            }
            if self.batches:
                out["mean_batch_rows"] = self.dispatched_rows / self.batches
                denom = self.dispatched_rows + self.padded_rows
                out["occupancy"] = (
                    self.dispatched_rows / denom if denom else 1.0
                )
            return out

    def raw_snapshot(self) -> dict:
        """The ``GET /metrics`` form: cumulative tallies plus the RAW
        latency bucket counts (mergeable bucket-wise, unlike the
        quantized quantiles in :meth:`snapshot`), stamped with
        ``window_start``/``mono`` so scrapers derive windowed rates
        without any server-side reset."""
        with self._lock:
            return {
                "requests": self.requests,
                "rows": self.rows,
                "batches": self.batches,
                "dispatched_rows": self.dispatched_rows,
                "padded_rows": self.padded_rows,
                "shed": self.shed,
                "errors": self.errors,
                "window_start": self.window_start,
                "mono": time.monotonic(),
                "latency_raw": self.latency.raw(),
            }
