"""Endpoint adapters: fitted estimators → pure batched inference programs.

An :class:`Endpoint` is the unit the server dispatches: a *pure* function
``fn(batch, *params)`` over a ``(bucket, features)`` request batch plus
the fitted parameters, compiled once per ladder bucket through
:func:`heat_tpu.core.program_cache.cached_program` (site
``serve.<name>``) and reused for every later batch of that shape — the
zero-compile steady state the warm-up pre-traces.

Two kernel families per endpoint, selected by ``HEAT_TPU_SERVE_EXACT``
(default on):

* **exact** — broadcast-then-reduce forms whose per-row reduction order
  is independent of the batch dimension, so a request served inside a
  padded 64-row bucket returns *bit-identical* results to the same
  request dispatched alone (the pad rows are zeros and every kernel is
  row-independent — the serving analog of the fusion engine's
  masked-neutral pad fill). This is the contract the batched/sequential
  bit-identity CI oracle pins, and the default because a cache hit on a
  different bucket must never change an answer.
* **fast** (``HEAT_TPU_SERVE_EXACT=0``) — the MXU GEMM forms the
  estimators themselves use (``x² + c² − 2xcᵀ`` etc.). On TPU these are
  several times faster for large reference sets, but XLA is free to
  re-tile the contraction per batch shape, so cross-bucket bit-identity
  is NOT guaranteed (still allclose at f32 ulp scale).

Parameters are passed as *arguments* to the jitted program, not closed
over: a checkpoint-restored estimator with identical shapes re-enters the
very same cached executable (the re-warm after ``Server.restore`` is all
registry hits), and two endpoints of one kind share programs when their
static config matches.
"""

from __future__ import annotations

from typing import Any, Callable, Dict, Optional, Sequence, Tuple

import jax
import jax.numpy as jnp
import numpy as np

from heat_tpu import _knobs as knobs

__all__ = [
    "Endpoint",
    "kmeans_predict",
    "knn_classify",
    "gaussian_nb_predict",
    "lasso_predict",
    "cdist_query",
    "rbf_query",
    "dense_forward",
    "sparse_query",
    "rebuild",
]


def exact_mode() -> bool:
    """Whether the bit-stable serving kernels are active (default). Off
    (``HEAT_TPU_SERVE_EXACT=0``) selects the GEMM forms — faster on the
    MXU, but batched-vs-solo results are only allclose, not bit-equal."""
    return knobs.raw("HEAT_TPU_SERVE_EXACT", "").strip().lower() not in (
        "0", "false", "no", "off",
    )


# -- shape-stable math helpers -------------------------------------------------
# Reduction order per output element must not depend on the batch dim:
# broadcast+reduce lowers to one fused elementwise+reduce loop per row,
# which XLA keeps row-independent, while a GEMM may re-tile (and hence
# re-associate) the contraction when the batch dimension changes —
# measured on this backend: (1,64)@(64,8) and (16,64)@(64,8) disagree in
# the last ulp.


def _d2_exact(xb: jax.Array, c: jax.Array) -> jax.Array:
    diff = xb[:, None, :] - c[None, :, :]
    return jnp.sum(diff * diff, axis=-1)


def _d2_fast(xb: jax.Array, c: jax.Array) -> jax.Array:
    x2 = jnp.sum(xb * xb, axis=1, keepdims=True)
    c2 = jnp.sum(c * c, axis=1)[None, :]
    prod = jnp.matmul(xb, c.T, precision=jax.lax.Precision.HIGH)
    return jnp.maximum(x2 + c2 - 2.0 * prod, 0.0)


def _d2(xb, c, exact: bool):
    return _d2_exact(xb, c) if exact else _d2_fast(xb, c)


def _matmul_exact(a: jax.Array, b: jax.Array) -> jax.Array:
    return jnp.sum(a[:, :, None] * b[None, :, :], axis=1)


def _matvec_exact(a: jax.Array, v: jax.Array) -> jax.Array:
    return jnp.sum(a * v[None, :], axis=1)


# -- kernel functions (module-level: stable identities for the registry) -------


def _kmeans_fn(xb, params, cfg):
    (centers,) = params
    d2 = _d2(xb.astype(centers.dtype), centers, cfg["exact"])
    return jnp.argmin(d2, axis=1).astype(jnp.int64)


def _knn_fn(xb, params, cfg):
    xt, yt, classes = params
    d2 = _d2(xb.astype(xt.dtype), xt, cfg["exact"])
    _, idx = jax.lax.top_k(-d2, cfg["k"])
    neigh = jnp.take(yt, idx)  # (m, k) labels
    votes = jnp.sum(
        (neigh[:, :, None] == classes[None, None, :]).astype(jnp.int32),
        axis=1,
    )
    return jnp.take(classes, jnp.argmax(votes, axis=1))


def _gnb_fn(xb, params, cfg):
    theta, var, prior, classes = params
    xl = xb.astype(jnp.float64)
    log_prior = jnp.log(prior)[None, :]
    n_ij = -0.5 * jnp.sum(jnp.log(2.0 * jnp.pi * var), axis=1)[None, :]
    diff = xl[:, None, :] - theta[None, :, :]  # (m, k, d)
    quad = -0.5 * jnp.sum(diff * diff / var[None, :, :], axis=2)
    jll = log_prior + n_ij + quad
    return jnp.take(classes, jnp.argmax(jll, axis=1))


def _lasso_fn(xb, params, cfg):
    coef, intercept = params
    xc = xb.astype(coef.dtype)
    if cfg["exact"]:
        return _matvec_exact(xc, coef) + intercept
    return jnp.matmul(xc, coef) + intercept


def _cdist_fn(xb, params, cfg):
    (y,) = params
    d2 = _d2(xb.astype(y.dtype), y, cfg["exact"])
    d2 = jnp.maximum(d2, 0.0)
    gamma = cfg.get("gamma")
    if gamma is not None:
        return jnp.exp(-gamma * d2)
    return jnp.sqrt(d2)


def _dense_fn(xb, params, cfg):
    w = params[0]
    xc = xb.astype(w.dtype)
    y = _matmul_exact(xc, w) if cfg["exact"] else jnp.matmul(xc, w)
    if cfg["bias"]:
        y = y + params[1]
    act = cfg.get("activation")
    if act == "relu":
        y = jnp.maximum(y, 0.0)
    elif act == "tanh":
        y = jnp.tanh(y)
    elif act == "sigmoid":
        y = 1.0 / (1.0 + jnp.exp(-y))
    return y


def _sparse_query_fn(xb, params, cfg):
    """Sparse-feature affine map: request rows arrive as padded CSR
    (``xb = (indptr, indices, values)``, the micro-batcher's
    ``(row bucket, nnz bucket)`` lattice — ISSUE 13) and contract
    against the dense weight by per-row segment reduction. Pad element
    slots sit past ``indptr[-1]`` and land on segment ``bucket`` (out of
    range — structurally dropped), pad rows have empty segments (bias
    only), so a request served inside a padded bucket reduces exactly
    its own elements in exactly its own order regardless of bucket —
    the sparse analog of the exact-mode broadcast+reduce contract."""
    indptr, indices, values = xb
    w = params[0]
    rows = (
        jnp.searchsorted(
            indptr,
            jnp.arange(indices.shape[0], dtype=indptr.dtype),
            side="right",
        ) - 1
    )
    contrib = values[:, None] * w[indices]
    y = jax.ops.segment_sum(
        contrib, rows, num_segments=indptr.shape[0] - 1
    )
    if cfg["bias"]:
        y = y + params[1]
    act = cfg.get("activation")
    if act == "relu":
        y = jnp.maximum(y, 0.0)
    elif act == "tanh":
        y = jnp.tanh(y)
    elif act == "sigmoid":
        y = 1.0 / (1.0 + jnp.exp(-y))
    return y


_KIND_FNS: Dict[str, Callable] = {
    "kmeans_predict": _kmeans_fn,
    "knn_classify": _knn_fn,
    "gaussian_nb_predict": _gnb_fn,
    "lasso_predict": _lasso_fn,
    "cdist_query": _cdist_fn,
    "dense_forward": _dense_fn,
    "sparse_query": _sparse_query_fn,
}

# kinds whose request payload is a ragged CSR row batch
# (heat_tpu.sparse.host.CsrRows) rather than a dense (rows, features)
# matrix — the server's submit/batcher/warmup paths branch on this
_SPARSE_KINDS = frozenset({"sparse_query"})


class Endpoint:
    """One named inference program family: ``kind`` selects the kernel,
    ``params`` are the fitted arrays (passed as program arguments),
    ``config`` the static knobs baked into the trace (and the registry
    key). ``features`` / ``dtype`` define the request contract the server
    validates against."""

    __slots__ = ("kind", "params", "config", "features", "dtype", "version")

    def __init__(
        self,
        kind: str,
        params: Sequence[jax.Array],
        config: Optional[Dict[str, Any]] = None,
        *,
        features: int,
        dtype,
        version: int = 1,
    ):
        if kind not in _KIND_FNS:
            raise ValueError(
                f"unknown endpoint kind {kind!r}; known: {sorted(_KIND_FNS)}"
            )
        self.kind = kind
        # canonical placement: a freshly-fitted param arrives with the
        # estimator's replicated MESH sharding, a checkpoint-restored one
        # as a plain default-device array — jit compiles a distinct
        # executable per input sharding, which would break the
        # "restore-then-rewarm compiles nothing" contract. The host
        # round-trip pins every param to the default single-device layout
        # (they are small: centers, coefficients, class stats).
        self.params = tuple(jnp.asarray(np.asarray(p)) for p in params)
        self.config = dict(config or {})
        self.config.setdefault("exact", exact_mode())
        self.features = int(features)
        self.dtype = np.dtype(dtype)
        # monotone publish counter (ISSUE 16): params are program
        # *arguments*, so a republish with identical avals re-enters the
        # warm executable — the version therefore deliberately does NOT
        # ride in program_key (that would fork one compile per publish).
        if int(version) < 1:
            raise ValueError(f"endpoint version must be >= 1, got {version}")
        self.version = int(version)

    # -- program plumbing ----------------------------------------------------

    @property
    def is_sparse(self) -> bool:
        """Whether requests are ragged CSR row batches
        (:class:`heat_tpu.sparse.host.CsrRows`) instead of dense
        ``(rows, features)`` matrices."""
        return self.kind in _SPARSE_KINDS

    def cfg_key(self) -> Tuple:
        return tuple(sorted(self.config.items()))

    def program_key(self, bucket: int, nnz_cap: Optional[int] = None) -> Tuple:
        """The program-cache static key for one ladder bucket. Parameter
        *avals* ride in the key so two same-kind endpoints with different
        reference-set sizes never collide, while a restored estimator
        with identical shapes re-hits the warm entry. Sparse endpoints
        key additionally on the nnz bucket — the second axis of the
        ragged pad lattice."""
        psig = tuple((tuple(p.shape), str(p.dtype)) for p in self.params)
        key = (
            self.kind, self.cfg_key(), int(bucket), self.features,
            str(self.dtype), psig,
        )
        if nnz_cap is not None:
            key = key + (int(nnz_cap),)
        return key

    def build(self) -> Callable:
        """The pure callable to jit — runs only on a registry miss."""
        fn = _KIND_FNS[self.kind]
        cfg = dict(self.config)

        if self.is_sparse:
            def call(indptr, indices, values, *params):
                return fn((indptr, indices, values), params, cfg)

            return call

        def call(xb, *params):
            return fn(xb, params, cfg)

        return call

    def nnz_cap_for(self, bucket: int, nnz: int) -> int:
        """The nnz bucket for a coalesced sparse batch: per-row element
        capacity rounded to the next power of two (floored at 1) times
        the row bucket. Duplicate-free rows top out at ``features``
        per row — the finite lattice :meth:`nnz_ladder` pre-traces, so
        ragged steady-state traffic stays zero-compile. Rows carrying
        duplicate columns (legal: the kernel sums them, scipy-style) can
        exceed ``features`` nnz; those keep the uncapped power-of-two
        bucket — an un-warmed program compiles on first use rather than
        failing the batch."""
        per_row = max(1, -(-int(nnz) // max(1, int(bucket))))
        cap = 1
        while cap < per_row:
            cap *= 2
        if per_row <= max(1, self.features):
            cap = min(cap, max(1, self.features))
        return int(bucket) * cap

    def nnz_ladder(self, bucket: int) -> Tuple[int, ...]:
        """Every nnz bucket :meth:`nnz_cap_for` can produce for one row
        bucket — the warm-up lattice (power-of-two per-row capacities up
        to ``features``)."""
        caps = []
        c = 1
        while True:
            caps.append(int(bucket) * min(c, max(1, self.features)))
            if c >= self.features:
                break
            c *= 2
        return tuple(dict.fromkeys(caps))

    def cost_bytes(self, bucket: int) -> int:
        """Analytic temp+output byte estimate for one ``bucket``-row
        dispatch — the admission controller's fallback when the bucket
        was never warmed (measured ``memory_analysis`` bytes win once
        available). Counts the request buffer, the (bucket, n_ref)
        intermediate the distance/likelihood kernels materialize, and
        the output. Sparse endpoints price the worst-case nnz bucket
        (dense rows) — conservative by design until the warmed
        measurement takes over."""
        item = max(self.dtype.itemsize, 4)
        if self.is_sparse:
            k = self.params[0].shape[1] if self.params[0].ndim > 1 else 1
            nnz = bucket * self.features
            inp = (bucket + 1) * 4 + nnz * (4 + item)
            mid = nnz * max(k, 1) * item
            out = bucket * max(k, 1) * item
            return int(inp + mid + out)
        n_ref = self.params[0].shape[0] if self.params[0].ndim else 1
        inp = bucket * self.features * item
        mid = bucket * max(n_ref, 1) * item
        out = bucket * max(n_ref, 1) * item
        return int(inp + mid + out)

    def with_params(
        self, params: Sequence, *, version: Optional[int] = None
    ) -> "Endpoint":
        """The versioned-publish constructor (ISSUE 16): the same program
        family with freshly fitted parameters and a bumped version
        (default ``self.version + 1``). Parameter avals must match the
        current ones exactly — that is the zero-compile swap contract
        (same ``program_key`` → the swap re-enters the warm executable);
        a shape/dtype change is a *new* endpoint family and must go
        through a fresh constructor + warmup instead."""
        new = tuple(jnp.asarray(np.asarray(p)) for p in params)
        old_sig = tuple((tuple(p.shape), str(p.dtype)) for p in self.params)
        new_sig = tuple((tuple(p.shape), str(p.dtype)) for p in new)
        if old_sig != new_sig:
            raise ValueError(
                f"with_params aval mismatch (zero-compile swaps need "
                f"identical parameter shapes/dtypes): {old_sig} -> {new_sig}"
            )
        ep = Endpoint(
            self.kind, new, config=dict(self.config),
            features=self.features, dtype=self.dtype,
            version=self.version + 1 if version is None else int(version),
        )
        return ep

    def describe(self) -> dict:
        """JSON-serializable manifest record (checkpoint/restore)."""
        return {
            "kind": self.kind,
            "config": dict(self.config),
            "features": self.features,
            "dtype": str(self.dtype),
            "n_params": len(self.params),
            "version": self.version,
        }


def rebuild(record: dict, params: Sequence) -> Endpoint:
    """Inverse of :meth:`Endpoint.describe` + saved params — the
    checkpoint-restore constructor (``Server.restore``). Pre-16
    checkpoints carry no version field and restore at version 1."""
    return Endpoint(
        record["kind"],
        [jnp.asarray(p) for p in params],
        config=record.get("config"),
        features=record["features"],
        dtype=np.dtype(record["dtype"]),
        version=int(record.get("version", 1)),
    )


# -- estimator adapters --------------------------------------------------------


def _replicated(x) -> jax.Array:
    """Fitted parameters are small (centers, coefficients, class stats):
    replicate DNDarrays onto the host process, accept plain arrays as-is."""
    from ..core.dndarray import DNDarray

    if isinstance(x, DNDarray):
        return x._replicated()
    return jnp.asarray(x)


def kmeans_predict(est) -> Endpoint:
    """Serve ``est.predict`` for a fitted K-family clusterer (KMeans,
    KMedians with euclidean assignment): nearest-centroid labels
    (int64), bit-matching :meth:`heat_tpu.cluster.KMeans.predict` in
    exact mode."""
    if est.cluster_centers_ is None:
        raise ValueError("estimator is not fitted (no cluster_centers_)")
    centers = _replicated(est.cluster_centers_)
    return Endpoint(
        "kmeans_predict", [centers],
        features=int(centers.shape[1]), dtype=np.dtype(centers.dtype),
    )


def knn_classify(est) -> Endpoint:
    """Serve a fitted :class:`~heat_tpu.classification.KNeighborsClassifier`:
    distance + top-k + one-hot vote, like ``est.predict``."""
    if est.x is None:
        raise ValueError("estimator is not fitted (call fit first)")
    xt = _replicated(est.x).astype(jnp.float32)
    yt = _replicated(est.y).ravel()
    classes = jnp.asarray(est._classes)
    k = min(int(est.n_neighbors), int(xt.shape[0]))
    return Endpoint(
        "knn_classify", [xt, yt, classes], {"k": k},
        features=int(xt.shape[1]), dtype=np.float32,
    )


def gaussian_nb_predict(est) -> Endpoint:
    """Serve a fitted :class:`~heat_tpu.naive_bayes.GaussianNB`: max joint
    log-likelihood class per row (float64 internally, like the
    estimator)."""
    if est.theta_ is None:
        raise ValueError("estimator is not fitted (call fit first)")
    theta = _replicated(est.theta_)
    var = _replicated(est.var_)
    prior = _replicated(est.class_prior_)
    classes = _replicated(est.classes_)
    return Endpoint(
        "gaussian_nb_predict", [theta, var, prior, classes],
        features=int(theta.shape[1]), dtype=np.float64,
    )


def lasso_predict(est) -> Endpoint:
    """Serve a fitted :class:`~heat_tpu.regression.Lasso`:
    ``x @ coef + intercept``."""
    if est.theta is None:
        raise ValueError("estimator is not fitted (call fit first)")
    theta = _replicated(est.theta).ravel()
    coef, intercept = theta[1:], theta[0]
    return Endpoint(
        "lasso_predict", [coef, intercept],
        features=int(coef.shape[0]), dtype=np.dtype(coef.dtype),
    )


def cdist_query(y) -> Endpoint:
    """Serve euclidean distance rows against a fixed reference matrix
    ``y`` ((n_ref, d) DNDarray or array): each request row yields its
    distance vector to every reference row."""
    yb = _replicated(y)
    if yb.ndim != 2:
        raise ValueError(f"reference matrix must be 2-D, got {yb.ndim}-D")
    if not jnp.issubdtype(yb.dtype, jnp.floating):
        yb = yb.astype(jnp.float32)
    return Endpoint(
        "cdist_query", [yb],
        features=int(yb.shape[1]), dtype=np.dtype(yb.dtype),
    )


def rbf_query(y, sigma: float = 1.0) -> Endpoint:
    """Gaussian-kernel rows ``exp(−‖x−y‖²/2σ²)`` against a fixed
    reference matrix — the serving form of :func:`heat_tpu.spatial.rbf`."""
    yb = _replicated(y)
    if yb.ndim != 2:
        raise ValueError(f"reference matrix must be 2-D, got {yb.ndim}-D")
    if not jnp.issubdtype(yb.dtype, jnp.floating):
        yb = yb.astype(jnp.float32)
    gamma = 1.0 / (2.0 * float(sigma) * float(sigma))
    return Endpoint(
        "cdist_query", [yb], {"gamma": gamma},
        features=int(yb.shape[1]), dtype=np.dtype(yb.dtype),
    )


def sparse_query(w, bias=None, activation: Optional[str] = None) -> Endpoint:
    """Serve ``activation(x_sparse @ w + bias)`` over **sparse feature
    rows** (ISSUE 13): requests are
    :class:`heat_tpu.sparse.host.CsrRows` batches — the realistic shape
    of high-volume inference traffic — and the micro-batcher pads them
    onto a ``(row bucket, nnz bucket)`` lattice so genuinely ragged
    streams stay zero-compile after warm-up. ``w`` is the dense
    ``(features, out)`` weight (DNDarray or array);
    ``activation`` ∈ {None, 'relu', 'tanh', 'sigmoid'}."""
    wb = _replicated(w)
    if wb.ndim != 2:
        raise ValueError(f"weight must be 2-D (features, out), got {wb.ndim}-D")
    if activation not in (None, "relu", "tanh", "sigmoid"):
        raise ValueError(
            f"activation must be None/'relu'/'tanh'/'sigmoid', "
            f"got {activation!r}"
        )
    if not jnp.issubdtype(wb.dtype, jnp.floating):
        wb = wb.astype(jnp.float32)
    params = [wb]
    if bias is not None:
        bb = _replicated(bias).ravel().astype(wb.dtype)
        if bb.shape[0] != wb.shape[1]:
            raise ValueError(
                f"bias length {bb.shape[0]} != output width {wb.shape[1]}"
            )
        params.append(bb)
    return Endpoint(
        "sparse_query", params,
        {"bias": bias is not None, "activation": activation},
        features=int(wb.shape[0]), dtype=np.dtype(wb.dtype),
    )


def dense_forward(w, bias=None, activation: Optional[str] = None) -> Endpoint:
    """Serve an affine layer ``activation(x @ w + bias)`` — the
    :func:`heat_tpu.nn.functional.dense` forward as an endpoint.
    ``activation`` ∈ {None, 'relu', 'tanh', 'sigmoid'}."""
    wb = _replicated(w)
    if wb.ndim != 2:
        raise ValueError(f"weight must be 2-D (d_in, d_out), got {wb.ndim}-D")
    if activation not in (None, "relu", "tanh", "sigmoid"):
        raise ValueError(
            f"activation must be None/'relu'/'tanh'/'sigmoid', "
            f"got {activation!r}"
        )
    params = [wb]
    if bias is not None:
        bb = _replicated(bias).ravel().astype(wb.dtype)
        if bb.shape[0] != wb.shape[1]:
            raise ValueError(
                f"bias length {bb.shape[0]} != output width {wb.shape[1]}"
            )
        params.append(bb)
    return Endpoint(
        "dense_forward", params,
        {"bias": bias is not None, "activation": activation},
        features=int(wb.shape[0]), dtype=np.dtype(wb.dtype),
    )
