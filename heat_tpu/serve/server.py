"""The multi-tenant micro-batched inference server (ISSUE 8 tentpole).

Request lifecycle::

    submit(name, payload)
      └─ admission gate (queue depth, memory budget → 503-style shed,
         or ladder degradation)                       [caller thread]
      └─ FIFO queue (thread-safe)
    batcher thread
      └─ coalesce consecutive same-endpoint requests up to the ladder cap
         within a short gather window (micro-batch)
      └─ pad the coalesced rows up to the smallest ladder bucket
         (masked-neutral zero rows; row-independent kernels → pad rows
         cannot perturb real rows, and in exact mode results are
         bit-identical to solo dispatch)
      └─ ONE dispatch through program_cache.cached_program
         (site ``serve.<name>``) — which is already wrapped in
         resilience.wrap_program, so the fault injector, the HBM
         preflight, and the transient-retry guard run per *batch*
         (a transient fault costs one batch retry, never the process)
      └─ slice results back per request, resolve futures, record
         latency/occupancy metrics + telemetry events

``warmup()`` pre-traces every endpoint's whole batch-size ladder (the
pad-to-bucket discipline keeps the program registry finite: one program
per (endpoint, bucket)), so the steady state is **zero compiles** — every
later dispatch is a registry dict hit, pinned by the CI serving gate via
:func:`heat_tpu.core.program_cache.site_stats`.

Knobs (all overridable per-``Server`` constructor argument):

* ``HEAT_TPU_SERVE_MAX_BATCH`` — ladder top (default 64);
* ``HEAT_TPU_SERVE_LADDER`` — explicit comma-separated bucket list
  (default: powers of two up to max_batch);
* ``HEAT_TPU_SERVE_MAX_WAIT_MS`` — micro-batch gather window (default 2);
* ``HEAT_TPU_SERVE_QUEUE_MAX`` — admission queue bound (default 1024);
* ``HEAT_TPU_SERVE_EXACT`` — bit-stable kernels (default on; see
  :mod:`.endpoints`).

Checkpoint story: ``server.save(path)`` writes every endpoint's fitted
parameters + static config through :mod:`heat_tpu.resilience.checkpoint`
(CRC-verified, atomically swapped); ``Server.restore(path)`` rebuilds the
endpoints without refitting — and because parameters are program
*arguments*, the re-warm after restore re-enters the same cached
executables bit-for-bit.
"""

from __future__ import annotations

import queue
import threading
import time
from concurrent.futures import Future
from typing import Dict, List, Optional, Sequence

import jax.numpy as jnp
import numpy as np

from heat_tpu import _knobs as knobs

from .. import telemetry
from ..core import program_cache
from ..resilience import memory_guard
from .admission import (
    AdmissionController,
    ServerClosedError,
    ServerOverloadedError,
)
from . import tracing
from .endpoints import Endpoint, rebuild
from .metrics import EndpointStats

__all__ = ["Server"]

DEFAULT_MAX_BATCH = 64
DEFAULT_WAIT_MS = 2.0

_SHUTDOWN = object()
# submit()'s trace default: mint locally at this ingress. Distinct from
# None, which transports pass to say "the remote ingress decides" — a
# pre-17 router that sent no trace field must not re-mint replica-local
# contexts (that would double-count against the ingress sampling rate).
_MINT = object()


def _resolve(fut: Future, value=None, exc=None) -> None:
    """Resolve a future exactly once. A close() racing a live batcher can
    reach the same request from both sides (drain vs in-flight batch);
    the second resolution must be a no-op, not an InvalidStateError that
    kills the batcher thread mid-batch."""
    try:
        if exc is not None:
            fut.set_exception(exc)
        else:
            fut.set_result(value)
    except Exception:  # concurrent.futures.InvalidStateError
        pass


def _env_float(name: str, default: float) -> float:
    raw = (knobs.raw(name, "") or "").strip()
    if raw:
        try:
            v = float(raw)
            if v >= 0:
                return v
        except ValueError:
            pass
    return default


def _default_ladder(max_batch: int) -> List[int]:
    ladder = []
    b = 1
    while b < max_batch:
        ladder.append(b)
        b *= 2
    ladder.append(max_batch)
    return ladder


def _env_ladder(max_batch: int) -> List[int]:
    raw = knobs.raw("HEAT_TPU_SERVE_LADDER", "").strip()
    if raw:
        try:
            vals = sorted({int(v) for v in raw.split(",") if v.strip()})
            if vals and all(v > 0 for v in vals):
                return vals
        except ValueError:
            pass
    return _default_ladder(max_batch)


class _Request:
    __slots__ = (
        "endpoint", "array", "rows", "squeeze", "future", "t_submit",
        "t_wall", "ctx",
    )

    def __init__(self, endpoint: str, array, squeeze: bool, ctx=None):
        # `array` is a dense (rows, features) ndarray, or a CsrRows
        # batch for sparse endpoints (both expose .shape[0])
        self.endpoint = endpoint
        self.array = array
        self.rows = int(array.shape[0])
        self.squeeze = squeeze
        self.future: Future = Future()
        self.t_submit = time.perf_counter()
        # wall-clock twin of t_submit: trace spans anchor on wall time
        # so cross-process merges have one clock domain to reconcile
        self.t_wall = time.time() if ctx is not None else 0.0
        self.ctx = ctx  # Optional[tracing.TraceContext]


class Server:
    """Multi-tenant micro-batched inference front end over fitted
    estimators (module docstring has the architecture; docs/SERVING.md
    the operator guide)."""

    def __init__(
        self,
        *,
        max_batch: Optional[int] = None,
        ladder: Optional[Sequence[int]] = None,
        max_wait_ms: Optional[float] = None,
        queue_max: Optional[int] = None,
    ):
        if knobs.get("HEAT_TPU_AUTOTUNE"):
            # tuned serve knobs (ladder top / gather window / queue
            # bound, ISSUE 11) land in the knob overlay BEFORE the reads
            # below, so a fresh process constructs its server already
            # tuned — one flag check when off, explicit constructor
            # arguments still win over any tuned value
            from .. import autotune as _autotune

            _autotune.warm_start()
        if max_batch is None:
            raw = knobs.raw("HEAT_TPU_SERVE_MAX_BATCH", "").strip()
            max_batch = DEFAULT_MAX_BATCH
            if raw:
                try:
                    max_batch = max(1, int(raw))
                except ValueError:
                    pass
        self.max_batch = int(max_batch)
        if ladder is not None:
            ladder = sorted({int(b) for b in ladder})
            if not ladder or ladder[0] < 1:
                raise ValueError(f"invalid bucket ladder {ladder!r}")
        else:
            ladder = _env_ladder(self.max_batch)
        self.ladder = list(ladder)
        self.max_wait = (
            max_wait_ms if max_wait_ms is not None
            else _env_float("HEAT_TPU_SERVE_MAX_WAIT_MS", DEFAULT_WAIT_MS)
        ) / 1e3
        self._endpoints: Dict[str, Endpoint] = {}
        self._stats: Dict[str, EndpointStats] = {}
        self._measured: Dict[tuple, int] = {}  # (name, bucket) -> bytes
        self.admission = AdmissionController(
            queue_max,
            measured_cost=lambda name, bucket: self._measured.get(
                (name, bucket)
            ),
        )
        self._queue: "queue.Queue" = queue.Queue()
        self._carry: Optional[_Request] = None
        self._lock = threading.Lock()
        self._thread: Optional[threading.Thread] = None
        self._closed = False
        self._draining = False
        # admitted-but-unresolved request count (NOT queue depth: a
        # request leaves the queue before its batch resolves). drain()
        # waits on this reaching zero, so in-flight batches finish.
        self._pending = 0
        self._pending_lock = threading.Lock()

    # -- registration --------------------------------------------------------

    def register(
        self, name: str, endpoint: Endpoint, *, replace: bool = False
    ) -> "Server":
        """Mount ``endpoint`` under ``name`` (the dispatch site becomes
        ``serve.<name>``). Re-registering a live name is an explicit
        *versioned publish* (ISSUE 16): it requires ``replace=True``,
        assigns the newcomer ``max(old, new) + 1`` when its version does
        not already supersede the old one, and swaps the endpoint in
        with one atomic dict assignment — the dispatch loop reads the
        endpoint exactly once per micro-batch, so a batch is served
        entirely by one version (bit-exact cutover between batches).
        Without ``replace=True`` a duplicate name raises instead of
        silently shadowing the fitted estimator. A same-aval publish
        keeps the warmed-cost memo and re-enters the warm programs; an
        aval change drops the memo (the old programs stay in the
        registry for any future endpoint with identical shapes)."""
        if not isinstance(endpoint, Endpoint):
            raise TypeError(
                f"endpoint must be a serve.Endpoint, got {type(endpoint)}"
            )
        if not name or "/" in name or ":" in name:
            raise ValueError(f"invalid endpoint name {name!r}")
        with self._lock:
            if self._closed:
                raise ServerClosedError("server is closed")
            old = self._endpoints.get(name)
            if old is not None:
                if not replace:
                    raise ValueError(
                        f"endpoint {name!r} is already registered "
                        f"(version {old.version}); re-registering a live "
                        f"name is a versioned publish — pass replace=True"
                    )
                if endpoint.version <= old.version:
                    endpoint.version = old.version + 1
                same_sig = (
                    old.program_key(0) == endpoint.program_key(0)
                )
                self._endpoints[name] = endpoint
                if not same_sig:
                    for key in [k for k in self._measured if k[0] == name]:
                        del self._measured[key]
                return self
            self._endpoints[name] = endpoint
            self._stats[name] = EndpointStats(name)
            for key in [k for k in self._measured if k[0] == name]:
                del self._measured[key]
        return self

    def endpoints(self) -> Dict[str, Endpoint]:
        return dict(self._endpoints)

    def endpoint_version(self, name: str) -> int:
        """The currently-mounted version of ``name`` (KeyError when the
        endpoint is unknown) — the transport stamps this into every
        response envelope so clients can observe rolling updates."""
        return self._endpoints[name].version

    def publish(self, name: str, endpoint: Endpoint, *, warm: bool = True) -> dict:
        """Versioned publish + compile accounting (ISSUE 16): swap
        ``endpoint`` in under ``name`` (``register(replace=True)``),
        re-warm it under a :class:`telemetry.CompileWatcher`, and emit a
        ``version_swap`` streaming event carrying the swap latency and
        the backend-compile count — the ``compiles_per_swap == 0``
        oracle for same-aval publishes. Returns ``{"name", "version",
        "seconds", "backend_compiles"}``."""
        t0 = time.perf_counter()
        self.register(name, endpoint, replace=True)
        compiles = 0
        if warm:
            report = self.warmup([name])
            compiles = int(report.get("backend_compiles", 0))
        version = self._endpoints[name].version
        out = {
            "name": name,
            "version": version,
            "seconds": round(time.perf_counter() - t0, 6),
            "backend_compiles": compiles,
        }
        from ..streaming import events as _stream_events

        _stream_events.emit(
            name, "version_swap",
            version=version, seconds=out["seconds"],
            backend_compiles=compiles,
        )
        return out

    # -- warm-up -------------------------------------------------------------

    def warmup(self, names: Optional[Sequence[str]] = None) -> dict:
        """Pre-trace (and execute once, on zeros) every registered
        endpoint's whole batch-size ladder so serving hits only warm
        programs. With an HBM budget armed, also pre-measures each
        bucket's compiled temp+output bytes for the admission
        controller. Returns ``{"endpoints", "programs",
        "backend_compiles", "seconds"}`` — ``backend_compiles`` counts
        real XLA builds in the window (0 on a re-warm)."""
        t0 = time.perf_counter()
        targets = list(names) if names is not None else list(self._endpoints)
        programs = 0
        budget_armed = memory_guard.budget_bytes() is not None
        with telemetry.CompileWatcher() as cw:
            for name in targets:
                ep = self._endpoints[name]  # KeyError = caller bug, loud
                for bucket in self.ladder:
                    if ep.is_sparse:
                        # sparse endpoints warm the whole (row bucket,
                        # nnz bucket) lattice — ragged steady-state
                        # traffic then lands only on warm programs
                        for nnz_cap in ep.nnz_ladder(bucket):
                            prog = self._program(name, ep, bucket, nnz_cap)
                            args = (
                                jnp.zeros((bucket + 1,), dtype=jnp.int32),
                                jnp.zeros((nnz_cap,), dtype=jnp.int32),
                                jnp.zeros((nnz_cap,), dtype=ep.dtype),
                            ) + tuple(ep.params)
                            out = prog(*args)
                            np.asarray(out)
                            programs += 1
                            if budget_armed:
                                self._measured[(name, bucket)] = max(
                                    self._measured.get((name, bucket), 0),
                                    memory_guard.program_bytes(prog, args),
                                )
                        continue
                    prog = self._program(name, ep, bucket)
                    zeros = jnp.zeros((bucket, ep.features), dtype=ep.dtype)
                    out = prog(zeros, *ep.params)
                    np.asarray(out)  # block: warm-up owns the compile wait
                    programs += 1
                    if budget_armed:
                        self._measured[(name, bucket)] = (
                            memory_guard.program_bytes(
                                prog, (zeros,) + tuple(ep.params)
                            )
                        )
        dt = time.perf_counter() - t0
        report = {
            "endpoints": len(targets),
            "programs": programs,
            "backend_compiles": cw.backend_compiles,
            "seconds": round(dt, 4),
        }
        if telemetry.enabled():
            telemetry.get_registry().emit(
                "serve", "warmup", event="warmup", **report
            )
        return report

    # -- request path --------------------------------------------------------

    def submit(self, name: str, payload, trace=_MINT) -> Future:
        """Admit + enqueue one request; returns a
        :class:`concurrent.futures.Future` resolving to the result rows
        (1-D payloads resolve to a single row). Sheds with
        :class:`ServerOverloadedError` (status 503) at the admission
        gate; a failed dispatch (after per-batch retries) resolves the
        future with the error.

        ``trace`` (ISSUE 17) selects the request's trace context: the
        default mints one here (in-process serving makes ``submit`` the
        ingress), an adopted :class:`~heat_tpu.serve.tracing.TraceContext`
        or wire dict continues an upstream router's trace, and ``None``
        means untraced (the transport's verdict for requests whose
        ingress sent no trace field). Tracing never changes the result —
        answers are bit-identical on and off."""
        with self._lock:
            if self._closed:
                raise ServerClosedError("server is closed")
            ep = self._endpoints.get(name)
        if ep is None:
            raise ValueError(
                f"unknown endpoint {name!r}; registered: "
                f"{sorted(self._endpoints)}"
            )
        if ep.is_sparse:
            from ..sparse.host import CsrRows

            squeeze = False
            if not isinstance(payload, CsrRows):
                # a dense row (or batch) is a legal sparse request too —
                # compact it so callers need not hand-build CSR
                dense = np.asarray(payload, dtype=ep.dtype)
                squeeze = dense.ndim == 1
                payload = CsrRows.from_dense(dense)
            if payload.cols != ep.features:
                raise ValueError(
                    f"endpoint {name!r} expects CSR rows over "
                    f"{ep.features} features, got {payload.cols}"
                )
            arr = CsrRows(
                payload.indptr, payload.indices,
                payload.values.astype(ep.dtype, copy=False), ep.features,
            )
        else:
            arr = np.asarray(payload, dtype=ep.dtype)
            squeeze = arr.ndim == 1
            if squeeze:
                arr = arr[None, :]
            if arr.ndim != 2 or arr.shape[1] != ep.features:
                raise ValueError(
                    f"endpoint {name!r} expects (rows, {ep.features}) "
                    f"payloads, got shape {np.asarray(payload).shape}"
                )
        st = self._stats[name]
        try:
            if self._draining:
                # drain-then-kill (ISSUE 12): a draining replica sheds
                # every NEW request 503-style so the router retries a
                # sibling, while queued + in-flight work still completes
                self.admission.shed(
                    name, "draining",
                    "server is draining (shutting down gracefully); "
                    "retry another replica",
                )
            self.admission.admit(
                name, ep, arr.shape[0], self._queue.qsize(), self.ladder
            )
        except ServerOverloadedError:
            st.record_shed()
            raise
        if trace is _MINT:
            ctx = tracing.mint("serve.submit")
        elif isinstance(trace, tracing.TraceContext):
            ctx = trace
        elif trace is not None:
            ctx = tracing.from_wire(trace)
        else:
            ctx = None
        req = _Request(name, arr, squeeze, ctx)
        with self._pending_lock:
            self._pending += 1
        st.record_request(req.rows)
        if telemetry.enabled():
            reg = telemetry.get_registry()
            reg.add("serve.requests", 1)
            reg.high_water("serve.queue_depth", self._queue.qsize() + 1)
        self._ensure_thread()
        self._queue.put(req)
        if self._closed:
            # close() may have drained the queue between our admission
            # check and the put — never strand a future
            self._drain_pending()
        return req.future

    def predict(self, name: str, payload, timeout: Optional[float] = 30.0):
        """Synchronous convenience: ``submit(...).result(timeout)``."""
        return self.submit(name, payload).result(timeout)

    # -- lifecycle -----------------------------------------------------------

    def drain(self, timeout: float = 30.0) -> bool:
        """Graceful shutdown, phase one (ISSUE 12): stop admitting —
        every new :meth:`submit` sheds with ``reason="draining"``
        (status 503, so a router retries siblings) — then wait for every
        already-admitted request (queued *and* in-flight batches) to
        resolve, and :meth:`close`. Returns ``True`` when the backlog
        fully resolved inside ``timeout`` (a ``False`` close still
        failed the leftovers with :class:`ServerClosedError`, nothing
        hangs). Idempotent; the replica SIGTERM handler runs exactly
        ``drain() -> telemetry.flush() -> exit 0``."""
        with self._lock:
            if self._closed:
                return True
            self._draining = True
        if telemetry.enabled():
            telemetry.get_registry().emit(
                "serve", "server", event="drain",
                pending=self._pending, queue_depth=self._queue.qsize(),
            )
        deadline = time.monotonic() + max(0.0, timeout)
        drained = False
        while True:
            with self._pending_lock:
                if self._pending == 0:
                    drained = True
            if drained or time.monotonic() >= deadline:
                break
            time.sleep(0.002)
        self.close()
        return drained

    @property
    def draining(self) -> bool:
        """Whether :meth:`drain` has begun (new submits shed 503)."""
        return self._draining

    def close(self, timeout: float = 10.0) -> None:
        """Stop accepting requests, drain the batcher, fail whatever is
        still pending with :class:`ServerClosedError`. Idempotent."""
        with self._lock:
            if self._closed:
                return
            self._closed = True
            thread = self._thread
        self._queue.put(_SHUTDOWN)
        if thread is not None:
            thread.join(timeout)
        self._drain_pending()

    def _drain_pending(self) -> None:
        """Fail every still-queued request with ServerClosedError (only
        called once the batcher is no longer consuming)."""
        leftovers = []
        if self._carry is not None:
            leftovers.append(self._carry)
            self._carry = None
        while True:
            try:
                item = self._queue.get_nowait()
            except queue.Empty:
                break
            if item is not _SHUTDOWN:
                leftovers.append(item)
        for req in leftovers:
            _resolve(
                req.future,
                exc=ServerClosedError("server closed with request pending"),
            )
        if leftovers:
            with self._pending_lock:
                self._pending -= len(leftovers)

    def __enter__(self) -> "Server":
        return self

    def __exit__(self, *exc) -> bool:
        self.close()
        return False

    # -- checkpoint/restore --------------------------------------------------

    def save(self, path: str) -> str:
        """Checkpoint every endpoint's fitted parameters + static config
        (CRC-verified blobs, atomic directory swap —
        :mod:`heat_tpu.resilience.checkpoint`). The server keeps
        serving; restore with :meth:`Server.restore`."""
        from .. import resilience

        leaves: List[np.ndarray] = []
        records = []
        with self._lock:
            for name in sorted(self._endpoints):
                ep = self._endpoints[name]
                rec = ep.describe()
                rec["name"] = name
                records.append(rec)
                leaves.extend(np.asarray(p) for p in ep.params)
        return resilience.save_checkpoint(
            leaves, path,
            extra={"serve": {"version": 1, "endpoints": records},
                   "algo": "serve"},
        )

    @classmethod
    def restore(cls, path: str, **server_kwargs) -> "Server":
        """Rebuild a server (endpoints + fitted parameters) from a
        :meth:`save` checkpoint — no refit. Call :meth:`warmup` after;
        identical parameter shapes re-enter the already-cached programs,
        so a restore-then-warm on a live process compiles nothing."""
        from .. import resilience

        leaves, extra = resilience.load_checkpoint(path, with_extra=True)
        meta = (extra or {}).get("serve")
        if not meta or "endpoints" not in meta:
            raise resilience.CheckpointError(
                f"{path!r} is not a serve checkpoint (algo="
                f"{(extra or {}).get('algo')!r})"
            )
        server = cls(**server_kwargs)
        off = 0
        for rec in meta["endpoints"]:
            n = int(rec["n_params"])
            server.register(rec["name"], rebuild(rec, leaves[off:off + n]))
            off += n
        if off != len(leaves):
            raise resilience.CheckpointError(
                f"serve checkpoint {path!r} holds {len(leaves)} parameter "
                f"blobs but the manifest accounts for {off}"
            )
        return server

    # -- introspection -------------------------------------------------------

    def stats(self) -> dict:
        """Live serving stats: per-endpoint request/batch/latency
        aggregates, queue depth, ladder state, shed/degrade counts, and
        the ``serve.*`` program-registry counters (the zero-recompile
        oracle)."""
        return {
            "endpoints": {
                name: s.snapshot() for name, s in self._stats.items()
            },
            "versions": {
                name: ep.version for name, ep in self._endpoints.items()
            },
            "queue_depth": self._queue.qsize(),
            "ladder": list(self.ladder),
            "bucket_cap": self.admission.bucket_cap(self.ladder),
            "shed": self.admission.sheds,
            "degrades": self.admission.degrades,
            "programs": program_cache.site_stats("serve."),
            "pending": self._pending,
            "draining": self._draining,
            "closed": self._closed,
        }

    def metrics(self) -> dict:
        """The mergeable form of :meth:`stats` (ISSUE 17, served on
        ``GET /metrics``): per-endpoint cumulative tallies with RAW
        latency-histogram bucket counts (bucket-wise addition across
        replicas is exact — :meth:`LatencyHistogram.merge`), endpoint
        versions (fleet version-lag detection), the ``serve.*``
        program-registry counters, and the process's telemetry counters
        (includes the ``tracing.*`` pair the CI off-run asserts zero)."""
        snap = telemetry.get_registry().snapshot()
        return {
            "endpoints": {
                name: s.raw_snapshot() for name, s in self._stats.items()
            },
            "versions": {
                name: ep.version for name, ep in self._endpoints.items()
            },
            "queue_depth": self._queue.qsize(),
            "shed": self.admission.sheds,
            "programs": program_cache.site_stats("serve."),
            "counters": snap["counters"],
        }

    # -- internals -----------------------------------------------------------

    def _ensure_thread(self) -> None:
        with self._lock:
            if self._thread is None or not self._thread.is_alive():
                self._thread = threading.Thread(
                    target=self._loop, name="heat_tpu.serve.batcher",
                    daemon=True,
                )
                self._thread.start()

    def _bucket_for(self, rows: int) -> int:
        for b in self.ladder:
            if b >= rows:
                return b
        return self.ladder[-1]

    def _program(
        self, name: str, ep: Endpoint, bucket: int,
        nnz_cap: Optional[int] = None,
    ):
        return program_cache.cached_program(
            f"serve.{name}", ep.program_key(bucket, nnz_cap), ep.build
        )

    def _loop(self) -> None:
        while True:
            if self._carry is not None:
                item, self._carry = self._carry, None
            else:
                try:
                    item = self._queue.get(timeout=0.1)
                except queue.Empty:
                    if self._closed:
                        return
                    continue
            if item is _SHUTDOWN:
                return
            batch = [item]
            rows = item.rows
            cap = self.admission.bucket_cap(self.ladder)
            deadline = time.perf_counter() + self.max_wait
            while rows < cap:
                try:
                    nxt = self._queue.get_nowait()
                except queue.Empty:
                    rem = deadline - time.perf_counter()
                    if rem <= 0:
                        break
                    try:
                        nxt = self._queue.get(timeout=rem)
                    except queue.Empty:
                        break
                if nxt is _SHUTDOWN:
                    self._run_batch(batch)
                    return
                if nxt.endpoint != item.endpoint:
                    # FIFO segments: a different endpoint closes this
                    # micro-batch and opens the next — no reordering
                    self._carry = nxt
                    break
                batch.append(nxt)
                rows += nxt.rows
            self._run_batch(batch)

    def _run_batch(self, reqs: List[_Request]) -> None:
        try:
            self._dispatch_batch(reqs)
        finally:
            # every request in this batch is resolved by now (result,
            # error, or the idempotent no-op if close() raced us) — it
            # stops counting against drain()
            with self._pending_lock:
                self._pending -= len(reqs)

    def _dispatch_batch(self, reqs: List[_Request]) -> None:
        name = reqs[0].endpoint
        ep = self._endpoints[name]
        st = self._stats[name]
        rows = sum(r.rows for r in reqs)
        # request-trace hop decomposition (ISSUE 17): ctxs is empty for
        # every untraced batch (tracing off, telemetry off, or nothing
        # sampled), and all per-hop clock reads stay behind that check —
        # the untraced dispatch path is timing-identical to pre-17.
        ctxs = [r.ctx for r in reqs if r.ctx is not None]
        t_start = time.perf_counter()
        wall0 = time.time() if ctxs else 0.0
        if ctxs:
            # serve.queue: replica ingress -> the batcher picked this
            # request up (one span per traced request; the coalesce
            # window is accounted to the batch, not the stragglers)
            for r in reqs:
                if r.ctx is not None:
                    # ingress marks the hop whose process MINTED the
                    # context (counter-pairing: one ingress span per
                    # tracing.sampled increment, so an offline sink
                    # replay reconstructs the sampled tally). Contexts
                    # adopted off the wire were counted at the router.
                    tracing.hop(
                        "serve.queue", (r.ctx,), r.t_wall,
                        max(0.0, wall0 - r.t_wall), endpoint=name,
                        ingress=r.ctx.parent_span == "serve.submit",
                    )
        if ep.is_sparse:
            from ..sparse.host import CsrRows

            x = (
                reqs[0].array if len(reqs) == 1
                else CsrRows.concat([r.array for r in reqs])
            )
        else:
            x = (
                reqs[0].array if len(reqs) == 1
                else np.concatenate([r.array for r in reqs], axis=0)
            )
        if ctxs:
            tracing.hop(
                "serve.coalesce", ctxs, wall0,
                time.perf_counter() - t_start, endpoint=name,
                requests=len(reqs), rows=rows,
            )
        cap = self.admission.bucket_cap(self.ladder)
        t0 = time.perf_counter()
        pad_s = 0.0
        exec_s = 0.0
        try:
            pieces = []
            padded_total = 0
            # rows == 0 (a valid empty query) still dispatches one
            # all-pad bucket so the result carries the endpoint's real
            # output shape/dtype with zero rows
            starts = range(0, rows, cap) if rows else (0,)
            for start in starts:
                chunk = x[start:start + cap]
                crows = chunk.shape[0]
                bucket = self._bucket_for(crows)
                pad = bucket - crows
                padded_total += pad
                if ep.is_sparse:
                    tp = time.perf_counter() if ctxs else 0.0
                    nnz_cap = ep.nnz_cap_for(bucket, chunk.nnz)
                    padded = chunk.padded(bucket, nnz_cap)
                    prog = self._program(name, ep, bucket, nnz_cap)
                    if ctxs:
                        te = time.perf_counter()
                        pad_s += te - tp
                    out = prog(
                        jnp.asarray(padded.indptr.astype(np.int32)),
                        jnp.asarray(padded.indices),
                        jnp.asarray(padded.values),
                        *ep.params,
                    )
                    pieces.append(np.asarray(out)[:crows])
                    if ctxs:
                        exec_s += time.perf_counter() - te
                    continue
                tp = time.perf_counter() if ctxs else 0.0
                if pad:
                    chunk = np.concatenate(
                        [chunk, np.zeros((pad, ep.features), dtype=ep.dtype)],
                        axis=0,
                    )
                prog = self._program(name, ep, bucket)
                if ctxs:
                    te = time.perf_counter()
                    pad_s += te - tp
                out = prog(jnp.asarray(chunk), *ep.params)
                pieces.append(np.asarray(out)[:crows])
                if ctxs:
                    exec_s += time.perf_counter() - te
            result = pieces[0] if len(pieces) == 1 else np.concatenate(
                pieces, axis=0
            )
        except Exception as e:  # noqa: BLE001 — per-batch failure isolation
            # the guard already retried transients per batch; whatever
            # reaches here is terminal for THESE requests only — the
            # batcher thread (and every other queued request) lives on
            st.record_error(len(reqs))
            if telemetry.enabled():
                reg = telemetry.get_registry()
                reg.add("serve.failed_requests", len(reqs))
                reg.emit(
                    "serve", name, event="batch_failed",
                    requests=len(reqs), rows=rows, error=repr(e),
                )
            for r in reqs:
                _resolve(r.future, exc=e)
            return
        dt = time.perf_counter() - t0
        st.record_batch(rows, padded_total)
        now = time.perf_counter()
        if ctxs:
            # pad/execute interleave per chunk, so each gets ONE span
            # with its accumulated seconds, anchored where the dispatch
            # loop began (wall = wall0 + perf-clock delta: both stamps
            # were taken at the same instant, so the offset is exact)
            wall_t0 = wall0 + (t0 - t_start)
            tracing.hop(
                "serve.pad", ctxs, wall_t0, pad_s,
                endpoint=name, padded_rows=padded_total,
            )
            tracing.hop(
                "serve.execute", ctxs, wall_t0 + pad_s, exec_s,
                endpoint=name, rows=rows,
            )
        tel = telemetry.enabled()
        reg = telemetry.get_registry() if tel else None
        if tel:
            reg.add("serve.batches", 1)
            reg.add("serve.batch_rows", rows)
            reg.add("serve.padded_rows", padded_total)
            reg.emit(
                "serve_batch", name, rows=rows, requests=len(reqs),
                padded_rows=padded_total, seconds=dt,
                queue_depth=self._queue.qsize(),
                occupancy=rows / max(rows + padded_total, 1),
            )
        off = 0
        for r in reqs:
            piece = result[off:off + r.rows]
            off += r.rows
            latency = now - r.t_submit
            st.record_done(latency)
            if tel:
                reg.emit(
                    "serve_request", name, seconds=latency, rows=r.rows,
                    ok=True,
                )
            _resolve(r.future, piece[0] if r.squeeze else piece)
        if ctxs:
            tracing.hop(
                "serve.reply", ctxs, wall0 + (now - t_start),
                time.perf_counter() - now, endpoint=name,
                requests=len(reqs),
            )
