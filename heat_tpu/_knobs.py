"""Central registry of every ``HEAT_TPU_*`` environment knob (ISSUE 10).

Before this module, ~20 ``os.environ`` reads were scattered across the
package — each with its own parse convention, its own default, and its own
(often missing) documentation. The static analyzer's HL005 rule now rejects
any direct ``HEAT_TPU_*`` environ read outside this file, so every knob is
declared exactly once, carrying its type, default, and docstring. The
``docs/API.md`` knob table is generated from :func:`markdown_table` and a
test pins the two in sync, so the env-var docs can never drift again.

This module is deliberately a **leaf**: stdlib imports only, no package
imports. ``heat_tpu.telemetry`` and ``heat_tpu.resilience`` load *before*
``heat_tpu.core`` during ``import heat_tpu``, so the registry must be
importable from anywhere in the package graph without touching
``heat_tpu.core.__init__``. The public face is
:mod:`heat_tpu.core.knobs`, a re-export of this module.

Usage inside the package::

    from heat_tpu import _knobs as knobs       # safe at any import depth
    raw = knobs.raw("HEAT_TPU_FUSION", "1")    # registered-name-checked
    on = knobs.get("HEAT_TPU_FUSION")          # typed parse

Modules with bespoke parse rules (byte-suffix budgets, fault specs,
comma ladders) call :func:`raw` and keep their local parser; simple
bool/int/float/enum knobs can use :func:`get` directly. Either way the
read is registered, typed, and documented here.
"""

from __future__ import annotations

import contextlib
import os
import threading
from dataclasses import dataclass, field
from typing import Dict, Iterable, Optional, Tuple, Union

__all__ = [
    "Knob",
    "Tunable",
    "REGISTRY",
    "raw",
    "get",
    "names",
    "tunables",
    "default_raw",
    "overrides",
    "set_override",
    "clear_overrides",
    "overlay",
    "markdown_table",
    "FALSY",
    "TRUTHY",
]

# Shared string-to-bool conventions. Default-ON knobs ("is the feature
# still enabled?") treat anything outside FALSY as on; default-OFF
# activation knobs ("did the user opt in?") require an explicit TRUTHY.
FALSY = ("0", "false", "off", "no")
TRUTHY = ("1", "true", "yes", "on")


@dataclass(frozen=True)
class Tunable:
    """Autotuner metadata for one knob (ISSUE 11): the candidate search
    space declared NEXT TO the knob, not hardcoded in the tuner.

    ``values`` are raw environment strings (what ``heat_tpu.autotune``
    installs into the knob overlay while searching). ``kind`` is the
    constraint class the trial validator enforces:

    * ``exact`` — every candidate value must leave results bit-identical
      (fusion depth, relayout plan, ring overlap); validated by digest.
    * ``lossy`` — values other than ``exact_value`` may change numerics
      (collective precision, cdist dot strategy, non-exact serve
      kernels); only searched under a caller-stated error budget, and a
      winning lossy pick must measure within it.
    * ``neutral`` — scheduling/throughput only (serve ladder, gather
      window, queue bound); results are still digest-validated where the
      workload produces any.
    """

    values: Tuple[str, ...]
    kind: str  # 'exact' | 'lossy' | 'neutral'
    exact_value: Optional[str] = None  # lossy knobs: the exact-semantics value


@dataclass(frozen=True)
class Knob:
    """One declared environment knob.

    ``type`` is one of ``bool`` / ``int`` / ``float`` / ``str`` / ``enum``
    / ``bytes`` (byte count with K/M/G/T suffixes) / ``spec`` (structured
    mini-language parsed by its owning module). ``default`` is the
    effective value when the variable is unset or malformed (None = the
    feature is simply off / derived elsewhere). ``scope`` groups the docs
    table: ``runtime`` knobs are read by the package itself, ``bench`` by
    the benchmark harnesses, ``ci`` by ``scripts/run_ci.sh``, ``tests`` by
    the pytest conftest. ``tunable`` (perf-relevant knobs only) declares
    the autotuner's candidate values and constraint class.
    """

    name: str
    type: str
    default: Union[bool, int, float, str, None]
    doc: str
    choices: Tuple[str, ...] = field(default=())
    scope: str = "runtime"
    tunable: Optional[Tunable] = None


REGISTRY: Dict[str, Knob] = {}


def _register(
    name: str,
    type: str,
    default,
    doc: str,
    *,
    choices: Tuple[str, ...] = (),
    scope: str = "runtime",
    tunable: Optional[Tunable] = None,
) -> None:
    if name in REGISTRY:
        raise ValueError(f"knob {name!r} registered twice")
    if not name.startswith("HEAT_TPU_"):
        raise ValueError(f"knob {name!r} must be namespaced HEAT_TPU_*")
    if tunable is not None:
        if tunable.kind not in ("exact", "lossy", "neutral"):
            raise ValueError(
                f"knob {name!r}: tunable kind {tunable.kind!r} is not one "
                "of exact/lossy/neutral"
            )
        if not tunable.values or not all(
            isinstance(v, str) and v for v in tunable.values
        ):
            raise ValueError(
                f"knob {name!r}: tunable values must be non-empty raw "
                f"strings, got {tunable.values!r}"
            )
        if tunable.kind == "lossy" and tunable.exact_value is None:
            raise ValueError(
                f"knob {name!r}: a lossy tunable must declare its "
                "exact-semantics value"
            )
    REGISTRY[name] = Knob(
        name, type, default, doc, choices=choices, scope=scope,
        tunable=tunable,
    )


# -- runtime knobs ------------------------------------------------------------

_register(
    "HEAT_TPU_TELEMETRY", "bool", False,
    "Turn telemetry recording on at `import heat_tpu` "
    "(docs/OBSERVABILITY.md). Counters, spans, collective cost events and "
    "compile accounting; one flag check per call site when off.",
)
_register(
    "HEAT_TPU_TELEMETRY_SINK", "str", None,
    "JSONL file that telemetry events stream to; unset records in memory "
    "only.",
)
_register(
    "HEAT_TPU_HLO_AUDIT", "bool", False,
    "Lower-compile every cached program and fail on predicted-vs-emitted "
    "collective drift (telemetry/hlo.py; the ground-truth auditor).",
)
_register(
    "HEAT_TPU_HLO_TOLERANCE", "float", 0.1,
    "Relative wire-byte drift tolerated by the HLO auditor before an "
    "audit fails.",
)
_register(
    "HEAT_TPU_PROGRAM_CACHE", "int", 512,
    "Max entries in the process-global compiled-program registry "
    "(core/program_cache.py); LRU eviction beyond it.",
)
_register(
    "HEAT_TPU_COMPILE_CACHE", "str", None,
    "Directory for the persistent on-disk XLA compilation cache; read at "
    "`import heat_tpu`. A second process deserializes instead of "
    "recompiling (docs/TUNING_RUNBOOK.md).",
)
_register(
    "HEAT_TPU_FUSION", "bool", True,
    "Elementwise defer-and-fuse dispatch (core/fusion.py). `0` restores "
    "pure-eager dispatch bit-for-bit.",
    tunable=Tunable(("1", "0"), "exact"),
)
_register(
    "HEAT_TPU_FUSION_REDUCE", "bool", True,
    "Fusion 2.0 through-reduction absorption and matmul/moments epilogue "
    "grafting. `0` restores flush-at-reduction dispatch.",
    tunable=Tunable(("1", "0"), "exact"),
)
_register(
    "HEAT_TPU_FUSION_DEPTH", "int", 16,
    "Max fused-chain depth before a forced flush (node cap is 4x this).",
    tunable=Tunable(("4", "8", "16", "32", "64"), "exact"),
)
_register(
    "HEAT_TPU_RELAYOUT_PLAN", "enum", "auto",
    "Relayout planning policy (core/relayout_planner.py): `auto` picks "
    "from tensor size vs the HBM budget; the rest force one decomposition.",
    choices=("auto", "monolithic", "chunked", "alltoall"),
    tunable=Tunable(("auto", "monolithic", "chunked", "alltoall"), "exact"),
)
_register(
    "HEAT_TPU_RING_OVERLAP", "bool", True,
    "Double-buffered ring schedules (cdist/manhattan/rbf, TSQR gram "
    "ring): issue the next hop's ppermute under the local GEMM. `0` "
    "restores the serial p-hop kernels verbatim.",
    tunable=Tunable(("1", "0"), "exact"),
)
_register(
    "HEAT_TPU_COLLECTIVE_PREC", "enum", "off",
    "Wire precision of payload-moving collectives "
    "(core/collective_prec.py, ISSUE 9): bf16 cast-move-upcast, int8 / "
    "blockwise EQuARX max-abs quantization. Exact-semantics sites pin "
    "`off` per call.",
    choices=("off", "bf16", "int8", "blockwise"),
    tunable=Tunable(
        ("off", "bf16", "int8", "blockwise"), "lossy", exact_value="off"
    ),
)
_register(
    "HEAT_TPU_COLLECTIVE_PREC_BLOCK", "int", 128,
    "Blockwise-quantization scale granularity in elements.",
    tunable=Tunable(("64", "128", "256"), "lossy", exact_value="128"),
)
_register(
    "HEAT_TPU_CDIST_PREC", "enum", "bf16x3",
    "In-kernel dot strategy of the fused pallas cdist kernel; the "
    "one-line revert knob while bf16x3 is unmeasured on chip "
    "(docs/TUNING_RUNBOOK.md).",
    choices=("bf16x3", "default", "high", "highest"),
    tunable=Tunable(
        ("bf16x3", "default", "high", "highest"), "lossy",
        exact_value="highest",
    ),
)
_register(
    "HEAT_TPU_RETRIES", "int", 0,
    "Transient-failure retry budget of the guarded dispatch sites "
    "(resilience/guard.py); 0 = retries off.",
)
_register(
    "HEAT_TPU_RETRY_BASE", "float", 0.05,
    "First retry backoff in seconds (doubles per attempt, jittered).",
)
_register(
    "HEAT_TPU_RETRY_CAP", "float", 2.0,
    "Retry backoff ceiling in seconds.",
)
_register(
    "HEAT_TPU_HBM_BUDGET", "bytes", None,
    "Per-device memory budget for pre-flight admission (plain bytes or "
    "K/M/G/T suffixes, e.g. `8G`). Unset disables the guard; malformed "
    "values disable it too (resilience/memory_guard.py).",
)
_register(
    "HEAT_TPU_FAULTS", "spec", None,
    "Deterministic fault-injection spec installed at `import heat_tpu` "
    "(resilience/faults.py), e.g. `relayout:kind=resource:calls=1`.",
)
_register(
    "HEAT_TPU_SERVE_MAX_BATCH", "int", 64,
    "Top bucket of the serving micro-batch ladder (serve/server.py).",
    tunable=Tunable(("16", "32", "64", "128"), "neutral"),
)
_register(
    "HEAT_TPU_SERVE_LADDER", "str", None,
    "Explicit comma-separated bucket ladder; unset derives powers of two "
    "up to the max batch.",
)
_register(
    "HEAT_TPU_SERVE_MAX_WAIT_MS", "float", 2.0,
    "Micro-batch gather window in milliseconds.",
    tunable=Tunable(("0.5", "1.0", "2.0", "4.0"), "neutral"),
)
_register(
    "HEAT_TPU_SERVE_QUEUE_MAX", "int", 1024,
    "Admission-control bound on pending serving requests (503-style shed "
    "beyond it).",
    tunable=Tunable(("256", "1024", "4096"), "neutral"),
)
_register(
    "HEAT_TPU_SERVE_EXACT", "bool", True,
    "Batch-shape-stable exact serving kernels (batched == solo "
    "bit-identity); `0` selects the MXU GEMM forms.",
    tunable=Tunable(("1", "0"), "lossy", exact_value="1"),
)

# -- hierarchy-aware tiered collectives (heat_tpu/core/topology.py, ISSUE 15) -

_register(
    "HEAT_TPU_TOPOLOGY", "str", None,
    "Declared 2-level (node x local) factorization of the device mesh, "
    "e.g. `2x4`: `node` is the slow (DCN) tier, `local` the fast (ICI) "
    "tier (core/topology.py). Unset auto-detects: the host-process "
    "structure on real multi-host hardware, the DASO-style emulated "
    "2-node split on a single even-sized host mesh. Malformed or "
    "mismatched values (node*local != mesh size) fall back to "
    "auto-detection.",
)
_register(
    "HEAT_TPU_HIERARCHICAL", "bool", False,
    "Tiered lowering of the payload-moving MeshCommunication wrappers "
    "(psum/all_gather/reduce_scatter/all_to_all): in-node reduce-scatter "
    "-> cross-node collective over the 1/local shard -> in-node "
    "all-gather, with per-tier wire precision (exact inside the node, "
    "HEAT_TPU_HIERARCHICAL_PREC across). `0` (default) keeps the flat "
    "lowering bit-for-bit.",
    tunable=Tunable(("0", "1"), "exact"),
)
_register(
    "HEAT_TPU_HIERARCHICAL_PREC", "str", None,
    "Wire precision of the CROSS-NODE tier of a tiered collective "
    "(core/topology.py; the DCN wire): off | bf16 | int8 | blockwise. "
    "Unset inherits HEAT_TPU_COLLECTIVE_PREC; the in-node (ICI) tier "
    "always moves exact.",
    tunable=Tunable(
        ("off", "bf16", "int8", "blockwise"), "lossy", exact_value="off"
    ),
)
_register(
    "HEAT_TPU_DCN_PREMIUM", "float", 8.0,
    "Relative cost of one cross-node (DCN) wire byte vs one in-node "
    "(ICI) byte in the analytic cost model "
    "(telemetry/collectives.weighted_wire): the planner and autotuner "
    "price tiered vs flat lowerings with DCN bytes multiplied by this "
    "factor. ~8-10 matches the production ICI/DCN bandwidth gap.",
)

# -- full FSDP parameter sharding (heat_tpu/parallel/fsdp.py, ISSUE 18) -------

_register(
    "HEAT_TPU_FSDP", "bool", False,
    "Full FSDP parameter sharding in heat_tpu.nn.FSDP: parameters live "
    "as flat 1/p shards on the mesh and each layer's weights are "
    "all-gathered just-in-time (tiered under HEAT_TPU_HIERARCHICAL=1), "
    "consumed, and re-scattered through the gather's transpose. `0` "
    "(default) keeps the replicated DataParallel dispatch bit-for-bit "
    "— the FSDP wrapper falls back to the identical replicated step "
    "program.",
    tunable=Tunable(("0", "1"), "exact"),
)
_register(
    "HEAT_TPU_FSDP_PREFETCH", "int", 1,
    "FSDP gather-prefetch depth: how many layers AHEAD of the one "
    "computing the weight all-gather is issued (parallel/fsdp.py "
    "prefetch window; the PR 6 ring-overlap trick applied to the "
    "weight stream, arXiv:2211.05322). Depth d keeps at most d+1 "
    "layers' gathered weights live — 0 is fully serial "
    "(minimum memory), larger depths give XLA's latency-hiding "
    "scheduler room to hide the gather under the previous layers' "
    "GEMMs. Pure scheduling: outputs are bit-identical at every depth.",
    tunable=Tunable(("0", "1", "2"), "neutral"),
)
_register(
    "HEAT_TPU_FSDP_PREC", "str", None,
    "Wire precision of FSDP weight gathers (and their transpose "
    "reduce-scatters) for partition rules that do not pin one: off | "
    "bf16 | int8 | blockwise. Unset inherits the tiered cross-node "
    "chain (HEAT_TPU_HIERARCHICAL_PREC, then HEAT_TPU_COLLECTIVE_PREC) "
    "under HEAT_TPU_HIERARCHICAL=1, and `off` (exact) on a flat mesh — "
    "compressed weight gathers change the model every step, so the "
    "flat default stays bit-exact.",
    tunable=Tunable(
        ("off", "bf16", "int8", "blockwise"), "lossy", exact_value="off"
    ),
)

# -- pipeline parallelism knobs (heat_tpu/parallel, ISSUE 19) -----------------

_register(
    "HEAT_TPU_PIPELINE_SCHEDULE", "enum", "gpipe",
    "Pipeline-training schedule of ht.nn.Pipeline / parallel/pipeline.py "
    "site pipeline.step (parallel/schedule.py tables): `gpipe` (default "
    "— all-forward wave, flush, all-backward wave, bit-compat with the "
    "historical kernel lineage) or `1f1b` (PipeDream-flush one-forward-"
    "one-backward: same results bit-for-bit — every stage still runs "
    "its backwards in increasing microbatch order — with the activation "
    "stash cut from M to min(S, M) in-flight microbatches and strictly "
    "fewer steady-window bubble ticks whenever M > 1 and S > 2).",
    choices=("gpipe", "1f1b"),
    tunable=Tunable(("gpipe", "1f1b"), "exact"),
)
_register(
    "HEAT_TPU_PIPELINE_STAGES", "int", 0,
    "Stage count of the pipeline mapping (parallel/schedule.plan_stages). "
    "0 (default) = auto: the node count of an ACTIVE 2-level topology "
    "(stages ARE the HEAT_TPU_TOPOLOGY node groups — every inter-stage "
    "hop crosses the DCN tier, and the `local` positions inside a stage "
    "keep the FSDP weight tier), else one stage per mesh position. Must "
    "divide the mesh size.",
)
_register(
    "HEAT_TPU_PIPELINE_MICROBATCHES", "int", 0,
    "Microbatch count M of ht.nn.Pipeline steps. 0 (default) = auto "
    "(the stage count S, the classic balanced point: bubble fraction "
    "(S-1)/(S+M-1) at M=S). Must divide the batch. Pure scheduling at "
    "fixed M; CHANGING M regroups the per-microbatch loss mean and "
    "gradient accumulation, so M itself tunes as a neutral axis only "
    "through the autotuner's guarded measured trials.",
    tunable=Tunable(("0", "2", "4", "8"), "neutral"),
)

# -- sparse container knobs (heat_tpu/sparse, ISSUE 13) -----------------------

_register(
    "HEAT_TPU_SPARSE_DENSE_THRESHOLD", "float", 0.25,
    "Density (nnz / rows*cols) above which sparse construction paths "
    "fall back to the dense pipeline (heat_tpu/sparse; the "
    "graph.Laplacian eNeighbour path densifies past it — a CSR denser "
    "than this moves more bytes than the dense GEMM it replaces).",
)
_register(
    "HEAT_TPU_SPARSE_SPMV_PREC", "enum", "off",
    "Wire precision of the float VALUE payloads in the sparse "
    "spmv/spmm collectives (operand gather + result all-reduce, "
    "heat_tpu/sparse/ops.py). Default pinned exact: index/indptr "
    "payloads never ride these hops at all (they stay shard-local), "
    "and the default keeps Krylov matvecs bit-stable. `bf16` moves the "
    "gathered operand as the uint16 bit pattern and the all-reduce on "
    "a bf16 payload.",
    choices=("off", "bf16"),
    tunable=Tunable(("off", "bf16"), "lossy", exact_value="off"),
)

# -- network serving tier knobs (heat_tpu/serve/net, ISSUE 12) ----------------

_register(
    "HEAT_TPU_SERVE_NET_PORT", "int", 0,
    "HTTP listen port of a serving replica (serve/net/transport.py). "
    "0 (the default) binds an ephemeral port — the replica prints the "
    "bound port in its ready line, which is how ReplicaPool wires the "
    "router without port collisions.",
)
_register(
    "HEAT_TPU_SERVE_NET_REPLICAS", "int", 2,
    "Default replica-process count of serve.net.ReplicaPool (each "
    "replica restores the endpoint checkpoint and warms from the shared "
    "HEAT_TPU_COMPILE_CACHE / HEAT_TPU_TUNE_DB).",
)
_register(
    "HEAT_TPU_SERVE_NET_POLL_MS", "float", 25.0,
    "Router /stats poll interval in milliseconds: refreshes the "
    "least-loaded scores of healthy replicas and health-probes evicted "
    "ones for re-add (serve/net/router.py).",
    tunable=Tunable(("10", "25", "50", "100"), "neutral"),
)
_register(
    "HEAT_TPU_SERVE_NET_RETRIES", "int", 2,
    "Router sibling-retry cap: how many ADDITIONAL replicas a request "
    "that was shed (503) or met a connect-refused replica is offered "
    "before the client sees the failure. In-flight connection drops are "
    "never blindly retried (the request may have executed).",
)

# -- autoscaling / priority / hedging knobs (serve/net, ISSUE 20) -------------

_register(
    "HEAT_TPU_AUTOSCALE_MIN", "int", 1,
    "Lower replica bound of serve.net.AutoscaleController: scale-down "
    "decisions clamp here (the pool never drains below it), so a "
    "diurnal trough cannot leave the endpoint cold.",
)
_register(
    "HEAT_TPU_AUTOSCALE_MAX", "int", 4,
    "Upper replica bound of the autoscale controller: scale-up clamps "
    "here (capacity/cost ceiling). A clamped-at-max tick is counted "
    "(`clamped_max`) so saturation is visible in stats().",
)
_register(
    "HEAT_TPU_AUTOSCALE_TICK_S", "float", 1.0,
    "Control-loop period of AutoscaleController.start() in seconds. "
    "Ticks observe, then maybe act; all cooldowns/streaks below are "
    "expressed in ticks or seconds of this clock.",
)
_register(
    "HEAT_TPU_AUTOSCALE_UP_COOLDOWN_S", "float", 5.0,
    "Minimum seconds between successive scale-UPS: lets the previous "
    "replica finish warm-up and absorb load before the controller "
    "decides more capacity is still needed (anti-flap, up side).",
)
_register(
    "HEAT_TPU_AUTOSCALE_DOWN_COOLDOWN_S", "float", 30.0,
    "Minimum seconds after ANY scaling action before a scale-DOWN: "
    "asymmetric hysteresis (down much slower than up) so a load dip "
    "right after a spike does not bounce replicas.",
)
_register(
    "HEAT_TPU_AUTOSCALE_BACKLOG_HIGH", "float", 4.0,
    "Per-replica backlog (queued + in-flight per live replica) above "
    "which a tick counts toward the sustained-pressure streak that "
    "triggers scale-up (see HEAT_TPU_AUTOSCALE_BACKLOG_TICKS). An "
    "`slo_burn` breach scales up immediately, bypassing the streak.",
)
_register(
    "HEAT_TPU_AUTOSCALE_BACKLOG_TICKS", "int", 2,
    "Consecutive over-backlog ticks required before a backlog-driven "
    "scale-up (debounce: one bursty tick is not a trend).",
)
_register(
    "HEAT_TPU_AUTOSCALE_IDLE_LOW", "float", 0.5,
    "Per-replica backlog below which a tick counts toward the "
    "drain-idle streak that triggers scale-down; any shed activity in "
    "the window resets the streak.",
)
_register(
    "HEAT_TPU_AUTOSCALE_IDLE_TICKS", "int", 5,
    "Consecutive idle ticks required before a scale-down (the "
    "drain-idle window; long relative to BACKLOG_TICKS — giving back "
    "capacity is cheap to delay, missing the SLO is not).",
)
_register(
    "HEAT_TPU_AUTOSCALE_SPAWN_RETRIES", "int", 2,
    "Extra spawn attempts ReplicaPool.spawn() makes after a replica "
    "dies during warmup (each failure is reaped — killed, logged, "
    "evented `spawn_fail`, never left a zombie target) with "
    "exponential backoff between attempts.",
)
_register(
    "HEAT_TPU_SERVE_PRIORITY_WEIGHTS", "str", "",
    "Priority-class weight table of the router's weighted-fair "
    "admission queue, e.g. 'latency=8,bulk=1'. Empty = every class "
    "weighs 1.0 (plain FIFO). Classes are attached per endpoint "
    "(Router.set_priority) or per request (submit(priority=...)); "
    "dispatch order follows smooth weighted round-robin over nonempty "
    "classes, and sheds take the newest job of the lowest-weight class "
    "first.",
)
_register(
    "HEAT_TPU_SERVE_PRIORITY_QUEUE_MAX", "int", 0,
    "Bound on the router's admission queue (0 = unbounded). When full, "
    "an arriving job sheds the newest queued job of the lowest-weight "
    "class strictly below its own weight — or is itself shed if no "
    "such victim exists — so a bulk tenant cannot starve a "
    "latency-sensitive one under overload.",
)
_register(
    "HEAT_TPU_HEDGE_ENABLE", "bool", False,
    "Hedged retries (router): after the hedge delay, duplicate a "
    "straggling in-flight request to an idle sibling replica, take the "
    "first answer, cancel the loser. Requires idempotent endpoints "
    "(both arms may execute). Off by default.",
)
_register(
    "HEAT_TPU_HEDGE_DELAY_MS", "float", 0.0,
    "Fixed hedge delay in milliseconds; 0 (default) derives the delay "
    "from the endpoint's observed p95 latency (no hedging until "
    "HEAT_TPU_HEDGE_MIN_SAMPLES completions exist).",
)
_register(
    "HEAT_TPU_HEDGE_MAX_FRACTION", "float", 0.05,
    "Hard cap on hedged requests as a fraction of all requests "
    "(budget earned by completions): hedging trims the tail, it must "
    "never become a load doubler during overload.",
)
_register(
    "HEAT_TPU_HEDGE_MIN_SAMPLES", "int", 32,
    "Completed-request count an endpoint needs before a p95-derived "
    "hedge delay is trusted (too few samples make p95 noise, and "
    "hedging on noise wastes the budget).",
)

# -- cluster observability knobs (ISSUE 17; docs/OBSERVABILITY.md) ------------

_register(
    "HEAT_TPU_TRACE_REQUESTS", "bool", True,
    "Record distributed request traces (serve/tracing.py): a trace id "
    "minted at ingress rides the wire `trace` field and every hop — "
    "router queue/post, replica queue/coalesce/pad/execute/reply — "
    "lands as a `trace_span` telemetry event, mergeable into ONE "
    "Perfetto timeline across processes. Off is a one-flag-check hot "
    "path; answers are bit-identical either way.",
)
_register(
    "HEAT_TPU_TRACE_SAMPLE", "float", 1.0,
    "Ingress trace-sampling rate in [0, 1]. The keep/drop decision is "
    "made ONCE where the id is minted (deterministic in the id, so "
    "every process agrees) and propagated — downstream hops never "
    "re-sample.",
)
_register(
    "HEAT_TPU_SLO_WINDOW_S", "float", 60.0,
    "Rolling window in seconds over which Router.cluster_summary() "
    "computes SLO burn rates (windowed deltas of the cumulative "
    "per-replica scrapes; the first evaluation falls back to the "
    "lifetime window).",
)
_register(
    "HEAT_TPU_SLO_BURN_THRESHOLD", "float", 1.0,
    "Burn-rate level above which Router.check_slos() emits a "
    "`slo_burn` event. 1.0 = consuming error budget exactly at the "
    "rate that exhausts it over the objective period.",
)

# -- autotuner knobs (heat_tpu/autotune, ISSUE 11) ----------------------------

_register(
    "HEAT_TPU_AUTOTUNE", "bool", False,
    "Arm the measured-feedback knob autotuner (heat_tpu/autotune, "
    "docs/AUTOTUNE.md): program-cache misses and Server construction "
    "consult the tuning DB (warm start) and `autotune.tune()` runs "
    "measured trials. Default-off is bit-for-bit the untuned dispatch "
    "path — one flag check, no DB reads.",
)
_register(
    "HEAT_TPU_TUNE_DB", "str", None,
    "Directory of the persistent tuning DB (atomic-swap JSON records "
    "keyed by program signature + mesh topology + backend). A second "
    "process pointed at a populated DB starts *tuned* with zero measured "
    "trials, the same way HEAT_TPU_COMPILE_CACHE makes it start "
    "*compiled*.",
)
_register(
    "HEAT_TPU_AUTOTUNE_TRIALS", "int", 5,
    "Measured trials per surviving candidate config (median-of-k with "
    "MAD outlier rejection).",
)
_register(
    "HEAT_TPU_AUTOTUNE_BUDGET", "float", None,
    "Ambient max amax-normalized relative error the tuner may trade for "
    "speed when the caller states none. Unset = exact-only: lossy knob "
    "values are never searched.",
)

# -- bench harness knobs ------------------------------------------------------

_register(
    "HEAT_TPU_SWEEP_ATTN", "bool", False,
    "bench.py: sweep ring/ulysses attention variants in the headline run.",
    scope="bench",
)
_register(
    "HEAT_TPU_BENCH_COOLDOWN", "float", 60.0,
    "bench.py: seconds to sleep between heavyweight rows (thermal "
    "settling on shared hosts).",
    scope="bench",
)
_register(
    "HEAT_TPU_BENCH_BUDGET", "float", 1500.0,
    "bench.py: wall-clock budget in seconds; rows past the deadline are "
    "skipped and marked partial.",
    scope="bench",
)

# -- streaming knobs (ISSUE 16; docs/STREAMING.md) ----------------------------

_register(
    "HEAT_TPU_STREAM_CHUNK_ROWS", "int", 0,
    "streaming.ChunkStream: rows per out-of-core chunk. 0 = auto-size "
    "so the chunk's device bytes fit memory_guard.temp_budget() "
    "(a quarter of HEAT_TPU_HBM_BUDGET when armed).",
)

_register(
    "HEAT_TPU_STREAM_DRAIN_TIMEOUT", "float", 60.0,
    "streaming.rolling_update: seconds an old replica may take to drain "
    "its backlog before the roll fails loudly (the version-swap drain "
    "policy).",
)

# -- test-suite knobs ---------------------------------------------------------

_register(
    "HEAT_TPU_TEST_DEVICES", "int", 8,
    "tests/conftest.py: virtual CPU mesh size the suite runs on "
    "(deliberately not a power of two by default).",
    scope="tests",
)

# -- CI knobs (read by scripts/run_ci.sh, not by Python) ----------------------

for _name, _doc in (
    ("HEAT_TPU_CI_SIZES", "Space-separated virtual-device sweep list "
     "(default `1 2 3 5 8`)."),
    ("HEAT_TPU_CI_CHUNKS", "Run each size's suite in N fresh-process "
     "chunks of test files (bounds accumulated XLA state)."),
    ("HEAT_TPU_CI_ALLOW_MISSING_IO", "Skip the loud optional-I/O backend "
     "presence check."),
    ("HEAT_TPU_CI_NO_COMPILE_CACHE", "Disable the sweep-wide persistent "
     "XLA compile cache (measure true cold compiles)."),
    ("HEAT_TPU_CI_SKIP_AUDIT", "Skip the HLO collective-audit step."),
    ("HEAT_TPU_CI_SKIP_WARMCACHE", "Skip the warm-compile-cache reuse "
     "check."),
    ("HEAT_TPU_CI_SKIP_FUSION", "Skip the fusion dispatch check."),
    ("HEAT_TPU_CI_SKIP_FUSION_REDUCE", "Skip the fusion-reduce dispatch "
     "check."),
    ("HEAT_TPU_CI_SKIP_PLANNER", "Skip the budget-constrained relayout "
     "planner step."),
    ("HEAT_TPU_CI_SKIP_COLLPREC", "Skip the quantized-collective wire "
     "audit step."),
    ("HEAT_TPU_CI_SKIP_CHAOS", "Skip the fault-injection chaos step."),
    ("HEAT_TPU_CI_SKIP_SERVING", "Skip the open-loop serving gate."),
    ("HEAT_TPU_CI_SKIP_SERVING_NET", "Skip the horizontally-scaled "
     "serving gate (ISSUE 12: 2-replica pool, router-vs-direct digest "
     "bit-identity, kill-one-replica recovery, zero steady-state "
     "compiles on the warm-started second replica)."),
    ("HEAT_TPU_CI_SKIP_HEATLINT", "Skip the heatlint static-analysis "
     "gate (ISSUE 10)."),
    ("HEAT_TPU_CI_SKIP_AUTOTUNE", "Skip the autotune gate (ISSUE 11: "
     "tuned-vs-default wall, budget/digest validation, second-process "
     "zero-trial warm start)."),
    ("HEAT_TPU_CI_SKIP_SPARSE", "Skip the sparse gate (ISSUE 13: spmv "
     "digest bit-identical to the dense reference mask-matmul, "
     "budget-bounded transpose, zero HLO-audit drift on the sparse "
     "collective sites)."),
    ("HEAT_TPU_CI_SKIP_STREAMING", "Skip the streaming gate (ISSUE 16: "
     "2-file HDF5 out-of-core stream under a pinned HEAT_TPU_HBM_BUDGET "
     "that forbids load-all, watermark strictly below the load-all "
     "bytes, digest parity vs the in-memory fit, and a 2-replica "
     "rolling update with zero steady-state compiles and zero failed "
     "requests)."),
    ("HEAT_TPU_CI_SKIP_HIERARCHY", "Skip the hierarchy gate (ISSUE 15: "
     "flat-vs-tiered digest bit-identity on the emulated 2x2 mesh, "
     "audited cross-node byte reduction >= the local shard factor, "
     "DASO tiered-send equivalence, ZeRO watermark check)."),
    ("HEAT_TPU_CI_SKIP_CLUSTER_OBS", "Skip the cluster-observability "
     "gate (ISSUE 17: 2-replica pool under loadgen — merged-trace hop "
     "completeness with a consistent trace id, /metrics merge equal to "
     "the loadgen totals, tracing-off digest bit-identity with zero "
     "tracing counters, and an induced-latency SLO burn emitting "
     "slo_burn events)."),
    ("HEAT_TPU_CI_SKIP_FSDP", "Skip the FSDP gate (ISSUE 18: sharded "
     "per-device param+state bytes strictly below replicated, train "
     "parity vs the replicated baseline, per-layer audited gather "
     "bytes equal to the cost model with zero drift, knob-off "
     "bit-identical dispatch, zero steady-state compiles)."),
    ("HEAT_TPU_CI_SKIP_PIPELINE", "Skip the pipeline gate (ISSUE 19: "
     "1f1b digest bit-identical to gpipe, measured bubble ticks equal "
     "to the analytic schedule table, audited inter-stage hop bytes "
     "equal to pipeline_hop_cost with zero drift, elastic kill/restore "
     "onto a different node-by-local factorization matching the "
     "uninterrupted trajectory, zero steady-state compiles)."),
    ("HEAT_TPU_CI_SKIP_AUTOSCALE", "Skip the autoscale gate (ISSUE 20: "
     "step-load scale-up then drain-down with zero failed requests, "
     "chaos SIGKILL under load replaced within bounded ticks with zero "
     "steady-state compiles on the respawned replica)."),
):
    _register(_name, "str", None, _doc, scope="ci")
del _name, _doc


# -- overlay ------------------------------------------------------------------
# Tuned knob values (heat_tpu/autotune, ISSUE 11) are installed HERE, in
# front of the environment, so every consumer of the registry — fusion,
# the relayout planner, collective precision, the serving ladder, and any
# future knob — sees tuned values through the reads it already performs.
# The overlay is the ONLY sanctioned way to override a knob in-process;
# it never writes os.environ (subprocesses inherit only what the caller
# exports deliberately).

_OVERRIDES: Dict[str, str] = {}
_OVERRIDE_LOCK = threading.RLock()


def overrides() -> Dict[str, str]:
    """Snapshot of the active overlay (knob name -> raw string)."""
    with _OVERRIDE_LOCK:
        return dict(_OVERRIDES)


def set_override(name: str, value: Optional[str]) -> None:
    """Install (or with ``None`` remove) one overlay entry. The name must
    be registered — the overlay cannot smuggle in undeclared knobs."""
    if name not in REGISTRY:
        raise KeyError(
            f"{name!r} is not a registered HEAT_TPU knob — declare it in "
            "heat_tpu/_knobs.py before overriding it"
        )
    with _OVERRIDE_LOCK:
        if value is None:
            _OVERRIDES.pop(name, None)
        else:
            _OVERRIDES[name] = str(value)


def clear_overrides(names_: Optional[Iterable[str]] = None) -> None:
    """Drop the whole overlay (default) or just ``names_``."""
    with _OVERRIDE_LOCK:
        if names_ is None:
            _OVERRIDES.clear()
        else:
            for n in names_:
                _OVERRIDES.pop(n, None)


@contextlib.contextmanager
def overlay(mapping: Dict[str, Optional[str]]):
    """Temporarily install ``mapping`` into the overlay (the autotuner's
    per-candidate scope), restoring the previous entries — including
    their absence — on exit."""
    with _OVERRIDE_LOCK:
        # validate every name BEFORE installing anything: a mid-loop
        # KeyError would otherwise leak the already-installed entries
        # permanently (the restore below never runs on an install error)
        unknown = [n for n in mapping if n not in REGISTRY]
        if unknown:
            raise KeyError(
                f"{unknown[0]!r} is not a registered HEAT_TPU knob — "
                "declare it in heat_tpu/_knobs.py before overriding it"
            )
        prev = {n: _OVERRIDES.get(n) for n in mapping}
        for n, v in mapping.items():
            set_override(n, v)
    try:
        yield
    finally:
        with _OVERRIDE_LOCK:
            for n, v in prev.items():
                if v is None:
                    _OVERRIDES.pop(n, None)
                else:
                    _OVERRIDES[n] = v


# -- reads --------------------------------------------------------------------


def names() -> frozenset:
    """Every registered knob name (the set HL005 validates against)."""
    return frozenset(REGISTRY)


def tunables() -> Dict[str, Knob]:
    """The knobs carrying autotuner search-space metadata."""
    return {n: k for n, k in REGISTRY.items() if k.tunable is not None}


def default_raw(name: str) -> str:
    """The raw string a knob effectively has RIGHT NOW without tuning:
    the overlay/environment value when set, else the declared default
    rendered in env convention. This is the autotuner's "default config"
    entry — the candidate the winner must beat or tie."""
    k = REGISTRY[name]
    v = raw(name)
    if v is not None and v.strip():
        return v.strip()
    if k.type == "bool":
        return "1" if k.default else "0"
    return "" if k.default is None else str(k.default)


def raw(name: str, default: Optional[str] = None) -> Optional[str]:
    """The raw string for a registered knob: the overlay entry when one
    is installed (tuned values, ISSUE 11), else the environment.

    This is the ONE sanctioned ``os.environ`` read for ``HEAT_TPU_*``
    variables (heatlint HL005). Unregistered names raise — a new knob
    must be declared above, with its type, default, and docstring, before
    any code can read it.
    """
    if name not in REGISTRY:
        raise KeyError(
            f"{name!r} is not a registered HEAT_TPU knob — declare it in "
            "heat_tpu/_knobs.py (type, default, docstring; re-exported via "
            "heat_tpu.core.knobs) before reading it"
        )
    if _OVERRIDES:
        with _OVERRIDE_LOCK:
            v = _OVERRIDES.get(name)
        if v is not None:
            return v
    return os.environ.get(name, default)


def get(name: str):
    """Typed live read of a registered knob: parse the raw string by the
    knob's declared type, falling back to the declared default when unset
    or malformed. Bool parsing follows the shared conventions: default-on
    knobs stay on unless the value is in :data:`FALSY`; default-off knobs
    need an explicit :data:`TRUTHY`. Consults the overlay first, like
    :func:`raw`."""
    k = REGISTRY[name]
    s = (raw(name) or "").strip()
    if not s:
        return k.default
    if k.type == "bool":
        low = s.lower()
        return (low not in FALSY) if k.default else (low in TRUTHY)
    if k.type == "int":
        try:
            return int(s)
        except ValueError:
            return k.default
    if k.type == "float":
        try:
            return float(s)
        except ValueError:
            return k.default
    if k.type == "enum":
        low = s.lower()
        return low if low in k.choices else k.default
    return s  # str / bytes / spec: owning module parses further


# -- documentation ------------------------------------------------------------

_SCOPE_TITLES = (
    ("runtime", "Runtime knobs"),
    ("bench", "Benchmark-harness knobs"),
    ("tests", "Test-suite knobs"),
    ("ci", "CI sweep knobs (`scripts/run_ci.sh`)"),
)


def _default_str(k: Knob) -> str:
    if k.default is None:
        return "*(unset)*"
    if k.type == "bool":
        return "on" if k.default else "off"
    return f"`{k.default}`"


def _tunable_str(k: Knob) -> str:
    t = k.tunable
    if t is None:
        return "—"
    vals = ", ".join(t.values)
    if t.kind == "lossy":
        return f"lossy (exact: `{t.exact_value}`): `{vals}`"
    return f"{t.kind}: `{vals}`"


def markdown_table() -> str:
    """The knob catalog as markdown, grouped by scope — the generated
    section of docs/API.md (``tests/test_heatlint.py`` pins the committed
    doc to this output; regenerate with
    ``python -m heat_tpu.analysis --knob-table``). The *Tunable* column
    is the autotuner's declared search space (docs/AUTOTUNE.md)."""
    out = []
    for scope, title in _SCOPE_TITLES:
        knobs = [k for k in REGISTRY.values() if k.scope == scope]
        if not knobs:
            continue
        out.append(f"### {title}\n")
        out.append("| Knob | Type | Default | Tunable | Description |")
        out.append("|---|---|---|---|---|")
        for k in sorted(knobs, key=lambda k: k.name):
            typ = k.type
            if k.choices:
                typ = " \\| ".join(k.choices)
            doc = " ".join(k.doc.split())
            out.append(
                f"| `{k.name}` | {typ} | {_default_str(k)} | "
                f"{_tunable_str(k)} | {doc} |"
            )
        out.append("")
    return "\n".join(out).rstrip() + "\n"
