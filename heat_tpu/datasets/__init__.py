"""Bundled sample datasets (reference `heat/datasets/` — iris.csv,
iris_X_train.csv …, diabetes.h5).

The reference ships static data files that its tests and examples load by
path (e.g. reference naive_bayes/tests/test_gaussiannb.py:27-32 reads
``heat/datasets/iris_X_train.csv`` with ``sep=";"``). This package carries
the same capability: the classic public-domain datasets as ``;``-separated
CSVs, **generated from scikit-learn's copies** by :func:`regenerate` (run
it to rebuild the files — nothing here is copied from the reference tree;
diabetes ships as CSV rather than HDF5 because h5py is an optional gated
dependency). Loader helpers return split DNDarrays directly so examples
don't need to know the on-disk location.
"""

from __future__ import annotations

import os
from typing import Optional, Tuple

_ROOT = os.path.dirname(os.path.abspath(__file__))

__all__ = ["path", "load_iris", "load_iris_split", "load_diabetes", "regenerate"]


def path(name: str) -> str:
    """Absolute path of a bundled dataset file, e.g. ``path('iris.csv')``."""
    p = os.path.join(_ROOT, name)
    if not os.path.isfile(p):
        raise FileNotFoundError(
            f"no bundled dataset {name!r}; run heat_tpu.datasets.regenerate() "
            "or pick one of: "
            + ", ".join(sorted(f for f in os.listdir(_ROOT) if f.endswith(".csv")))
        )
    return p


def load_iris(split: Optional[int] = 0):
    """Iris features (150, 4) and labels (150,) as DNDarrays."""
    import heat_tpu as ht

    X = ht.load_csv(path("iris.csv"), sep=";", split=split)
    y = ht.load_csv(path("iris_labels.csv"), sep=";", split=split)
    return X, y.squeeze(1).astype(ht.int64)


def load_iris_split(split: Optional[int] = 0) -> Tuple:
    """The bundled stratified 70/30 train/test split of iris
    (X_train, X_test, y_train, y_test)."""
    import heat_tpu as ht

    Xtr = ht.load_csv(path("iris_X_train.csv"), sep=";", split=split)
    Xte = ht.load_csv(path("iris_X_test.csv"), sep=";", split=split)
    ytr = ht.load_csv(path("iris_y_train.csv"), sep=";", split=split)
    yte = ht.load_csv(path("iris_y_test.csv"), sep=";", split=split)
    return Xtr, Xte, ytr.squeeze(1).astype(ht.int64), yte.squeeze(1).astype(ht.int64)


def load_diabetes(split: Optional[int] = 0):
    """Diabetes features (442, 10) and target (442,) as DNDarrays."""
    import heat_tpu as ht

    D = ht.load_csv(path("diabetes.csv"), sep=";", split=split)
    return D[:, :10], D[:, 10]


def regenerate() -> None:
    """Rebuild every bundled CSV from scikit-learn's dataset copies
    (deterministic: fixed random_state for the train/test split)."""
    import numpy as np
    from sklearn import datasets as skd
    from sklearn.model_selection import train_test_split

    def wcsv(name, arr, fmt):
        np.savetxt(os.path.join(_ROOT, name), arr, delimiter=";", fmt=fmt)

    iris = skd.load_iris()
    X, y = iris.data, iris.target
    wcsv("iris.csv", X, "%.1f")
    wcsv("iris_labels.csv", y.reshape(-1, 1), "%d")
    Xtr, Xte, ytr, yte = train_test_split(
        X, y, test_size=0.3, random_state=0, stratify=y
    )
    wcsv("iris_X_train.csv", Xtr, "%.1f")
    wcsv("iris_X_test.csv", Xte, "%.1f")
    wcsv("iris_y_train.csv", ytr.reshape(-1, 1), "%d")
    wcsv("iris_y_test.csv", yte.reshape(-1, 1), "%d")

    dia = skd.load_diabetes()
    D = np.concatenate([dia.data, dia.target.reshape(-1, 1)], axis=1)
    wcsv("diabetes.csv", D, "%.18e")
