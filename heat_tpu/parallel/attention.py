"""Long-context attention over a sequence-sharded mesh.

The reference framework has no transformer code; its parity mechanisms are
the ring block schedule (reference heat/spatial/distance.py:280-326) and the
axis-aware Alltoall (reference heat/core/communication.py:1180-1322). This
module is the capability those mechanisms exist for, built TPU-first:

* :func:`ring_attention` — blockwise softmax(QKᵀ)V with K/V blocks circulated
  around the ICI ring (`ppermute`) and flash-style online renormalization, so
  a sequence of length T sharded p ways never materializes a (T, T) matrix
  and each chip holds O(T/p) activations.
* :func:`ulysses_attention` — `all_to_all` swaps the sharded axis from
  sequence to heads, runs dense local attention per head group, and swaps
  back. Cheaper per step than the ring when heads ≥ p, at the cost of two
  all_to_alls.
* :func:`local_attention` — the single-device blockwise kernel both build on.

Shapes follow jax convention ``(batch, seq, heads, head_dim)``; the sharded
axis is ``seq`` (axis 1) on input and output for both distributed variants.
All kernels are jit-pure and differentiable (the backward pass re-runs the
ring under autodiff; `jax.checkpoint` the caller for O(T/p) memory).
"""

from __future__ import annotations

import functools
import math
from typing import Optional

import jax
import jax.numpy as jnp

NEG_INF = -1e30


def _block_attn(q, k, v, m, l, o, q_start, k_start, scale, causal, kv_len_valid):
    """One flash-attention accumulation step on local blocks.

    q: (B, Tq, H, D); k, v: (B, Tk, H, D); m, l: (B, H, Tq); o like q.
    ``q_start``/``k_start`` are the blocks' global sequence offsets (traced
    scalars) used for causal masking; ``kv_len_valid`` masks K tail padding.
    """
    # MXU dots run in the INPUT dtype with f32 accumulation — an up-front
    # astype(f32) would force true-f32 MXU passes at ~1/4 throughput (the
    # r3 lm_step/backward bottleneck); softmax stays f32 throughout
    s = jnp.einsum(
        "bqhd,bkhd->bhqk", q, k, preferred_element_type=jnp.float32
    ) * scale
    tk = k.shape[1]
    k_pos = k_start + jnp.arange(tk)
    mask = k_pos[None, :] < kv_len_valid  # (1, Tk) — valid K positions
    if causal:
        q_pos = q_start + jnp.arange(q.shape[1])
        mask = mask & (k_pos[None, :] <= q_pos[:, None])
    s = jnp.where(mask[None, None, :, :], s, NEG_INF)

    m_new = jnp.maximum(m, s.max(axis=-1))
    # guard fully-masked rows: keep m finite so exp() stays well-defined
    m_safe = jnp.where(m_new <= NEG_INF / 2, 0.0, m_new)
    p = jnp.exp(s - m_safe[..., None])
    p = jnp.where(mask[None, None, :, :], p, 0.0)
    alpha = jnp.exp(jnp.where(m <= NEG_INF / 2, NEG_INF, m) - m_safe)
    alpha = jnp.where(m <= NEG_INF / 2, 0.0, alpha)
    l_new = alpha * l + p.sum(axis=-1)
    # PV in v's dtype (standard flash practice): f32 probabilities round to
    # bf16 on the way into the MXU for bf16 v, accumulating in f32
    p_mx = p if v.dtype == jnp.float32 else p.astype(v.dtype)
    pv = jnp.einsum(
        "bhqk,bkhd->bqhd", p_mx, v, preferred_element_type=jnp.float32
    )
    o_new = o * alpha.transpose(0, 2, 1)[..., None] + pv
    return m_new, l_new, o_new


def _finalize(m, l, o):
    denom = jnp.where(l == 0.0, 1.0, l)
    return o / denom.transpose(0, 2, 1)[..., None]


def local_attention(
    q: jax.Array,
    k: jax.Array,
    v: jax.Array,
    *,
    causal: bool = False,
    scale: Optional[float] = None,
    block_size: int = 512,
    kv_valid: Optional[int] = None,
) -> jax.Array:
    """Blockwise (flash) attention on one device. ``(B, T, H, D)`` layout.

    K/V are processed in ``block_size`` chunks with online softmax — the same
    accumulator the distributed variants carry around the ring, so numerics
    are identical across all three entry points. K/V positions ``>= kv_valid``
    are treated as padding and masked out.
    """
    b, tq, h, d = q.shape
    tk = k.shape[1]
    kv_valid = tk if kv_valid is None else kv_valid
    scale = scale if scale is not None else 1.0 / math.sqrt(d)
    nblk = max(1, -(-tk // block_size))
    pad = nblk * block_size - tk
    if pad:
        k = jnp.pad(k, ((0, 0), (0, pad), (0, 0), (0, 0)))
        v = jnp.pad(v, ((0, 0), (0, pad), (0, 0), (0, 0)))

    # derive the accumulators from q (zeros_like-style) so that when this
    # kernel runs inside a shard_map the carry inherits q's device-varying
    # type — a literal jnp.zeros would be replicated and break the fori_loop
    # carry typing
    zero_q = jnp.zeros_like(q, dtype=jnp.float32)
    m = zero_q.sum(axis=-1).transpose(0, 2, 1) + NEG_INF  # (B, H, Tq)
    l = zero_q.sum(axis=-1).transpose(0, 2, 1)
    o = zero_q

    def body(i, carry):
        m, l, o = carry
        k_start = i * block_size
        kb = jax.lax.dynamic_slice_in_dim(k, k_start, block_size, axis=1)
        vb = jax.lax.dynamic_slice_in_dim(v, k_start, block_size, axis=1)
        # inputs keep their dtype: the MXU dots inside _block_attn accumulate
        # in f32 via preferred_element_type (bf16 inputs run full-rate)
        return _block_attn(
            q, kb, vb, m, l, o, 0, k_start, scale, causal, kv_valid,
        )

    m, l, o = jax.lax.fori_loop(0, nblk, body, (m, l, o))
    return _finalize(m, l, o).astype(q.dtype)


def ring_attention(
    q: jax.Array,
    k: jax.Array,
    v: jax.Array,
    *,
    comm,
    causal: bool = False,
    scale: Optional[float] = None,
    seq_len: Optional[int] = None,
) -> jax.Array:
    """Ring attention over a sequence-sharded mesh (Liu et al. 2023).

    ``q``, ``k``, ``v``: ``(B, T_pad, H, D)`` sharded along axis 1 over
    ``comm``'s mesh (``T_pad`` divisible by ``comm.size``; positions
    ``>= seq_len`` are padding and are masked out of the softmax). Each mesh
    position keeps its Q block stationary and circulates its K/V block one
    hop per step; the flash accumulator makes the p partial softmaxes exact.
    Communication rides ICI and overlaps with the per-step MXU work.
    """
    p = comm.size
    axis = comm.axis_name
    b, t_pad, h, d = q.shape
    seq_len = t_pad if seq_len is None else seq_len
    scale = scale if scale is not None else 1.0 / math.sqrt(d)
    tc = t_pad // p
    perm = [(i, (i + 1) % p) for i in range(p)]

    def kernel(qb, kb, vb):
        rank = jax.lax.axis_index(axis)
        m = jnp.full((b, h, tc), NEG_INF, dtype=jnp.float32)
        l = jnp.zeros((b, h, tc), dtype=jnp.float32)
        o = jnp.zeros((b, tc, h, d), dtype=jnp.float32)
        # freshly-built accumulators are replicated; the scan carry must be
        # device-varying because it mixes with the sharded q/k/v blocks
        m, l, o = (jax.lax.pcast(a, (axis,), to="varying") for a in (m, l, o))

        def body(t, carry):
            kc, vc, m, l, o = carry
            origin = (rank - t) % p
            m, l, o = _block_attn(
                qb, kc, vc,
                m, l, o, rank * tc, origin * tc, scale, causal, seq_len,
            )
            # the K/V hops ride the wrapper chokepoint (ISSUE 15: the
            # cost model prices them — ring_attention_cost — and the
            # HLO auditor sees them); exact pinned: a compressed block
            # would re-quantize p times around the ring and drift the
            # softmax renormalization
            kc = comm.ppermute(kc, perm, precision="off")
            vc = comm.ppermute(vc, perm, precision="off")
            return (kc, vc, m, l, o)

        kc, vc, m, l, o = jax.lax.fori_loop(0, p, body, (kb, vb, m, l, o))
        return _finalize(m, l, o).astype(qb.dtype)

    spec = comm.spec(1, 4)
    return jax.shard_map(
        kernel, mesh=comm.mesh, in_specs=(spec, spec, spec), out_specs=spec
    )(q, k, v)


def ulysses_attention(
    q: jax.Array,
    k: jax.Array,
    v: jax.Array,
    *,
    comm,
    causal: bool = False,
    scale: Optional[float] = None,
    seq_len: Optional[int] = None,
    block_size: int = 512,
    use_pallas: bool = False,
) -> jax.Array:
    """Ulysses sequence parallelism (Jacobs et al. 2023).

    ``all_to_all`` swaps sharding sequence→heads (each position then holds
    the full sequence for H/p heads), runs the dense blockwise kernel, and
    swaps back. This is the TPU-native form of the reference's axis-aware
    Alltoall reshard (reference heat/core/communication.py:1180-1322).
    Requires ``H`` divisible by ``comm.size``. ``use_pallas=True`` runs the
    local step through the hand-tiled Pallas kernel
    (:func:`heat_tpu.parallel.flash_attention`, ~2.7× the XLA path on v5e)
    at its tuned tile sizes — ``block_size`` applies to the XLA path only.
    """
    p = comm.size
    b, t_pad, h, d = q.shape
    if h % p != 0:
        raise ValueError(f"heads ({h}) must divide over mesh size ({p})")
    seq_len = t_pad if seq_len is None else seq_len
    # resolve interpreter mode from the mesh's devices here, outside
    # shard_map — inside the kernel the inputs are tracers and the global
    # default backend misleads in mixed-platform processes
    pallas_interpret = any(d.platform != "tpu" for d in comm.devices)

    def kernel(qb, kb, vb):
        # (B, T/p, H, D) -> (B, T, H/p, D): gather seq, scatter heads.
        # Wrapper-routed (ISSUE 15): the exchanges are priced by
        # ulysses_attention_cost and lower tiered under
        # HEAT_TPU_HIERARCHICAL; exact pinned — Q/K/V bits feed the
        # softmax, compression belongs to the collective, not here.
        a2a = functools.partial(
            comm.all_to_all, split_axis=2, concat_axis=1, precision="off",
        )
        qh, kh, vh = a2a(qb), a2a(kb), a2a(vb)
        if use_pallas:
            from .pallas_attention import flash_attention

            oh = flash_attention(
                qh, kh, vh, causal=causal, scale=scale, kv_valid=seq_len,
                interpret=pallas_interpret,
            )
        else:
            oh = local_attention(
                qh, kh, vh, causal=causal, scale=scale, block_size=block_size,
                kv_valid=seq_len,
            )
        back = functools.partial(
            comm.all_to_all, split_axis=1, concat_axis=2, precision="off",
        )
        return back(oh)

    spec = comm.spec(1, 4)
    out = jax.shard_map(
        kernel, mesh=comm.mesh, in_specs=(spec, spec, spec), out_specs=spec
    )(q, k, v)
    return out
