"""Halo exchange — neighbor-overlap slices for stencil/boundary ops.

Reference: ``DNDarray.get_halo`` (reference heat/core/dndarray.py:360-433)
exchanges ``halo_size`` edge rows with the previous/next MPI rank via
Isend/Irecv. TPU-native form: one `shard_map` kernel where each mesh position
sends its leading edge to the previous position and its trailing edge to the
next with two `ppermute`s (both ride ICI in parallel), then concatenates.
"""

from __future__ import annotations

from typing import Optional, Tuple

import jax
import jax.numpy as jnp


def _halo_parts(xb, halo_size: int, axis: int, name: str, p: int, wrap: bool):
    """In-kernel neighbor slices: (from_prev, from_next) for one shard.
    The single home of the ring perms and terminal zero-fill — every halo
    consumer (exchange, stencils) shares it."""
    fwd = [(i, (i + 1) % p) for i in range(p)]   # send to next
    bwd = [(i, (i - 1) % p) for i in range(p)]   # send to prev
    rank = jax.lax.axis_index(name)
    lead = jax.lax.slice_in_dim(xb, 0, halo_size, axis=axis)
    n = xb.shape[axis]
    trail = jax.lax.slice_in_dim(xb, n - halo_size, n, axis=axis)
    # heatlint: disable=HL002 -- generic axis-NAME helper: callers hand us
    # a bare mesh axis string, no MeshCommunication object exists in scope;
    # halo volumes are not yet priced by the cost model
    from_prev = jax.lax.ppermute(trail, name, perm=fwd)
    # heatlint: disable=HL002 -- same: axis-name helper, no comm in scope
    from_next = jax.lax.ppermute(lead, name, perm=bwd)
    if not wrap:
        zero = jnp.zeros_like(from_prev)
        from_prev = jnp.where(rank == 0, zero, from_prev)
        from_next = jnp.where(rank == p - 1, zero, from_next)
    return from_prev, from_next


def _check_halo(x, halo_size: int, axis: int, p: int) -> None:
    if x.shape[axis] // p < halo_size:
        raise ValueError(
            f"halo_size {halo_size} exceeds local extent {x.shape[axis] // p}"
        )


def halo_exchange(
    x: jax.Array,
    halo_size: int,
    *,
    comm,
    axis: int = 0,
    wrap: bool = False,
    return_parts: bool = False,
) -> jax.Array:
    """Return per-shard blocks extended with neighbor halos along ``axis``.

    ``x`` must be sharded along ``axis`` over ``comm``'s mesh. The result is
    sharded the same way with each local block grown by up to ``2*halo_size``
    rows: ``halo_size`` from the previous shard prepended and ``halo_size``
    from the next appended. Terminal shards get zero-filled halos unless
    ``wrap=True`` (periodic boundary). ``return_parts=True`` skips the
    concatenation and returns ``(from_prev, from_next)`` — the form
    :meth:`DNDarray.get_halo` caches.
    """
    p = comm.size
    name = comm.axis_name
    _check_halo(x, halo_size, axis, p)

    def kernel(xb):
        from_prev, from_next = _halo_parts(xb, halo_size, axis, name, p, wrap)
        if return_parts:
            return from_prev, from_next
        return jnp.concatenate([from_prev, xb, from_next], axis=axis)

    spec = comm.spec(axis, x.ndim)
    out_specs = (spec, spec) if return_parts else spec
    return jax.shard_map(
        kernel, mesh=comm.mesh, in_specs=(spec,), out_specs=out_specs
    )(x)


def halo_stencil(
    x: jax.Array,
    halo_size: int,
    fn,
    *,
    comm,
    axis: int = 0,
    wrap: bool = False,
    sides: str = "both",
) -> jax.Array:
    """Apply ``fn`` to each shard's halo-extended block inside ONE shard_map.

    ``fn`` receives the local block with ``halo_size`` neighbor rows
    prepended/appended per ``sides`` ("prev" | "next" | "both") and must
    return a block sharded the same way (out spec = in spec). This is the
    boundary-op building block: a stencil that would otherwise need an
    eager gather runs as local compute + two ppermutes over ICI
    (reference analog: DNDarray.get_halo Isend/Irecv,
    reference heat/core/dndarray.py:360-433)."""
    p = comm.size
    name = comm.axis_name
    _check_halo(x, halo_size, axis, p)
    if sides not in ("prev", "next", "both"):
        raise ValueError(f"sides must be 'prev', 'next' or 'both', got {sides!r}")

    def kernel(xb):
        from_prev, from_next = _halo_parts(xb, halo_size, axis, name, p, wrap)
        parts = []
        if sides in ("prev", "both"):
            parts.append(from_prev)
        parts.append(xb)
        if sides in ("next", "both"):
            parts.append(from_next)
        return fn(jnp.concatenate(parts, axis=axis))

    spec = comm.spec(axis, x.ndim)
    return jax.shard_map(
        kernel, mesh=comm.mesh, in_specs=(spec,), out_specs=spec
    )(x)
