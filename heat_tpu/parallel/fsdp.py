"""Parameter/optimizer-state sharding over the mesh (FSDP/ZeRO building
blocks).

The reference replicates model state on every rank (its DP keeps full
parameter copies; SURVEY §2.5). On TPU, HBM is the bottleneck — sharding
each large leaf over the mesh and letting XLA insert the all-gathers at
use sites is the standard recipe (fully-sharded data parallelism). These
helpers are deliberately thin: placement is just a `NamedSharding` per
leaf, and XLA does the rest.

* :func:`shard_pytree` — `device_put` each leaf with its largest
  mesh-divisible axis sharded (small or indivisible leaves replicate).
  Use on params and optimizer state once, outside jit.
* :func:`constrain_pytree` — the in-jit form (`with_sharding_constraint`)
  for pinning intermediate state to the same layout.
* :func:`replicate_pytree` — the inverse, for host export/checkpoint
  interchange.
"""

from __future__ import annotations

import ast
import dataclasses
import re
from typing import Any, Iterable, List, Optional, Sequence, Tuple

import jax
import jax.numpy as jnp

__all__ = [
    "shard_pytree",
    "constrain_pytree",
    "replicate_pytree",
    "flat_chunk",
    "flat_shard_pytree",
    "flat_unshard_leaf",
    "PartitionRules",
    "FsdpLeaf",
    "FsdpPlan",
    "leaf_paths",
    "plan_partition",
    "fsdp_shard",
    "fsdp_unshard",
    "fsdp_gather",
    "bytes_per_device",
]


def _leaf_sharding(leaf, comm, min_size):
    """Sharding for one leaf: biggest axis divisible by the mesh size, or
    replicated when the leaf is small/indivisible/scalar. Non-array leaves
    (Python scalars in a train state — step counters etc.) replicate."""
    p = comm.size
    ndim = getattr(leaf, "ndim", 0)
    size = getattr(leaf, "size", 1)
    if ndim == 0 or size < min_size:
        return comm.sharding(None, ndim)
    axes = sorted(range(ndim), key=lambda a: -leaf.shape[a])
    for ax in axes:
        if leaf.shape[ax] % p == 0 and leaf.shape[ax] >= p:
            return comm.sharding(ax, ndim)
    return comm.sharding(None, ndim)


def shard_pytree(tree: Any, comm, *, min_size: int = 1024) -> Any:
    """Place every leaf on the mesh with its largest divisible axis sharded.

    Leaves smaller than ``min_size`` elements (or with no axis divisible by
    the mesh size) replicate — sharding tiny tensors costs more in
    collectives than it saves in HBM.
    """
    return jax.tree_util.tree_map(
        lambda l: jax.device_put(l, _leaf_sharding(l, comm, min_size)), tree
    )


def constrain_pytree(tree: Any, comm, *, min_size: int = 1024) -> Any:
    """`with_sharding_constraint` per leaf with the same placement rule —
    use inside a jitted step to keep updated params/opt-state sharded."""
    return jax.tree_util.tree_map(
        lambda l: jax.lax.with_sharding_constraint(
            l, _leaf_sharding(l, comm, min_size)
        ),
        tree,
    )


def replicate_pytree(tree: Any, comm) -> Any:
    """`device_put` every leaf replicated (checkpoint/export layout)."""
    return jax.tree_util.tree_map(
        lambda l: jax.device_put(l, comm.replicated()), tree
    )


# -- flat 1/p shard layout (the ZeRO state layout, ISSUE 15) -------------------
# ZeRO-style optimizer-state sharding (arXiv:2004.13336) flattens each
# leaf and gives every mesh position one contiguous 1/p chunk — the layout
# heat_tpu.optim.ZeroOptimizer builds its reduce-scatter → shard update →
# all-gather step on. Kept here because it is the same capability family
# as shard_pytree: placement over the mesh, XLA does the rest.


def flat_chunk(numel: int, p: int, wire: str = "off", block: int = 128) -> int:
    """Per-position chunk length of a flattened ``numel``-element leaf:
    ``ceil(numel/p)``, rounded up to whole quantization blocks when the
    gradient reduce-scatter wire is ``blockwise`` — so the compressed
    collective's chunk boundaries coincide with the state shards
    (one fixed point of collective_prec's clamp arithmetic)."""
    c = -(-int(numel) // int(p))
    if wire == "blockwise":
        b = max(1, min(int(block), c))
        c = -(-c // b) * b
    return c


def flat_shard_pytree(tree: Any, comm, wire: str = "off",
                      block: int = 128) -> Any:
    """Every leaf flattened, zero-padded to ``p * flat_chunk`` and placed
    as a ``(p, chunk)`` array sharded along axis 0 — position ``i`` owns
    flat elements ``[i*chunk, (i+1)*chunk)``."""
    p = comm.size

    def shard(l):
        l = jnp.asarray(l)
        c = flat_chunk(l.size, p, wire, block)
        flat = l.reshape(-1)
        if p * c != l.size:
            flat = jnp.pad(flat, (0, p * c - l.size))
        return jax.device_put(flat.reshape(p, c), comm.sharding(0, 2))

    return jax.tree_util.tree_map(shard, tree)


# -- partition rules (ISSUE 18) ------------------------------------------------
# Full FSDP needs a *declarative* layout map so arbitrary model pytrees —
# not just the nn/ demos — get shardings without hand-placed device_puts.
# The idiom is the regex rule table of the big JAX training codebases
# (match_partition_rules, SNIPPETS.md [3]): leaf key paths are joined
# with "/" and matched against an ORDERED rule list; the first match
# wins. Two deliberate divergences from the exemplar: an unmatched leaf
# REPLICATES (it does not raise — partial rule tables must be safe on
# models they were not written for), and a rule may pin a per-rule wire
# precision for its gather/scatter stream (ISSUE 9 vocabulary).

_PLACEMENTS = ("fsdp", "replicate")


def _key_str(k) -> str:
    """One path component of `jax.tree_util.tree_flatten_with_path` as
    text: dict keys and attr names verbatim, sequence indices as digits."""
    for attr in ("key", "name", "idx"):
        if hasattr(k, attr):
            return str(getattr(k, attr))
    return str(k)


def leaf_paths(tree: Any) -> List[Tuple[str, Any]]:
    """``(path, leaf)`` per leaf, paths "/"-joined in flatten order —
    the strings :class:`PartitionRules` patterns match against. Nested
    dicts, lists/tuples, and registered custom nodes (flax FrozenDict,
    optax states) all spell naturally: ``"block0/attn/query/kernel"``,
    ``"0/bias"``."""
    flat = jax.tree_util.tree_flatten_with_path(tree)[0]
    return [
        ("/".join(_key_str(k) for k in path), leaf) for path, leaf in flat
    ]


@dataclasses.dataclass(frozen=True)
class FsdpLeaf:
    """One leaf's resolved layout: ``sharded`` leaves live as flat
    ``(p, chunk)`` rows (axis 0 over the mesh) and are gathered
    just-in-time at wire mode ``wire``; replicated leaves keep their
    logical shape on every position. ``rule`` is the index of the
    matched rule (−1: the replicated default)."""

    path: str
    shape: Tuple[int, ...]
    dtype: str
    sharded: bool
    wire: str
    chunk: int
    rule: int

    @property
    def numel(self) -> int:
        n = 1
        for s in self.shape:
            n *= int(s)
        return n


class PartitionRules:
    """Ordered ``(pattern, placement[, wire])`` rules mapping leaf key
    paths to FSDP layouts.

    ``pattern`` is an uncompiled regex matched with ``re.search`` against
    the "/"-joined leaf path; the FIRST matching rule wins. ``placement``
    is ``"fsdp"`` (flat 1/p shard) or ``"replicate"``. The optional
    ``wire`` pins that rule's gather/scatter wire precision
    (``off | bf16 | int8 | blockwise``); omitted, the leaf inherits
    :func:`heat_tpu.core.topology.fsdp_wire`'s chain. Unmatched leaves
    and scalars replicate. ``repr`` round-trips through :meth:`parse`."""

    def __init__(self, rules: Iterable[Sequence]):
        norm = []
        for r in rules:
            r = tuple(r)
            if len(r) == 2:
                pattern, placement, wire = r[0], r[1], None
            elif len(r) == 3:
                pattern, placement, wire = r
            else:
                raise ValueError(
                    f"rule must be (pattern, placement[, wire]), got {r!r}"
                )
            re.compile(pattern)  # fail fast on a bad regex
            if placement not in _PLACEMENTS:
                raise ValueError(
                    f"placement must be one of {_PLACEMENTS}, got "
                    f"{placement!r} (rule {pattern!r})"
                )
            if wire is not None:
                from ..core import collective_prec

                if wire not in collective_prec.MODES:
                    raise ValueError(
                        f"wire must be one of {sorted(collective_prec.MODES)},"
                        f" got {wire!r} (rule {pattern!r})"
                    )
            norm.append((str(pattern), str(placement), wire))
        self.rules: Tuple[Tuple[str, str, Optional[str]], ...] = tuple(norm)

    @classmethod
    def fsdp_default(cls) -> "PartitionRules":
        """Shard every non-scalar leaf (scalars always replicate)."""
        return cls(((".*", "fsdp"),))

    def match(self, path: str) -> Tuple[str, Optional[str], int]:
        """``(placement, wire, rule_index)`` of the first rule whose
        pattern ``re.search``-matches ``path``; the replicated default
        (``rule_index == -1``) when none does."""
        for i, (pattern, placement, wire) in enumerate(self.rules):
            if re.search(pattern, path):
                return placement, wire, i
        return "replicate", None, -1

    def __repr__(self) -> str:
        return f"PartitionRules({self.rules!r})"

    @classmethod
    def parse(cls, text: str) -> "PartitionRules":
        """Invert :meth:`__repr__` (also accepts the bare tuple literal)
        — the rule table is plain data, so tuned layouts can live in
        configs and survive a round-trip textually."""
        s = text.strip()
        if s.startswith("PartitionRules(") and s.endswith(")"):
            s = s[len("PartitionRules("):-1]
        return cls(ast.literal_eval(s))

    def __eq__(self, other) -> bool:
        return isinstance(other, PartitionRules) and self.rules == other.rules

    def __hash__(self) -> int:
        return hash(self.rules)


class FsdpPlan:
    """The resolved layout of one parameter pytree: a :class:`FsdpLeaf`
    per leaf (flatten order) plus the treedef. Built once per
    (template, rules, mesh) by :func:`plan_partition`; its
    :meth:`signature` is the program-cache key component every compiled
    FSDP step is memoized on."""

    def __init__(self, leaves: Sequence[FsdpLeaf], treedef, p: int):
        self.leaves: Tuple[FsdpLeaf, ...] = tuple(leaves)
        self.treedef = treedef
        self.p = int(p)
        self.by_path = {l.path: l for l in self.leaves}

    def signature(self) -> Tuple:
        """Hashable identity of the layout (program-cache key part)."""
        return tuple(
            (l.path, l.shape, l.dtype, l.sharded, l.wire, l.chunk)
            for l in self.leaves
        ) + (self.p,)

    def unflatten(self, values: Sequence[Any]) -> Any:
        return jax.tree_util.tree_unflatten(self.treedef, list(values))

    def sharded_numels(self) -> List[int]:
        return [l.numel for l in self.leaves if l.sharded]

    def __repr__(self) -> str:
        n_sh = sum(1 for l in self.leaves if l.sharded)
        return (
            f"FsdpPlan(p={self.p}, leaves={len(self.leaves)}, "
            f"sharded={n_sh})"
        )


def plan_partition(
    tree: Any,
    rules: Optional[PartitionRules],
    comm,
    *,
    precision: Optional[str] = None,
    block: Optional[int] = None,
) -> FsdpPlan:
    """Resolve ``rules`` over a parameter pytree (arrays or
    ``ShapeDtypeStruct`` templates) into an :class:`FsdpPlan`.

    Scalars always replicate — a 1/p shard of a scalar is meaningless.
    Each sharded leaf's wire mode runs the
    :func:`heat_tpu.core.topology.fsdp_wire` chain (rule wire →
    ``HEAT_TPU_FSDP_PREC`` → tiered cross-node chain → exact) and its
    chunk is :func:`flat_chunk` under that wire, so blockwise chunk
    boundaries land on the shard boundaries. Refuses layouts where a
    REPLICATED leaf's logical shape collides with a sharded leaf's
    ``(p, chunk)`` row shape — downstream state-sharding inference tells
    the two apart by shape, and an ambiguous table is a rules bug better
    caught here than as a silently misplaced optimizer state."""
    from ..core import collective_prec, topology

    if rules is None:
        rules = PartitionRules.fsdp_default()
    p = comm.size
    if block is None:
        block = collective_prec.block_size()
    paths = leaf_paths(tree)
    treedef = jax.tree_util.tree_structure(tree)

    leaves = []
    for path, leaf in paths:
        shape = tuple(int(s) for s in getattr(leaf, "shape", ()))
        dtype = jnp.asarray(leaf).dtype if not hasattr(leaf, "dtype") else leaf.dtype
        dtype = jnp.dtype(dtype)
        placement, rule_wire, idx = rules.match(path)
        sharded = placement == "fsdp" and len(shape) > 0
        if sharded:
            wire = topology.fsdp_wire(
                dtype, p, rule_wire if rule_wire is not None else precision
            )
            numel = 1
            for s in shape:
                numel *= s
            chunk = flat_chunk(numel, p, wire, block)
        else:
            wire, chunk = "off", 0
        leaves.append(
            FsdpLeaf(path, shape, str(dtype), sharded, wire, chunk, idx)
        )

    row_shapes = {(p, l.chunk) for l in leaves if l.sharded}
    for l in leaves:
        if not l.sharded and l.shape in row_shapes:
            raise ValueError(
                f"ambiguous partition plan: replicated leaf {l.path!r} has "
                f"logical shape {l.shape}, identical to a sharded leaf's "
                f"(p, chunk) row shape — state-sharding inference pairs "
                "state to parameters by shape, so this table cannot be "
                "placed safely. Shard that leaf too, or adjust the rules."
            )
    return FsdpPlan(leaves, treedef, p)


def fsdp_shard(tree: Any, plan: FsdpPlan, comm) -> Any:
    """Place a logical parameter pytree into ``plan``'s layout: sharded
    leaves as ``(p, chunk)`` rows (axis 0 over the mesh, zero-padded
    tail), replicated leaves replicated. The persistent-state half of
    FSDP — parameters STAY in this layout across steps."""
    p = comm.size
    flat = jax.tree_util.tree_flatten(tree)[0]
    out = []
    for leaf, lp in zip(flat, plan.leaves):
        l = jnp.asarray(leaf)
        if tuple(l.shape) != lp.shape:
            raise ValueError(
                f"leaf {lp.path!r} has shape {tuple(l.shape)}, plan says "
                f"{lp.shape} — re-plan before sharding"
            )
        if not lp.sharded:
            out.append(jax.device_put(l, comm.replicated()))
            continue
        flat_l = l.reshape(-1)
        if p * lp.chunk != l.size:
            flat_l = jnp.pad(flat_l, (0, p * lp.chunk - l.size))
        out.append(
            jax.device_put(flat_l.reshape(p, lp.chunk), comm.sharding(0, 2))
        )
    return plan.unflatten(out)


def fsdp_unshard(tree: Any, plan: FsdpPlan) -> Any:
    """Invert :func:`fsdp_shard` to the topology-independent logical
    form (numpy leaves) — the checkpoint interchange layout. A tree
    sharded over 4 positions unshards to the same logical bytes as one
    sharded over 8 (same property the ZeRO restore relies on)."""
    import numpy as np

    flat = jax.tree_util.tree_flatten(tree)[0]
    out = []
    for leaf, lp in zip(flat, plan.leaves):
        if lp.sharded:
            out.append(flat_unshard_leaf(leaf, lp.shape, lp.dtype))
        else:
            out.append(np.asarray(leaf))
    return plan.unflatten(out)


def fsdp_gather(local_chunk, leaf: FsdpLeaf, comm, *, block: Optional[int] = None):
    """Just-in-time weight gather of one flat-sharded leaf inside a
    ``shard_map`` kernel: the per-position ``(1, chunk)`` row all-gathers
    (tiered under ``HEAT_TPU_HIERARCHICAL=1``; wire-compressed at
    ``leaf.wire``) back to the logical parameter the layer consumes.

    Differentiable by construction (``jax.custom_vjp``): the backward of
    an all-gather is exactly the reduce-scatter of the cotangent — each
    position gets the global SUM over its own chunk, the canonical FSDP
    gradient path — at the SAME wire mode, so forward and backward move
    symmetric volumes. The custom rule also sidesteps differentiating
    through the quantized collectives, which have no meaningful gradient
    of their own. No residuals are saved: callers wrap the *consuming*
    layer in ``jax.checkpoint`` so the backward re-gathers instead of
    keeping every layer's full weights live.

    Emits trace-time ``fsdp_gather``/``fsdp_scatter`` events priced by
    :func:`heat_tpu.telemetry.collectives.fsdp_gather_cost` /
    ``fsdp_scatter_cost`` — per-leaf attribution on top of the wrappers'
    own ``all_gather``/``reduce_scatter`` events, and the figures the CI
    gate audits against the HLO."""
    from .. import telemetry
    from ..core import collective_prec, topology

    if not leaf.sharded:
        return local_chunk
    if block is None:
        block = collective_prec.block_size()
    p = comm.size
    topo = topology.active(p)
    node, local = (topo.node, topo.local) if topo is not None else (1, p)
    dtype = jnp.dtype(leaf.dtype)
    shape, numel, chunk, wire = leaf.shape, leaf.numel, leaf.chunk, leaf.wire
    in_shape, in_dtype = local_chunk.shape, local_chunk.dtype

    @jax.custom_vjp
    def gather(c):
        return _fwd(c)[0]

    def _fwd(c):
        telemetry.trace_event(
            "fsdp_gather", path=leaf.path, wire=wire,
            **telemetry.collectives.fsdp_gather_cost(
                chunk, dtype.itemsize, node, local, wire, block
            ).as_fields(),
        )
        flat = comm.all_gather(c.reshape(-1), tiled=True, precision=wire)
        return flat[:numel].reshape(shape).astype(dtype), None

    def _bwd(_, ct):
        telemetry.trace_event(
            "fsdp_scatter", path=leaf.path, wire=wire,
            **telemetry.collectives.fsdp_scatter_cost(
                p * chunk, dtype.itemsize, node, local, wire, block
            ).as_fields(),
        )
        flat = ct.reshape(-1)
        if p * chunk != numel:
            flat = jnp.pad(flat, (0, p * chunk - numel))
        g = comm.reduce_scatter(flat, precision=wire)
        return (g.reshape(in_shape).astype(in_dtype),)

    gather.defvjp(_fwd, _bwd)
    return gather(local_chunk)


def bytes_per_device(tree: Any) -> int:
    """Worst-case per-device live bytes of a pytree of placed jax
    arrays (``addressable_shards`` accounting — the same figure
    ``ZeroOptimizer.state_bytes_per_device`` reports for state, here
    usable for params + state together: the FSDP watermark oracle)."""
    per_dev: dict = {}
    for leaf in jax.tree_util.tree_leaves(tree):
        if not hasattr(leaf, "addressable_shards"):
            continue
        for sh in leaf.addressable_shards:
            d = str(sh.device)
            per_dev[d] = per_dev.get(d, 0) + sh.data.nbytes
    return max(per_dev.values()) if per_dev else 0


def flat_unshard_leaf(padded, shape, dtype=None):
    """Invert :func:`flat_shard_pytree` for one leaf: ``(p, chunk)`` back
    to the logical ``shape`` (pad rows sliced off). The inverse is
    topology-independent — a leaf sharded over 4 positions unshards to
    the same logical bytes as one sharded over 8, which is what makes
    the ZeRO checkpoint restore cross-topology bit-exact."""
    import numpy as np

    numel = 1
    for s in shape:
        numel *= int(s)
    flat = np.asarray(padded).reshape(-1)[:numel]
    out = flat.reshape(tuple(int(s) for s in shape))
    return out.astype(dtype) if dtype is not None else out
