"""Parameter/optimizer-state sharding over the mesh (FSDP/ZeRO building
blocks).

The reference replicates model state on every rank (its DP keeps full
parameter copies; SURVEY §2.5). On TPU, HBM is the bottleneck — sharding
each large leaf over the mesh and letting XLA insert the all-gathers at
use sites is the standard recipe (fully-sharded data parallelism). These
helpers are deliberately thin: placement is just a `NamedSharding` per
leaf, and XLA does the rest.

* :func:`shard_pytree` — `device_put` each leaf with its largest
  mesh-divisible axis sharded (small or indivisible leaves replicate).
  Use on params and optimizer state once, outside jit.
* :func:`constrain_pytree` — the in-jit form (`with_sharding_constraint`)
  for pinning intermediate state to the same layout.
* :func:`replicate_pytree` — the inverse, for host export/checkpoint
  interchange.
"""

from __future__ import annotations

from typing import Any

import jax
import jax.numpy as jnp

__all__ = [
    "shard_pytree",
    "constrain_pytree",
    "replicate_pytree",
    "flat_chunk",
    "flat_shard_pytree",
    "flat_unshard_leaf",
]


def _leaf_sharding(leaf, comm, min_size):
    """Sharding for one leaf: biggest axis divisible by the mesh size, or
    replicated when the leaf is small/indivisible/scalar. Non-array leaves
    (Python scalars in a train state — step counters etc.) replicate."""
    p = comm.size
    ndim = getattr(leaf, "ndim", 0)
    size = getattr(leaf, "size", 1)
    if ndim == 0 or size < min_size:
        return comm.sharding(None, ndim)
    axes = sorted(range(ndim), key=lambda a: -leaf.shape[a])
    for ax in axes:
        if leaf.shape[ax] % p == 0 and leaf.shape[ax] >= p:
            return comm.sharding(ax, ndim)
    return comm.sharding(None, ndim)


def shard_pytree(tree: Any, comm, *, min_size: int = 1024) -> Any:
    """Place every leaf on the mesh with its largest divisible axis sharded.

    Leaves smaller than ``min_size`` elements (or with no axis divisible by
    the mesh size) replicate — sharding tiny tensors costs more in
    collectives than it saves in HBM.
    """
    return jax.tree_util.tree_map(
        lambda l: jax.device_put(l, _leaf_sharding(l, comm, min_size)), tree
    )


def constrain_pytree(tree: Any, comm, *, min_size: int = 1024) -> Any:
    """`with_sharding_constraint` per leaf with the same placement rule —
    use inside a jitted step to keep updated params/opt-state sharded."""
    return jax.tree_util.tree_map(
        lambda l: jax.lax.with_sharding_constraint(
            l, _leaf_sharding(l, comm, min_size)
        ),
        tree,
    )


def replicate_pytree(tree: Any, comm) -> Any:
    """`device_put` every leaf replicated (checkpoint/export layout)."""
    return jax.tree_util.tree_map(
        lambda l: jax.device_put(l, comm.replicated()), tree
    )


# -- flat 1/p shard layout (the ZeRO state layout, ISSUE 15) -------------------
# ZeRO-style optimizer-state sharding (arXiv:2004.13336) flattens each
# leaf and gives every mesh position one contiguous 1/p chunk — the layout
# heat_tpu.optim.ZeroOptimizer builds its reduce-scatter → shard update →
# all-gather step on. Kept here because it is the same capability family
# as shard_pytree: placement over the mesh, XLA does the rest.


def flat_chunk(numel: int, p: int, wire: str = "off", block: int = 128) -> int:
    """Per-position chunk length of a flattened ``numel``-element leaf:
    ``ceil(numel/p)``, rounded up to whole quantization blocks when the
    gradient reduce-scatter wire is ``blockwise`` — so the compressed
    collective's chunk boundaries coincide with the state shards
    (one fixed point of collective_prec's clamp arithmetic)."""
    c = -(-int(numel) // int(p))
    if wire == "blockwise":
        b = max(1, min(int(block), c))
        c = -(-c // b) * b
    return c


def flat_shard_pytree(tree: Any, comm, wire: str = "off",
                      block: int = 128) -> Any:
    """Every leaf flattened, zero-padded to ``p * flat_chunk`` and placed
    as a ``(p, chunk)`` array sharded along axis 0 — position ``i`` owns
    flat elements ``[i*chunk, (i+1)*chunk)``."""
    p = comm.size

    def shard(l):
        l = jnp.asarray(l)
        c = flat_chunk(l.size, p, wire, block)
        flat = l.reshape(-1)
        if p * c != l.size:
            flat = jnp.pad(flat, (0, p * c - l.size))
        return jax.device_put(flat.reshape(p, c), comm.sharding(0, 2))

    return jax.tree_util.tree_map(shard, tree)


def flat_unshard_leaf(padded, shape, dtype=None):
    """Invert :func:`flat_shard_pytree` for one leaf: ``(p, chunk)`` back
    to the logical ``shape`` (pad rows sliced off). The inverse is
    topology-independent — a leaf sharded over 4 positions unshards to
    the same logical bytes as one sharded over 8, which is what makes
    the ZeRO checkpoint restore cross-topology bit-exact."""
    import numpy as np

    numel = 1
    for s in shape:
        numel *= int(s)
    flat = np.asarray(padded).reshape(-1)[:numel]
    out = flat.reshape(tuple(int(s) for s in shape))
    return out.astype(dtype) if dtype is not None else out
