"""Parameter/optimizer-state sharding over the mesh (FSDP/ZeRO building
blocks).

The reference replicates model state on every rank (its DP keeps full
parameter copies; SURVEY §2.5). On TPU, HBM is the bottleneck — sharding
each large leaf over the mesh and letting XLA insert the all-gathers at
use sites is the standard recipe (fully-sharded data parallelism). These
helpers are deliberately thin: placement is just a `NamedSharding` per
leaf, and XLA does the rest.

* :func:`shard_pytree` — `device_put` each leaf with its largest
  mesh-divisible axis sharded (small or indivisible leaves replicate).
  Use on params and optimizer state once, outside jit.
* :func:`constrain_pytree` — the in-jit form (`with_sharding_constraint`)
  for pinning intermediate state to the same layout.
* :func:`replicate_pytree` — the inverse, for host export/checkpoint
  interchange.
"""

from __future__ import annotations

from typing import Any

import jax

__all__ = ["shard_pytree", "constrain_pytree", "replicate_pytree"]


def _leaf_sharding(leaf, comm, min_size):
    """Sharding for one leaf: biggest axis divisible by the mesh size, or
    replicated when the leaf is small/indivisible/scalar. Non-array leaves
    (Python scalars in a train state — step counters etc.) replicate."""
    p = comm.size
    ndim = getattr(leaf, "ndim", 0)
    size = getattr(leaf, "size", 1)
    if ndim == 0 or size < min_size:
        return comm.sharding(None, ndim)
    axes = sorted(range(ndim), key=lambda a: -leaf.shape[a])
    for ax in axes:
        if leaf.shape[ax] % p == 0 and leaf.shape[ax] >= p:
            return comm.sharding(ax, ndim)
    return comm.sharding(None, ndim)


def shard_pytree(tree: Any, comm, *, min_size: int = 1024) -> Any:
    """Place every leaf on the mesh with its largest divisible axis sharded.

    Leaves smaller than ``min_size`` elements (or with no axis divisible by
    the mesh size) replicate — sharding tiny tensors costs more in
    collectives than it saves in HBM.
    """
    return jax.tree_util.tree_map(
        lambda l: jax.device_put(l, _leaf_sharding(l, comm, min_size)), tree
    )


def constrain_pytree(tree: Any, comm, *, min_size: int = 1024) -> Any:
    """`with_sharding_constraint` per leaf with the same placement rule —
    use inside a jitted step to keep updated params/opt-state sharded."""
    return jax.tree_util.tree_map(
        lambda l: jax.lax.with_sharding_constraint(
            l, _leaf_sharding(l, comm, min_size)
        ),
        tree,
    )


def replicate_pytree(tree: Any, comm) -> Any:
    """`device_put` every leaf replicated (checkpoint/export layout)."""
    return jax.tree_util.tree_map(
        lambda l: jax.device_put(l, comm.replicated()), tree
    )
