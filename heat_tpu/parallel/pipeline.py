"""Pipeline parallelism (pp) over the mesh axis.

The reference implements data parallelism only (SURVEY §2.5: "no pipeline
parallelism"); this is the TPU-native strategy built on the same mesh
machinery: stages live one-per-mesh-position (their params stacked with a
leading stage dim sharded over the axis), microbatch activations hop
stage→stage over ICI with `ppermute`, and the whole GPipe schedule —
S + M - 1 ticks for S stages and M microbatches — is a single
`lax.fori_loop` inside one `shard_map`, so XLA overlaps each tick's
compute with the next hop's transfer.

Differentiable end to end (autodiff re-runs the loop; `jax.checkpoint`
the stage fn for long pipelines). The multichip dryrun
(`__graft_entry__.py`) runs a pipelined forward+backward as its pp
layout.
"""

from __future__ import annotations

from typing import Any, Callable, Sequence

import jax
import jax.numpy as jnp


def stack_stage_params(params_list: Sequence[Any]):
    """Stack per-stage pytrees into one pytree with leading stage dim
    (shard it over the mesh axis with ``comm.sharding(0, leaf.ndim)``)."""
    return jax.tree_util.tree_map(lambda *ls: jnp.stack(ls), *params_list)


def pipeline_apply(
    stage_fn: Callable[[Any, jax.Array], jax.Array],
    stacked_params: Any,
    x: jax.Array,
    *,
    comm,
    n_microbatches: int,
) -> jax.Array:
    """Apply ``stage_{p-1} ∘ … ∘ stage_0`` to ``x`` with the GPipe schedule.

    ``stage_fn(params, h) -> h`` must preserve the activation shape (the
    classic homogeneous-pipeline contract). ``stacked_params`` leaves carry
    a leading dim of size ``comm.size`` (stage-major, sharded or
    replicated — the kernel slices its own stage either way). ``x`` is the
    full batch ``(B, ...)``, ``B`` divisible by ``n_microbatches``; the
    result is replicated (every position holds the full output after the
    final psum).
    """
    p = comm.size
    axis = comm.axis_name
    m = n_microbatches
    b = x.shape[0]
    if b % m:
        raise ValueError(f"batch {b} not divisible into {m} microbatches")
    bad = [
        l.shape[:1]
        for l in jax.tree_util.tree_leaves(stacked_params)
        if l.shape[:1] != (p,)
    ]
    if bad:
        # a 2p stack would silently shard 2 stages per position and run
        # only the first of each — reject any mismatched leaf loudly
        raise ValueError(
            f"stacked_params leaves carry leading dims {sorted(set(bad))} for "
            f"a {p}-position mesh; exactly one stage per position is required"
        )
    mb = b // m
    micro = x.reshape(m, mb, *x.shape[1:])
    fwd_perm = [(i, (i + 1) % p) for i in range(p)]

    def kernel(params_blk, micro_all):
        # params_blk leaves: (1, ...) when sharded — this position's stage
        params = jax.tree_util.tree_map(lambda l: l[0], params_blk)
        s = comm.axis_index()
        act = jnp.zeros((mb,) + micro.shape[2:], micro.dtype)
        out = jnp.zeros_like(micro_all)
        # fresh accumulators are replicated; the loop carry mixes with
        # device-varying values (same pcast pattern as ring_attention)
        act, out = (
            jax.lax.pcast(a, (axis,), to="varying") for a in (act, out)
        )

        def tick(t, carry):
            act, out = carry
            # stage 0 injects microbatch t (if any remain)
            inject = jax.lax.dynamic_index_in_dim(
                micro_all, jnp.minimum(t, m - 1), keepdims=False
            )
            inject = jax.lax.pcast(inject, (axis,), to="varying")
            act = jnp.where((s == 0) & (t < m), inject, act)
            mth = t - s  # microbatch index flowing through this stage now
            active = (mth >= 0) & (mth < m)
            computed = stage_fn(params, act)
            h = jnp.where(active, computed, act)
            # last stage collects its finished microbatch
            out = jax.lax.cond(
                (s == p - 1) & active,
                lambda o: jax.lax.dynamic_update_index_in_dim(
                    o, h, jnp.maximum(mth, 0), axis=0
                ),
                lambda o: o,
                out,
            )
            # stage->stage hop through the wrapper chokepoint (ISSUE 15:
            # priced by pipeline_cost, visible to the HLO auditor); exact
            # pinned — activations are the model's forward values
            act = comm.ppermute(h, fwd_perm, precision="off")
            return act, out

        act, out = jax.lax.fori_loop(0, p + m - 1, tick, (act, out))
        # only the last position ever wrote `out` (others carry their zero
        # init), so the psum both collects and replicates the result —
        # exact by construction (one nonzero contribution per element)
        return comm.psum(out, precision="off")

    from jax.sharding import PartitionSpec as P

    pspec = jax.tree_util.tree_map(lambda l: comm.spec(0, l.ndim), stacked_params)

    out = jax.shard_map(
        kernel,
        mesh=comm.mesh,
        in_specs=(pspec, P()),
        out_specs=P(),
    )(stacked_params, micro)
    return out.reshape(b, *x.shape[1:])
