"""Pipeline parallelism (pp) over the mesh axis.

The reference implements data parallelism only (SURVEY §2.5: "no pipeline
parallelism"); this is the TPU-native strategy built on the same mesh
machinery, in two layers (ISSUE 19):

* :func:`pipeline_apply` — the historical flat GPipe forward: stages live
  one-per-mesh-position (their params stacked with a leading stage dim
  sharded over the axis), microbatch activations hop stage→stage with
  `ppermute`, and the whole ``S + M - 1``-tick wave is one `lax.fori_loop`
  inside one `shard_map`, cached at program-cache site ``pipeline.apply``
  (stage compute on inactive warmup/drain ticks is guarded by `lax.cond`,
  not computed-and-discarded). Differentiable end to end.

* the schedule-table-driven MPMD kernel (site ``pipeline.step``) behind
  :class:`heat_tpu.nn.Pipeline` — stages map onto `core/topology.py`
  node groups (:class:`~.schedule.StageMapping`), the ``local`` positions
  inside a stage carry flat-sharded (FSDP-tier) stage weights gathered
  in-group just-in-time, the inter-stage hop crosses the node tier
  (priced by :func:`~heat_tpu.telemetry.collectives.pipeline_hop_cost`),
  and a static :class:`~.schedule.ScheduleTable` (gpipe or 1f1b) drives
  one unrolled forward/backward program with a hand-rolled per-microbatch
  vjp: each stage stashes only the INPUT activation of in-flight
  microbatches and rematerializes its forward inside the backward tick
  (`jax.checkpoint` per layer), so the stash is ``stash_depth`` deep —
  ``M`` for gpipe, ``min(S, M)`` for 1f1b.

Within-stage compute is REPLICATED across the ``local`` tier (weights are
sharded ``1/local``, activations are not row-split): the grad of a
microbatch is therefore identical on every group member and each member
slices its own chunk — no gradient collective at all — which is what
makes the elastic contract bit-exact across ``node × local``
re-factorizations (a row-split data tier would change the gradient
reduction order with ``local``).
"""

from __future__ import annotations

from dataclasses import dataclass
from typing import Any, Callable, List, Optional, Sequence, Tuple

import jax
import jax.numpy as jnp

from .. import telemetry
from ..core import program_cache
from ..telemetry import collectives as _coll
from . import schedule as _schedule


def stack_stage_params(params_list: Sequence[Any]):
    """Stack per-stage pytrees into one pytree with leading stage dim
    (shard it over the mesh axis with ``comm.sharding(0, leaf.ndim)``)."""
    return jax.tree_util.tree_map(lambda *ls: jnp.stack(ls), *params_list)


def pipeline_apply(
    stage_fn: Callable[[Any, jax.Array], jax.Array],
    stacked_params: Any,
    x: jax.Array,
    *,
    comm,
    n_microbatches: int,
) -> jax.Array:
    """Apply ``stage_{p-1} ∘ … ∘ stage_0`` to ``x`` with the GPipe schedule.

    ``stage_fn(params, h) -> h`` must preserve the activation shape (the
    classic homogeneous-pipeline contract) and contain no collectives (its
    compute is guarded by a per-position ``lax.cond``). ``stacked_params``
    leaves carry a leading dim of size ``comm.size`` (stage-major, sharded
    or replicated — the kernel slices its own stage either way). ``x`` is
    the full batch ``(B, ...)``, ``B`` divisible by ``n_microbatches``;
    the result is replicated (every position holds the full output after
    the final psum).

    The program is memoized at site ``pipeline.apply`` keyed on the stage
    fn's identity and the microbatch count — repeat calls (any shapes:
    aval dispatch happens inside the cached wrapper) are pure cache hits,
    zero retraces (the CompileWatcher oracle in ``tests/test_pipeline.py``).
    """
    p = comm.size
    axis = comm.axis_name
    m = int(n_microbatches)
    b = x.shape[0]
    if b % m:
        raise ValueError(f"batch {b} not divisible into {m} microbatches")
    bad = [
        l.shape[:1]
        for l in jax.tree_util.tree_leaves(stacked_params)
        if l.shape[:1] != (p,)
    ]
    if bad:
        # a 2p stack would silently shard 2 stages per position and run
        # only the first of each — reject any mismatched leaf loudly
        raise ValueError(
            f"stacked_params leaves carry leading dims {sorted(set(bad))} for "
            f"a {p}-position mesh; exactly one stage per position is required"
        )
    mb = b // m
    micro = x.reshape(m, mb, *x.shape[1:])
    fwd_perm = [(i, (i + 1) % p) for i in range(p)]

    def build():
        from jax.sharding import PartitionSpec as P

        def kernel(params_blk, micro_all):
            # params_blk leaves: (1, ...) when sharded — this position's stage
            params = jax.tree_util.tree_map(lambda l: l[0], params_blk)
            s = comm.axis_index()
            act = jnp.zeros(micro_all.shape[1:], micro_all.dtype)
            out = jnp.zeros_like(micro_all)
            # fresh accumulators are replicated; the loop carry mixes with
            # device-varying values (same pcast pattern as ring_attention)
            act, out = (
                jax.lax.pcast(a, (axis,), to="varying") for a in (act, out)
            )

            def tick(t, carry):
                act, out = carry
                # stage 0 injects microbatch t (if any remain)
                inject = jax.lax.dynamic_index_in_dim(
                    micro_all, jnp.minimum(t, m - 1), keepdims=False
                )
                inject = jax.lax.pcast(inject, (axis,), to="varying")
                act = jnp.where((s == 0) & (t < m), inject, act)
                mth = t - s  # microbatch index flowing through this stage now
                active = (mth >= 0) & (mth < m)
                # inactive warmup/drain positions skip the stage compute
                # entirely (the ISSUE 19 dead-compute fix: cond, not
                # compute-and-discard through jnp.where)
                h = jax.lax.cond(
                    active,
                    lambda a: stage_fn(params, a),
                    lambda a: a,
                    act,
                )
                # last stage collects its finished microbatch
                out = jax.lax.cond(
                    (s == p - 1) & active,
                    lambda o: jax.lax.dynamic_update_index_in_dim(
                        o, h, jnp.maximum(mth, 0), axis=0
                    ),
                    lambda o: o,
                    out,
                )
                # stage->stage hop through the wrapper chokepoint (ISSUE 15:
                # priced by pipeline_cost, visible to the HLO auditor); exact
                # pinned — activations are the model's forward values
                act = comm.ppermute(h, fwd_perm, precision="off")
                return act, out

            act, out = jax.lax.fori_loop(0, p + m - 1, tick, (act, out))
            # only the last position ever wrote `out` (others carry their zero
            # init), so the psum both collects and replicates the result —
            # exact by construction (one nonzero contribution per element)
            return comm.psum(out, precision="off")

        def apply_fn(stacked, micro_all):
            pspec = jax.tree_util.tree_map(
                lambda l: comm.spec(0, l.ndim), stacked
            )
            return jax.shard_map(
                kernel,
                mesh=comm.mesh,
                in_specs=(pspec, P()),
                out_specs=P(),
            )(stacked, micro_all)

        return apply_fn

    prog = program_cache.cached_program(
        "pipeline.apply", (stage_fn, m), build, comm=comm
    )
    out = prog(stacked_params, micro)
    return out.reshape(b, *x.shape[1:])


# -- the schedule-table MPMD kernel (site pipeline.step) ----------------------


@dataclass(frozen=True)
class PipelineLayout:
    """The chunked stage-layer parameter layout behind ``ht.nn.Pipeline``.

    ``n_layers`` homogeneous layers (identical param pytrees) are grouped
    ``lps = n_layers / n_stages`` per stage; each param leaf of logical
    shape ``shape_k`` lives as a ``(p, lps, chunk_k)`` row array sharded
    over the flat axis — position ``(s, l)`` holds, for each of its
    stage's layers, the ``l``-th ``chunk_k = ceil(numel_k / local)`` slice
    of the flattened leaf (zero-padded tail). The layout is
    topology-INDEPENDENT in logical form (per-layer unpadded leaves), so
    checkpoints restore across ``node × local`` re-factorizations."""

    p: int
    n_stages: int
    n_layers: int
    treedef: Any                       # one layer's params treedef
    shapes: Tuple[Tuple[int, ...], ...]
    dtypes: Tuple[str, ...]
    wire: str                          # "off" | "bf16"

    @property
    def local(self) -> int:
        return self.p // self.n_stages

    @property
    def layers_per_stage(self) -> int:
        return self.n_layers // self.n_stages

    def numel(self, k: int) -> int:
        n = 1
        for d in self.shapes[k]:
            n *= int(d)
        return n

    def chunk(self, k: int) -> int:
        return -(-self.numel(k) // self.local)

    def row_shapes(self) -> set:
        return {
            (self.p, self.layers_per_stage, self.chunk(k))
            for k in range(len(self.shapes))
        }

    def signature(self) -> tuple:
        return (
            self.p, self.n_stages, self.n_layers, self.treedef,
            self.shapes, self.dtypes, self.wire,
        )

    def bytes_per_device(self) -> int:
        return sum(
            self.layers_per_stage * self.chunk(k)
            * jnp.dtype(self.dtypes[k]).itemsize
            for k in range(len(self.shapes))
        )


def plan_pipeline(
    layer_params: Sequence[Any],
    mapping: _schedule.StageMapping,
    wire: str = "off",
) -> PipelineLayout:
    """Resolve the layout from one logical per-layer params list.

    All layers must be homogeneous (same treedef, leaf shapes and
    dtypes — the classic pipeline contract, which is also what lets a
    checkpoint re-stage onto any divisor stage count). ``wire`` is the
    in-stage gather's wire mode; the layout supports ``off`` (exact) and
    ``bf16`` — the blockwise/int8 modes of the flat FSDP stream would
    make chunk-boundary-dependent quantization decisions, which the
    elastic bit-exact contract forbids, so they coerce to ``bf16``."""
    layers = list(layer_params)
    L = len(layers)
    if L == 0:
        raise ValueError("need at least one layer")
    if L % mapping.n_stages:
        raise ValueError(
            f"{L} layers do not divide into {mapping.n_stages} equal stages"
        )
    leaves0, treedef = jax.tree_util.tree_flatten(layers[0])
    shapes = tuple(tuple(l.shape) for l in leaves0)
    dtypes = tuple(str(jnp.asarray(l).dtype) for l in leaves0)
    for j, layer in enumerate(layers[1:], start=1):
        lj, tj = jax.tree_util.tree_flatten(layer)
        if tj != treedef or tuple(tuple(l.shape) for l in lj) != shapes:
            raise ValueError(
                f"layer {j} is not homogeneous with layer 0 "
                "(pipeline stages must share one parameter signature)"
            )
    if wire in ("int8", "blockwise"):
        wire = "bf16"
    if wire not in ("off", "bf16"):
        raise ValueError(f"unsupported pipeline gather wire {wire!r}")
    return PipelineLayout(
        mapping.p, mapping.n_stages, L, treedef, shapes, dtypes, wire
    )


def shard_pipeline_params(layer_params: Sequence[Any], layout, comm):
    """Logical per-layer list → the persistent ``(p, lps, chunk)`` rows."""
    layers = list(layer_params)
    lps, loc, S = layout.layers_per_stage, layout.local, layout.n_stages
    by_layer = [jax.tree_util.tree_flatten(l)[0] for l in layers]
    out = []
    for k in range(len(layout.shapes)):
        chunk = layout.chunk(k)
        flat = jnp.stack(
            [
                jnp.pad(
                    jnp.asarray(by_layer[j][k]).reshape(-1),
                    (0, loc * chunk - layout.numel(k)),
                )
                for j in range(layout.n_layers)
            ]
        )  # (L, local*chunk)
        rows = (
            flat.reshape(S, lps, loc, chunk)
            .transpose(0, 2, 1, 3)
            .reshape(layout.p, lps, chunk)
        )
        out.append(jax.device_put(rows, comm.sharding(0, 3)))
    return jax.tree_util.tree_unflatten(layout.treedef, out)


def unshard_pipeline_params(stacked, layout) -> List[Any]:
    """Persistent rows → logical per-layer numpy list (checkpoint form)."""
    import numpy as np

    leaves = jax.tree_util.tree_flatten(stacked)[0]
    lps, loc, S = layout.layers_per_stage, layout.local, layout.n_stages
    per_layer_leaves: List[List[Any]] = [[] for _ in range(layout.n_layers)]
    for k, rows in enumerate(leaves):
        chunk = layout.chunk(k)
        flat = (
            np.asarray(rows)
            .reshape(S, loc, lps, chunk)
            .transpose(0, 2, 1, 3)
            .reshape(layout.n_layers, loc * chunk)
        )
        for j in range(layout.n_layers):
            per_layer_leaves[j].append(
                flat[j, : layout.numel(k)].reshape(layout.shapes[k])
            )
    return [
        jax.tree_util.tree_unflatten(layout.treedef, ls)
        for ls in per_layer_leaves
    ]


def unshard_state_rows(rows, layout, numel: int, shape) -> Any:
    """One ``(p, lps, chunk)`` optimizer-state leaf → stacked logical
    ``(n_layers, *shape)`` (the per-param-leaf correspondence supplies
    ``numel``/``shape`` — row shapes alone cannot, two leaves may share a
    chunk size)."""
    import numpy as np

    lps, loc, S = layout.layers_per_stage, layout.local, layout.n_stages
    chunk = rows.shape[-1]
    flat = (
        np.asarray(rows)
        .reshape(S, loc, lps, chunk)
        .transpose(0, 2, 1, 3)
        .reshape(layout.n_layers, loc * chunk)
    )
    return flat[:, :numel].reshape((layout.n_layers,) + tuple(shape))


def shard_state_rows(logical, layout, comm):
    """Stacked logical ``(n_layers, *shape)`` → ``(p, lps, chunk)`` rows."""
    logical = jnp.asarray(logical)
    L = layout.n_layers
    lps, loc, S = layout.layers_per_stage, layout.local, layout.n_stages
    numel = 1
    for d in logical.shape[1:]:
        numel *= int(d)
    chunk = -(-numel // loc)
    flat = jnp.pad(
        logical.reshape(L, numel), ((0, 0), (0, loc * chunk - numel))
    )
    rows = (
        flat.reshape(S, lps, loc, chunk)
        .transpose(0, 2, 1, 3)
        .reshape(layout.p, lps, chunk)
    )
    return jax.device_put(rows, comm.sharding(0, 3))


def _tie(x, token):
    """Schedule barrier: value-identity, but XLA cannot issue any op
    consuming ``x`` before ``token`` exists — the gather-prefetch window
    bound (no custom vjp needed here: the pipeline kernel's backward is
    hand-rolled per tick, nothing differentiates through the tie)."""
    if token is None:
        return x
    out, _ = jax.lax.optimization_barrier((x, token))
    return out


def _gather_chunk(chunk_val, axis, mapping, wire):
    """In-stage grouped all-gather of one layer-leaf chunk: ``(chunk,)`` →
    ``(local, chunk)`` over this position's stage group (the node-group
    ICI tier — zero DCN bytes). ``bf16`` moves a 2-byte wire element."""
    if mapping.local == 1:
        return chunk_val[None]
    groups = mapping.groups()
    payload = chunk_val
    lossy = wire == "bf16" and jnp.issubdtype(chunk_val.dtype, jnp.floating)
    if lossy:
        payload = payload.astype(jnp.bfloat16)
    telemetry.trace_event(
        "pipeline_gather",
        axis=axis,
        wire="bf16" if lossy else "off",
        collective="all-gather",
        bytes=mapping.p * (mapping.local - 1) * int(chunk_val.shape[0])
        * (2 if lossy else chunk_val.dtype.itemsize),
        group=mapping.describe(),
    )
    full = jax.lax.all_gather(  # heatlint: disable=HL002 -- in-stage
        # GROUPED gather (axis_index_groups = the stage members): the comm
        # wrapper has no grouped form; the pipeline_gather event above is
        # its telemetry/pricing chokepoint, mirroring core/topology.py
        payload, axis, axis_index_groups=groups, tiled=False
    )
    if lossy:
        full = full.astype(chunk_val.dtype)
    return full


def _chunk_slice(full, member, local, chunk):
    """This member's ``(chunk,)`` slice of one full gradient leaf
    (zero-padded tail) — the no-wire ZeRO slice of a replicated grad."""
    flat = full.reshape(-1)
    pad = local * chunk - flat.size
    if pad:
        flat = jnp.pad(flat, (0, pad))
    return jax.lax.dynamic_slice(flat, (member * chunk,), (chunk,))


def pipeline_step_program(
    layer_fn: Callable[[Any, jax.Array], jax.Array],
    layout: PipelineLayout,
    mapping: _schedule.StageMapping,
    table: _schedule.ScheduleTable,
    *,
    comm,
    loss_fn: Optional[Callable] = None,
    optimizer=None,
    prefetch: int = 0,
    remat: bool = True,
) -> Callable:
    """The cached schedule-table pipeline program (site ``pipeline.step``).

    Training tables (``table.train`` with ``loss_fn``/``optimizer``)
    return ``step(params, opt_state, micro_x, micro_y) -> (params,
    opt_state, loss)``; forward tables return ``fwd(params, micro_x) ->
    (M, mb, ...)``. ``micro_*`` carry the microbatch-major
    ``(M, mb, ...)`` reshape of the replicated batch.

    One unrolled program: per static tick, each position looks its stage's
    action up in the baked table, `lax.cond`-guards the forward (gather →
    layer chain, input stashed) and backward (gather → per-microbatch
    ``jax.vjp`` with per-layer `jax.checkpoint` remat, grad chunk-sliced,
    accumulated), then both inter-stage hops permute unconditionally —
    the uniform-collective SPMD contract: gathers sit inside conds whose
    predicate is uniform across each stage group, permutes outside any
    cond. Gradients accumulate in increasing microbatch order on every
    stage for BOTH schedules, which is the cross-schedule bit-identity
    invariant the CI gate pins."""
    train = table.train
    if train and (loss_fn is None or optimizer is None):
        raise ValueError("training tables need loss_fn and optimizer")
    axis = comm.axis_name
    p, S, M = layout.p, mapping.n_stages, table.n_microbatches
    loc, lps = mapping.local, layout.layers_per_stage
    K = table.stash_depth()
    fwd_tab, bwd_tab = table.action_arrays()
    fwd_perm, bwd_perm = mapping.fwd_perm(), mapping.bwd_perm()
    n_leaves = len(layout.shapes)
    depth = int(prefetch)

    def local_leaves(params_blk):
        # (1, lps, chunk) blocks -> this position's (lps, chunk) leaves
        return [
            l[0] for l in jax.tree_util.tree_flatten(params_blk)[0]
        ]

    def gather_layer(pleaves, j, tie_token):
        ws = []
        for k in range(n_leaves):
            chunk_val = _tie(pleaves[k][j], tie_token)
            full = _gather_chunk(chunk_val, axis, mapping, layout.wire)
            ws.append(
                full.reshape(-1)[: layout.numel(k)].reshape(layout.shapes[k])
            )
        return jax.tree_util.tree_unflatten(layout.treedef, ws)

    def stage_forward(pleaves, x0):
        # fwd-tick chain: gather each layer just-in-time, prefetch window
        # `depth` tied to the activation `depth` layers back
        acts = [x0]
        h = x0
        for j in range(lps):
            w = gather_layer(pleaves, j, acts[max(0, j - depth)])
            h = layer_fn(w, h)
            acts.append(h)
        return h

    layer_apply = jax.checkpoint(layer_fn) if remat else layer_fn

    def apply_gathered(ws, x0):
        # bwd-tick recompute target: weights pre-gathered OUTSIDE the vjp
        # (no collective differentiates; the replicated-compute grad needs
        # a plain slice, not an all-gather transpose)
        h = x0
        for w in ws:
            h = layer_apply(w, h)
        return h

    hop_cost = None
    leaf0_item = jnp.dtype(layout.dtypes[0]).itemsize

    def emit_tick_events(t, mb_numel):
        nonlocal hop_cost
        frow, brow = fwd_tab[t], bwd_tab[t]
        busy = sum(1 for s in range(S) if frow[s] >= 0 or brow[s] >= 0)
        from ..core import topology as _topo

        active = _topo.active(p)
        hop_cost = _coll.pipeline_hop_cost(
            1, mb_numel, leaf0_item, p, stride=loc,
            local=active.local if active is not None else None,
        )
        telemetry.trace_event(
            "pipeline_tick",
            tick=t,
            schedule=table.name,
            phase=table.phase_of(t),
            stages=S,
            n_fwd=sum(1 for v in frow if v >= 0),
            n_bwd=sum(1 for v in brow if v >= 0),
            bubble=S - busy,
            hops=(2 if train else 1) if t < table.n_ticks - 1 else 0,
            **{f"hop_{k}": v for k, v in hop_cost.as_fields().items()},
        )

    def build():
        from jax.sharding import PartitionSpec as P

        def kernel(sflags, params_blk, opt_blk, micro_x, micro_y):
            i = jax.lax.axis_index(axis)
            sI, mI = i // loc, i % loc
            pleaves = local_leaves(params_blk)
            mb_shape = micro_x.shape[1:]
            mb_numel = 1
            for d in mb_shape[1:]:
                mb_numel *= int(d)
            varying = lambda v: jax.lax.pcast(v, (axis,), to="varying")
            micro_x = varying(micro_x)
            if train:
                micro_y = varying(micro_y)
            fwd_in = varying(jnp.zeros(mb_shape, micro_x.dtype))
            bwd_in = varying(jnp.zeros(mb_shape, micro_x.dtype))
            stash = varying(jnp.zeros((K,) + mb_shape, micro_x.dtype))
            loss_acc = varying(jnp.zeros((), jnp.float32))
            out = varying(jnp.zeros_like(micro_x)) if not train else None
            grad_acc = [
                varying(jnp.zeros_like(l)) for l in pleaves
            ] if train else None

            for t in range(table.n_ticks):
                emit_tick_events(t, int(mb_shape[0]) * mb_numel)
                frow = jnp.asarray(fwd_tab[t], jnp.int32)
                brow = jnp.asarray(bwd_tab[t], jnp.int32)
                my_f = jnp.take(frow, sI)
                my_b = jnp.take(brow, sI)
                do_f, do_b = my_f >= 0, my_b >= 0

                inject = jax.lax.dynamic_index_in_dim(
                    micro_x, jnp.clip(my_f, 0, M - 1), keepdims=False
                )
                h_in = jnp.where(sI == 0, inject, fwd_in)

                def fwd_branch(stash, h_in, my_f):
                    new_stash = jax.lax.dynamic_update_index_in_dim(
                        stash, h_in, jnp.remainder(my_f, K), axis=0
                    )
                    return new_stash, stage_forward(pleaves, h_in)

                stash, h_out = jax.lax.cond(
                    do_f,
                    fwd_branch,
                    lambda stash, h_in, my_f: (stash, h_in),
                    stash, h_in, my_f,
                )

                if not train:
                    out = jax.lax.cond(
                        (sI == S - 1) & (mI == 0) & do_f,
                        lambda o, h, m: jax.lax.dynamic_update_index_in_dim(
                            o, h, jnp.clip(m, 0, M - 1), axis=0
                        ),
                        lambda o, h, m: o,
                        out, h_out, my_f,
                    )
                else:
                    def bwd_branch(stash, bwd_in, my_b, loss_acc, *gacc):
                        x_in = jax.lax.dynamic_index_in_dim(
                            stash, jnp.remainder(my_b, K), keepdims=False
                        )
                        ws = [
                            gather_layer(pleaves, j, None)
                            for j in range(lps)
                        ]
                        y_mb = jax.lax.dynamic_index_in_dim(
                            micro_y, jnp.clip(my_b, 0, M - 1), keepdims=False
                        )

                        def last(ws, x_in, g_in):
                            def fl(ws, xi):
                                return (
                                    loss_fn(apply_gathered(ws, xi), y_mb) / M
                                )

                            lval, vjp = jax.vjp(fl, ws, x_in)
                            dws, dx = vjp(jnp.ones((), lval.dtype))
                            return dws, dx, lval.astype(jnp.float32)

                        def mid(ws, x_in, g_in):
                            _, vjp = jax.vjp(apply_gathered, ws, x_in)
                            dws, dx = vjp(g_in)
                            return dws, dx, varying(
                                jnp.zeros((), jnp.float32)
                            )

                        dws, dx, lval = jax.lax.cond(
                            sI == S - 1, last, mid, ws, x_in, bwd_in
                        )
                        dleaves = [
                            jax.tree_util.tree_flatten(dw)[0] for dw in dws
                        ]
                        new_gacc = []
                        for k in range(n_leaves):
                            upd = jnp.stack(
                                [
                                    _chunk_slice(
                                        dleaves[j][k], mI, loc,
                                        layout.chunk(k),
                                    )
                                    for j in range(lps)
                                ]
                            )
                            new_gacc.append(
                                gacc[k] + upd.astype(gacc[k].dtype)
                            )
                        return (dx, loss_acc + lval) + tuple(new_gacc)

                    res = jax.lax.cond(
                        do_b,
                        bwd_branch,
                        lambda stash, bwd_in, my_b, loss_acc, *gacc: (
                            (
                                varying(
                                    jnp.zeros(mb_shape, micro_x.dtype)
                                ),
                                loss_acc,
                            )
                            + tuple(gacc)
                        ),
                        stash, bwd_in, my_b, loss_acc, *grad_acc,
                    )
                    dx_out, loss_acc = res[0], res[1]
                    grad_acc = list(res[2:])

                # the inter-stage hops: unconditional (uniform SPMD), one
                # fwd and — training — one bwd collective-permute per tick,
                # each priced by pipeline_hop_cost (DCN when the stage
                # boundary crosses the node tier), audited zero-drift. The
                # final tick ships nothing (no later tick could consume the
                # payload), so the compiled program emits exactly
                # 2 x (n_ticks - 1) permutes and the analytic total agrees.
                if t < table.n_ticks - 1:
                    recv_f = comm.ppermute(h_out, fwd_perm, precision="off")
                    f_sent = (sI > 0) & (
                        jnp.take(frow, jnp.maximum(sI - 1, 0)) >= 0
                    )
                    fwd_in = jnp.where(f_sent, recv_f, fwd_in)
                    if train:
                        recv_b = comm.ppermute(
                            dx_out, bwd_perm, precision="off"
                        )
                        b_sent = (sI < S - 1) & (
                            jnp.take(brow, jnp.minimum(sI + 1, S - 1)) >= 0
                        )
                        bwd_in = jnp.where(b_sent, recv_b, bwd_in)

            if not train:
                return comm.psum(out, precision="off")

            # per-chunk optimizer update (ZeRO-composed: padded grad cells
            # are zero, elementwise transforms keep them zero)
            import optax

            params_local = jax.tree_util.tree_unflatten(
                layout.treedef, pleaves
            )
            grads = jax.tree_util.tree_unflatten(layout.treedef, grad_acc)
            opt_local = jax.tree_util.tree_map(
                lambda l, f: l[0] if f else l, opt_blk, sflags
            )
            updates, opt_new = optimizer.update(
                grads, opt_local, params_local
            )
            params_new = optax.apply_updates(params_local, updates)
            loss = comm.psum(
                jnp.where((sI == S - 1) & (mI == 0), loss_acc, 0.0),
                precision="off",
            )
            return (
                jax.tree_util.tree_map(lambda l: l[None], params_new),
                jax.tree_util.tree_map(
                    lambda l, f: l[None] if f else l, opt_new, sflags
                ),
                loss,
            )

        p_specs = jax.tree_util.tree_unflatten(
            layout.treedef, [P(axis)] * n_leaves
        )

        if train:
            def step(params, opt_state, micro_x, micro_y):
                rows = layout.row_shapes()
                sflags = jax.tree_util.tree_map(
                    lambda l: tuple(getattr(l, "shape", ())) in rows,
                    opt_state,
                )
                s_specs = jax.tree_util.tree_map(
                    lambda f: P(axis) if f else P(), sflags
                )
                return jax.shard_map(
                    lambda *a: kernel(sflags, *a),
                    mesh=comm.mesh,
                    in_specs=(p_specs, s_specs, P(), P()),
                    out_specs=(p_specs, s_specs, P()),
                )(params, opt_state, micro_x, micro_y)

            return step

        def fwd(params, micro_x):
            return jax.shard_map(
                lambda pp, xx: kernel(None, pp, None, xx, None),
                mesh=comm.mesh,
                in_specs=(p_specs, P()),
                out_specs=P(),
            )(params, micro_x)

        return fwd

    return program_cache.cached_program(
        "pipeline.step",
        (
            layer_fn, loss_fn, optimizer, layout.signature(),
            mapping.describe(), table.name, table.train, S, M,
            depth, remat,
        ),
        build,
        comm=comm,
    )
