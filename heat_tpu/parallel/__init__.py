"""Parallelism primitives — sequence/context parallelism as first-class ops.

The reference has no attention code, but it ships the *mechanisms* that
sequence parallelism is made of (SURVEY §5): the ring-pipelined stationary/
circulating block schedule (reference heat/spatial/distance.py:280-326), the
axis-aware Alltoall reshard (reference heat/core/communication.py:1180-1322 —
exactly the Ulysses head↔sequence swap), and halo exchange (reference
heat/core/dndarray.py:360-433). This package re-expresses those three as
TPU-native kernels (`shard_map` + `ppermute`/`all_to_all` over the mesh) and
builds long-context attention on top of them:

* :func:`ring_pipeline` — the generic stationary/circulating schedule.
* :func:`ring_attention` — blockwise flash attention with K/V circulated
  around the ring (Liu et al. 2023 schedule), sequence axis sharded.
* :func:`ulysses_attention` — all_to_all sequence↔head reshard, local
  attention, reshard back (Jacobs et al. 2023 schedule).
* :func:`halo_exchange` — neighbor-overlap slices for stencil ops.
* :func:`flash_attention` — the single-chip hot path as a hand-tiled Pallas
  TPU kernel (VMEM-resident online softmax, MXU-blocked QKᵀ/PV).
* :func:`pipeline_apply` — GPipe pipeline parallelism: one stage per mesh
  position, microbatch activations hopping the ring via `ppermute`.
* :class:`ScheduleTable` / :func:`build_schedule` / :class:`StageMapping`
  / :func:`pipeline_step_program` — MPMD pipeline training (ISSUE 19):
  static gpipe/1f1b action tables driving one cached `shard_map` train
  program, stages mapped per node group with the in-stage FSDP weight
  tier (see :class:`heat_tpu.nn.Pipeline`).
* :func:`shard_pytree` / :func:`constrain_pytree` — FSDP/ZeRO-style
  parameter and optimizer-state sharding (largest divisible axis per
  leaf; XLA inserts the use-site all-gathers).
* :class:`PartitionRules` / :func:`plan_partition` / :func:`fsdp_gather`
  — full FSDP (ISSUE 18): regex rule tables resolve arbitrary pytrees to
  flat 1/p layouts, and the just-in-time weight gather (tiered,
  wire-compressible, custom-vjp reduce-scatter backward) that
  :class:`heat_tpu.nn.FSDP` schedules with prefetch overlap.
"""

from .ring import ring_pipeline
from .attention import local_attention, ring_attention, ulysses_attention
from .halo import halo_exchange
from .pallas_attention import flash_attention
from .pipeline import (
    PipelineLayout,
    pipeline_apply,
    pipeline_step_program,
    plan_pipeline,
    shard_pipeline_params,
    stack_stage_params,
    unshard_pipeline_params,
)
from .schedule import (
    ScheduleTable,
    StageMapping,
    build_schedule,
    gpipe_schedule,
    one_f1b_schedule,
    plan_stages,
    resolve_schedule_name,
)
from .fsdp import (
    FsdpLeaf,
    FsdpPlan,
    PartitionRules,
    constrain_pytree,
    fsdp_gather,
    fsdp_shard,
    fsdp_unshard,
    leaf_paths,
    plan_partition,
    replicate_pytree,
    shard_pytree,
)

__all__ = [
    "ring_pipeline",
    "local_attention",
    "ring_attention",
    "ulysses_attention",
    "halo_exchange",
    "flash_attention",
    "pipeline_apply",
    "stack_stage_params",
    "PipelineLayout",
    "pipeline_step_program",
    "plan_pipeline",
    "shard_pipeline_params",
    "unshard_pipeline_params",
    "ScheduleTable",
    "StageMapping",
    "build_schedule",
    "gpipe_schedule",
    "one_f1b_schedule",
    "plan_stages",
    "resolve_schedule_name",
    "shard_pytree",
    "constrain_pytree",
    "replicate_pytree",
    "PartitionRules",
    "FsdpLeaf",
    "FsdpPlan",
    "leaf_paths",
    "plan_partition",
    "fsdp_shard",
    "fsdp_unshard",
    "fsdp_gather",
]
