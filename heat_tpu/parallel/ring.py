"""Generic ring pipeline: stationary block + circulating blocks.

This is the communication schedule the reference hand-writes twice — for the
pairwise distance matrix (reference heat/spatial/distance.py:280-326:
stationary x-block, y-blocks circulated rank→rank+1 with Send/Recv) and for
`linalg.outer` (reference heat/core/linalg/basics.py:1056). It is also
exactly the ring-attention schedule (stationary Q, circulating K/V). Here it
is one reusable `shard_map` kernel: `ppermute` moves the circulating operand
one hop per step over ICI while the MXU works on the current block, and XLA
overlaps the two.
"""

from __future__ import annotations

from typing import Any, Callable, Sequence, Union

import jax
import jax.numpy as jnp


def ring_pipeline(
    step_fn: Callable,
    stationary,
    circulating,
    init_carry,
    *,
    comm,
    shift: int = 1,
):
    """Run ``p`` ring steps of ``carry = step_fn(t, origin, stationary,
    circulating, carry)`` inside one compiled `shard_map` kernel.

    Parameters
    ----------
    step_fn : callable
        ``(t, origin, stationary, circulating, carry) -> carry`` where ``t``
        is the step index and ``origin`` the mesh position the circulating
        block currently held was sourced from (both traced scalars). Must be
        jit-pure; runs on the device-local blocks.
    stationary : pytree of jax.Array
        Sharded along their leading axis; never moves.
    circulating : pytree of jax.Array
        Sharded along their leading axis; rotated one hop per step.
    init_carry : pytree
        Initial accumulator; built per-shard from zeros/full shapes. Arrays
        are promoted to device-varying automatically.
    comm : MeshCommunication
        Supplies mesh, axis name and size.
    shift : int
        Ring direction; +1 sends shard i → i+1.

    Returns
    -------
    The final carry, as a `shard_map` output sharded along the leading axis
    (carry leaves keep their per-shard shape).
    """
    p = comm.size
    axis = comm.axis_name
    perm = [(i, (i + shift) % p) for i in range(p)]

    def kernel(stat, circ, carry):
        rank = jax.lax.axis_index(axis)

        def body(t, loop_carry):
            circ_t, acc = loop_carry
            # after t hops along +shift, shard r holds the block that
            # originated at (r - t*shift) mod p
            origin = (rank - t * shift) % p
            acc = step_fn(t, origin, stat, circ_t, acc)
            circ_t = jax.tree.map(
                # heatlint: disable=HL002 -- generic axis-name ring scaffold
                # (no comm object in scope); the PRICED rings (cdist, gram)
                # route their hops through comm wrappers at the call layer
                lambda x: jax.lax.ppermute(x, axis, perm=perm), circ_t
            )
            return (circ_t, acc)

        _, carry = jax.lax.fori_loop(0, p, body, (circ, carry))
        return carry

    spec_of = lambda x: comm.spec(0, x.ndim)
    in_stat_specs = jax.tree.map(spec_of, stationary)
    in_circ_specs = jax.tree.map(spec_of, circulating)
    carry_specs = jax.tree.map(spec_of, init_carry)
    return jax.shard_map(
        kernel,
        mesh=comm.mesh,
        in_specs=(in_stat_specs, in_circ_specs, carry_specs),
        out_specs=carry_specs,
    )(stationary, circulating, init_carry)
