"""Pallas TPU flash-attention kernel.

The hot op of the long-context stack (:mod:`heat_tpu.parallel.attention`)
hand-tiled for the TPU memory hierarchy: Q/K/V stream HBM→VMEM in
(block_q, block_k) tiles, the online-softmax accumulators (m, l, acc) live
in VMEM scratch across the K-block grid axis, and the QKᵀ / PV products hit
the MXU with explicit ``preferred_element_type=float32``. The reference
framework has no attention code at all (SURVEY §2.5); this kernel is the
TPU-native capability its ring/Alltoall mechanisms exist to enable, and a
drop-in replacement for the XLA-fused :func:`local_attention` path.

Numerics: same f32 online softmax and padding/causal mask semantics as
:func:`heat_tpu.parallel.attention.local_attention`. For f32 inputs the two
paths agree to tight tolerance (asserted on CPU via the Pallas
interpreter); for bf16 inputs the MXU dots run in bf16 with f32
accumulation (and p rounds to bf16 before the PV product — standard flash
practice), so agreement is to bf16 tolerance, also asserted. The backward
pass recomputes through the jnp path under ``jax.custom_vjp`` — flash
recomputation, O(T) memory, no stored (T, T) matrix.
"""

from __future__ import annotations

import functools
import math
from typing import Optional

import jax
import jax.numpy as jnp
import numpy as np
from jax.experimental import pallas as pl
from jax.experimental.pallas import tpu as pltpu

NEG_INF = -1e30
_LANES = 128  # TPU lane width: scratch rows are broadcast across it
# heat_tpu enables jax_enable_x64; a Python-int 0 in an index map then traces
# as an i64 constant, which Mosaic cannot legalize — pin index literals to i32
_I0 = np.int32(0)


def _flash_kernel(
    q_ref, k_ref, v_ref, o_ref, m_s, l_s, acc_s,
    *, scale, causal, kv_valid, block_q, block_k,
):
    """Grid = (B, H, num_q_blocks, num_k_blocks); last axis is sequential.

    Refs arrive as (1, 1, block, D) VMEM tiles. The (m, l, acc) scratch
    persists across the K axis — initialised at ik == 0, finalised into
    ``o_ref`` at the last K block.
    """
    iq = pl.program_id(2)
    ik = pl.program_id(3)
    nk = pl.num_programs(3)
    # Mosaic legalizes only f32 float constants — keep every scalar f32
    neg_inf = jnp.float32(NEG_INF)
    half_neg = jnp.float32(NEG_INF / 2)

    @pl.when(ik == 0)
    def _init():
        m_s[:] = jnp.full_like(m_s, neg_inf)
        l_s[:] = jnp.zeros_like(l_s)
        acc_s[:] = jnp.zeros_like(acc_s)

    # causal skip: a K block strictly above the diagonal band contributes
    # nothing — skip its MXU work entirely (DMA still streams it; the win is
    # ~2× compute on long causal sequences)
    if causal:
        live = ik * block_k <= iq * block_q + (block_q - 1)
    else:
        live = ik >= 0  # always true, keeps one code path

    @pl.when(live)
    def _accumulate():
        # MXU dots run in the INPUT dtype with f32 accumulation
        # (preferred_element_type): bf16 inputs hit the full-rate bf16 MXU
        # (an up-front astype(f32) would force true-f32 passes at ~1/4 the
        # throughput); f32 inputs keep exact f32 passes. Softmax stays f32.
        q = q_ref[0, 0]  # (bq, D)
        k = k_ref[0, 0]  # (bk, D)
        v = v_ref[0, 0]

        s = jax.lax.dot_general(
            q, k, (((1,), (1,)), ((), ())), preferred_element_type=jnp.float32
        ) * jnp.float32(scale)  # (bq, bk), f32

        k_pos = ik * block_k + jax.lax.broadcasted_iota(
            jnp.int32, (block_q, block_k), 1
        )
        mask = k_pos < kv_valid
        if causal:
            q_pos = iq * block_q + jax.lax.broadcasted_iota(
                jnp.int32, (block_q, block_k), 0
            )
            mask = mask & (k_pos <= q_pos)
        s = jnp.where(mask, s, neg_inf)

        m_prev = m_s[:, 0:1]  # (bq, 1), lanes hold copies
        l_prev = l_s[:, 0:1]
        m_cur = jnp.max(s, axis=-1, keepdims=True)
        m_new = jnp.maximum(m_prev, m_cur)
        zero = jnp.float32(0.0)
        m_safe = jnp.where(m_new <= half_neg, zero, m_new)
        p = jnp.where(mask, jnp.exp(s - m_safe), zero)
        alpha = jnp.where(m_prev <= half_neg, zero, jnp.exp(m_prev - m_safe))
        l_new = alpha * l_prev + jnp.sum(p, axis=-1, keepdims=True)
        # PV in v's dtype (standard flash practice): for bf16 v the f32
        # probabilities round to bf16 on the way into the MXU, accumulating
        # in f32 — covered by the bf16 agreement tolerance; f32 v unchanged
        p_mx = p if v.dtype == jnp.float32 else p.astype(v.dtype)
        pv = jax.lax.dot_general(
            p_mx, v, (((1,), (0,)), ((), ())), preferred_element_type=jnp.float32
        )  # (bq, D), f32

        m_s[:] = jnp.broadcast_to(m_new, m_s.shape)
        l_s[:] = jnp.broadcast_to(l_new, l_s.shape)
        acc_s[:] = acc_s[:] * alpha + pv

    @pl.when(ik == nk - 1)
    def _finalize():
        l_fin = l_s[:, 0:1]
        denom = jnp.where(l_fin == jnp.float32(0.0), jnp.float32(1.0), l_fin)
        o_ref[0, 0] = (acc_s[:] / denom).astype(o_ref.dtype)


def _out_struct(shape, like):
    """ShapeDtypeStruct matching ``like``'s dtype — inside a shard_map the
    output must also declare how it varies over mesh axes (vma), inherited
    from the input block."""
    try:
        vma = jax.typeof(like).vma
    except (AttributeError, TypeError):
        vma = None
    if vma:
        return jax.ShapeDtypeStruct(shape, like.dtype, vma=vma)
    return jax.ShapeDtypeStruct(shape, like.dtype)


def _flash_forward(q, k, v, scale, causal, kv_valid, block_q, block_k, interpret):
    b, h, t_q, d = q.shape
    t_k = k.shape[2]

    # clamp blocks for short sequences so padding stays one lane-tile, then
    # pad seq lengths to block multiples and head dim to the lane width;
    # zero-pad K/V tails are masked out via kv_valid, Q tail rows sliced off
    block_q = min(block_q, -(-t_q // _LANES) * _LANES)
    block_k = min(block_k, -(-t_k // _LANES) * _LANES)
    pq = -t_q % block_q
    pk = -t_k % block_k
    pd = -d % _LANES
    if pq:
        q = jnp.pad(q, ((0, 0), (0, 0), (0, pq), (0, 0)))
    if pk:
        k = jnp.pad(k, ((0, 0), (0, 0), (0, pk), (0, 0)))
        v = jnp.pad(v, ((0, 0), (0, 0), (0, pk), (0, 0)))
    if pd:
        q = jnp.pad(q, ((0, 0), (0, 0), (0, 0), (0, pd)))
        k = jnp.pad(k, ((0, 0), (0, 0), (0, 0), (0, pd)))
        v = jnp.pad(v, ((0, 0), (0, 0), (0, 0), (0, pd)))
    dp = d + pd

    grid = (b, h, (t_q + pq) // block_q, (t_k + pk) // block_k)
    kernel = functools.partial(
        _flash_kernel,
        scale=scale, causal=causal, kv_valid=kv_valid,
        block_q=block_q, block_k=block_k,
    )
    out = pl.pallas_call(
        kernel,
        grid=grid,
        in_specs=[
            pl.BlockSpec(
                (1, 1, block_q, dp), lambda bi, hi, qi, ki: (bi, hi, qi, _I0),
                memory_space=pltpu.VMEM,
            ),
            pl.BlockSpec(
                (1, 1, block_k, dp), lambda bi, hi, qi, ki: (bi, hi, ki, _I0),
                memory_space=pltpu.VMEM,
            ),
            pl.BlockSpec(
                (1, 1, block_k, dp), lambda bi, hi, qi, ki: (bi, hi, ki, _I0),
                memory_space=pltpu.VMEM,
            ),
        ],
        out_specs=pl.BlockSpec(
            (1, 1, block_q, dp), lambda bi, hi, qi, ki: (bi, hi, qi, _I0),
            memory_space=pltpu.VMEM,
        ),
        out_shape=_out_struct((b, h, t_q + pq, dp), q),
        scratch_shapes=[
            pltpu.VMEM((block_q, _LANES), jnp.float32),  # m
            pltpu.VMEM((block_q, _LANES), jnp.float32),  # l
            pltpu.VMEM((block_q, dp), jnp.float32),      # acc
        ],
        compiler_params=pltpu.CompilerParams(
            dimension_semantics=("parallel", "parallel", "parallel", "arbitrary"),
        ),
        interpret=interpret,
    )(q, k, v)
    return out[:, :, :t_q, :d]


@functools.partial(
    jax.custom_vjp, nondiff_argnums=(3, 4, 5, 6, 7, 8)
)
def _flash(q, k, v, scale, causal, kv_valid, block_q, block_k, interpret):
    return _flash_forward(
        q, k, v, scale, causal, kv_valid, block_q, block_k, interpret
    )


def _flash_fwd(q, k, v, scale, causal, kv_valid, block_q, block_k, interpret):
    out = _flash(q, k, v, scale, causal, kv_valid, block_q, block_k, interpret)
    return out, (q, k, v)


def _flash_bwd(scale, causal, kv_valid, block_q, block_k, interpret, res, g):
    # flash recomputation: rebuild the forward through the XLA online-softmax
    # path (same numerics) and let autodiff produce the gradients — O(T)
    # memory, nothing saved but q/k/v
    from .attention import local_attention

    q, k, v = res

    def ref_fwd(q_, k_, v_):
        o = local_attention(
            q_.transpose(0, 2, 1, 3), k_.transpose(0, 2, 1, 3),
            v_.transpose(0, 2, 1, 3),
            causal=causal, scale=scale, kv_valid=kv_valid,
        )
        return o.transpose(0, 2, 1, 3)

    _, vjp = jax.vjp(ref_fwd, q, k, v)
    return vjp(g)


_flash.defvjp(_flash_fwd, _flash_bwd)


def _resolve_interpret(x) -> bool:
    """True when the kernel must run in the Pallas interpreter.

    Resolved from where the computation actually runs, not the global
    default backend: a concrete input's device platform wins, because in a
    mixed-platform process (a forced virtual CPU mesh alongside a live TPU
    backend, e.g. the multichip dryrun after a real-chip compile check)
    ``jax.default_backend()`` says "tpu" while the arrays live on CPU.
    Tracers carry no placement, so they fall back to the default backend.
    """
    try:
        platforms = {d.platform for d in x.devices()}
        if platforms:
            return platforms != {"tpu"}
    except Exception:
        pass
    return jax.default_backend() != "tpu"


def flash_attention(
    q: jax.Array,
    k: jax.Array,
    v: jax.Array,
    *,
    causal: bool = False,
    scale: Optional[float] = None,
    kv_valid: Optional[int] = None,
    block_q: int = 512,
    block_k: int = 1024,
    interpret: Optional[bool] = None,
) -> jax.Array:
    """Flash attention as a hand-tiled Pallas TPU kernel.

    Same contract as :func:`heat_tpu.parallel.attention.local_attention`:
    ``(B, T, H, D)`` layout, f32 online softmax, K/V positions >= ``kv_valid``
    masked as padding. Default (512, 1024) blocks won the v5e block sweep;
    the jit-chained benchmark at B4·T4096·H8·D128 bf16 measures 68.2 TFLOP/s
    (README table), 2.7× the XLA online-softmax path. Blocks are clamped for
    short sequences. ``interpret`` defaults to True off-TPU so the same
    tests run on the CPU mesh via the Pallas interpreter.
    """
    if q.ndim != 4:
        raise ValueError(f"expected (B, T, H, D) inputs, got {q.shape}")
    if interpret is None:
        interpret = _resolve_interpret(q)
    d = q.shape[-1]
    t_k = k.shape[1]
    scale = scale if scale is not None else 1.0 / math.sqrt(d)
    kv_valid = t_k if kv_valid is None else int(kv_valid)
    # kernel works in (B, H, T, D); public layout is (B, T, H, D)
    out = _flash(
        q.transpose(0, 2, 1, 3), k.transpose(0, 2, 1, 3),
        v.transpose(0, 2, 1, 3),
        scale, causal, kv_valid, block_q, block_k, interpret,
    )
    return out.transpose(0, 2, 1, 3)
