"""Pallas TPU flash-attention kernel.

The hot op of the long-context stack (:mod:`heat_tpu.parallel.attention`)
hand-tiled for the TPU memory hierarchy: Q/K/V stream HBM→VMEM in
(block_q, block_k) tiles, the online-softmax accumulators (m, l, acc) live
in VMEM scratch across the K-block grid axis, and the QKᵀ / PV products hit
the MXU with explicit ``preferred_element_type=float32``. The reference
framework has no attention code at all (SURVEY §2.5); this kernel is the
TPU-native capability its ring/Alltoall mechanisms exist to enable, and a
drop-in replacement for the XLA-fused :func:`local_attention` path.

Numerics: same f32 online softmax and padding/causal mask semantics as
:func:`heat_tpu.parallel.attention.local_attention`. For f32 inputs the two
paths agree to tight tolerance (asserted on CPU via the Pallas
interpreter); for bf16 inputs the MXU dots run in bf16 with f32
accumulation (and p rounds to bf16 before the PV product — standard flash
practice), so agreement is to bf16 tolerance, also asserted. The backward
rebuilds probabilities from the saved O and log-sum-exp residuals — O(T)
memory (no stored (T, T) matrix), every MXU dot in the input dtype — in
one of two selectable strategies (``flash_attention(bwd_impl=...)``):
``"two_pass"`` hand-tiled kernels (dq; dk/dv), or the ``"fused"``
single-pass kernel that shares the rebuild across dq/dk/dv with a
VMEM-resident f32 dQ block (``"auto"`` picks fused when that block fits).
The inference-only forward skips the log-sum-exp output entirely.
"""

from __future__ import annotations

import functools
import math
from typing import Optional

import jax
import jax.numpy as jnp
import numpy as np
from jax.experimental import pallas as pl
from jax.experimental.pallas import tpu as pltpu

NEG_INF = -1e30
_LANES = 128  # TPU lane width: scratch rows are broadcast across it
# heat_tpu enables jax_enable_x64; a Python-int 0 in an index map then traces
# as an i64 constant, which Mosaic cannot legalize — pin index literals to i32
_I0 = np.int32(0)


def _flash_kernel(
    q_ref, k_ref, v_ref, o_ref, lse_ref, m_s, l_s, acc_s,
    *, scale, causal, kv_valid, block_q, block_k,
):
    """Grid = (B, H, num_q_blocks, num_k_blocks); last axis is sequential.

    Refs arrive as (1, 1, block, D) VMEM tiles. The (m, l, acc) scratch
    persists across the K axis — initialised at ik == 0, finalised into
    ``o_ref`` at the last K block.
    """
    iq = pl.program_id(2)
    ik = pl.program_id(3)
    nk = pl.num_programs(3)
    # Mosaic legalizes only f32 float constants — keep every scalar f32
    neg_inf = jnp.float32(NEG_INF)
    half_neg = jnp.float32(NEG_INF / 2)

    @pl.when(ik == 0)
    def _init():
        m_s[:] = jnp.full_like(m_s, neg_inf)
        l_s[:] = jnp.zeros_like(l_s)
        acc_s[:] = jnp.zeros_like(acc_s)

    # causal skip: a K block strictly above the diagonal band contributes
    # nothing — skip its MXU work entirely (DMA still streams it; the win is
    # ~2× compute on long causal sequences)
    if causal:
        live = ik * block_k <= iq * block_q + (block_q - 1)
    else:
        live = ik >= 0  # always true, keeps one code path

    @pl.when(live)
    def _accumulate():
        # MXU dots run in the INPUT dtype with f32 accumulation
        # (preferred_element_type): bf16 inputs hit the full-rate bf16 MXU
        # (an up-front astype(f32) would force true-f32 passes at ~1/4 the
        # throughput); f32 inputs keep exact f32 passes. Softmax stays f32.
        q = q_ref[0, 0]  # (bq, D)
        k = k_ref[0, 0]  # (bk, D)
        v = v_ref[0, 0]

        s = jax.lax.dot_general(
            q, k, (((1,), (1,)), ((), ())), preferred_element_type=jnp.float32
        ) * jnp.float32(scale)  # (bq, bk), f32

        k_pos = ik * block_k + jax.lax.broadcasted_iota(
            jnp.int32, (block_q, block_k), 1
        )
        mask = k_pos < kv_valid
        if causal:
            q_pos = iq * block_q + jax.lax.broadcasted_iota(
                jnp.int32, (block_q, block_k), 0
            )
            mask = mask & (k_pos <= q_pos)
        s = jnp.where(mask, s, neg_inf)

        m_prev = m_s[:, 0:1]  # (bq, 1), lanes hold copies
        l_prev = l_s[:, 0:1]
        m_cur = jnp.max(s, axis=-1, keepdims=True)
        m_new = jnp.maximum(m_prev, m_cur)
        zero = jnp.float32(0.0)
        m_safe = jnp.where(m_new <= half_neg, zero, m_new)
        p = jnp.where(mask, jnp.exp(s - m_safe), zero)
        alpha = jnp.where(m_prev <= half_neg, zero, jnp.exp(m_prev - m_safe))
        l_new = alpha * l_prev + jnp.sum(p, axis=-1, keepdims=True)
        # PV in v's dtype (standard flash practice): for bf16 v the f32
        # probabilities round to bf16 on the way into the MXU, accumulating
        # in f32 — covered by the bf16 agreement tolerance; f32 v unchanged
        p_mx = p if v.dtype == jnp.float32 else p.astype(v.dtype)
        pv = jax.lax.dot_general(
            p_mx, v, (((1,), (0,)), ((), ())), preferred_element_type=jnp.float32
        )  # (bq, D), f32

        m_s[:] = jnp.broadcast_to(m_new, m_s.shape)
        l_s[:] = jnp.broadcast_to(l_new, l_s.shape)
        acc_s[:] = acc_s[:] * alpha + pv

    @pl.when(ik == nk - 1)
    def _finalize():
        l_fin = l_s[:, 0:1]
        denom = jnp.where(l_fin == jnp.float32(0.0), jnp.float32(1.0), l_fin)
        o_ref[0, 0] = (acc_s[:] / denom).astype(o_ref.dtype)
        if lse_ref is not None:
            # log-sum-exp for the backward pass, lane-broadcast layout
            # (block_q, 128) like the scratch; fully-masked rows get +BIG so
            # the backward's exp(s - lse) is exactly 0 there
            big = jnp.float32(1e30)
            m_fin = m_s[:]
            l_full = l_s[:]
            m_fin_safe = jnp.where(m_fin <= half_neg, jnp.float32(0.0), m_fin)
            lse = jnp.where(
                l_full == jnp.float32(0.0),
                big,
                m_fin_safe + jnp.log(jnp.maximum(l_full, jnp.float32(1e-38))),
            )
            lse_ref[0, 0] = lse


def _out_struct(shape, like, dtype=None):
    """ShapeDtypeStruct matching ``like``'s dtype (or an explicit one) —
    inside a shard_map the output must also declare how it varies over mesh
    axes (vma), inherited from the input block."""
    dtype = like.dtype if dtype is None else dtype
    try:
        vma = jax.typeof(like).vma
    except (AttributeError, TypeError):
        vma = None
    if vma:
        return jax.ShapeDtypeStruct(shape, dtype, vma=vma)
    return jax.ShapeDtypeStruct(shape, dtype)


def _block_geometry(t_q, t_k, d, block_q, block_k):
    """Resolve the effective tiling: clamped blocks and pad amounts.

    The ONE source of truth for this arithmetic — `_pad_blocks` pads with
    it and `_flash_bwd_dispatch`'s "auto" sizes the fused dQ block with
    it, so the two can never disagree about the resident-block footprint.
    """
    block_q = min(block_q, -(-t_q // _LANES) * _LANES)
    block_k = min(block_k, -(-t_k // _LANES) * _LANES)
    pq = -t_q % block_q
    pk = -t_k % block_k
    pd = -d % _LANES
    return block_q, block_k, pq, pk, pd


def _pad_blocks(q, k, v, t_q, t_k, d, block_q, block_k):
    """Clamp blocks for short sequences, pad seq lengths to block multiples
    and the head dim to the lane width. Returns the padded operands and the
    resolved geometry."""
    block_q, block_k, pq, pk, pd = _block_geometry(
        t_q, t_k, d, block_q, block_k
    )
    if pq:
        q = jnp.pad(q, ((0, 0), (0, 0), (0, pq), (0, 0)))
    if pk:
        k = jnp.pad(k, ((0, 0), (0, 0), (0, pk), (0, 0)))
        v = jnp.pad(v, ((0, 0), (0, 0), (0, pk), (0, 0)))
    if pd:
        q = jnp.pad(q, ((0, 0), (0, 0), (0, 0), (0, pd)))
        k = jnp.pad(k, ((0, 0), (0, 0), (0, 0), (0, pd)))
        v = jnp.pad(v, ((0, 0), (0, 0), (0, 0), (0, pd)))
    return q, k, v, block_q, block_k, pq, pk, d + pd


def _flash_forward(
    q, k, v, scale, causal, kv_valid, block_q, block_k, interpret,
    return_lse=False,
):
    b, h, t_q, d = q.shape
    t_k = k.shape[2]
    # zero-pad K/V tails are masked out via kv_valid, Q tail rows sliced off
    q, k, v, block_q, block_k, pq, pk, dp = _pad_blocks(
        q, k, v, t_q, t_k, d, block_q, block_k
    )

    grid = (b, h, (t_q + pq) // block_q, (t_k + pk) // block_k)
    kernel = functools.partial(
        _flash_kernel,
        scale=scale, causal=causal, kv_valid=kv_valid,
        block_q=block_q, block_k=block_k,
    )
    o_spec = pl.BlockSpec(
        (1, 1, block_q, dp), lambda bi, hi, qi, ki: (bi, hi, qi, _I0),
        memory_space=pltpu.VMEM,
    )
    if return_lse:
        out_specs = [
            o_spec,
            pl.BlockSpec(
                (1, 1, block_q, _LANES),
                lambda bi, hi, qi, ki: (bi, hi, qi, _I0),
                memory_space=pltpu.VMEM,
            ),
        ]
        out_shape = [
            _out_struct((b, h, t_q + pq, dp), q),
            _out_struct((b, h, t_q + pq, _LANES), q, dtype=jnp.float32),
        ]
        kfn = kernel
    else:
        # inference-only path: no lse buffer is declared or written — a
        # custom call's unused output would not be DCE'd and at bench shapes
        # the f32 lse would cost 2x the bytes of the bf16 output itself
        out_specs = o_spec
        out_shape = _out_struct((b, h, t_q + pq, dp), q)

        def kfn(q_ref, k_ref, v_ref, o_ref, m_s, l_s, acc_s):
            return kernel(q_ref, k_ref, v_ref, o_ref, None, m_s, l_s, acc_s)

    res = pl.pallas_call(
        kfn,
        grid=grid,
        in_specs=[
            pl.BlockSpec(
                (1, 1, block_q, dp), lambda bi, hi, qi, ki: (bi, hi, qi, _I0),
                memory_space=pltpu.VMEM,
            ),
            pl.BlockSpec(
                (1, 1, block_k, dp), lambda bi, hi, qi, ki: (bi, hi, ki, _I0),
                memory_space=pltpu.VMEM,
            ),
            pl.BlockSpec(
                (1, 1, block_k, dp), lambda bi, hi, qi, ki: (bi, hi, ki, _I0),
                memory_space=pltpu.VMEM,
            ),
        ],
        out_specs=out_specs,
        out_shape=out_shape,
        scratch_shapes=[
            pltpu.VMEM((block_q, _LANES), jnp.float32),  # m
            pltpu.VMEM((block_q, _LANES), jnp.float32),  # l
            pltpu.VMEM((block_q, dp), jnp.float32),      # acc
        ],
        compiler_params=pltpu.CompilerParams(
            dimension_semantics=("parallel", "parallel", "parallel", "arbitrary"),
        ),
        interpret=interpret,
    )(q, k, v)
    if return_lse:
        out, lse = res
        # lse stays in padded lane-broadcast layout
        return out[:, :, :t_q, :d], lse
    return res[:, :, :t_q, :d]


def _rebuild_probs(q, k, lse, iq, ik, *, scale, causal, kv_valid, block_q, block_k):
    """Shared backward-pass probability reconstruction: the (bq, bk) score
    block, kv_valid + causal masking, and ``p = exp(s − lse)`` — one
    definition so the dq and dk/dv kernels can never desynchronize."""
    neg_inf = jnp.float32(NEG_INF)
    s = jax.lax.dot_general(
        q, k, (((1,), (1,)), ((), ())), preferred_element_type=jnp.float32
    ) * jnp.float32(scale)
    k_pos = ik * block_k + jax.lax.broadcasted_iota(
        jnp.int32, (block_q, block_k), 1
    )
    mask = k_pos < kv_valid
    if causal:
        q_pos = iq * block_q + jax.lax.broadcasted_iota(
            jnp.int32, (block_q, block_k), 0
        )
        mask = mask & (k_pos <= q_pos)
    s = jnp.where(mask, s, neg_inf)
    p = jnp.where(mask, jnp.exp(s - lse), jnp.float32(0.0))
    return p


def _bwd_block_terms(
    refs, iq, ik, *, scale, causal, kv_valid, block_q, block_k
):
    """Shared backward block math: unpack the (1, 1, blk, D) refs, rebuild
    p, compute ``dP = dO Vᵀ`` and ``dS = P ∘ (dP − D) · scale`` with the
    MXU-dtype casts — ONE definition so the dq, dk/dv, and fused kernels
    can never desynchronize. Returns (q, k, v, do, p_mx, ds_mx)."""
    q_ref, k_ref, v_ref, do_ref, lse_ref, dd_ref = refs
    q = q_ref[0, 0]
    k = k_ref[0, 0]
    v = v_ref[0, 0]
    do = do_ref[0, 0]
    lse = lse_ref[0, 0][:, 0:1]  # (bq, 1)
    dd = dd_ref[0, 0][:, 0:1]

    p = _rebuild_probs(
        q, k, lse, iq, ik, scale=scale, causal=causal, kv_valid=kv_valid,
        block_q=block_q, block_k=block_k,
    )  # (bq, bk)
    p_mx = p if do.dtype == jnp.float32 else p.astype(do.dtype)
    dp = jax.lax.dot_general(
        do, v, (((1,), (1,)), ((), ())), preferred_element_type=jnp.float32
    )  # (bq, bk)
    ds = p * (dp - dd) * jnp.float32(scale)
    ds_mx = ds if q.dtype == jnp.float32 else ds.astype(q.dtype)
    return q, k, v, do, p_mx, ds_mx


def _bwd_dq_kernel(
    q_ref, k_ref, v_ref, do_ref, lse_ref, dd_ref, dq_ref, dq_acc,
    *, scale, causal, kv_valid, block_q, block_k,
):
    """dQ pass. Grid = (B, H, num_q_blocks, num_k_blocks), last sequential.

    p is rebuilt from the saved log-sum-exp (``p = exp(s − lse)``), then
    ``dS = P ∘ (dP − D)`` and ``dQ += scale · dS Kᵀ`` accumulate in VMEM
    scratch across the K axis — the standard flash backward, all four MXU
    dots in the input dtype with f32 accumulation.
    """
    iq = pl.program_id(2)
    ik = pl.program_id(3)
    nk = pl.num_programs(3)

    @pl.when(ik == 0)
    def _init():
        dq_acc[:] = jnp.zeros_like(dq_acc)

    if causal:
        live = ik * block_k <= iq * block_q + (block_q - 1)
    else:
        live = ik >= 0

    @pl.when(live)
    def _accumulate():
        _, k, _, _, _, ds_mx = _bwd_block_terms(
            (q_ref, k_ref, v_ref, do_ref, lse_ref, dd_ref), iq, ik,
            scale=scale, causal=causal, kv_valid=kv_valid,
            block_q=block_q, block_k=block_k,
        )
        dq_acc[:] = dq_acc[:] + jax.lax.dot_general(
            ds_mx, k, (((1,), (0,)), ((), ())),
            preferred_element_type=jnp.float32,
        )

    @pl.when(ik == nk - 1)
    def _finalize():
        dq_ref[0, 0] = dq_acc[:].astype(dq_ref.dtype)


def _bwd_dkv_kernel(
    q_ref, k_ref, v_ref, do_ref, lse_ref, dd_ref, dk_ref, dv_ref,
    dk_acc, dv_acc,
    *, scale, causal, kv_valid, block_q, block_k,
):
    """dK/dV pass. Grid = (B, H, num_k_blocks, num_q_blocks), last
    sequential: the transposed-probability form — ``dV += Pᵀ dO`` and
    ``dK += scale · dSᵀ Q`` accumulate per K block across the Q axis."""
    ik = pl.program_id(2)
    iq = pl.program_id(3)
    nq = pl.num_programs(3)

    @pl.when(iq == 0)
    def _init():
        dk_acc[:] = jnp.zeros_like(dk_acc)
        dv_acc[:] = jnp.zeros_like(dv_acc)

    if causal:
        live = iq * block_q + (block_q - 1) >= ik * block_k
    else:
        live = iq >= 0

    @pl.when(live)
    def _accumulate():
        # same (bq, bk) score orientation as the dq pass — the q-dim
        # contractions below transpose implicitly via dot_general dimension
        # numbers (no Mosaic-side transposes)
        q, _, _, do, p_mx, ds_mx = _bwd_block_terms(
            (q_ref, k_ref, v_ref, do_ref, lse_ref, dd_ref), iq, ik,
            scale=scale, causal=causal, kv_valid=kv_valid,
            block_q=block_q, block_k=block_k,
        )
        # dV += Pᵀ dO: contract the q dim of both operands
        dv_acc[:] = dv_acc[:] + jax.lax.dot_general(
            p_mx, do, (((0,), (0,)), ((), ())),
            preferred_element_type=jnp.float32,
        )
        # dK += dSᵀ Q: contract the q dim
        dk_acc[:] = dk_acc[:] + jax.lax.dot_general(
            ds_mx, q, (((0,), (0,)), ((), ())),
            preferred_element_type=jnp.float32,
        )

    @pl.when(iq == nq - 1)
    def _finalize():
        dk_ref[0, 0] = dk_acc[:].astype(dk_ref.dtype)
        dv_ref[0, 0] = dv_acc[:].astype(dv_ref.dtype)


def _bwd_prologue(res, g, block_q, block_k):
    """Shared backward host-side prep: the D = rowsum(dO ∘ O) residual,
    block clamping/padding of every operand, and the lane-broadcast dd
    layout. One definition for the two-pass and fused drivers."""
    q, k, v, out, lse = res
    b, h, t_q, d = q.shape
    t_k = k.shape[2]

    dd = (g.astype(jnp.float32) * out.astype(jnp.float32)).sum(axis=-1)
    qp, kp, vp, block_q, block_k, pq, pk, dp = _pad_blocks(
        q, k, v, t_q, t_k, d, block_q, block_k
    )
    pd_extra = dp - d
    if pq or pd_extra:
        do_p = jnp.pad(g, ((0, 0), (0, 0), (0, pq), (0, pd_extra)))
    else:
        do_p = g
    dd_p = jnp.pad(dd, ((0, 0), (0, 0), (0, pq)))[..., None] * jnp.ones(
        (_LANES,), jnp.float32
    )
    return qp, kp, vp, do_p, dd_p, block_q, block_k, pq, pk, dp


def _bwd_fused_kernel(
    q_ref, k_ref, v_ref, do_ref, lse_ref, dd_ref, dq_ref, dk_ref, dv_ref,
    dk_acc, dv_acc,
    *, scale, causal, kv_valid, block_q, block_k,
):
    """Single-pass backward. Grid = (B, H, num_k_blocks, num_q_blocks),
    the last two sequential.

    The two-pass backward rebuilds p and recomputes the dP dot once per
    pass — 7 MXU dots, two exp sweeps, and two full Q/K/V/dO streams per
    live block pair. Here each (ki, qi) pair is visited ONCE: p, dP, dS
    are shared, dV/dK accumulate in per-ki scratch (flushed when qi
    wraps, as in the two-pass dkv kernel) and dQ accumulates into its
    own full-resident f32 output block via a dynamic row-slice — 5 dots,
    one exp sweep, one stream. Costs VMEM: the whole (T_q, d) f32 dQ
    block stays resident, which is why the fused path is gated on
    ``_fused_bwd_fits``."""
    ik = pl.program_id(2)
    iq = pl.program_id(3)
    nq = pl.num_programs(3)

    @pl.when((ik == 0) & (iq == 0))
    def _init_dq():
        dq_ref[0, 0] = jnp.zeros_like(dq_ref[0, 0])

    @pl.when(iq == 0)
    def _init_kv():
        dk_acc[:] = jnp.zeros_like(dk_acc)
        dv_acc[:] = jnp.zeros_like(dv_acc)

    if causal:
        live = iq * block_q + (block_q - 1) >= ik * block_k
    else:
        live = iq >= 0

    @pl.when(live)
    def _accumulate():
        q, k, _, do, p_mx, ds_mx = _bwd_block_terms(
            (q_ref, k_ref, v_ref, do_ref, lse_ref, dd_ref), iq, ik,
            scale=scale, causal=causal, kv_valid=kv_valid,
            block_q=block_q, block_k=block_k,
        )
        dv_acc[:] = dv_acc[:] + jax.lax.dot_general(
            p_mx, do, (((0,), (0,)), ((), ())),
            preferred_element_type=jnp.float32,
        )
        dk_acc[:] = dk_acc[:] + jax.lax.dot_general(
            ds_mx, q, (((0,), (0,)), ((), ())),
            preferred_element_type=jnp.float32,
        )
        row = pl.multiple_of(iq * block_q, block_q)
        dq_ref[0, 0, pl.ds(row, block_q), :] = dq_ref[
            0, 0, pl.ds(row, block_q), :
        ] + jax.lax.dot_general(
            ds_mx, k, (((1,), (0,)), ((), ())),
            preferred_element_type=jnp.float32,
        )

    @pl.when(iq == nq - 1)
    def _finalize():
        dk_ref[0, 0] = dk_acc[:].astype(dk_ref.dtype)
        dv_ref[0, 0] = dv_acc[:].astype(dv_ref.dtype)


# the fused backward keeps the whole (T_q, d) f32 dQ block resident in
# VMEM (~16 MB/core on v5e); 4 MB leaves room for the streaming blocks,
# their double buffers, and the dK/dV scratch
_FUSED_BWD_DQ_BYTES = 4 * 1024 * 1024


def _fused_bwd_fits(t_q_padded: int, dp: int) -> bool:
    return t_q_padded * dp * 4 <= _FUSED_BWD_DQ_BYTES


def _flash_bwd_fused(
    scale, causal, kv_valid, block_q, block_k, interpret, res, g
):
    """Fused-kernel backward; same contract as the two-pass `_flash_bwd`."""
    q, k, v, out, lse = res
    b, h, t_q, d = q.shape
    t_k = k.shape[2]
    qp, kp, vp, do_p, dd_p, block_q, block_k, pq, pk, dp = _bwd_prologue(
        res, g, block_q, block_k
    )

    tq_p = t_q + pq
    grid = (b, h, (t_k + pk) // block_k, tq_p // block_q)
    qo_spec = pl.BlockSpec(
        (1, 1, block_q, dp), lambda bi, hi, ki, qi: (bi, hi, qi, _I0),
        memory_space=pltpu.VMEM,
    )
    kv_spec = pl.BlockSpec(
        (1, 1, block_k, dp), lambda bi, hi, ki, qi: (bi, hi, ki, _I0),
        memory_space=pltpu.VMEM,
    )
    lm_spec = pl.BlockSpec(
        (1, 1, block_q, _LANES), lambda bi, hi, ki, qi: (bi, hi, qi, _I0),
        memory_space=pltpu.VMEM,
    )
    dq_spec = pl.BlockSpec(
        (1, 1, tq_p, dp), lambda bi, hi, ki, qi: (bi, hi, _I0, _I0),
        memory_space=pltpu.VMEM,
    )
    dq, dk, dv = pl.pallas_call(
        functools.partial(
            _bwd_fused_kernel, scale=scale, causal=causal, kv_valid=kv_valid,
            block_q=block_q, block_k=block_k,
        ),
        grid=grid,
        in_specs=[qo_spec, kv_spec, kv_spec, qo_spec, lm_spec, lm_spec],
        out_specs=[dq_spec, kv_spec, kv_spec],
        out_shape=[
            # f32: the output block IS the cross-ki accumulator
            _out_struct((b, h, tq_p, dp), q, dtype=jnp.float32),
            _out_struct((b, h, t_k + pk, dp), k),
            _out_struct((b, h, t_k + pk, dp), v),
        ],
        scratch_shapes=[
            pltpu.VMEM((block_k, dp), jnp.float32),
            pltpu.VMEM((block_k, dp), jnp.float32),
        ],
        compiler_params=pltpu.CompilerParams(
            dimension_semantics=(
                "parallel", "parallel", "arbitrary", "arbitrary"
            ),
        ),
        interpret=interpret,
    )(qp, kp, vp, do_p, lse, dd_p)

    return (
        dq[:, :, :t_q, :d].astype(q.dtype),
        dk[:, :, :t_k, :d].astype(k.dtype),
        dv[:, :, :t_k, :d].astype(v.dtype),
    )


@functools.partial(
    jax.custom_vjp, nondiff_argnums=(3, 4, 5, 6, 7, 8, 9)
)
def _flash(
    q, k, v, scale, causal, kv_valid, block_q, block_k, interpret, bwd_impl
):
    return _flash_forward(
        q, k, v, scale, causal, kv_valid, block_q, block_k, interpret
    )


def _flash_fwd(
    q, k, v, scale, causal, kv_valid, block_q, block_k, interpret, bwd_impl
):
    out, lse = _flash_forward(
        q, k, v, scale, causal, kv_valid, block_q, block_k, interpret,
        return_lse=True,
    )
    return out, (q, k, v, out, lse)


def _flash_bwd_dispatch(
    scale, causal, kv_valid, block_q, block_k, interpret, bwd_impl, res, g
):
    """Pick the backward implementation. ``"auto"`` takes the fused
    single-pass kernel whenever its resident f32 dQ block fits the VMEM
    budget, else the two-pass kernels."""
    if bwd_impl == "auto":
        t_q, d = res[0].shape[2], res[0].shape[3]
        t_k = res[1].shape[2]
        _, _, pq, _, pd = _block_geometry(t_q, t_k, d, block_q, block_k)
        bwd_impl = (
            "fused" if _fused_bwd_fits(t_q + pq, d + pd) else "two_pass"
        )
    if bwd_impl == "fused":
        return _flash_bwd_fused(
            scale, causal, kv_valid, block_q, block_k, interpret, res, g
        )
    return _flash_bwd(
        scale, causal, kv_valid, block_q, block_k, interpret, res, g
    )


def _flash_bwd(scale, causal, kv_valid, block_q, block_k, interpret, res, g):
    """Flash backward as two Pallas kernels (dq; dk/dv) using the saved O
    and log-sum-exp — O(T) memory, every MXU dot in the input dtype (the
    r3 XLA-recompute backward ran true-f32 passes; this is the lm_step MFU
    lever)."""
    q, k, v, out, lse = res
    b, h, t_q, d = q.shape
    t_k = k.shape[2]
    qp, kp, vp, do_p, dd_p, block_q, block_k, pq, pk, dp = _bwd_prologue(
        res, g, block_q, block_k
    )

    grid_q = (b, h, (t_q + pq) // block_q, (t_k + pk) // block_k)
    qo_spec = pl.BlockSpec(
        (1, 1, block_q, dp), lambda bi, hi, qi, ki: (bi, hi, qi, _I0),
        memory_space=pltpu.VMEM,
    )
    kv_spec_q = pl.BlockSpec(
        (1, 1, block_k, dp), lambda bi, hi, qi, ki: (bi, hi, ki, _I0),
        memory_space=pltpu.VMEM,
    )
    lm_spec_q = pl.BlockSpec(
        (1, 1, block_q, _LANES), lambda bi, hi, qi, ki: (bi, hi, qi, _I0),
        memory_space=pltpu.VMEM,
    )
    dq = pl.pallas_call(
        functools.partial(
            _bwd_dq_kernel, scale=scale, causal=causal, kv_valid=kv_valid,
            block_q=block_q, block_k=block_k,
        ),
        grid=grid_q,
        in_specs=[qo_spec, kv_spec_q, kv_spec_q, qo_spec, lm_spec_q, lm_spec_q],
        out_specs=qo_spec,
        out_shape=_out_struct((b, h, t_q + pq, dp), q),
        scratch_shapes=[pltpu.VMEM((block_q, dp), jnp.float32)],
        compiler_params=pltpu.CompilerParams(
            dimension_semantics=("parallel", "parallel", "parallel", "arbitrary"),
        ),
        interpret=interpret,
    )(qp, kp, vp, do_p, lse, dd_p)

    # dk/dv pass: K blocks on the parallel axis, Q sequential
    grid_k = (b, h, (t_k + pk) // block_k, (t_q + pq) // block_q)
    qo_spec_k = pl.BlockSpec(
        (1, 1, block_q, dp), lambda bi, hi, ki, qi: (bi, hi, qi, _I0),
        memory_space=pltpu.VMEM,
    )
    kv_spec_k = pl.BlockSpec(
        (1, 1, block_k, dp), lambda bi, hi, ki, qi: (bi, hi, ki, _I0),
        memory_space=pltpu.VMEM,
    )
    lm_spec_k = pl.BlockSpec(
        (1, 1, block_q, _LANES), lambda bi, hi, ki, qi: (bi, hi, qi, _I0),
        memory_space=pltpu.VMEM,
    )
    dk, dv = pl.pallas_call(
        functools.partial(
            _bwd_dkv_kernel, scale=scale, causal=causal, kv_valid=kv_valid,
            block_q=block_q, block_k=block_k,
        ),
        grid=grid_k,
        in_specs=[
            qo_spec_k, kv_spec_k, kv_spec_k, qo_spec_k, lm_spec_k, lm_spec_k,
        ],
        out_specs=[kv_spec_k, kv_spec_k],
        out_shape=[
            _out_struct((b, h, t_k + pk, dp), k),
            _out_struct((b, h, t_k + pk, dp), v),
        ],
        scratch_shapes=[
            pltpu.VMEM((block_k, dp), jnp.float32),
            pltpu.VMEM((block_k, dp), jnp.float32),
        ],
        compiler_params=pltpu.CompilerParams(
            dimension_semantics=("parallel", "parallel", "parallel", "arbitrary"),
        ),
        interpret=interpret,
    )(qp, kp, vp, do_p, lse, dd_p)

    return (
        dq[:, :, :t_q, :d].astype(q.dtype),
        dk[:, :, :t_k, :d].astype(k.dtype),
        dv[:, :, :t_k, :d].astype(v.dtype),
    )


_flash.defvjp(_flash_fwd, _flash_bwd_dispatch)


def _resolve_interpret(x) -> bool:
    """True when the kernel must run in the Pallas interpreter.

    Resolved from where the computation actually runs, not the global
    default backend: a concrete input's device platform wins, because in a
    mixed-platform process (a forced virtual CPU mesh alongside a live TPU
    backend, e.g. the multichip dryrun after a real-chip compile check)
    ``jax.default_backend()`` says "tpu" while the arrays live on CPU.
    Tracers carry no placement, so they fall back to the default backend.
    """
    try:
        platforms = {d.platform for d in x.devices()}
        if platforms:
            return platforms != {"tpu"}
    except Exception:
        pass
    return jax.default_backend() != "tpu"


def flash_attention(
    q: jax.Array,
    k: jax.Array,
    v: jax.Array,
    *,
    causal: bool = False,
    scale: Optional[float] = None,
    kv_valid: Optional[int] = None,
    block_q: int = 512,
    block_k: int = 1024,
    interpret: Optional[bool] = None,
    bwd_impl: str = "two_pass",
) -> jax.Array:
    """Flash attention as a hand-tiled Pallas TPU kernel.

    Same contract as :func:`heat_tpu.parallel.attention.local_attention`:
    ``(B, T, H, D)`` layout, f32 online softmax, K/V positions >= ``kv_valid``
    masked as padding. Default (512, 1024) blocks won the v5e block sweep;
    the jit-chained benchmark at B4·T4096·H8·D128 bf16 measures 68.2 TFLOP/s
    (README table), 2.7× the XLA online-softmax path. Blocks are clamped for
    short sequences. ``interpret`` defaults to True off-TPU so the same
    tests run on the CPU mesh via the Pallas interpreter.

    ``bwd_impl`` selects the backward strategy: ``"two_pass"`` (the r4
    dq + dk/dv kernels, the measured default), ``"fused"`` (single-pass
    kernel sharing the probability rebuild, resident f32 dQ — see
    `_bwd_fused_kernel`), or ``"auto"`` (fused whenever the dQ block fits
    the VMEM budget). The fused path stays opt-in until the on-chip sweep
    (scripts/tpu_tune.py attn_bwd) records it winning.
    """
    if q.ndim != 4:
        raise ValueError(f"expected (B, T, H, D) inputs, got {q.shape}")
    if interpret is None:
        interpret = _resolve_interpret(q)
    d = q.shape[-1]
    t_k = k.shape[1]
    scale = scale if scale is not None else 1.0 / math.sqrt(d)
    kv_valid = t_k if kv_valid is None else int(kv_valid)
    # kernel works in (B, H, T, D); public layout is (B, T, H, D)
    if bwd_impl not in ("two_pass", "fused", "auto"):
        raise ValueError(
            f"bwd_impl must be 'two_pass', 'fused' or 'auto', got {bwd_impl!r}"
        )
    out = _flash(
        q.transpose(0, 2, 1, 3), k.transpose(0, 2, 1, 3),
        v.transpose(0, 2, 1, 3),
        scale, causal, kv_valid, block_q, block_k, interpret, bwd_impl,
    )
    return out.transpose(0, 2, 1, 3)
