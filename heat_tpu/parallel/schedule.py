"""Static pipeline schedule tables (ISSUE 19).

A pipeline schedule is a STATIC per-tick action table: for every tick
``t`` and stage ``s`` it names the one action the stage performs —
``F(m)`` (forward of microbatch ``m``), ``B(m)`` (backward of
microbatch ``m``), or idle. The table is computed in plain Python from
``(schedule, S, M)`` and baked into the compiled ``shard_map`` kernel
(`heat_tpu/parallel/pipeline.py` site ``pipeline.step``) as constant
lookup arrays, so the kernel itself has no data-dependent control
beyond per-position table lookups. The same table drives the bubble
accounting the CI gate pins and the per-tick telemetry spans, so the
analytic and measured bubble figures share one source of truth.

Two schedules (``HEAT_TPU_PIPELINE_SCHEDULE``):

``gpipe`` (default — bit-compat with the historical kernel lineage)
    All-forward wave (``S + M - 1`` ticks), a full pipeline flush, then
    the mirrored all-backward wave — the flush means every stage
    stashes all ``M`` in-flight input activations and the drain of the
    forward wave never overlaps the fill of the backward wave.

``1f1b``
    PipeDream-flush one-forward-one-backward: stage ``s`` warms up with
    at most ``min(M, S-1-s)`` forwards, then strictly alternates
    backward-priority, bounded by ``min(M, S-s)`` in-flight
    microbatches. Bit-identical results (each stage still runs its
    backwards in increasing microbatch order, so every accumulation
    order matches gpipe) while the activation stash shrinks from ``M``
    to ``min(S, M)`` and the steady-state bubble cells drop strictly
    below gpipe's whenever ``M > 1`` and ``S > 2``.

Both tables share the same makespan lower bound ``2(S + M - 1)`` — the
classical result that 1F1B's win over GPipe is memory plus the
steady-state bubble structure, not end-to-end ticks. The accounting
here is therefore explicit about WHICH cells it counts (see
:meth:`ScheduleTable.steady_bubble_ticks`).
"""

from __future__ import annotations

from dataclasses import dataclass
from typing import List, Optional, Tuple

from .. import _knobs as knobs

__all__ = [
    "Action",
    "ScheduleTable",
    "StageMapping",
    "build_schedule",
    "gpipe_schedule",
    "one_f1b_schedule",
    "plan_stages",
    "resolve_schedule_name",
]

SCHEDULES = ("gpipe", "1f1b")


@dataclass(frozen=True)
class Action:
    """One cell of the table: ``kind`` is ``"F"`` or ``"B"``, ``mb`` the
    microbatch index."""

    kind: str
    mb: int

    def __str__(self) -> str:  # pragma: no cover - debug/doc helper
        return f"{self.kind}{self.mb}"


@dataclass(frozen=True)
class ScheduleTable:
    """A fully-resolved static schedule: ``ticks[t][s]`` is the Action
    stage ``s`` performs at tick ``t`` (or None)."""

    name: str
    n_stages: int
    n_microbatches: int
    train: bool
    ticks: Tuple[Tuple[Optional[Action], ...], ...]

    # -- structural views ----------------------------------------------------

    @property
    def n_ticks(self) -> int:
        return len(self.ticks)

    def action_arrays(self) -> Tuple[List[List[int]], List[List[int]]]:
        """``(fwd, bwd)`` integer lookup tables, each ``(T, S)`` with the
        microbatch index or ``-1`` — the constants the kernel bakes in."""
        fwd = [[-1] * self.n_stages for _ in range(self.n_ticks)]
        bwd = [[-1] * self.n_stages for _ in range(self.n_ticks)]
        for t, row in enumerate(self.ticks):
            for s, act in enumerate(row):
                if act is None:
                    continue
                (fwd if act.kind == "F" else bwd)[t][s] = act.mb
        return fwd, bwd

    def describe(self) -> str:
        """ASCII timeline (stages as rows) — the doc/golden-test view."""
        lines = []
        for s in range(self.n_stages):
            cells = []
            for t in range(self.n_ticks):
                act = self.ticks[t][s]
                cells.append("...." if act is None else f"{act!s:<4}")
            lines.append(f"s{s}: " + " ".join(c.rstrip() for c in cells))
        return "\n".join(lines)

    # -- accounting ----------------------------------------------------------

    def busy_cells(self) -> int:
        return sum(1 for row in self.ticks for a in row if a is not None)

    def bubble_cells(self) -> int:
        """Idle ``(tick, stage)`` cells over the whole table."""
        return self.n_ticks * self.n_stages - self.busy_cells()

    def bubble_fraction(self) -> float:
        return self.bubble_cells() / float(self.n_ticks * self.n_stages)

    def _stage_spans(self) -> List[Tuple[int, int]]:
        spans = []
        for s in range(self.n_stages):
            busy = [
                t for t in range(self.n_ticks) if self.ticks[t][s] is not None
            ]
            spans.append((busy[0], busy[-1]))
        return spans

    def steady_window(self) -> Tuple[int, int]:
        """``(lo, hi)`` inclusive tick range in which EVERY stage has
        started and no stage has finished — the globally-active window.
        Ticks before ``lo`` are the warmup ramp, ticks after ``hi`` the
        cooldown drain; both are unavoidable fill/drain cells shared by
        every schedule at the same ``(S, M)``."""
        spans = self._stage_spans()
        return max(lo for lo, _ in spans), min(hi for _, hi in spans)

    def steady_bubble_ticks(self) -> int:
        """Idle cells inside :meth:`steady_window` — the schedule-shaped
        bubble (GPipe's flush barrier lands here; 1F1B's steady
        alternation keeps more of the window busy). This is the figure
        the ISSUE 19 acceptance pins strictly lower for 1f1b at
        ``S=4, M=8`` and the per-tick telemetry spans re-measure."""
        lo, hi = self.steady_window()
        idle = 0
        for t in range(lo, hi + 1):
            idle += sum(1 for a in self.ticks[t] if a is None)
        return idle

    def phase_of(self, t: int) -> str:
        lo, hi = self.steady_window()
        if t < lo:
            return "warmup"
        if t > hi:
            return "cooldown"
        return "steady"

    def stash_depth(self) -> int:
        """Max in-flight microbatches any stage holds at once (forwarded
        but not yet backwarded) — the static size of the kernel's input-
        activation stash buffer. ``M`` for gpipe, ``min(S, M)`` for 1f1b
        (forward-only tables need exactly 1: the input is consumed the
        same tick)."""
        if not self.train:
            return 1
        worst = 1
        for s in range(self.n_stages):
            inflight = 0
            for t in range(self.n_ticks):
                act = self.ticks[t][s]
                if act is None:
                    continue
                inflight += 1 if act.kind == "F" else -1
                worst = max(worst, inflight)
        return worst

    # -- validation ----------------------------------------------------------

    def validate(self) -> "ScheduleTable":
        """Check the causal contract the kernel relies on: stage ``s``
        forwards microbatch ``m`` only after stage ``s-1`` did (at least
        one tick earlier — hops deliver next tick), backwards it only
        after its own forward and (for non-last stages) after stage
        ``s+1``'s backward, and every stage runs its forwards AND
        backwards in increasing microbatch order (the accumulation-order
        invariant behind cross-schedule bit-identity)."""
        S, M = self.n_stages, self.n_microbatches
        ftick = [[None] * M for _ in range(S)]
        btick = [[None] * M for _ in range(S)]
        for t, row in enumerate(self.ticks):
            for s, act in enumerate(row):
                if act is None:
                    continue
                tab = ftick if act.kind == "F" else btick
                if tab[s][act.mb] is not None:
                    raise ValueError(
                        f"{self.name}: duplicate {act} at stage {s}"
                    )
                tab[s][act.mb] = t
        for s in range(S):
            f_order = [ftick[s][m] for m in range(M)]
            if any(x is None for x in f_order) or f_order != sorted(f_order):
                raise ValueError(
                    f"{self.name}: stage {s} forward order broken: {f_order}"
                )
            for m in range(M):
                if s > 0 and ftick[s][m] <= ftick[s - 1][m]:
                    raise ValueError(
                        f"{self.name}: F{m} at stage {s} before the "
                        f"stage-{s - 1} hop could deliver it"
                    )
            if not self.train:
                continue
            b_order = [btick[s][m] for m in range(M)]
            if any(x is None for x in b_order) or b_order != sorted(b_order):
                raise ValueError(
                    f"{self.name}: stage {s} backward order broken: {b_order}"
                )
            for m in range(M):
                if btick[s][m] <= ftick[s][m]:
                    raise ValueError(
                        f"{self.name}: B{m} at stage {s} before its forward"
                    )
                if s < S - 1 and btick[s][m] <= btick[s + 1][m]:
                    raise ValueError(
                        f"{self.name}: B{m} at stage {s} before the "
                        f"stage-{s + 1} cotangent hop could deliver it"
                    )
        return self


def gpipe_schedule(
    n_stages: int, n_microbatches: int, train: bool = True
) -> ScheduleTable:
    """The flush-barrier GPipe table: forward wave, full drain, mirrored
    backward wave (microbatches in increasing order both ways)."""
    S, M = int(n_stages), int(n_microbatches)
    _check_sm(S, M)
    wave = S + M - 1
    ticks: List[Tuple[Optional[Action], ...]] = []
    for t in range(wave):
        ticks.append(
            tuple(
                Action("F", t - s) if 0 <= t - s < M else None
                for s in range(S)
            )
        )
    if train:
        for u in range(wave):
            ticks.append(
                tuple(
                    Action("B", u - (S - 1 - s))
                    if 0 <= u - (S - 1 - s) < M
                    else None
                    for s in range(S)
                )
            )
    return ScheduleTable(
        "gpipe", S, M, train, tuple(ticks)
    ).validate()


def one_f1b_schedule(n_stages: int, n_microbatches: int) -> ScheduleTable:
    """The PipeDream-flush 1F1B table, built by event simulation: each
    stage greedily prefers a ready backward, falls back to a ready
    forward, and caps in-flight microbatches at ``min(M, S - s)`` (the
    cap is what creates the warmup/steady/cooldown phase structure)."""
    S, M = int(n_stages), int(n_microbatches)
    _check_sm(S, M)
    cap = [min(M, S - s) for s in range(S)]
    next_f = [0] * S        # next microbatch to forward
    next_b = [0] * S        # next microbatch to backward
    # messages in flight: (arrival_tick-sorted) microbatches whose input /
    # cotangent has ARRIVED at the stage (hops deliver next tick)
    f_ready = [set() for _ in range(S)]   # stages 1.. : fwd inputs
    b_ready = [set() for _ in range(S)]   # stages ..S-2 : cotangents
    f_done_last: set = set()              # last stage: own fwd completions
    ticks: List[Tuple[Optional[Action], ...]] = []
    guard = 4 * (S + M) + 8
    while (min(next_b) < M) and len(ticks) < guard:
        row: List[Optional[Action]] = [None] * S
        for s in range(S):
            m_b, m_f = next_b[s], next_f[s]
            can_b = m_b < m_f and (
                (m_b in f_done_last) if s == S - 1 else (m_b in b_ready[s])
            )
            can_f = (
                m_f < M
                and (m_f - m_b) < cap[s]
                and (s == 0 or m_f in f_ready[s])
            )
            if can_b:
                row[s] = Action("B", m_b)
            elif can_f:
                row[s] = Action("F", m_f)
        # commit the tick: completions become next-tick arrivals
        for s, act in enumerate(row):
            if act is None:
                continue
            if act.kind == "B":
                next_b[s] += 1
                if s > 0:
                    b_ready[s - 1].add(act.mb)
            else:
                next_f[s] += 1
                if s == S - 1:
                    f_done_last.add(act.mb)
                else:
                    f_ready[s + 1].add(act.mb)
        ticks.append(tuple(row))
    if min(next_b) < M:  # pragma: no cover - simulator invariant
        raise RuntimeError("1f1b simulation did not converge")
    return ScheduleTable(
        "1f1b", S, M, True, tuple(ticks)
    ).validate()


def resolve_schedule_name(name: Optional[str] = None) -> str:
    """Explicit argument, else the ``HEAT_TPU_PIPELINE_SCHEDULE`` knob."""
    raw = name if name is not None else knobs.get("HEAT_TPU_PIPELINE_SCHEDULE")
    raw = str(raw).lower()
    if raw not in SCHEDULES:
        raise ValueError(
            f"unknown pipeline schedule {raw!r}; expected one of {SCHEDULES}"
        )
    return raw


def build_schedule(
    n_stages: int,
    n_microbatches: int,
    name: Optional[str] = None,
    train: bool = True,
) -> ScheduleTable:
    """Build the resolved table. Forward-only requests always get the
    gpipe forward wave — without backwards the two schedules are the
    same wave, and one table keeps the forward program count at one."""
    sched = resolve_schedule_name(name)
    if not train:
        return gpipe_schedule(n_stages, n_microbatches, train=False)
    if sched == "gpipe":
        return gpipe_schedule(n_stages, n_microbatches, train=True)
    return one_f1b_schedule(n_stages, n_microbatches)


def _check_sm(S: int, M: int) -> None:
    if S < 1:
        raise ValueError(f"need at least one stage, got {S}")
    if M < 1:
        raise ValueError(f"need at least one microbatch, got {M}")


# -- stage-per-node-group placement (the ISSUE 19 mapping grammar) ------------


@dataclass(frozen=True)
class StageMapping:
    """How ``n_stages`` map onto the ``p`` flat mesh positions: stage
    ``s`` owns the ``local`` consecutive positions
    ``[s*local, (s+1)*local)`` — exactly the `core/topology.py`
    node-group grammar, so with ``HEAT_TPU_PIPELINE_STAGES`` at its
    auto default the stages ARE the node groups and every inter-stage
    hop crosses the node (DCN) tier. The ``local`` positions inside a
    stage carry the FSDP tier: stage weights live flat-sharded ``1/local``
    and are gathered in-group (ICI) just-in-time."""

    p: int
    n_stages: int

    def __post_init__(self):
        if self.n_stages < 1 or self.p % self.n_stages:
            raise ValueError(
                f"{self.n_stages} stages do not divide a {self.p}-position "
                "mesh into equal node groups"
            )

    @property
    def local(self) -> int:
        return self.p // self.n_stages

    def groups(self) -> List[List[int]]:
        """``axis_index_groups`` of the in-stage (FSDP/ICI) tier."""
        loc = self.local
        return [
            [s * loc + l for l in range(loc)] for s in range(self.n_stages)
        ]

    def fwd_perm(self) -> List[Tuple[int, int]]:
        """The stage->stage hop: position ``(s, l)`` sends to
        ``(s+1, l)`` (full ring — the wraparound pair carries no consumed
        payload but rides the same collective-permute, so the cost model
        and the HLO audit count it too)."""
        return [(i, (i + self.local) % self.p) for i in range(self.p)]

    def bwd_perm(self) -> List[Tuple[int, int]]:
        return [(i, (i - self.local) % self.p) for i in range(self.p)]

    def describe(self) -> str:
        return f"{self.n_stages}x{self.local}"


def plan_stages(p: int, n_stages: Optional[int] = None) -> StageMapping:
    """Resolve the stage count and build the mapping.

    Explicit argument wins; else the ``HEAT_TPU_PIPELINE_STAGES`` knob
    (``0`` = auto); auto is the node count of an ACTIVE 2-level topology
    (``HEAT_TPU_HIERARCHICAL=1`` + nontrivial factorization — stages per
    node group, the MPMD placement), else one stage per position (the
    flat historical layout)."""
    if n_stages is None:
        n_stages = int(knobs.get("HEAT_TPU_PIPELINE_STAGES"))
    if n_stages == 0:
        from ..core import topology as _topo

        active = _topo.active(int(p))
        n_stages = active.node if active is not None else int(p)
    return StageMapping(int(p), int(n_stages))
