"""Regression algorithms (reference: heat/regression/)."""

from .lasso import *
