"""L1-regularized linear regression via coordinate descent.

Re-design of reference heat/regression/lasso.py:10-186: per-coordinate rho
``(X_j · (y − ŷ + θ_j X_j)).mean()`` (:159) with soft-thresholding (:90),
distribution inherited from the framework ops. Here the full sweep over
coordinates is one jit-compiled `lax.fori_loop` on the padded sharded design
matrix (validity weights neutralize tail pads), so an entire epoch runs
on-device.
"""

from __future__ import annotations

from typing import Optional

import jax
import jax.numpy as jnp

from ..core import types
from ..core.base import BaseEstimator, RegressionMixin
from ..core.dndarray import DNDarray

__all__ = ["Lasso"]


@jax.jit
def _cd_fit(xbuf: jax.Array, ybuf: jax.Array, n_logical, m_logical, lam, tol, max_iter):
    """The whole coordinate-descent fit — input prep AND epochs — as ONE
    compiled program, so a fit is a single dispatch + a single host sync.
    (The reference's Python epoch loop syncs per epoch, lasso.py:121-186;
    per-op eager dispatch also pays a host↔device round trip per op, which
    dominated wall-clock.) Returns (theta, n_iter).

    ``xbuf``/``ybuf`` are the *physical* (tail-padded) buffers; rows at
    global index ≥ ``n_logical`` and columns ≥ ``m_logical`` are pad and are
    zeroed (a feature-split input pads columns)."""
    valid = jnp.arange(xbuf.shape[0]) < n_logical
    validc = jnp.arange(xbuf.shape[1]) < m_logical
    w = valid.astype(xbuf.dtype)
    # where (not *w): pad rows/cols may hold inf/nan and 0*inf = nan
    xclean = jnp.where(valid[:, None] & validc[None, :], xbuf, 0)
    xb = jnp.concatenate([w[:, None], xclean], axis=1)
    y1 = ybuf[:, 0] if ybuf.ndim == 2 else ybuf
    yb = jnp.where(valid, y1, 0)
    z = (w @ (xb * xb)) / jnp.sum(w)  # epoch-invariant curvature per coord
    xt = xb.T  # coordinate rows contiguous along the minor axis
    m = xt.shape[0]
    n = jnp.sum(w)

    def epoch_body(j, carry):
        theta, y_est = carry
        xj = jax.lax.dynamic_index_in_dim(xt, j, axis=0, keepdims=False)
        tj = jax.lax.dynamic_index_in_dim(theta, j, keepdims=False)
        # no ·w here: pad columns of xb (hence xj) are already zero
        rho = jnp.sum(xj * (yb - y_est + tj * xj)) / n
        soft = jnp.sign(rho) * jnp.maximum(jnp.abs(rho) - lam, 0.0)
        zj = jax.lax.dynamic_index_in_dim(z, j, keepdims=False)
        new_tj = jnp.where(j == 0, rho, soft) / jnp.maximum(zj, 1e-30)
        y_est = y_est + (new_tj - tj) * xj
        return jax.lax.dynamic_update_index_in_dim(theta, new_tj, j, axis=0), y_est

    def epoch(carry):
        theta, it, _ = carry
        new_theta, _ = jax.lax.fori_loop(
            0, m, epoch_body, (theta, theta @ xt)
        )
        diff = jnp.max(jnp.abs(new_theta - theta))
        return new_theta, it + 1, diff

    def cond(carry):
        _, it, diff = carry
        return (it < max_iter) & (diff > tol)

    theta0 = jnp.zeros((m,), dtype=xt.dtype)
    theta, n_iter, _ = jax.lax.while_loop(
        cond, epoch, (theta0, jnp.int32(0), jnp.asarray(jnp.inf, dtype=xt.dtype))
    )
    return theta, n_iter


class Lasso(BaseEstimator, RegressionMixin):
    """Lasso regressor (reference lasso.py:10).

    Parameters
    ----------
    lam : float
        L1 penalty weight (the reference's ``lam``).
    max_iter : int
        Maximum coordinate-descent epochs.
    tol : float
        Convergence threshold on the coefficient change.
    """

    def __init__(self, lam: float = 0.1, max_iter: int = 100, tol: float = 1e-6):
        self.lam = lam
        self.max_iter = max_iter
        self.tol = tol
        self.__theta = None
        self.n_iter = None

    @property
    def coef_(self) -> Optional[DNDarray]:
        return None if self.__theta is None else self.__theta[1:]

    @property
    def intercept_(self) -> Optional[DNDarray]:
        return None if self.__theta is None else self.__theta[0]

    @property
    def theta(self):
        return self.__theta

    def soft_threshold(self, rho: DNDarray):
        """Soft-thresholding operator (reference lasso.py:90),
        ``sign(ρ)·max(|ρ|−λ, 0)`` expressed in framework ops: the 4-op
        elementwise tail defers into ONE fused program — and when ``rho``
        is itself a pending chain or kernel result (the coordinate
        update's residual), the whole residual+threshold expression
        grafts into a single dispatch (Fusion 2.0 epilogue)."""
        from ..core import arithmetics, rounding, statistics

        mag = arithmetics.sub(rounding.abs(rho), float(self.lam))
        return arithmetics.mul(
            rounding.sign(rho), statistics.maximum(mag, 0.0)
        )

    def rmse(self, gt: DNDarray, yest: DNDarray) -> float:
        """Root mean squared error (reference lasso.py:103)."""
        from ..core import arithmetics, statistics, exponential

        d = arithmetics.sub(gt, yest)
        return float(exponential.sqrt(statistics.mean(arithmetics.mul(d, d))).item())

    def fit(self, x: DNDarray, y: DNDarray) -> "Lasso":
        """Coordinate descent with an intercept column (reference
        lasso.py:121)."""
        if not isinstance(x, DNDarray) or not isinstance(y, DNDarray):
            raise TypeError("x and y need to be DNDarrays")
        if x.ndim != 2:
            raise ValueError("x needs to be 2D")
        if y.ndim not in (1, 2):
            raise ValueError("y needs to be 1D or 2D")

        dt = types.promote_types(x.dtype, types.float32)
        xbuf = x.larray.astype(dt.jnp_type())
        ybuf = y.larray.astype(dt.jnp_type())
        theta, n_iter = _cd_fit(
            xbuf, ybuf, x.shape[0], x.shape[1], float(self.lam),
            float(self.tol), int(self.max_iter),
        )
        self.n_iter = int(n_iter)
        # drop pad-column coordinates (feature-split inputs pad columns)
        theta = theta[: x.shape[1] + 1]
        self.__theta = DNDarray.from_logical(theta, None, x.device, x.comm, dt)
        return self

    def predict(self, x: DNDarray) -> DNDarray:
        """ŷ = X θ + intercept (reference lasso.py `predict`), in
        framework ops: the matvec is a lazy kernel node and the intercept
        add grafts onto it — one cached program per input layout
        (Fusion 2.0 epilogue)."""
        if self.__theta is None:
            raise RuntimeError("fit needs to be called before predict")
        from ..core import arithmetics
        from ..core.linalg import matmul

        return arithmetics.add(matmul(x, self.__theta[1:]), self.__theta[0])
