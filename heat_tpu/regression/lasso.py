"""L1-regularized linear regression via coordinate descent.

Re-design of reference heat/regression/lasso.py:10-186: per-coordinate rho
``(X_j · (y − ŷ + θ_j X_j)).mean()`` (:159) with soft-thresholding (:90),
distribution inherited from the framework ops. Here the full sweep over
coordinates is one jit-compiled `lax.fori_loop` on the padded sharded design
matrix (validity weights neutralize tail pads), so an entire epoch runs
on-device.
"""

from __future__ import annotations

from functools import partial
from typing import Optional

import jax
import jax.numpy as jnp

from ..core import types
from ..core.base import BaseEstimator, RegressionMixin
from ..core.dndarray import DNDarray

__all__ = ["Lasso"]


@partial(jax.jit, static_argnums=())
def _cd_epoch(xb: jax.Array, yb: jax.Array, w: jax.Array, theta: jax.Array, lam: jnp.float32):
    """One full coordinate-descent sweep (reference lasso.py:121-171).

    theta[0] is the unpenalized intercept (reference treats j==0 specially).
    """
    n = jnp.sum(w)
    m = xb.shape[1]

    def body(j, theta):
        y_est = xb @ theta
        xj = xb[:, j]
        rho = jnp.sum(xj * (yb - y_est + theta[j] * xj) * w) / n
        zj = jnp.sum(xj * xj * w) / n
        soft = jnp.sign(rho) * jnp.maximum(jnp.abs(rho) - lam, 0.0)
        new_tj = jnp.where(j == 0, rho, soft) / jnp.maximum(zj, 1e-30)
        return theta.at[j].set(new_tj)

    return jax.lax.fori_loop(0, m, body, theta)


class Lasso(BaseEstimator, RegressionMixin):
    """Lasso regressor (reference lasso.py:10).

    Parameters
    ----------
    lam : float
        L1 penalty weight (the reference's ``lam``).
    max_iter : int
        Maximum coordinate-descent epochs.
    tol : float
        Convergence threshold on the coefficient change.
    """

    def __init__(self, lam: float = 0.1, max_iter: int = 100, tol: float = 1e-6):
        self.lam = lam
        self.max_iter = max_iter
        self.tol = tol
        self.__theta = None
        self.n_iter = None

    @property
    def coef_(self) -> Optional[DNDarray]:
        return None if self.__theta is None else self.__theta[1:]

    @property
    def intercept_(self) -> Optional[DNDarray]:
        return None if self.__theta is None else self.__theta[0]

    @property
    def theta(self):
        return self.__theta

    def soft_threshold(self, rho: DNDarray):
        """Soft-thresholding operator (reference lasso.py:90)."""

        import jax.numpy as _jnp

        r = rho.larray
        out = _jnp.sign(r) * _jnp.maximum(_jnp.abs(r) - self.lam, 0.0)
        return DNDarray(out, rho.shape, rho.dtype, rho.split, rho.device, rho.comm, True)

    def rmse(self, gt: DNDarray, yest: DNDarray) -> float:
        """Root mean squared error (reference lasso.py:103)."""
        from ..core import arithmetics, statistics, exponential

        d = arithmetics.sub(gt, yest)
        return float(exponential.sqrt(statistics.mean(arithmetics.mul(d, d))).item())

    def fit(self, x: DNDarray, y: DNDarray) -> "Lasso":
        """Coordinate descent with an intercept column (reference
        lasso.py:121)."""
        if not isinstance(x, DNDarray) or not isinstance(y, DNDarray):
            raise TypeError("x and y need to be DNDarrays")
        if x.ndim != 2:
            raise ValueError("x needs to be 2D")
        if y.ndim not in (1, 2):
            raise ValueError("y needs to be 1D or 2D")

        dt = types.promote_types(x.dtype, types.float32)
        xb = x._masked(0).astype(dt.jnp_type())
        # prepend the intercept column of ones (weighted out on pads)
        w = (jnp.arange(xb.shape[0]) < x.shape[0]).astype(xb.dtype)
        ones = w[:, None]
        xb = jnp.concatenate([ones, xb], axis=1)
        yb = y._masked(0).astype(dt.jnp_type())
        if yb.ndim == 2:
            yb = yb[:, 0]

        theta = jnp.zeros((xb.shape[1],), dtype=xb.dtype)
        lam = jnp.asarray(self.lam, dtype=xb.dtype)
        for it in range(self.max_iter):
            new_theta = _cd_epoch(xb, yb, w, theta, lam)
            diff = float(jnp.max(jnp.abs(new_theta - theta)))
            theta = new_theta
            self.n_iter = it + 1
            if diff <= self.tol:
                break

        self.__theta = DNDarray.from_logical(theta, None, x.device, x.comm, dt)
        return self

    def predict(self, x: DNDarray) -> DNDarray:
        """ŷ = X θ + intercept (reference lasso.py `predict`)."""
        if self.__theta is None:
            raise RuntimeError("fit needs to be called before predict")
        th = self.__theta._logical()
        xb = x.larray.astype(th.dtype)
        yhat = xb @ th[1:] + th[0]
        return DNDarray(yhat, (x.shape[0],), types.canonical_heat_type(yhat.dtype), x.split, x.device, x.comm, True)
